"""Sharding planner for distributed embedding tables.

Re-implementation of the reference ``DistEmbeddingStrategy``
(`/root/reference/distributed_embeddings/python/layers/dist_model_parallel.py:59-324`)
with the same observable semantics:

- auto column-slice threshold when there are fewer tables than workers
  (repeatedly halve the largest table until there are enough slices);
- column slicing into the smallest power-of-two number of slices that brings
  each slice under the threshold, capped by ``min(N, world, output_dim)``,
  remainder columns spread over the first slices;
- three placement strategies: ``basic`` (round-robin), ``memory_balanced``
  (size-sorted boustrophedon, two per pass), ``memory_optimized`` (greedy
  bin-pack onto the least-loaded worker);
- re-merge of slices of the same table that land on the same worker (they are
  always column-contiguous: slices are handed out in rank order);
- per-rank fusion of same-(width, combiner) tables into one concatenated
  table with row offsets;
- deterministic pure-Python global view: every process computes the identical
  plan with no collectives.

On top of the per-rank view, this planner also emits a **width-class layout**
unique to the TPU build: for every distinct (width, combiner) class, each
rank's fused table becomes one row-padded block of a uniform row-stacked 2-D
array ``[world * max_rows, width]`` (sharded ``PartitionSpec(axis, None)``
over the mesh). That turns the reference's per-rank heterogeneous
program (each GPU runs different lookups) into a single SPMD program — the same
XLA code on every device — which is what ``shard_map``/``pjit`` require and what
makes the hybrid-parallel backward a single compiled graph on TPU.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from .embedding import Embedding, TableConfig

# (width, combiner, kind, gen) — kind is 'sparse' (row-gather path) or
# 'dense' (small-vocab MXU one-hot path; see
# DistEmbeddingStrategy.dense_row_threshold). gen splits one width class
# into multiple fused buffers, bounded hard by XLA's 2^31-element buffer
# indexing and soft by ``max_class_bytes``. (Round-3 measurement retired
# the earlier >=4 GiB copy-on-use fear: a donated 6.0 GB buffer
# scatter-adds at 20.6 ns/row, identical to small buffers.) Every input's
# ids statically target exactly one generation, so the split adds no
# per-index work; generation COMPOSITION is chosen to keep each backward
# scatter in XLA's fast regime — see _assign_generations.
ClassKey = Tuple[int, Optional[str], str, int]


@dataclasses.dataclass
class Shard:
  """A (possibly merged) column or row shard of one table on one rank.

  ``input_dim`` is the number of vocabulary rows this shard holds. For a
  row shard (``row_sliced``), those are global rows ``[row_start,
  row_start + input_dim)`` of the table; ids outside the window are served
  by other ranks' shards (routing sends them to the sentinel here).
  """

  table_id: int
  col_start: int
  col_end: int  # exclusive
  input_dim: int
  combiner: Optional[str]
  initializer: object
  gen: int = 0  # width-class generation (assigned by the planner)
  row_start: int = 0
  row_sliced: bool = False

  @property
  def width(self) -> int:
    return self.col_end - self.col_start

  def size(self) -> int:
    return self.input_dim * self.width


@dataclasses.dataclass
class ClassSlot:
  """One lookup slot of a width class on a rank: which global input feeds it
  and where its shard's rows start inside the rank's fused buffer."""

  input_id: int
  row_offset: int
  shard: Shard


@dataclasses.dataclass
class WidthClassPlan:
  """Uniform stacked layout for one (width, combiner) class.

  ``shards_per_rank[r]`` lists rank r's shards fused (row-concatenated) into
  this class's buffer; ``rows_per_rank[r]`` is the unpadded row count. The
  physical array is ``[world * max_rows, width]`` sharded over the mesh axis
  (rank r's block at rows ``[r * max_rows, (r + 1) * max_rows)``).
  ``slots_per_rank[r]`` lists the lookups rank r performs for this class;
  ``num_slots`` is the padded (max) slot count used by the SPMD program.
  """

  width: int
  combiner: Optional[str]
  kind: str  # 'sparse' | 'dense'
  shards_per_rank: List[List[Shard]]
  row_offsets_per_rank: List[List[int]]
  rows_per_rank: List[int]
  slots_per_rank: List[List[ClassSlot]]

  @property
  def max_rows(self) -> int:
    return max(self.rows_per_rank)

  @property
  def num_slots(self) -> int:
    return max(len(s) for s in self.slots_per_rank)


@dataclasses.dataclass
class OutputPiece:
  """Where one slice of one input's output comes from.

  Column slices (``row_sliced=False``) concatenate along the width axis;
  row slices (``row_sliced=True``) are full-width partial results that SUM
  (each holds the rows its vocab window served; the rest gathered the
  sentinel and contributed zeros)."""

  class_key: ClassKey
  rank: int
  slot: int
  width: int
  col_start: int
  row_sliced: bool = False


def _normalize_configs(embeddings) -> List[TableConfig]:
  configs = []
  for e in embeddings:
    if isinstance(e, TableConfig):
      configs.append(dataclasses.replace(e))
    elif isinstance(e, Embedding):
      configs.append(TableConfig.from_layer(e))
    elif isinstance(e, dict):
      # accept stock-Keras Embedding configs like the reference
      # (`embedding.py:145-152` drops mask_zero/input_length): map the
      # Keras initializer key and ignore Keras-only fields
      d = dict(e)
      if "embeddings_initializer" in d:
        d.setdefault("initializer", d.pop("embeddings_initializer"))
      if "embeddings_regularizer" in d:
        d.setdefault("regularizer", d.pop("embeddings_regularizer"))
      if "embeddings_constraint" in d:
        d.setdefault("constraint", d.pop("embeddings_constraint"))
      # a non-None activity regularizer cannot be honored by the
      # distributed path (outputs are assembled from shards) — error
      # instead of the silent drop the reference-config acceptance used
      # to do (reference accepts it, `embedding.py:64-70`)
      if d.pop("activity_regularizer", None) is not None:
        raise ValueError(
            "activity_regularizer is not supported in the distributed "
            "path: apply it to the model outputs in the loss instead")
      for k in ("mask_zero", "input_length", "dtype",
                "batch_input_shape", "trainable"):
        d.pop(k, None)
      configs.append(TableConfig(**d))
    else:
      raise TypeError(f"Cannot build TableConfig from {type(e)}")
  return configs


def _pow2_ranges(total_units: int, size: float, threshold: Optional[float],
                 world_size: int) -> List[Tuple[int, int]]:
  """Split ``total_units`` into the smallest power-of-two number of
  contiguous ranges with ``size / N <= threshold``, capped at
  ``min(N, world, total_units)``; the remainder spreads over the first
  ranges. The split rule of the reference ``maybe_slice_table_column``
  (`dist_model_parallel.py:157-188`), shared by column and row slicing."""
  if threshold is None:
    return [(0, total_units)]
  if threshold <= 0:
    raise ValueError(f"slice threshold must be positive, got {threshold}")
  num_slices = 1
  while size > threshold:
    num_slices *= 2
    size /= 2
  num_slices = min(num_slices, world_size, total_units)
  if num_slices <= 1:
    return [(0, total_units)]
  base = total_units // num_slices
  rem = total_units % num_slices
  ranges, start = [], 0
  for i in range(num_slices):
    n = base + (1 if i < rem else 0)
    ranges.append((start, start + n))
    start += n
  return ranges


def slice_columns(config: TableConfig, threshold: Optional[float],
                  world_size: int) -> List[Tuple[int, int]]:
  """Column ranges for one table under a slice threshold (semantics of the
  reference ``maybe_slice_table_column``, `dist_model_parallel.py:157-188`)."""
  return _pow2_ranges(config.output_dim, float(config.size()), threshold,
                      world_size)


def slice_rows(config: TableConfig, threshold: Optional[float],
               world_size: int) -> List[Tuple[int, int]]:
  """Row (vocabulary) ranges for one table under a row-slice threshold.

  Same split rule as :func:`slice_columns` applied to the vocab dim. The
  reference only stubs row slicing (`dist_model_parallel.py:343,364-365`
  raises NotImplementedError); this build implements it — the natural
  split for tables whose single-column footprint still exceeds one device
  (e.g. multi-hundred-GiB vocabularies).
  """
  return _pow2_ranges(config.input_dim, float(config.size()), threshold,
                      world_size)


def auto_column_slice_threshold(sizes: Sequence[int],
                                world_size: int) -> Optional[float]:
  """Pick a threshold so every worker gets at least one slice.

  Reference `dist_model_parallel.py:205-211`: while there are fewer tables
  than workers, halve the largest table; the threshold ends just below the
  largest table seen at the final halving step.
  """
  if len(sizes) >= world_size:
    return None
  sizes = sorted(sizes)
  threshold = None
  while world_size > len(sizes):
    threshold = sizes[-1] - 1
    largest = sizes.pop()
    sizes += [largest // 2, largest // 2]
    sizes.sort()
  return threshold


def apply_placement(mode: str, world_size: int,
                    slice_sizes: List[int], slice_table_ids: List[int]
                    ) -> List[List[int]]:
  """Distribute slice ids (positions into the flat slice list) to workers.

  Reference ``apply_stragety`` (`dist_model_parallel.py:227-263`), returning
  per-rank lists of *flat slice indices* (the reference returns table ids; we
  keep slice identity and map back to tables later, which avoids its
  input-id/table-id conflation in slice-range bookkeeping).
  """
  n = len(slice_sizes)
  flat = list(range(n))
  if mode == "basic":
    return [flat[i::world_size] for i in range(world_size)]
  if mode == "memory_balanced":
    order = [i for _, _, i in
             sorted(((slice_sizes[i], slice_table_ids[i], i) for i in flat),
                    reverse=True)]
    return [
        order[i::2 * world_size] + order[(2 * world_size - 1 - i)::2 * world_size]
        for i in range(world_size)
    ]
  if mode == "memory_optimized":
    # Greedy: biggest slice first onto the least-loaded worker.
    order = sorted(flat, key=lambda i: (slice_sizes[i], slice_table_ids[i]),
                   reverse=True)
    loads = [(0, r) for r in range(world_size)]
    assignment: List[List[int]] = [[] for _ in range(world_size)]
    import heapq
    heapq.heapify(loads)
    for i in order:
      load, r = heapq.heappop(loads)
      assignment[r].append(i)
      heapq.heappush(loads, (load + slice_sizes[i], r))
    return assignment
  raise ValueError(f"Unsupported strategy {mode}")



def _rows_hard_noaux(width: int) -> int:
  """Max shard rows that fit one 2^31-element TPU buffer with NO packed
  aux state (the plan-time hard bound; the exact per-rule check lives in
  DistributedLookup.fused_layouts)."""
  stride = width
  rpp = max(1, 128 // stride)
  pw = max(128, -(-stride // 128) * 128)
  return max(1, int((2 ** 31) // (pw / rpp)))


def _raise_shard_too_big(table_id: int, rows: int, width: int) -> None:
  raise ValueError(
      f"table {table_id}'s shard of {rows:,} rows x width {width} "
      f"exceeds one TPU buffer (2^31 elements ~= "
      f"{_rows_hard_noaux(width):,} rows at this width) and a generation "
      "cannot split a single shard. Shard it finer: more workers, a "
      "smaller row_slice threshold (slices are capped at "
      "min(2^k, world)), or column slicing (column_slice_threshold).")


class DistEmbeddingStrategy:
  """Global-view embedding placement plan (deterministic, collective-free).

  Args:
    embeddings: global list of ``Embedding`` layers / ``TableConfig``s / dicts.
    world_size: number of model-parallel workers.
    strategy: 'basic' | 'memory_balanced' | 'memory_optimized'.
    input_table_map: input i feeds table ``input_table_map[i]`` (shared
      tables); None means the identity map.
    column_slice_threshold: max elements per slice, or None for auto.
  """

  def __init__(self,
               embeddings,
               world_size: int,
               strategy: str = "basic",
               input_table_map: Optional[Sequence[int]] = None,
               column_slice_threshold: Optional[int] = None,
               dense_row_threshold: int = 0,
               max_class_bytes: int = 3 * 1024 ** 3,
               row_slice_threshold: Optional[int] = None,
               input_hotness: Optional[Sequence[int]] = None,
               batch_hint: Optional[int] = None,
               gen_assignment: str = "auto",
               host_row_threshold: Optional[int] = None,
               hbm_budget_bytes: Optional[int] = None,
               oov: str = "clip",
               vocab_capacity: Optional[int] = None,
               admit_threshold: int = 1,
               evict_ttl: Optional[int] = None,
               wire_dtype: str = "f32",
               dedup_exchange: bool = False,
               overlap: str = "none",
               exchange_chunks: int = 1,
               dedup_capacity: Optional[int] = None):
    if strategy not in ("basic", "memory_balanced", "memory_optimized"):
      raise ValueError(f"Unsupported shard strategy {strategy}")
    # ---- wire format of the dp<->mp exchanges ---------------------------
    # Plan-level because the wire is a contract between routing, combine,
    # backward and audit — one lookup call flipping it per-site would
    # desynchronize the reverse (autodiff-inserted) exchange from the
    # forward one. "wire_dtype": float payloads (activations + reverse
    # cotangents) travel 'f32' (identity, the pre-knob program), 'bf16'
    # (half the float wire bytes), or 'fp8' (quarter: float8_e4m3 payload
    # with one f32 amax scale shipped per destination block/chunk —
    # tables, combiners and the one-scatter-add backward stay f32 master
    # precision in every mode; the narrowing exists only in flight).
    # "dedup_exchange": per (source, dest, bucket) block, ship the
    # sorted-unique id set and one activation/cotangent row per unique
    # id instead of one per occurrence/sample (lookup_engine.DedupRouted;
    # sparse-kind padded buckets only — dense MXU classes and ragged
    # value streams keep the raw exchange). "overlap='pipelined'":
    # rewrite each monolithic all_to_all as (world - 1) ppermute rounds
    # per chunk, the payload split into "exchange_chunks" chunks, so the
    # receiving side's gather/combine of chunk k overlaps chunk k+1's
    # flight (wire.pipelined_float_exchange / pipelined_exchange_ids;
    # f32 pipelined is bit-exact vs monolithic — pure data movement).
    # "overlap='fused'": the just-in-time form of the pipelined schedule
    # — sparse-class activation/cotangent rows are gathered (and, under
    # dedup_exchange, expanded/segment-summed) per ROUND immediately
    # before each wire.fused_block_send instead of in one monolithic
    # pre-gather, so round k's collective can overlap round k+1's gather
    # (and on a real TPU the ops/pallas_exchange.py remote-DMA kernel
    # takes over). Id exchanges and dense-class floats still ride the
    # pipelined schedule; f32 fused is bit-exact vs both other modes.
    # None of these knobs changes any buffer layout, so checkpoints
    # restore across knob changes; training step builders reject
    # exact=True with a narrowed (bf16/fp8) wire (the exact path's
    # bit-for-bit claim cannot survive a narrowed cotangent exchange).
    if wire_dtype not in ("f32", "bf16", "fp8"):
      raise ValueError(
          f"wire_dtype must be 'f32', 'bf16' or 'fp8', got {wire_dtype!r}")
    self.wire_dtype = wire_dtype
    self.dedup_exchange = bool(dedup_exchange)
    if overlap not in ("none", "pipelined", "fused"):
      raise ValueError(
          f"overlap must be 'none', 'pipelined' or 'fused', got {overlap!r}")
    if not isinstance(exchange_chunks, int) or exchange_chunks < 1:
      raise ValueError(
          f"exchange_chunks must be a positive int, got {exchange_chunks!r}")
    if exchange_chunks > 1 and overlap == "none":
      raise ValueError(
          f"exchange_chunks={exchange_chunks} without overlap='pipelined' "
          "or 'fused' would be silently ignored: the monolithic all_to_all "
          "has no chunk axis. Set overlap='pipelined'/'fused' (or "
          "exchange_chunks=1).")
    self.overlap = overlap
    self.exchange_chunks = exchange_chunks
    # "dedup_capacity": override the dedup'd exchange's per-block unique
    # capacity K (default min(block occurrences, sentinel + 1) — the
    # value-range bound, which can never overflow). A SMALLER cap shrinks
    # the unique wire further but creates an overflow path: distinct ids
    # beyond the cap alias onto the last slot and gather the wrong row.
    # The knob is therefore only legal alongside the counter that makes
    # that observable — guarded train steps and with_metrics eval surface
    # a psum'd per-class 'dedup_overflow' count, and the unguarded step
    # builders REFUSE a capped plan at build time.
    if dedup_capacity is not None:
      if not dedup_exchange:
        raise ValueError(
            "dedup_capacity requires dedup_exchange=True: the capacity "
            "caps the dedup'd exchange's unique blocks, which a raw "
            "exchange does not have.")
      if not isinstance(dedup_capacity, int) or dedup_capacity < 1:
        raise ValueError(
            f"dedup_capacity must be a positive int, got {dedup_capacity!r}")
    self.dedup_capacity = dedup_capacity
    # Out-of-vocabulary id POLICY (plan-level — one id pipeline feeds all
    # tables, so the policy is a property of the plan, not a lookup-call
    # flag). "clip": ids >= input_dim clamp to the last row (reference
    # numeric semantics, unchanged) but are COUNTED — guarded train steps
    # surface a per-class OOV counter in their metrics so clipping is
    # observable instead of silent. "error": a nonzero counter raises —
    # eagerly at routing time for concrete (non-traced) inputs, host-side
    # from step metrics under jit (resilience.guards.check_oov) — for
    # debugging id pipelines where a clip would bury the bug. Not part of
    # the plan fingerprint: the policy changes no layout and no numerics.
    # "allocate" (dynamic vocabulary, dynvocab/ subsystem): raw 64-bit ids
    # are TRANSLATED host-side — between steps, like the tiered
    # prefetcher's classify — to physical rows a host-side
    # open-addressing table allocates on first admission
    # (count-min-sketch frequency admission, TTL eviction recycling rows
    # in place). The traced step only ever sees translated in-range ids,
    # so the jaxpr is byte-identical to an oov='clip' plan's; a nonzero
    # in-trace OOV counter under this policy means raw ids leaked past
    # the translator, which guards.check_oov escalates like 'error'.
    if oov not in ("clip", "error", "allocate"):
      raise ValueError(
          f"oov policy must be 'clip', 'error' or 'allocate', got {oov!r}")
    self.oov = oov
    # ---- dynamic-vocabulary knobs (oov='allocate' only) -----------------
    # vocab_capacity: allocatable rows per table (None = the table's full
    # input_dim — every physical row is allocatable). admit_threshold: an
    # id must be OBSERVED this many times (count-min-sketch estimate)
    # before it earns a row; 1 admits everything on first sight.
    # evict_ttl: steps of non-observation after which a row is reclaimed
    # to the freelist (its table AND interleaved optimizer lanes re-zero
    # before reuse); None never evicts. None of these knobs changes any
    # buffer layout or the traced step, so they are NOT part of the plan
    # fingerprint — the checkpoint manifest's 'vocab' section pins the
    # translator state they govern instead.
    if oov != "allocate":
      if vocab_capacity is not None or admit_threshold != 1 \
          or evict_ttl is not None:
        raise ValueError(
            "vocab_capacity/admit_threshold/evict_ttl only apply to the "
            "dynamic-vocabulary policy: build the plan with "
            "oov='allocate' (got oov=" + repr(oov) + ").")
      for t, c in enumerate(_normalize_configs(embeddings)):
        if getattr(c, "vocab_capacity", None) is not None:
          raise ValueError(
              f"table {t} carries a per-table vocab_capacity "
              f"({c.vocab_capacity}) on a static-vocab plan "
              f"(oov={oov!r}): the cap only governs dynamic allocation "
              "— build the plan with oov='allocate' or drop the field.")
    else:
      if vocab_capacity is not None and (
          not isinstance(vocab_capacity, int) or vocab_capacity < 1):
        raise ValueError(
            f"vocab_capacity must be a positive int, got "
            f"{vocab_capacity!r}")
      for t, c in enumerate(_normalize_configs(embeddings)):
        per = getattr(c, "vocab_capacity", None)
        if per is not None and (not isinstance(per, int) or per < 1):
          raise ValueError(
              f"table {t}'s vocab_capacity must be a positive int, got "
              f"{per!r}")
        for cap, what in ((vocab_capacity, "vocab_capacity"),
                          (per, f"table {t}'s vocab_capacity")):
          if cap is not None and cap > c.input_dim:
            raise ValueError(
                f"{what}={cap:,} exceeds table {t}'s "
                f"input_dim={c.input_dim:,}: allocated rows must fit the "
                "physical table. Lower the capacity or grow the table.")
      if not isinstance(admit_threshold, int) or admit_threshold < 1:
        raise ValueError(
            f"admit_threshold must be an int >= 1, got {admit_threshold!r}")
      if evict_ttl is not None and (not isinstance(evict_ttl, int)
                                    or evict_ttl < 1):
        raise ValueError(
            f"evict_ttl must be None or an int >= 1, got {evict_ttl!r}")
    self.vocab_capacity = vocab_capacity
    self.admit_threshold = admit_threshold
    self.evict_ttl = evict_ttl
    self.strategy = "basic" if world_size == 1 else strategy
    self.world_size = world_size
    # ---- third placement tier: host-offloaded cold storage --------------
    # Tables with input_dim > host_row_threshold are HOST-tier: their rows
    # live in host RAM (the cold store) and only a frequency-ranked hot
    # subset is resident on device, plus a per-step staging buffer for the
    # batch's cold rows (see distributed_embeddings_tpu/tiering/). The
    # placement/fusion/routing math is unchanged — tiering is a physical
    # storage attribute of a class, resolved per class after generation
    # assignment (host-tier tables get their own generations so small
    # tables fused in the same width class are not dragged to host).
    # ``hbm_budget_bytes`` (per device) is the accounting input the
    # tiering planner sizes hot caches against; recorded here for
    # tier_capacity_report. It is deliberately NOT in the plan
    # fingerprint — checkpoints pin the RESULTING per-class cache/staging
    # geometry (manifest tiering section), so a different budget that
    # yields the same geometry restores fine.
    if host_row_threshold is not None:
      if host_row_threshold <= 0:
        raise ValueError(
            f"host_row_threshold must be positive, got {host_row_threshold}")
      if host_row_threshold <= dense_row_threshold:
        raise ValueError(
            f"host_row_threshold ({host_row_threshold}) must exceed "
            f"dense_row_threshold ({dense_row_threshold}): a table cannot "
            "be both MXU-dense and host-offloaded")
    self.host_row_threshold = host_row_threshold
    self.hbm_budget_bytes = hbm_budget_bytes
    # Tables with input_dim <= dense_row_threshold are served by the MXU
    # one-hot-matmul path (zero indexed row ops, dense autodiff grads)
    # instead of HBM row gathers; 0 disables. On v5e every gathered/scattered
    # row costs ~8-23ns regardless of width, so small tables are strictly
    # cheaper as matmuls (the TPU answer to the reference's
    # ConcatOneHotEmbedding, `embedding.py:155-180`).
    self.dense_row_threshold = dense_row_threshold
    self.global_configs = _normalize_configs(embeddings)
    for t, c in enumerate(self.global_configs):
      # Routing tensors carry LOCALIZED ids as int32 on the wire; GLOBAL
      # ids for a >int32 table arrive as int64 (the engine keeps int64
      # inputs wide, `lookup_engine._normalize_input`; the reference
      # registers the same two widths, `embedding_lookup_ops.cc:24-88`)
      # and the row-slice window subtraction narrows them. That only
      # works when every SHARD's window fits int32 — i.e. the table is
      # row-sliced — so an unsliceable >int32 table still fails at plan
      # time rather than folding ids at the engine's cast. (The per-rank
      # 2^31 buffer-element bound in fused_layouts/_buffer_limit already
      # forces such tables into row slices far below int32 rows.)
      if c.input_dim > 2 ** 31 - 1 and not row_slice_threshold:
        raise ValueError(
            f"table {t} has input_dim={c.input_dim:,} > int32 max "
            f"({2 ** 31 - 1:,}): global ids need the int64 routing path, "
            "which localizes them through row-slice windows. Enable row "
            "slicing (row_slice_threshold), split the id space across "
            "several tables (an input_table_map entry per split, with a "
            "host-side id fold), or reduce the vocabulary.")
    num_tables = len(self.global_configs)
    if input_table_map is None:
      input_table_map = list(range(num_tables))
    self.input_table_map = list(input_table_map)
    self.num_inputs = len(self.input_table_map)
    if input_hotness is not None and len(input_hotness) != self.num_inputs:
      raise ValueError(
          f"input_hotness has {len(input_hotness)} entries for "
          f"{self.num_inputs} inputs")
    self.input_hotness = None if input_hotness is None else list(input_hotness)
    # A NEGATIVE input_hotness entry declares "input i may be ragged":
    # its table is kept on the sparse (gather) path regardless of
    # dense_row_threshold, because the MXU one-hot path has no
    # value-stream form. |entry| still serves as the occurrence weight
    # for generation balancing (use -avg_hotness when known, else -1).
    self._ragged_tables = set()
    if self.input_hotness is not None:
      for i, h in enumerate(self.input_hotness):
        if h < 0:
          self._ragged_tables.add(self.input_table_map[i])
    # expected per-step GLOBAL batch (optional): lets the generation
    # assignment evaluate the scatter-regime cost model on absolute id
    # counts instead of only balancing ratios — see _assign_generations
    self.batch_hint = batch_hint

    # ---- column slicing --------------------------------------------------
    self.column_slice_threshold = column_slice_threshold
    threshold = column_slice_threshold
    if threshold is None and row_slice_threshold is None:
      # the auto threshold exists to give every worker a shard when there
      # are fewer tables than workers; an explicit row_slice request can
      # provide that coverage itself, so auto column slicing must not
      # preempt it (it would cap at output_dim and crash for one huge
      # narrow table across many workers)
      threshold = auto_column_slice_threshold(
          [c.size() for c in self.global_configs], world_size)
    self.table_col_ranges: List[List[Tuple[int, int]]] = [
        slice_columns(c, threshold, world_size) for c in self.global_configs
    ]
    for t, c in enumerate(self.global_configs):
      if c.constraint is not None and len(self.table_col_ranges[t]) > 1:
        raise ValueError(
            f"table {t} has an embeddings_constraint but would be column-"
            "sliced: a row projection (e.g. max_norm) needs the full row "
            "on one shard. Raise column_slice_threshold for this table or "
            "drop the constraint.")

    # API-parity view: [input_id, input_id + num_slices] per sliced input.
    self.sliced_out_ranges = [
        [i, i + len(self.table_col_ranges[t])]
        for i, t in enumerate(self.input_table_map)
        if len(self.table_col_ranges[t]) > 1
    ]

    # ---- row slicing (vocab dim; this build's extension — the reference
    # stubs it, `dist_model_parallel.py:364-365`). A table is sliced along
    # ONE dim: column slicing wins when both thresholds would trigger.
    self.row_slice_threshold = row_slice_threshold
    self.table_row_ranges: List[List[Tuple[int, int]]] = [
        slice_rows(c, row_slice_threshold, world_size)
        if len(self.table_col_ranges[t]) == 1 else [(0, c.input_dim)]
        for t, c in enumerate(self.global_configs)
    ]
    # int64 routing backstop (completes the __init__ guard, which only
    # proves row slicing was REQUESTED): every >int32 table must have
    # actually sliced into int32-sized windows — column slicing or a
    # too-coarse row threshold can leave a single full-vocab range, and
    # the engine's post-localization int32 narrowing would then wrap.
    for t, c in enumerate(self.global_configs):
      if c.input_dim <= 2 ** 31 - 1:
        continue
      windows = self.table_row_ranges[t]
      worst = max(r1 - r0 for (r0, r1) in windows)
      if worst > 2 ** 31 - 1:
        raise ValueError(
            f"table {t} (input_dim={c.input_dim:,}) did not row-slice "
            f"into int32-sized windows (largest window {worst:,} rows): "
            "the int64 routing path localizes ids through row-slice "
            "windows. Lower row_slice_threshold (and note column "
            "slicing disables row slicing for a table).")

    # ---- placement -------------------------------------------------------
    # one placement unit per (table, column range or row range)
    slice_sizes, slice_table_ids = [], []
    for t, config in enumerate(self.global_configs):
      for (s, e) in self.table_col_ranges[t]:
        if len(self.table_row_ranges[t]) > 1 and (s, e) == (
            0, config.output_dim):
          continue  # row-sliced table: units come from row ranges below
        slice_sizes.append(config.input_dim * (e - s))
        slice_table_ids.append(t)
      if len(self.table_row_ranges[t]) > 1:
        for (r0, r1) in self.table_row_ranges[t]:
          slice_sizes.append((r1 - r0) * config.output_dim)
          slice_table_ids.append(t)
    placement = apply_placement(self.strategy, world_size, slice_sizes,
                                slice_table_ids)

    # ---- per-rank shards: hand out column/row ranges in rank order,
    # merging same-table slices that land together (always contiguous in
    # the sliced dim: slices are handed out in rank order).
    next_slice: List[int] = [0] * num_tables
    self.rank_shards: List[List[Shard]] = []
    for rank in range(world_size):
      shards: List[Shard] = []
      by_table: Dict[int, Shard] = {}
      for flat_idx in placement[rank]:
        t = slice_table_ids[flat_idx]
        config = self.global_configs[t]
        row_sliced = len(self.table_row_ranges[t]) > 1
        if row_sliced:
          r0, r1 = self.table_row_ranges[t][next_slice[t]]
          next_slice[t] += 1
          if t in by_table:  # merge row-contiguous slices on this rank
            by_table[t].input_dim += r1 - r0
          else:
            shard = Shard(table_id=t, col_start=0,
                          col_end=config.output_dim, input_dim=r1 - r0,
                          combiner=config.combiner,
                          initializer=config.initializer,
                          row_start=r0, row_sliced=True)
            by_table[t] = shard
            shards.append(shard)
        else:
          s, e = self.table_col_ranges[t][next_slice[t]]
          next_slice[t] += 1
          if t in by_table:  # merge with earlier shard on this rank
            by_table[t].col_end = e
          else:
            shard = Shard(table_id=t, col_start=s, col_end=e,
                          input_dim=config.input_dim,
                          combiner=config.combiner,
                          initializer=config.initializer)
            by_table[t] = shard
            shards.append(shard)
      self.rank_shards.append(shards)
    if world_size > 1 and not all(self.rank_shards):
      raise ValueError(
          "Not enough tables after slicing to run on all workers. "
          "Try decreasing column_slice_threshold or the worker count")

    # reference-compatible per-rank table id lists (for get/set weights order)
    self.table_ids = [[sh.table_id for sh in shards]
                      for shards in self.rank_shards]

    # ---- per-rank inputs + width-class fusion ----------------------------
    # Generation assignment. A width class bigger than one TPU buffer can
    # hold (2^31 elements — XLA's 32-bit buffer indexing) splits into
    # generations, each a separate buffer with its own gather and backward
    # scatter. Two measured facts drive the assignment
    # (tools/profile_scatter_regimes.py, docs/BENCHMARKS.md):
    #
    # 1. XLA's scatter-add has two regimes: a fast path at ~16-25 ns/row
    #    it only picks when the id stream is a large enough fraction of
    #    the buffer's rows (>= ~0.15 ids/row empirically — raw buffer
    #    bytes do NOT matter), and a ~75 ns/row serial path otherwise.
    #    First-fit in table order packed the Tiny model's nine 1-hot
    #    1M-row tables into a generation of their own: a 590k-id stream
    #    over 8.25M physical rows (ratio 0.07) ran at 74.7 ns — 44
    #    ms/step, traced — while a mixed assignment keeps every
    #    generation's scatter in the fast regime.
    # 2. Gather cost is flat in buffer size, so fewer+bigger generations
    #    are otherwise free.
    #
    # The assignment therefore MAXIMIZES THE MINIMUM ids/rows ratio over
    # generations: try every feasible generation count from the capped
    # minimum up, balance each by expected id traffic (input_hotness when
    # known, else inputs-per-table), and keep the best. Generations never
    # exceed max_class_bytes (min'd with the element limit) unless a
    # single shard alone does.
    self.max_class_bytes = max_class_bytes
    if gen_assignment not in ("auto", "first_fit"):
      raise ValueError(
          f"gen_assignment must be 'auto' or 'first_fit', got "
          f"{gen_assignment!r}")
    self.gen_assignment = gen_assignment
    occ_of = [0.0] * num_tables
    for i, t in enumerate(self.input_table_map):
      # negative entries are ragged markers; |h| is the occurrence weight
      occ_of[t] += (abs(self.input_hotness[i])
                    if self.input_hotness is not None else 1)
    if gen_assignment == "first_fit":
      # Legacy (round-2) layout: first-fit in shard order against the byte
      # cap. Exists so checkpoints written under the old assignment stay
      # restorable (pass gen_assignment='first_fit' plus the saving run's
      # max_class_bytes — the checkpoint manifest's layout diff names the
      # mismatch otherwise). Performance-wise the occurrence-balanced
      # default dominates it (docs/BENCHMARKS.md, scatter-regime matrix).
      for shards in self.rank_shards:
        gen_rows: Dict[tuple, List[int]] = {}
        for sh in shards:
          base = (sh.width, sh.combiner, self._kind_of(sh),
                  self.table_tier(sh.table_id))
          # same plan-time hard error as the auto mode (a generation
          # cannot split a shard, and one shard past the 2^31-element
          # buffer limit is untrainable regardless of assignment) —
          # except host-tier shards, whose device footprint is the
          # compact cache+staging buffer (TieringPlan enforces ITS 2^31
          # bound), not the full vocabulary
          if (base[3] != "host"
              and sh.input_dim > _rows_hard_noaux(sh.width)):
            _raise_shard_too_big(sh.table_id, sh.input_dim, sh.width)
          rows_list = gen_rows.setdefault(base, [0])
          cap_rows = max(1, max_class_bytes // (sh.width * 4))
          for g, r in enumerate(rows_list):
            if r == 0 or r + sh.input_dim <= cap_rows:
              sh.gen = g
              rows_list[g] += sh.input_dim
              break
          else:
            sh.gen = len(rows_list)
            rows_list.append(sh.input_dim)
    else:
      for shards in self.rank_shards:
        by_base: Dict[tuple, List] = {}
        for sh in shards:
          # tier joins the grouping key so host-tier tables never share a
          # generation with device-tier ones — a class (one physical
          # buffer) must be uniformly device-resident or host-offloaded
          by_base.setdefault(
              (sh.width, sh.combiner, self._kind_of(sh),
               self.table_tier(sh.table_id)), []).append(sh)
        for base, group in by_base.items():
          self._assign_generations(base[0], group, occ_of)

    if host_row_threshold is not None:
      # Host-tier generations are renumbered after a GLOBAL offset (max
      # device-tier gen over every rank, per (width, combiner, kind)):
      # gens are assigned per rank, and a rank-local offset could give the
      # same generation number a device shard on one rank and a host
      # shard on another — one class, two tiers, which the storage split
      # cannot represent.
      max_dev_gen: Dict[tuple, int] = {}
      for shards in self.rank_shards:
        for sh in shards:
          if self.table_tier(sh.table_id) == "device":
            k = (sh.width, sh.combiner, self._kind_of(sh))
            max_dev_gen[k] = max(max_dev_gen.get(k, -1), sh.gen)
      for shards in self.rank_shards:
        for sh in shards:
          if self.table_tier(sh.table_id) == "host":
            k = (sh.width, sh.combiner, self._kind_of(sh))
            sh.gen += max_dev_gen.get(k, -1) + 1

    class_keys: List[ClassKey] = []
    for shards in self.rank_shards:
      for sh in shards:
        key = self.class_key_of(sh)
        if key not in class_keys:
          class_keys.append(key)
    class_keys.sort(key=lambda k: (k[0], str(k[1]), k[2], k[3]))
    self.class_keys = class_keys

    # Per-class storage tier, derived from member tables (uniform by
    # construction: host-tier tables have disjoint generations). "device"
    # = the class buffer is fully HBM-resident (the only tier before this
    # existed); "host" = rows live in the host cold store with a device
    # hot cache + staging buffer (tiering/ subsystem).
    self.class_tiers: Dict[ClassKey, str] = {}
    for shards in self.rank_shards:
      for sh in shards:
        key = self.class_key_of(sh)
        tier = self.table_tier(sh.table_id)
        prev = self.class_tiers.setdefault(key, tier)
        if prev != tier:
          raise AssertionError(
              f"class {key} mixes storage tiers ({prev} vs {tier}) — "
              "generation separation failed; this is a planner bug")

    self.classes: Dict[ClassKey, WidthClassPlan] = {
        key: WidthClassPlan(width=key[0], combiner=key[1], kind=key[2],
                            shards_per_rank=[[] for _ in range(world_size)],
                            row_offsets_per_rank=[[] for _ in range(world_size)],
                            rows_per_rank=[0] * world_size,
                            slots_per_rank=[[] for _ in range(world_size)])
        for key in class_keys
    }

    # worker-order input ids (an input appears once per slice of its table)
    self.input_ids_list: List[List[int]] = []
    # output routing: input_id -> pieces in column order
    self.output_pieces: List[List[OutputPiece]] = [
        [] for _ in range(self.num_inputs)
    ]

    for rank, shards in enumerate(self.rank_shards):
      # fuse: row-concat shards of equal (width, combiner, kind) in local order
      for sh in shards:
        plan = self.classes[self.class_key_of(sh)]
        plan.shards_per_rank[rank].append(sh)
        plan.row_offsets_per_rank[rank].append(plan.rows_per_rank[rank])
        plan.rows_per_rank[rank] += sh.input_dim

      rank_input_ids: List[int] = []
      for sh in shards:
        key = self.class_key_of(sh)
        plan = self.classes[key]
        idx_in_rank = plan.shards_per_rank[rank].index(sh)
        row_offset = plan.row_offsets_per_rank[rank][idx_in_rank]
        for input_id, mapped_table in enumerate(self.input_table_map):
          if mapped_table == sh.table_id:
            rank_input_ids.append(input_id)
            slot = ClassSlot(input_id=input_id, row_offset=row_offset, shard=sh)
            plan.slots_per_rank[rank].append(slot)
            self.output_pieces[input_id].append(
                OutputPiece(class_key=key, rank=rank,
                            slot=len(plan.slots_per_rank[rank]) - 1,
                            width=sh.width, col_start=sh.col_start,
                            row_sliced=sh.row_sliced))
      self.input_ids_list.append(rank_input_ids)

    # column slices of one input must concat in column order
    for pieces in self.output_pieces:
      pieces.sort(key=lambda p: p.col_start)

    # ---- reference-compatible per-rank fused views -----------------------
    self.local_configs: List[List[dict]] = []
    self.local_group_list: List[List[List[int]]] = []
    self.local_weight_offsets: List[List[List[int]]] = []
    self.local_maps: List[List[int]] = []
    self.local_input_offsets: List[List[int]] = []
    self.widths_list_flat: List[int] = []
    for rank in range(world_size):
      configs, groups, weight_offsets = [], [], []
      # fused groups in class order, skipping classes absent on this rank
      rank_class_keys = [k for k in class_keys
                         if self.classes[k].shards_per_rank[rank]]
      shards_flat = self.rank_shards[rank]
      for key in rank_class_keys:
        plan = self.classes[key]
        members = plan.shards_per_rank[rank]
        configs.append({
            "input_dim": plan.rows_per_rank[rank],
            "output_dim": key[0],
            "combiner": key[1],
        })
        groups.append([shards_flat.index(sh) for sh in members])
        offs = [0]
        for sh in members:
          offs.append(offs[-1] + sh.input_dim)
        weight_offsets.append(offs)
      self.local_configs.append(configs)
      self.local_group_list.append(groups)
      self.local_weight_offsets.append(weight_offsets)

      input_map, input_offsets = [], []
      for input_id in self.input_ids_list[rank]:
        piece = next(p for p in self.output_pieces[input_id] if p.rank == rank)
        # recover class + slot for this (input, rank)
        key = piece.class_key
        gid = rank_class_keys.index(key)
        input_map.append(gid)
        slot = self.classes[key].slots_per_rank[rank][piece.slot]
        input_offsets.append(slot.row_offset)
        # flat output widths in worker order (reference widths_list_flat)
        self.widths_list_flat.append(piece.width)
      self.local_maps.append(input_map)
      self.local_input_offsets.append(input_offsets)

    worker_order = [i for rank_ids in self.input_ids_list for i in rank_ids]
    self.rev_global_input_ids = [
        idx for _, idx in sorted(zip(worker_order, range(len(worker_order))))
    ]

  # ---- convenience -------------------------------------------------------
  def _assign_generations(self, width: int, group: List,
                          occ_of: Sequence[float]) -> None:
    """Set ``sh.gen`` for one (width, combiner, kind) shard group.

    Tries every feasible generation count from the capped minimum
    (``max_class_bytes``, min'd with the 2^31-element buffer limit under a
    one-aux packed layout) upward; within a count, shards are handed out
    in descending occurrence-weight order to the generation with the
    least weight so far (ties: fewest rows). Keeps the assignment
    maximizing the minimum occurrence-weight / physical-rows ratio — the
    quantity that decides the backward scatter's regime. See __init__ for
    the measured rationale."""
    # per-logical-row element count under a 1-aux packed layout (the common
    # training case; n_aux is unknown at plan time — assuming 1 is
    # conservative for SGD and exact for Adagrad)
    stride = width * 2
    rpp = max(1, 128 // stride)
    phys_width = max(128, -(-stride // 128) * 128)
    elems_per_row = phys_width / rpp
    rows_hard = max(1, int((2 ** 31) // elems_per_row))
    cap_rows = min(rows_hard,
                   max(1, self.max_class_bytes // (width * 4)))
    total = sum(sh.input_dim for sh in group)
    largest = max(sh.input_dim for sh in group)
    # The plan doesn't know the optimizer yet, so the hard error uses the
    # aux-free bound (illegal for ANY rule); the 1-aux estimate only warns.
    # The exact check (actual n_aux) lives in DistributedLookup.fused_layouts.
    # Host-tier groups are exempt from both: their full image lives in host
    # RAM and only the compact cache+staging buffer (bounded by
    # TieringPlan's own 2^31 check) ever occupies a device — training
    # vocabularies past the device buffer limit is the tier's purpose.
    host_tier = self.table_tier(group[0].table_id) == "host"
    if largest > _rows_hard_noaux(width) and not host_tier:
      big = max(group, key=lambda sh: sh.input_dim)
      _raise_shard_too_big(big.table_id, big.input_dim, width)
    if largest > rows_hard and not host_tier:
      import warnings
      big = max(group, key=lambda sh: sh.input_dim)
      warnings.warn(
          f"table {big.table_id}'s shard of {big.input_dim:,} rows x "
          f"width {width} fits one TPU buffer only WITHOUT packed "
          f"optimizer state (> {rows_hard:,} rows at one aux slot); "
          "training with Adagrad-style rules will fail the exact check "
          "in DistributedLookup.fused_layouts — shard finer for training.")
    n_min = max(1, -(-total // cap_rows))
    order = sorted(group, key=lambda sh: (-occ_of[sh.table_id],
                                          -sh.input_dim, sh.table_id))

    def attempt(n_bins):
      # row target balances bins; a shard over the cap only lands in an
      # empty bin (its generation may then exceed the cap — unavoidable
      # without row-slicing the table)
      rows_cap = min(cap_rows, max(-(-total // n_bins) * 21 // 20, largest))
      bins = [[0, 0.0] for _ in range(n_bins)]  # [rows, occ]
      assign = {}
      for sh in order:
        cands = [g for g in range(n_bins)
                 if bins[g][0] + sh.input_dim <= rows_cap or bins[g][0] == 0]
        if not cands:
          return None, -1.0
        best = min(cands, key=lambda g: (bins[g][1], bins[g][0]))
        assign[id(sh)] = best
        bins[best][0] += sh.input_dim
        bins[best][1] += occ_of[sh.table_id]
      score = min((o / max(1.0, r / rpp) if r else float("inf"))
                  for r, o in bins)
      return assign, score

    candidates = []  # (assign dict, bins [rows, occ] list)
    for n_bins in range(n_min, n_min + 7):
      assign, score = attempt(n_bins)
      if assign is not None:
        candidates.append((assign, score, n_bins))

    if self.batch_hint is None:
      # no absolute id counts: keep the best-balanced candidate
      # (strict > : equal-regime ties keep FEWER generations — fewer
      # gather/scatter launches and routing tensors)
      best_assign, best_score = None, -1.0
      for assign, score, _ in candidates:
        if score > best_score:
          best_assign, best_score = assign, score
    else:
      # absolute id counts known: score every candidate with the measured
      # cost model (fast sorted-scatter path at >= ~0.15 ids/physical-row,
      # else the ~75 ns serial path) and also try a CONCENTRATION layout —
      # when traffic is scarce (small batch, huge vocabularies) no
      # balanced split reaches the fast regime, but packing the heavy
      # multi-hot streams together can carry most ids at fast-path cost
      # while quarantining low-traffic giants into few slow generations.
      T, NS_FAST, NS_SLOW = 0.15, 20.0, 75.0
      b = float(self.batch_hint)

      def cost_of(assign):
        bins: Dict[int, List[float]] = {}
        for sh in group:
          g = assign[id(sh)]
          bins.setdefault(g, [0.0, 0.0])
          bins[g][0] += sh.input_dim
          bins[g][1] += occ_of[sh.table_id]
        total_ns = 0.0
        for r, o in bins.values():
          ids = o * b
          ratio = ids / max(1.0, r / rpp)
          # ~0.2 ms fixed cost per generation (its own gather + scatter
          # launch and routing tensors) breaks regime-cost ties toward
          # fewer, larger generations
          total_ns += ids * (NS_FAST if ratio >= T else NS_SLOW) + 200_000.0
        return total_ns

      conc = self._concentrate(group, occ_of, b, rpp, cap_rows, T)
      if conc is not None:
        candidates.append((conc, 0.0, -1))
      best_assign, best_cost = None, float("inf")
      for assign, _, _ in candidates:
        c = cost_of(assign)
        if c < best_cost:
          best_assign, best_cost = assign, c

    if best_assign is None:  # pathological: give every shard its own gen
      for g, sh in enumerate(order):
        sh.gen = g
      return
    # renumber generations densely in first-appearance order (stable names)
    remap: Dict[int, int] = {}
    for sh in group:
      bnum = best_assign[id(sh)]
      sh.gen = remap.setdefault(bnum, len(remap))

  @staticmethod
  def _concentrate(group, occ_of, batch, rpp, cap_rows, threshold):
    """Concentration generation layout: greedy fast-generation packing in
    traffic-density order, then first-fit-decreasing for the slow pool."""
    dens = lambda sh: (occ_of[sh.table_id] * batch  # noqa: E731
                       / max(1.0, sh.input_dim / rpp))
    order = sorted(group, key=lambda sh: (-dens(sh), sh.table_id))
    assign = {}
    bins: List[List[float]] = []  # [rows, ids]
    cur = None
    slow = []
    for sh in order:
      ids = occ_of[sh.table_id] * batch
      if cur is not None:
        r, i = bins[cur]
        if (r + sh.input_dim <= cap_rows
            and (i + ids) / ((r + sh.input_dim) / rpp) >= threshold):
          assign[id(sh)] = cur
          bins[cur][0] += sh.input_dim
          bins[cur][1] += ids
          continue
      if (sh.input_dim <= cap_rows
          and ids / max(1.0, sh.input_dim / rpp) >= threshold):
        cur = len(bins)
        bins.append([sh.input_dim, ids])
        assign[id(sh)] = cur
      else:
        slow.append(sh)
    # slow pool: plain FFD by rows (composition cannot change its regime)
    for sh in sorted(slow, key=lambda s: (-s.input_dim, s.table_id)):
      placed = False
      for g in range(len(bins)):
        if bins[g][1] == -1 and bins[g][0] + sh.input_dim <= cap_rows:
          assign[id(sh)] = g
          bins[g][0] += sh.input_dim
          placed = True
          break
      if not placed:
        assign[id(sh)] = len(bins)
        bins.append([sh.input_dim, -1])
    return assign if assign else None

  def table_vocab_capacity(self, table_id: int) -> int:
    """Allocatable rows of one table under ``oov='allocate'``: the
    table's own ``TableConfig.vocab_capacity`` when set, else the
    plan-level ``vocab_capacity``, else the full ``input_dim``."""
    cfg = self.global_configs[table_id]
    cap = cfg.input_dim
    if getattr(self, "vocab_capacity", None) is not None:
      cap = min(cap, self.vocab_capacity)
    if getattr(cfg, "vocab_capacity", None) is not None:
      cap = min(cap, cfg.vocab_capacity)
    return cap

  def table_tier(self, table_id: int) -> str:
    """Storage tier of one table: 'host' (cold store + hot cache) or
    'device' (fully HBM-resident)."""
    if self.host_row_threshold is None:
      return "device"
    return ("host"
            if self.global_configs[table_id].input_dim
            > self.host_row_threshold else "device")

  def host_tier_class_keys(self) -> List[ClassKey]:
    """Class keys whose buffers are host-offloaded (in class_keys order)."""
    return [k for k in self.class_keys if self.class_tiers[k] == "host"]

  def tier_capacity_report(self, n_aux: int = 1) -> Dict[str, object]:
    """Per-rank storage accounting by tier.

    Sizes each class's packed buffer under ``n_aux`` interleaved
    optimizer-state slots (1 = Adagrad-style, the conservative default
    the generation assignment also uses; dense classes have no aux
    lanes). Dense-class buffers are estimated at ``max_rows`` — the
    one-hot window tail padding (``lookup_engine.padded_rows``) adds a
    little on top for small-vocab classes. Host-tier entries report the
    COLD STORE footprint; the device side of a host-tier class (hot
    cache + staging + resident map) is chosen by the tiering planner
    against ``hbm_budget_bytes`` (`tiering/plan.py`)."""
    from ..ops.packed_table import PackedLayout

    device = host = 0
    classes = {}
    for key in self.class_keys:
      cp = self.classes[key]
      if cp.kind == "dense":
        nbytes = cp.max_rows * cp.width * 4
      else:
        lay = PackedLayout(rows=cp.max_rows, width=cp.width, n_aux=n_aux)
        nbytes = lay.phys_rows * lay.phys_width * 4
      tier = self.class_tiers[key]
      classes[key] = {"tier": tier, "bytes_per_rank": nbytes}
      if tier == "host":
        host += nbytes
      else:
        device += nbytes
    return {
        "device_bytes_per_rank": device,
        "host_bytes_per_rank": host,
        "hbm_budget_bytes": self.hbm_budget_bytes,
        "classes": classes,
    }

  def exchange_report(self) -> Dict[str, object]:
    """Wire-format summary of the dp<->mp exchange path.

    Per class: its kind and whether the deduplicated exchange applies to
    its padded buckets (sparse-kind classes only — dense MXU classes have
    no row gather to dedup, and ragged value streams already scale with
    the true id count, so both keep the raw exchange; a class serving a
    call-time-ragged input routes that bucket raw even when ``dedup``
    reports True here). ``float_wire_bytes_per_value`` is the in-flight
    element size of activation/cotangent payloads under ``wire_dtype``.
    ``rounds_per_exchange`` is the pipelined schedule's collective count
    per exchange: ``(world - 1) * exchange_chunks`` ppermute rounds
    under ``overlap='pipelined'`` or ``'fused'`` (the jaxpr audit pins
    exactly this per artifact; fused sparse-class exchanges may carry
    fewer when a block has fewer rows than chunks — the per-bucket chunk
    count caps at the row count), 1 monolithic all_to_all otherwise.
    ``jit_gather`` reports whether the fused just-in-time per-round
    gather schedule is active.
    """
    from ..parallel.lookup_engine import class_param_name
    classes = {}
    for key in self.class_keys:
      cp = self.classes[key]
      classes[class_param_name(*key)] = {
          "kind": cp.kind,
          "width": cp.width,
          "dedup": bool(self.dedup_exchange and cp.kind == "sparse"
                        and self.world_size > 1),
      }
    pipelined = (self.overlap in ("pipelined", "fused")
                 and self.world_size > 1)
    return {
        "wire_dtype": self.wire_dtype,
        "dedup_exchange": self.dedup_exchange,
        "dedup_capacity": self.dedup_capacity,
        "float_wire_bytes_per_value": {"f32": 4, "bf16": 2,
                                       "fp8": 1}[self.wire_dtype],
        "overlap": self.overlap,
        "exchange_chunks": self.exchange_chunks,
        "rounds_per_exchange": ((self.world_size - 1) * self.exchange_chunks
                                if pipelined else
                                (1 if self.world_size > 1 else 0)),
        "jit_gather": self.overlap == "fused" and self.world_size > 1,
        "world_size": self.world_size,
        "classes": classes,
    }

  def _kind_of(self, shard: Shard) -> str:
    # row shards always take the gather path: the one-hot window trick
    # assumes slot-local ids cover the full table from offset 0
    if shard.row_sliced:
      return "sparse"
    # tables declared ragged-fed (negative input_hotness hint) stay on the
    # sparse path: the MXU one-hot lookup has no value-stream form, and
    # demoting at plan time is what lets ragged inputs reach ANY
    # non-row-sliced table (reference parity: embedding_lookup_ops.py
    # accepts ragged into any single-process layer)
    if shard.table_id in self._ragged_tables:
      return "sparse"
    return ("dense" if shard.input_dim <= self.dense_row_threshold
            else "sparse")

  def class_key_of(self, shard: Shard) -> ClassKey:
    return (shard.width, shard.combiner, self._kind_of(shard), shard.gen)

  def table_shard_map(self, table_id: int) -> List[Tuple[int, Shard]]:
    """All (rank, shard) holding part of ``table_id``, in (column, row)
    order — column slices concat along width, row slices along vocab."""
    entries = []
    for rank, shards in enumerate(self.rank_shards):
      for sh in shards:
        if sh.table_id == table_id:
          entries.append((rank, sh))
    entries.sort(key=lambda e: (e[1].col_start, e[1].row_start))
    return entries

  def routing_recipe(self, key) -> List[List[Tuple[int, int, int, int,
                                                   int, bool]]]:
    """Host-side routing slots of one class, per rank: ``(input_id,
    row_offset, row_start, shard_rows, vocab, row_sliced)``.

    The numpy replica of the engine's in-trace id routing
    (``lookup_engine._build_routing``): a raw id of ``input_id`` lands on
    ``rank`` at logical row ``clip(id, 0, shard_rows - 1) + row_offset``
    (row-sliced shards keep only ids in ``[row_start, row_start +
    shard_rows)`` after the vocab clamp). One shared recipe so every
    host-side pass that must agree with the traced step's row targeting
    — the tiered prefetcher's classify, the streaming row-generation
    tracker — derives it from the plan instead of hand-copying the
    slot walk."""
    cp = self.classes[key]
    per_rank = []
    for rank in range(self.world_size):
      slots = []
      for slot in cp.slots_per_rank[rank]:
        sh = slot.shard
        vocab = self.global_configs[sh.table_id].input_dim
        slots.append((slot.input_id, slot.row_offset, sh.row_start,
                      sh.input_dim, vocab, sh.row_sliced))
      per_rank.append(slots)
    return per_rank


def routed_rows(slots, cats, ids_of):
  """Apply one rank's :meth:`DistEmbeddingStrategy.routing_recipe` slots
  to a batch: the LOGICAL rows this rank's block is addressed at, as one
  concatenated int64 array (valid ids only — hotness padding dropped;
  occurrences kept, for callers that count traffic).

  ``ids_of(x)`` flattens one input to a 1-D id array — callers differ
  only in their ragged-input policy (the tiered prefetcher refuses
  RaggedIds, the streaming tracker reads the value stream), so the
  routing arithmetic itself lives HERE, once: clip to the shard (or, row
  -sliced, clamp to the vocab then keep the shard's window) and offset
  into the rank block — exactly what the traced step's routing does."""
  import numpy as np
  routed_all = []
  for (input_id, off, row_start, rows, vocab, rs) in slots:
    ids = ids_of(cats[input_id])
    if rs:
      clamped = np.clip(ids, 0, vocab - 1)
      m = (ids >= 0) & (clamped >= row_start) \
          & (clamped < row_start + rows)
      routed = clamped[m] - row_start + off
    else:
      routed = np.clip(ids[ids >= 0], 0, rows - 1) + off
    routed_all.append(routed.astype(np.int64))
  if not routed_all:
    return np.zeros((0,), np.int64)
  return np.concatenate(routed_all)
