"""DistributedEmbedding: hybrid model-parallel embedding over a TPU mesh.

Counterpart of the reference wrapper
(`/root/reference/distributed_embeddings/python/layers/dist_model_parallel.py:327-693`)
with the same constructor surface (embeddings, strategy,
column_slice_threshold, row_slice, dp_input, input_table_map) but a
TPU-native execution model:

- Physical layout: per (width, combiner) class, all ranks' fused tables are
  stacked row-wise into one 2-D array ``[world * max_rows, width]`` sharded
  over the mesh axis. One array per class instead of N per-rank variables
  makes the whole model a uniform SPMD program (see
  ``parallel/lookup_engine.py``).
- Comm: ``lax.all_to_all`` inside ``shard_map`` replaces ``hvd.alltoall``.
- Hybrid single-backward: embedding grads are grads of mesh-sharded arrays —
  local by construction. Dense grads are finalized by ``DistributedOptimizer``
  (an optax transformation) — replacing the reference's Horovod tape/optimizer
  monkey-patching (`dist_model_parallel.py:696-799`) with ~20 functional lines.
- Checkpoint: :func:`get_weights` / :func:`set_weights` give the reference's
  global-view numpy semantics (`dist_model_parallel.py:471-664`); per-shard
  assembly goes through ``jax.make_array_from_callback`` so each device
  materializes only its slice (the TPU equivalent of the reference's chunked
  scatter-update/allgather dance around MPI 32-bit limits).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compat import SHARD_MAP_PSUMS_REPLICATED_GRADS, axis_size
from ..parallel.lookup_engine import (
    DistributedLookup,
    class_param_name,
    pack_mp_inputs,
    padded_rows,
)
from .embedding import resolve_initializer
from .planner import DistEmbeddingStrategy

MP_PARAM_PREFIX = "mp_table_"


def is_model_parallel_param(path_element_names: Sequence[str]) -> bool:
  """True if a param pytree path belongs to a sharded embedding table."""
  return any(str(p).startswith(MP_PARAM_PREFIX) for p in path_element_names)


def make_class_initializer(plan: DistEmbeddingStrategy, key):
  """Initializer for one class buffer [world * max_rows, width].

  Each member shard's rows are drawn from its own table initializer (column
  slices get independent draws at slice shape, matching the reference where
  each slice is its own variable); padding rows are zeros. Equivalent of the
  reference ``ConcatInitializer`` (`dist_model_parallel.py:29-40`) extended
  with row padding. Rank blocks concatenate along the row axis (see
  ``DistributedLookup.param_shapes``).
  """
  cp = plan.classes[key]
  world = plan.world_size
  rows = padded_rows(plan, key)

  def init(rng, shape, dtype=jnp.float32):
    del shape  # fixed by the plan
    blocks = []
    for rank in range(world):
      parts = []
      for sh in cp.shards_per_rank[rank]:
        rng, sub = jax.random.split(rng)
        fn = resolve_initializer(sh.initializer)
        parts.append(jnp.asarray(fn(sub, (sh.input_dim, cp.width)), dtype))
      pad = rows - cp.rows_per_rank[rank]
      if pad:
        parts.append(jnp.zeros((pad, cp.width), dtype))
      blocks.append(jnp.concatenate(parts, axis=0) if parts
                    else jnp.zeros((rows, cp.width), dtype))
    return jnp.concatenate(blocks, axis=0)

  return init


class DistributedEmbedding(nn.Module):
  """Hybrid-parallel distributed embedding layer (flax).

  Args:
    embeddings: global list of ``TableConfig``s / ``Embedding`` layers / dicts.
    strategy: 'basic' | 'memory_balanced' | 'memory_optimized'.
    column_slice_threshold: max elements per slice; None = auto when there
      are fewer tables than workers.
    row_slice: max elements per row (vocabulary) slice, or None. Tables
      larger than this are split along the vocab dim into the smallest
      power-of-two number of row slices under the threshold (capped by
      world size), placed like any other shard. Goes beyond the reference,
      which stubs row slicing with NotImplementedError
      (`dist_model_parallel.py:364-365`). Column slicing wins when both
      thresholds trigger on one table.
    dp_input: True = [B_local, ...] data-parallel inputs; False = packed
      model-parallel inputs from :func:`pack_mp_inputs`.
    input_table_map: input i feeds table input_table_map[i]; None = identity.
    world_size: number of mesh shards (defaults to 1; must equal the mesh
      axis size when used under shard_map).
    axis_name: mesh axis to communicate over.

  Usage with a mesh (world > 1): init params outside shard_map (class params
  get shape [world * max_rows, width]), shard them with
  ``PartitionSpec(axis_name, None)``, and call apply inside
  ``shard_map``. With world == 1 it is an ordinary layer.
  """

  embeddings: Sequence[Any]
  strategy: str = "basic"
  column_slice_threshold: Optional[int] = None
  row_slice: Optional[Any] = None
  dp_input: bool = True
  input_table_map: Optional[Sequence[int]] = None
  world_size: int = 1
  axis_name: str = "mp"
  # Tables with input_dim <= dense_row_threshold are served by the MXU
  # one-hot path instead of HBM row gathers (see planner); 0 disables.
  dense_row_threshold: int = 0
  # Per global input id, its static hotness. Used in BOTH input modes:
  # the planner weighs it when balancing width-class generations so every
  # backward scatter stays in XLA's fast regime (None falls back to
  # inputs-per-table weights — pass it whenever hotness is known up
  # front). With dp_input=False it is additionally REQUIRED to match what
  # was passed to pack_mp_inputs. None = all one-hot.
  input_hotness: Optional[Sequence[int]] = None
  # Expected per-step GLOBAL batch (optional): lets the planner score
  # generation layouts with its measured scatter-regime cost model instead
  # of ratio balancing alone (see planner._assign_generations).
  batch_hint: Optional[int] = None

  def __post_init__(self):
    super().__post_init__()
    if self.row_slice is not None and (isinstance(self.row_slice, bool)
                                       or not isinstance(self.row_slice,
                                                         int)):
      raise TypeError(
          f"row_slice must be an int element threshold, got "
          f"{self.row_slice!r}")

  @property
  def plan(self) -> DistEmbeddingStrategy:
    if not hasattr(self, "_plan_cache"):
      object.__setattr__(
          self, "_plan_cache",
          DistEmbeddingStrategy(
              list(self.embeddings), self.world_size, self.strategy,
              input_table_map=(list(self.input_table_map)
                               if self.input_table_map is not None else None),
              column_slice_threshold=self.column_slice_threshold,
              dense_row_threshold=self.dense_row_threshold,
              row_slice_threshold=self.row_slice,
              input_hotness=(list(self.input_hotness)
                             if self.input_hotness is not None else None),
              batch_hint=self.batch_hint))
    return self._plan_cache

  @nn.compact
  def __call__(self, inputs):
    plan = self.plan
    engine = DistributedLookup(plan, dp_input=self.dp_input,
                               axis_name=self.axis_name)
    shapes = engine.param_shapes()
    class_params = {}
    for key in plan.class_keys:
      name = class_param_name(*key)
      shape = shapes[name]
      if self.is_initializing():
        class_params[name] = self.param(
            name, make_class_initializer(plan, key), shape)
      else:
        # Read the stored value directly: under shard_map the
        # [world * R, w] param arrives as its local [R, w] block, which
        # flax's shape-checking self.param would reject.
        class_params[name] = self.scope.get_variable("params", name)

    if self.is_initializing() and self.world_size > 1:
      # init runs outside shard_map on global shapes; skip the collective
      # forward and just report output structure.
      if self.dp_input:
        from ..parallel.lookup_engine import _batch_of
        b = _batch_of(inputs)
      else:
        first = next(iter(inputs.values()))
        b = first.shape[2] // self.world_size
      return [jnp.zeros((b, cfg.output_dim))
              for cfg in (plan.global_configs[t] for t in plan.input_table_map)]

    if self.dp_input:
      self._sow_oov_metrics(engine, inputs)
      return engine.forward(class_params, inputs)
    return engine.forward_mp(class_params, inputs,
                             hotness=self.input_hotness)

  def _sow_oov_metrics(self, engine, inputs) -> None:
    """Per-class OOV occurrence counters via the ``'metrics'`` variable
    collection — the module-forward counterpart of the counters the
    guarded train step and ``make_sparse_eval_step(with_metrics=True)``
    already return. DP-INPUT forwards only: the packed-mp path
    (``dp_input=False``) receives pre-routed tensors whose per-input id
    view no longer exists here — its ids were clipped/validated at
    ``pack_mp_inputs`` time on the host, where the policy is already
    enforceable eagerly.

    Opt-in by mutability: ``module.apply(vars, x, mutable=['metrics'])``
    returns ``(out, {'metrics': {'oov_<class>': count}})``; a plain
    apply (serving) neither computes nor carries the counters, and init
    never records them (the collection would otherwise pollute the
    variables tree every caller threads around). Counters are int32
    scalars, psum'd across the mesh under ``world_size > 1`` (the
    forward already runs inside shard_map there) — matching the train
    step's global-count convention."""
    if self.is_initializing() or not self.is_mutable_collection("metrics"):
      return
    oov = engine.oov_counts(inputs)
    if self.world_size > 1:
      oov = {n: jax.lax.psum(c, self.axis_name) for n, c in oov.items()}
    for name, c in oov.items():
      # reduce_fn accumulates across calls within one apply (a module
      # invoked twice sums, like the step metrics would)
      self.sow("metrics", f"oov_{name}", c,
               init_fn=lambda: jnp.zeros((), jnp.int32),
               reduce_fn=lambda a, b: a + b)


# ---------------------------------------------------------------------------
# Global-view checkpoint get/set (reference `dist_model_parallel.py:471-664`)
# ---------------------------------------------------------------------------


def _fetch_rows(arr, row0: int, n: int, width: int,
                max_fetch_elements: int) -> np.ndarray:
  """Fetch rows ``[row0, row0+n)`` of a (possibly sharded) device array in
  bounded host-memory chunks.

  Multi-process safe: when ``arr`` is a jax.Array this process cannot
  fully address (multi-controller runs), the window is assembled from
  ``addressable_shards`` instead of global indexing — which works exactly
  when this process's devices hold the window. A window owned by another
  process raises with guidance instead of hanging or crashing inside
  XLA (the reference handles the same situation with chunked
  ``hvd.allgather``, `dist_model_parallel.py:596-617`; here cross-process
  windows are served by the per-process checkpoint files instead)."""
  if isinstance(arr, jax.Array) and not arr.is_fully_addressable:
    from ..parallel.mesh import addressable_row_spans
    out = np.empty((n, arr.shape[1]) if arr.ndim == 2 else (n,),
                   arr.dtype)
    have = np.zeros((n,), bool)
    for s0, s1, shard in addressable_row_spans(arr):
      lo, hi = max(s0, row0), min(s1, row0 + n)
      if lo < hi:
        # slice ON DEVICE before the host copy: a small window over a
        # multi-GiB local shard must not stage the whole shard on host
        # (this function's bounded-host-memory contract)
        out[lo - row0:hi - row0] = np.asarray(
            shard.data[lo - s0:hi - s0])
        have[lo - row0:hi - row0] = True
    if not have.all():
      raise RuntimeError(
          f"rows [{row0}, {row0 + n}) of a non-fully-addressable array are "
          "not owned by this process. In multi-controller runs, fetch "
          "global weights from the per-process checkpoint files "
          "(checkpoint.save writes only locally-addressable rank blocks) "
          "or restrict get_weights to tables whose shards are local.")
    return out
  chunk = max(1, max_fetch_elements // max(1, width))
  if n <= chunk:
    return np.asarray(jax.device_get(arr[row0:row0 + n]))
  out = None
  for c0 in range(0, n, chunk):
    cn = min(chunk, n - c0)
    block = np.asarray(jax.device_get(arr[row0 + c0:row0 + c0 + cn]))
    if out is None:
      out = np.empty((n,) + block.shape[1:], block.dtype)
    out[c0:c0 + cn] = block
  return out


def get_weights(plan: DistEmbeddingStrategy,
                class_params: Dict[str, Any],
                max_fetch_elements: int = 1 << 27) -> List[np.ndarray]:
  """Reassemble the global per-table weights from class-stacked params.

  Inverse of :func:`set_weights`: unstacks each rank's fused rows, undoes
  concat fusion via shard row offsets, and re-concatenates column slices in
  column order. On a single-controller setup the sharded arrays are fully
  addressable so this is collective-free (the reference needed chunked
  ``hvd.allgather``, capped at 2G elements per chunk,
  `dist_model_parallel.py:596-617`, for the same reason this function
  fetches per-shard row windows in ``max_fetch_elements``-bounded blocks:
  a global view of a jumbo class buffer must never be staged on one host
  at once — peak extra host memory here is one table plus one block).
  """
  weights = []
  for t, config in enumerate(plan.global_configs):
    parts = []
    row_sliced = False
    for rank, shard in plan.table_shard_map(t):
      key = plan.class_key_of(shard)
      cp = plan.classes[key]
      idx = cp.shards_per_rank[rank].index(shard)
      row0 = rank * padded_rows(plan, key) + \
          cp.row_offsets_per_rank[rank][idx]
      parts.append(_fetch_rows(class_params[class_param_name(*key)],
                               row0, shard.input_dim, cp.width,
                               max_fetch_elements))
      row_sliced = shard.row_sliced
    if len(parts) == 1:
      weights.append(parts[0])
    else:
      # table_shard_map orders by (col_start, row_start); a table is sliced
      # along exactly one dim, so this is a plain concat either way
      weights.append(np.concatenate(parts, axis=0 if row_sliced else 1))
  return weights


def set_weights(plan: DistEmbeddingStrategy,
                weights: Sequence[Union[np.ndarray, str]],
                mesh: Optional[Mesh] = None,
                axis_name: str = "mp") -> Dict[str, Any]:
  """Build class-stacked params from global per-table weights.

  Args:
    plan: the strategy.
    weights: per original table, [input_dim, output_dim] numpy arrays or
      ``.npy`` paths (mmap'd, like the reference `dist_model_parallel.py:492-493`).
    mesh: if given, assemble directly into mesh-sharded arrays via
      ``jax.make_array_from_callback`` — each device materializes only its
      own [max_rows, width] slice, so terabyte tables never exist on one host
      (TPU-native replacement for the reference's chunked scatter_update).

  Returns:
    name -> [world * max_rows, width] arrays (numpy if mesh is None).
  """
  if len(weights) != len(plan.global_configs):
    raise ValueError(
        f"Expected {len(plan.global_configs)} weights, got {len(weights)}")
  loaded = [np.load(w, mmap_mode="r") if isinstance(w, str) else np.asarray(w)
            for w in weights]
  for t, (w, cfg) in enumerate(zip(loaded, plan.global_configs)):
    if w.shape != (cfg.input_dim, cfg.output_dim):
      raise ValueError(f"weights[{t}] has shape {w.shape}, expected "
                       f"{(cfg.input_dim, cfg.output_dim)}")

  def rank_block(key, rank) -> np.ndarray:
    cp = plan.classes[key]
    block = np.zeros((padded_rows(plan, key), cp.width), np.float32)
    for idx, shard in enumerate(cp.shards_per_rank[rank]):
      row0 = cp.row_offsets_per_rank[rank][idx]
      block[row0:row0 + shard.input_dim] = (
          loaded[shard.table_id][
              shard.row_start:shard.row_start + shard.input_dim,
              shard.col_start:shard.col_end])
    return block

  out = {}
  for key in plan.class_keys:
    cp = plan.classes[key]
    name = class_param_name(*key)
    rows = padded_rows(plan, key)
    shape = (plan.world_size * rows, cp.width)
    if mesh is None:
      out[name] = np.concatenate([rank_block(key, r)
                                  for r in range(plan.world_size)])
    else:
      sharding = NamedSharding(mesh, P(axis_name, None))

      def cb(index, key=key, rows=rows):
        rank = (index[0].start or 0) // rows
        return rank_block(key, rank)

      out[name] = jax.make_array_from_callback(shape, sharding, cb)
  return out


# ---------------------------------------------------------------------------
# Hybrid-parallel training utilities
# (replacing the reference Horovod shims, `dist_model_parallel.py:696-799`)
# ---------------------------------------------------------------------------


def broadcast_variables(variables, root_rank: int = 0):
  """API-parity shim for the reference ``broadcast_variables``
  (`dist_model_parallel.py:698-712`).

  Under JAX there is nothing to broadcast: dense (data-parallel) params are
  *replicated by sharding* (``PartitionSpec()``), so every device reads the
  same buffer by construction, and model-parallel class params are sharded.
  Returns the variables unchanged.
  """
  del root_rank
  return variables


def hybrid_partition_specs(tree, axis_name: str = "mp"):
  """PartitionSpecs for any params-structured pytree (incl. optax states).

  Leaves under an ``mp_table_*`` key get ``P(axis_name, None)`` (the
  class-stacked ``[world * rows, width]`` table layout); everything else is
  replicated ``P()``. Use for shard_map in/out_specs of params, grads, and
  optimizer states — e.g. adagrad's ``sum_of_squares`` mirrors the param
  tree and must shard the same way (the reference gets this implicitly from
  per-rank TF slot variables; here it is one tree_map).
  """
  def spec(path, leaf):
    names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
    if is_model_parallel_param(names) and getattr(leaf, "ndim", 0) == 2:
      return P(axis_name, None)
    return P()

  return jax.tree_util.tree_map_with_path(spec, tree)


def finalize_hybrid_grads(grads, axis_name: str = "mp"):
  """Convert in-shard_map autodiff grads to global-batch-mean grads.

  The single-backward hybrid-parallel core, TPU-style. With a per-device
  loss of ``mean(batch_shard)``, autodiff under ``jax.shard_map`` already
  produces, per leaf:

  - dense (replicated, ``P()``) params: the *psum* of all devices'
    local-mean grads — shard_map inserts the psum because the transpose of
    replication is a sum (so do NOT psum again);
  - ``mp_table_*`` (sharded) params: the local shard's grad, with remote
    contributions already summed in by the reverse ``all_to_all``.

  Both are ``world_size ×`` the single-device global-batch-mean gradient, so
  dividing every leaf by the axis size yields grads *numerically identical*
  to non-distributed training — which is what the reference achieves with
  ``register_local_var`` + averaging Horovod allreduce
  (`dist_model_parallel.py:715-773`).

  On jax 0.4.x, whose experimental shard_map does NOT insert the
  replicated-grad psum during in-body autodiff
  (``compat.SHARD_MAP_PSUMS_REPLICATED_GRADS``), the psum is applied here
  explicitly — to replicated leaves only; ``mp_table_*`` shard grads are
  rank-local by construction and summing them would mix different tables'
  row windows.
  """
  scale = 1.0 / axis_size(axis_name)
  if not SHARD_MAP_PSUMS_REPLICATED_GRADS:
    def fin(path, g):
      names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
      if is_model_parallel_param(names):
        return g * scale
      return jax.lax.psum(g, axis_name) * scale
    return jax.tree_util.tree_map_with_path(fin, grads)
  return jax.tree_util.tree_map(lambda g: g * scale, grads)


def DistributedOptimizer(optimizer, axis_name: str = "mp"):
  """Wrap an optax optimizer for hybrid parallel in a single backward.

  Equivalent of the reference ``DistributedOptimizer``
  (`dist_model_parallel.py:743-773`): rescales shard_map autodiff grads to
  the global-batch-mean convention (see :func:`finalize_hybrid_grads`) and
  applies model-parallel (``mp_table_*``) grads locally. Use inside
  shard_map with a local-mean loss.
  """
  import optax

  def init_fn(params):
    return optimizer.init(params)

  def update_fn(updates, state, params=None):
    updates = finalize_hybrid_grads(updates, axis_name)
    return optimizer.update(updates, state, params)

  return optax.GradientTransformation(init_fn, update_fn)


def DistributedGradientTape(*args, **kwargs):
  """The reference patches Horovod's tape to mix local (model-parallel) and
  allreduced (data-parallel) grads in one backward
  (`dist_model_parallel.py:715-740`). JAX has no tape: use
  ``jax.value_and_grad`` inside shard_map and pass the grads through
  :func:`finalize_hybrid_grads` (or use :func:`DistributedOptimizer`)."""
  raise NotImplementedError(
      "JAX has no gradient tape. Use jax.value_and_grad inside shard_map + "
      "finalize_hybrid_grads / DistributedOptimizer for hybrid parallel.")


class BroadcastGlobalVariablesCallback:
  """API-parity shim (reference `dist_model_parallel.py:776-799`): dense
  variables are replicated by sharding, so initial-state broadcast is a
  no-op under JAX. Provided so training scripts can keep their structure."""

  def __init__(self, root_rank: int = 0, *args, **kwargs):
    self.root_rank = root_rank

  def on_batch_end(self, batch, logs=None):
    return None
