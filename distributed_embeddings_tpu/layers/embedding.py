"""Embedding layers (flax.linen).

TPU-native counterpart of the reference Keras layers
(`/root/reference/distributed_embeddings/python/layers/embedding.py:41-180`):
an ``Embedding`` unifying plain and combiner (multi-hot) lookups over dense /
ragged / sparse inputs, and ``ConcatOneHotEmbedding`` fusing N one-hot tables
into one weight.

Differences by design:
- flax modules are pure; parameters live in pytrees, so the reference's
  ``CPUInitializer`` (GPU-OOM workaround, `embedding.py:28-38`) is unnecessary —
  giant tables are initialized directly into their sharded layout via
  ``jax.jit`` + sharding annotations.
- ``get_config`` / ``from_config`` are kept for planner interop
  (``DistEmbeddingStrategy`` consumes layer configs the same way the reference
  does, `dist_model_parallel.py:95-98`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..ops.embedding_lookup import embedding_lookup
from ..ops.ragged import RaggedIds, SparseIds

Initializer = Callable[[jax.Array, tuple, Any], jax.Array]


def _keras_uniform(scale=0.05):
  def init(key, shape, dtype=jnp.float32):
    return jax.random.uniform(key, shape, dtype, minval=-scale, maxval=scale)
  # marker consumed by the direct packed-state initializer
  # (training.init_sparse_state_direct): uniform(-scale, scale) can be
  # generated straight into the packed physical layout without ever
  # materializing the [rows, width] logical table
  init.scale = scale
  return init


_NAMED_INITIALIZERS = {
    "uniform": _keras_uniform,
    "random_uniform": _keras_uniform,
    "normal": lambda: nn.initializers.normal(stddev=0.05),
    "random_normal": lambda: nn.initializers.normal(stddev=0.05),
    "zeros": lambda: nn.initializers.zeros_init(),
    "ones": lambda: nn.initializers.ones_init(),
    "glorot_uniform": lambda: nn.initializers.glorot_uniform(),
    "glorot_normal": lambda: nn.initializers.glorot_normal(),
    "he_uniform": lambda: nn.initializers.he_uniform(),
    "he_normal": lambda: nn.initializers.he_normal(),
}


def resolve_initializer(spec: Union[str, Initializer, None]) -> Initializer:
  """Accepts a named initializer (Keras-style), a callable, or None."""
  if spec is None:
    return _keras_uniform()
  if callable(spec):
    return spec
  if isinstance(spec, str):
    key = spec.lower()
    if key in _NAMED_INITIALIZERS:
      return _NAMED_INITIALIZERS[key]()
    raise ValueError(f"Unknown initializer {spec!r}")
  raise TypeError(f"Cannot resolve initializer from {spec!r}")


# ---------------------------------------------------------------------------
# Regularizers / constraints (reference `embedding.py:62-70,96-100` accepts
# Keras regularizer/constraint objects; here the Keras names resolve to
# plain callables)
# ---------------------------------------------------------------------------


def _l1(factor=0.01):
  return lambda w: factor * jnp.sum(jnp.abs(w))


def _l2(factor=0.01):
  return lambda w: factor * jnp.sum(jnp.square(w))


_NAMED_REGULARIZERS = {
    "l1": _l1,
    "l2": _l2,
    "l1_l2": lambda: (lambda w: 0.01 * jnp.sum(jnp.abs(w))
                      + 0.01 * jnp.sum(jnp.square(w))),
}


def resolve_regularizer(spec) -> Optional[Callable[[jax.Array], jax.Array]]:
  """``None`` | Keras name ('l1'/'l2'/'l1_l2') | ``{'name': .., 'factor': ..}``
  | callable -> callable.

  The callable maps a weight array to a scalar penalty added to the loss
  (Keras regularizer semantics, defaults matching ``keras.regularizers``)."""
  if spec is None:
    return None
  if callable(spec):
    return spec
  if isinstance(spec, dict):
    d = {str(k).lower(): v for k, v in spec.items()}
    name = str(d.get("name", "")).lower()
    if name in ("l1", "l2"):
      factor = float(d.get("factor", d.get(name, 0.01)))
      return (_l1 if name == "l1" else _l2)(factor)
    if name == "l1_l2":
      f1 = float(d.get("l1", 0.01))
      f2 = float(d.get("l2", 0.01))
      return lambda w: (f1 * jnp.sum(jnp.abs(w))
                        + f2 * jnp.sum(jnp.square(w)))
    raise ValueError(f"Unknown regularizer spec {spec!r}")
  if isinstance(spec, str):
    key = spec.lower()
    if key in _NAMED_REGULARIZERS:
      return _NAMED_REGULARIZERS[key]()
    raise ValueError(f"Unknown regularizer {spec!r}")
  raise TypeError(f"Cannot resolve regularizer from {spec!r}")


def l2_decay_factor(spec) -> Optional[float]:
  """λ when ``spec`` is a recognizable PURE-l2 regularizer, else None.

  The fused sparse path can fold exactly this form into its per-occurrence
  deltas (``SparseRule.weight_decay``); every other regularizer shape
  (l1, custom callables) has no additive touched-rows form."""
  if isinstance(spec, str) and spec.lower() == "l2":
    return 0.01  # keras.regularizers.l2 default
  if isinstance(spec, dict):
    d = {str(k).lower(): v for k, v in spec.items()}
    if str(d.get("name", "")).lower() == "l2":
      return float(d.get("factor", d.get("l2", 0.01)))
  return None


def _max_norm(max_value=2.0, eps=1e-7):
  def project(w):
    norms = jnp.sqrt(jnp.sum(jnp.square(w), axis=-1, keepdims=True))
    desired = jnp.clip(norms, 0, max_value)
    return w * (desired / (eps + norms))
  return project


def _unit_norm(eps=1e-7):
  def project(w):
    return w / (eps + jnp.sqrt(jnp.sum(jnp.square(w), axis=-1,
                                       keepdims=True)))
  return project


_NAMED_CONSTRAINTS = {
    "non_neg": lambda: (lambda w: jnp.maximum(w, 0.0)),
    "max_norm": _max_norm,
    "unit_norm": _unit_norm,
}


def resolve_constraint(spec) -> Optional[Callable[[jax.Array], jax.Array]]:
  """``None`` | Keras name ('non_neg'/'max_norm'/'unit_norm') | callable.

  The callable projects a weight array after each optimizer update (Keras
  constraint semantics; per-row norms use the last axis)."""
  if spec is None:
    return None
  if callable(spec):
    return spec
  if isinstance(spec, str):
    key = spec.lower()
    if key in _NAMED_CONSTRAINTS:
      return _NAMED_CONSTRAINTS[key]()
    raise ValueError(f"Unknown constraint {spec!r}")
  raise TypeError(f"Cannot resolve constraint from {spec!r}")


class Embedding(nn.Module):
  """Turns indices into vectors of fixed size; optional multi-hot reduce.

  Parity with the reference ``Embedding`` (`embedding.py:41-152`). When
  ``combiner`` is not None, supported inputs and output shapes:

  - N-D integer array ``(d1,...,dn)`` -> ``(d1,...,dn-1, output_dim)``, N >= 2
  - 2-D ``RaggedIds`` ``(batch, ragged)`` -> ``(batch, output_dim)``
  - 2-D ``SparseIds`` ``(batch, max_hot)`` -> ``(batch, output_dim)``

  With ``combiner=None``, output is ``input.shape + (output_dim,)``.

  Regularizers (reference `embedding.py:64-70,96-100`): penalties are
  ``sow``n into the ``"losses"`` collection — run
  ``apply({...}, x, mutable=["losses"])`` and add
  :func:`collect_regularization_losses` of the mutated collection to the
  loss. The constraint is a post-update projection: apply
  :meth:`apply_constraint` (the train-step builders in ``training.py`` do
  both automatically for distributed plans).

  Attributes:
    input_dim: vocabulary size (max index + 1).
    output_dim: embedding width.
    embeddings_initializer: named or callable initializer.
    embeddings_regularizer: None | 'l1'/'l2'/'l1_l2' | callable -> scalar
      penalty on the table.
    activity_regularizer: same, applied to the layer output.
    embeddings_constraint: None | 'non_neg'/'max_norm'/'unit_norm' |
      callable row projection applied after optimizer updates.
    combiner: None, 'sum', or 'mean'.
  """

  input_dim: int
  output_dim: int
  embeddings_initializer: Union[str, Initializer, None] = "uniform"
  embeddings_regularizer: Any = None
  activity_regularizer: Any = None
  embeddings_constraint: Any = None
  combiner: Optional[str] = None
  param_dtype: Any = jnp.float32

  def __post_init__(self):
    super().__post_init__()
    if self.input_dim <= 0 or self.output_dim <= 0:
      raise ValueError(
          "Both input_dim and output_dim should be positive, "
          f"found {self.input_dim} and {self.output_dim}")

  @nn.compact
  def __call__(self, inputs):
    embeddings = self.param(
        "embeddings",
        resolve_initializer(self.embeddings_initializer),
        (self.input_dim, self.output_dim),
        self.param_dtype,
    )
    out = self.lookup(embeddings, inputs)
    reg = resolve_regularizer(self.embeddings_regularizer)
    if reg is not None:
      # overwrite, don't append: a shared layer applied N times must count
      # its WEIGHT penalty once (Keras adds it per variable, not per call)
      self.sow("losses", "embeddings_regularizer", reg(embeddings),
               reduce_fn=lambda prev, new: new,
               init_fn=lambda: jnp.zeros(()))
    act_reg = resolve_regularizer(self.activity_regularizer)
    if act_reg is not None:
      # accumulate: the ACTIVITY penalty applies to every call's output
      self.sow("losses", "activity_regularizer", act_reg(out),
               reduce_fn=lambda prev, new: prev + new,
               init_fn=lambda: jnp.zeros(()))
    return out

  def apply_constraint(self, embeddings: jax.Array) -> jax.Array:
    """Post-update projection of the table (Keras constraint semantics)."""
    proj = resolve_constraint(self.embeddings_constraint)
    return embeddings if proj is None else proj(embeddings)

  def lookup(self, embeddings, inputs):
    """Input normalization + lookup (reference `embedding.py:108-133`)."""
    if isinstance(inputs, (RaggedIds, SparseIds)):
      return embedding_lookup(embeddings, inputs, combiner=self.combiner)
    inputs = jnp.asarray(inputs)
    if not jnp.issubdtype(inputs.dtype, jnp.integer):
      inputs = inputs.astype(jnp.int32)
    out_shape = None
    if inputs.ndim == 1:
      if self.combiner is not None:
        raise ValueError(
            "1D input with combiner is ambiguous. Please create batch dimension.")
      inputs = inputs.reshape(-1, 1)
      out_shape = (-1, self.output_dim)
    elif inputs.ndim > 2:
      if self.combiner is None:
        out_shape = inputs.shape + (self.output_dim,)
      else:
        out_shape = inputs.shape[:-1] + (self.output_dim,)
      inputs = inputs.reshape(-1, inputs.shape[-1])
    out = embedding_lookup(embeddings, inputs, combiner=self.combiner)
    if out_shape is not None:
      out = out.reshape(out_shape)
    return out

  def get_config(self):
    return {
        "input_dim": self.input_dim,
        "output_dim": self.output_dim,
        "embeddings_initializer": self.embeddings_initializer,
        "embeddings_regularizer": self.embeddings_regularizer,
        "activity_regularizer": self.activity_regularizer,
        "embeddings_constraint": self.embeddings_constraint,
        "combiner": self.combiner,
        "name": self.name,
    }

  @classmethod
  def from_config(cls, config):
    config = dict(config)
    config.pop("mask_zero", None)
    config.pop("input_length", None)
    config.pop("name", None)
    return cls(**config)


def collect_regularization_losses(variables) -> jax.Array:
  """Sum every penalty sown into a ``"losses"`` collection.

  ``variables`` is the mutated-collection dict returned by
  ``module.apply(..., mutable=["losses"])`` (or its ``"losses"`` subtree)."""
  tree = variables.get("losses", variables) if isinstance(variables, dict) \
      else variables
  leaves = jax.tree_util.tree_leaves(tree)
  if not leaves:
    return jnp.zeros(())
  return sum(jnp.sum(jnp.asarray(x)) for x in leaves)


@dataclasses.dataclass
class TableConfig:
  """Plain-data description of one embedding table, for the planner.

  Equivalent to a reference layer config dict
  (`dist_model_parallel.py:95-98`). ``from_layer``/``to_layer`` convert to and
  from ``Embedding`` modules.
  """

  input_dim: int
  output_dim: int
  combiner: Optional[str] = None
  initializer: Union[str, Initializer, None] = "uniform"
  regularizer: Any = None  # table penalty (None | name | callable)
  constraint: Any = None  # post-update row projection (None | name | callable)
  name: Optional[str] = None
  # Dynamic vocabulary (plan oov='allocate'): allocatable rows of THIS
  # table, overriding the plan-level ``vocab_capacity`` downward (a hot
  # user table and a long-tail item table rarely want one global cap).
  # None defers to the plan; the planner refuses the field on static
  # plans, and it never changes any buffer layout — the manifest's
  # ``vocab`` section pins the resulting capacity, not this knob.
  vocab_capacity: Optional[int] = None

  def size(self) -> int:
    return self.input_dim * self.output_dim

  @classmethod
  def from_layer(cls, layer: Embedding) -> "TableConfig":
    if layer.activity_regularizer is not None:
      raise ValueError(
          "activity_regularizer is not supported in the distributed path "
          f"(table {layer.name!r}): apply it to the layer outputs in the "
          "model's loss instead")
    return cls(
        input_dim=layer.input_dim,
        output_dim=layer.output_dim,
        combiner=layer.combiner,
        initializer=layer.embeddings_initializer,
        regularizer=layer.embeddings_regularizer,
        constraint=layer.embeddings_constraint,
        name=layer.name,
    )

  def to_layer(self) -> Embedding:
    return Embedding(
        input_dim=self.input_dim,
        output_dim=self.output_dim,
        embeddings_initializer=self.initializer,
        embeddings_regularizer=self.regularizer,
        embeddings_constraint=self.constraint,
        combiner=self.combiner,
    )


class ConcatOneHotEmbedding(nn.Module):
  """N one-hot tables concatenated row-wise into a single weight.

  Parity with the reference ``ConcatOneHotEmbedding`` (`embedding.py:155-180`):
  lookup adds per-feature row offsets, then performs one gather.
  """

  feature_sizes: tuple
  embedding_width: int
  params_initializer: Union[str, Initializer, None] = "uniform"

  @nn.compact
  def __call__(self, inputs):
    offsets = np.concatenate([[0], np.cumsum(self.feature_sizes)])
    table = self.param(
        "embeddings",
        resolve_initializer(self.params_initializer),
        (int(offsets[-1]), self.embedding_width),
        jnp.float32,
    )
    if inputs.shape[-1] != len(self.feature_sizes):
      raise ValueError(
          f"Expected {len(self.feature_sizes)} features, got {inputs.shape[-1]}")
    # clamp per feature so a bad id cannot bleed into the next table's rows
    sizes = jnp.asarray(np.asarray(self.feature_sizes), inputs.dtype)
    clamped = jnp.clip(inputs, 0, sizes - 1)
    shifted = clamped + jnp.asarray(offsets[:-1], inputs.dtype)
    return jnp.take(table, shifted, axis=0, mode="clip")
