"""Embedding layers."""

from .embedding import ConcatOneHotEmbedding, Embedding, TableConfig

__all__ = ["ConcatOneHotEmbedding", "Embedding", "TableConfig"]
