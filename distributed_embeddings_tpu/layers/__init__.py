"""Embedding layers."""

from .dist_model_parallel import (
    BroadcastGlobalVariablesCallback,
    DistributedEmbedding,
    DistributedOptimizer,
    broadcast_variables,
    finalize_hybrid_grads,
    get_weights,
    hybrid_partition_specs,
    set_weights,
)
from .embedding import ConcatOneHotEmbedding, Embedding, TableConfig
from .planner import DistEmbeddingStrategy

__all__ = [
    "BroadcastGlobalVariablesCallback",
    "ConcatOneHotEmbedding",
    "DistEmbeddingStrategy",
    "DistributedEmbedding",
    "DistributedOptimizer",
    "Embedding",
    "TableConfig",
    "broadcast_variables",
    "finalize_hybrid_grads",
    "get_weights",
    "hybrid_partition_specs",
    "set_weights",
]
