"""Count-min-sketch frequency admission for the dynamic vocabulary.

A row is a scarce resource: the embedding-bag access skew measured in
"Dissecting Embedding Bag Performance in DLRM Inference" (PAPERS.md)
means most raw ids in a production stream are seen once and never again
— materializing a row (table + interleaved optimizer lanes) for each is
pure waste. The admission policy therefore requires an id to be OBSERVED
``admit_threshold`` times before it earns a row, and the observation
counts live in a count-min sketch: O(depth x width) memory regardless of
the raw id universe, with the classic one-sided error — the estimate
NEVER undercounts, and overcounts by at most the hash-collision mass in
an id's cells (so admission can only err toward admitting a little
early, never toward starving a genuinely hot id).

Host-side numpy, fixed-constant hashing (one splitmix64 finalizer per
depth row, seeded by the row index): deterministic across runs and
restores, no RNG (the sketch is checkpoint state — its counts persist
through the manifest's ``vocab`` section so admission decisions resume
exactly).
"""

from __future__ import annotations

import numpy as np

from .table import _mix


class CountMinSketch:
  """``depth`` rows of ``width`` int64 counters, min-of-rows estimates.

  ``width`` must be a power of two (masked indexing); the defaults hold
  ~1M-id working sets with small overcount at a few MiB of host RAM.
  """

  def __init__(self, width: int = 1 << 16, depth: int = 4):
    if width < 2 or width & (width - 1):
      raise ValueError(f"width must be a power of two >= 2, got {width}")
    if depth < 1:
      raise ValueError(f"depth must be >= 1, got {depth}")
    self.width = int(width)
    self.depth = int(depth)
    self.counts = np.zeros((depth, width), np.int64)
    # one fixed odd salt per depth row: the same id lands in independent
    # columns per row, which is what makes min-of-rows tighten
    self._salts = (np.arange(1, depth + 1, dtype=np.uint64)
                   * np.uint64(0x9E3779B97F4A7C15)) | np.uint64(1)

  def _cols(self, ids: np.ndarray) -> np.ndarray:
    """[depth, n] column indices of ``ids`` (int64, any shape)."""
    x = np.ascontiguousarray(ids, np.int64).reshape(-1).astype(np.uint64)
    mask = np.uint64(self.width - 1)
    return np.stack([(_mix(x ^ s) & mask).astype(np.int64)
                     for s in self._salts])

  def update(self, ids: np.ndarray) -> None:
    """Count one OCCURRENCE per entry of ``ids`` (duplicates add)."""
    ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
    if not ids.size:
      return
    cols = self._cols(ids)
    for j in range(self.depth):
      np.add.at(self.counts[j], cols[j], 1)

  def estimate(self, ids: np.ndarray) -> np.ndarray:
    """Per id, the count estimate (int64; >= the true count, always)."""
    ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
    if not ids.size:
      return np.zeros((0,), np.int64)
    cols = self._cols(ids)
    ests = np.stack([self.counts[j][cols[j]] for j in range(self.depth)])
    return np.min(ests, axis=0)

  # ---- serialization ------------------------------------------------------
  def state(self) -> np.ndarray:
    return self.counts

  def load_state(self, counts: np.ndarray) -> None:
    if counts.shape != self.counts.shape:
      raise ValueError(
          f"sketch state shape {counts.shape} does not match this "
          f"sketch's ({self.counts.shape}) — width/depth differ from "
          "the saving run's.")
    self.counts = np.asarray(counts, np.int64).copy()
