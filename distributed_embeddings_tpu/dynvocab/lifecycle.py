"""Row lifecycle: TTL eviction, freelist recycling, in-place re-zeroing.

Rows of a dynamic table cycle through three states — free (never
allocated, or reclaimed), live (mapped to a raw id), expired (live but
unobserved for ``evict_ttl`` steps). :class:`RowRecycler` owns the
host-side bookkeeping (``row_to_id`` inverse map, ``last_seen`` step
stamps, a FIFO freelist so the longest-reclaimed row is reused first —
deterministic), and :func:`zero_rows_update` is the device side: an
evicted LOGICAL row's lanes — the table row AND its interleaved
optimizer-state lanes — are scattered to zero inside the packed class
buffer before the row can be re-admitted, so a recycled row trains
exactly like the training-neutral padding rows an elastic re-shard
re-zeroes (a stale Adagrad accumulator leaking into a new id's first
update would silently skew its learning rate).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


class RowRecycler:
  """Free/live/expired bookkeeping for one dynamic table's rows."""

  def __init__(self, capacity: int):
    self.capacity = int(capacity)
    self.row_to_id = np.full((self.capacity,), -1, np.int64)
    self.last_seen = np.full((self.capacity,), -1, np.int64)
    # FIFO freelist as a numpy ring would be overkill at eviction volume;
    # a plain int list keeps serialization trivial and order explicit
    self.freelist: list = []
    self.next_fresh = 0

  @property
  def occupancy(self) -> int:
    return int(np.sum(self.row_to_id >= 0))

  def allocate(self, raw_id: int, step: int) -> int:
    """A row for ``raw_id``: oldest freelist entry first, else the next
    never-used row; -1 when the capacity is exhausted (denied)."""
    if self.freelist:
      row = self.freelist.pop(0)
    elif self.next_fresh < self.capacity:
      row = self.next_fresh
      self.next_fresh += 1
    else:
      return -1
    self.row_to_id[row] = raw_id
    self.last_seen[row] = step
    return row

  def touch(self, rows: np.ndarray, step: int) -> None:
    if rows.size:
      self.last_seen[rows] = step

  def expired(self, step: int, ttl: int) -> np.ndarray:
    """Live rows unobserved for more than ``ttl`` steps (ascending)."""
    live = self.row_to_id >= 0
    return np.where(live & (step - self.last_seen > ttl))[0]

  def release(self, row: int) -> None:
    self.row_to_id[row] = -1
    self.last_seen[row] = -1
    self.freelist.append(int(row))

  # ---- serialization ------------------------------------------------------
  def state(self) -> dict:
    return {
        "row_to_id": self.row_to_id,
        "last_seen": self.last_seen,
        "freelist": np.asarray(self.freelist, np.int64),
        "next_fresh": np.asarray([self.next_fresh], np.int64),
    }

  def load_state(self, state: dict) -> None:
    row_to_id = np.asarray(state["row_to_id"], np.int64)
    if row_to_id.shape != self.row_to_id.shape:
      raise ValueError(
          f"recycler state has {row_to_id.shape[0]} rows, this table has "
          f"{self.capacity} — vocab_capacity differs from the saving run.")
    self.row_to_id = row_to_id.copy()
    self.last_seen = np.asarray(state["last_seen"], np.int64).copy()
    self.freelist = [int(r) for r in np.asarray(state["freelist"])]
    self.next_fresh = int(np.asarray(state["next_fresh"]).reshape(-1)[0])


@functools.partial(jax.jit, static_argnums=(2,), donate_argnums=(0,))
def _zero_lanes(buf: jax.Array, grp_sub: jax.Array, stride: int) -> jax.Array:
  """Zero ``stride`` lanes per (physical row, sub-row) pair in place.

  ``grp_sub``: int32 ``[n, 2]`` — global physical row index and the
  logical row's sub-row slot within it. Donated, so the multi-GiB packed
  buffer is never copied; duplicate targets (a padded tail repeating the
  first pair) are harmless because the written value is a constant 0."""
  grp = grp_sub[:, 0]
  lanes = grp_sub[:, 1:2] * stride + jnp.arange(stride, dtype=jnp.int32)
  return buf.at[grp[:, None], lanes].set(0.0)


def zero_rows_update(layout, buf: jax.Array, grp: np.ndarray,
                     sub: np.ndarray) -> jax.Array:
  """Zero logical rows ``(grp, sub)`` of one packed class buffer.

  ``grp`` indexes GLOBAL physical rows (rank blocks stacked — the fused
  buffer's row axis); ``sub`` is each row's slot within its physical row
  (``local_row % rows_per_phys``). The target count is padded to the
  next power of two (repeating the first target) so eviction bursts of
  any size reuse a handful of jit traces instead of one per distinct
  count."""
  n = int(grp.shape[0])
  if n == 0:
    return buf
  cap = 1
  while cap < n:
    cap *= 2
  pairs = np.stack([np.asarray(grp, np.int64),
                    np.asarray(sub, np.int64)], axis=1)
  if cap > n:
    pairs = np.concatenate(
        [pairs, np.broadcast_to(pairs[:1], (cap - n, 2))])
  # values are bounded by the buffer's 2^31-element indexing; int32 wire
  return _zero_lanes(buf, jnp.asarray(pairs.astype(np.int32)),
                     int(layout.stride))


def zero_targets(recipe, rows: np.ndarray):
  """Evicted TABLE rows -> per-class zero targets.

  ``recipe``: the translator's per-table window list — entries
  ``(class_name, rank_phys_base, row_start, nrows, row_offset, rpp)``
  covering every shard that holds part of the table (column slices put
  the same table rows on several ranks; each copy must re-zero).
  Returns ``{class_name: (grp, sub)}`` int64 arrays."""
  out = {}
  for (name, base, row_start, nrows, row_offset, rpp) in recipe:
    m = (rows >= row_start) & (rows < row_start + nrows)
    if not np.any(m):
      continue
    local = rows[m] - row_start + row_offset
    grp = base + local // rpp
    sub = local % rpp
    if name in out:
      pg, ps = out[name]
      out[name] = (np.concatenate([pg, grp]), np.concatenate([ps, sub]))
    else:
      out[name] = (grp, sub)
  return out


def merge_zero_work(into: dict, work: dict) -> dict:
  """Accumulate per-class zero targets across tables."""
  for name, (grp, sub) in work.items():
    if name in into:
      pg, ps = into[name]
      into[name] = (np.concatenate([pg, grp]), np.concatenate([ps, sub]))
    else:
      into[name] = (grp, sub)
  return into


def dedupe_zero_work(work: dict) -> dict:
  """Sort + dedupe each class's (grp, sub) targets (deterministic
  scatter order; duplicates are idempotent but cost scatter rows)."""
  out = {}
  for name, (grp, sub) in work.items():
    pairs = np.unique(np.stack([grp, sub], axis=1), axis=0)
    out[name] = (pairs[:, 0], pairs[:, 1])
  return out


def apply_zero_work(layouts, fused: dict, work: dict) -> Tuple[dict, int]:
  """Zero the accumulated targets in the fused buffers; returns the
  updated dict and the number of logical rows zeroed."""
  if not work:
    return fused, 0
  fused = dict(fused)
  total = 0
  for name, (grp, sub) in dedupe_zero_work(work).items():
    fused[name] = zero_rows_update(layouts[name], fused[name], grp, sub)
    total += int(grp.shape[0])
  return fused, total
