"""Host-side open-addressing id translation table (raw 64-bit id -> row).

The dynamic-vocabulary layer's core data structure: one
:class:`IdTranslationTable` per dynamic table maps arbitrary non-negative
raw 64-bit ids onto physical rows ``[0, capacity)`` of the EXISTING
packed class buffers. It is a plain numpy open-addressing hash table
(linear probing, load factor <= 0.5, tombstone deletion with periodic
compaction), because the translation runs on the HOST between steps —
exactly like the tiered prefetcher's classify stage — so the traced step
only ever sees already-translated, in-range ids and stays byte-identical
to a static-vocab plan's.

Determinism contract: ``lookup`` is a pure function of the current
MAPPING; the mapping itself is a deterministic function of the insertion
/ removal sequence (no RNG, no wall clock — the hash is a fixed-constant
splitmix64 finalizer). Serialization (:meth:`items`) captures the
mapping, not the probe history, so a restore rebuilds an equivalent
table regardless of how many tombstones the saving run had accumulated.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

# splitmix64 finalizer constants (fixed — the table must hash identically
# across runs and restores)
_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)
_EMPTY = np.int64(-1)
_TOMBSTONE = np.int64(-2)


def _mix(ids: np.ndarray) -> np.ndarray:
  """splitmix64 finalizer over uint64 (vectorized, wrap-around exact)."""
  x = ids.astype(np.uint64)
  x ^= x >> np.uint64(30)
  x *= _M1
  x ^= x >> np.uint64(27)
  x *= _M2
  x ^= x >> np.uint64(31)
  return x


class IdTranslationTable:
  """Open-addressing map: raw id (int64 >= 0) -> physical row (int32).

  ``capacity`` bounds the number of live entries (the allocatable row
  count); the backing array is the next power of two >= 2x capacity so
  linear probe chains stay short. Raw ids are non-negative by the engine
  contract (negative ids are hotness padding everywhere else in the
  repo), which frees the sign bit for the EMPTY/TOMBSTONE sentinels.
  """

  def __init__(self, capacity: int):
    if capacity < 1:
      raise ValueError(f"capacity must be >= 1, got {capacity}")
    self.capacity = int(capacity)
    size = 8
    while size < 2 * self.capacity:
      size *= 2
    self._size = size
    self._mask = np.uint64(size - 1)
    self._keys = np.full((size,), _EMPTY, np.int64)
    self._vals = np.zeros((size,), np.int32)
    self._live = 0
    self._tombstones = 0

  def __len__(self) -> int:
    return self._live

  def _start(self, ids: np.ndarray) -> np.ndarray:
    return (_mix(ids) & self._mask).astype(np.int64)

  # ---- vectorized read path ----------------------------------------------
  def lookup(self, ids: np.ndarray) -> np.ndarray:
    """Rows for ``ids`` (int64 array, any shape); -1 where unmapped.

    Vectorized linear probing: each round resolves every id whose probe
    slot holds its key (hit) or EMPTY (definitive miss); tombstoned
    slots keep probing. Probe counts are bounded by the longest chain
    (load <= 0.5 plus compacted tombstones keeps chains short)."""
    ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
    out = np.full(ids.shape, -1, np.int32)
    if not ids.size:
      return out
    if np.any(ids < 0):
      bad = int(ids[ids < 0][0])
      raise ValueError(
          f"raw id {bad} is negative: negative ids are hotness padding "
          "by the engine contract and must never reach the translation "
          "table — filter with ids >= 0 first.")
    active = np.arange(ids.size)
    pos = self._start(ids)
    for _ in range(self._size + 1):
      if not active.size:
        return out
      k = self._keys[pos[active]]
      hit = k == ids[active]
      out[active[hit]] = self._vals[pos[active[hit]]]
      done = hit | (k == _EMPTY)
      active = active[~done]
      pos[active] = (pos[active] + 1) & np.int64(self._mask)
    raise RuntimeError(
        "translation-table probe chain exceeded the table size — the "
        "open-addressing invariants are broken (this is a bug).")

  def items(self) -> Tuple[np.ndarray, np.ndarray]:
    """The live mapping as ``(ids, rows)``, sorted by row (the
    serialization form: probe-history-free and deterministic)."""
    live = self._keys >= 0
    ids = self._keys[live]
    rows = self._vals[live]
    order = np.argsort(rows, kind="stable")
    return ids[order], rows[order].astype(np.int32)

  # ---- scalar write path (allocation volume per step is small) -----------
  def insert(self, raw_id: int, row: int) -> None:
    """Map ``raw_id`` -> ``row``; the id must not already be mapped."""
    if self._live >= self.capacity:
      raise RuntimeError(
          f"translation table is full ({self.capacity} live entries): "
          "the caller must check occupancy (freelist/fresh rows) before "
          "inserting — denied admissions never reach insert().")
    raw_id = int(raw_id)
    if raw_id < 0:
      raise ValueError(f"raw id must be >= 0, got {raw_id}")
    pos = int(self._start(np.asarray([raw_id], np.int64))[0])
    first_tomb = -1
    for _ in range(self._size):
      k = int(self._keys[pos])
      if k == raw_id:
        raise ValueError(f"raw id {raw_id} is already mapped to row "
                         f"{int(self._vals[pos])}")
      if k == _TOMBSTONE and first_tomb < 0:
        first_tomb = pos
      if k == _EMPTY:
        slot = first_tomb if first_tomb >= 0 else pos
        if slot == first_tomb and first_tomb >= 0:
          self._tombstones -= 1
        self._keys[slot] = raw_id
        self._vals[slot] = np.int32(row)
        self._live += 1
        return
      pos = (pos + 1) & int(self._mask)
    raise RuntimeError("translation-table insert found no slot — the "
                       "open-addressing invariants are broken.")

  def remove(self, raw_id: int) -> int:
    """Unmap ``raw_id``; returns the row it held. Tombstones the slot
    (probe chains through it stay intact) and compacts the table once
    tombstones pile past a quarter of the backing array."""
    raw_id = int(raw_id)
    pos = int(self._start(np.asarray([raw_id], np.int64))[0])
    for _ in range(self._size):
      k = int(self._keys[pos])
      if k == raw_id:
        row = int(self._vals[pos])
        self._keys[pos] = _TOMBSTONE
        self._live -= 1
        self._tombstones += 1
        if self._tombstones > self._size // 4:
          self._rebuild()
        return row
      if k == _EMPTY:
        raise KeyError(f"raw id {raw_id} is not mapped")
      pos = (pos + 1) & int(self._mask)
    raise KeyError(f"raw id {raw_id} is not mapped")

  def _rebuild(self) -> None:
    """Re-insert every live entry into a fresh backing array (drops the
    tombstones so probe chains shrink back)."""
    ids, rows = self.items()
    self._keys.fill(_EMPTY)
    self._vals.fill(0)
    self._live = 0
    self._tombstones = 0
    for i, r in zip(ids.tolist(), rows.tolist()):
      self.insert(i, r)

  # ---- serialization ------------------------------------------------------
  def load_items(self, ids: np.ndarray, rows: np.ndarray) -> None:
    """Replace the mapping with ``(ids, rows)`` (a checkpointed
    :meth:`items` pair)."""
    if ids.shape != rows.shape:
      raise ValueError(f"ids/rows shape mismatch: {ids.shape} vs "
                       f"{rows.shape}")
    if ids.size > self.capacity:
      raise ValueError(
          f"checkpointed mapping holds {ids.size} entries but this "
          f"table's capacity is {self.capacity} — the vocab_capacity "
          "differs from the saving run's.")
    self._keys.fill(_EMPTY)
    self._vals.fill(0)
    self._live = 0
    self._tombstones = 0
    for i, r in zip(ids.tolist(), rows.tolist()):
      self.insert(int(i), int(r))


class ReadonlyIdTranslator:
  """An immutable, serializable snapshot of a dynamic id space — the
  SERVE-SIDE form of ``DynVocabTranslator.translate_readonly``.

  The live translator is trainer state (admission sketch, freelist, TTL
  stamps) that must never leave the training process; what serving needs
  is only the pure raw-id -> row MAPPING at one instant, plus the plan's
  input -> table wiring so request inputs route to the right table. This
  class captures exactly that pair, round-trips through flat npz arrays
  (it rides the serve artifact and every streaming delta — new ids
  admitted by training become servable in the same delta cycle), and
  translates with the identical semantics: unmapped ids emit ``PAD_ID``
  (-1, the engine's hotness-padding sentinel — a row-less id contributes
  a zero embedding), inputs of non-dynamic tables pass through.
  """

  def __init__(self, tables: Dict[int, IdTranslationTable],
               input_table_map: List[int]):
    self.tables = tables
    self.input_table_map = [int(t) for t in input_table_map]

  @classmethod
  def from_translator(cls, translator) -> "ReadonlyIdTranslator":
    """Snapshot a live ``DynVocabTranslator`` (mapping only — the
    sketch / freelist / TTL state stays behind)."""
    tables = {}
    for t in translator.dynamic_tables:
      ids, rows = translator.tables[t].items()
      tab = IdTranslationTable(max(1, translator.tables[t].capacity))
      tab.load_items(ids, rows)
      tables[int(t)] = tab
    return cls(tables, list(translator.plan.input_table_map))

  # ---- the read path ------------------------------------------------------
  def translate(self, inputs) -> list:
    """Raw-id inputs -> translated int32 arrays (pure lookup; the id
    space cannot change under a reader by construction — promotion
    swaps the whole snapshot reference)."""
    out = []
    for i, x in enumerate(inputs):
      t = self.input_table_map[i]
      tab = self.tables.get(t)
      if tab is None:
        out.append(x)
        continue
      arr = np.asarray(x)
      flat = arr.reshape(-1).astype(np.int64)
      valid = flat >= 0
      res = np.full(flat.shape, -1, np.int32)
      res[valid] = tab.lookup(flat[valid])
      out.append(res.reshape(arr.shape))
    return out

  def occupancy(self) -> Dict[int, int]:
    return {t: len(tab) for t, tab in self.tables.items()}

  # ---- serialization (rides serve artifacts and streaming deltas) --------
  def state_arrays(self) -> Dict[str, np.ndarray]:
    flat: Dict[str, np.ndarray] = {
        "input_table_map": np.asarray(self.input_table_map, np.int64)}
    for t, tab in sorted(self.tables.items()):
      ids, rows = tab.items()
      flat[f"t{t}/ids"] = ids
      flat[f"t{t}/rows"] = rows
      flat[f"t{t}/capacity"] = np.asarray([tab.capacity], np.int64)
    return flat

  def manifest_section(self) -> Dict[str, Any]:
    """The artifact manifest's ``vocab_snapshot`` section (geometry +
    occupancy — observability and a load-time cross-check)."""
    return {
        "tables": {str(t): {"capacity": tab.capacity,
                            "occupancy": len(tab)}
                   for t, tab in sorted(self.tables.items())},
        "input_table_map": list(self.input_table_map),
    }

  @classmethod
  def from_arrays(cls, flat: Dict[str, np.ndarray]) -> "ReadonlyIdTranslator":
    tables: Dict[int, IdTranslationTable] = {}
    for key in flat:
      if not (key.startswith("t") and key.endswith("/ids")):
        continue
      t = int(key[1:].split("/", 1)[0])
      cap = int(np.asarray(flat[f"t{t}/capacity"]).reshape(-1)[0])
      tab = IdTranslationTable(max(1, cap))
      tab.load_items(np.asarray(flat[f"t{t}/ids"], np.int64),
                     np.asarray(flat[f"t{t}/rows"], np.int32))
      tables[t] = tab
    return cls(tables,
               np.asarray(flat["input_table_map"], np.int64).tolist())
