"""The dynamic-vocabulary translator + host-side training loop.

:class:`DynVocabTranslator` composes the three lifecycle pieces — the
open-addressing id table (:mod:`.table`), count-min-sketch admission
(:mod:`.admission`), and TTL eviction / row recycling (:mod:`.lifecycle`)
— into the per-step host pass that makes ``oov='allocate'`` real:

    evict expired rows (freelist + device re-zero targets)
    -> observe the batch's raw ids (sketch)
    -> translate (admitting ids past ``admit_threshold`` onto recycled
       or fresh rows; un-admitted ids emit PAD_ID and contribute nothing)

It runs BETWEEN steps on the host — the ``TieredPrefetcher`` pattern —
so the traced train step sees only translated in-range ids and its jaxpr
is byte-identical to a static-vocab (``oov='clip'``) plan's; with every
id pre-admitted the whole run is bit-exact against the static run
(pinned in tests/test_dynvocab.py).

Stream-position discipline: the id space consumes EVERY batch (a
guard-skipped poison batch still observed its ids — exactly like the
``consumed`` counter of PR 2 counts skipped batches), while the commit
gate keeps the trained state bit-identical on skips. An unkilled
reference and a kill/resume run therefore agree on both states.

:class:`DynVocabTrainer` drives the protocol around the guarded fused
step (translate -> re-zero evicted rows in the packed buffers -> device
step) and accounts per-class lifecycle counters
``[allocs, evictions, admit_denied, occupancy]`` next to the guarded
step's ``oov``/``dedup_overflow`` metrics. ``resilience.ResilientTrainer
(dynvocab=...)`` wraps it with durable snapshots (the translator state
rides the checkpoint manifest's ``vocab`` section) and auto-resume.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np

from ..ops.packed_table import SparseRule
from ..parallel.lookup_engine import (
    DistributedLookup,
    class_param_name,
)
from ..telemetry import get_registry as _registry, span as _span
from .admission import CountMinSketch
from .lifecycle import RowRecycler, apply_zero_work, merge_zero_work, \
    zero_targets
from .table import IdTranslationTable


class DynVocabTranslator:
  """Host-side dynamic id space for every sparse-kind table of a plan.

  One translation table / sketch / recycler per DYNAMIC table (sparse
  kind; MXU-dense small-vocab tables keep static ids — their one-hot
  windows have no row scarcity to manage, and the ISSUE's allocation
  protocol is a property of the gather path). State is keyed by TABLE
  id, not rank: raw-id -> row is a logical-vocabulary fact, which is
  what lets the checkpointed id space restore unchanged across an
  elastic world resize.
  """

  def __init__(self, plan, rule: SparseRule, axis_name: str = "mp",
               sketch_width: int = 1 << 16, sketch_depth: int = 4):
    if getattr(plan, "oov", "clip") != "allocate":
      raise ValueError(
          "DynVocabTranslator needs a plan built with oov='allocate' "
          f"(got oov={getattr(plan, 'oov', 'clip')!r}): the dynamic id "
          "layer replaces the clip/error policies, it does not wrap "
          "them.")
    if plan.host_tier_class_keys():
      raise NotImplementedError(
          "oov='allocate' with host-tier (tiered) classes: the tiered "
          "prefetcher classifies RAW ids, so the two host passes would "
          "have to compose — an open follow-on (ROADMAP). Keep dynamic "
          "tables device-resident (host_row_threshold=None).")
    self.plan = plan
    self.rule = rule
    self.axis_name = axis_name
    engine = DistributedLookup(plan, axis_name=axis_name)
    layouts = engine.fused_layouts(rule)
    table_kind: Dict[int, str] = {}
    for shards in plan.rank_shards:
      for sh in shards:
        table_kind[sh.table_id] = plan._kind_of(sh)
    self.dynamic_tables = tuple(sorted(
        t for t, k in table_kind.items() if k == "sparse"))
    if not self.dynamic_tables:
      raise ValueError(
          "plan has no sparse-kind tables: every table rides the MXU "
          "one-hot path, which keeps static ids — there is nothing for "
          "oov='allocate' to allocate. Lower dense_row_threshold.")
    self.tables: Dict[int, IdTranslationTable] = {}
    self.sketches: Dict[int, CountMinSketch] = {}
    self.recyclers: Dict[int, RowRecycler] = {}
    # cumulative [allocs, evictions, admit_denied] per table — lives IN
    # the translator (serialized with it) so restarts never double-count
    self.totals: Dict[int, np.ndarray] = {}
    # per-table zero recipe: every shard window holding the table's rows
    # (column slices replicate rows across ranks; each copy re-zeroes)
    self._recipe: Dict[int, List[tuple]] = {}
    # classes each table touches (counter aggregation granularity — the
    # same convention as oov_counts: shared/sliced tables count once per
    # class)
    self._classes_of: Dict[int, List[str]] = {}
    for t in self.dynamic_tables:
      cap = plan.table_vocab_capacity(t)
      self.tables[t] = IdTranslationTable(cap)
      self.sketches[t] = CountMinSketch(sketch_width, sketch_depth)
      self.recyclers[t] = RowRecycler(cap)
      self.totals[t] = np.zeros((3,), np.int64)
      entries, names = [], []
      for rank, sh in plan.table_shard_map(t):
        key = plan.class_key_of(sh)
        cp = plan.classes[key]
        name = class_param_name(*key)
        lay = layouts[name]
        idx = cp.shards_per_rank[rank].index(sh)
        row_offset = cp.row_offsets_per_rank[rank][idx]
        entries.append((name, rank * lay.phys_rows, sh.row_start,
                        sh.input_dim, row_offset, lay.rows_per_phys))
        if name not in names:
          names.append(name)
      self._recipe[t] = entries
      self._classes_of[t] = names
    self.steps = 0  # the TTL clock: batches CONSUMED by the id space

  # ---- the per-step host pass --------------------------------------------
  def _evict(self, step: int):
    """Reclaim expired rows; returns (per-table eviction counts,
    per-class zero targets)."""
    ttl = getattr(self.plan, "evict_ttl", None)
    evicted = {t: 0 for t in self.dynamic_tables}
    zero: Dict[str, tuple] = {}
    if ttl is None:
      return evicted, zero
    for t in self.dynamic_tables:
      rec, tab = self.recyclers[t], self.tables[t]
      rows = rec.expired(step, ttl)
      if not rows.size:
        continue
      for row in rows.tolist():
        tab.remove(int(rec.row_to_id[row]))
        rec.release(row)
      evicted[t] = int(rows.size)
      self.totals[t][1] += rows.size
      merge_zero_work(zero, zero_targets(self._recipe[t], rows))
    return evicted, zero

  def _translate_one(self, t: int, ids: np.ndarray, step: int,
                     mutate: bool) -> tuple:
    """One input's raw ids -> (translated int32 array, allocs, denied).

    Un-admitted / capacity-denied ids emit PAD_ID (-1): the engine
    treats them as hotness padding, so they gather nothing and train
    nothing — a row-less id contributes a zero embedding, which is
    exactly what "no row yet" means."""
    from ..ops.ragged import RaggedIds
    if isinstance(ids, RaggedIds):
      raise NotImplementedError(
          "dynamic-vocab translation of RaggedIds inputs: translate "
          "over the value stream is not wired up yet — pad to dense "
          "multi-hot (ragged_to_padded) for dynamic tables.")
    arr = np.asarray(ids)
    flat = arr.reshape(-1).astype(np.int64)
    valid = flat >= 0
    vids = flat[valid]
    tab, rec, sk = self.tables[t], self.recyclers[t], self.sketches[t]
    allocs = denied = 0
    if mutate:
      sk.update(vids)
    rows = tab.lookup(vids)
    if mutate:
      missing = np.unique(vids[rows < 0])
      if missing.size:
        est = sk.estimate(missing)
        thr = getattr(self.plan, "admit_threshold", 1)
        for mid, e in zip(missing.tolist(), est.tolist()):
          if e >= thr:
            row = rec.allocate(mid, step)
            if row >= 0:
              tab.insert(mid, row)
              allocs += 1
            else:
              denied += 1
          else:
            denied += 1
        if allocs:
          rows = tab.lookup(vids)
      hit = rows[rows >= 0]
      if hit.size:
        rec.touch(np.unique(hit), step)
      self.totals[t][0] += allocs
      self.totals[t][2] += denied
    out = np.full(flat.shape, -1, np.int32)
    out[valid] = rows
    return out.reshape(arr.shape), allocs, denied

  def translate_batch(self, inputs) -> tuple:
    """The full host pass over one batch of raw-id inputs.

    Returns ``(translated_inputs, metrics, zero_work)``:

    - ``translated_inputs``: per input, the int32 translated array
      (inputs of non-dynamic tables pass through untouched);
    - ``metrics``: class name -> int64 ``[4]`` counter vector
      ``[allocs, evictions, admit_denied, occupancy]`` for THIS step
      (occupancy = live rows after it). The translator sees the GLOBAL
      batch — like the tiered prefetcher's classify — so the counters
      are already global; the trainer surfaces them in the step metrics
      next to the guarded step's psum'd ``oov``/``dedup_overflow``.
    - ``zero_work``: class name -> (grp, sub) device re-zero targets of
      this step's evicted rows (apply BEFORE dispatching the step —
      ``lifecycle.apply_zero_work`` — so a recycled row re-admits onto
      zeroed lanes).
    """
    if len(inputs) != self.plan.num_inputs:
      raise ValueError(
          f"expected {self.plan.num_inputs} inputs, got {len(inputs)}")
    self.steps += 1
    step = self.steps
    evicted, zero = self._evict(step)
    per_table = {t: np.zeros((2,), np.int64) for t in self.dynamic_tables}
    out_inputs = []
    for i, x in enumerate(inputs):
      t = self.plan.input_table_map[i]
      if t not in self.tables:
        out_inputs.append(x)
        continue
      tx, allocs, denied = self._translate_one(t, x, step, mutate=True)
      per_table[t] += np.asarray([allocs, denied], np.int64)
      out_inputs.append(tx)
    metrics: Dict[str, np.ndarray] = {}
    for t in self.dynamic_tables:
      vec = np.asarray([per_table[t][0], evicted[t], per_table[t][1],
                        self.recyclers[t].occupancy], np.int64)
      for name in self._classes_of[t]:
        metrics[name] = metrics.get(name, np.zeros((4,), np.int64)) + vec
    return out_inputs, metrics, zero

  def translate_readonly(self, inputs) -> list:
    """Pure lookup (no observation, admission, or eviction): the eval /
    serve form — an inference path must never mutate the id space, which
    is also why the eval and serve step BUILDERS refuse ``'allocate'``
    plans outright. Unmapped ids emit PAD_ID."""
    out = []
    for i, x in enumerate(inputs):
      t = self.plan.input_table_map[i]
      if t not in self.tables:
        out.append(x)
        continue
      tx, _, _ = self._translate_one(t, x, self.steps, mutate=False)
      out.append(tx)
    return out

  def occupancy(self) -> Dict[int, int]:
    return {t: self.recyclers[t].occupancy for t in self.dynamic_tables}

  # ---- checkpoint state ---------------------------------------------------
  def state_arrays(self) -> Dict[str, np.ndarray]:
    """Flat npz-ready state: mapping, sketch, recycler, cumulative
    counters per table, plus the TTL clock."""
    flat: Dict[str, np.ndarray] = {
        "steps": np.asarray([self.steps], np.int64)}
    for t in self.dynamic_tables:
      ids, rows = self.tables[t].items()
      flat[f"t{t}/ids"] = ids
      flat[f"t{t}/rows"] = rows
      flat[f"t{t}/sketch"] = self.sketches[t].state()
      flat[f"t{t}/totals"] = self.totals[t]
      for k, v in self.recyclers[t].state().items():
        flat[f"t{t}/{k}"] = v
    return flat

  def manifest_section(self) -> Dict[str, Any]:
    """The checkpoint manifest's ``vocab`` section: the knobs and
    geometry a restore must match (occupancy rides along as
    observability, not identity)."""
    return {
        "admit_threshold": int(getattr(self.plan, "admit_threshold", 1)),
        "evict_ttl": getattr(self.plan, "evict_ttl", None),
        "sketch": {"width": self.sketches[self.dynamic_tables[0]].width,
                   "depth": self.sketches[self.dynamic_tables[0]].depth},
        "tables": {str(t): {"capacity": self.tables[t].capacity,
                            "occupancy": self.recyclers[t].occupancy}
                   for t in self.dynamic_tables},
    }

  def config_mismatch(self, section: Dict[str, Any]) -> Optional[str]:
    """None when a checkpoint's ``vocab`` section is loadable into this
    translator, else the first reason it is not."""
    want = self.manifest_section()
    for k in ("admit_threshold", "evict_ttl"):
      if section.get(k) != want[k]:
        return (f"{k} was {section.get(k)!r} at save time, this plan has "
                f"{want[k]!r}")
    if section.get("sketch") != want["sketch"]:
      return (f"sketch geometry was {section.get('sketch')!r} at save "
              f"time, this translator has {want['sketch']!r}")
    saved_tables = section.get("tables", {})
    if set(saved_tables) != set(want["tables"]):
      return (f"dynamic table set was {sorted(saved_tables)} at save "
              f"time, this plan has {sorted(want['tables'])}")
    for t, meta in sorted(saved_tables.items()):
      if meta["capacity"] != want["tables"][t]["capacity"]:
        return (f"table {t} capacity was {meta['capacity']} at save "
                f"time, this plan allows {want['tables'][t]['capacity']}")
    return None

  def load_state(self, flat: Dict[str, np.ndarray],
                 section: Dict[str, Any]) -> None:
    """Restore the id space from a checkpoint's ``vocab.npz`` + manifest
    section (refuses a knob/geometry mismatch with the reason named)."""
    reason = self.config_mismatch(section)
    if reason is not None:
      raise ValueError(
          f"checkpoint vocab state does not fit this translator: "
          f"{reason} — rebuild the plan/translator with the saving "
          "run's dynamic-vocabulary knobs.")
    self.steps = int(np.asarray(flat["steps"]).reshape(-1)[0])
    for t in self.dynamic_tables:
      self.tables[t].load_items(np.asarray(flat[f"t{t}/ids"], np.int64),
                                np.asarray(flat[f"t{t}/rows"], np.int32))
      self.sketches[t].load_state(np.asarray(flat[f"t{t}/sketch"]))
      self.totals[t] = np.asarray(flat[f"t{t}/totals"], np.int64).copy()
      self.recyclers[t].load_state(
          {k: flat[f"t{t}/{k}"]
           for k in ("row_to_id", "last_seen", "freelist", "next_fresh")})


class DynVocabTrainer:
  """Drives dynamic-vocabulary training: translate, re-zero, device step.

  Owns the train ``state`` pytree and the :class:`DynVocabTranslator`;
  one :meth:`step` call is the synchronous protocol (the translate pass
  is host-side and independent of the device step's results, so a
  wrapping loop may overlap it exactly like the tiered classify — kept
  synchronous here for the same reason ``TieredTrainer.step`` is).

  Counters (cumulative, aggregated per class like the tier hit
  counters): ``vocab_totals[name] = [allocs, evictions, admit_denied,
  occupancy]`` with occupancy holding the LATEST value. ``guard=True``
  builds the hardened step and accounts ``bad_steps``/``oov_totals``
  exactly like ``TieredTrainer`` — and under ``oov='allocate'`` a
  nonzero in-trace OOV counter means raw ids leaked past the translator,
  which ``guards.check_oov`` escalates to a host-side error with the
  state uncommitted.
  """

  def __init__(self, model, plan, translator: DynVocabTranslator,
               loss_fn: Callable, dense_optimizer, rule: SparseRule,
               mesh, state: Dict[str, Any], batch_example: Any,
               axis_name: str = "mp", emb_dense_optimizer=None,
               micro_batches: int = 1, guard: bool = False,
               donate: bool = True, telemetry=None,
               overlap_host: bool = False):
    from ..training import make_sparse_train_step
    if getattr(plan, "oov", "clip") != "allocate":
      raise ValueError(
          "DynVocabTrainer needs a plan built with oov='allocate' "
          f"(got {getattr(plan, 'oov', 'clip')!r}).")
    if translator.plan is not plan:
      raise ValueError(
          "translator was built for a different plan object: the zero "
          "recipe and class names are plan-derived, so the two must "
          "share one DistEmbeddingStrategy.")
    self.plan = plan
    self.translator = translator
    self.mesh = mesh
    self.axis_name = axis_name
    self.state = state
    self.guard = guard
    self.overlap_host = overlap_host
    # lifecycle counters/gauges emit here (default: process registry)
    self.telemetry = telemetry if telemetry is not None else _registry()
    self.engine = DistributedLookup(plan, dp_input=True,
                                    axis_name=axis_name)
    self.layouts = self.engine.fused_layouts(rule)
    self._step_fn = make_sparse_train_step(
        model, plan, loss_fn, dense_optimizer, rule, mesh, state,
        batch_example, axis_name=axis_name,
        emb_dense_optimizer=emb_dense_optimizer,
        micro_batches=micro_batches, guard=guard, donate=donate)
    self.vocab_totals: Dict[str, np.ndarray] = {}
    self.rows_zeroed = 0
    self.steps = 0
    self.bad_steps = 0
    self.oov_totals: Dict[str, int] = {}
    self.dedup_overflow_totals: Dict[str, int] = {}

  # ---- metrics -----------------------------------------------------------
  def account_vocab(self, vocab: Dict[str, np.ndarray]) -> None:
    """Accumulate one step's per-class lifecycle counters (allocs /
    evictions / denied sum; occupancy is the latest value)."""
    reg = self.telemetry
    for name, vec in vocab.items():
      tot = self.vocab_totals.setdefault(name, np.zeros((4,), np.int64))
      tot[:3] += vec[:3]
      tot[3] = vec[3]
      reg.counter(f"vocab/allocs/{name}").inc(int(vec[0]))
      reg.counter(f"vocab/evictions/{name}").inc(int(vec[1]))
      reg.counter(f"vocab/admit_denied/{name}").inc(int(vec[2]))
      reg.gauge(f"vocab/occupancy/{name}").set(int(vec[3]))

  def _account(self, metrics) -> None:
    if self.guard:
      self.bad_steps += int(np.asarray(metrics["bad_step"]))
      counts = {name: int(np.asarray(v))
                for name, v in metrics["oov"].items()}
      for name, n in counts.items():
        self.oov_totals[name] = self.oov_totals.get(name, 0) + n
      for name, v in metrics.get("dedup_overflow", {}).items():
        n = int(np.asarray(v))
        if n:
          self.dedup_overflow_totals[name] = \
              self.dedup_overflow_totals.get(name, 0) + n
      from ..resilience import guards as _guards
      _guards.check_oov(self.plan, counts, where="dynvocab step")
    self.steps += 1

  def metrics_summary(self) -> Dict[str, Any]:
    out = {
        "steps": self.steps,
        "per_class": {
            name: {"allocs": int(v[0]), "evictions": int(v[1]),
                   "admit_denied": int(v[2]), "occupancy": int(v[3])}
            for name, v in self.vocab_totals.items()},
        "occupancy": self.translator.occupancy(),
        "rows_zeroed": self.rows_zeroed,
    }
    if self.guard:
      out["bad_steps"] = self.bad_steps
      out["oov"] = dict(self.oov_totals)
      if self.dedup_overflow_totals:
        out["dedup_overflow"] = dict(self.dedup_overflow_totals)
    return out

  # ---- stepping ----------------------------------------------------------
  def _apply_zero(self, zero) -> None:
    """Main-thread half of translation: clear recycled rows on device
    BEFORE the step that may read them (the engine contract — the
    overlap scheduler translates on its worker but always applies the
    zero work here, pre-dispatch)."""
    self.state["fused"], zeroed = apply_zero_work(
        self.layouts, self.state["fused"], zero)
    self.rows_zeroed += zeroed

  def _translate(self, cats):
    with _span("dynvocab/translate"):
      cats_t, vocab_metrics, zero = self.engine.translate_dynamic_ids(
          cats, self.translator)
      self._apply_zero(zero)
      return cats_t, vocab_metrics

  def _dispatch(self, numerical, cats_t, labels):
    """Dispatch one TRANSLATED batch; returns ``(loss, metrics|None)``
    as device values with the device span left open on
    ``self._dev_span`` — the caller's first host sync ends the window
    and must finish the span."""
    from ..training import shard_batch
    self._dev_span = _span("device/step", track="device").start()
    batch = shard_batch((numerical, list(cats_t), labels), self.mesh,
                        self.axis_name)
    if self.guard:
      self.state, loss, metrics = self._step_fn(self.state, *batch)
      return loss, metrics
    self.state, loss = self._step_fn(self.state, *batch)
    return loss, None

  def step(self, numerical, cats, labels) -> float:
    """One train step on a GLOBAL host batch of RAW ids."""
    cats_t, vocab_metrics = self._translate(cats)
    loss, metrics = self._dispatch(numerical, cats_t, labels)
    loss = float(np.asarray(loss))  # the host sync ending the window
    self._dev_span.finish()
    if self.guard:
      self._account(metrics)
    else:
      self.steps += 1
    self.account_vocab(vocab_metrics)
    return loss

  def run(self, batches: Iterable) -> list:
    """Train over host batches of ``(numerical, cats, labels)``.

    With ``overlap_host=True`` the translate pass for batch k+1 runs on
    the pipeline worker while step k executes on device — bit-exact
    with the serial loop (the translator mutates in batch order on the
    single worker; see ``pipeline.run_dynvocab_overlapped``)."""
    if self.overlap_host:
      from ..pipeline import run_dynvocab_overlapped
      return run_dynvocab_overlapped(self, batches)
    return [self.step(*b) for b in batches]
