"""Dynamic vocabularies: the id space itself becomes mutable state.

Every subsystem before this one — tiering, resilience, the compressed
and overlapped exchanges, elastic pods, serving — assumed a frozen id
space; production recommenders never see one (PAPERS.md: "Scalable ML
Training Infrastructure for Online Ads at Google"). This subsystem
replaces the static-vocab assumption with a dynamic id layer, riding the
OOV-policy plumbing of the resilience round: where ``oov='clip'|'error'``
clamp or reject out-of-range ids, ``oov='allocate'`` ALLOCATES for them:

- a host-side open-addressing translation table per sparse-kind table
  maps raw 64-bit ids onto physical rows of the EXISTING packed class
  buffers (:mod:`.table`), run between steps like the tiered
  prefetcher's classify — the traced step sees only translated in-range
  ids, so its jaxpr is byte-identical to a static plan's and the
  one-scatter-add backward is untouched;
- count-min-sketch admission (:mod:`.admission`): an id must be observed
  ``admit_threshold`` times before it earns a row — one-shot ids (the
  bulk of a power-law tail) never allocate;
- TTL eviction recycles rows in place through a freelist
  (:mod:`.lifecycle`): an expired row's table AND interleaved
  optimizer-state lanes re-zero on device before reuse, so a re-admitted
  id starts training-neutral;
- per-class lifecycle counters ``[allocs, evictions, admit_denied,
  occupancy]`` surface in the step metrics next to ``oov`` /
  ``dedup_overflow`` (:class:`DynVocabTrainer`);
- the whole id space — mapping, sketch, freelist, cumulative counters —
  persists through the crc32-manifest-last checkpoint protocol under a
  ``vocab`` manifest section, so ``ResilientTrainer(dynvocab=...)``
  auto-resume restores it exactly (the consumed-id discipline of PR 2's
  stream position, applied to rows).
"""

from .admission import CountMinSketch
from .lifecycle import RowRecycler, apply_zero_work, zero_rows_update
from .table import IdTranslationTable, ReadonlyIdTranslator
from .trainer import DynVocabTrainer, DynVocabTranslator

__all__ = [
    "CountMinSketch",
    "DynVocabTrainer",
    "DynVocabTranslator",
    "IdTranslationTable",
    "ReadonlyIdTranslator",
    "RowRecycler",
    "apply_zero_work",
    "zero_rows_update",
]
