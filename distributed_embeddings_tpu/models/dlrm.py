"""DLRM (Deep Learning Recommendation Model), TPU-native.

Functional equivalent of the reference example model
(`/root/reference/examples/dlrm/main.py:76-147` and ``dot_interact`` in
`/root/reference/examples/dlrm/utils.py:92-113`): bottom MLP over numerical
features, embeddings over categorical features (hybrid-parallel via
``DistributedEmbedding`` when world > 1), pairwise dot-product feature
interaction (lower triangle), top MLP to one logit.

TPU notes: the interaction is a [B, F, D] x [B, D, F] batched matmul — MXU
work — and the lower-triangle selection uses a static gather index (no
boolean_mask / dynamic shapes). ``compute_dtype=bfloat16`` runs the MLPs and
interaction in bf16 with fp32 params/accumulation (the AMP configuration of
the reference's headline benchmark).
"""

from __future__ import annotations

import functools
import warnings
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..layers.dist_model_parallel import DistributedEmbedding
from ..layers.embedding import TableConfig
from ..ops.packed_table import mxu_operand_dtype as _mxu_operand_dtype
from ..ops.pallas_interact import (
    interact_bwd,
    interact_fwd,
    interact_parts_bwd,
    interact_parts_fwd,
    use_pallas_interact,
)


class MLP(nn.Module):
  features: Sequence[int]
  activate_final: bool = False
  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, x):
    for i, width in enumerate(self.features):
      x = nn.Dense(width, dtype=self.dtype, name=f"dense_{i}")(x)
      if i < len(self.features) - 1 or self.activate_final:
        x = nn.relu(x)
    return x


@functools.lru_cache(maxsize=None)
def _tril_select_np(f: int, k: int):
  """Half-weight symmetric selection tensor ``M [f, f, p]``.

  ``einsum("bpq,pqn->bn", inter, M)`` extracts the lower-triangle pairs
  from the full pairwise product: both mirrored cells carry weight 0.5
  (diagonal pairs 1.0), and ``inter`` is bitwise symmetric (each mirrored
  pair is the same dot product with the same reduction order), so
  ``0.5*a + 0.5*a`` reproduces the pair value exactly. The selection is a
  matmul — MXU work — instead of the flat ``jnp.take`` an index map needs,
  whose lane-crossing gather + reshape cost ~4 ms of relayout copies per
  step at F=27, B=64k (traced round 4)."""
  rows, cols = np.tril_indices(f, k=k)
  p = len(rows)
  m = np.zeros((f, f, p), np.float32)
  for n, (i, j) in enumerate(zip(rows, cols)):
    if i == j:  # self-interaction diagonal: single cell, full weight
      m[i, j, n] = 1.0
    else:
      m[i, j, n] = 0.5
      m[j, i, n] = 0.5
  return m, p


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _tril_products(flat: jax.Array, f: int, k: int) -> jax.Array:
  """Flat ``[B, F*D]`` features -> [B, P] lower-triangle pairwise dots.

  Takes the lane-concatenated flat array (reshaped to [B, F, D]
  internally — see _tril_fwd's layout note) with ``f`` static.

  Both directions are pure matmuls (no gathers, no index maps): forward is
  the pairwise product einsum followed by the ``M``-selection einsum; the
  hand-written VJP exploits the symmetry of the selection cotangent
  (``d_sym = einsum(d_acts, M)`` is symmetric by construction) to compute
  ``d_feats = (G + G^T) @ feats`` as ONE product einsum scaled by 2, where
  XLA's autodiff would run two. Equivalent of the reference's
  ``boolean_mask`` interaction (`examples/dlrm/utils.py:92-113`)."""
  out, _ = _tril_fwd(flat, f, k)
  return out


def _tril_fwd(flat, f, k):
  # the [B, F*D] -> [B, F, D] reshape lives INSIDE the custom-vjp
  # boundary: placed outside, XLA's layout assignment round-trips the
  # concat through a {0,1} layout and back (~2.7 ms/step of copies at
  # F=27, B=64k, traced round 4)
  b = flat.shape[0]
  d = flat.shape[1] // f
  feats = flat.reshape(b, f, d)
  m_np, p = _tril_select_np(f, k)
  if use_pallas_interact(b, f, d, flat.dtype):
    # fused VMEM kernel: no inter round-trip, no layout copies (round 5,
    # ~13 -> ~3 ms of the B=64k step; ops/pallas_interact.py)
    acts = interact_fwd(feats, jnp.asarray(m_np, jnp.bfloat16))
    return acts, feats
  cd = _mxu_operand_dtype(feats.dtype)
  m = jnp.asarray(m_np, cd)
  inter = jnp.einsum("bpd,bqd->bpq", feats.astype(cd), feats.astype(cd),
                     preferred_element_type=jnp.float32)
  acts = jnp.einsum("bpq,pqn->bn", inter.astype(cd), m,
                    preferred_element_type=jnp.float32)
  return acts, feats


def _tril_bwd(f, k, feats, d_acts):
  b, _, d = feats.shape
  m_np, _ = _tril_select_np(f, k)
  if use_pallas_interact(b, f, d, feats.dtype):
    m3t = jnp.asarray(np.swapaxes(m_np, 1, 2), jnp.bfloat16)
    d_feats = interact_bwd(d_acts, feats, m3t)
    return (d_feats.reshape(b, f * d),)
  # under bf16 compute (AMP) the cotangent is rounded to bf16 before the
  # grad einsums — the AMP convention (the reference's fp16 backward does
  # the same); on-TPU f32 parity with autodiff holds because DEFAULT MXU
  # precision rounds einsum operands to bf16 either way (_mxu_operand_dtype)
  cd = _mxu_operand_dtype(feats.dtype)
  m = jnp.asarray(m_np, cd)
  d_sym = jnp.einsum("bn,pqn->bpq", d_acts.astype(cd), m,
                     preferred_element_type=jnp.float32)
  # d(F F^T) needs (G + G^T) @ F; d_sym = (G + G^T)/2 is symmetric by
  # construction (M weights both mirrored cells), so one einsum x2 does it
  d_feats = 2.0 * jnp.einsum("bqp,bqd->bpd", d_sym.astype(cd),
                             feats.astype(cd),
                             preferred_element_type=jnp.float32)
  return (d_feats.astype(feats.dtype).reshape(b, f * d),)


_tril_products.defvjp(_tril_fwd, _tril_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _pair_products_pallas(parts, f: int, k: int) -> jax.Array:
  """Per-part fused-kernel form of :func:`_tril_products` (bf16, TPU).

  Takes the f per-table [B, D] slices directly — no flat concat exists
  at the XLA level in either direction (see ops/pallas_interact.py)."""
  out, _ = _pair_fwd(parts, f, k)
  return out


def _pair_fwd(parts, f, k):
  m_np, _ = _tril_select_np(f, k)
  acts = interact_parts_fwd(parts, jnp.asarray(m_np, jnp.bfloat16))
  return acts, parts


def _pair_bwd(f, k, parts, d_acts):
  m_np, _ = _tril_select_np(f, k)
  m3t = jnp.asarray(np.swapaxes(m_np, 1, 2), jnp.bfloat16)
  return (interact_parts_bwd(d_acts, parts, m3t),)


_pair_products_pallas.defvjp(_pair_fwd, _pair_bwd)


def dot_interact(bottom_out: jax.Array, emb_outs: Sequence[jax.Array],
                 self_interaction: bool = False,
                 pack: int = 1) -> jax.Array:
  """Pairwise dot-product interaction + bottom-MLP passthrough.

  Equivalent of `examples/dlrm/utils.py:92-113`, with the dynamic
  ``boolean_mask`` replaced by the matmul-form triangle selection
  (:func:`_tril_products`). Output: [B, F*(F-1)/2 + D] where
  F = num embeddings + 1.

  ``pack`` is accepted for API compatibility and ignored: the matmul-form
  selection has no pack concept (the round-2 pack study measured pack=1
  fastest anyway — the product bytes grow pack^2).
  """
  if pack < 1:
    raise ValueError(f"pack must be >= 1, got {pack}")
  if pack > 1:
    # FutureWarning: shown under default filters (DeprecationWarning is
    # suppressed outside __main__, so library callers would never see it)
    warnings.warn(
        "dot_interact(pack>1) is ignored: the matmul-form selection has no "
        "pack concept (pack=1 measured fastest; product bytes grow pack^2)",
        FutureWarning, stacklevel=2)
  # 2-D lane-axis concat, then a row-major (free) reshape: the backward of
  # this build is F clean [B, D] lane-window slices, where a stack's
  # backward slices [B, 1, D] pieces in T(1,128) layouts (~3 ms/step of
  # relayout at F=27, B=64k, traced round 4)
  parts = [bottom_out] + list(emb_outs)
  b, d = parts[0].shape
  bad = [p.shape for p in parts if p.shape != (b, d)]
  if bad:  # the concat+reshape build would silently scramble lanes
    raise ValueError(
        f"dot_interact needs equal [B, D] features; got {bad} vs ({b}, {d})")
  # cast the einsum operands at the source (see _mxu_operand_dtype: a
  # numerics no-op for the products on TPU, where DEFAULT MXU precision
  # rounds operands to bf16 anyway) so the concat, its relayout copies,
  # and the backward split all move half the bytes. The casts' VJP
  # returns the feature cotangents in their original dtype; the one real
  # divergence is a single bf16 rounding of each cotangent value, within
  # the precision class the TF32 reference computes its backward in.
  cd = _mxu_operand_dtype(parts[0].dtype)
  k = 0 if self_interaction else -1
  if use_pallas_interact(b, len(parts), d, cd):
    # per-part kernel I/O: the slices keep their natural row-major layout
    # and the feature concat/split lives in VMEM (ops/pallas_interact.py)
    activations = _pair_products_pallas(
        tuple(p.astype(cd) for p in parts), len(parts), k)
  else:
    flat = jnp.concatenate([p.astype(cd) for p in parts], axis=1)
    activations = _tril_products(flat, len(parts), k)
  return jnp.concatenate([activations, bottom_out.astype(activations.dtype)],
                         axis=1)


class DLRM(nn.Module):
  """DLRM with hybrid-parallel embeddings.

  Args:
    vocab_sizes: per categorical feature, its vocabulary size (26 for Criteo).
    embedding_dim: embedding width (128 for the MLPerf config).
    bottom_mlp / top_mlp: dense stack widths; top ends in 1 logit.
    world_size / strategy / column_slice_threshold / dp_input: forwarded to
      :class:`DistributedEmbedding`.
    compute_dtype: dtype for MLP/interaction compute (bf16 = AMP-equivalent).
  """

  vocab_sizes: Sequence[int]
  embedding_dim: int = 128
  bottom_mlp: Tuple[int, ...] = (512, 256, 128)
  top_mlp: Tuple[int, ...] = (1024, 1024, 512, 256, 1)
  world_size: int = 1
  strategy: str = "basic"
  column_slice_threshold: Optional[int] = None
  row_slice: Optional[int] = None
  dp_input: bool = True
  compute_dtype: Any = jnp.float32
  # small-vocab tables ride the MXU one-hot path (see planner); 4096 is
  # the measured crossover on v5e where the windowed one-hot matmul
  # (fwd + bwd) still beats gather + scatter-apply for a 65k batch
  dense_row_threshold: int = 4096
  # expected global batch (feeds the planner's scatter-regime cost model);
  # pass the same value to dlrm_embedding_plan for a matching plan
  batch_hint: Optional[int] = None

  def setup(self):
    if self.bottom_mlp[-1] != self.embedding_dim:
      raise ValueError(
          f"bottom MLP must end at embedding_dim ({self.embedding_dim}), "
          f"got {self.bottom_mlp}")
    tables = tuple(
        TableConfig(input_dim=int(v), output_dim=self.embedding_dim,
                    initializer=_dlrm_initializer(int(v)))
        for v in self.vocab_sizes)
    self.embeddings = DistributedEmbedding(
        embeddings=tables,
        strategy=self.strategy,
        column_slice_threshold=self.column_slice_threshold,
        row_slice=self.row_slice,
        dp_input=self.dp_input,
        world_size=self.world_size,
        dense_row_threshold=self.dense_row_threshold,
        batch_hint=self.batch_hint,
        name="embeddings")
    self.bottom = MLP(self.bottom_mlp, activate_final=True,
                      dtype=self.compute_dtype, name="bottom_mlp")
    self.top = MLP(self.top_mlp, dtype=self.compute_dtype, name="top_mlp")

  def __call__(self, numerical, categorical, emb_acts=None):
    """numerical [B, num_numerical]; categorical: list of [B] int ids (or
    the packed dict in mp-input mode). Returns [B] logits.

    ``emb_acts`` overrides the embedding lookup with precomputed activations
    (the sparse-gradient training path computes them outside autodiff; see
    ``training.make_sparse_train_step``).
    """
    bottom_out = self.bottom(numerical.astype(self.compute_dtype))
    emb_outs = emb_acts if emb_acts is not None \
        else self.embeddings(categorical)
    emb_outs = [e.astype(self.compute_dtype) for e in emb_outs]
    x = dot_interact(bottom_out, emb_outs)
    logit = self.top(x.astype(self.compute_dtype))
    return jnp.squeeze(logit, -1).astype(jnp.float32)


def dlrm_embedding_plan(vocab_sizes, embedding_dim: int = 128,
                        world_size: int = 1, strategy: str = "basic",
                        column_slice_threshold: Optional[int] = None,
                        dense_row_threshold: int = 4096,
                        row_slice: Optional[int] = None,
                        batch_hint: Optional[int] = None):
  """The placement plan a :class:`DLRM`'s embeddings use (for
  get_weights/set_weights on the ``embeddings`` param subtree)."""
  from ..layers.planner import DistEmbeddingStrategy

  tables = [TableConfig(input_dim=int(v), output_dim=embedding_dim)
            for v in vocab_sizes]
  return DistEmbeddingStrategy(tables, world_size, strategy,
                               column_slice_threshold=column_slice_threshold,
                               dense_row_threshold=dense_row_threshold,
                               row_slice_threshold=row_slice,
                               batch_hint=batch_hint)


def _dlrm_initializer(rows: int):
  """Uniform(-1/sqrt(rows), 1/sqrt(rows)) per table
  (reference ``DLRMInitializer``, `examples/dlrm/utils.py:27-41`)."""
  scale = 1.0 / np.sqrt(rows)

  def init(key, shape, dtype=jnp.float32):
    return jax.random.uniform(key, shape, dtype, minval=-scale, maxval=scale)

  init.scale = scale  # enables direct packed init (init_sparse_state_direct)
  return init


def bce_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
  """Mean sigmoid binary cross-entropy (reference trains with
  ``BinaryCrossentropy(from_logits=True)``, `examples/dlrm/main.py:195-199`)."""
  labels = labels.astype(jnp.float32)
  return jnp.mean(
      jnp.maximum(logits, 0) - logits * labels +
      jnp.log1p(jnp.exp(-jnp.abs(logits))))
