"""DLRM (Deep Learning Recommendation Model), TPU-native.

Functional equivalent of the reference example model
(`/root/reference/examples/dlrm/main.py:76-147` and ``dot_interact`` in
`/root/reference/examples/dlrm/utils.py:92-113`): bottom MLP over numerical
features, embeddings over categorical features (hybrid-parallel via
``DistributedEmbedding`` when world > 1), pairwise dot-product feature
interaction (lower triangle), top MLP to one logit.

TPU notes: the interaction is a [B, F, D] x [B, D, F] batched matmul — MXU
work — and the lower-triangle selection uses a static gather index (no
boolean_mask / dynamic shapes). ``compute_dtype=bfloat16`` runs the MLPs and
interaction in bf16 with fp32 params/accumulation (the AMP configuration of
the reference's headline benchmark).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..layers.dist_model_parallel import DistributedEmbedding
from ..layers.embedding import TableConfig


class MLP(nn.Module):
  features: Sequence[int]
  activate_final: bool = False
  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, x):
    for i, width in enumerate(self.features):
      x = nn.Dense(width, dtype=self.dtype, name=f"dense_{i}")(x)
      if i < len(self.features) - 1 or self.activate_final:
        x = nn.relu(x)
    return x


def dot_interact(bottom_out: jax.Array, emb_outs: Sequence[jax.Array],
                 self_interaction: bool = False) -> jax.Array:
  """Pairwise dot-product interaction + bottom-MLP passthrough.

  Equivalent of `examples/dlrm/utils.py:92-113`, with the dynamic
  ``boolean_mask`` replaced by a static lower-triangle gather (XLA-friendly).
  Output: [B, F*(F-1)/2 + D] where F = num embeddings + 1.
  """
  feats = jnp.stack([bottom_out] + list(emb_outs), axis=1)  # [B, F, D]
  inter = jnp.einsum("bfd,bgd->bfg", feats, feats,
                     preferred_element_type=jnp.float32)  # [B, F, F]
  f = feats.shape[1]
  k = 0 if self_interaction else -1
  rows, cols = np.tril_indices(f, k=k)
  flat = inter.reshape(inter.shape[0], f * f)
  take = jnp.asarray(rows * f + cols, jnp.int32)
  activations = jnp.take(flat, take, axis=1)
  return jnp.concatenate([activations, bottom_out.astype(activations.dtype)],
                         axis=1)


class DLRM(nn.Module):
  """DLRM with hybrid-parallel embeddings.

  Args:
    vocab_sizes: per categorical feature, its vocabulary size (26 for Criteo).
    embedding_dim: embedding width (128 for the MLPerf config).
    bottom_mlp / top_mlp: dense stack widths; top ends in 1 logit.
    world_size / strategy / column_slice_threshold / dp_input: forwarded to
      :class:`DistributedEmbedding`.
    compute_dtype: dtype for MLP/interaction compute (bf16 = AMP-equivalent).
  """

  vocab_sizes: Sequence[int]
  embedding_dim: int = 128
  bottom_mlp: Tuple[int, ...] = (512, 256, 128)
  top_mlp: Tuple[int, ...] = (1024, 1024, 512, 256, 1)
  world_size: int = 1
  strategy: str = "basic"
  column_slice_threshold: Optional[int] = None
  row_slice: Optional[int] = None
  dp_input: bool = True
  compute_dtype: Any = jnp.float32
  # small-vocab tables ride the MXU one-hot path (see planner)
  dense_row_threshold: int = 2048

  def setup(self):
    if self.bottom_mlp[-1] != self.embedding_dim:
      raise ValueError(
          f"bottom MLP must end at embedding_dim ({self.embedding_dim}), "
          f"got {self.bottom_mlp}")
    tables = tuple(
        TableConfig(input_dim=int(v), output_dim=self.embedding_dim,
                    initializer=_dlrm_initializer(int(v)))
        for v in self.vocab_sizes)
    self.embeddings = DistributedEmbedding(
        embeddings=tables,
        strategy=self.strategy,
        column_slice_threshold=self.column_slice_threshold,
        row_slice=self.row_slice,
        dp_input=self.dp_input,
        world_size=self.world_size,
        dense_row_threshold=self.dense_row_threshold,
        name="embeddings")
    self.bottom = MLP(self.bottom_mlp, activate_final=True,
                      dtype=self.compute_dtype, name="bottom_mlp")
    self.top = MLP(self.top_mlp, dtype=self.compute_dtype, name="top_mlp")

  def __call__(self, numerical, categorical, emb_acts=None):
    """numerical [B, num_numerical]; categorical: list of [B] int ids (or
    the packed dict in mp-input mode). Returns [B] logits.

    ``emb_acts`` overrides the embedding lookup with precomputed activations
    (the sparse-gradient training path computes them outside autodiff; see
    ``training.make_sparse_train_step``).
    """
    bottom_out = self.bottom(numerical.astype(self.compute_dtype))
    emb_outs = emb_acts if emb_acts is not None \
        else self.embeddings(categorical)
    emb_outs = [e.astype(self.compute_dtype) for e in emb_outs]
    x = dot_interact(bottom_out, emb_outs)
    logit = self.top(x.astype(self.compute_dtype))
    return jnp.squeeze(logit, -1).astype(jnp.float32)


def dlrm_embedding_plan(vocab_sizes, embedding_dim: int = 128,
                        world_size: int = 1, strategy: str = "basic",
                        column_slice_threshold: Optional[int] = None,
                        dense_row_threshold: int = 2048,
                        row_slice: Optional[int] = None):
  """The placement plan a :class:`DLRM`'s embeddings use (for
  get_weights/set_weights on the ``embeddings`` param subtree)."""
  from ..layers.planner import DistEmbeddingStrategy

  tables = [TableConfig(input_dim=int(v), output_dim=embedding_dim)
            for v in vocab_sizes]
  return DistEmbeddingStrategy(tables, world_size, strategy,
                               column_slice_threshold=column_slice_threshold,
                               dense_row_threshold=dense_row_threshold,
                               row_slice_threshold=row_slice)


def _dlrm_initializer(rows: int):
  """Uniform(-1/sqrt(rows), 1/sqrt(rows)) per table
  (reference ``DLRMInitializer``, `examples/dlrm/utils.py:27-41`)."""
  scale = 1.0 / np.sqrt(rows)

  def init(key, shape, dtype=jnp.float32):
    return jax.random.uniform(key, shape, dtype, minval=-scale, maxval=scale)

  init.scale = scale  # enables direct packed init (init_sparse_state_direct)
  return init


def bce_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
  """Mean sigmoid binary cross-entropy (reference trains with
  ``BinaryCrossentropy(from_logits=True)``, `examples/dlrm/main.py:195-199`)."""
  labels = labels.astype(jnp.float32)
  return jnp.mean(
      jnp.maximum(logits, 0) - logits * labels +
      jnp.log1p(jnp.exp(-jnp.abs(logits))))
