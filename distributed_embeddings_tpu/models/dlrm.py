"""DLRM (Deep Learning Recommendation Model), TPU-native.

Functional equivalent of the reference example model
(`/root/reference/examples/dlrm/main.py:76-147` and ``dot_interact`` in
`/root/reference/examples/dlrm/utils.py:92-113`): bottom MLP over numerical
features, embeddings over categorical features (hybrid-parallel via
``DistributedEmbedding`` when world > 1), pairwise dot-product feature
interaction (lower triangle), top MLP to one logit.

TPU notes: the interaction is a [B, F, D] x [B, D, F] batched matmul — MXU
work — and the lower-triangle selection uses a static gather index (no
boolean_mask / dynamic shapes). ``compute_dtype=bfloat16`` runs the MLPs and
interaction in bf16 with fp32 params/accumulation (the AMP configuration of
the reference's headline benchmark).
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..layers.dist_model_parallel import DistributedEmbedding
from ..layers.embedding import TableConfig


class MLP(nn.Module):
  features: Sequence[int]
  activate_final: bool = False
  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, x):
    for i, width in enumerate(self.features):
      x = nn.Dense(width, dtype=self.dtype, name=f"dense_{i}")(x)
      if i < len(self.features) - 1 or self.activate_final:
        x = nn.relu(x)
    return x


def _tril_maps(f: int, pack: int, k: int):
  """Static index maps for the packed interaction.

  Returns ``take`` — per pack-group, the flat positions in the
  ``[pack*f, pack*f]`` product holding each group sample's lower-triangle
  pairs — and ``inv``, the inverse map used by the backward: for every flat
  position, which output pair (or the zero sentinel ``pack*P``) it
  corresponds to, with BOTH (i,j) and (j,i) mapped so the gathered
  cotangent is already symmetrized (d(F F^T) needs D + D^T)."""
  rows, cols = np.tril_indices(f, k=k)
  p = len(rows)
  gf = pack * f
  take = np.concatenate(
      [(s * f + rows) * gf + (s * f + cols) for s in range(pack)])
  inv = np.full((gf * gf,), pack * p, np.int32)  # sentinel -> zero column
  scale = np.ones((gf * gf,), np.float32)
  for s in range(pack):
    for n, (i, j) in enumerate(zip(rows, cols)):
      inv[(s * f + i) * gf + (s * f + j)] = s * p + n
      if i != j:
        inv[(s * f + j) * gf + (s * f + i)] = s * p + n
      else:
        # diagonal pair (self_interaction): d(x.x)/dx = 2x, and the
        # symmetrizing double-map above can't fire for i == j
        scale[(s * f + i) * gf + (s * f + j)] = 2.0
  return (jnp.asarray(take, jnp.int32), jnp.asarray(inv, jnp.int32),
          jnp.asarray(scale), p)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _packed_tril_products(feats: jax.Array, pack: int, k: int) -> jax.Array:
  """[B, F, D] -> [B, P] lower-triangle pairwise dot products.

  The hand-written VJP is the point (measured on v5e, F=27, B=64k): XLA's
  autodiff of ``einsum + take`` runs a slow axis-1 scatter for the take
  backward plus TWO product einsums (one per operand slot), ~3x the cost of
  the forward. Here the backward is ONE static gather — ``inv`` maps both
  (i,j) and (j,i) to the pair cotangent, building the symmetrized
  ``D + D^T`` directly, with non-pair positions reading an appended zero
  column — followed by ONE einsum ``(D + D^T) @ feats``.

  ``pack`` reshapes ``pack`` samples into one [pack*F, D] operand before
  the batched product (bigger MXU tiles at the cost of pack^2 x the
  product bytes); measured memory-bound at these shapes, so pack=1 wins.
  """
  out, _ = _packed_tril_fwd(feats, pack, k)
  return out


def _packed_tril_fwd(feats, pack, k):
  b, f, d = feats.shape
  take, _, _, p = _tril_maps(f, pack, k)
  packed = feats.reshape(b // pack, pack * f, d)
  inter = jnp.einsum("bpd,bqd->bpq", packed, packed,
                     preferred_element_type=jnp.float32)
  # keep the triangle gather OUT of the matmul fusion: letting XLA fuse the
  # take into the einsum consumer de-tiles the matmul (measured 3.7 + 0.6 ms
  # separate vs 14.6 ms fused at F=27, B=64k)
  inter = jax.lax.optimization_barrier(inter)
  flat = inter.reshape(b // pack, (pack * f) ** 2)
  acts = jnp.take(flat, take, axis=1).reshape(b, p)
  return acts, feats


def _packed_tril_bwd(pack, k, feats, d_acts):
  b, f, d = feats.shape
  _, inv, scale, p = _tril_maps(f, pack, k)
  # gather (not scatter) the cotangent into the [pack*F, pack*F] layout:
  # inv maps both (i,j) and (j,i) to the pair's cotangent and everything
  # else to an appended zero column, so this one static gather builds the
  # already-symmetrized D + D^T and the backward needs a single einsum
  dg = d_acts.reshape(b // pack, pack * p)
  dg = jnp.concatenate([dg, jnp.zeros((b // pack, 1), dg.dtype)], axis=1)
  d_sym = jnp.take(dg, inv, axis=1)
  if k == 0:  # self-interaction diagonals carry factor 2 (see _tril_maps)
    d_sym = d_sym * scale
  # under bf16 compute (AMP) the cotangent is rounded to bf16 before the
  # grad einsum — the AMP convention (the reference's fp16 backward does
  # the same); exact-f32 parity with autodiff holds for f32 feats
  d_sym = d_sym.reshape(b // pack, pack * f, pack * f).astype(feats.dtype)
  # same fusion hazard as the forward, mirrored: keep the gather-built
  # cotangent out of the backward einsum's fusion
  d_sym = jax.lax.optimization_barrier(d_sym)
  packed = feats.reshape(b // pack, pack * f, d)
  d_packed = jnp.einsum("bpq,bqd->bpd", d_sym, packed,
                        preferred_element_type=jnp.float32)
  return (d_packed.reshape(b, f, d).astype(feats.dtype),)


_packed_tril_products.defvjp(_packed_tril_fwd, _packed_tril_bwd)


def dot_interact(bottom_out: jax.Array, emb_outs: Sequence[jax.Array],
                 self_interaction: bool = False,
                 pack: int = 1) -> jax.Array:
  """Pairwise dot-product interaction + bottom-MLP passthrough.

  Equivalent of `examples/dlrm/utils.py:92-113`, with the dynamic
  ``boolean_mask`` replaced by a static lower-triangle gather (XLA-friendly)
  and the per-sample product MXU-packed (see :func:`_packed_tril_products`).
  Output: [B, F*(F-1)/2 + D] where F = num embeddings + 1.
  """
  if pack < 1:
    raise ValueError(f"pack must be >= 1, got {pack}")
  feats = jnp.stack([bottom_out] + list(emb_outs), axis=1)  # [B, F, D]
  b = feats.shape[0]
  k = 0 if self_interaction else -1
  while pack > 1 and b % pack:
    pack //= 2
  activations = _packed_tril_products(feats, pack, k)
  return jnp.concatenate([activations, bottom_out.astype(activations.dtype)],
                         axis=1)


class DLRM(nn.Module):
  """DLRM with hybrid-parallel embeddings.

  Args:
    vocab_sizes: per categorical feature, its vocabulary size (26 for Criteo).
    embedding_dim: embedding width (128 for the MLPerf config).
    bottom_mlp / top_mlp: dense stack widths; top ends in 1 logit.
    world_size / strategy / column_slice_threshold / dp_input: forwarded to
      :class:`DistributedEmbedding`.
    compute_dtype: dtype for MLP/interaction compute (bf16 = AMP-equivalent).
  """

  vocab_sizes: Sequence[int]
  embedding_dim: int = 128
  bottom_mlp: Tuple[int, ...] = (512, 256, 128)
  top_mlp: Tuple[int, ...] = (1024, 1024, 512, 256, 1)
  world_size: int = 1
  strategy: str = "basic"
  column_slice_threshold: Optional[int] = None
  row_slice: Optional[int] = None
  dp_input: bool = True
  compute_dtype: Any = jnp.float32
  # small-vocab tables ride the MXU one-hot path (see planner); 4096 is
  # the measured crossover on v5e where the windowed one-hot matmul
  # (fwd + bwd) still beats gather + scatter-apply for a 65k batch
  dense_row_threshold: int = 4096
  # expected global batch (feeds the planner's scatter-regime cost model);
  # pass the same value to dlrm_embedding_plan for a matching plan
  batch_hint: Optional[int] = None

  def setup(self):
    if self.bottom_mlp[-1] != self.embedding_dim:
      raise ValueError(
          f"bottom MLP must end at embedding_dim ({self.embedding_dim}), "
          f"got {self.bottom_mlp}")
    tables = tuple(
        TableConfig(input_dim=int(v), output_dim=self.embedding_dim,
                    initializer=_dlrm_initializer(int(v)))
        for v in self.vocab_sizes)
    self.embeddings = DistributedEmbedding(
        embeddings=tables,
        strategy=self.strategy,
        column_slice_threshold=self.column_slice_threshold,
        row_slice=self.row_slice,
        dp_input=self.dp_input,
        world_size=self.world_size,
        dense_row_threshold=self.dense_row_threshold,
        batch_hint=self.batch_hint,
        name="embeddings")
    self.bottom = MLP(self.bottom_mlp, activate_final=True,
                      dtype=self.compute_dtype, name="bottom_mlp")
    self.top = MLP(self.top_mlp, dtype=self.compute_dtype, name="top_mlp")

  def __call__(self, numerical, categorical, emb_acts=None):
    """numerical [B, num_numerical]; categorical: list of [B] int ids (or
    the packed dict in mp-input mode). Returns [B] logits.

    ``emb_acts`` overrides the embedding lookup with precomputed activations
    (the sparse-gradient training path computes them outside autodiff; see
    ``training.make_sparse_train_step``).
    """
    bottom_out = self.bottom(numerical.astype(self.compute_dtype))
    emb_outs = emb_acts if emb_acts is not None \
        else self.embeddings(categorical)
    emb_outs = [e.astype(self.compute_dtype) for e in emb_outs]
    x = dot_interact(bottom_out, emb_outs)
    logit = self.top(x.astype(self.compute_dtype))
    return jnp.squeeze(logit, -1).astype(jnp.float32)


def dlrm_embedding_plan(vocab_sizes, embedding_dim: int = 128,
                        world_size: int = 1, strategy: str = "basic",
                        column_slice_threshold: Optional[int] = None,
                        dense_row_threshold: int = 4096,
                        row_slice: Optional[int] = None,
                        batch_hint: Optional[int] = None):
  """The placement plan a :class:`DLRM`'s embeddings use (for
  get_weights/set_weights on the ``embeddings`` param subtree)."""
  from ..layers.planner import DistEmbeddingStrategy

  tables = [TableConfig(input_dim=int(v), output_dim=embedding_dim)
            for v in vocab_sizes]
  return DistEmbeddingStrategy(tables, world_size, strategy,
                               column_slice_threshold=column_slice_threshold,
                               dense_row_threshold=dense_row_threshold,
                               row_slice_threshold=row_slice,
                               batch_hint=batch_hint)


def _dlrm_initializer(rows: int):
  """Uniform(-1/sqrt(rows), 1/sqrt(rows)) per table
  (reference ``DLRMInitializer``, `examples/dlrm/utils.py:27-41`)."""
  scale = 1.0 / np.sqrt(rows)

  def init(key, shape, dtype=jnp.float32):
    return jax.random.uniform(key, shape, dtype, minval=-scale, maxval=scale)

  init.scale = scale  # enables direct packed init (init_sparse_state_direct)
  return init


def bce_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
  """Mean sigmoid binary cross-entropy (reference trains with
  ``BinaryCrossentropy(from_logits=True)``, `examples/dlrm/main.py:195-199`)."""
  labels = labels.astype(jnp.float32)
  return jnp.mean(
      jnp.maximum(logits, 0) - logits * labels +
      jnp.log1p(jnp.exp(-jnp.abs(logits))))
