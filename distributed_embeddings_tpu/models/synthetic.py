"""Synthetic recommender model zoo (tiny -> colossal) + power-law inputs.

Mirror of the reference benchmark models
(`/root/reference/examples/benchmarks/synthetic_models/config_v3.py:30-142`,
`synthetic_models.py:31-233`): the model-size table and per-config embedding
specs are the reference's published benchmark definitions; the model itself
(sum-combined embeddings -> optional strided average-pool "interaction" ->
MLP -> logit) is re-implemented as a flax module over
``DistributedEmbedding``.

| config   | tables | embedding GiB |
|----------|--------|---------------|
| tiny     |     55 |           4.2 |
| small    |    107 |          26.3 |
| medium   |    311 |         206.2 |
| large    |    612 |         773.8 |
| jumbo    |   1022 |        3109.5 |
| colossal |   2002 |       22327.4 |
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..layers.dist_model_parallel import DistributedEmbedding
from ..layers.embedding import TableConfig
from .dlrm import MLP


@dataclasses.dataclass(frozen=True)
class EmbeddingGroup:
  """A group of identical tables (reference ``EmbeddingConfig``,
  `config_v3.py:21-23`). ``nnz`` lists the hotness of each input reading the
  table; len(nnz) > 1 requires ``shared`` (multiple inputs, one table)."""

  num_tables: int
  nnz: Tuple[int, ...]
  num_rows: int
  width: int
  shared: bool


@dataclasses.dataclass(frozen=True)
class SyntheticModelConfig:
  name: str
  embedding_groups: Tuple[EmbeddingGroup, ...]
  mlp_sizes: Tuple[int, ...]
  num_numerical_features: int
  interact_stride: Optional[int]


def _cfg(name, groups, mlp, numerical, stride):
  return SyntheticModelConfig(
      name=name,
      embedding_groups=tuple(EmbeddingGroup(*g) for g in groups),
      mlp_sizes=tuple(mlp),
      num_numerical_features=numerical,
      interact_stride=stride)


# Model definitions transcribed from the reference benchmark suite
# (`config_v3.py:30-142`); (num_tables, nnz, rows, width, shared).
SYNTHETIC_MODELS = {
    "criteo": _cfg("Criteo-dlrm-like",
                   [(26, (1,), 100_000, 128, False)],
                   [512, 256, 128], 13, None),
    "tiny": _cfg("Tiny V3",
                 [(1, (1, 10), 10_000, 8, True),
                  (1, (1, 10), 1_000_000, 16, True),
                  (1, (1, 10), 25_000_000, 16, True),
                  (1, (1,), 25_000_000, 16, False),
                  (16, (1,), 10, 8, False),
                  (10, (1,), 1_000, 8, False),
                  (4, (1,), 10_000, 8, False),
                  (2, (1,), 100_000, 16, False),
                  (19, (1,), 1_000_000, 16, False)],
                 [256, 128], 10, None),
    "small": _cfg("Small V3",
                  [(5, (1, 30), 10_000, 16, True),
                   (3, (1, 30), 4_000_000, 32, True),
                   (1, (1, 30), 50_000_000, 32, True),
                   (1, (1,), 50_000_000, 32, False),
                   (30, (1,), 10, 16, False),
                   (30, (1,), 1_000, 16, False),
                   (5, (1,), 10_000, 16, False),
                   (5, (1,), 100_000, 32, False),
                   (27, (1,), 4_000_000, 32, False)],
                  [512, 256, 128], 10, None),
    "medium": _cfg("Medium v3",
                   [(20, (1, 50), 100_000, 64, True),
                    (5, (1, 50), 10_000_000, 64, True),
                    (1, (1, 50), 100_000_000, 128, True),
                    (1, (1,), 100_000_000, 128, False),
                    (80, (1,), 10, 32, False),
                    (60, (1,), 1_000, 32, False),
                    (80, (1,), 100_000, 64, False),
                    (24, (1,), 200_000, 64, False),
                    (40, (1,), 10_000_000, 64, False)],
                   [1024, 512, 256, 128], 25, 7),
    "large": _cfg("Large v3",
                  [(40, (1, 100), 100_000, 64, True),
                   (16, (1, 100), 15_000_000, 64, True),
                   (1, (1, 100), 200_000_000, 128, True),
                   (1, (1,), 200_000_000, 128, False),
                   (100, (1,), 10, 32, False),
                   (100, (1,), 10_000, 32, False),
                   (160, (1,), 100_000, 64, False),
                   (50, (1,), 500_000, 64, False),
                   (144, (1,), 15_000_000, 64, False)],
                  [2048, 1024, 512, 256], 100, 8),
    "jumbo": _cfg("Jumbo v3",
                  [(50, (1, 200), 100_000, 128, True),
                   (24, (1, 200), 20_000_000, 128, True),
                   (1, (1, 200), 400_000_000, 256, True),
                   (1, (1,), 400_000_000, 256, False),
                   (100, (1,), 10, 32, False),
                   (200, (1,), 10_000, 64, False),
                   (350, (1,), 100_000, 128, False),
                   (80, (1,), 1_000_000, 128, False),
                   (216, (1,), 20_000_000, 128, False)],
                  [2048, 1024, 512, 256], 200, 20),
    "colossal": _cfg("Colossal v3",
                     [(100, (1, 300), 100_000, 128, True),
                      (50, (1, 300), 40_000_000, 256, True),
                      (1, (1, 300), 2_000_000_000, 256, True),
                      (1, (1,), 1_000_000_000, 256, False),
                      (100, (1,), 10, 32, False),
                      (400, (1,), 10_000, 128, False),
                      (100, (1,), 100_000, 128, False),
                      (800, (1,), 1_000_000, 128, False),
                      (450, (1,), 40_000_000, 256, False)],
                     [4096, 2048, 1024, 512, 256], 500, 30),
}


def expand_tables(config: SyntheticModelConfig
                  ) -> Tuple[List[TableConfig], List[int], List[int]]:
  """-> (table configs, input_table_map, per-input hotness)."""
  tables: List[TableConfig] = []
  input_table_map: List[int] = []
  hotness: List[int] = []
  for group in config.embedding_groups:
    if len(group.nnz) > 1 and not group.shared:
      raise NotImplementedError(
          "Non-shared multi-hot embedding groups are not supported "
          "(reference `synthetic_models.py:136-137` has the same restriction)")
    for _ in range(group.num_tables):
      tables.append(TableConfig(input_dim=group.num_rows,
                                output_dim=group.width, combiner="sum"))
      for h in group.nnz:
        input_table_map.append(len(tables) - 1)
        hotness.append(h)
  return tables, input_table_map, hotness


def model_size_gib(config: SyntheticModelConfig) -> float:
  tables, _, _ = expand_tables(config)
  return sum(t.size() for t in tables) * 4 / 2**30


def power_law_ids(rng: np.random.Generator, batch: int, hotness: int,
                  num_rows: int, alpha: float) -> np.ndarray:
  """Power-law distributed ids in [0, num_rows) (reference ``power_law``,
  `synthetic_models.py:31-46`): inverse-CDF transform of uniform samples with
  exponent alpha; alpha=0 degenerates to uniform."""
  if alpha == 0:
    return rng.integers(0, num_rows, size=(batch, hotness), dtype=np.int64)
  gamma = 1.0 - alpha
  r = rng.random(batch * hotness)
  lo, hi = 1.0, float(num_rows + 1)
  y = (r * (hi**gamma - lo**gamma) + lo**gamma) ** (1.0 / gamma)
  return (y.astype(np.int64) - 1).clip(0, num_rows - 1).reshape(batch, hotness)


def generate_batch(config: SyntheticModelConfig, global_batch: int,
                   alpha: float = 0.0, seed: int = 0,
                   ) -> Tuple[np.ndarray, List[np.ndarray], np.ndarray]:
  """One synthetic (numerical, categorical list, labels) batch
  (reference ``InputGenerator``, `synthetic_models.py:51-113`)."""
  rng = np.random.default_rng(seed)
  tables, input_table_map, hotness = expand_tables(config)
  cats = [
      power_law_ids(rng, global_batch, h, tables[t].input_dim, alpha)
      .astype(np.int32)
      for t, h in zip(input_table_map, hotness)
  ]
  numerical = rng.uniform(0, 100, size=(
      global_batch, config.num_numerical_features)).astype(np.float32)
  labels = rng.integers(0, 2, size=(global_batch,)).astype(np.float32)
  return numerical, cats, labels


class SyntheticModel(nn.Module):
  """Synthetic benchmark model (reference ``SyntheticModelTFDE``,
  `synthetic_models.py:116-176`): sum-combined embeddings over power-law
  inputs, optional strided average-pool interaction, MLP head."""

  config: SyntheticModelConfig
  world_size: int = 1
  strategy: str = "memory_balanced"
  column_slice_threshold: Optional[int] = None
  row_slice: Optional[int] = None
  dp_input: bool = True
  compute_dtype: Any = jnp.float32
  # small-vocab tables ride the MXU one-hot path (see planner)
  dense_row_threshold: int = 2048
  # expected global batch (feeds the planner's scatter-regime cost model)
  batch_hint: Optional[int] = None

  def setup(self):
    tables, input_table_map, self._hotness = expand_tables(self.config)
    self.embeddings = DistributedEmbedding(
        embeddings=tuple(tables),
        strategy=self.strategy,
        column_slice_threshold=self.column_slice_threshold,
        row_slice=self.row_slice,
        dp_input=self.dp_input,
        input_table_map=tuple(input_table_map),
        world_size=self.world_size,
        input_hotness=tuple(self._hotness),
        dense_row_threshold=self.dense_row_threshold,
        batch_hint=self.batch_hint,
        name="embeddings")
    self.mlp = MLP(tuple(self.config.mlp_sizes) + (1,),
                   dtype=self.compute_dtype, name="mlp")

  def __call__(self, numerical, cat_features, emb_acts=None):
    outs = emb_acts if emb_acts is not None \
        else self.embeddings(cat_features)
    x = jnp.concatenate([o.astype(self.compute_dtype) for o in outs], axis=1)
    if self.config.interact_stride is not None:
      # strided average pooling over the concatenated feature axis emulates a
      # bandwidth-limited interaction (reference `synthetic_models.py:151-156`)
      x = nn.avg_pool(x[..., None], window_shape=(self.config.interact_stride,),
                      strides=(self.config.interact_stride,),
                      padding="SAME")[..., 0]
    x = jnp.concatenate([x, numerical.astype(self.compute_dtype)], axis=1)
    return jnp.squeeze(self.mlp(x), -1).astype(jnp.float32)
