"""Prefetch stage for tiered classes: classify, stage, write back, re-rank.

Runs AHEAD of the jitted train step, host-side. Per step:

1. **classify**: replicate the engine's routing arithmetic
   (`lookup_engine._build_routing`) in numpy over the global batch to get,
   per (host-tier class, rank), the deduped requested physical rows, and
   split them hot/cold against the resident map. Also accumulates the
   per-row observed counts that drive re-ranking.
2. **stage**: host-gather the cold rows (with their interleaved
   optimizer-state lanes) from the class image and ``jax.device_put`` them
   as this step's staging upload — sorted ids + row block, padded to the
   staging size. A batch whose deduped cold rows overflow the base region
   spills deterministically into the next power-of-two bucket (a larger
   second host gather; the step retraces once per new bucket size and
   never drops an update).
3. **write_back**: after the step, fetch the post-scatter staging region
   and overwrite the staged rows in the host image (they are the new
   authoritative values).
4. **rerank** (periodic): promote the highest-count rows into the cache
   and evict the lowest — value-preserving swaps through the image, then
   refresh the device resident maps.

The classify step is independent of the previous step's results, so a
trainer can run it on a worker thread while the device computes
(`pipeline.run_tiered_overlapped` via ``TieredTrainer(overlap_host=
True)``). The stage gather historically had to wait for the previous
write-back (a row staged twice in a row needs its updated value); the
overlap path gathers concurrently instead and REPAIRS the conflict set
afterward — once step k's write-back lands, only
``intersect(cold rows staged for k+1, rows staged by k)`` can hold a
stale or torn value, and :meth:`TieredPrefetcher.repair_conflicts`
re-gathers exactly those rows, making the staged block byte-identical
to a serial gather's. The worker half is side-effect-free: classify
returns its count updates as data (`classify_pure` / `apply_counts`)
and the gather builds host blocks only (`gather_cold`); the device
upload and the shared counters commit on the main thread
(`upload_staged`).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.ragged import RaggedIds
from ..parallel.lookup_engine import TIER_PAD_GRP
from ..resilience import retry as _retry
from ..telemetry import get_registry as _registry, span as _span
from .plan import TieringPlan
from .store import HostTierStore


@dataclasses.dataclass
class StagedBatch:
  """One step's staging upload + the host-side info to write it back."""

  device: dict                       # step input: {"grps", "rows", "resident"}
  cold: Dict[str, List[np.ndarray]]  # per class, per rank: staged row ids
  s_eff: Dict[str, int]              # per class: padded staging size
  host_gather_bytes: int
  spilled: bool


@dataclasses.dataclass
class ColdBlocks:
  """The host half of one batch's staging: padded id/row blocks, all
  numpy. Built by ``gather_cold`` (worker-thread safe), optionally
  patched by ``repair_conflicts``, committed by ``upload_staged``."""

  cold: Dict[str, List[np.ndarray]]          # per class, per rank: sorted ids
  s_eff: Dict[str, int]                      # per class: padded staging size
  g_blocks: Dict[str, Dict[int, np.ndarray]]  # per class, per rank: padded ids
  r_blocks: Dict[str, Dict[int, np.ndarray]]  # owned ranks: padded row blocks
  host_gather_bytes: int
  spilled: bool


class TieredPrefetcher:
  """Host-side prefetch pipeline bound to one plan + store."""

  def __init__(self, tplan: TieringPlan, store: HostTierStore,
               mesh=None, axis_name: str = "mp",
               retry_policy: _retry.RetryPolicy = _retry.DEFAULT_POLICY,
               telemetry=None):
    self.axis_name = axis_name  # rebind() below derives the rest
    # the registry the gather/spill counters land in (default: the
    # process-wide one; a wrapping trainer may re-point it so isolated
    # accounting captures the WHOLE protocol's counters)
    self.telemetry = telemetry if telemetry is not None else _registry()
    # Host gathers are the one step-critical operation here that touches
    # storage outside our control (host RAM today, NFS/disk-backed
    # stores tomorrow — and the fault injector either way): a transient
    # OSError retries with exponential backoff instead of killing the
    # run. Retries are counted for metrics_summary; non-OSError failures
    # (e.g. the store's bounds IndexError) propagate immediately.
    self.host_gather_retries = 0

    def _count_retry(attempt, exc):
      self.host_gather_retries += 1
      self.telemetry.counter("tiered/host_gather_retries").inc()

    self._count_retry = _count_retry
    self._retry_policy = retry_policy
    self.total_host_gather_bytes = 0
    self.spill_steps = 0
    # binding-dependent state (_gather/_recipe/_resident_dev/re-rank
    # phase) derives in ONE place so a constructed and a rebound
    # prefetcher can never route differently
    self.rebind(tplan, store, mesh=mesh, axis_name=axis_name)

  def rebind(self, tplan: TieringPlan, store: HostTierStore,
             mesh=None, axis_name: Optional[str] = None) -> None:
    """(Re-)point this prefetcher at a plan + store — the constructor
    tail, and the live elastic resize's hook
    (``resilience.elastic.elastic_resize`` built a new
    ``TieringPlan``/``HostTierStore`` for the new world, and the
    classify/stage pipeline must route against them from the next
    step). Re-derives the routing recipe (class key -> per rank ->
    [(input_id, row_offset, row_start, shard_rows, vocab, row_sliced)]
    — the plan's shared host-side replica of the traced routing, also
    consumed by the streaming row-generation tracker) and the device
    resident maps, re-wraps the retried gather around the new store,
    and resets the re-rank phase; the cumulative gather/spill/retry
    counters survive — they describe the run, not the world shape."""
    self.tplan = tplan
    self.store = store
    self.plan = tplan.plan
    self.mesh = mesh
    if axis_name is not None:
      self.axis_name = axis_name
    self._gather = _retry.retrying(store.gather, policy=self._retry_policy,
                                   on_retry=self._count_retry)
    self._recipe: Dict[tuple, List[list]] = {
        key: self.plan.routing_recipe(key) for key in tplan.classes}
    self._resident_dev = store.resident_arrays(self.mesh, self.axis_name)
    self.steps_since_rerank = 0

  def refresh_resident(self) -> None:
    """Re-derive the device resident maps from the store.

    Call after anything rewrites the store's resident state OUTSIDE the
    prefetcher's own re-rank — e.g. a checkpoint restore (auto-resume /
    rollback): classifying against the pre-restore maps would stage the
    wrong cold rows and trip the ``missed > 0`` contract."""
    self._resident_dev = self.store.resident_arrays(self.mesh,
                                                    self.axis_name)

  # ---- classification ----------------------------------------------------
  @staticmethod
  def _input_ids_np(x) -> np.ndarray:
    if isinstance(x, RaggedIds):
      raise NotImplementedError(
          "tiered prefetch of RaggedIds inputs: classify over the value "
          "stream is not wired up yet — pad to dense multi-hot "
          "(ragged_to_padded) for host-tiered tables")
    return np.asarray(x).reshape(-1)

  def classify(self, cats: Sequence) -> Dict[str, List[np.ndarray]]:
    """Global batch -> per class name, per rank, the deduped COLD
    physical rows; updates the observed counts (occurrences, not dedup
    presence — re-ranking should weight by traffic)."""
    with _span("tiered/classify"):
      return self._classify(cats)

  def _classify(self, cats: Sequence) -> Dict[str, List[np.ndarray]]:
    cold, updates = self.classify_pure(cats)
    self.apply_counts(updates)
    return cold

  def classify_pure(self, cats: Sequence):
    """The classify pass WITHOUT its side effect: returns ``(cold,
    count_updates)`` where the observed-count increments come back as
    data (``{name: [(req, occ), ...]}`` per rank) for ``apply_counts``
    at the main thread's commit point. This is the overlap worker's
    form — it reads only plan geometry and the resident maps (stable
    between re-ranks), so it may run while the device computes and
    while a snapshot serializes the counts."""
    from ..layers.planner import routed_rows
    cold: Dict[str, List[np.ndarray]] = {}
    updates: Dict[str, list] = {}
    for key, c in self.tplan.classes.items():
      rpp = c.spec.rpp
      per_rank = []
      per_rank_updates = []
      for rank in range(self.plan.world_size):
        # the shared numpy replica of the traced routing (planner.
        # routed_rows — also the streaming tracker's), then physical
        # groups for the hot/cold split
        grps_occ = routed_rows(self._recipe[key][rank], cats,
                               self._input_ids_np) // rpp
        # one sort serves both outputs: dedup for the hot/cold split and
        # occurrence counts for re-ranking (np.add.at over the raw stream
        # is ~10x slower, and this stage must stay ahead of the device)
        req, occ = np.unique(grps_occ, return_counts=True)
        # batch-derived indices: bounds-check against the image before
        # any fancy indexing (descriptive error instead of numpy's)
        req = self.store.check_rows(c.name, rank, req.astype(np.int32))
        per_rank_updates.append((req, occ))
        rmap = self.store.resident_map[c.name][rank]
        per_rank.append(req[rmap[req] < 0])
      cold[c.name] = per_rank
      updates[c.name] = per_rank_updates
    return cold, updates

  def apply_counts(self, count_updates: Dict[str, list]) -> None:
    """Commit ``classify_pure``'s deferred observed-count increments.

    Main thread only, AFTER the preceding step's snapshot/drain hooks:
    a snapshot taken after committed step j then observes counts
    covering exactly batches 1..j — the serial ordering — even though
    batch j+1's classify already ran on the worker."""
    for name, per_rank in count_updates.items():
      for rank, (req, occ) in enumerate(per_rank):
        self.store.counts[name][rank][req] += occ

  # ---- staging -----------------------------------------------------------
  def _bucket(self, c, n: int) -> int:
    """Padded staging size for ``n`` deduped cold rows: the base region,
    or on overflow the next power-of-two multiple up to
    ``spill_factor_max``; demand past that pads to exactly ``n`` (no
    bucket rounding — never-drop beats retrace economy there). Clamped
    to the hard cap so compact ids stay under the sentinel."""
    base = c.spec.staging_grps
    fmax = self.tplan.config.spill_factor_max
    if n <= base:
      return base
    factor = 1
    while base * factor < n and factor < fmax:
      factor = min(factor * 2, fmax)
    s = min(max(base * factor, n), c.spill_cap_grps)
    if n > s:
      raise ValueError(
          f"class {c.name}: batch touches {n:,} distinct cold physical "
          f"rows but at most {s:,} can stage (cache {c.spec.cache_grps:,}"
          f" of {c.layout_logical.phys_rows:,} rows). This batch covers "
          "nearly the whole table — tiering cannot serve it; raise "
          "host_row_threshold or enlarge the cache/staging budget.")
    return s

  def stage(self, cold: Dict[str, List[np.ndarray]]) -> StagedBatch:
    """Host-gather the cold rows and upload the staging inputs."""
    with _span("tiered/stage"):
      return self._stage(cold)

  def _stage(self, cold: Dict[str, List[np.ndarray]]) -> StagedBatch:
    return self.upload_staged(self.gather_cold(cold))

  def gather_cold(self, cold: Dict[str, List[np.ndarray]]) -> ColdBlocks:
    """The host half of staging: padded id blocks for every rank plus
    host-gathered row blocks for the OWNED ranks, all numpy.

    Worker-thread safe: reads plan geometry and the host images only,
    and touches no shared mutable state (the cumulative gather/spill
    counters commit in ``upload_staged``). A concurrent write-back may
    race this gather — only on rows both batches staged, which
    ``repair_conflicts`` re-reads afterward."""
    g_blocks_all: Dict[str, Dict[int, np.ndarray]] = {}
    r_blocks_all: Dict[str, Dict[int, np.ndarray]] = {}
    s_eff: Dict[str, int] = {}
    nbytes = 0
    spilled = False
    owned = frozenset(self.store.owned_ranks)
    for c in self.tplan.classes.values():
      per_rank_cold = cold[c.name]
      lay = c.layout_logical
      # the padded size is a GLOBAL max over every rank's cold count —
      # classify runs over the replicated batch on every process, so a
      # sharded pod's processes derive the same s and the staged arrays
      # have one global shape
      s = max(self._bucket(c, len(g)) for g in per_rank_cold)
      spilled |= s > c.spec.staging_grps
      g_blocks: Dict[int, np.ndarray] = {}
      r_blocks: Dict[int, np.ndarray] = {}
      for rank, g in enumerate(per_rank_cold):
        pad = s - g.shape[0]
        g_blocks[rank] = np.concatenate(
            [g, np.full((pad,), TIER_PAD_GRP, np.int32)])
        if rank not in owned:
          continue  # the owner host-gathers its own image
        rows = self._gather(c.name, rank, g)  # bounds-checked, retried
        nbytes += rows.nbytes
        r_blocks[rank] = np.concatenate(
            # pad in the image dtype: f32 training stores, and the serve
            # tier's stripped f32/int8 images ride the same pipeline
            [rows, np.zeros((pad, lay.phys_width), rows.dtype)])
      g_blocks_all[c.name] = g_blocks
      r_blocks_all[c.name] = r_blocks
      s_eff[c.name] = s
    return ColdBlocks(cold=cold, s_eff=s_eff, g_blocks=g_blocks_all,
                      r_blocks=r_blocks_all, host_gather_bytes=nbytes,
                      spilled=spilled)

  def repair_conflicts(self, blocks: ColdBlocks,
                       prev_cold: Dict[str, List[np.ndarray]]) -> int:
    """Re-gather the rows a concurrent write-back may have raced.

    ``blocks`` was gathered while the PREVIOUS step's write-back was
    landing; only rows in ``intersect(blocks.cold, prev_cold)`` were
    scattered under the gather, so re-reading exactly those (after the
    write-back returned) makes every row block byte-identical to a
    serial gather-after-write-back. Both id sets are sorted-unique
    (np.unique upstream), so the intersection and the patch positions
    are a couple of merges. Returns the number of rows re-gathered."""
    owned = frozenset(self.store.owned_ranks)
    repaired = 0
    for c in self.tplan.classes.values():
      for rank in range(self.plan.world_size):
        if rank not in owned:
          continue
        g = blocks.cold[c.name][rank]
        conflict = np.intersect1d(g, prev_cold[c.name][rank],
                                  assume_unique=True)
        if not conflict.size:
          continue
        rows = self._gather(c.name, rank, conflict.astype(np.int32))
        blocks.r_blocks[c.name][rank][np.searchsorted(g, conflict)] = rows
        repaired += int(conflict.size)
    if repaired:
      self.telemetry.counter("tiered/conflict_rows_regathered").inc(repaired)
    return repaired

  def upload_staged(self, blocks: ColdBlocks) -> StagedBatch:
    """The device half of staging (main thread): upload the padded
    blocks and commit the cumulative gather/spill counters."""
    grps_dev, rows_dev = {}, {}
    for c in self.tplan.classes.values():
      s = blocks.s_eff[c.name]
      lay = c.layout_logical
      grps_dev[c.name] = self.store._global_or_callback(
          c.name, s, None, lambda r, b=blocks.g_blocks[c.name]: b[r],
          self.mesh, self.axis_name)
      rows_dev[c.name] = self.store._global_or_callback(
          c.name, s, lay.phys_width, lambda r, b=blocks.r_blocks[c.name]: b[r],
          self.mesh, self.axis_name)
    self.total_host_gather_bytes += blocks.host_gather_bytes
    self.spill_steps += int(blocks.spilled)
    self.telemetry.counter("tiered/host_gather_bytes").inc(
        blocks.host_gather_bytes)
    if blocks.spilled:
      self.telemetry.counter("tiered/spill_steps").inc()
    return StagedBatch(
        device={"grps": grps_dev, "rows": rows_dev,
                "resident": self._resident_dev},
        cold=blocks.cold, s_eff=blocks.s_eff,
        host_gather_bytes=blocks.host_gather_bytes, spilled=blocks.spilled)

  def prepare(self, cats: Sequence) -> StagedBatch:
    """classify + stage in one call (the synchronous path)."""
    return self.stage(self.classify(cats))

  # ---- write-back --------------------------------------------------------
  def write_back(self, staged: StagedBatch,
                 staged_out: Dict[str, jax.Array]) -> None:
    """Overwrite the staged rows in the host images with the
    post-scatter device values.

    Owner-local under rank-owner sharding: each process fetches only
    its owned ranks' windows of the staged output (addressable-shard
    reads — global indexing of a non-addressable array is an error)
    and scatters them into only its own images; every process doing so
    covers the world with no cross-process row ever moving."""
    from .store import read_row_window
    owned = frozenset(self.store.owned_ranks)
    with _span("tiered/write_back"):
      for c in self.tplan.classes.values():
        s = staged.s_eff[c.name]
        for rank, g in enumerate(staged.cold[c.name]):
          if not g.shape[0] or rank not in owned:
            continue
          rows = read_row_window(staged_out[c.name], rank * s,
                                 rank * s + g.shape[0])
          self.store.scatter(c.name, rank, g, rows)

  # ---- promotion / eviction ----------------------------------------------
  def maybe_rerank(self, fused: Dict[str, jax.Array], decay: bool = True
                   ) -> Dict[str, jax.Array]:
    """Re-rank the resident set by observed counts when the configured
    interval elapsed; otherwise a no-op. Returns the (possibly updated)
    fused buffers."""
    interval = self.tplan.config.rerank_interval
    self.steps_since_rerank += 1
    if not interval or self.steps_since_rerank < interval:
      return fused
    self.steps_since_rerank = 0
    return self.rerank(fused, decay=decay)

  def rerank(self, fused: Dict[str, jax.Array], decay: bool = True
             ) -> Dict[str, jax.Array]:
    """Promote the top-count rows into the cache, evicting the rest.

    Value-preserving: evicted rows' device values go to the image, the
    promoted rows' image values go to the vacated cache slots, and the
    resident maps (host + device) are refreshed. ``decay`` halves the
    counts afterward so the ranking tracks traffic drift instead of
    accumulating forever."""
    with _span("tiered/rerank"):
      return self._rerank(fused, decay=decay)

  def _rerank(self, fused: Dict[str, jax.Array], decay: bool = True
              ) -> Dict[str, jax.Array]:
    if not self.store.owns_all:
      return self._rerank_sharded(fused, decay=decay)
    fused = dict(fused)
    for c in self.tplan.classes.values():
      spec, lay = c.spec, c.layout_logical
      per = spec.cache_grps + spec.staging_grps
      name = c.name
      all_idx, all_rows = [], []
      for rank in range(self.plan.world_size):
        counts = self.store.counts[name][rank]
        # top-K by count desc, ties broken row-id asc — O(n) partition
        # instead of a full lexsort (counts spans the whole vocabulary):
        # rows above the K-th count are in outright, rows AT it fill the
        # remainder lowest-id-first (np.where returns ascending ids)
        k = spec.cache_grps
        cand = np.argpartition(-counts, k - 1)[:k]
        cstar = counts[cand].min()
        sure = np.where(counts > cstar)[0]
        ties = np.where(counts == cstar)[0][:k - sure.shape[0]]
        top = np.sort(np.concatenate([sure, ties]).astype(np.int32))
        current = self.store.resident_grps[name][rank]
        leaving_mask = ~np.isin(current, top)
        entering = np.setdiff1d(top, current)
        slots = np.where(leaving_mask)[0].astype(np.int32)
        k = min(slots.shape[0], entering.shape[0])
        if not k:
          continue
        slots, entering = slots[:k], entering[:k]
        gidx = rank * per + slots
        # evict: device values -> image
        self.store.scatter(name, rank, current[slots],
                           np.asarray(fused[name][gidx]))
        # promote: image values -> vacated slots
        all_idx.append(gidx)
        all_rows.append(self._gather(name, rank, entering))
        rmap = self.store.resident_map[name][rank]
        rmap[current[slots]] = -1
        rmap[entering] = slots
        current[slots] = entering
      if all_idx:
        idx = jnp.asarray(np.concatenate(all_idx))
        rows = jnp.asarray(np.concatenate(all_rows))
        fused[name] = fused[name].at[idx].set(rows)
      if decay:
        for rank in range(self.plan.world_size):
          self.store.counts[name][rank] >>= 1
    self._resident_dev = self.store.resident_arrays(self.mesh,
                                                    self.axis_name)
    return fused

  def _rerank_sharded(self, fused: Dict[str, jax.Array], decay: bool = True
                      ) -> Dict[str, jax.Array]:
    """Owner-local re-rank for rank-owner-sharded stores.

    The incremental path's eager ``.at[idx].set`` would need every
    process to issue the same global update — but each process only
    knows its own ranks' rows. Instead: flush (owned cache rows become
    authoritative in the images), recompute the top-K resident set for
    EVERY rank from the replicated counts (all processes agree on the
    new maps — counts evolve identically from the replicated batch
    stream), then rebuild the fused blocks from the images via
    ``make_array_from_callback`` (each process uploads only its owned
    ranks). Same resident set as the incremental path; slot ASSIGNMENT
    may differ (wholesale rebuild vs in-place swaps), which only the
    translation maps see — and they are refreshed here too."""
    fused = dict(fused)
    self.store.flush(fused)
    for c in self.tplan.classes.values():
      name, spec = c.name, c.spec
      k = spec.cache_grps
      for rank in range(self.plan.world_size):
        counts = self.store.counts[name][rank]
        # same top-K-by-count policy as the incremental path: rows above
        # the K-th count outright, ties filled lowest-row-id-first
        cand = np.argpartition(-counts, k - 1)[:k]
        cstar = counts[cand].min()
        sure = np.where(counts > cstar)[0]
        ties = np.where(counts == cstar)[0][:k - sure.shape[0]]
        top = np.sort(np.concatenate([sure, ties]).astype(np.int32))
        rmap = self.store.resident_map[name][rank]
        rmap[:] = -1
        rmap[top] = np.arange(k, dtype=np.int32)
        self.store.resident_grps[name][rank] = top.copy()
        if decay:
          counts >>= 1
    fused.update(self.store.build_fused(self.mesh, self.axis_name))
    self._resident_dev = self.store.resident_arrays(self.mesh,
                                                    self.axis_name)
    return fused
