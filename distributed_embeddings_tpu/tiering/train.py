"""Tiered state construction + the host-side training loop.

:func:`init_tiered_state` is ``training.init_sparse_state_direct`` with a
third placement kind: host-tier classes draw their FULL packed image in
host RAM (:class:`HostTierStore`) and put only the compact hot-cache +
staging buffer on device; device-tier sparse classes and MXU dense
classes are unchanged.

:class:`TieredTrainer` owns the per-step protocol around
``training.make_tiered_train_step``:

    classify (host)  ->  stage (host gather + upload)  ->  device step
    ->  write back (staging region -> host image)  ->  periodic re-rank

:meth:`TieredTrainer.run` overlaps the NEXT batch's classification with
the device step (jax dispatch is asynchronous; the classify needs only
the resident map, not the step's results), which is the prefetch-ahead
stage of the paper's production pattern. The stage gather itself must
wait for the previous write-back — a row staged twice in a row needs its
updated value — so the overlap depth is one classify, not a full stage.
On a re-rank step the look-ahead classify is deferred until after the
re-rank (classifying against a resident map the re-rank is about to
replace could mark a just-evicted row hot and silently drop its update).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..ops.packed_table import SparseRule
from ..parallel.lookup_engine import (
    DistributedLookup,
    class_param_name,
    padded_rows,
)
from ..telemetry import get_registry as _registry, span as _span
from ..training import make_tiered_train_step, shard_batch
from .plan import TieringPlan
from .prefetch import TieredPrefetcher
from .store import HostTierStore


def init_tiered_state(tplan: TieringPlan, store: HostTierStore,
                      rule: SparseRule,
                      dense_params: Any,
                      dense_optimizer: optax.GradientTransformation,
                      rng: jax.Array,
                      emb_dense_optimizer: Optional[
                          optax.GradientTransformation] = None,
                      mesh=None,
                      axis_name: str = "mp",
                      image_seed: Optional[int] = 0,
                      dtype=jnp.float32) -> Dict[str, Any]:
  """Build the fused train state for a tiered plan.

  Host-tier classes: the full packed image is drawn (or kept, see
  ``image_seed``) in ``store``'s host RAM, and the device gets the
  compact ``[cache + staging]`` buffer seeded from the resident set
  (``HostTierStore.build_fused``). Device-tier sparse classes are drawn
  directly in packed layout; dense classes in the simple layout — both
  exactly as ``init_sparse_state_direct``.

  Args:
    image_seed: seed for drawing the host images (numpy RNG — nothing of
      a host-tier class ever stages on device). ``None`` keeps the
      store's current images (caller installed them via ``set_image``,
      e.g. packed from a reference run or a checkpoint).
  """
  from ..layers.dist_model_parallel import make_class_initializer
  from ..training import draw_packed_class

  plan = tplan.plan
  if image_seed is not None:
    store.init_uniform(image_seed)
  engine = DistributedLookup(plan, axis_name=axis_name)
  layouts = engine.fused_layouts(rule, rows_overrides=tplan.rows_overrides)
  tiered_fused = store.build_fused(mesh, axis_name)

  fused = {}
  emb_dense = {}
  for ki, key in enumerate(plan.class_keys):
    name = class_param_name(*key)
    cp = plan.classes[key]
    sub = jax.random.fold_in(rng, ki)
    if name in tplan.tier_specs:
      fused[name] = tiered_fused[name]
    elif cp.kind == "sparse":
      fused[name] = draw_packed_class(plan, key, layouts[name], rule, sub,
                                      dtype)
    else:
      shape = (plan.world_size * padded_rows(plan, key), cp.width)
      emb_dense[name] = make_class_initializer(plan, key)(sub, shape, dtype)

  opt = emb_dense_optimizer or dense_optimizer
  return {
      "dense": dense_params,
      "dense_opt": dense_optimizer.init(dense_params),
      "emb_dense": emb_dense,
      "emb_dense_opt": opt.init(emb_dense),
      "fused": fused,
      "step": jnp.zeros((), jnp.int32),
  }


def init_tiered_state_from_params(tplan: TieringPlan, store: HostTierStore,
                                  rule: SparseRule,
                                  params: Any,
                                  dense_optimizer:
                                  optax.GradientTransformation,
                                  emb_dense_optimizer: Optional[
                                      optax.GradientTransformation] = None,
                                  mesh=None,
                                  axis_name: str = "mp",
                                  emb_collection: str = "embeddings"
                                  ) -> Dict[str, Any]:
  """Build the tiered train state from fully-initialized simple-layout
  params (``training.init_sparse_state``'s tiered counterpart).

  Host-tier classes are packed HOST-SIDE into the store's images (numpy;
  the class never materializes on device — which is the point), then the
  compact device buffers are gathered from the resident set. Mainly for
  parity tests and for migrating an existing run onto tiering; fresh
  training should use :func:`init_tiered_state` (direct draws).
  """
  plan = tplan.plan
  engine = DistributedLookup(plan, axis_name=axis_name)
  layouts = engine.fused_layouts(rule, rows_overrides=tplan.rows_overrides)
  tables = params[emb_collection]
  dense = {k: v for k, v in params.items() if k != emb_collection}

  fused = {}
  emb_dense = {}
  for key in plan.class_keys:
    name = class_param_name(*key)
    cp = plan.classes[key]
    arr = tables[name]
    if name in tplan.tier_specs:
      lay = tplan.by_name(name).layout_logical
      arr_np = np.asarray(jax.device_get(arr))
      for rank in range(plan.world_size):
        block = arr_np[rank * lay.rows:(rank + 1) * lay.rows]
        store.set_image(name, rank, lay.pack(
            block, rule.init_aux(lay.rows, lay.width, np.float32)))
    elif cp.kind == "sparse":
      layout = layouts[name]

      def pack_all(a, layout=layout):
        rows = a.shape[0] // plan.world_size
        return jnp.concatenate(
            [layout.pack_chunked(a[r * rows:(r + 1) * rows], rule.aux_init)
             for r in range(plan.world_size)])

      fused[name] = jax.jit(pack_all)(arr)
    else:
      emb_dense[name] = arr
  fused.update(store.build_fused(mesh, axis_name))

  opt = emb_dense_optimizer or dense_optimizer
  return {
      "dense": dense,
      "dense_opt": dense_optimizer.init(dense),
      "emb_dense": emb_dense,
      "emb_dense_opt": opt.init(emb_dense),
      "fused": fused,
      "step": jnp.zeros((), jnp.int32),
  }


def unpack_tiered_state(tplan: TieringPlan, store: HostTierStore,
                        rule: SparseRule, state: Dict[str, Any],
                        emb_collection: str = "embeddings",
                        axis_name: str = "mp"):
  """Tiered state -> simple-layout params (checkpoint / get_weights view).

  The caller must reconcile first (``TieredTrainer.flush`` /
  ``HostTierStore.flush``): host-tier tables are read from the host
  images, which are only authoritative for resident rows after a flush.
  """
  plan = tplan.plan
  engine = DistributedLookup(plan, axis_name=axis_name)
  layouts = engine.fused_layouts(rule, rows_overrides=tplan.rows_overrides)
  tables = {}
  for key in plan.class_keys:
    name = class_param_name(*key)
    cp = plan.classes[key]
    if name in tplan.tier_specs:
      # unpack HOST-side (PackedLayout.unpack is numpy-generic): the
      # image may not fit any device buffer — that being possible is the
      # tier's whole point
      lay = tplan.by_name(name).layout_logical
      tables[name] = np.concatenate(
          [lay.unpack(img)[0] for img in store.images[name]])
    elif cp.kind == "sparse":
      layout = layouts[name]
      buf = state["fused"][name]
      tables[name] = jnp.concatenate(
          [layout.unpack_table_chunked(
              buf[r * layout.phys_rows:(r + 1) * layout.phys_rows])
           for r in range(plan.world_size)])
    else:
      tables[name] = state["emb_dense"][name]
  return {**state["dense"], emb_collection: tables}


class TieredTrainer:
  """Drives tiered training: prefetch, device step, write-back, re-rank.

  Owns the mutable pieces — the train ``state`` pytree, the host
  :class:`HostTierStore`, and the cumulative hit-rate counters. One call
  to :meth:`step` is the synchronous protocol; :meth:`run` pipelines the
  classify stage ahead of the device step.

  Counters (occurrence counts over all steps, summed across ranks):
  ``hits[name] = [hot_hits, staged_hits, missed, valid_total]``. A
  nonzero ``missed`` raises — it means an id was neither resident nor
  staged, its update went to the sentinel, and training silently
  diverged from the all-device semantics (prefetch contract violation,
  e.g. a re-rank raced the classify).

  ``guard=True`` builds the hardened step
  (``make_tiered_train_step(guard=True)``): a non-finite batch commits
  nothing — dense params, packed buffers, AND the host-tier images stay
  bit-identical (the staging write-back rewrites unchanged rows) — and
  the trainer counts the skips (``bad_steps``) and OOV occurrences
  (``oov_totals``; ``plan.oov='error'`` raises host-side with the state
  untouched, exactly like the sparse ResilientTrainer path).

  Plans built with ``dedup_exchange=True`` compose transparently (the
  tiered id translation rewrites the deduplicated unique blocks; the
  staged wire inherits the plan's ``wire_dtype`` like every other
  exchange), with one accounting caveat: the counters then count UNIQUE
  ids per (source rank, dest rank, bucket) block rather than
  occurrences — hit *rates* shift toward the cold tail (each hot id
  counts once per block, not once per duplicate), while the
  ``missed > 0`` abort contract is unchanged.
  """

  def __init__(self, model, tplan: TieringPlan, store: HostTierStore,
               loss_fn: Callable,
               dense_optimizer: optax.GradientTransformation,
               rule: SparseRule,
               mesh,
               state: Dict[str, Any],
               batch_example: Any,
               axis_name: str = "mp",
               emb_dense_optimizer: Optional[
                   optax.GradientTransformation] = None,
               exact: bool = False,
               donate: bool = True,
               guard: bool = False,
               telemetry=None,
               overlap_host: bool = False):
    self.tplan = tplan
    self.store = store
    self.mesh = mesh
    self.axis_name = axis_name
    self.state = state
    self.guard = guard
    self.overlap_host = overlap_host
    # hit/lookup counters emit here (default: the process registry);
    # the prefetcher shares it so one registry sees the whole protocol
    self.telemetry = telemetry if telemetry is not None else _registry()
    self.prefetcher = TieredPrefetcher(tplan, store, mesh, axis_name,
                                       telemetry=self.telemetry)
    self._step_fn = make_tiered_train_step(
        model, tplan, loss_fn, dense_optimizer, rule, mesh, state,
        batch_example, axis_name=axis_name,
        emb_dense_optimizer=emb_dense_optimizer, exact=exact, donate=donate,
        guard=guard)
    self.hits: Dict[str, np.ndarray] = {
        name: np.zeros((4,), np.int64) for name in tplan.tier_specs}
    self.steps = 0
    self.bad_steps = 0
    self.oov_totals: Dict[str, int] = {}
    self.dedup_overflow_totals: Dict[str, int] = {}

  # ---- metrics -----------------------------------------------------------
  def account_tier(self, tier: Dict[str, jax.Array]) -> None:
    """Accumulate one step's per-class hit counters and enforce the
    ``missed > 0`` prefetch contract. Split out of :meth:`_account` so a
    wrapping trainer (``resilience.ResilientTrainer(tiered=...)``) can
    own the guard accounting while the tier bookkeeping stays here."""
    reg = self.telemetry
    for name, m in tier.items():
      m = np.asarray(m, np.int64)
      self.hits[name] += m
      reg.counter(f"tiered/hits_hot/{name}").inc(int(m[0]))
      reg.counter(f"tiered/hits_staged/{name}").inc(int(m[1]))
      reg.counter(f"tiered/lookups/{name}").inc(int(m[3]))
      if m[2]:
        raise RuntimeError(
            f"class {name}: {int(m[2])} of {int(m[3])} lookups hit neither "
            "the hot cache nor the staging buffer this step — their "
            "updates were dropped at the sentinel. The prefetch contract "
            "is broken (classify ran against a stale resident map?).")

  def _account(self, metrics: Dict[str, jax.Array]) -> None:
    # guarded steps nest the tier counters under 'tier' and add the
    # guard verdict + OOV counters (make_tiered_train_step(guard=True))
    self.account_tier(metrics["tier"] if self.guard else metrics)
    if self.guard:
      self.bad_steps += int(np.asarray(metrics["bad_step"]))
      # account FIRST, enforce second (ResilientTrainer convention): the
      # oov='error' raise below must leave the totals covering the
      # rejected batch — which committed nothing, its gate held
      counts = {name: int(np.asarray(v))
                for name, v in metrics["oov"].items()}
      for name, n in counts.items():
        self.oov_totals[name] = self.oov_totals.get(name, 0) + n
      # dedup_capacity plans ride their overflow counter here too — the
      # counter existing is what makes the smaller cap legal at all
      for name, v in metrics.get("dedup_overflow", {}).items():
        n = int(np.asarray(v))
        if n:
          self.dedup_overflow_totals[name] = \
              self.dedup_overflow_totals.get(name, 0) + n
      from ..resilience import guards as _guards
      _guards.check_oov(self.tplan.plan, counts,
                        where="guarded tiered step")
    self.steps += 1

  def hit_rate(self, name: Optional[str] = None) -> float:
    """Hot-tier hit rate (cache hits / valid lookups), cumulative; over
    all tiered classes when ``name`` is None."""
    ms = [self.hits[name]] if name else list(self.hits.values())
    total = sum(int(m[3]) for m in ms)
    return sum(int(m[0]) for m in ms) / total if total else 0.0

  def metrics_summary(self) -> Dict[str, Any]:
    out = {
        "steps": self.steps,
        "hit_rate": self.hit_rate(),
        "per_class": {
            name: {"hot": int(m[0]), "staged": int(m[1]),
                   "missed": int(m[2]), "total": int(m[3]),
                   "hit_rate": int(m[0]) / int(m[3]) if m[3] else 0.0}
            for name, m in self.hits.items()},
        "host_gather_bytes": self.prefetcher.total_host_gather_bytes,
        "spill_steps": self.prefetcher.spill_steps,
        "host_gather_retries": self.prefetcher.host_gather_retries,
    }
    if self.guard:
      out["bad_steps"] = self.bad_steps
      out["oov"] = dict(self.oov_totals)
      if self.dedup_overflow_totals:
        out["dedup_overflow"] = dict(self.dedup_overflow_totals)
    return out

  # ---- stepping ----------------------------------------------------------
  def _device_batch(self, numerical, cats, labels):
    return shard_batch((jnp.asarray(numerical), [jnp.asarray(c) for c in cats],
                        jnp.asarray(labels)), self.mesh, self.axis_name)

  def _dispatch(self, staged, numerical, cats, labels):
    # the device window rides its own trace track, from dispatch (jax
    # returns immediately — dispatch is asynchronous) to the first host
    # sync (_finish's write-back fetch), so the look-ahead classify on
    # the main-thread track is VISIBLY inside it in trace.json
    self._dev_span = _span("device/step", track="device").start()
    with _span("tiered/dispatch"):
      batch = self._device_batch(numerical, cats, labels)
      self.state, staged_out, metrics, loss = self._step_fn(
          self.state, staged.device, *batch)
    return staged_out, metrics, loss

  def _finish(self, staged, staged_out, metrics, account=None):
    """The post-dispatch protocol tail: write-back, accounting, re-rank
    — in that order (the accounting may raise, e.g. oov='error', and
    must do so with the write-back landed but before the re-rank).
    ``account`` overrides the accounting stage so a wrapping trainer
    (``resilience.ResilientTrainer(tiered=...)``) can own the guard
    bookkeeping without duplicating this sequence."""
    self.prefetcher.write_back(staged, staged_out)  # syncs on the device
    self._dev_span.finish()  # dispatch -> post-write-back sync window
    (account or self._account)(metrics)
    self.state["fused"] = self.prefetcher.maybe_rerank(self.state["fused"])

  def step(self, numerical, cats, labels) -> float:
    """One synchronous train step on a GLOBAL host batch."""
    staged = self.prefetcher.prepare(cats)
    staged_out, metrics, loss = self._dispatch(staged, numerical, cats,
                                               labels)
    self._finish(staged, staged_out, metrics)
    return float(loss)

  def run(self, batches: Iterable) -> list:
    """Train over ``batches`` of ``(numerical, cats, labels)`` with the
    classify stage prefetched one batch ahead of the device step.

    With ``overlap_host=True`` the WHOLE host pass for batch k+1
    (classify + cold-row gather) runs on the pipeline worker while step
    k executes on device, with write-back conflicts repaired afterward
    — bit-exact with this serial loop (see
    ``pipeline.run_tiered_overlapped``)."""
    if self.overlap_host:
      from ..pipeline import run_tiered_overlapped
      return run_tiered_overlapped(self, batches)
    losses = []
    it = iter(batches)
    nxt = next(it, None)
    cold = None
    interval = self.tplan.config.rerank_interval
    while nxt is not None:
      numerical, cats, labels = nxt
      if cold is None:
        cold = self.prefetcher.classify(cats)
      staged = self.prefetcher.stage(cold)
      staged_out, metrics, loss = self._dispatch(staged, numerical, cats,
                                                 labels)
      nxt = next(it, None)
      # look-ahead classify overlaps the device step — except when this
      # step re-ranks (the classification must see the new resident map)
      will_rerank = bool(interval) and (
          self.prefetcher.steps_since_rerank + 1 >= interval)
      cold = (self.prefetcher.classify(nxt[1])
              if nxt is not None and not will_rerank else None)
      self._finish(staged, staged_out, metrics)
      losses.append(float(loss))
    return losses

  # ---- reconciliation ----------------------------------------------------
  def flush(self) -> None:
    """Reconcile resident rows' device values into the host images (call
    before checkpointing or reading a global weight view)."""
    self.store.flush(self.state["fused"])
