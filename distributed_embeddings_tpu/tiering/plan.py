"""Tiering plan: size each host-tier class's hot cache + staging buffer.

The :class:`DistEmbeddingStrategy` decides WHICH classes are host-tier
(``host_row_threshold``); this module decides their device-side geometry:

- ``cache_grps``: resident hot physical rows per rank — sized against the
  per-device ``hbm_budget_bytes`` after the fully-device-resident classes,
  the staging regions and the resident maps are accounted for (budget
  shares proportional to each class's cold-store size), or as a plain
  fraction of the class when no budget is given;
- ``staging_grps``: the persistent per-step staging region for the
  batch's cold rows. A batch can stage at most ``staging_grps`` distinct
  cold physical rows before the spill path kicks in
  (`prefetch.TieredPrefetcher`), so size it near the expected per-rank
  deduped cold row count (~ global batch x hotness x miss rate).

Invariant enforced here: ``(cache_grps + staging_max) * rows_per_phys <=
logical rows`` — the compact buffer must actually be smaller than the
vocabulary (otherwise tiering is pointless) AND translated compact ids
must stay below the routing sentinel so the engine's sentinel/mean-count
comparisons (`lookup_engine._combine`) hold unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from ..layers.planner import DistEmbeddingStrategy
from ..ops.packed_table import PackedLayout, SparseRule
from ..parallel.lookup_engine import (
    TierSpec,
    class_param_name,
    padded_rows,
)


@dataclasses.dataclass(frozen=True)
class TieringConfig:
  """Knobs for the tiered storage subsystem.

  Attributes:
    hbm_budget_bytes: per-device HBM budget covering ALL embedding-class
      buffers (device-tier classes + host-tier caches/staging/maps).
      None = no budget; caches are sized by ``cache_fraction``.
    cache_fraction: resident fraction of each host-tier class's physical
      rows when no budget is given.
    staging_grps: persistent staging physical rows per class per rank.
    rerank_interval: steps between resident-set re-rankings by observed
      counts (promotion/eviction); 0 disables.
    spill_factor_max: staging may grow in ``staging_grps * 2^k`` buckets
      up to this factor when a batch's deduped cold rows overflow the
      base region (growth retraces the step, so the factor bounds
      compile-cache churn); demand past ``staging_grps *
      spill_factor_max`` pads to exactly the deduped count instead of a
      bucket — updates are never dropped.
  """

  hbm_budget_bytes: Optional[int] = None
  cache_fraction: float = 0.25
  staging_grps: int = 1024
  rerank_interval: int = 0
  spill_factor_max: int = 16


@dataclasses.dataclass(frozen=True)
class TieredClassPlan:
  """Resolved geometry of one host-tier class."""

  key: tuple
  name: str
  spec: TierSpec
  layout_logical: PackedLayout  # full vocabulary (host image shape)
  layout_compact: PackedLayout  # cache + staging (device buffer shape)
  spill_cap_grps: int           # hard max staged rows for one step


class TieringPlan:
  """Per-class :class:`TierSpec` geometry + capacity accounting."""

  def __init__(self, plan: DistEmbeddingStrategy, rule: SparseRule,
               config: TieringConfig = TieringConfig()):
    host_keys = plan.host_tier_class_keys()
    if not host_keys:
      raise ValueError(
          "plan has no host-tier classes: set host_row_threshold on the "
          "DistEmbeddingStrategy (tables above it are host-offloaded)")
    if config.staging_grps < 1:
      raise ValueError(f"staging_grps must be >= 1, got "
                       f"{config.staging_grps}")
    self.plan = plan
    self.rule = rule
    self.config = config

    logical: Dict[tuple, PackedLayout] = {}
    for key in host_keys:
      cp = plan.classes[key]
      logical[key] = PackedLayout(rows=padded_rows(plan, key),
                                  width=cp.width, n_aux=rule.n_aux)

    # ---- cache sizing ----------------------------------------------------
    cache_of: Dict[tuple, int] = {}
    if config.hbm_budget_bytes is not None:
      report = plan.tier_capacity_report(rule.n_aux)
      fixed = report["device_bytes_per_rank"]
      for key in host_keys:
        lay = logical[key]
        staging = min(config.staging_grps, lay.phys_rows - 1)
        # staging rows + the int32 resident map (4 B per physical row)
        fixed += (staging * lay.phys_width + lay.phys_rows) * 4
      avail = config.hbm_budget_bytes - fixed
      if avail <= 0:
        raise ValueError(
            f"hbm_budget_bytes={config.hbm_budget_bytes:,} leaves no room "
            f"for hot caches: device-tier classes + staging regions + "
            f"resident maps already need {fixed:,} bytes/rank. Raise the "
            "budget, lower host_row_threshold (offload more classes), or "
            "shrink staging_grps.")
      total_w = sum(logical[k].phys_rows for k in host_keys)
      for key in host_keys:
        lay = logical[key]
        share = avail * lay.phys_rows // total_w
        cache_of[key] = max(1, share // (lay.phys_width * 4))
    else:
      for key in host_keys:
        cache_of[key] = max(1, int(logical[key].phys_rows
                                   * config.cache_fraction))

    # ---- per-class geometry ---------------------------------------------
    self.classes: Dict[tuple, TieredClassPlan] = {}
    for key in host_keys:
      lay = logical[key]
      name = class_param_name(*key)
      rpp = lay.rows_per_phys
      # compact ids must stay under the logical sentinel (see module doc):
      # cache + staging (incl. any spill growth) <= rows // rpp
      hard_cap = lay.rows // rpp
      staging = min(config.staging_grps, max(1, lay.phys_rows - 1))
      cache = min(cache_of[key], hard_cap - staging)
      if cache < 1:
        raise ValueError(
            f"class {name}: no room for a hot cache "
            f"(staging_grps={staging}, class has {lay.phys_rows:,} "
            "physical rows). Shrink staging_grps or raise the budget — "
            "or keep the class on device (raise host_row_threshold): "
            "tiering a class this small cannot shrink it.")
      spec = TierSpec(name=name, rows=lay.rows, rpp=rpp,
                      cache_grps=cache, staging_grps=staging)
      compact = PackedLayout(rows=spec.compact_rows, width=lay.width,
                             n_aux=rule.n_aux)
      if compact.phys_rows * compact.phys_width > 2 ** 31:
        raise ValueError(
            f"class {name}: compact buffer [{compact.phys_rows:,} x "
            f"{compact.phys_width}] exceeds XLA's 2^31-element indexing; "
            "shrink the cache (budget) or shard finer.")
      self.classes[key] = TieredClassPlan(
          key=key, name=name, spec=spec, layout_logical=lay,
          layout_compact=compact, spill_cap_grps=hard_cap - cache)

    self.tier_specs: Dict[str, TierSpec] = {
        c.name: c.spec for c in self.classes.values()}
    # fused_layouts() substitution: device buffers at compact size
    self.rows_overrides: Dict[str, int] = {
        c.name: c.spec.compact_rows for c in self.classes.values()}

  def geometry(self) -> Dict[str, Dict[str, int]]:
    """Per-class tier geometry as plain ints — the checkpoint manifest's
    ``tiering.classes`` section. A same-world restore validates its
    store's plan against the saved copy; an ELASTIC restore re-derives
    resident sets and staging geometry from the new plan instead (the
    cold images re-shard, the hot set is a cache policy, not state)."""
    return {c.name: {"cache_grps": c.spec.cache_grps,
                     "staging_grps": c.spec.staging_grps,
                     "phys_rows": c.layout_logical.phys_rows,
                     "phys_width": c.layout_logical.phys_width}
            for c in self.classes.values()}

  def by_name(self, name: str) -> TieredClassPlan:
    for c in self.classes.values():
      if c.name == name:
        return c
    raise KeyError(name)

  def device_bytes_per_rank(self) -> int:
    """HBM the tiered classes' device side occupies per rank (compact
    buffers + resident maps)."""
    total = 0
    for c in self.classes.values():
      total += c.layout_compact.phys_rows * c.layout_compact.phys_width * 4
      total += c.layout_logical.phys_rows * 4  # int32 resident map
    return total

  def host_bytes_per_rank(self) -> int:
    """Host RAM the cold stores occupy per rank (full packed images)."""
    return sum(
        c.layout_logical.phys_rows * c.layout_logical.phys_width * 4
        for c in self.classes.values())
