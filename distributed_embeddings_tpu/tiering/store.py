"""Host-RAM cold store for tiered classes + resident-set bookkeeping.

One :class:`HostTierStore` holds, per host-tier class and per rank:

- ``images``: the FULL packed class image ``[phys_rows, phys_width]`` in
  host memory — same physical layout as a device buffer (optimizer-state
  lanes interleaved), so tier moves are pure block copies
  (`ops/packed_table.host_gather_rows` / ``host_scatter_rows``);
- ``resident_map``: int32 ``[phys_rows]``, the physical row's hot-cache
  slot or -1 (host mirror of the device-side translation map);
- ``resident_grps``: int32 ``[cache_grps]``, the inverse map (slot ->
  physical row);
- ``counts``: int64 ``[phys_rows]`` observed lookup counts, the
  re-ranking signal.

Authority convention: rows resident in the device cache have their
authoritative values ON DEVICE (the image copy goes stale between
flushes); cold rows are authoritative in the image (the prefetcher writes
staged rows back every step). ``flush`` reconciles before checkpointing.

Rank-owner sharding (elastic pods): under multi-controller each process
constructs its store with ``owned_ranks`` = the mesh ranks its devices
hold, and materializes ONLY those ranks' images — the cold store's
BYTES shard across hosts exactly like the device buffers shard across
chips. The resident-set BOOKKEEPING (``resident_map`` /
``resident_grps`` / ``counts``) stays materialized for every rank on
every process: it is tiny (ints per physical row), it derives
deterministically from the globally-replicated batch stream, and the
prefetcher's classify must agree on every rank's hot/cold split across
processes for the staged device arrays to have one global shape.
Gather/scatter on an un-owned rank's IMAGE raises (it names the owner
contract); ``checkpoint.save`` writes per-owner ``cold_*_r<rank>.npy``
blocks and seals them through the DONE-marker protocol, and
``build_fused``/``resident_arrays`` assemble the global device arrays
via ``jax.make_array_from_callback`` so each process uploads only its
blocks. The single-controller default (``owned_ranks=None``) owns
every rank and behaves as before.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.packed_table import (
    host_gather_rows,
    host_scatter_rows,
    init_host_store,
)
from ..resilience import faultinject
from .plan import TieringPlan


def read_row_window(arr, lo: int, hi: int) -> np.ndarray:
  """Rows ``[lo, hi)`` of a device array, multi-controller safe.

  Global indexing of a non-fully-addressable array is an error, so the
  window assembles from this process's addressable shards instead — the
  rank-owner contract guarantees an owner's windows are local; asking
  for a peer's raises with the contract named. Fully-addressable arrays
  take the plain slice."""
  if isinstance(arr, jax.Array) and not arr.is_fully_addressable:
    from ..parallel.mesh import addressable_row_spans
    out = np.empty((hi - lo,) + tuple(arr.shape[1:]), arr.dtype)
    have = 0
    for s0, s1, shard in addressable_row_spans(arr):
      a, b = max(s0, lo), min(s1, hi)
      if a < b:
        out[a - lo:b - lo] = np.asarray(shard.data[a - s0:b - s0])
        have += b - a
    if have != hi - lo:
      raise RuntimeError(
          f"rows [{lo}, {hi}) are not fully addressable by this process "
          "— each rank's window must be read on its owner")
    return out
  return np.asarray(arr[lo:hi])


class HostTierStore:
  """Cold-store images + resident-set state for one :class:`TieringPlan`.

  ``dtype`` parametrizes the image element type: training stores are f32
  (the default — packed f32 lanes with interleaved optimizer state), and
  the serving subsystem reuses this class for its stripped inference
  images (f32 or int8 rows with bit-packed scale columns) by passing a
  serve-geometry plan duck-type plus the serve dtype."""

  def __init__(self, tplan: TieringPlan,
               owned_ranks: Optional[Iterable[int]] = None,
               dtype=np.float32):
    self.tplan = tplan
    self.plan = tplan.plan
    self.dtype = np.dtype(dtype)
    world = self.plan.world_size
    if owned_ranks is None:
      self.owned_ranks = tuple(range(world))
    else:
      self.owned_ranks = tuple(sorted(set(int(r) for r in owned_ranks)))
      if not self.owned_ranks:
        raise ValueError("owned_ranks must name at least one rank")
      if self.owned_ranks[0] < 0 or self.owned_ranks[-1] >= world:
        raise ValueError(
            f"owned_ranks {self.owned_ranks} outside [0, {world}) — the "
            "store shards by MESH rank, not process index")
    owned = frozenset(self.owned_ranks)
    self.images: Dict[str, List[Optional[np.ndarray]]] = {}
    self.resident_map: Dict[str, List[Optional[np.ndarray]]] = {}
    self.resident_grps: Dict[str, List[Optional[np.ndarray]]] = {}
    self.counts: Dict[str, List[Optional[np.ndarray]]] = {}
    for c in tplan.classes.values():
      lay = c.layout_logical
      # images shard by owner; the resident/count bookkeeping replicates
      # (every process must agree on every rank's hot/cold split)
      self.images[c.name] = [
          np.zeros((lay.phys_rows, lay.phys_width), self.dtype)
          if r in owned else None for r in range(world)]
      self.resident_map[c.name] = [
          np.full((lay.phys_rows,), -1, np.int32) for _ in range(world)]
      self.resident_grps[c.name] = [
          np.zeros((c.spec.cache_grps,), np.int32) for _ in range(world)]
      self.counts[c.name] = [
          np.zeros((lay.phys_rows,), np.int64) for _ in range(world)]
    self.warm_start()

  @property
  def owns_all(self) -> bool:
    return len(self.owned_ranks) == self.plan.world_size

  def _own(self, name: str, rank: int) -> int:
    """Validate that this store holds ``rank``'s block of ``name``."""
    rank = int(rank)
    if rank < 0 or rank >= self.plan.world_size \
        or self.images[name][rank] is None:
      raise ValueError(
          f"class {name!r} rank {rank} is not owned by this store "
          f"(owned_ranks={self.owned_ranks}): in a rank-owner-sharded "
          "cold store each process holds only its mesh ranks' blocks — "
          "route the access to the owning process (checkpoint.save / "
          "restore already do).")
    return rank

  # ---- initialization ----------------------------------------------------
  def _scale_rows(self, key, rank) -> np.ndarray:
    """Per-logical-row uniform-init scale for one rank's class block
    (numpy materialization of ``training.init_scale_spans``)."""
    from ..training import init_scale_spans

    lay = self.tplan.classes[key].layout_logical
    scale = np.zeros((lay.rows,), np.float32)
    for off, n, s in init_scale_spans(self.plan, key, rank):
      scale[off:off + n] = s
    return scale

  def init_uniform(self, seed: int = 0) -> None:
    """Draw every OWNED image in place (host RAM only; nothing touches a
    device). Deterministic in ``seed``/class/rank — a sharded store's
    processes draw disjoint ranks of the same global initialization."""
    for ki, (key, c) in enumerate(sorted(
        self.tplan.classes.items(), key=lambda kv: kv[1].name)):
      for rank in self.owned_ranks:
        rng = np.random.default_rng((seed, ki, rank))
        self.images[c.name][rank] = init_host_store(
            c.layout_logical, rng, self._scale_rows(key, rank),
            self.tplan.rule.aux_init)

  def set_image(self, name: str, rank: int, image: np.ndarray) -> None:
    """Install an explicit packed image (e.g. packed from a reference
    run's initial table, or a checkpoint block)."""
    rank = self._own(name, rank)
    lay = self.tplan.by_name(name).layout_logical
    if image.shape != (lay.phys_rows, lay.phys_width):
      raise ValueError(f"image shape {image.shape}, expected "
                       f"{(lay.phys_rows, lay.phys_width)}")
    self.images[name][rank] = np.asarray(image, self.dtype).copy()

  def warm_start(self, ranking: Optional[Dict[str, List[np.ndarray]]] = None
                 ) -> None:
    """Choose the initial resident set.

    ``ranking[name][rank]``: physical rows in descending priority (e.g.
    restored counts, or profiled hotness). Default: the lowest row ids —
    for the id-sorted-by-frequency vocabularies recommender pipelines
    emit (and the synthetic power-law streams), that IS the hot set; the
    periodic re-rank repairs any other distribution."""
    world = self.plan.world_size
    for name, maps in self.resident_map.items():
      cache = self.tplan.by_name(name).spec.cache_grps
      for rank in range(world):
        if ranking is not None and name in ranking:
          grps = np.asarray(ranking[name][rank][:cache], np.int32)
          if grps.shape[0] < cache:
            # fill the remaining slots with the lowest unranked rows
            rest = np.setdiff1d(
                np.arange(maps[rank].shape[0], dtype=np.int32), grps,
                assume_unique=False)[:cache - grps.shape[0]]
            grps = np.concatenate([grps, rest])
        else:
          grps = np.arange(cache, dtype=np.int32)
        maps[rank][:] = -1
        maps[rank][grps] = np.arange(cache, dtype=np.int32)
        self.resident_grps[name][rank] = grps.copy()

  # ---- bounds-checked image access ---------------------------------------
  def check_rows(self, name: str, rank: int, grps: np.ndarray) -> np.ndarray:
    """Validate physical-row indices against a class's host image.

    Every index the prefetch pipeline derives from BATCH DATA passes
    through here before it touches an image: a routing-arithmetic bug or
    a corrupt id stream must fail with the class named and the offending
    index shown, not as a bare numpy fancy-index ``IndexError`` three
    frames deep (or — worse, for negative indices — as a silent
    wrap-around read of the wrong rows). Pure bounds arithmetic against
    the class geometry: valid for ANY rank, owned or not (a sharded
    pod's classify checks every rank; only image access is
    owner-gated)."""
    grps = np.asarray(grps)
    if not grps.size:
      return grps
    c = self.tplan.by_name(name)
    lay = c.layout_logical
    lo, hi = int(grps.min()), int(grps.max())
    if lo < 0 or hi >= lay.phys_rows:
      bad = int(grps[(grps < 0) | (grps >= lay.phys_rows)][0])
      raise IndexError(
          f"class {name!r} rank {rank}: physical-row index {bad} is "
          f"outside this rank's host image [0, {lay.phys_rows}) "
          f"(= {lay.rows} logical vocab rows at {lay.rows_per_phys}/"
          "physical row). The ids came from the batch's routing "
          "arithmetic — this is a routing/classify bug or a corrupt id "
          "stream, not a capacity problem.")
    return grps

  def gather(self, name: str, rank: int, grps: np.ndarray) -> np.ndarray:
    """Bounds-checked cold-row gather from one rank's host image.

    The ``host_gather`` fault-injection site lives here (simulated
    transient read errors); the prefetcher wraps this call in
    retry/backoff, so a blip in host/NFS-backed storage costs
    milliseconds, not the run."""
    faultinject.fire("host_gather", clazz=name, rank=rank,
                     rows=int(np.asarray(grps).size))
    rank = self._own(name, rank)
    grps = self.check_rows(name, rank, grps)
    return host_gather_rows(self.tplan.by_name(name).layout_logical,
                            self.images[name][rank], grps)

  def scatter(self, name: str, rank: int, grps: np.ndarray,
              rows: np.ndarray) -> None:
    """Bounds-checked write-back into one rank's host image."""
    rank = self._own(name, rank)
    grps = self.check_rows(name, rank, grps)
    host_scatter_rows(self.tplan.by_name(name).layout_logical,
                      self.images[name][rank], grps, rows)

  # ---- device-state construction ----------------------------------------
  def _put(self, arr: np.ndarray, mesh, axis_name: str):
    if mesh is None:
      return jnp.asarray(arr)
    from jax.sharding import NamedSharding, PartitionSpec as P
    spec = P(axis_name) if arr.ndim == 1 else P(axis_name, None)
    return jax.device_put(arr, NamedSharding(mesh, spec))

  def _rank_block(self, name: str, rank: int) -> np.ndarray:
    """One rank's compact device block: cache rows gathered from the
    image at the resident set, staging region zeroed."""
    c = self.tplan.by_name(name)
    rank = self._own(name, rank)
    cache_rows = self.images[name][rank][self.resident_grps[name][rank]]
    return np.concatenate([
        cache_rows,
        np.zeros((c.spec.staging_grps, c.layout_logical.phys_width),
                 self.dtype)])

  def _global_or_callback(self, name: str, per_rank_rows: int, width,
                          block_of, mesh, axis_name: str):
    """Assemble a ``[world * per_rank_rows, ...]`` device array from
    per-rank host blocks. Fully-owned stores concatenate and device_put;
    a SHARDED store builds via ``jax.make_array_from_callback`` so each
    process materializes exactly its owned ranks' blocks (asking it for
    an un-owned block would raise — by construction the callback only
    runs for this process's addressable shards)."""
    world = self.plan.world_size
    if self.owns_all:
      blocks = [block_of(r) for r in range(world)]
      return self._put(np.concatenate(blocks), mesh, axis_name)
    if mesh is None:
      raise ValueError(
          "a rank-owner-sharded HostTierStore (owned_ranks="
          f"{self.owned_ranks}) needs the global mesh to build device "
          "arrays: without it this process would have to materialize "
          "ranks it does not own")
    from jax.sharding import NamedSharding, PartitionSpec as P
    shape = (world * per_rank_rows,) + ((width,) if width else ())
    spec = P(axis_name, None) if width else P(axis_name)
    sharding = NamedSharding(mesh, spec)

    def cb(index):
      rank = (index[0].start or 0) // per_rank_rows
      return block_of(rank)

    return jax.make_array_from_callback(shape, sharding, cb)

  def build_fused(self, mesh=None, axis_name: str = "mp"
                  ) -> Dict[str, jax.Array]:
    """Compact device buffers ``[world * (cache + staging), phys_width]``:
    cache rows gathered from the images at the resident set, staging
    region zeroed."""
    out = {}
    for c in self.tplan.classes.values():
      per = c.spec.cache_grps + c.spec.staging_grps
      out[c.name] = self._global_or_callback(
          c.name, per, c.layout_logical.phys_width,
          lambda r, name=c.name: self._rank_block(name, r),
          mesh, axis_name)
    return out

  def resident_arrays(self, mesh=None, axis_name: str = "mp"
                      ) -> Dict[str, jax.Array]:
    """Device translation maps ``[world * phys_rows]`` int32."""
    out = {}
    for c in self.tplan.classes.values():
      out[c.name] = self._global_or_callback(
          c.name, c.layout_logical.phys_rows, None,
          lambda r, name=c.name: self.resident_map[name][self._own(name, r)],
          mesh, axis_name)
    return out

  # ---- device -> host reconciliation -------------------------------------
  def _rank_cache_rows(self, fused: Dict[str, jax.Array], name: str,
                       rank: int) -> np.ndarray:
    spec = self.tplan.by_name(name).spec
    per = spec.cache_grps + spec.staging_grps
    return read_row_window(fused[name], rank * per,
                           rank * per + spec.cache_grps)

  def flush(self, fused: Dict[str, jax.Array]) -> None:
    """Copy every OWNED resident row's device value back into the host
    image (cold rows are already authoritative there) — call before
    checkpointing or unpacking a global view. A sharded store flushes
    its ranks only; every process flushing its own store covers the
    world."""
    for name in self.images:
      lay = self.tplan.by_name(name).layout_logical
      for rank in self.owned_ranks:
        host_scatter_rows(lay, self.images[name][rank],
                          self.resident_grps[name][rank],
                          self._rank_cache_rows(fused, name, rank))

  # ---- read-only reconciled views ---------------------------------------
  def snapshot_view(self, fused: Dict[str, jax.Array]
                    ) -> "TierStoreSnapshot":
    """Copy-on-snapshot view for async checkpointing: every OWNED image
    is COPIED with the resident rows' device values scattered into the
    copy — the same reconciliation ``flush`` applies to the live images,
    without mutating them. The device reads happen here, synchronously;
    the returned view is frozen host state a background writer can
    serialize while training (and the overlap worker) keep mutating this
    store. Cost: one image copy per owned (class, rank)."""
    return TierStoreSnapshot(self, fused)

  def overlay_reader(self, name: str, rank: int,
                     fused: Dict[str, jax.Array]):
    """Flush-free window reader over one rank's RECONCILED image:
    ``reader(p0, p1)`` returns a COPY of physical rows ``[p0, p1)`` with
    the resident rows' values overlaid from the device cache —
    byte-identical to flushing then slicing, with the live image left
    untouched (the overlap worker may be gathering cold rows from it
    concurrently, and the authority convention deliberately keeps
    resident rows' image copies stale between flushes). The device
    cache window is fetched once, lazily, on the first window that
    needs a resident row."""
    rank = self._own(name, rank)
    img = self.images[name][rank]
    grps = self.resident_grps[name][rank]
    lay = self.tplan.by_name(name).layout_logical
    cache: Dict[str, np.ndarray] = {}

    def read(p0: int, p1: int) -> np.ndarray:
      win = img[p0:p1].copy()
      sel = np.where((grps >= p0) & (grps < p1))[0]
      if sel.size:
        if "rows" not in cache:
          cache["rows"] = self._rank_cache_rows(fused, name, rank)
        # mirror host_scatter_rows' bounds discipline on the window
        self.check_rows(name, rank, grps[sel])
        win[grps[sel] - p0] = cache["rows"][sel]
      assert win.shape[1] == lay.phys_width
      return win

    return read


class TierStoreSnapshot:
  """Frozen, reconciled copy of a :class:`HostTierStore`'s checkpoint
  surface.

  Duck-types exactly what ``checkpoint.save``'s tier path reads —
  ``tplan``/``plan``, ``owned_ranks``/``owns_all``, ``images``,
  ``resident_grps``, ``resident_map``, ``counts`` — with ``flush`` a
  no-op because the resident rows were already scattered into the image
  COPIES at construction. This is what lets ``snapshot(async_=True)``
  coexist with a live mutable store: the writer thread serializes this
  view while the training loop keeps gathering/scattering the real one.
  """

  def __init__(self, store: HostTierStore, fused: Dict[str, jax.Array]):
    self.tplan = store.tplan
    self.plan = store.plan
    self.dtype = store.dtype
    self.owned_ranks = store.owned_ranks
    owned = frozenset(store.owned_ranks)
    self.images: Dict[str, List[Optional[np.ndarray]]] = {}
    self.resident_map: Dict[str, List[np.ndarray]] = {}
    self.resident_grps: Dict[str, List[np.ndarray]] = {}
    self.counts: Dict[str, List[np.ndarray]] = {}
    for name in store.images:
      lay = store.tplan.by_name(name).layout_logical
      imgs: List[Optional[np.ndarray]] = []
      for rank in range(store.plan.world_size):
        if rank not in owned:
          imgs.append(None)
          continue
        img = store.images[name][rank].copy()
        host_scatter_rows(lay, img, store.resident_grps[name][rank],
                          store._rank_cache_rows(fused, name, rank))
        imgs.append(img)
      self.images[name] = imgs
      self.resident_map[name] = [m.copy()
                                 for m in store.resident_map[name]]
      self.resident_grps[name] = [g.copy()
                                  for g in store.resident_grps[name]]
      self.counts[name] = [c.copy() for c in store.counts[name]]

  @property
  def owns_all(self) -> bool:
    return len(self.owned_ranks) == self.plan.world_size

  def flush(self, fused: Dict[str, jax.Array]) -> None:
    """No-op: the view was reconciled at construction time."""
