"""Tiered embedding storage: host-offloaded cold rows + device hot cache.

The paper's premise is embedding tables that exceed one accelerator's
memory; the reference answers only with more accelerators. Production ads
stacks (PAPERS.md: "Scalable Machine Learning Training Infrastructure for
Online Ads Recommendation") instead exploit the extreme skew of
recommender id traffic with a storage hierarchy. This subsystem adds that
hierarchy as a third placement tier:

- the planner marks classes of tables above ``host_row_threshold`` as
  host-tier (`layers/planner.py`);
- each host-tier class keeps its FULL packed image (table rows with
  interleaved optimizer-state lanes) in host RAM (:class:`HostTierStore`),
  while the device holds a compact buffer: a frequency-ranked hot cache
  plus a fixed staging region (:class:`TieringPlan` sizes both against an
  HBM budget);
- per step, a prefetcher dedups the batch's ids, classifies hot/cold,
  host-gathers the cold rows and uploads them into the staging region
  (:class:`TieredPrefetcher`); routed ids are translated to compact slots
  inside the jitted step (`parallel/lookup_engine.translate_tiered_ids`),
  so the fused gather and the one-scatter-add backward of
  ``make_sparse_train_step`` cover both tiers unchanged;
- after the step, updated staging rows are written back to the host
  image; periodically the resident set is re-ranked by observed counts
  (promotion/eviction);
- staging overflow spills deterministically into a power-of-two-bucketed
  larger staging upload (a second host gather) — updates are never
  dropped.
"""

from .plan import TieringConfig, TieringPlan
from .prefetch import TieredPrefetcher
from .store import HostTierStore
from .train import (
    TieredTrainer,
    init_tiered_state,
    init_tiered_state_from_params,
    unpack_tiered_state,
)

__all__ = [
    "TieringConfig",
    "TieringPlan",
    "TieredPrefetcher",
    "HostTierStore",
    "TieredTrainer",
    "init_tiered_state",
    "init_tiered_state_from_params",
    "unpack_tiered_state",
]
