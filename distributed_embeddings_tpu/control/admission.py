"""SLO-driven admission: deadline-class budgets become shed thresholds.

The :class:`~..serving.MicroBatcher` already sheds load when its queue
fills — but ``queue_rows`` is a static constructor argument, and the
right bound is a function of how fast the backend is moving RIGHT NOW.
:class:`ControlPolicy` closes the loop: operators declare latency
budgets per deadline class (``{"realtime": 0.010, "bulk": 0.100}`` —
p99 seconds), the policy watches the recent p99 through its own
:class:`~..telemetry.WindowedHistogram`, and each tick it moves the
batcher's admission bound through
:meth:`~..serving.MicroBatcher.set_admission`:

- **tighten** (geometrically, by ``step``) toward ``min_queue_rows``
  while recent p99 exceeds ``slack × budget`` — a shorter queue sheds
  sooner, which converts would-be deadline misses into counted, fast
  rejections the client can retry elsewhere (the "fail fast beats fail
  slow" admission doctrine);
- **relax** (same factor, inverted) toward the original bound while
  recent p99 sits under ``relax × budget`` — capacity that recovered
  is capacity re-admitted, gradually (the asymmetric band between
  ``relax`` and ``slack`` is the hysteresis: no flapping on a p99 that
  hovers at the budget);
- the **effective budget is the tightest class** — the batcher has one
  queue, so the strictest declared deadline governs it.

Like every control loop here, the decision is a pure function of the
observed p99 and the policy's state, and each tick logs one replayable
decision record.  Disabled (no budgets) the policy never calls
``set_admission`` — the batcher behaves exactly as shipped.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

from ..telemetry import WindowedHistogram
from .decisions import DecisionLog

__all__ = ["AdmissionConfig", "ControlPolicy"]


@dataclasses.dataclass(frozen=True)
class AdmissionConfig:
  """The admission controller's band.

  Attributes:
    slack: tighten while ``recent p99 > slack * budget`` (>= 1.0 means
      "only act on an actual breach"; the default 0.9 acts just before).
    relax: relax while ``recent p99 < relax * budget``; must sit below
      ``slack`` — the gap is the hysteresis dead-band.
    step: geometric step per tick (0.7 → each tighten cuts the bound to
      70%; each relax grows it by 1/0.7).  Geometric, not linear: the
      right bound can be an order of magnitude away, and a linear
      crawl would take the whole incident to get there.
    min_queue_rows: the tighten floor (never below the batcher's
      ``max_batch`` — :meth:`~..serving.MicroBatcher.set_admission`
      enforces that refusal; this floor should sit at or above it).
    min_samples: recent-window observation count below which the policy
      holds — a p99 of three requests is noise, not a signal.
    window_slots / window_rotate_s: the recent-latency window shape
      (see :class:`~..telemetry.WindowedHistogram`).
  """

  slack: float = 0.9
  relax: float = 0.5
  step: float = 0.7
  min_queue_rows: int = 1
  min_samples: int = 20
  window_slots: int = 6
  window_rotate_s: float = 1.0

  def __post_init__(self):
    if not 0.0 < self.relax < self.slack:
      raise ValueError(
          f"need 0 < relax ({self.relax}) < slack ({self.slack}) — the "
          "gap between them is the anti-flap dead-band")
    if not 0.0 < self.step < 1.0:
      raise ValueError(f"step must be in (0, 1), got {self.step}")
    if self.min_queue_rows < 1 or self.min_samples < 1:
      raise ValueError("min_queue_rows and min_samples must be >= 1")


class ControlPolicy:
  """Deadline-class budgets driving the batcher's shed threshold.

  Args:
    batcher: the :class:`~..serving.MicroBatcher` to govern (anything
      with ``queue_rows``/``max_batch`` attributes and a
      ``set_admission`` method).
    budgets: ``{class_name: p99_budget_seconds}``; the minimum governs.
      Empty: the policy is a no-op (every tick logs ``hold``/
      ``no_budgets`` and touches nothing).
    config: the band (:class:`AdmissionConfig`).
    decisions: shared :class:`~.decisions.DecisionLog`.
  """

  SOURCE = "admission"

  def __init__(self, batcher, budgets: Dict[str, float],
               config: AdmissionConfig = AdmissionConfig(),
               decisions: Optional[DecisionLog] = None):
    for name, b in dict(budgets).items():
      if not (b > 0.0 and math.isfinite(b)):
        raise ValueError(
            f"budget for class {name!r} must be a finite positive "
            f"seconds value, got {b!r}")
    self.batcher = batcher
    self.budgets = dict(budgets)
    self.config = config
    self.decisions = decisions if decisions is not None else DecisionLog()
    self._window = WindowedHistogram(
        "control/admission_latency_s", slots=config.window_slots,
        rotate_every_s=config.window_rotate_s)
    # the relax ceiling is wherever the operator started the batcher —
    # the policy borrows admission during pressure, it never grants
    # more than the deployment configured
    self._baseline_rows = int(batcher.queue_rows)
    self._tick = 0

  @property
  def effective_budget_s(self) -> Optional[float]:
    """The tightest declared class budget (``None``: no budgets)."""
    return min(self.budgets.values()) if self.budgets else None

  def observe_latency(self, seconds: float, now: Optional[float] = None) \
      -> None:
    """Feed one served request's latency (``future.latency_s``) into
    the recent window; ``now`` (telemetry-clock seconds) drives slot
    rotation when given."""
    if now is not None:
      self._window.maybe_rotate(now)
    self._window.observe(seconds)

  # ---- the pure part ------------------------------------------------------
  def decide(self, p99_s: float, samples: int, tick: int,
             current_rows: int) -> Dict[str, Any]:
    """One tick's tighten/relax/hold choice given the recent p99 —
    pure, so the logged decisions replay."""
    cfg = self.config
    budget = self.effective_budget_s
    inputs = {"p99_s": None if math.isnan(p99_s) else p99_s,
              "samples": int(samples), "queue_rows": int(current_rows),
              "budget_s": budget}
    if budget is None:
      return self.decisions.record(
          self.SOURCE, tick, "hold", "no_budgets", inputs=inputs,
          target_rows=current_rows)
    if samples < cfg.min_samples or math.isnan(p99_s):
      return self.decisions.record(
          self.SOURCE, tick, "hold", "insufficient_samples", inputs=inputs,
          target_rows=current_rows)
    floor = max(cfg.min_queue_rows, int(self.batcher.max_batch))
    if p99_s > cfg.slack * budget:
      target = max(floor, int(math.floor(current_rows * cfg.step)))
      if target < current_rows:
        return self.decisions.record(
            self.SOURCE, tick, "tighten", "p99_over_budget", inputs=inputs,
            target_rows=target)
      return self.decisions.record(
          self.SOURCE, tick, "hold", "at_floor", inputs=inputs,
          target_rows=current_rows)
    if p99_s < cfg.relax * budget:
      target = min(self._baseline_rows,
                   int(math.ceil(current_rows / cfg.step)))
      if target > current_rows:
        return self.decisions.record(
            self.SOURCE, tick, "relax", "p99_under_budget", inputs=inputs,
            target_rows=target)
      return self.decisions.record(
          self.SOURCE, tick, "hold", "at_baseline", inputs=inputs,
          target_rows=current_rows)
    return self.decisions.record(
        self.SOURCE, tick, "hold", "in_band", inputs=inputs,
        target_rows=current_rows)

  # ---- decide + actuate ---------------------------------------------------
  def tick(self, now: Optional[float] = None) -> Dict[str, Any]:
    """One control cycle: read the recent window, decide, and apply the
    new bound through ``set_admission`` when the decision moves it."""
    self._tick += 1
    if now is not None:
      self._window.maybe_rotate(now)
    view = self._window.view()
    p99 = view.percentile(99.0) if view.count else math.nan
    rec = self.decide(p99, view.count, self._tick,
                      int(self.batcher.queue_rows))
    if rec["action"] in ("tighten", "relax"):
      self.batcher.set_admission(queue_rows=rec["target_rows"])
    return rec
