"""QPS/staleness-driven replica scaling with hysteresis and cooldown.

The serve-side twin of the trainer's elastic resize: the fleet's
replica count becomes a CONTROLLED variable.  :class:`FleetAutoscaler`
watches per-tick :class:`~.signals.ControlSnapshot` readings and moves
the replica count when the load per replica leaves its band:

- **scale up** when recent QPS per replica exceeds
  ``qps_high_per_replica`` (or serve staleness exceeds
  ``staleness_high_s`` — a fleet that cannot keep up with its delta
  chain is capacity-starved) for ``up_after`` CONSECUTIVE ticks;
- **scale down** when QPS per replica has been below
  ``qps_low_per_replica`` for ``down_after`` consecutive ticks — the
  longer streak on the way down is deliberate asymmetry: under-capacity
  costs users latency, over-capacity costs only machines;
- **never flap**: after any scaling action the loop holds for
  ``cooldown_ticks`` regardless of the signals (a resize changes the
  very signals being watched — deciding on mid-transition readings is
  how oscillation starts), and the consecutive-streak requirement means
  a single noisy tick moves nothing.

The decision function is deterministic: given the same snapshot
sequence and config, the same decisions come out (pinned in
tests/test_control.py).  Actuation is a callback — the deployment
supplies "spawn owners + :meth:`~..fleet.FleetRouter.apply_fleet`" (or
``fleet.reshard`` for a rank re-cut); the decision logic never imports
the machinery it drives, so it unit-tests without a fleet.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Dict, Optional

from .decisions import DecisionLog
from .signals import ControlSnapshot

__all__ = ["AutoscalerConfig", "FleetAutoscaler"]


@dataclasses.dataclass(frozen=True)
class AutoscalerConfig:
  """The scaling band and its anti-flap guards.

  Attributes:
    qps_high_per_replica: recent QPS per replica above which the fleet
      is under-provisioned.
    qps_low_per_replica: recent QPS per replica below which it is
      over-provisioned (must sit well under ``high`` after a downsize:
      the no-flap test checks ``high * (r-1)/r > low`` for adjacent
      sizes, or a downsize immediately re-triggers an upsize).
    staleness_high_s: serve staleness above which the fleet scales up
      regardless of QPS (``inf`` disables the staleness trigger).
    min_replicas / max_replicas: the hard bounds.
    up_after / down_after: consecutive breached ticks required before
      acting (hysteresis; down is slower than up on purpose).
    cooldown_ticks: ticks to hold after ANY action.
  """

  qps_high_per_replica: float
  qps_low_per_replica: float
  staleness_high_s: float = math.inf
  min_replicas: int = 1
  max_replicas: int = 4
  up_after: int = 2
  down_after: int = 3
  cooldown_ticks: int = 3

  def __post_init__(self):
    if not 0.0 <= self.qps_low_per_replica < self.qps_high_per_replica:
      raise ValueError(
          f"need 0 <= qps_low ({self.qps_low_per_replica}) < qps_high "
          f"({self.qps_high_per_replica}) — an inverted band scales up "
          "and down on the same reading")
    if not 1 <= self.min_replicas <= self.max_replicas:
      raise ValueError(
          f"need 1 <= min_replicas ({self.min_replicas}) <= "
          f"max_replicas ({self.max_replicas})")
    if self.up_after < 1 or self.down_after < 1 or self.cooldown_ticks < 0:
      raise ValueError("up_after/down_after must be >= 1 and "
                       "cooldown_ticks >= 0")


class FleetAutoscaler:
  """The replica-scaling decision loop.

  Args:
    config: the band (:class:`AutoscalerConfig`).
    actuate: ``actuate(target_replicas, decision_record)`` — performs
      the resize (owner spawn/drain + ``apply_fleet``, or a full
      ``fleet.reshard``); called only for scale actions, AFTER the
      decision is logged.  An actuation that raises logs a follow-up
      ``actuate_failed`` record and re-raises — the log never silently
      claims a resize that did not happen.
    decisions: the shared :class:`~.decisions.DecisionLog` (one stream
      for the whole control plane; default: a fresh in-memory log).
  """

  SOURCE = "autoscaler"

  def __init__(self, config: AutoscalerConfig,
               actuate: Optional[Callable[[int, Dict[str, Any]], None]]
               = None,
               decisions: Optional[DecisionLog] = None):
    self.config = config
    self.actuate = actuate
    self.decisions = decisions if decisions is not None else DecisionLog()
    self._high_streak = 0
    self._low_streak = 0
    self._cooldown = 0

  # ---- the pure part ------------------------------------------------------
  def decide(self, snap: ControlSnapshot) -> Dict[str, Any]:
    """One tick's decision (state update + logged record, no
    actuation).  Deterministic: same snapshot sequence in, same
    decision sequence out."""
    cfg = self.config
    r = max(1, int(snap.replicas))
    per_replica = snap.qps / r
    stale = snap.staleness_s > cfg.staleness_high_s
    high = per_replica > cfg.qps_high_per_replica or stale
    low = per_replica < cfg.qps_low_per_replica and not stale

    # streaks advance even through cooldown — a breach that persists
    # ACROSS the cooldown window acts on its first eligible tick
    self._high_streak = self._high_streak + 1 if high else 0
    self._low_streak = self._low_streak + 1 if low else 0

    action, target, reason = "hold", r, "in_band"
    if self._cooldown > 0:
      self._cooldown -= 1
      reason = "cooldown"
    elif self._high_streak >= cfg.up_after and r < cfg.max_replicas:
      action, target = "scale_up", r + 1
      reason = "staleness_high" if stale and per_replica \
          <= cfg.qps_high_per_replica else "qps_high"
    elif self._high_streak >= cfg.up_after:
      reason = "at_max_replicas"
    elif self._low_streak >= cfg.down_after and r > cfg.min_replicas:
      action, target, reason = "scale_down", r - 1, "qps_low"
    elif self._low_streak >= cfg.down_after:
      reason = "at_min_replicas"
    if action != "hold":
      self._cooldown = cfg.cooldown_ticks
      self._high_streak = self._low_streak = 0
    return self.decisions.record(
        self.SOURCE, snap.tick, action, reason,
        inputs=snap.to_inputs(), target_replicas=target,
        qps_per_replica=per_replica,
        high_streak=self._high_streak, low_streak=self._low_streak)

  # ---- decide + actuate ---------------------------------------------------
  def tick(self, snap: ControlSnapshot) -> Dict[str, Any]:
    rec = self.decide(snap)
    if rec["action"] in ("scale_up", "scale_down") \
        and self.actuate is not None:
      try:
        self.actuate(rec["target_replicas"], rec)
      except BaseException as e:  # noqa: BLE001 — logged, then re-raised
        self.decisions.record(
            self.SOURCE, snap.tick, "actuate_failed", repr(e),
            inputs={"target_replicas": rec["target_replicas"]})
        raise
    return rec
