"""The autonomous serving control plane: observe, decide, actuate, log.

Everything under :mod:`distributed_embeddings_tpu.control` is a CLOSED
LOOP over machinery the repo already has — no new data paths, no new
formats.  Four loops share one discipline:

- **hedged requests** live in the router itself
  (:class:`~..fleet.FleetConfig.hedge_quantile` — a slow gather past
  the per-owner recent latency quantile is duplicated to a second
  replica, first answer wins); this package supplies the windows and
  the accounting conventions it uses;
- :class:`FleetAutoscaler` moves the replica count when QPS per
  replica or serve staleness leaves its band — hysteresis + cooldown,
  actuating through ``apply_fleet``/``fleet.reshard``;
- :class:`CompactorDaemon` schedules delta-chain folds: lag-aware
  ``through_seq`` (never past the slowest live subscriber), priority-
  aware fold order (hot classes first);
- :class:`ControlPolicy` converts deadline-class latency budgets into
  the batcher's shed threshold via ``set_admission``.

The shared discipline: every decision is a pure function of an explicit
inputs snapshot, every decision is logged to the replayable
``control/decisions`` stream (:class:`DecisionLog`), nothing in the
decision paths reads a wall clock (callers pass ``now``), and a
DISABLED loop is a true no-op — the governed components behave
byte-for-byte as they did before this package existed.

Actuation boundary (graftlint GL117): the fleet/chain mutation surfaces
(``reshard``, ``apply_fleet``, ``set_fleet``, ``compact_once``,
``gc_deltas``, ``compact_chain``) are reachable only from this package,
the owning packages' internals, and operator tools — serving/request
code cannot resize a fleet as a side effect.
"""

from __future__ import annotations

from .admission import AdmissionConfig, ControlPolicy
from .autoscaler import AutoscalerConfig, FleetAutoscaler
from .compactor import CompactorConfig, CompactorDaemon
from .decisions import DecisionLog, decision_key, replay_decisions
from .signals import ControlSnapshot, CounterRate

__all__ = [
    "AdmissionConfig",
    "AutoscalerConfig",
    "CompactorConfig",
    "CompactorDaemon",
    "ControlPolicy",
    "ControlSnapshot",
    "CounterRate",
    "DecisionLog",
    "FleetAutoscaler",
    "decision_key",
    "replay_decisions",
]
