"""Control-plane inputs: recent-window readings over existing signals.

The control loops decide on what the system already exports — fleet
counter roll-ups, ``serve/latency_s`` histograms, ``/healthz``-style
staleness gauges — but every decision needs the RECENT value, not the
lifetime-cumulative one.  This module is the small adapter layer:

- :class:`CounterRate` turns a monotone cumulative counter into a
  per-interval rate (the QPS signal: successive samples of
  ``serve/submitted`` over the tick interval);
- :class:`ControlSnapshot` is the frozen per-tick reading every loop's
  ``decide`` consumes — and, verbatim, the ``inputs`` field of the
  decision it logs, which is what makes the log replayable: the
  snapshot IS everything the decision saw.

Clock discipline: nothing here reads a clock.  Callers pass ``now`` (a
seconds reading from the telemetry clock) into :meth:`CounterRate.sample`
and stamp snapshots with their own tick counter — control stays a
deterministic function of its inputs, and GL113 stays true without
suppressions.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

__all__ = ["ControlSnapshot", "CounterRate"]


@dataclasses.dataclass(frozen=True)
class ControlSnapshot:
  """One control tick's reading of the world.

  Attributes:
    tick: the control loop's monotone tick counter (its logical clock).
    qps: recent offered request rate (a :class:`CounterRate` sample of
      the batcher's ``serve/submitted``).
    p99_s / p999_s: RECENT latency quantiles (a
      :class:`~..telemetry.WindowedHistogram` view, not the lifetime
      histogram).
    staleness_s: the serve tier's freshness lag (the ``/healthz``
      most-stale promote reading, or ``stream/freshness_s``).
    replicas: the fleet's current replica count for the hot rank set.
    pending_rows: the batcher's queued row count (queue pressure).
  """

  tick: int
  qps: float = 0.0
  p99_s: float = math.nan
  p999_s: float = math.nan
  staleness_s: float = 0.0
  replicas: int = 1
  pending_rows: int = 0

  def to_inputs(self) -> Dict[str, Any]:
    """The snapshot as a decision record's ``inputs`` dict (NaNs to
    None: the log is JSON, and ``NaN`` is not)."""
    out = {}
    for f in dataclasses.fields(self):
      v = getattr(self, f.name)
      if isinstance(v, float) and math.isnan(v):
        v = None
      out[f.name] = v
    return out


class CounterRate:
  """Per-interval rate from a monotone cumulative counter.

  ``sample(value, now)`` returns the rate over the elapsed interval
  since the previous sample (0.0 on the first sample, or when no time
  has passed — a rate needs an interval).  The caller supplies both the
  counter reading and the clock reading, so the sampler itself is a
  pure difference engine — replayable and clock-free."""

  __slots__ = ("_last_value", "_last_now")

  def __init__(self):
    self._last_value: Optional[float] = None
    self._last_now: Optional[float] = None

  def sample(self, value: float, now: float) -> float:
    value, now = float(value), float(now)
    last_v, last_t = self._last_value, self._last_now
    self._last_value, self._last_now = value, now
    if last_v is None or now <= last_t:
      return 0.0
    return max(0.0, value - last_v) / (now - last_t)
