"""The control plane's decision record: structured, durable, replayable.

Every loop in :mod:`~distributed_embeddings_tpu.control` — the
autoscaler, the compactor daemon, the admission policy — emits one
record per tick through :class:`DecisionLog`: what it saw (``inputs``),
what it did (``action``), and why (``reason``).  Three consumers:

- **operations**: the ``control/decisions`` JSONL stream (the
  :class:`~..telemetry.JsonlWriter` fsync-per-line protocol) is the
  audit trail "why did the fleet shrink at 03:12" reads — each line is
  self-contained;
- **determinism**: a decision is a pure function of its ``inputs`` plus
  the loop's declared config, so replaying the logged inputs through a
  fresh loop instance must reproduce the logged actions exactly —
  :func:`replay_decisions` + the pinned tests in tests/test_control.py
  are that contract (the wall stamp is the ONE non-deterministic field,
  and it is excluded from the comparison by construction);
- **verdicts**: the in-memory mirror (:attr:`DecisionLog.records`)
  feeds the bench tools' ``emit_verdict`` sections without re-reading
  the file.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

from ..telemetry import JsonlWriter, get_registry as _registry

__all__ = ["DecisionLog", "decision_key", "replay_decisions"]

# the deterministic identity of a decision: every field EXCEPT the wall
# stamp and the log sequence — what replay compares
_NONDETERMINISTIC_FIELDS = ("wall", "log_seq")


def decision_key(record: Dict[str, Any]) -> Dict[str, Any]:
  """The record minus its non-deterministic fields (wall stamp, log
  sequence) — the value two replays of the same inputs must agree on."""
  return {k: v for k, v in record.items()
          if k not in _NONDETERMINISTIC_FIELDS}


class DecisionLog:
  """Append-only decision stream: JSONL on disk, mirrored in memory.

  Args:
    path: the ``control/decisions`` JSONL file (rotated, fsync-per-line
      — a SIGKILLed control process keeps every decision it made).
      ``None``: in-memory only (unit tests, dry runs).
    telemetry: registry for the ``control/decisions`` counter (default
      process-wide).
  """

  def __init__(self, path: Optional[str] = None, telemetry=None):
    self._writer = JsonlWriter(path) if path else None
    self._lock = threading.Lock()
    self._records: List[Dict[str, Any]] = []
    self._seq = 0
    self.telemetry = telemetry if telemetry is not None else _registry()

  def record(self, source: str, tick: int, action: str, reason: str,
             inputs: Optional[Dict[str, Any]] = None,
             **detail) -> Dict[str, Any]:
    """Append one decision; returns the full record (with its stamp).

    ``inputs`` must be everything the decision read — the replay
    contract depends on the record being self-contained."""
    rec: Dict[str, Any] = {
        "source": source,
        "tick": int(tick),
        "action": action,
        "reason": reason,
        "inputs": dict(inputs or {}),
    }
    rec.update(detail)
    with self._lock:
      rec["log_seq"] = self._seq
      self._seq += 1
      rec["wall"] = time.time()
      self._records.append(rec)
      if self._writer is not None:
        self._writer.write(rec)
    self.telemetry.counter("control/decisions").inc()
    self.telemetry.counter(f"control/decisions/{source}").inc()
    return rec

  @property
  def records(self) -> List[Dict[str, Any]]:
    with self._lock:
      return list(self._records)

  def close(self) -> None:
    with self._lock:
      if self._writer is not None:
        self._writer.close()

  def __enter__(self) -> "DecisionLog":
    return self

  def __exit__(self, exc_type, exc, tb):
    self.close()
    return False


def replay_decisions(path: str) -> List[Dict[str, Any]]:
  """Read a decision log back (main file only — rotation archives are
  the operator's history, not the replay's)."""
  out = []
  with open(path) as f:
    for line in f:
      line = line.strip()
      if line:
        out.append(json.loads(line))
  return out
