"""Scheduled delta compaction: lag-aware, priority-aware, decision-logged.

PR 16 gave the chain a manual compactor
(:class:`~..streaming.DeltaCompactor`): an operator hand-picks
``through_seq`` and runs a fold.  :class:`CompactorDaemon` closes that
loop.  Each tick it reads the chain's observable state — the base
anchor, the contiguous published run, the live subscribers' fsynced
heartbeats — and decides:

- **lag-aware ``through_seq``**: never fold past the slowest LIVE
  subscriber's ``applied_seq`` floor.  Folding further is *correct*
  (the stranded subscriber would rebase onto the compacted base), but
  a rebase is a staleness spike the scheduler exists to avoid; expired
  heartbeats drop out of the floor (the publisher's quorum rule — a
  dead subscriber must not pin the chain forever);
- **fold only when worth it**: at least ``min_deltas`` foldable deltas
  (each fold rewrites every class image — folding per-delta would turn
  the compactor into the bottleneck it exists to remove);
- **priority-aware promotion**: the fold order feeds
  ``class_priority`` (hot classes first — typically the serve plan's
  hotness ranking), so a mid-fold kill leaves the freshest work on the
  classes that matter.

Every tick logs one decision (``fold`` / ``hold``) with the full chain
state as ``inputs`` — :meth:`decide` is a pure function of that state,
so the log replays (pinned in tests/test_control.py).  ``start()`` runs
the tick on a daemon thread at ``interval_s``; deployments that already
have a control loop call :meth:`tick` themselves.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any, Dict, Optional

from ..checkpoint import manifest_fingerprint, read_manifest
from ..streaming.compact import DeltaCompactor
from ..streaming.publish import (
    BASE_DIR,
    chain_anchor,
    published_delta_seqs,
    read_heartbeats,
)
from ..telemetry import get_registry as _registry
from .decisions import DecisionLog

__all__ = ["CompactorConfig", "CompactorDaemon"]


@dataclasses.dataclass(frozen=True)
class CompactorConfig:
  """The fold schedule's knobs.

  Attributes:
    interval_s: tick period of the daemon thread (:meth:`start`).
    min_deltas: smallest foldable backlog worth a fold.
    heartbeat_ttl_s: heartbeats older than this drop out of the lag
      floor (must match the publisher's quorum TTL).
  """

  interval_s: float = 30.0
  min_deltas: int = 4
  heartbeat_ttl_s: float = 30.0

  def __post_init__(self):
    if self.min_deltas < 1:
      raise ValueError(f"min_deltas must be >= 1, got {self.min_deltas}")


class CompactorDaemon:
  """The compaction scheduler over one publish directory."""

  SOURCE = "compactor"

  def __init__(self, path: str,
               config: CompactorConfig = CompactorConfig(),
               class_priority: Optional[Dict[str, float]] = None,
               decisions: Optional[DecisionLog] = None,
               telemetry=None):
    self.path = str(path)
    self.config = config
    self.class_priority = dict(class_priority or {})
    self.decisions = decisions if decisions is not None else DecisionLog()
    self.telemetry = telemetry if telemetry is not None else _registry()
    self._compactor = DeltaCompactor(
        self.path, heartbeat_ttl_s=config.heartbeat_ttl_s,
        telemetry=self.telemetry)
    self._tick = 0
    self._thread: Optional[threading.Thread] = None
    self._stop = threading.Event()

  # ---- observation --------------------------------------------------------
  def observe(self) -> Dict[str, Any]:
    """The chain's state as the decision's inputs: base anchor,
    contiguous published run end, and the live-subscriber lag floor
    (``None`` when no live subscriber is registered)."""
    base = os.path.join(self.path, BASE_DIR)
    if not os.path.isfile(os.path.join(base, "manifest.json")):
      return {"anchor_seq": None, "run_end": None, "live_floor": None,
              "live_subscribers": 0, "expired_subscribers": 0}
    bman = read_manifest(base)
    anchor_seq, _fp, _root = chain_anchor(bman, manifest_fingerprint(base))
    seqs = published_delta_seqs(self.path)
    run_end = anchor_seq
    while run_end + 1 in seqs:
      run_end += 1
    live, expired = read_heartbeats(self.path,
                                    self.config.heartbeat_ttl_s)
    floor = min((int(hb["applied_seq"]) for hb in live.values()),
                default=None) if live else None
    return {"anchor_seq": anchor_seq, "run_end": run_end,
            "live_floor": floor, "live_subscribers": len(live),
            "expired_subscribers": len(expired)}

  # ---- the pure part ------------------------------------------------------
  def decide(self, state: Dict[str, Any], tick: int) -> Dict[str, Any]:
    """Pure fold/hold decision over an :meth:`observe` state dict —
    deterministic, so the decision log replays against recorded
    inputs."""
    cfg = self.config
    if state["anchor_seq"] is None:
      return self.decisions.record(
          self.SOURCE, tick, "hold", "no_base", inputs=state,
          through_seq=None)
    k = int(state["run_end"])
    if state["live_floor"] is not None:
      # the lag-aware clamp: the slowest live subscriber's heartbeat is
      # the fold ceiling — nobody gets stranded behind the compaction
      # point while their heartbeat is current
      k = min(k, int(state["live_floor"]))
    foldable = k - int(state["anchor_seq"])
    if foldable < cfg.min_deltas:
      reason = "backlog_below_min" if int(state["run_end"]) \
          - int(state["anchor_seq"]) < cfg.min_deltas else "subscriber_lag"
      return self.decisions.record(
          self.SOURCE, tick, "hold", reason, inputs=state, through_seq=k)
    return self.decisions.record(
        self.SOURCE, tick, "fold", "backlog", inputs=state,
        through_seq=k, deltas=foldable,
        fold_priority=sorted(self.class_priority,
                             key=lambda n: (-self.class_priority[n], n)))

  # ---- decide + actuate ---------------------------------------------------
  def tick(self) -> Dict[str, Any]:
    """One scheduling cycle: observe, decide, and run the fold when the
    decision says so.  Returns the decision record (with the fold's
    summary attached in memory on success)."""
    self._tick += 1
    rec = self.decide(self.observe(), self._tick)
    if rec["action"] == "fold":
      try:
        result = self._compactor.compact_once(
            through_seq=rec["through_seq"], gc=True,
            class_priority=self.class_priority)
      except BaseException as e:  # noqa: BLE001 — logged, then re-raised
        self.decisions.record(
            self.SOURCE, self._tick, "fold_failed", repr(e),
            inputs={"through_seq": rec["through_seq"]})
        raise
      rec["result"] = result
    return rec

  # ---- the daemon ---------------------------------------------------------
  def start(self) -> "CompactorDaemon":
    if self._thread is not None:
      raise RuntimeError("CompactorDaemon already started")
    self._stop.clear()

    def loop():
      while not self._stop.wait(self.config.interval_s):
        try:
          self.tick()
        except Exception:  # noqa: BLE001 — the failure is in the log
          # a failed fold (torn chain, transient IO) must not kill the
          # scheduler: the fold_failed decision is recorded, the old
          # base is untouched (manifest-last), and the next tick retries
          continue

    self._thread = threading.Thread(target=loop, name="compactor-daemon",
                                    daemon=True)
    self._thread.start()
    return self

  def stop(self) -> None:
    self._stop.set()
    if self._thread is not None:
      self._thread.join(timeout=10.0)
      self._thread = None
