"""Test-time lock-order sanitizer: the runtime half of threadlint.

:mod:`..analysis.threadlint` derives a static lock-acquisition graph
from lexically nested ``with`` blocks and rejects cycles (GL121).  The
static view is conservative — it cannot see cross-function nesting
(e.g. ``FleetRouter.apply_fleet`` holding ``router.lock`` while the
store's methods take ``store._lock``) or orders that only materialize
under a particular interleaving.  This module closes that gap in
tests: wrap a subsystem's locks in :class:`LockOrderMonitor`
instruments, run the normal workload, and the monitor records every
ACTUAL held->acquired edge.  A same-run inversion (B-then-A observed
after A-then-B) raises :class:`LockOrderError` at acquisition time —
at the exact second acquisition, with both sites in the message — and
:meth:`LockOrderMonitor.assert_consistent_with` asserts the observed
edges merged with the static graph stay acyclic, so the runtime truth
and the checked-in model cannot drift apart silently.

Usage (see tests/test_micro_batch.py)::

    mon = LockOrderMonitor()
    b._lock = mon.wrap(b._lock, "MicroBatcher._lock")
    b._nonempty = mon.wrap(b._nonempty, "MicroBatcher._lock")
    ... run the workload ...
    mon.assert_consistent_with(threadlint.static_lock_edges())

A ``Condition`` and its underlying lock share one NAME (holding either
is holding both — the same canonicalization threadlint applies), so
the condition's internal re-acquire never self-reports.  The wrapper
delegates the full lock/condvar surface and is reentrancy-aware: a
re-acquire of an already-held name records no edge.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = ["InstrumentedLock", "LockOrderError", "LockOrderMonitor"]


class LockOrderError(AssertionError):
  """A lock-acquisition-order inversion (potential deadlock)."""


class _HeldState(threading.local):
  def __init__(self):
    self.stack: List[str] = []


class LockOrderMonitor:
  """Records held->acquired edges across every wrapped lock."""

  def __init__(self):
    self._meta = threading.Lock()
    # (held, acquired) -> first site description
    self._edges: Dict[Tuple[str, str], str] = {}
    self._held = _HeldState()

  def wrap(self, lock, name: str) -> "InstrumentedLock":
    """Wrap any lock-like object (Lock/RLock/Condition) under ``name``.
    Use threadlint's canonical token (``Class.attr``) so the runtime
    edges line up with the static graph."""
    return InstrumentedLock(self, lock, name)

  # -- recording ------------------------------------------------------------
  def _on_acquire(self, name: str) -> None:
    stack = self._held.stack
    if name in stack:
      stack.append(name)  # reentrant: no new edges
      return
    site = f"thread {threading.current_thread().name}"
    with self._meta:
      for held in set(stack):
        rev = self._edges.get((name, held))
        if rev is not None:
          raise LockOrderError(
              f"lock-order inversion: acquiring {name!r} while "
              f"holding {held!r} ({site}), but the opposite order "
              f"{name!r} -> {held!r} was already observed ({rev}) — "
              "two threads interleaving these paths can deadlock.")
        self._edges.setdefault((held, name), site)
    stack.append(name)

  def _on_release(self, name: str) -> None:
    stack = self._held.stack
    for i in range(len(stack) - 1, -1, -1):
      if stack[i] == name:
        del stack[i]
        return

  # -- inspection -----------------------------------------------------------
  def edges(self) -> Set[Tuple[str, str]]:
    with self._meta:
      return set(self._edges)

  def assert_consistent_with(
      self, static_edges: Iterable[Tuple[str, str]]) -> None:
    """The observed edges merged with threadlint's static graph must be
    acyclic; a cycle means the runtime order contradicts (or extends
    into a knot with) the checked-in model."""
    graph: Dict[str, Set[str]] = {}
    for a, b in list(static_edges) + sorted(self.edges()):
      graph.setdefault(a, set()).add(b)
      graph.setdefault(b, set())
    state: Dict[str, int] = {}  # 1=visiting, 2=done

    def visit(node: str, path: List[str]) -> Optional[List[str]]:
      state[node] = 1
      path.append(node)
      for nxt in sorted(graph[node]):
        if state.get(nxt) == 1:
          return path[path.index(nxt):] + [nxt]
        if state.get(nxt) != 2:
          cyc = visit(nxt, path)
          if cyc is not None:
            return cyc
      path.pop()
      state[node] = 2
      return None

    for node in sorted(graph):
      if state.get(node) is None:
        cyc = visit(node, [])
        if cyc is not None:
          raise LockOrderError(
              "observed lock order contradicts the static "
              f"acquisition graph: cycle {' -> '.join(cyc)} in the "
              "merged (static + runtime) graph.")


class InstrumentedLock:
  """Delegating wrapper recording acquisition order into a monitor.

  Covers the Lock, RLock and Condition surfaces; anything else
  (``locked``, ``wait``, ``wait_for``...) falls through to the wrapped
  object.  ``wait()`` releases and re-acquires the underlying lock
  internally without changing the held NAME set — correct, because the
  condvar shares its lock's name."""

  def __init__(self, monitor: LockOrderMonitor, lock, name: str):
    self._monitor = monitor
    self._lock = lock
    self._name = name

  def acquire(self, *args, **kwargs):
    got = self._lock.acquire(*args, **kwargs)
    if got:
      self._monitor._on_acquire(self._name)
    return got

  def release(self):
    self._monitor._on_release(self._name)
    return self._lock.release()

  def __enter__(self):
    got = self._lock.__enter__()
    self._monitor._on_acquire(self._name)
    return got

  def __exit__(self, *exc):
    self._monitor._on_release(self._name)
    return self._lock.__exit__(*exc)

  def __getattr__(self, attr):
    return getattr(self._lock, attr)

  def __repr__(self):
    return f"InstrumentedLock({self._name!r}, {self._lock!r})"
