"""Host/device span tracing: nestable spans -> Chrome trace-event JSON.

The trainers' per-step protocols are host-side pipelines (dynvocab
translate, tiered classify/stage/write-back/re-rank, device dispatch +
the block_until_ready boundary, snapshot save, batcher flush/complete)
whose whole value proposition is OVERLAP — the prefetcher classifying
batch k+1 while the device computes batch k, the batcher packing the
next dispatch while the completer drains the last.  This module makes
those claims visible instead of asserted: every stage runs under a
``span(...)`` and an enabled run writes ``trace.json``, viewable in
``chrome://tracing`` / Perfetto, with one track per real thread (the
batcher's flusher/completer workers, the async checkpoint writer) plus
named VIRTUAL tracks (``track="device"``) for windows that are not a
thread — the device-compute window between dispatch and the first host
sync.

Disabled mode is a true no-op and the default: :func:`span` returns one
process-wide ``_NullSpan`` singleton — no object, dict, or closure is
allocated per call (pinned by a tracemalloc test), nothing is timed, and
traced step code is never touched at all (spans live strictly on the
host side of the step boundary; the jaxpr fingerprints stay
byte-identical).

When enabled (:func:`install_tracer` / the :func:`tracing` context
manager), each span costs two ``perf_counter_ns`` reads and one
append to a thread-local buffer — no lock on the hot path.

This module is the sanctioned home of raw clock reads in the library
package: graftlint GL113 flags ``time.perf_counter``/``time.monotonic``
calls in library modules outside ``telemetry/``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "Tracer",
    "span",
    "tracing",
    "install_tracer",
    "uninstall_tracer",
    "current_tracer",
]

_tracer: Optional["Tracer"] = None


class _NullSpan:
  """The disabled-mode span: a process-wide singleton whose enter/exit
  do nothing.  ``start``/``finish`` support the cross-function window
  form (``span(...).start()`` ... ``.finish()``)."""

  __slots__ = ()

  def __enter__(self):
    return self

  def __exit__(self, exc_type, exc, tb):
    return False

  def start(self):
    return self

  def finish(self):
    return None


_NULL_SPAN = _NullSpan()


class _Span:
  """One live span: records on exit into its tracer.  Exit/finish is
  idempotent — a protocol that syncs earlier than its tail (the
  resilient tiered step's metric fetch) may close the window at the
  true first sync and let the tail's finish be a no-op."""

  __slots__ = ("_tracer", "name", "track", "args", "_t0", "_done")

  def __init__(self, tracer: "Tracer", name: str, track: Optional[str],
               args: Optional[Dict[str, Any]]):
    self._tracer = tracer
    self.name = name
    self.track = track
    self.args = args
    self._t0 = 0
    self._done = False

  def __enter__(self):
    self._t0 = time.perf_counter_ns()
    return self

  def __exit__(self, exc_type, exc, tb):
    if not self._done:
      self._done = True
      self._tracer._record(self)
    return False

  # cross-function window form (e.g. device dispatch -> first host sync)
  def start(self):
    return self.__enter__()

  def finish(self):
    self.__exit__(None, None, None)


def span(name: str, track: Optional[str] = None,
         args: Optional[Dict[str, Any]] = None):
  """A context manager timing one pipeline stage.

  ``track`` names a virtual track (e.g. ``"device"``) instead of the
  calling thread's; ``args`` is an optional JSON-able payload shown in
  the trace viewer.  With tracing disabled this returns the no-op
  singleton and allocates nothing."""
  tr = _tracer
  if tr is None:
    return _NULL_SPAN
  return _Span(tr, name, track, args)


def instant(name: str, track: Optional[str] = None) -> None:
  """A zero-duration marker event (no-op when tracing is disabled)."""
  tr = _tracer
  if tr is not None:
    tr._instant(name, track)


class Tracer:
  """Collects span events and renders Chrome trace-event JSON.

  Buffers are per thread (``threading.local``): the hot path is an
  unlocked list append; the tracer's lock is taken only when a thread
  records its FIRST event (buffer registration) and at render time.
  Events carry their track key, so a span targeting a virtual track is
  still appended to the calling thread's buffer."""

  def __init__(self):
    self._lock = threading.Lock()
    self._local = threading.local()
    self._buffers: List[List[tuple]] = []
    self._threads: Dict[int, str] = {}
    self.t0_ns = time.perf_counter_ns()

  # ---- recording ----------------------------------------------------------
  def _buffer(self) -> List[tuple]:
    buf = getattr(self._local, "buf", None)
    if buf is None:
      t = threading.current_thread()
      buf = self._local.buf = []
      with self._lock:
        # the track key is the registration index, NOT t.ident: CPython
        # reuses idents after a thread exits, so two short-lived writer
        # threads (ckpt-writer-<k>, ckpt-writer-<k+n>) would otherwise
        # merge onto one misnamed track
        key = len(self._buffers)
        self._buffers.append(buf)
        self._threads[key] = t.name
      self._local.tid = key
    return buf

  def _record(self, sp: _Span) -> None:
    t1 = time.perf_counter_ns()
    self._buffer().append(
        ("X", sp.track or self._local.tid, sp.name, sp._t0, t1 - sp._t0,
         sp.args))

  def _instant(self, name: str, track: Optional[str]) -> None:
    t = time.perf_counter_ns()
    self._buffer().append(
        ("i", track or self._local.tid, name, t, 0, None))

  def record_window(self, name: str, t0_ns: int, t1_ns: int,
                    track: Optional[str] = None,
                    args: Optional[Dict[str, Any]] = None) -> None:
    """Record an already-measured ``[t0_ns, t1_ns)`` window (the
    ``timed`` helper's path — its clock reads happen either way, so it
    hands the finished window here instead of opening a span)."""
    buf = self._buffer()
    buf.append(("X", track or self._local.tid, name, t0_ns, t1_ns - t0_ns,
                args))

  # ---- rendering ----------------------------------------------------------
  def events(self) -> List[tuple]:
    with self._lock:
      return [e for buf in self._buffers for e in buf]

  def to_chrome(self) -> Dict[str, Any]:
    """The trace as a Chrome trace-event JSON object: one ``pid``, one
    ``tid`` per real thread, virtual tracks as extra tids sorted below
    the threads, ``ts``/``dur`` in microseconds from tracer start."""
    pid = 1
    with self._lock:
      events = [e for buf in self._buffers for e in buf]
      threads = dict(self._threads)
    tids: Dict[Any, int] = {}
    out: List[Dict[str, Any]] = [
        {"ph": "M", "pid": pid, "name": "process_name",
         "args": {"name": "distributed_embeddings_tpu"}}]

    def tid_of(key) -> int:
      tid = tids.get(key)
      if tid is None:
        tid = tids[key] = len(tids) + 1
        label = threads.get(key, key if isinstance(key, str) else
                            f"thread-{key}")
        out.append({"ph": "M", "pid": pid, "tid": tid,
                    "name": "thread_name", "args": {"name": str(label)}})
        # virtual tracks sort below the real threads
        out.append({"ph": "M", "pid": pid, "tid": tid,
                    "name": "thread_sort_index",
                    "args": {"sort_index": 1000 + tid
                             if isinstance(key, str) else tid}})
      return tid

    for ph, key, name, t0, dur, args in sorted(
        events, key=lambda e: e[3]):
      ev: Dict[str, Any] = {
          "ph": ph, "pid": pid, "tid": tid_of(key), "name": name,
          "ts": (t0 - self.t0_ns) / 1e3,
      }
      if ph == "X":
        ev["dur"] = dur / 1e3
      if args:
        ev["args"] = dict(args)
      out.append(ev)
    return {"traceEvents": out, "displayTimeUnit": "ms"}

  def save(self, path: str) -> str:
    """Write the trace as ``chrome://tracing``-viewable JSON through the
    durable-write protocol (tmp + fsync + atomic rename)."""
    from .export import atomic_write_text
    atomic_write_text(path, json.dumps(self.to_chrome()))
    return path


def install_tracer(tracer: Tracer) -> Tracer:
  """Enable tracing process-wide; returns the installed tracer."""
  global _tracer
  _tracer = tracer
  return tracer


def uninstall_tracer() -> Optional[Tracer]:
  """Disable tracing; returns the tracer that was active (if any)."""
  global _tracer
  tr, _tracer = _tracer, None
  return tr


def current_tracer() -> Optional[Tracer]:
  return _tracer


class tracing:
  """``with tracing("trace.json") as tr:`` — install a fresh tracer for
  the block, then save (when a path was given) and uninstall.  The
  previously-installed tracer (if any) is restored on exit, so scoped
  traces compose with a long-lived one."""

  def __init__(self, path: Optional[str] = None):
    self.path = path
    self.tracer = Tracer()
    self._prev: Optional[Tracer] = None

  def __enter__(self) -> Tracer:
    global _tracer
    self._prev = _tracer
    install_tracer(self.tracer)
    return self.tracer

  def __exit__(self, exc_type, exc, tb):
    global _tracer
    _tracer = self._prev
    if self.path is not None:
      os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                  exist_ok=True)
      self.tracer.save(self.path)
    return False
