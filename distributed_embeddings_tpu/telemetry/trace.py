"""Host/device span tracing: nestable spans -> Chrome trace-event JSON.

The trainers' per-step protocols are host-side pipelines (dynvocab
translate, tiered classify/stage/write-back/re-rank, device dispatch +
the block_until_ready boundary, snapshot save, batcher flush/complete)
whose whole value proposition is OVERLAP — the prefetcher classifying
batch k+1 while the device computes batch k, the batcher packing the
next dispatch while the completer drains the last.  This module makes
those claims visible instead of asserted: every stage runs under a
``span(...)`` and an enabled run writes ``trace.json``, viewable in
``chrome://tracing`` / Perfetto, with one track per real thread (the
batcher's flusher/completer workers, the async checkpoint writer) plus
named VIRTUAL tracks (``track="device"``) for windows that are not a
thread — the device-compute window between dispatch and the first host
sync.

Disabled mode is a true no-op and the default: :func:`span` returns one
process-wide ``_NullSpan`` singleton — no object, dict, or closure is
allocated per call (pinned by a tracemalloc test), nothing is timed, and
traced step code is never touched at all (spans live strictly on the
host side of the step boundary; the jaxpr fingerprints stay
byte-identical).

When enabled (:func:`install_tracer` / the :func:`tracing` context
manager), each span costs two ``perf_counter_ns`` reads and one
append to a thread-local buffer — no lock on the hot path.

Distributed tracing (round 18): serving is a multi-process system
(batcher -> router -> transports -> owners), so one request's timeline
spans several processes. A :class:`TraceContext` — trace id + parent
span id + origin epoch — is MINTED here (:func:`mint_context`), carried
on a thread-local (:func:`use_context`), and serialized over the fleet
wire framing; an enabled span under a context records its
``trace_id``/``span_id``/``parent_span_id`` into the event args, so the
per-process Chrome buffers can be assembled into ONE timeline
(:func:`merge_traces`) after a clock-offset handshake
(:func:`estimate_clock_offset` — NTP-style, min-RTT sample, the true
offset provably within ``±rtt/2`` of the estimate). jax.profiler's
device trace joins the merged timeline as a ``device`` track
(:func:`attach_device_track`), anchored on a host dispatch span.

This module is the sanctioned home of raw clock reads AND of trace-id /
clock-epoch minting in the library package: graftlint GL113 flags
``time.perf_counter``/``time.monotonic`` calls in library modules
outside ``telemetry/``, and GL115 flags raw ``uuid``/epoch minting in
the request/delta-path packages (``serving/``, ``fleet/``,
``streaming/``) — ids minted anywhere else would never land on one
trace, and a second clock-epoch source could not be correlated.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "ClockOffset",
    "TraceContext",
    "Tracer",
    "attach_device_track",
    "clock_ns",
    "device_events",
    "estimate_clock_offset",
    "get_current_context",
    "install_tracer",
    "merge_traces",
    "mint_context",
    "mint_id",
    "set_current_context",
    "span",
    "tracing",
    "uninstall_tracer",
    "use_context",
    "current_tracer",
]

_tracer: Optional["Tracer"] = None


def clock_ns() -> int:
  """The library's one span/handshake clock: ``perf_counter_ns`` (on
  Linux, CLOCK_MONOTONIC — shared by every process on one host, so
  same-host offsets are ~0 and the handshake's estimate is a pure
  uncertainty measurement; across hosts the offset is real)."""
  return time.perf_counter_ns()


# ---------------------------------------------------------------------------
# trace context: minted at admission, carried end-to-end
# ---------------------------------------------------------------------------

# process-unique span-id prefix + a cheap atomic counter: span ids stay
# unique across the processes a merged timeline assembles, without an
# os.urandom syscall per span
_PROC_TAG = os.urandom(4).hex()
_span_seq = itertools.count(1)


def _remint_proc_tag() -> None:
  # a fork()ed child inherits the parent's tag AND counter position —
  # both must re-mint or the two processes emit colliding span ids
  # that silently mis-parent a merged timeline
  global _PROC_TAG, _span_seq
  _PROC_TAG = os.urandom(4).hex()
  _span_seq = itertools.count(1)


if hasattr(os, "register_at_fork"):  # pragma: no branch
  os.register_at_fork(after_in_child=_remint_proc_tag)


def mint_id(nbytes: int = 8) -> str:
  """Mint one opaque hex id (trace ids, subscriber ids). The one
  sanctioned id mint for the request/delta-path packages (GL115)."""
  return os.urandom(int(nbytes)).hex()


def _next_span_id() -> str:
  return f"{_PROC_TAG}-{next(_span_seq):x}"


@dataclasses.dataclass(frozen=True)
class TraceContext:
  """One request's identity as it crosses process boundaries.

  Attributes:
    trace_id: the request's (or the dispatch's primary) trace id.
    span_id: the CURRENT span — a span opened under this context
      becomes its child (``parent_span_id = span_id``).
    epoch_ns: the origin process's :func:`clock_ns` at mint — with a
      handshaked offset, any receiver can bound the request's age.
    trace_ids: every trace id riding this context (a micro-batched
      dispatch carries all of its coalesced requests' ids, so each
      request's id appears on every process track the dispatch
      touches). Defaults to ``(trace_id,)``.
  """

  trace_id: str
  span_id: str
  epoch_ns: int
  trace_ids: Tuple[str, ...] = ()

  def to_wire(self) -> Dict[str, Any]:
    out = {"tid": self.trace_id, "sid": self.span_id,
           "epoch_ns": int(self.epoch_ns)}
    if len(self.trace_ids) > 1:
      out["tids"] = list(self.trace_ids)
    return out

  @classmethod
  def from_wire(cls, d: Dict[str, Any]) -> "TraceContext":
    return cls(trace_id=str(d["tid"]), span_id=str(d["sid"]),
               epoch_ns=int(d.get("epoch_ns", 0)),
               trace_ids=tuple(d.get("tids", ())) or (str(d["tid"]),))


def mint_context(trace_ids: Sequence[str] = ()) -> TraceContext:
  """Mint a fresh root context (a new trace id, a root span id, this
  process's epoch). ``trace_ids``: member ids a coalescing context
  carries (the dispatch form); the primary id is the first."""
  ids = tuple(trace_ids)
  tid = ids[0] if ids else mint_id(8)
  return TraceContext(trace_id=tid, span_id=_next_span_id(),
                      epoch_ns=clock_ns(), trace_ids=ids or (tid,))


_ctx_tls = threading.local()


def get_current_context() -> Optional[TraceContext]:
  return getattr(_ctx_tls, "ctx", None)


def set_current_context(ctx: Optional[TraceContext]
                        ) -> Optional[TraceContext]:
  """Install ``ctx`` as this thread's current context; returns the
  previous one (restore it when done — or use :class:`use_context`)."""
  prev = getattr(_ctx_tls, "ctx", None)
  _ctx_tls.ctx = ctx
  return prev


class use_context:
  """``with use_context(ctx): ...`` — scope a context to a block (the
  fan-out worker / RPC-handler form). ``None`` is legal and clears the
  context for the block."""

  __slots__ = ("ctx", "_prev")

  def __init__(self, ctx: Optional[TraceContext]):
    self.ctx = ctx

  def __enter__(self) -> Optional[TraceContext]:
    self._prev = set_current_context(self.ctx)
    return self.ctx

  def __exit__(self, exc_type, exc, tb):
    set_current_context(self._prev)
    return False


class _NullSpan:
  """The disabled-mode span: a process-wide singleton whose enter/exit
  do nothing.  ``start``/``finish`` support the cross-function window
  form (``span(...).start()`` ... ``.finish()``)."""

  __slots__ = ()

  def __enter__(self):
    return self

  def __exit__(self, exc_type, exc, tb):
    return False

  def start(self):
    return self

  def finish(self):
    return None


_NULL_SPAN = _NullSpan()


class _Span:
  """One live span: records on exit into its tracer.  Exit/finish is
  idempotent — a protocol that syncs earlier than its tail (the
  resilient tiered step's metric fetch) may close the window at the
  true first sync and let the tail's finish be a no-op.

  Under a current :class:`TraceContext`, the span mints its own span id,
  becomes the context's child, and (context-manager form only) installs
  itself as the current context for the block — so nesting and
  cross-process parenting fall out of the thread-local alone. The
  ``start()/finish()`` window form captures the parent but never pushes
  (the window may finish on another thread or not at all)."""

  __slots__ = ("_tracer", "name", "track", "args", "_t0", "_done",
               "_ctx", "_parent_id", "_restore", "_windowed")

  def __init__(self, tracer: "Tracer", name: str, track: Optional[str],
               args: Optional[Dict[str, Any]]):
    self._tracer = tracer
    self.name = name
    self.track = track
    self.args = args
    self._t0 = 0
    self._done = False
    self._ctx: Optional[TraceContext] = None
    self._parent_id: Optional[str] = None
    self._restore = False
    self._windowed = False

  def __enter__(self):
    cur = get_current_context()
    if cur is not None:
      self._ctx = TraceContext(cur.trace_id, _next_span_id(),
                               cur.epoch_ns, cur.trace_ids)
      self._parent_id = cur.span_id
      if not self._windowed:
        set_current_context(self._ctx)
        self._restore = True
    self._t0 = time.perf_counter_ns()
    return self

  def __exit__(self, exc_type, exc, tb):
    if not self._done:
      self._done = True
      if self._restore:
        # restore the parent (pushed only when a context was current)
        set_current_context(
            TraceContext(self._ctx.trace_id, self._parent_id,
                         self._ctx.epoch_ns, self._ctx.trace_ids))
      self._tracer._record(self)
    return False

  @property
  def context(self) -> Optional[TraceContext]:
    return self._ctx

  # cross-function window form (e.g. device dispatch -> first host sync)
  def start(self):
    self._windowed = True
    return self.__enter__()

  def finish(self):
    self.__exit__(None, None, None)


def span(name: str, track: Optional[str] = None,
         args: Optional[Dict[str, Any]] = None):
  """A context manager timing one pipeline stage.

  ``track`` names a virtual track (e.g. ``"device"``) instead of the
  calling thread's; ``args`` is an optional JSON-able payload shown in
  the trace viewer.  With tracing disabled this returns the no-op
  singleton and allocates nothing."""
  tr = _tracer
  if tr is None:
    return _NULL_SPAN
  return _Span(tr, name, track, args)


def instant(name: str, track: Optional[str] = None) -> None:
  """A zero-duration marker event (no-op when tracing is disabled)."""
  tr = _tracer
  if tr is not None:
    tr._instant(name, track)


class Tracer:
  """Collects span events and renders Chrome trace-event JSON.

  Buffers are per thread (``threading.local``): the hot path is an
  unlocked list append; the tracer's lock is taken only when a thread
  records its FIRST event (buffer registration) and at render time.
  Events carry their track key, so a span targeting a virtual track is
  still appended to the calling thread's buffer."""

  def __init__(self, label: str = "distributed_embeddings_tpu"):
    self._lock = threading.Lock()
    self._local = threading.local()
    self._buffers: List[List[tuple]] = []
    self._threads: Dict[int, str] = {}
    self.label = str(label)
    self.t0_ns = time.perf_counter_ns()

  # ---- recording ----------------------------------------------------------
  def _buffer(self) -> List[tuple]:
    buf = getattr(self._local, "buf", None)
    if buf is None:
      t = threading.current_thread()
      buf = self._local.buf = []
      with self._lock:
        # the track key is the registration index, NOT t.ident: CPython
        # reuses idents after a thread exits, so two short-lived writer
        # threads (ckpt-writer-<k>, ckpt-writer-<k+n>) would otherwise
        # merge onto one misnamed track
        key = len(self._buffers)
        self._buffers.append(buf)
        self._threads[key] = t.name
      self._local.tid = key
    return buf

  def _record(self, sp: _Span) -> None:
    t1 = time.perf_counter_ns()
    args = sp.args
    if sp._ctx is not None:
      args = dict(args) if args else {}
      args["trace_id"] = sp._ctx.trace_id
      args["span_id"] = sp._ctx.span_id
      if sp._parent_id is not None:
        args["parent_span_id"] = sp._parent_id
      if len(sp._ctx.trace_ids) > 1:
        args["trace_ids"] = list(sp._ctx.trace_ids)
    self._buffer().append(
        ("X", sp.track or self._local.tid, sp.name, sp._t0, t1 - sp._t0,
         args))

  def _instant(self, name: str, track: Optional[str]) -> None:
    t = time.perf_counter_ns()
    self._buffer().append(
        ("i", track or self._local.tid, name, t, 0, None))

  def record_window(self, name: str, t0_ns: int, t1_ns: int,
                    track: Optional[str] = None,
                    args: Optional[Dict[str, Any]] = None) -> None:
    """Record an already-measured ``[t0_ns, t1_ns)`` window (the
    ``timed`` helper's path — its clock reads happen either way, so it
    hands the finished window here instead of opening a span)."""
    buf = self._buffer()
    buf.append(("X", track or self._local.tid, name, t0_ns, t1_ns - t0_ns,
                args))

  # ---- rendering ----------------------------------------------------------
  def events(self) -> List[tuple]:
    with self._lock:
      return [e for buf in self._buffers for e in buf]

  def to_chrome(self) -> Dict[str, Any]:
    """The trace as a Chrome trace-event JSON object: one ``pid``, one
    ``tid`` per real thread, virtual tracks as extra tids sorted below
    the threads, ``ts``/``dur`` in microseconds from tracer start."""
    pid = 1
    with self._lock:
      events = [e for buf in self._buffers for e in buf]
      threads = dict(self._threads)
    tids: Dict[Any, int] = {}
    out: List[Dict[str, Any]] = [
        {"ph": "M", "pid": pid, "name": "process_name",
         "args": {"name": self.label}}]

    def tid_of(key) -> int:
      tid = tids.get(key)
      if tid is None:
        tid = tids[key] = len(tids) + 1
        label = threads.get(key, key if isinstance(key, str) else
                            f"thread-{key}")
        out.append({"ph": "M", "pid": pid, "tid": tid,
                    "name": "thread_name", "args": {"name": str(label)}})
        # virtual tracks sort below the real threads
        out.append({"ph": "M", "pid": pid, "tid": tid,
                    "name": "thread_sort_index",
                    "args": {"sort_index": 1000 + tid
                             if isinstance(key, str) else tid}})
      return tid

    for ph, key, name, t0, dur, args in sorted(
        events, key=lambda e: e[3]):
      ev: Dict[str, Any] = {
          "ph": ph, "pid": pid, "tid": tid_of(key), "name": name,
          "ts": (t0 - self.t0_ns) / 1e3,
      }
      if ph == "X":
        ev["dur"] = dur / 1e3
      if args:
        ev["args"] = dict(args)
      out.append(ev)
    # t0_ns/label/clock ride as top-level keys (Chrome ignores unknown
    # keys): merge_traces recovers absolute perf_counter_ns times from
    # ts + t0_ns, which is what a clock offset can be applied to
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "t0_ns": self.t0_ns, "label": self.label,
            "clock": "perf_counter_ns"}

  def save(self, path: str) -> str:
    """Write the trace as ``chrome://tracing``-viewable JSON through the
    durable-write protocol (tmp + fsync + atomic rename)."""
    from .export import atomic_write_text
    atomic_write_text(path, json.dumps(self.to_chrome()))
    return path


def install_tracer(tracer: Tracer) -> Tracer:
  """Enable tracing process-wide; returns the installed tracer."""
  global _tracer
  _tracer = tracer
  return tracer


def uninstall_tracer() -> Optional[Tracer]:
  """Disable tracing; returns the tracer that was active (if any)."""
  global _tracer
  tr, _tracer = _tracer, None
  return tr


def current_tracer() -> Optional[Tracer]:
  return _tracer


class tracing:
  """``with tracing("trace.json") as tr:`` — install a fresh tracer for
  the block, then save (when a path was given) and uninstall.  The
  previously-installed tracer (if any) is restored on exit, so scoped
  traces compose with a long-lived one."""

  def __init__(self, path: Optional[str] = None,
               label: str = "distributed_embeddings_tpu"):
    self.path = path
    self.tracer = Tracer(label=label)
    self._prev: Optional[Tracer] = None

  def __enter__(self) -> Tracer:
    global _tracer
    self._prev = _tracer
    install_tracer(self.tracer)
    return self.tracer

  def __exit__(self, exc_type, exc, tb):
    global _tracer
    _tracer = self._prev
    if self.path is not None:
      os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                  exist_ok=True)
      self.tracer.save(self.path)
    return False


# ---------------------------------------------------------------------------
# clock-offset handshake: one fleet, one correlated clock
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ClockOffset:
  """A bounded-uncertainty estimate of a remote clock's offset.

  ``remote_ns ~= local_ns + offset_ns``, so a remote timestamp maps to
  this process's clock as ``remote_ns - offset_ns``.  The bound is not
  statistical: the remote read happened somewhere inside the minimum
  round trip, so the TRUE offset lies within ``+-uncertainty_ns``
  (``rtt_ns / 2``) of the estimate — pinned by tests against injected
  skews.  ``to_local`` applies the mapping."""

  offset_ns: int
  uncertainty_ns: int
  rtt_ns: int
  rounds: int

  def to_local(self, remote_ns: int) -> int:
    return int(remote_ns) - self.offset_ns

  def to_json(self) -> Dict[str, int]:
    return {"offset_ns": self.offset_ns,
            "uncertainty_ns": self.uncertainty_ns,
            "rtt_ns": self.rtt_ns, "rounds": self.rounds}


def estimate_clock_offset(remote_clock_fn: Callable[[], int],
                          rounds: int = 8) -> ClockOffset:
  """NTP-style offset estimation over any request/reply channel.

  Each round reads the local clock, fetches the remote clock once
  (``remote_clock_fn`` — e.g. a ``clock`` RPC through a fleet
  transport), and reads the local clock again; the remote read is
  assumed at the round-trip midpoint.  The MIN-RTT round wins: whatever
  the queueing noise, the remote read provably happened inside
  ``[t0, t1]``, so the true offset is within ``rtt/2`` of that round's
  estimate — the stated uncertainty.  This is the ONE sanctioned
  handshake mint (GL115): callers pass a channel, never roll their own
  epoch exchange."""
  if rounds < 1:
    raise ValueError(f"rounds must be >= 1, got {rounds}")
  best_rtt = None
  best_off = 0
  for _ in range(rounds):
    t0 = clock_ns()
    t_remote = int(remote_clock_fn())
    t1 = clock_ns()
    rtt = t1 - t0
    if best_rtt is None or rtt < best_rtt:
      best_rtt = rtt
      best_off = t_remote - (t0 + t1) // 2
  return ClockOffset(offset_ns=int(best_off),
                     uncertainty_ns=max(1, int(best_rtt) // 2),
                     rtt_ns=int(best_rtt), rounds=int(rounds))


# ---------------------------------------------------------------------------
# timeline assembly: per-process buffers -> one merged Chrome trace
# ---------------------------------------------------------------------------


def merge_traces(traces: Sequence[Dict[str, Any]],
                 path: Optional[str] = None) -> Dict[str, Any]:
  """Assemble per-process Chrome traces into ONE timeline.

  ``traces``: one entry per process — ``{"trace": <Tracer.to_chrome()
  dict>, "offset_ns": <ClockOffset.offset_ns vs the reference process,
  0 for the reference>, "label": <track-group name, defaults to the
  trace's own label>}``.  The first entry is the reference clock.
  Every event's absolute time is recovered as ``ts*1e3 + t0_ns`` on its
  process's clock, mapped onto the reference clock by subtracting the
  offset, and rebased so the merged timeline starts at 0.  Each process
  becomes its own pid (its thread/virtual tracks ride along), so
  Perfetto shows one track group per process.  Returns the merged dict
  (``base_ns`` records the rebase point on the reference clock);
  ``path`` additionally saves it durably."""
  if not traces:
    raise ValueError("merge_traces: no traces given")
  prepared = []
  base_ns = None
  for i, entry in enumerate(traces):
    t = entry["trace"]
    t0 = int(t.get("t0_ns", 0))
    off = int(entry.get("offset_ns", 0))
    label = entry.get("label") or t.get("label") or f"process-{i}"
    evs = []
    for ev in t.get("traceEvents", []):
      if ev.get("ph") == "M":
        evs.append((None, ev))
        continue
      abs_ns = int(ev.get("ts", 0.0) * 1e3) + t0 - off
      evs.append((abs_ns, ev))
      if base_ns is None or abs_ns < base_ns:
        base_ns = abs_ns
    prepared.append((label, evs))
  if base_ns is None:
    base_ns = 0
  out: List[Dict[str, Any]] = []
  for i, (label, evs) in enumerate(prepared):
    pid = i + 1
    out.append({"ph": "M", "pid": pid, "name": "process_name",
                "args": {"name": label}})
    out.append({"ph": "M", "pid": pid, "name": "process_sort_index",
                "args": {"sort_index": pid}})
    for abs_ns, ev in evs:
      ev = dict(ev, pid=pid)
      if abs_ns is not None:
        ev["ts"] = (abs_ns - base_ns) / 1e3
      elif ev.get("name") == "process_name":
        continue  # per-process label already emitted above
      out.append(ev)
  merged = {"traceEvents": out, "displayTimeUnit": "ms",
            "base_ns": int(base_ns), "clock": "perf_counter_ns"}
  if path is not None:
    save_trace(merged, path)
  return merged


def save_trace(trace: Dict[str, Any], path: str) -> str:
  """Durably write any Chrome trace dict (tmp + fsync + rename)."""
  from .export import atomic_write_text
  d = os.path.dirname(os.path.abspath(path))
  os.makedirs(d, exist_ok=True)
  atomic_write_text(path, json.dumps(trace))
  return path


def device_events(trace: Dict[str, Any]) -> List[Dict[str, Any]]:
  """Select the DEVICE-side events of a jax.profiler Chrome trace.

  Preference order: pids whose process_name mentions TPU (real
  hardware), else pids carrying ``jit_*`` executions (the CPU-proxy
  form), else every duration event — the profile path layout is
  XLA-version-dependent, so the fallback chain keeps the merge usable
  across versions."""
  names: Dict[Any, str] = {}
  for ev in trace.get("traceEvents", []):
    if ev.get("ph") == "M" and ev.get("name") == "process_name":
      names[ev.get("pid")] = str(ev.get("args", {}).get("name", ""))
  xs = [ev for ev in trace.get("traceEvents", []) if ev.get("ph") == "X"]
  tpu = {p for p, n in names.items() if "TPU" in n}
  if tpu:
    return [ev for ev in xs if ev.get("pid") in tpu]
  jit_pids = {ev.get("pid") for ev in xs
              if str(ev.get("name", "")).startswith("jit_")}
  if jit_pids:
    return [ev for ev in xs if ev.get("pid") in jit_pids]
  return xs


def attach_device_track(merged: Dict[str, Any],
                        device_trace: Dict[str, Any],
                        anchor_ns: int,
                        label: str = "device") -> Dict[str, Any]:
  """Join jax.profiler's device trace onto a merged timeline.

  The profiler's timestamps live in their own epoch, so they are
  correlated by ANCHOR: the earliest selected device event is aligned
  to ``anchor_ns`` — an absolute reference-clock time the caller knows
  the device work began at (the first jitted dispatch span's start; the
  dispatch->enqueue latency bounds the alignment error).  Device events
  land under one new pid named ``label``, their relative spacing
  preserved exactly."""
  evs = device_events(device_trace)
  if not evs:
    return merged
  pid = 1 + max((ev.get("pid", 0) for ev in merged["traceEvents"]
                 if isinstance(ev.get("pid"), int)), default=0)
  base_ns = int(merged.get("base_ns", 0))
  dev_min_us = min(float(ev.get("ts", 0.0)) for ev in evs)
  shift_us = (int(anchor_ns) - base_ns) / 1e3 - dev_min_us
  out = list(merged["traceEvents"])
  out.append({"ph": "M", "pid": pid, "name": "process_name",
              "args": {"name": label}})
  tids: Dict[Any, int] = {}
  for ev in sorted(evs, key=lambda e: float(e.get("ts", 0.0))):
    key = ev.get("tid", 0)
    tid = tids.get(key)
    if tid is None:
      tid = tids[key] = len(tids) + 1
      out.append({"ph": "M", "pid": pid, "tid": tid,
                  "name": "thread_name",
                  "args": {"name": f"{label}:{key}"}})
    new = {"ph": "X", "pid": pid, "tid": tid,
           "name": ev.get("name", "?"),
           "ts": float(ev.get("ts", 0.0)) + shift_us,
           "dur": float(ev.get("dur", 0.0))}
    if ev.get("args"):
      new["args"] = ev["args"]
    out.append(new)
  return dict(merged, traceEvents=out)
