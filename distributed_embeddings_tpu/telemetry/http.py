"""Live ``/metrics`` scrape endpoint over the Prometheus renderer.

The textfile exporter (:func:`.export.write_prometheus`) covers the
node-exporter deployment shape — a sidecar reads a file the process
atomically replaces.  A live serving or training process wants the other
standard shape too: Prometheus scraping ``GET /metrics`` straight off
the process, no file and no sidecar.  :class:`MetricsServer` is that
endpoint — a stdlib ``ThreadingHTTPServer`` on a daemon thread rendering
:func:`.export.prometheus_text` per request, so the scrape always sees a
point-in-time consistent snapshot (the registry lock is taken once per
render, never held across the socket write).

**Fleet roll-up** (``GET /metrics?scope=fleet``): a fleet of serving
processes each keeps a private registry for exact per-process
accounting; the roll-up view answers "what is the FLEET doing" from one
scrape.  Members push registry snapshots (``state_dict()`` JSON) — in
process via :meth:`MetricsServer.push`, or over HTTP via
``POST /push`` with ``{"source": id, "telemetry": state_dict}`` — and
the fleet scope renders this process's registry MERGED with every
pushed snapshot through ``MetricsRegistry.merge``: counters and
histogram buckets ADD, gauges take the LAST writer (push order), metric
geometry mismatches fail the scrape loudly.  Snapshots replace by
source id, so a re-pushing member never double-counts.  With
``snapshot_ttl_s`` set, a snapshot older than the TTL DROPS from the
roll-up — counted once per newly-expired source
(``telemetry/snapshots_expired``), re-entering on the next push — the
publisher's heartbeat-quorum rule applied to the metrics plane: a dead
member's last numbers must not be reported as the fleet's forever.

**Readiness detail** (``GET /healthz``): a JSON body carrying the
served train watermark (``stream/served_step``), the last promote wall
time, and the computed STALENESS age in seconds — so a stalled
subscriber (live process, dead freshness) is visible from the probe
alone, without scraping and joining two metrics.  Both gauges are set
by the delta subscriber / fleet follower at each promote; a process
that never promoted reports nulls.

Lifecycle is explicit and shutdown-clean: ``close()`` (or the context
manager) shuts the serve loop down, closes the listening socket, and
JOINS the serve thread — a test or a draining server never leaks the
port or the thread.  Bind ``port=0`` to let the OS pick a free port
(``server.port`` reports the bound one).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional
from urllib.parse import parse_qs, urlparse

from .export import prometheus_text
from .registry import MetricsRegistry, get_registry

__all__ = ["MetricsServer", "record_promote", "clear_promote"]

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# the readiness-detail gauge names the /healthz probe scans; the ONE
# place they are spelled — subscribers/followers write and clear them
# through the helpers below, never by hand
PROMOTE_GAUGE_STEMS = ("stream/served_step",
                       "stream/last_promote_unixtime")

# set (unkeyed + keyed by thread name) by a MicroBatcher whose
# flusher/completer thread died of an unexpected exception — the probe
# turns it into ok=False + the dead thread names, so a serving process
# whose batcher silently lost its engine room fails readiness instead
# of answering "ok" while every request times out
DEAD_THREAD_GAUGE_STEM = "serve/flusher_dead"


def record_promote(registry: MetricsRegistry, step: int,
                   subscriber_id: Optional[str] = None) -> None:
  """Set the /healthz readiness-detail gauges for one promote: the
  served train watermark and the promote wall time, BOTH unkeyed
  (single-subscriber convenience, last-writer) and keyed by
  ``subscriber_id`` — the keyed pair keeps a stalled member visible
  when followers share one registry (the probe reports the MOST STALE
  member)."""
  now = time.time()
  step_g, wall_g = PROMOTE_GAUGE_STEMS
  registry.gauge(step_g).set(int(step))
  registry.gauge(wall_g).set(now)
  if subscriber_id:
    registry.gauge(f"{step_g}/{subscriber_id}").set(int(step))
    registry.gauge(f"{wall_g}/{subscriber_id}").set(now)


def clear_promote(registry: MetricsRegistry,
                  subscriber_id: Optional[str] = None) -> None:
  """Leave the /healthz quorum: a DELIBERATELY stopped member removes
  its keyed promote gauges AND the unkeyed pair (last-writer state
  about a decommissioned member must not read as a stalled subscriber
  forever — a live sibling's next promote re-sets the unkeyed pair,
  and its keyed pair keeps the probe correct meanwhile). A genuinely
  stalled member never calls this, so it stays visible."""
  for stem in PROMOTE_GAUGE_STEMS:
    registry.remove(stem)
    if subscriber_id:
      registry.remove(f"{stem}/{subscriber_id}")


class _Handler(BaseHTTPRequestHandler):
  """One registry, three routes: ``/metrics`` (Prometheus text —
  ``?scope=fleet`` renders the merged roll-up), ``/healthz`` (liveness
  ping), and ``POST /push`` (fleet snapshot ingestion). Everything else
  is 404."""

  # the registry rides the SERVER object (one handler instance per
  # request; BaseHTTPRequestHandler offers no clean per-handler state)
  def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler's contract
    parsed = urlparse(self.path)
    path = parsed.path
    if path == "/metrics":
      scope = parse_qs(parsed.query).get("scope", ["self"])[0]
      try:
        registry = self.server.fleet_registry() if scope == "fleet" \
            else self.server.registry
        body = prometheus_text(registry).encode("utf-8")
        self.send_response(200)
        self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
      except ValueError as e:
        # a geometry mismatch across members must fail the scrape
        # loudly, not render half a fleet
        body = f"fleet merge failed: {e}\n".encode("utf-8")
        self.send_response(500)
        self.send_header("Content-Type", "text/plain; charset=utf-8")
    elif path == "/healthz":
      body = json.dumps(self.server.health()).encode("utf-8") + b"\n"
      self.send_response(200)
      self.send_header("Content-Type", "application/json; charset=utf-8")
    else:
      body = b"not found: /metrics, /healthz and POST /push are served\n"
      self.send_response(404)
      self.send_header("Content-Type", "text/plain; charset=utf-8")
    self.send_header("Content-Length", str(len(body)))
    self.end_headers()
    self.wfile.write(body)

  def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler's contract
    if urlparse(self.path).path != "/push":
      body = b"not found\n"
      self.send_response(404)
    else:
      try:
        n = int(self.headers.get("Content-Length", "0"))
        payload = json.loads(self.rfile.read(n).decode("utf-8"))
        self.server.push(str(payload["source"]), payload["telemetry"])
        body = b"ok\n"
        self.send_response(200)
      except (ValueError, KeyError, TypeError) as e:
        body = f"bad push payload: {e}\n".encode("utf-8")
        self.send_response(400)
    self.send_header("Content-Type", "text/plain; charset=utf-8")
    self.send_header("Content-Length", str(len(body)))
    self.end_headers()
    self.wfile.write(body)

  def log_message(self, format, *args):  # noqa: A002 — base signature
    pass  # scrapes every few seconds must not spam the process log


class _Server(ThreadingHTTPServer):
  daemon_threads = True  # per-request handler threads die with close()
  registry: MetricsRegistry
  snapshot_ttl_s: Optional[float] = None

  def __init__(self, *args, **kwargs):
    super().__init__(*args, **kwargs)
    self._push_lock = threading.Lock()
    # source -> (monotonic push stamp, section); insertion-ordered
    self._snapshots: Dict[str, Any] = {}

  def push(self, source: str, section: Dict[str, Any]) -> None:
    # validate BEFORE adopting: a malformed snapshot must fail ITS push
    # (400 to the sender), never poison every later fleet scrape — the
    # throwaway load raises exactly what fleet_registry() would have
    try:
      MetricsRegistry().load_state_dict(section)
    except (ValueError, TypeError, KeyError, AttributeError) as e:
      raise ValueError(
          f"snapshot from {source!r} is not a registry state_dict: {e}"
      ) from e
    now = time.monotonic()
    with self._push_lock:
      # replace-by-source: a member re-pushing moves to the back of the
      # last-writer order and never double-counts; a re-push also
      # REVIVES an expired member (the heartbeat-quorum rule)
      self._snapshots.pop(source, None)
      self._snapshots[source] = (now, section)
      # sweep on every WRITE too — a churning fleet whose operator
      # never scrapes ?scope=fleet must not accumulate dead source
      # ids' sections forever (the sweep-on-read alone would only
      # evict when someone asks for the roll-up)
      expired = self._sweep_expired_locked(now)
    self._count_expired(expired)

  def _sweep_expired_locked(self, now: float) -> list:
    """Drop every snapshot older than the TTL from the store (caller
    holds ``_push_lock``); returns the evicted source ids. Expired
    members drop from the roll-up AND from the store — counted once
    per expiry by construction (mirroring ``stream/
    subscribers_expired``; a re-push revives): stale numbers from a
    dead process must not masquerade as the fleet's current state."""
    ttl = self.snapshot_ttl_s
    if ttl is None:
      return []
    expired = [source for source, (stamp, _) in self._snapshots.items()
               if now - stamp > ttl]
    for source in expired:
      del self._snapshots[source]
    return expired

  def _count_expired(self, expired: list) -> None:
    if expired:
      self.registry.counter("telemetry/snapshots_expired").inc(
          len(expired))

  def fleet_registry(self) -> MetricsRegistry:
    now = time.monotonic()
    with self._push_lock:
      expired = self._sweep_expired_locked(now)
      snaps = [section for _, section in self._snapshots.values()]
    self._count_expired(expired)
    merged = MetricsRegistry()
    merged.merge(self.registry)
    for section in snaps:
      tmp = MetricsRegistry()
      tmp.load_state_dict(section)
      merged.merge(tmp)
    return merged

  def health(self) -> Dict[str, Any]:
    """The /healthz readiness body: served watermark + staleness age.

    Subscribers/followers set BOTH an unkeyed gauge pair (single
    -subscriber convenience, last-writer) and per-subscriber keyed
    pairs (``.../<subscriber_id>``); the probe scans every
    ``stream/last_promote_unixtime*`` gauge and reports the MOST STALE
    member — a stalled follower must not be masked by a healthy
    sibling's later write.  Reads via the metrics map (never creating
    gauges a process hasn't earned); the names are
    :data:`PROMOTE_GAUGE_STEMS` — spelled once, written/cleared only
    through :func:`record_promote` / :func:`clear_promote`."""
    step_g, wall_g = PROMOTE_GAUGE_STEMS
    lasts: Dict[str, float] = {}
    steps: Dict[str, int] = {}
    dead_threads: list = []
    for name, m in self.registry.metrics().items():
      if name == wall_g:
        lasts[""] = float(m.value)
      elif name.startswith(wall_g + "/"):
        lasts[name.rsplit("/", 1)[1]] = float(m.value)
      elif name == step_g:
        steps[""] = int(m.value)
      elif name.startswith(step_g + "/"):
        steps[name.rsplit("/", 1)[1]] = int(m.value)
      elif name.startswith(DEAD_THREAD_GAUGE_STEM + "/") and m.value:
        # a batcher worker thread died (MicroBatcher._on_worker_death):
        # the process is alive but cannot serve — readiness must say so
        dead_threads.append(name.rsplit("/", 1)[1])
    out: Dict[str, Any]
    if not lasts:
      step = steps.get("")
      out = {"ok": True, "served_step": step,
             "last_promote_unix": None, "staleness_s": None}
    else:
      stalest = min(lasts, key=lambda k: lasts[k])
      last_wall = lasts[stalest]
      step = steps.get(stalest, steps.get(""))
      out = {
          "ok": True,
          "served_step": step,
          "last_promote_unix": last_wall,
          "staleness_s": max(0.0, time.time() - last_wall),
          "members": len([k for k in lasts if k]) or None,
      }
    if dead_threads:
      out["ok"] = False
      out["dead_threads"] = sorted(dead_threads)
    return out


class MetricsServer:
  """Serve a registry at ``http://host:port/metrics`` until closed.

  Args:
    registry: the registry to expose (default: the process-wide one).
    host: bind address — default loopback; bind ``"0.0.0.0"`` only when
      the scraper really is remote.
    port: TCP port; ``0`` (the default) picks a free one, reported by
      :attr:`port` / :attr:`url`.
    snapshot_ttl_s: fleet roll-up TTL — a pushed member snapshot older
      than this drops from ``?scope=fleet`` (counted once per expiry
      through ``telemetry/snapshots_expired``; a re-push revives).
      ``None`` (the default) keeps every snapshot forever.
  """

  def __init__(self, registry: Optional[MetricsRegistry] = None,
               host: str = "127.0.0.1", port: int = 0,
               snapshot_ttl_s: Optional[float] = None):
    self._server = _Server((host, port), _Handler)
    self._server.registry = registry if registry is not None \
        else get_registry()
    self._server.snapshot_ttl_s = None if snapshot_ttl_s is None \
        else float(snapshot_ttl_s)
    self.host = self._server.server_address[0]
    self.port = int(self._server.server_address[1])
    self._thread = threading.Thread(
        target=self._server.serve_forever, name="telemetry-metrics-http",
        daemon=True)
    self._thread.start()

  @property
  def url(self) -> str:
    return f"http://{self.host}:{self.port}/metrics"

  @property
  def fleet_url(self) -> str:
    return f"http://{self.host}:{self.port}/metrics?scope=fleet"

  def push(self, source: str, snapshot) -> None:
    """Adopt one fleet member's registry snapshot for the fleet scope.
    ``snapshot``: a ``MetricsRegistry`` (its ``state_dict()`` is taken
    now) or a ``state_dict()``-shaped JSON section."""
    if isinstance(snapshot, MetricsRegistry):
      snapshot = snapshot.state_dict()
    self._server.push(source, snapshot)

  def health(self) -> Dict[str, Any]:
    """The /healthz readiness body (also served over HTTP)."""
    return self._server.health()

  @property
  def closed(self) -> bool:
    return not self._thread.is_alive()

  def close(self) -> None:
    """Stop serving: shut the loop down, close the socket, join the
    thread. Idempotent."""
    if self._thread.is_alive():
      self._server.shutdown()
      self._thread.join(timeout=10.0)
    self._server.server_close()

  def __enter__(self) -> "MetricsServer":
    return self

  def __exit__(self, exc_type, exc, tb):
    self.close()
    return False
