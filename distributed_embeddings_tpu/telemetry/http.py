"""Live ``/metrics`` scrape endpoint over the Prometheus renderer.

The textfile exporter (:func:`.export.write_prometheus`) covers the
node-exporter deployment shape — a sidecar reads a file the process
atomically replaces.  A live serving or training process wants the other
standard shape too: Prometheus scraping ``GET /metrics`` straight off
the process, no file and no sidecar.  :class:`MetricsServer` is that
endpoint — a stdlib ``ThreadingHTTPServer`` on a daemon thread rendering
:func:`.export.prometheus_text` per request, so the scrape always sees a
point-in-time consistent snapshot (the registry lock is taken once per
render, never held across the socket write).

**Fleet roll-up** (``GET /metrics?scope=fleet``): a fleet of serving
processes each keeps a private registry for exact per-process
accounting; the roll-up view answers "what is the FLEET doing" from one
scrape.  Members push registry snapshots (``state_dict()`` JSON) — in
process via :meth:`MetricsServer.push`, or over HTTP via
``POST /push`` with ``{"source": id, "telemetry": state_dict}`` — and
the fleet scope renders this process's registry MERGED with every
pushed snapshot through ``MetricsRegistry.merge``: counters and
histogram buckets ADD, gauges take the LAST writer (push order), metric
geometry mismatches fail the scrape loudly.  Snapshots replace by
source id, so a re-pushing member never double-counts.

Lifecycle is explicit and shutdown-clean: ``close()`` (or the context
manager) shuts the serve loop down, closes the listening socket, and
JOINS the serve thread — a test or a draining server never leaks the
port or the thread.  Bind ``port=0`` to let the OS pick a free port
(``server.port`` reports the bound one).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional
from urllib.parse import parse_qs, urlparse

from .export import prometheus_text
from .registry import MetricsRegistry, get_registry

__all__ = ["MetricsServer"]

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _Handler(BaseHTTPRequestHandler):
  """One registry, three routes: ``/metrics`` (Prometheus text —
  ``?scope=fleet`` renders the merged roll-up), ``/healthz`` (liveness
  ping), and ``POST /push`` (fleet snapshot ingestion). Everything else
  is 404."""

  # the registry rides the SERVER object (one handler instance per
  # request; BaseHTTPRequestHandler offers no clean per-handler state)
  def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler's contract
    parsed = urlparse(self.path)
    path = parsed.path
    if path == "/metrics":
      scope = parse_qs(parsed.query).get("scope", ["self"])[0]
      try:
        registry = self.server.fleet_registry() if scope == "fleet" \
            else self.server.registry
        body = prometheus_text(registry).encode("utf-8")
        self.send_response(200)
        self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
      except ValueError as e:
        # a geometry mismatch across members must fail the scrape
        # loudly, not render half a fleet
        body = f"fleet merge failed: {e}\n".encode("utf-8")
        self.send_response(500)
        self.send_header("Content-Type", "text/plain; charset=utf-8")
    elif path == "/healthz":
      body = b"ok\n"
      self.send_response(200)
      self.send_header("Content-Type", "text/plain; charset=utf-8")
    else:
      body = b"not found: /metrics, /healthz and POST /push are served\n"
      self.send_response(404)
      self.send_header("Content-Type", "text/plain; charset=utf-8")
    self.send_header("Content-Length", str(len(body)))
    self.end_headers()
    self.wfile.write(body)

  def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler's contract
    if urlparse(self.path).path != "/push":
      body = b"not found\n"
      self.send_response(404)
    else:
      try:
        n = int(self.headers.get("Content-Length", "0"))
        payload = json.loads(self.rfile.read(n).decode("utf-8"))
        self.server.push(str(payload["source"]), payload["telemetry"])
        body = b"ok\n"
        self.send_response(200)
      except (ValueError, KeyError, TypeError) as e:
        body = f"bad push payload: {e}\n".encode("utf-8")
        self.send_response(400)
    self.send_header("Content-Type", "text/plain; charset=utf-8")
    self.send_header("Content-Length", str(len(body)))
    self.end_headers()
    self.wfile.write(body)

  def log_message(self, format, *args):  # noqa: A002 — base signature
    pass  # scrapes every few seconds must not spam the process log


class _Server(ThreadingHTTPServer):
  daemon_threads = True  # per-request handler threads die with close()
  registry: MetricsRegistry

  def __init__(self, *args, **kwargs):
    super().__init__(*args, **kwargs)
    self._push_lock = threading.Lock()
    self._snapshots: Dict[str, Dict[str, Any]] = {}  # insertion-ordered

  def push(self, source: str, section: Dict[str, Any]) -> None:
    # validate BEFORE adopting: a malformed snapshot must fail ITS push
    # (400 to the sender), never poison every later fleet scrape — the
    # throwaway load raises exactly what fleet_registry() would have
    try:
      MetricsRegistry().load_state_dict(section)
    except (ValueError, TypeError, KeyError, AttributeError) as e:
      raise ValueError(
          f"snapshot from {source!r} is not a registry state_dict: {e}"
      ) from e
    with self._push_lock:
      # replace-by-source: a member re-pushing moves to the back of the
      # last-writer order and never double-counts
      self._snapshots.pop(source, None)
      self._snapshots[source] = section

  def fleet_registry(self) -> MetricsRegistry:
    merged = MetricsRegistry()
    merged.merge(self.registry)
    with self._push_lock:
      snaps = list(self._snapshots.items())
    for _source, section in snaps:
      tmp = MetricsRegistry()
      tmp.load_state_dict(section)
      merged.merge(tmp)
    return merged


class MetricsServer:
  """Serve a registry at ``http://host:port/metrics`` until closed.

  Args:
    registry: the registry to expose (default: the process-wide one).
    host: bind address — default loopback; bind ``"0.0.0.0"`` only when
      the scraper really is remote.
    port: TCP port; ``0`` (the default) picks a free one, reported by
      :attr:`port` / :attr:`url`.
  """

  def __init__(self, registry: Optional[MetricsRegistry] = None,
               host: str = "127.0.0.1", port: int = 0):
    self._server = _Server((host, port), _Handler)
    self._server.registry = registry if registry is not None \
        else get_registry()
    self.host = self._server.server_address[0]
    self.port = int(self._server.server_address[1])
    self._thread = threading.Thread(
        target=self._server.serve_forever, name="telemetry-metrics-http",
        daemon=True)
    self._thread.start()

  @property
  def url(self) -> str:
    return f"http://{self.host}:{self.port}/metrics"

  @property
  def fleet_url(self) -> str:
    return f"http://{self.host}:{self.port}/metrics?scope=fleet"

  def push(self, source: str, snapshot) -> None:
    """Adopt one fleet member's registry snapshot for the fleet scope.
    ``snapshot``: a ``MetricsRegistry`` (its ``state_dict()`` is taken
    now) or a ``state_dict()``-shaped JSON section."""
    if isinstance(snapshot, MetricsRegistry):
      snapshot = snapshot.state_dict()
    self._server.push(source, snapshot)

  @property
  def closed(self) -> bool:
    return not self._thread.is_alive()

  def close(self) -> None:
    """Stop serving: shut the loop down, close the socket, join the
    thread. Idempotent."""
    if self._thread.is_alive():
      self._server.shutdown()
      self._thread.join(timeout=10.0)
    self._server.server_close()

  def __enter__(self) -> "MetricsServer":
    return self

  def __exit__(self, exc_type, exc, tb):
    self.close()
    return False
