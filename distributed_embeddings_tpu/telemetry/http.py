"""Live ``/metrics`` scrape endpoint over the Prometheus renderer.

The textfile exporter (:func:`.export.write_prometheus`) covers the
node-exporter deployment shape — a sidecar reads a file the process
atomically replaces.  A live serving or training process wants the other
standard shape too: Prometheus scraping ``GET /metrics`` straight off
the process, no file and no sidecar.  :class:`MetricsServer` is that
endpoint — a stdlib ``ThreadingHTTPServer`` on a daemon thread rendering
:func:`.export.prometheus_text` per request, so the scrape always sees a
point-in-time consistent snapshot (the registry lock is taken once per
render, never held across the socket write).

Lifecycle is explicit and shutdown-clean: ``close()`` (or the context
manager) shuts the serve loop down, closes the listening socket, and
JOINS the serve thread — a test or a draining server never leaks the
port or the thread.  Bind ``port=0`` to let the OS pick a free port
(``server.port`` reports the bound one).
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .export import prometheus_text
from .registry import MetricsRegistry, get_registry

__all__ = ["MetricsServer"]

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _Handler(BaseHTTPRequestHandler):
  """One registry, two routes: ``/metrics`` (Prometheus text) and
  ``/healthz`` (liveness ping). Everything else is 404."""

  # the registry rides the SERVER object (one handler instance per
  # request; BaseHTTPRequestHandler offers no clean per-handler state)
  def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler's contract
    path = self.path.split("?", 1)[0]
    if path == "/metrics":
      body = prometheus_text(self.server.registry).encode("utf-8")
      self.send_response(200)
      self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
    elif path == "/healthz":
      body = b"ok\n"
      self.send_response(200)
      self.send_header("Content-Type", "text/plain; charset=utf-8")
    else:
      body = b"not found: /metrics and /healthz are served\n"
      self.send_response(404)
      self.send_header("Content-Type", "text/plain; charset=utf-8")
    self.send_header("Content-Length", str(len(body)))
    self.end_headers()
    self.wfile.write(body)

  def log_message(self, format, *args):  # noqa: A002 — base signature
    pass  # scrapes every few seconds must not spam the process log


class _Server(ThreadingHTTPServer):
  daemon_threads = True  # per-request handler threads die with close()
  registry: MetricsRegistry


class MetricsServer:
  """Serve a registry at ``http://host:port/metrics`` until closed.

  Args:
    registry: the registry to expose (default: the process-wide one).
    host: bind address — default loopback; bind ``"0.0.0.0"`` only when
      the scraper really is remote.
    port: TCP port; ``0`` (the default) picks a free one, reported by
      :attr:`port` / :attr:`url`.
  """

  def __init__(self, registry: Optional[MetricsRegistry] = None,
               host: str = "127.0.0.1", port: int = 0):
    self._server = _Server((host, port), _Handler)
    self._server.registry = registry if registry is not None \
        else get_registry()
    self.host = self._server.server_address[0]
    self.port = int(self._server.server_address[1])
    self._thread = threading.Thread(
        target=self._server.serve_forever, name="telemetry-metrics-http",
        daemon=True)
    self._thread.start()

  @property
  def url(self) -> str:
    return f"http://{self.host}:{self.port}/metrics"

  @property
  def closed(self) -> bool:
    return not self._thread.is_alive()

  def close(self) -> None:
    """Stop serving: shut the loop down, close the socket, join the
    thread. Idempotent."""
    if self._thread.is_alive():
      self._server.shutdown()
      self._thread.join(timeout=10.0)
    self._server.server_close()

  def __enter__(self) -> "MetricsServer":
    return self

  def __exit__(self, exc_type, exc, tb):
    self.close()
    return False
