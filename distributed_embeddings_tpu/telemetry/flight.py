"""Flight recorder: a bounded ring of recent request traces + debug dumps.

When a fleet misbehaves — a failover fires, a delta is refused, the
batcher sheds load — the question is always "what were the last
requests doing, and where did the slow one spend its time?".  By the
time an operator attaches a profiler the moment is gone.  The
:class:`FlightRecorder` keeps the answer resident: a bounded ring
buffer of the last N per-request (per-dispatch) records, each carrying
a per-stage critical-path breakdown over the serve pipeline's stage
taxonomy::

    queue    submit -> flush pop (the oldest coalesced request's wait)
    pack     request coalescing + padding into the dispatch shape
    rpc      the router's remote owner fan-out (incl. retries/failover)
    gather   staging-buffer build + device upload of the fetched rows
    combine  the jitted serve-step dispatch (route/translate + launch)
    dequant  drain of the async device result to host (the device's
             gather/dequant/combine executes behind this window, on the
             completer thread)

Every stage observation also feeds a ``serve/stage_s/<stage>``
histogram in the registry, so the stage taxonomy is queryable as
percentiles whether or not a recorder is installed.

A TRIP (:meth:`FlightRecorder.trip` / module-level :func:`flight_trip`)
dumps a debug bundle — the ring's request traces, the per-stage
histogram digests, the slowest request's critical path, a metrics
snapshot, and the trip reason — as one JSON file through the durable
write protocol.  Trips fired mid-dispatch defer the dump until the
in-flight records complete (the failed-then-retried request must be IN
its own bundle), and a per-reason rate limit keeps an overload's shed
storm from dumping thousands of bundles.

Like the tracer, the recorder is an installed process-wide singleton
(:func:`install_flight_recorder`); the module-level helpers are no-ops
when none is installed, so the serve path stays cheap by default.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .registry import MetricsRegistry, get_registry

__all__ = [
    "STAGES",
    "FlightRecorder",
    "RequestRecord",
    "current_flight_recorder",
    "flight_trip",
    "install_flight_recorder",
    "observe_stage",
    "stage",
    "uninstall_flight_recorder",
]

# the serve pipeline's stage taxonomy (docs/ARCHITECTURE.md section 21)
STAGES = ("queue", "pack", "rpc", "gather", "combine", "dequant")

_recorder: Optional["FlightRecorder"] = None
_tls = threading.local()


class RequestRecord:
  """One dispatch's flight record (mutated only by the threads the
  batcher hands it to — flusher then completer — so no lock)."""

  __slots__ = ("trace_id", "trace_ids", "started_wall", "stages", "notes",
               "error", "total_s", "done", "_t0_ns")

  def __init__(self, trace_id: str, trace_ids=()):
    from .trace import clock_ns
    self.trace_id = trace_id
    self.trace_ids = list(trace_ids) or [trace_id]
    self.started_wall = time.time()
    self.stages: Dict[str, float] = {}
    self.notes: List[Dict[str, Any]] = []
    self.error: Optional[str] = None
    self.total_s = 0.0
    self.done = False
    self._t0_ns = clock_ns()

  def observe(self, stage_name: str, seconds: float) -> None:
    self.stages[stage_name] = self.stages.get(stage_name, 0.0) \
        + float(seconds)

  def note(self, kind: str, **detail) -> None:
    self.notes.append({"kind": kind, **detail})

  @property
  def critical_stage(self) -> Optional[str]:
    """The stage this request spent the most time in."""
    if not self.stages:
      return None
    return max(self.stages.items(), key=lambda kv: kv[1])[0]

  def to_json(self) -> Dict[str, Any]:
    return {
        "trace_id": self.trace_id,
        "trace_ids": list(self.trace_ids),
        "started_wall": self.started_wall,
        "total_s": self.total_s,
        "stages": {k: self.stages[k] for k in sorted(self.stages)},
        "critical_stage": self.critical_stage,
        "notes": list(self.notes),
        "error": self.error,
        "done": self.done,
    }


class FlightRecorder:
  """Bounded ring of request records + trip-triggered debug bundles.

  Args:
    dir: where bundles land (``flight_<k>.json``, oldest overwritten
      past ``max_bundles`` — the recorder itself must never fill a
      disk).
    capacity: ring size (the "last N requests" of a bundle).
    registry: the metrics registry stage histograms and the bundle's
      snapshot read from (default: the process-wide one).
    max_bundles: bundle files kept before the sequence wraps.
    min_interval_s: per-reason dump rate limit — a shed storm trips
      once per interval, not once per request.
  """

  def __init__(self, dir: str, capacity: int = 64,
               registry: Optional[MetricsRegistry] = None,
               max_bundles: int = 8, min_interval_s: float = 1.0):
    if capacity < 1:
      raise ValueError(f"capacity must be >= 1, got {capacity}")
    self.dir = str(dir)
    os.makedirs(self.dir, exist_ok=True)
    self.capacity = int(capacity)
    self.registry = registry if registry is not None else get_registry()
    self.max_bundles = int(max_bundles)
    self.min_interval_s = float(min_interval_s)
    self._lock = threading.Lock()
    self._ring: List[RequestRecord] = []            # guarded-by: _lock
    self._live: Dict[int, RequestRecord] = {}       # guarded-by: _lock
    self._pending_trip: Optional[Dict[str, Any]] = None  # guarded-by: _lock
    # the records that were live AT TRIP TIME: the dump fires when THEY
    # end, not when the pipeline fully drains — under sustained load
    # _live never empties, and waiting for it would starve the bundle
    # past the ring's memory of the triggering request
    self._pending_waits: set = set()                # guarded-by: _lock
    # reason -> monotonic stamp
    self._last_dump: Dict[str, float] = {}          # guarded-by: _lock
    self._seq = 0                                   # guarded-by: _lock
    self.bundles: List[str] = []                    # guarded-by: _lock

  # ---- request records ----------------------------------------------------
  def begin(self, trace_id: str, trace_ids=()) -> RequestRecord:
    rec = RequestRecord(trace_id, trace_ids)
    with self._lock:
      self._live[id(rec)] = rec
    return rec

  def bind(self, rec: Optional[RequestRecord]) -> None:
    """Make ``rec`` the calling thread's current record (the batcher
    binds on the flusher thread for pack/dispatch and re-binds on the
    completer thread for the drain)."""
    _tls.rec = rec

  def current(self) -> Optional[RequestRecord]:
    return getattr(_tls, "rec", None)

  def observe_stage(self, stage_name: str, seconds: float,
                    rec: Optional[RequestRecord] = None) -> None:
    rec = rec if rec is not None else self.current()
    if rec is not None:
      rec.observe(stage_name, seconds)

  def note(self, kind: str, **detail) -> None:
    rec = self.current()
    if rec is not None:
      rec.note(kind, **detail)

  def end(self, rec: RequestRecord,
          error: Optional[BaseException] = None) -> None:
    from .trace import clock_ns
    rec.total_s = (clock_ns() - rec._t0_ns) / 1e9
    rec.error = None if error is None else repr(error)
    rec.done = True
    pending = None
    with self._lock:
      self._live.pop(id(rec), None)
      self._ring.append(rec)
      if len(self._ring) > self.capacity:
        del self._ring[:len(self._ring) - self.capacity]
      if self._pending_trip is not None:
        self._pending_waits.discard(id(rec))
        if not self._pending_waits:
          pending, self._pending_trip = self._pending_trip, None
    if pending is not None:
      self._dump(pending)

  # ---- trips --------------------------------------------------------------
  def trip(self, reason: str, defer: bool = False,
           **detail) -> Optional[str]:
    """A failover/refusal/shed fired: dump a debug bundle.  Deferred
    until the in-flight dispatch completes (its record — the one the
    trip is usually ABOUT — must be in the bundle); a pending trip is
    never overwritten by a later one (first reason wins — the earliest
    moment is the one worth capturing); rate-limited per reason.
    ``defer=True`` moves an otherwise-inline dump to a one-shot daemon
    thread (the batcher's shed path trips while holding its submit
    lock — a write+fsync there would stall every submitter).  Returns
    the bundle path when dumped inline."""
    self.registry.counter("flight/trips").inc()
    self.registry.counter(
        f"flight/trips/{reason.split('/', 1)[0]}").inc()
    now = time.monotonic()
    with self._lock:
      last = self._last_dump.get(reason)
      if last is not None and now - last < self.min_interval_s:
        return None
      record = {"reason": reason, "detail": detail, "wall": time.time()}
      if self._live:
        if self._pending_trip is None:
          self._pending_trip = record
          self._pending_waits = set(self._live)
          # the stamp is recorded only for trips that WILL dump — a
          # trip dropped because another is pending must not consume
          # its reason's rate-limit window
          self._last_dump[reason] = now
        return None
      self._last_dump[reason] = now
    if defer:
      threading.Thread(target=self._dump, args=(record,),
                       name="flight-dump", daemon=True).start()
      return None
    return self._dump(record)

  def dump_now(self, reason: str, **detail) -> str:
    """Unconditional bundle (tools' end-of-run capture)."""
    return self._dump({"reason": reason, "detail": detail,
                       "wall": time.time()})

  # ---- the bundle ---------------------------------------------------------
  def _stage_digest(self) -> Dict[str, Any]:
    out = {}
    for name, m in sorted(self.registry.metrics().items()):
      if name.startswith("serve/stage_s/") and m.kind == "histogram":
        out[name.split("/")[-1]] = {
            "count": m.count, "total_s": m.sum, "p50": m.p50,
            "p99": m.p99, "max": m.max}
    return out

  def snapshot(self) -> Dict[str, Any]:
    """The bundle body (also the tools' verdict section)."""
    with self._lock:
      ring = list(self._ring)
      live = list(self._live.values())
    requests = [r.to_json() for r in ring] + [r.to_json() for r in live]
    slowest = max(ring, key=lambda r: r.total_s, default=None)
    return {
        "requests": requests,
        "slowest": None if slowest is None else slowest.to_json(),
        "stage_s": self._stage_digest(),
        "metrics": self.registry.snapshot(),
    }

  def _dump(self, trip_record: Dict[str, Any]) -> str:
    from .export import atomic_write_text
    body = dict(trip_record)
    body.update(self.snapshot())
    with self._lock:
      seq = self._seq
      self._seq += 1
    path = os.path.join(self.dir,
                        f"flight_{seq % self.max_bundles}.json")
    atomic_write_text(path, json.dumps(body, indent=1, sort_keys=True))
    with self._lock:
      if path not in self.bundles:
        self.bundles.append(path)
    self.registry.counter("flight/bundles").inc()
    return path


# ---------------------------------------------------------------------------
# module-level surface (no-op safe, like the tracer's)
# ---------------------------------------------------------------------------


def install_flight_recorder(rec: FlightRecorder) -> FlightRecorder:
  global _recorder
  _recorder = rec
  return rec


def uninstall_flight_recorder() -> Optional[FlightRecorder]:
  global _recorder
  rec, _recorder = _recorder, None
  return rec


def current_flight_recorder() -> Optional[FlightRecorder]:
  return _recorder


def flight_trip(reason: str, defer: bool = False,
                **detail) -> Optional[str]:
  """Trip the installed recorder (no-op without one): the one hook the
  failover/refusal/shed paths call."""
  rec = _recorder
  if rec is None:
    return None
  return rec.trip(reason, defer=defer, **detail)


def observe_stage(stage_name: str, seconds: float,
                  registry: Optional[MetricsRegistry] = None) -> None:
  """Feed one stage observation: into the ``serve/stage_s/<stage>``
  histogram — the installed recorder's registry when one is installed
  (the bundle's stage digests must see every stage, whichever
  component emitted it), else the emitting component's ``registry``
  (exact per-component accounting, the batcher's private-registry
  contract), else the process-wide one — and into the current request
  record when a recorder is installed."""
  rec = _recorder
  reg = rec.registry if rec is not None else (
      registry if registry is not None else get_registry())
  reg.histogram(f"serve/stage_s/{stage_name}").observe(seconds)
  if rec is not None:
    rec.observe_stage(stage_name, seconds)


class stage:
  """Time one pipeline stage into the stage taxonomy::

      with flight.stage("rpc"):
          fan_out()

  Clock reads live here (telemetry/ is the GL113/GL115-sanctioned
  home); the elapsed seconds go to the stage histogram and the current
  flight record.  ``.elapsed`` holds the seconds after exit."""

  __slots__ = ("name", "registry", "elapsed", "_t0")

  def __init__(self, name: str, registry: Optional[MetricsRegistry] = None):
    self.name = name
    self.registry = registry
    self.elapsed = 0.0

  def __enter__(self) -> "stage":
    from .trace import clock_ns
    self._t0 = clock_ns()
    return self

  def __exit__(self, exc_type, exc, tb):
    from .trace import clock_ns
    self.elapsed = (clock_ns() - self._t0) / 1e9
    observe_stage(self.name, self.elapsed, registry=self.registry)
    return False
