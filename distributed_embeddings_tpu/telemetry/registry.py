"""Process-wide metrics registry: counters, gauges, latency histograms.

Every subsystem in the stack grew its own ad-hoc metric surface —
guarded steps return ``{'bad_step', 'oov'}`` dicts, the dynvocab trainer
keeps ``[allocs, evictions, admit_denied, occupancy]`` vectors, the
tiering prefetcher counts hits and retries, the micro-batcher counts
rejections.  This module is the one schema they all converge on:

- :class:`Counter` — a monotone cumulative ``int`` (events since the
  LOGICAL start of the run, not the process: the value persists through
  the checkpoint manifest's ``telemetry`` section and auto-resume adopts
  it, so restarts never double-count — the dynvocab totals pattern,
  generalized).
- :class:`Gauge` — a point-in-time ``float`` (occupancy, queue depth).
- :class:`Histogram` — log-bucketed magnitudes (latencies, bytes) with
  percentile queries whose RELATIVE error is bounded by construction:
  bucket boundaries are powers of ``gamma = (1+e)/(1-e)``, so the
  estimate for any quantile is within ``rel_err`` of the exact
  nearest-rank sample value, over any distribution, at O(1) memory per
  occupied bucket.  (The DDSketch boundary scheme; the full sketch's
  bucket-collapse machinery is not needed at the cardinalities a trainer
  produces.)

Thread-safety: registries and metrics are mutated from trainer threads,
the batcher's flusher/completer workers, and async checkpoint writers —
every mutation takes the owning registry's lock.  The lock is per
REGISTRY (not global): surfaces that need isolated exact accounting (the
micro-batcher's load-shed counters, unit tests) construct a private
:class:`MetricsRegistry`; everything else shares :func:`get_registry`.

Naming: ``/``-separated lowercase paths (``train/bad_step``,
``tiered/hot_hits/<class>``).  The Prometheus exporter
(:mod:`.export`) sanitizes them to the textfile charset.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Dict, Iterable, Optional

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "WindowedHistogram",
    "get_registry",
    "counter",
    "gauge",
    "histogram",
]


class Counter:
  """Monotone cumulative event count."""

  __slots__ = ("name", "_lock", "_value")

  kind = "counter"

  def __init__(self, name: str, lock: threading.RLock):
    self.name = name
    self._lock = lock
    self._value = 0    # guarded-by: _lock [writes]

  def inc(self, n: int = 1) -> None:
    if n < 0:
      raise ValueError(f"counter {self.name!r}: inc({n}) — counters are "
                       "monotone; use a Gauge for values that go down")
    with self._lock:
      self._value += int(n)

  @property
  def value(self) -> int:
    return self._value

  def state(self) -> int:
    return self._value

  def load(self, state: Any) -> None:
    with self._lock:
      self._value = int(state)


class Gauge:
  """Point-in-time value (last write wins)."""

  __slots__ = ("name", "_lock", "_value")

  kind = "gauge"

  def __init__(self, name: str, lock: threading.RLock):
    self.name = name
    self._lock = lock
    self._value = 0.0  # guarded-by: _lock [writes]

  def set(self, v: float) -> None:
    with self._lock:
      self._value = float(v)

  @property
  def value(self) -> float:
    return self._value

  def state(self) -> float:
    return self._value

  def load(self, state: Any) -> None:
    with self._lock:
      self._value = float(state)


class Histogram:
  """Log-bucketed histogram with bounded-relative-error percentiles.

  Positive observations ``x`` land in bucket ``i = ceil(log_g(x))`` with
  ``g = (1 + rel_err) / (1 - rel_err)``; bucket ``i`` covers
  ``(g^(i-1), g^i]`` and is reported as ``2 g^i / (g + 1)`` — the value
  minimizing the worst-case relative error over the bucket, which is
  exactly ``rel_err``.  Non-positive observations (a clock that read
  zero) count in a dedicated zero bucket reported as ``0.0``.

  :meth:`percentile` answers the NEAREST-RANK quantile: the estimated
  value of the sample at 1-indexed rank ``ceil(q/100 * count)``.  For
  any distribution, ``|estimate - exact| <= rel_err * exact`` against
  the exact nearest-rank value of the raw stream (pinned adversarially
  in tests/test_telemetry.py).

  ``max_buckets`` bounds the occupied-bucket cardinality for metrics fed
  by unbounded-magnitude streams (a freshness lag that can span
  microseconds to hours would otherwise grow a bucket per decade-ish of
  gamma): when the bound is exceeded the LOWEST buckets collapse upward
  (the DDSketch policy — the smallest observations are the ones a
  latency/lag SLO never reads), so memory is O(max_buckets) forever.
  The ``rel_err`` percentile guarantee then holds only for quantiles
  landing ABOVE the collapse boundary; collapsed mass is reported at the
  boundary bucket's value (an overestimate of the collapsed samples,
  never of the upper quantiles).
  """

  __slots__ = ("name", "_lock", "rel_err", "_gamma", "_log_gamma",
               "_buckets", "_zero", "_count", "_sum", "_min", "_max",
               "max_buckets", "_collapsed")

  kind = "histogram"

  def __init__(self, name: str = "", rel_err: float = 0.01,
               lock: Optional[threading.RLock] = None,
               max_buckets: Optional[int] = None):
    if not 0.0 < rel_err < 1.0:
      raise ValueError(f"rel_err must be in (0, 1), got {rel_err}")
    if max_buckets is not None and max_buckets < 2:
      raise ValueError(f"max_buckets must be >= 2 (the collapse needs a "
                       f"boundary bucket to merge into), got {max_buckets}")
    self.name = name
    self._lock = lock if lock is not None else threading.RLock()
    self.rel_err = float(rel_err)
    self._gamma = (1.0 + rel_err) / (1.0 - rel_err)
    self._log_gamma = math.log(self._gamma)
    self._buckets: Dict[int, int] = {}  # guarded-by: _lock
    self._zero = 0         # guarded-by: _lock [writes]
    self._count = 0        # guarded-by: _lock [writes]
    self._sum = 0.0        # guarded-by: _lock [writes]
    self._min = math.inf   # guarded-by: _lock [writes]
    self._max = -math.inf  # guarded-by: _lock [writes]
    self.max_buckets = max_buckets
    # observations folded upward by bucket collapse
    self._collapsed = 0  # guarded-by: _lock [writes]

  # ---- recording ----------------------------------------------------------
  def observe(self, x: float) -> None:
    x = float(x)
    if math.isnan(x):
      raise ValueError(f"histogram {self.name!r}: observe(nan)")
    with self._lock:
      self._count += 1
      self._sum += x
      self._min = min(self._min, x)
      self._max = max(self._max, x)
      if x <= 0.0:
        self._zero += 1
      else:
        i = math.ceil(math.log(x) / self._log_gamma)
        self._buckets[i] = self._buckets.get(i, 0) + 1
        if self.max_buckets is not None \
            and len(self._buckets) > self.max_buckets:
          self._collapse_locked()

  def _collapse_locked(self) -> None:  # requires-lock: _lock
    """Merge the lowest buckets upward until the cardinality bound
    holds (caller holds the lock). Count/sum/min/max are exact
    regardless; only the collapsed samples' bucket resolution is lost."""
    while len(self._buckets) > self.max_buckets:
      lo = sorted(self._buckets)[:2]
      n = self._buckets.pop(lo[0])
      self._buckets[lo[1]] += n
      self._collapsed += n

  def observe_many(self, xs: Iterable[float]) -> None:
    for x in xs:
      self.observe(x)

  # ---- queries ------------------------------------------------------------
  @property
  def count(self) -> int:
    return self._count

  @property
  def sum(self) -> float:
    return self._sum

  @property
  def min(self) -> float:
    return self._min if self._count else math.nan

  @property
  def max(self) -> float:
    return self._max if self._count else math.nan

  @property
  def mean(self) -> float:
    return self._sum / self._count if self._count else math.nan

  def _bucket_value(self, i: int) -> float:
    return 2.0 * self._gamma ** i / (self._gamma + 1.0)

  def percentile(self, q: float) -> float:
    """Nearest-rank quantile estimate (``q`` in [0, 100]); NaN when
    empty.  Relative error vs the exact nearest-rank sample is bounded
    by ``rel_err``."""
    if not 0.0 <= q <= 100.0:
      raise ValueError(f"q must be in [0, 100], got {q}")
    with self._lock:
      if not self._count:
        return math.nan
      rank = max(1, math.ceil(q / 100.0 * self._count))
      if rank <= self._zero:
        return 0.0
      seen = self._zero
      for i in sorted(self._buckets):
        seen += self._buckets[i]
        if seen >= rank:
          return self._bucket_value(i)
      return self._bucket_value(max(self._buckets))  # fp-rounding guard

  @property
  def p50(self) -> float:
    return self.percentile(50.0)

  @property
  def p99(self) -> float:
    return self.percentile(99.0)

  def merge(self, other: "Histogram") -> None:
    """Fold ``other``'s observations into this histogram (geometries
    must match — merged buckets would otherwise mean nothing)."""
    if other.rel_err != self.rel_err:
      raise ValueError(
          f"histogram merge: rel_err {other.rel_err} != {self.rel_err} — "
          "bucket boundaries differ, counts cannot be combined")
    with self._lock:
      for i, n in other._buckets.items():
        self._buckets[i] = self._buckets.get(i, 0) + n
      self._zero += other._zero
      self._count += other._count
      self._sum += other._sum
      self._min = min(self._min, other._min)
      self._max = max(self._max, other._max)
      self._collapsed += other._collapsed
      if self.max_buckets is not None \
          and len(self._buckets) > self.max_buckets:
        self._collapse_locked()

  # ---- persistence --------------------------------------------------------
  def state(self) -> Dict[str, Any]:
    with self._lock:
      out = {
          "rel_err": self.rel_err,
          "count": self._count,
          "sum": self._sum,
          "min": None if not self._count else self._min,
          "max": None if not self._count else self._max,
          "zero": self._zero,
          # JSON object keys are strings; indices may be negative
          "buckets": {str(i): n for i, n in sorted(self._buckets.items())},
      }
      if self._collapsed:
        out["collapsed"] = self._collapsed
      return out

  def load(self, state: Dict[str, Any]) -> None:
    if float(state["rel_err"]) != self.rel_err:
      raise ValueError(
          f"histogram {self.name!r}: persisted rel_err "
          f"{state['rel_err']} != configured {self.rel_err} — the bucket "
          "boundaries differ, so the saved counts cannot be adopted")
    with self._lock:
      self._count = int(state["count"])
      self._sum = float(state["sum"])
      self._min = math.inf if state["min"] is None else float(state["min"])
      self._max = -math.inf if state["max"] is None else float(state["max"])
      self._zero = int(state["zero"])
      self._buckets = {int(i): int(n)
                       for i, n in state.get("buckets", {}).items()}
      self._collapsed = int(state.get("collapsed", 0))
      if self.max_buckets is not None \
          and len(self._buckets) > self.max_buckets:
        # a persisted unbounded (or wider-bound) histogram adopts this
        # configuration's bound on load
        self._collapse_locked()


class WindowedHistogram:
  """Rolling-window view over a :class:`Histogram` stream.

  A cumulative histogram answers "what has the p99 been since the
  process started" — useless to a control loop, which must react to the
  LAST few seconds.  This class keeps a ring of ``slots`` sealed
  sub-histograms plus one open slot: observations land in the open
  slot, :meth:`rotate` seals it into the ring (evicting the oldest
  sealed slot once the ring is full), and every read merges the ring
  plus the open slot into a throwaway cumulative view.  Because
  :meth:`Histogram.merge` is EXACT (bucket counts add; identical
  geometry by construction), the windowed percentile carries the same
  ``rel_err`` bound as a single histogram fed the same recent stream —
  pinned in tests/test_telemetry.py.

  Rotation is the CALLER's clock: the control tick (or any scheduler)
  calls :meth:`rotate` at its cadence, so the window span is
  ``slots x tick`` and — critically for the replayable decision log —
  the view is a deterministic function of the observation/rotation
  sequence, with no wall clock hidden inside.  ``maybe_rotate(now)``
  is the convenience for callers that do hold a clock reading: it
  rotates when ``rotate_every_s`` has elapsed since the last seal.

  Not a registry kind: windows are control-plane working state, not
  run-cumulative telemetry, so they never enter ``state_dict`` (a
  resumed run's "recent" is by definition empty).
  """

  __slots__ = ("name", "rel_err", "slots", "max_buckets", "_lock",
               "_open", "_ring", "_rotations", "rotate_every_s",
               "_last_rotate")

  def __init__(self, name: str = "", slots: int = 6,
               rel_err: float = 0.01,
               max_buckets: Optional[int] = None,
               rotate_every_s: Optional[float] = None):
    if slots < 1:
      raise ValueError(f"slots must be >= 1, got {slots}")
    self.name = name
    self.rel_err = float(rel_err)
    self.slots = int(slots)
    self.max_buckets = max_buckets
    self._lock = threading.RLock()
    self._open = self._fresh()  # guarded-by: _lock [writes]
    # oldest first, at most ``slots`` sealed
    self._ring: list = []       # guarded-by: _lock
    self._rotations = 0         # guarded-by: _lock [writes]
    self.rotate_every_s = rotate_every_s
    self._last_rotate: Optional[float] = None  # guarded-by: _lock

  def _fresh(self) -> Histogram:
    return Histogram(self.name, rel_err=self.rel_err, lock=self._lock,
                     max_buckets=self.max_buckets)

  # ---- recording ----------------------------------------------------------
  def observe(self, x: float) -> None:
    self._open.observe(x)

  def rotate(self) -> Histogram:
    """Seal the open slot into the ring and start a new one; returns
    the sealed sub-histogram (callers that also feed a lifetime
    histogram merge it there)."""
    with self._lock:
      sealed, self._open = self._open, self._fresh()
      self._ring.append(sealed)
      if len(self._ring) > self.slots:
        del self._ring[:len(self._ring) - self.slots]
      self._rotations += 1
      return sealed

  def maybe_rotate(self, now: float) -> bool:
    """Rotate if ``rotate_every_s`` elapsed since the last seal (the
    caller supplies the clock reading — this class never reads one)."""
    if self.rotate_every_s is None:
      return False
    with self._lock:
      if self._last_rotate is None:
        self._last_rotate = float(now)
        return False
      if now - self._last_rotate < self.rotate_every_s:
        return False
      self._last_rotate = float(now)
    self.rotate()
    return True

  # ---- reads --------------------------------------------------------------
  def view(self) -> Histogram:
    """The window as one cumulative histogram: sealed ring + open slot
    merged into a fresh (caller-owned) Histogram — reads never mutate
    the window."""
    out = Histogram(self.name, rel_err=self.rel_err,
                    max_buckets=self.max_buckets)
    with self._lock:
      for h in self._ring:
        out.merge(h)
      out.merge(self._open)
    return out

  def percentile(self, q: float) -> float:
    return self.view().percentile(q)

  @property
  def p50(self) -> float:
    return self.percentile(50.0)

  @property
  def p99(self) -> float:
    return self.percentile(99.0)

  @property
  def count(self) -> int:
    with self._lock:
      return self._open.count + sum(h.count for h in self._ring)

  @property
  def rotations(self) -> int:
    return self._rotations


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
  """One namespace of metrics, with JSON persistence.

  ``state_dict()`` is the checkpoint manifest's ``telemetry`` section:
  pure JSON (counters/gauges as scalars, histograms as sparse bucket
  maps), a deterministic function of what was observed.
  ``load_state_dict()`` REPLACES the values of every metric named in the
  section (creating them if absent) and leaves other metrics alone —
  adopt-on-resume, exactly how the ResilientTrainer adopts the persisted
  skip/OOV counters."""

  def __init__(self):
    self._lock = threading.RLock()
    self._metrics: Dict[str, Any] = {}  # guarded-by: _lock

  def _get(self, name: str, kind: str, **kwargs):
    with self._lock:
      m = self._metrics.get(name)
      if m is None:
        cls = _KINDS[kind]
        if kind == "histogram":
          m = cls(name, lock=self._lock, **kwargs)
        else:
          m = cls(name, self._lock)
        self._metrics[name] = m
      elif m.kind != kind:
        raise ValueError(
            f"metric {name!r} already registered as a {m.kind}, "
            f"requested as a {kind}")
      return m

  def counter(self, name: str) -> Counter:
    return self._get(name, "counter")

  def gauge(self, name: str) -> Gauge:
    return self._get(name, "gauge")

  def histogram(self, name: str, rel_err: float = 0.01,
                max_buckets: Optional[int] = None) -> Histogram:
    h = self._get(name, "histogram", rel_err=rel_err,
                  max_buckets=max_buckets)
    if h.rel_err != rel_err:
      # the silent alternative would hand back buckets with a different
      # error bound than the caller asked for — the same loud-mismatch
      # policy as Histogram.load/merge
      raise ValueError(
          f"histogram {name!r} already registered with rel_err="
          f"{h.rel_err}, requested {rel_err} — the bucket geometries "
          "differ; pick one rel_err per metric name")
    if max_buckets is not None and h.max_buckets != max_buckets:
      if h.max_buckets is not None:
        raise ValueError(
            f"histogram {name!r} already bounded at max_buckets="
            f"{h.max_buckets}, requested {max_buckets} — pick one bound "
            "per metric name")
      # an unbounded histogram adopts the first explicit bound (readers
      # calling histogram(name) with the default None keep not caring)
      with h._lock:
        h.max_buckets = max_buckets
        if len(h._buckets) > max_buckets:
          h._collapse_locked()
    return h

  def metrics(self) -> Dict[str, Any]:
    with self._lock:
      return dict(self._metrics)

  def peek(self, name: str):
    """The metric named ``name``, or None — WITHOUT creating it: a
    probe-style read (/healthz scans the :meth:`metrics` view for the
    same reason) must not materialize a gauge that nothing ever set."""
    with self._lock:
      return self._metrics.get(name)

  def remove(self, name: str) -> bool:
    """Drop the metric named ``name``; False if absent. A DELIBERATELY
    stopped fleet member removes its keyed promote gauges so the
    /healthz most-stale scan doesn't report a decommissioned member as
    stalled forever — a genuinely stalled member never calls this, so
    it stays visible (the heartbeat-quorum rule on the health plane)."""
    with self._lock:
      return self._metrics.pop(name, None) is not None

  def snapshot(self) -> Dict[str, Any]:
    """Human-facing summary: scalar values, histogram digests."""
    out: Dict[str, Any] = {}
    for name, m in sorted(self.metrics().items()):
      if m.kind == "histogram":
        out[name] = {"count": m.count, "mean": m.mean,
                     "p50": m.p50, "p99": m.p99, "max": m.max}
      else:
        out[name] = m.value
    return out

  def merge(self, other: "MetricsRegistry") -> None:
    """Fold another registry's observations into this one — the fleet
    ROLL-UP: N serving processes (or N subscribers on one delta chain)
    each keep a private registry for exact per-process accounting, and
    an aggregator merges them for the global view. Counters and
    histograms ADD (both are pure observation counts); gauges take the
    other's value (last-writer — a gauge is a point-in-time reading, so
    roll up gauges only from registries snapshotted together). Metric
    geometry mismatches (kind, histogram rel_err) raise loudly, the
    same policy as ``Histogram.merge``."""
    for name, m in sorted(other.metrics().items()):
      if m.kind == "counter":
        self.counter(name).inc(m.value)
      elif m.kind == "gauge":
        self.gauge(name).set(m.value)
      else:
        self.histogram(name, rel_err=m.rel_err,
                       max_buckets=m.max_buckets).merge(m)

  # ---- persistence --------------------------------------------------------
  def state_dict(self) -> Dict[str, Any]:
    """The manifest ``telemetry`` section (JSON-serializable)."""
    out: Dict[str, Dict[str, Any]] = \
        {"counters": {}, "gauges": {}, "histograms": {}}
    for name, m in sorted(self.metrics().items()):
      out[m.kind + "s"][name] = m.state()
    return out

  def load_state_dict(self, section: Dict[str, Any]) -> None:
    for name, v in section.get("counters", {}).items():
      self.counter(name).load(v)
    for name, v in section.get("gauges", {}).items():
      self.gauge(name).load(v)
    for name, st in section.get("histograms", {}).items():
      self.histogram(name, rel_err=float(st["rel_err"])).load(st)


_GLOBAL = MetricsRegistry()


def get_registry() -> MetricsRegistry:
  """The process-wide default registry."""
  return _GLOBAL


def counter(name: str) -> Counter:
  return _GLOBAL.counter(name)


def gauge(name: str) -> Gauge:
  return _GLOBAL.gauge(name)


def histogram(name: str, rel_err: float = 0.01,
              max_buckets: Optional[int] = None) -> Histogram:
  return _GLOBAL.histogram(name, rel_err=rel_err, max_buckets=max_buckets)
