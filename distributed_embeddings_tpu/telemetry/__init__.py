"""Unified telemetry: metrics registry, span tracing, durable export.

The one observability layer every subsystem emits through (round 14):

- :mod:`.registry` — process-wide counters / gauges / log-bucketed
  latency histograms with bounded-error percentile queries; cumulative
  counters persist through the checkpoint manifest's ``telemetry``
  section, so auto-resume adopts instead of double-counting.
- :mod:`.trace` — nestable ``span("stage")`` context managers over every
  host-side pipeline stage (dynvocab translate, tiered
  classify/stage/write-back/re-rank, device dispatch + sync boundary,
  snapshot save, batcher flush/complete), rendered as Chrome trace-event
  JSON with one track per worker thread plus virtual tracks (the device
  window).  Disabled tracing is a true no-op: ``span`` returns a
  singleton, allocates nothing, and traced step code is never touched —
  the jaxpr fingerprints stay byte-identical.
- :mod:`.export` — Prometheus textfile writer, rotated fsynced JSONL
  event log, and the normalized tool-verdict emitter, all through the
  durable-write protocol.

Round 18 made the tracing DISTRIBUTED: a :class:`TraceContext` minted
at batcher admission rides the fleet wire framing (owner gather spans
become the router rpc span's children across processes), a
clock-offset handshake (:func:`estimate_clock_offset` — bounded
uncertainty) lets :func:`merge_traces` assemble every process's buffer
plus jax.profiler's device trace into ONE timeline, and
:mod:`.flight`'s :class:`FlightRecorder` keeps the last N request
traces with per-stage critical paths, dumping a debug bundle whenever
a failover/refusal/shed fires.

Round 19 adds :mod:`.lockorder`: a test-time lock wrapper
(:class:`LockOrderMonitor`) that records actual lock-acquisition order
and asserts agreement with threadlint's static lock graph (GL121) —
the runtime half of the concurrency lint.

graftlint GL113 makes spans the sanctioned timing form: raw
``time.perf_counter``/``time.monotonic`` calls in library modules
outside this package are lint errors; GL115 pins trace-id/clock-epoch
minting to this package on the request/delta paths.
"""

from .export import (
    JsonlWriter,
    atomic_write_text,
    emit_verdict,
    prometheus_text,
    write_prometheus,
)
from .registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    WindowedHistogram,
    counter,
    gauge,
    get_registry,
    histogram,
)
from .http import MetricsServer, clear_promote, record_promote
from .lockorder import InstrumentedLock, LockOrderError, LockOrderMonitor
from .flight import (
    FlightRecorder,
    current_flight_recorder,
    flight_trip,
    install_flight_recorder,
    uninstall_flight_recorder,
)
from .trace import (
    ClockOffset,
    TraceContext,
    Tracer,
    attach_device_track,
    current_tracer,
    estimate_clock_offset,
    get_current_context,
    install_tracer,
    instant,
    merge_traces,
    mint_context,
    mint_id,
    set_current_context,
    span,
    tracing,
    uninstall_tracer,
    use_context,
)

__all__ = [
    "ClockOffset",
    "Counter",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "InstrumentedLock",
    "JsonlWriter",
    "LockOrderError",
    "LockOrderMonitor",
    "MetricsRegistry",
    "MetricsServer",
    "TraceContext",
    "Tracer",
    "WindowedHistogram",
    "atomic_write_text",
    "attach_device_track",
    "clear_promote",
    "counter",
    "current_flight_recorder",
    "current_tracer",
    "emit_verdict",
    "estimate_clock_offset",
    "flight_trip",
    "gauge",
    "get_current_context",
    "get_registry",
    "histogram",
    "install_flight_recorder",
    "install_tracer",
    "instant",
    "merge_traces",
    "mint_context",
    "mint_id",
    "record_promote",
    "prometheus_text",
    "set_current_context",
    "span",
    "timed",
    "tracing",
    "uninstall_flight_recorder",
    "uninstall_tracer",
    "use_context",
    "write_prometheus",
]


class timed:
  """Time a block into a named histogram (and a span of the same name).

  The consolidation point for the tools' hand-rolled ``perf_counter``
  loops::

      with timed("serve/step"):
          run_once()
      p50 = get_registry().histogram("serve/step").p50

  ``.elapsed`` holds the block's seconds after exit.  Recording goes
  through a span even when tracing is disabled: the clock read lives in
  :mod:`.trace` (the GL113-sanctioned home), and the histogram is
  observed either way."""

  __slots__ = ("name", "registry", "elapsed", "_t0")

  def __init__(self, name: str, registry: MetricsRegistry = None):
    self.name = name
    self.registry = registry if registry is not None else get_registry()
    self.elapsed = 0.0

  def __enter__(self) -> "timed":
    import time
    self._t0 = time.perf_counter_ns()
    return self

  def __exit__(self, exc_type, exc, tb):
    import time
    t1 = time.perf_counter_ns()
    self.elapsed = (t1 - self._t0) / 1e9
    self.registry.histogram(self.name).observe(self.elapsed)
    tr = current_tracer()
    if tr is not None:
      tr.record_window(self.name, self._t0, t1)
    return False
