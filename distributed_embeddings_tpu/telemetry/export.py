"""Telemetry export: Prometheus textfile, rotated JSONL event log, verdicts.

Three durable sinks over the registry/tracer state, all through the
repo's durable-write discipline (write + flush + fsync before any rename
— the checkpoint layer's protocol, minus the manifest machinery a
single flat file does not need):

- :func:`write_prometheus` — the registry as a node-exporter
  textfile-collector file (atomic replace, so the scraper never reads a
  torn file).  Counters/gauges as scalars, histograms as summaries with
  ``quantile`` labels plus ``_sum``/``_count``.
- :class:`JsonlWriter` — an append-only JSON-lines event log with size
  rotation (``events.jsonl`` -> ``.1`` -> ``.2`` ...), each line fsynced
  before :meth:`write` returns, so the last event of a SIGKILLed process
  is on disk.
- :func:`emit_verdict` — the one way a chaos/bench tool reports its
  result: a normalized ``{"tool", "ok", "verdict"}`` record printed as
  JSON, appended to a JSONL log when configured (``path=`` or the
  ``DE_TPU_VERDICT_LOG`` environment variable), and mapped to the exit
  code (0 iff ``ok``) — so ``chaos_train``/``chaos_kill``/the obs bench
  cannot drift apart in fields or exit-code semantics.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Optional

from .registry import MetricsRegistry

__all__ = [
    "atomic_write_text",
    "write_prometheus",
    "prometheus_text",
    "JsonlWriter",
    "emit_verdict",
    "VERDICT_LOG_ENV",
]

VERDICT_LOG_ENV = "DE_TPU_VERDICT_LOG"


def _fsync_file(f) -> None:
  f.flush()
  os.fsync(f.fileno())


def _fsync_dir(path: str) -> None:
  # same best-effort convention as checkpoint._fsync_dir: the entry
  # publication matters on filesystems that support it, EINVAL elsewhere
  try:
    fd = os.open(path, os.O_RDONLY)
  except OSError:
    return
  try:
    os.fsync(fd)
  except OSError:
    pass
  finally:
    os.close(fd)


def atomic_write_text(path: str, text: str) -> None:
  """Write ``text`` to ``path`` durably: tmp file, fsync, atomic
  replace (a reader — the Prometheus textfile collector, a trace viewer
  — sees the old complete file or the new complete file, never a torn
  one)."""
  tmp = path + ".tmp"
  with open(tmp, "w") as f:
    f.write(text)
    _fsync_file(f)
  os.replace(tmp, path)
  _fsync_dir(os.path.dirname(os.path.abspath(path)))


_PROM_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
  n = _PROM_NAME_RE.sub("_", name)
  if n and n[0].isdigit():
    n = "_" + n
  return n


def prometheus_text(registry: MetricsRegistry) -> str:
  """Render a registry in the Prometheus text exposition format."""
  lines = []
  for name, m in sorted(registry.metrics().items()):
    pn = _prom_name(name)
    if m.kind == "counter":
      lines.append(f"# TYPE {pn} counter")
      lines.append(f"{pn} {m.value}")
    elif m.kind == "gauge":
      lines.append(f"# TYPE {pn} gauge")
      lines.append(f"{pn} {_fmt(m.value)}")
    else:  # histogram -> summary (quantiles are what latency SLOs read)
      lines.append(f"# TYPE {pn} summary")
      for q in (0.5, 0.9, 0.99, 0.999):
        lines.append(f'{pn}{{quantile="{q}"}} '
                     f"{_fmt(m.percentile(q * 100.0))}")
      lines.append(f"{pn}_sum {_fmt(m.sum)}")
      lines.append(f"{pn}_count {m.count}")
  return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
  if v != v:  # NaN
    return "NaN"
  return repr(float(v))


def write_prometheus(registry: MetricsRegistry, path: str) -> str:
  """Atomically publish ``registry`` as a textfile-collector file."""
  atomic_write_text(path, prometheus_text(registry))
  return path


class JsonlWriter:
  """Durable append-only JSON-lines log with size rotation.

  ``write(obj)`` appends one line and fsyncs before returning; when the
  file exceeds ``max_bytes`` it rotates — ``path`` -> ``path.1`` ->
  ``path.2`` ... keeping ``keep`` rotated files (the oldest is
  deleted).  Rotation renames are preceded by an fsync of the live
  file, so a crash at any point leaves every already-written line on
  disk in some file of the set."""

  def __init__(self, path: str, max_bytes: int = 16 << 20, keep: int = 3,
               fsync: bool = True):
    if max_bytes < 1:
      raise ValueError(f"max_bytes must be >= 1, got {max_bytes}")
    if keep < 1:
      raise ValueError(f"keep must be >= 1, got {keep}")
    self.path = path
    self.max_bytes = int(max_bytes)
    self.keep = int(keep)
    self.fsync = fsync
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    self._f = open(path, "a")

  def write(self, obj: Any) -> None:
    line = json.dumps(obj, sort_keys=True)
    self._f.write(line + "\n")
    if self.fsync:
      _fsync_file(self._f)
    else:
      self._f.flush()
    if self._f.tell() >= self.max_bytes:
      self._rotate()

  def _rotate(self) -> None:
    _fsync_file(self._f)
    self._f.close()
    oldest = f"{self.path}.{self.keep}"
    if os.path.exists(oldest):
      os.remove(oldest)
    for i in range(self.keep - 1, 0, -1):
      src = f"{self.path}.{i}"
      if os.path.exists(src):
        os.replace(src, f"{self.path}.{i + 1}")
    os.replace(self.path, f"{self.path}.1")
    _fsync_dir(os.path.dirname(os.path.abspath(self.path)))
    self._f = open(self.path, "a")

  def close(self) -> None:
    if not self._f.closed:
      _fsync_file(self._f)
      self._f.close()

  def __enter__(self) -> "JsonlWriter":
    return self

  def __exit__(self, exc_type, exc, tb):
    self.close()
    return False


def emit_verdict(tool: str, result: Dict[str, Any], verbose: bool = True,
                 path: Optional[str] = None) -> int:
  """Report a tool verdict the one sanctioned way; returns the exit
  code (0 iff ``result['ok']`` is truthy).

  The normalized record is ``{"tool": <name>, "ok": <bool>,
  "verdict": <the tool's full result dict>}`` — printed as indented
  JSON plus the classic ``TOOL: PASS|FAIL`` line, and appended through
  :class:`JsonlWriter` to ``path`` (or ``$DE_TPU_VERDICT_LOG`` when
  set), so every chaos/bench tool shares one field schema and one
  exit-code convention instead of hand-building both."""
  ok = bool(result.get("ok", False))
  record = {"tool": tool, "ok": ok, "verdict": result}
  if verbose:
    print(json.dumps(record, indent=1))
  log_path = path if path is not None else os.environ.get(VERDICT_LOG_ENV)
  if log_path:
    with JsonlWriter(log_path) as w:
      w.write(record)
  print(f"{tool.upper()}: {'PASS' if ok else 'FAIL'}")
  return 0 if ok else 1
