"""The serve step + engine: donated-batch jitted inference on frozen tables.

``make_serve_step`` is ``training.make_sparse_eval_step``'s
inference-first counterpart, built on the stripped images of
:mod:`.export` instead of the training buffers:

- **no scatters, no metrics, no guard**: the traced program is route ->
  gather -> exchange -> assemble -> model forward, nothing else (the
  jaxpr audit pins zero scatter ops and zero host callbacks on the
  ``serve_step_{f32,int8}`` artifacts);
- **dequantize-on-gather**: int8 rows gather as bytes and widen to f32
  in one fused multiply against the row's bit-packed scale — the gather
  is row-bound, so the narrower row is the whole win (PAPERS.md,
  "Dissecting Embedding Bag Performance in DLRM Inference": lookup
  bytes dominate serve time);
- **f32 serving is BIT-exact** against ``make_sparse_eval_step``: same
  gather values, and the multi-hot combine replicates the eval step's
  fp-addition grouping on narrow aux-packed classes
  (:func:`_combine_masked_order`);
- **parameter buffers are never donated** — a serve step is called
  thousands of times against one frozen table; only the per-dispatch
  request arrays may be donated (``donate_batch``). The persistent
  resident maps ride the staged inputs and are never donated either.

Tiered plans serve hot ids from the device cache and cold ids from the
stripped host image: :class:`ServeEngine` rebuilds the tiering stack
(``HostTierStore`` + ``TieredPrefetcher``) on the SERVE geometry — the
classify/stage pipeline is reused verbatim, only the images are
stripped (and possibly int8) and nothing is ever written back.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..compat import shard_map
from ..layers.dist_model_parallel import hybrid_partition_specs
from ..layers.planner import DistEmbeddingStrategy
from ..telemetry import flight as _flight
from ..telemetry import get_registry as _get_registry
from ..ops.packed_table import PackedLayout, gather_fused_chunked
from ..parallel.lookup_engine import (
    DedupRouted,
    DistributedLookup,
    TierSpec,
    class_param_name,
    padded_rows,
    ragged_hotness,
)
from ..training import shard_batch
from .export import (
    INT8_SCALE_LANES,
    FrozenTables,
    ServeArtifact,
    ServeClassMeta,
    frozen_device_state,
)


def _dequant_rows(rows: jax.Array, meta: ServeClassMeta) -> jax.Array:
  """Gathered serve rows -> f32 table rows.

  f32 images pass through (the gather already returned ``[..., width]``
  f32 lanes). int8/fp8 images arrive ``[..., width + 4]``: the trailing
  4 byte-wide lanes bitcast back to the row's f32 scale (the export
  packed it there — no second gather), and the dequant is one fused
  widen+multiply per row. Sentinel/OOB ids gathered all-zero rows whose
  scale bytes decode to 0.0, so they stay exactly zero after the
  multiply."""
  if meta.quantize == "f32":
    return rows
  w = meta.width
  q = rows[..., :w]
  scale = lax.bitcast_convert_type(
      lax.bitcast_convert_type(rows[..., w:w + INT8_SCALE_LANES],
                               jnp.uint8), jnp.float32)
  return q.astype(jnp.float32) * scale[..., None]


def _combine_masked_order(engine: DistributedLookup, key,
                          rows: jax.Array, oids: jax.Array,
                          rpp: int, rs: bool) -> jax.Array:
  """Multi-hot combine replicating the eval step's masked-window order.

  The training layout packs ``rpp`` logical rows per physical row, and
  the eval step's narrow multi-hot fast path
  (``lookup_engine._z_sparse_fused``) sums window-MASKED physical rows
  over the hotness axis first and folds the ``rpp`` windows once per
  bag. That groups the fp additions by ``id % rpp`` — a different
  summation order than a plain h-axis sum, hence (in general) different
  last-ulp bits. The f32 serve path claims BIT-exactness against eval,
  so it reproduces the grouping: serve rows are already table-width, but
  masking them into ``rpp`` width-w windows by the LOGICAL id's sub-row
  and reducing h-then-windows adds the same values in the same order
  (zeros added where eval added a masked-out window's zeros — exact)."""
  cp = engine.plan.classes[key]
  if cp.combiner is None:
    raise ValueError("combiner=None requires hotness-1 inputs in the "
                     "distributed path (2-D model-parallel outputs)")
  sentinel = padded_rows(engine.plan, key)
  valid = (oids >= 0) & (oids < sentinel)
  sub = jnp.where(valid, oids, 0) % rpp
  w = rows.shape[-1]
  win = lax.broadcasted_iota(jnp.int32, (rpp * w,), 0) // w
  # The tile-to-rpp-windows form is deliberate: XLA's reduce
  # association varies with the minor-dim shape, and this shape is the
  # one whose h-axis reduce reproduces the eval path's bit pattern (a
  # width-w per-window select measured barely faster and broke
  # bit-exactness). The masked tensor is the same order of size as the
  # eval step's own masked-phys staging, so f32 serving of multi-hot
  # narrow classes costs what eval costs — the serving win is int8,
  # whose generic combine skips this path entirely.
  masked = jnp.where(win == sub[..., None], jnp.tile(rows, rpp), 0)
  bag = jnp.sum(masked, axis=2)                       # [n_b, G, rpp*w]
  z = jnp.sum(bag.reshape(bag.shape[:-1] + (rpp, w)), axis=-2)
  if cp.combiner == "mean" and not rs:
    counts = jnp.sum(oids < sentinel, axis=2).astype(z.dtype)
    z = z / jnp.maximum(counts, 1)[..., None]
  return z


def _serve_lookup(engine: DistributedLookup,
                  serve_params: Dict[str, jax.Array],
                  layouts: Dict[str, PackedLayout],
                  meta: Dict[str, ServeClassMeta],
                  ids_gather: Dict[tuple, Any],
                  ids_order: Dict[tuple, Any]) -> Dict[tuple, jax.Array]:
  """mp-side lookup over the inference images (the serve counterpart of
  ``lookup_sparse_fused`` — no residuals, dequant fused in).

  ``ids_gather`` addresses the buffers (tiered classes: compact ids
  after ``translate_tiered_ids``); ``ids_order`` keeps the LOGICAL
  routing tensors, whose sentinel pattern drives the combiner's
  valid-counts and the masked-order fold — identical to what the
  all-device eval step sees, which is what makes tiered f32 serving
  bit-exact against it."""
  z: Dict[tuple, jax.Array] = {}
  for bk, ids in ids_gather.items():
    key = bk.class_key
    if engine.plan.classes[key].kind != "sparse":
      continue
    name = class_param_name(*key)
    m = meta[name]
    lay = layouts[name]
    buf = engine._squeeze_local(serve_params[name])
    if isinstance(ids, DedupRouted):
      # one row per unique id; dp side expands + combines (the reverse
      # of nothing — serve has no backward) via engine.exchange
      z[bk] = _dequant_rows(gather_fused_chunked(lay, buf, ids.uniq), m)
    elif isinstance(ids, tuple):  # ragged value stream (vals, lens)
      vals, lens = ids
      rows = _dequant_rows(gather_fused_chunked(lay, buf, vals), m)
      ovals, _olens = ids_order[bk]
      z[bk] = engine._combine_ragged(rows, ovals, lens, key, bk.rs)
    else:
      rows = _dequant_rows(gather_fused_chunked(lay, buf, ids), m)
      oids = ids_order[bk]
      if (m.quantize == "f32" and m.combine_rpp > 1 and oids.ndim == 3
          and oids.shape[-1] > 1):
        z[bk] = _combine_masked_order(engine, key, rows, oids,
                                      m.combine_rpp, bk.rs)
      else:
        z[bk] = engine._combine(rows, oids, key, bk.rs)
  return z


def make_serve_step(model, plan: DistEmbeddingStrategy,
                    serve_meta: Dict[str, ServeClassMeta],
                    mesh, state: Dict[str, Any], batch_example,
                    axis_name: str = "mp",
                    tier_specs: Optional[Dict[str, TierSpec]] = None,
                    with_metrics: bool = False,
                    donate_batch: bool = False):
  """Build the jitted serve step over a frozen-table state.

  Args:
    serve_meta: per sparse class the inference-image geometry
      (:class:`~.export.ServeClassMeta` — from ``export.freeze`` or a
      loaded artifact's ``.meta``).
    state: ``{'dense', 'emb_dense', 'serve'}`` (device-placed); tiered
      plans pass the compact cache+staging buffers in ``'serve'`` and
      the per-dispatch staging upload as the step's ``staged`` input.
    batch_example: ``(numerical, cats)`` request structure (specs only).
    tier_specs: serve-geometry :class:`TierSpec` per host-tier class
      (from :class:`ServeEngine`'s tier plan); routed logical ids are
      rewritten to cache/staging slots exactly as in the tiered train
      step, and a spill dispatch retraces per staging bucket.
    with_metrics: tiered steps also return ``{'tier': {class: [hot,
      staged, missed, valid] int32}}`` (psum'd) — ``missed > 0`` means
      the prefetch contract was violated and those lookups read zeros.
    donate_batch: donate the REQUEST arrays (numerical + cats; the
      micro-batcher builds fresh ones per dispatch). The parameter
      buffers and the staged inputs (whose ``resident`` maps persist
      across dispatches) are NEVER donated: a serve step must be
      repeatable against one frozen table — see the regression tests.

  Returns:
    ``step(state, numerical, cats) -> preds`` (tiered:
    ``step(state, staged, numerical, cats)``; with metrics, ``->
    (preds, metrics)``).
  """
  if getattr(plan, "dedup_capacity", None) is not None:
    raise ValueError(
        "plan.dedup_capacity is not servable: a capacity below the safe "
        "bound aliases distinct ids onto the cap's last slot — those "
        "predictions read the WRONG rows — and the serve step carries no "
        "metrics path to count it. Serve an uncapped plan (the artifact "
        "is the same), or use make_sparse_eval_step(with_metrics=True).")
  if getattr(plan, "oov", "clip") == "error":
    raise ValueError(
        "plan.oov='error' is not servable: enforcement rides the guarded "
        "train step's metrics + commit gate, and the serve step carries "
        "neither. Serve with oov='clip' (the routing clamp is identical) "
        "or run make_sparse_eval_step(with_metrics=True) to count OOV.")
  if getattr(plan, "oov", "clip") == "allocate":
    raise ValueError(
        "plan.oov='allocate' is not servable: allocation MUTATES the id "
        "space (admission counts, row allocation, TTL eviction), and an "
        "inference path must never mutate it — a serve request earning "
        "rows would shift what training trains, from a path with no "
        "commit gate. Serve with oov='clip' (same tables, same frozen "
        "image) and translate request ids read-only host-side "
        "(dynvocab.DynVocabTranslator.translate_readonly).")
  engine = DistributedLookup(plan, dp_input=True, axis_name=axis_name)
  base_layouts = {n: m.packed for n, m in serve_meta.items()}
  tiered = tier_specs is not None and bool(tier_specs)

  def local_serve(state, *args):
    if tiered:
      staged, numerical = args[0], args[1]
      cats = list(args[2])
    else:
      numerical = args[0]
      cats = list(args[1])
    b = numerical.shape[0]
    hotness = [ragged_hotness(c) for c in cats]
    hotness_of = lambda i: hotness[i]  # noqa: E731
    ids_all = engine.route_ids(cats, hotness_of)
    counts = engine.mean_counts(cats)
    if tiered:
      # effective layouts from THIS dispatch's staging shapes (spill
      # dispatches retrace, same contract as the tiered train step)
      layouts = dict(base_layouts)
      for name, spec in tier_specs.items():
        s = staged["grps"][name].shape[0]
        layouts[name] = PackedLayout(
            rows=(spec.cache_grps + s) * spec.rpp,
            width=base_layouts[name].width, n_aux=0)
      ids_gather, tier_m = engine.translate_tiered_ids(
          ids_all, tier_specs, staged["resident"], staged["grps"])
      serve_bufs = engine.install_staging(state["serve"], tier_specs,
                                          staged["rows"])
    else:
      layouts, ids_gather, serve_bufs, tier_m = (
          base_layouts, ids_all, state["serve"], None)
    z = _serve_lookup(engine, serve_bufs, layouts, serve_meta,
                      ids_gather, ids_all)
    acts = engine.finish_forward(z, state["emb_dense"], ids_gather, b,
                                 hotness_of, counts)
    preds = model.apply({"params": state["dense"]}, numerical, cats,
                        emb_acts=acts)
    if with_metrics and tiered:
      if mesh is not None:
        tier_m = {n: lax.psum(m, axis_name) for n, m in tier_m.items()}
      return preds, {"tier": tier_m}
    return preds

  # Donation contract: argnum 0 (the frozen state) is NEVER donated —
  # donating it would invalidate the table on the first dispatch and
  # poison every later one. Tiered argnum 1 (staged) is never donated
  # either: its 'resident' maps persist across dispatches. Only the
  # request arrays may be donated.
  batch0 = 2 if tiered else 1
  donate = tuple(range(batch0, batch0 + 2)) if donate_batch else ()
  if mesh is None:
    return jax.jit(local_serve, donate_argnums=donate)
  sspec = hybrid_partition_specs(state, axis_name)
  bspec = jax.tree_util.tree_map(
      lambda _: P(axis_name), tuple(batch_example))
  in_specs = (sspec,) + bspec
  if tiered:
    staged_specs = {
        "grps": {n: P(axis_name) for n in tier_specs},
        "resident": {n: P(axis_name) for n in tier_specs},
        "rows": {n: P(axis_name, None) for n in tier_specs},
    }
    in_specs = (sspec, staged_specs) + bspec
  out_specs = P(axis_name)
  if with_metrics and tiered:
    out_specs = (P(axis_name), {"tier": {n: P() for n in tier_specs}})
  return jax.jit(
      shard_map(local_serve, mesh=mesh, in_specs=in_specs,
                out_specs=out_specs),
      donate_argnums=donate)


# ---------------------------------------------------------------------------
# tiered serve residency: the tiering stack on serve geometry
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ServeTierConfig:
  """Serve-side residency knobs (decided at deployment, not export —
  the same artifact serves from chips with different HBM budgets).

  Attributes:
    cache_fraction: resident fraction of each host-tier class's serve
      physical rows. The stripped image packs 2-3x more logical rows
      per physical row than the training layout, so the same HBM holds
      a proportionally larger hot set.
    staging_grps: persistent staging physical rows per class per rank
      (size near the expected per-dispatch deduped cold-row count).
    spill_factor_max: staging growth bound (power-of-two buckets; a
      spill dispatch retraces once per bucket, as in training).
  """

  cache_fraction: float = 0.25
  staging_grps: int = 1024
  spill_factor_max: int = 16
  rerank_interval: int = 0  # serve residency is frozen; kept for the
  # prefetcher's maybe_rerank signature compatibility


@dataclasses.dataclass(frozen=True)
class _ServeTierClass:
  """Duck-type of ``tiering.plan.TieredClassPlan`` on serve geometry —
  what ``HostTierStore`` and ``TieredPrefetcher`` actually consume."""

  key: tuple
  name: str
  spec: TierSpec
  layout_logical: PackedLayout
  spill_cap_grps: int


class ServeTierPlan:
  """Serve-geometry twin of ``tiering.TieringPlan``: same classify /
  stage / translate machinery, sized on the stripped image's physical
  rows. Duck-types the ``tplan`` the tiering stack binds to.

  ``keys``: the class keys whose rows live off-device (default: the
  plan's host-tier classes — single-process tiered serving). The fleet
  router passes EVERY sparse class: behind a routing tier, all rows are
  "cold" on their rank owners, and the hot cache is the router's local
  hot-shard replica."""

  def __init__(self, plan: DistEmbeddingStrategy,
               meta: Dict[str, ServeClassMeta],
               config: ServeTierConfig = ServeTierConfig(),
               keys=None):
    host_keys = plan.host_tier_class_keys() if keys is None else list(keys)
    if not host_keys:
      raise ValueError("plan has no host-tier classes")
    self.plan = plan
    self.config = config
    self.classes: Dict[tuple, _ServeTierClass] = {}
    for key in host_keys:
      name = class_param_name(*key)
      m = meta[name]
      lay = m.packed
      rpp = lay.rows_per_phys
      hard_cap = lay.rows // rpp
      # clamp to the class's own capacity: a small class must leave at
      # least one physical row of cache under the hard cap (compact ids
      # stay below the sentinel), whatever the configured staging is
      staging = min(config.staging_grps, max(1, lay.phys_rows - 1),
                    max(1, hard_cap - 1))
      cache = min(max(1, int(lay.phys_rows * config.cache_fraction)),
                  hard_cap - staging)
      if cache < 1:
        raise ValueError(
            f"class {name}: no room for a serve hot cache "
            f"(staging_grps={staging}, {lay.phys_rows:,} serve physical "
            "rows); shrink staging_grps or raise cache_fraction's "
            "denominator by serving the class all-device.")
      spec = TierSpec(name=name, rows=lay.rows, rpp=rpp,
                      cache_grps=cache, staging_grps=staging)
      self.classes[key] = _ServeTierClass(
          key=key, name=name, spec=spec, layout_logical=lay,
          spill_cap_grps=hard_cap - cache)
    self.tier_specs: Dict[str, TierSpec] = {
        c.name: c.spec for c in self.classes.values()}

  def by_name(self, name: str) -> _ServeTierClass:
    for c in self.classes.values():
      if c.name == name:
        return c
    raise KeyError(name)


class ServeEngine:
  """Host-side driver: frozen tables in, asynchronous predictions out.

  Owns the jitted serve step (one per traced batch/staging shape), and
  for tiered plans the serve-geometry residency stack: a
  ``HostTierStore`` holding the stripped cold images (f32 or int8) with
  the resident set seeded from the export-time observed-count ranking,
  and a ``TieredPrefetcher`` whose classify/stage path uploads each
  dispatch's cold rows — hot ids are served from the device cache, cold
  ids from the host image, and the upload overlaps the previous
  dispatch's device work (jax dispatch is asynchronous). Nothing is
  ever written back: serve images are immutable.

  ``dispatch`` returns the (not-yet-materialized) device predictions so
  callers — the micro-batcher above all — can pipeline; ``predict``
  blocks and returns numpy.
  """

  def __init__(self, model, plan: DistEmbeddingStrategy,
               artifact, mesh=None, axis_name: str = "mp",
               tier_config: Optional[ServeTierConfig] = None,
               with_metrics: bool = False,
               donate_batch: bool = False,
               telemetry=None):
    if isinstance(artifact, FrozenTables):
      state = frozen_device_state(artifact, plan, mesh, axis_name)
      host_images, ranking = artifact.host_images, artifact.ranking
    elif isinstance(artifact, ServeArtifact):
      state = dict(artifact.state)
      state["serve"] = dict(state["serve"])
      host_images, ranking = artifact.host_images, artifact.ranking
    else:
      raise TypeError(
          f"artifact must be a FrozenTables (export.freeze) or "
          f"ServeArtifact (export.load), got {type(artifact)!r}")
    self.model = model
    self.plan = plan
    self.mesh = mesh
    self.axis_name = axis_name
    self.meta = artifact.meta
    self.quantize = artifact.quantize
    # the TRAIN step the served rows were exported at — the serving
    # watermark. A DeltaSubscriber advances it (under `lock`) with each
    # promoted delta, so operators/chaos can ask a live engine "whose
    # training state am I serving" without touching the pubdir.
    self.step = int(getattr(artifact, "step", 0))  # guarded-by: lock [writes]
    self.with_metrics = with_metrics
    self.donate_batch = donate_batch
    # where this engine's gather/combine stage observations land when
    # no flight recorder is installed — threaded through like
    # FleetRouter's, so one registry can hold the WHOLE serve/stage_s
    # taxonomy (wire the batcher's registry here for that)
    self.telemetry = telemetry if telemetry is not None \
        else _get_registry()
    self._steps: Dict[Any, Any] = {}  # guarded-by: lock
    # The promote point (streaming deltas): dispatch holds this lock for
    # the brief host-side dispatch window, and a DeltaSubscriber holds
    # it while SWAPPING the serve state references — so a swap lands
    # between dispatches, never inside one. Re-entrant so a wrapper
    # (translate-then-dispatch) can hold it across both.
    self.lock = threading.RLock()

    self.tplan: Optional[ServeTierPlan] = None
    self.prefetcher = None
    if host_images:
      from ..tiering import HostTierStore, TieredPrefetcher
      from .export import np_dtype_of
      self.tplan = ServeTierPlan(plan, self.meta,
                                 tier_config or ServeTierConfig())
      store = HostTierStore(self.tplan, dtype=np_dtype_of(self.quantize))
      for name, images in host_images.items():
        for r, img in enumerate(images):
          store.set_image(name, r, img)
      store.warm_start({n: ranking[n] for n in host_images})
      self.store = store
      self.prefetcher = TieredPrefetcher(self.tplan, store, mesh,
                                         axis_name)
      state["serve"].update(store.build_fused(mesh, axis_name))
    self.state = state  # guarded-by: lock

  @property
  def tiered(self) -> bool:
    return self.prefetcher is not None

  def _step_for(self, batch_example, s_eff=None):  # requires-lock: lock
    numerical, cats = batch_example
    key = (numerical.shape, tuple(np.shape(c) for c in cats),
           tuple(sorted(s_eff.items())) if s_eff else None)
    step = self._steps.get(key)
    if step is None:
      step = make_serve_step(
          self.model, self.plan, self.meta, self.mesh, self.state,
          batch_example, axis_name=self.axis_name,
          tier_specs=self.tplan.tier_specs if self.tiered else None,
          with_metrics=self.with_metrics,
          donate_batch=self.donate_batch)
      self._steps[key] = step
    return step

  def dispatch(self, numerical, cats):
    """One device dispatch; returns device predictions WITHOUT blocking
    (jax async dispatch — the next dispatch's classify/stage overlaps
    this one's device work). With ``with_metrics`` on a tiered plan,
    returns ``(preds, metrics)``.

    Runs under :attr:`lock`: a concurrent delta promotion swaps the
    serve state references only between dispatches, so one dispatch
    always sees one consistent (images, resident maps, buffers)
    snapshot — the in-flight device work itself holds references to the
    old arrays and is never disturbed."""
    with self.lock:
      cats = tuple(np.asarray(c) for c in cats)
      numerical = np.asarray(numerical)
      if self.tiered:
        # the serve pipeline's stage taxonomy (flight recorder /
        # serve/stage_s histograms): classify+stage+upload is `gather`,
        # the jitted step launch is `combine`
        with _flight.stage("gather", registry=self.telemetry):
          staged = self.prefetcher.prepare(list(cats))
      else:
        staged = None
      step = self._step_for((numerical, cats),
                            staged.s_eff if staged else None)
      bt = shard_batch((numerical, cats), self.mesh, self.axis_name)
      with _flight.stage("combine", registry=self.telemetry):
        if staged is not None:
          return step(self.state, staged.device, *bt)
        return step(self.state, *bt)

  def predict(self, numerical, cats):
    """Blocking convenience wrapper: numpy predictions."""
    out = self.dispatch(numerical, cats)
    if self.with_metrics and self.tiered:
      preds, metrics = out
      return np.asarray(preds), jax.tree_util.tree_map(np.asarray, metrics)
    return np.asarray(out)
