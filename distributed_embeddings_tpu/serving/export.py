"""Frozen-table artifact export: train state -> inference image.

The packed training buffers carry ``1 + n_aux`` lanes per logical row —
the table row plus its interleaved optimizer state (`ops/packed_table`).
Serving gathers never touch the aux lanes, yet every serve-time gather
of the training image moves them (2x the bytes for adagrad, 3x for
adam) and every byte of HBM they occupy is a row the hot cache cannot
hold. :func:`freeze` strips them into a contiguous **inference image**:

- **f32**: the packed layout with ``n_aux=0`` — same physical-row
  machinery (128-lane rows, sub-row packing), just denser: a width-16
  adagrad class goes from 4 to 8 logical rows per physical row.
- **int8**: per-row symmetric quantization. Each logical row stores
  ``width`` int8 lanes ``q = round(row / scale)`` with
  ``scale = max|row| / 127`` — plus the row's f32 scale bit-packed into
  4 trailing int8 lanes, mirroring the fp8 wire's amax-scale trick
  (`parallel/wire.py`): the scale travels WITH the row, so the serve
  gather dequantizes in one fused multiply with no second lookup. The
  per-row dequantization error is bounded by ``scale / 2 =
  max|row| / 254 < 2^-7 * max|row|``.
- **fp8**: the wire format (`float8_e4m3fn`) as ROW STORAGE — same
  bytes-per-row as int8 (``width`` single-byte lanes + the f32 scale in
  4 trailing fp8 lanes), but the rounding grid is logarithmic: rows are
  scaled so ``max|row|`` maps to the largest finite e4m3 value (448)
  and cast, so small-magnitude elements keep ~2 significant digits
  where int8's uniform grid flushes them toward zero. The per-element
  error is bounded by ``2^-4 * max|row|`` (3 mantissa bits), looser at
  the top of the range than int8's ``2^-7 * max|row|`` — which of the
  two serves a given model better is a real-TPU pricing question
  (ROADMAP); both ride the same gather + fused-dequant path.

Both forms ride :class:`~..ops.packed_table.PackedLayout` (its pack /
gather arithmetic is dtype-agnostic — for int8 the "lanes" are bytes),
so the serve engine reuses the row-bound gather path unchanged.

Artifact format — a directory written through the checkpoint layer's
durable protocol (every file fsynced, per-file crc32+size table in a
manifest written LAST, atomic rename; ``checkpoint.verify`` validates
it):

    manifest.json                      'serve' section: quantize mode +
                                       per-class geometry; plan
                                       fingerprint; step
    serve_<class>_r<rank>.npy          device-tier stripped packed blocks
    serve_cold_<class>_r<rank>.npy     host-tier stripped images
    serve_ranking.npz                  per host-tier class/rank: serve
                                       physical rows by export-time
                                       observed-count priority (seeds
                                       the serve cache's resident set),
                                       plus the per-serve-physical-row
                                       observed counts themselves
                                       (``counts/<class>/r<rank>`` —
                                       the fleet plan's hot-rank
                                       replication signal)
    dense.npz / emb_dense.npz          model params + MXU-dense tables
                                       (small by definition; kept f32)

Export is a single-controller operation (the serving pods load the
artifact read-only); multi-controller exports are refused.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..checkpoint import (
    _crc32_file,
    _flatten_with_paths,
    _fsync_path,
    _plan_fingerprint,
    publish_manifest_last,
)
from ..checkpoint import verify as verify_dir
from ..layers.dist_model_parallel import hybrid_partition_specs
from ..layers.planner import DistEmbeddingStrategy
from ..ops.packed_table import PackedLayout, SparseRule
from ..parallel.lookup_engine import (
    DistributedLookup,
    class_param_name,
    padded_rows,
)
from ..resilience import faultinject

SERVE_FORMAT_VERSION = 1

# trailing single-byte lanes per logical row carrying the row's f32
# scale (4 bytes bitcast into 4 byte-wide lanes — the fp8 wire's trick
# at row granularity; int8 and fp8 rows pack it identically)
INT8_SCALE_LANES = 4

QUANTIZE_MODES = ("f32", "int8", "fp8")

# largest finite float8_e4m3fn value — fp8 rows are scaled so the row's
# amax lands exactly here (the same normalization as the fp8 wire's
# per-block scale, parallel/wire.py)
FP8_MAX = 448.0


def fp8_dtype() -> np.dtype:
  """The float8_e4m3fn numpy dtype (via ml_dtypes, jax's own dep)."""
  import ml_dtypes
  return np.dtype(ml_dtypes.float8_e4m3fn)


def np_dtype_of(quantize: str) -> np.dtype:
  """Element dtype of a serve image under one quantize mode."""
  if quantize == "int8":
    return np.dtype(np.int8)
  if quantize == "fp8":
    return fp8_dtype()
  return np.dtype(np.float32)


@dataclasses.dataclass(frozen=True)
class ServeClassMeta:
  """Geometry of one sparse class's inference image."""

  name: str
  rows: int           # logical rows (= padded_rows of the class)
  width: int          # table width (f32 output lanes after dequant)
  tier: str           # 'device' | 'host'
  quantize: str       # 'f32' | 'int8'
  # The training layout's rows-per-physical-row when the train rule
  # interleaved aux lanes into narrow rows. The eval step's multi-hot
  # combine on such classes sums window-MASKED physical rows and folds
  # the rpp windows per bag — a specific fp-addition grouping — and the
  # f32 serve path replicates that grouping to stay BIT-exact against
  # eval (engine._combine_masked_order). 1 = the generic h-axis sum.
  combine_rpp: int = 1

  @property
  def lanes(self) -> int:
    """byte lanes (int8/fp8) or f32 lanes per stored logical row."""
    return self.width + (INT8_SCALE_LANES
                         if self.quantize in ("int8", "fp8") else 0)

  @property
  def packed(self) -> PackedLayout:
    """Physical layout of the inference image (lane unit = element)."""
    return PackedLayout(rows=self.rows, width=self.lanes, n_aux=0)

  @property
  def np_dtype(self):
    return np_dtype_of(self.quantize)

  def to_disk(self, arr: np.ndarray) -> np.ndarray:
    """On-disk byte view: fp8 arrays persist viewed as int8 (np.load
    round-trips ml_dtypes as an opaque void dtype otherwise)."""
    return arr.view(np.int8) if self.quantize == "fp8" else arr

  def from_disk(self, arr: np.ndarray) -> np.ndarray:
    """Inverse of :meth:`to_disk` (also re-types the void-dtype form)."""
    if self.quantize == "fp8":
      return np.asarray(arr).view(fp8_dtype())
    return arr

  def to_json(self) -> Dict[str, Any]:
    lay = self.packed
    return {"rows": self.rows, "width": self.width, "tier": self.tier,
            "quantize": self.quantize, "combine_rpp": self.combine_rpp,
            "phys_rows": lay.phys_rows, "phys_width": lay.phys_width,
            "dtype": str(np.dtype(self.np_dtype))}

  @classmethod
  def from_json(cls, name: str, d: Dict[str, Any]) -> "ServeClassMeta":
    return cls(name=name, rows=int(d["rows"]), width=int(d["width"]),
               tier=d["tier"], quantize=d["quantize"],
               combine_rpp=int(d.get("combine_rpp", 1)))


def serve_layout(meta: ServeClassMeta) -> PackedLayout:
  """The inference image's :class:`PackedLayout` (alias of
  ``meta.packed``, exported for callers building layouts dicts)."""
  return meta.packed


# ---------------------------------------------------------------------------
# int8 row codec
# ---------------------------------------------------------------------------


def quantize_rows_int8(table: np.ndarray) -> np.ndarray:
  """``[N, w]`` f32 rows -> ``[N, w + 4]`` int8 rows-with-scale.

  Symmetric per-row quantization: ``scale = max|row| / 127`` (1.0 for
  all-zero rows — nothing to quantize), ``q = clip(round(row / scale),
  -127, 127)``, the f32 scale bitcast into the 4 trailing int8 lanes.
  ``|row - q * scale| <= scale / 2 < 2^-7 * max|row|`` per element."""
  table = np.asarray(table, np.float32)
  amax = np.max(np.abs(table), axis=1)
  scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
  q = np.clip(np.rint(table / scale[:, None]), -127, 127).astype(np.int8)
  lanes = scale.view(np.uint8).reshape(-1, INT8_SCALE_LANES).view(np.int8)
  return np.concatenate([q, lanes], axis=1)


def dequantize_rows_int8(qrows: np.ndarray) -> np.ndarray:
  """Inverse of :func:`quantize_rows_int8` (host-side; the device path
  fuses this into the gather, `engine._dequant_rows`)."""
  q = qrows[:, :-INT8_SCALE_LANES].astype(np.float32)
  scale = np.ascontiguousarray(
      qrows[:, -INT8_SCALE_LANES:]).view(np.uint8).view(
          np.float32).reshape(-1)
  return q * scale[:, None]


def quantize_rows_fp8(table: np.ndarray) -> np.ndarray:
  """``[N, w]`` f32 rows -> ``[N, w + 4]`` fp8 rows-with-scale.

  Per-row amax scaling onto the e4m3 grid: ``scale = max|row| / 448``
  (1.0 for all-zero rows), elements cast to ``float8_e4m3fn`` after the
  divide — the row's amax lands exactly on the largest finite value, so
  nothing saturates — and the f32 scale bitcast into the 4 trailing fp8
  lanes. ``|row - deq| <= 2^-4 * max|row|`` per element (3 mantissa
  bits; the fp8 wire's bound at row granularity)."""
  f8 = fp8_dtype()
  table = np.asarray(table, np.float32)
  amax = np.max(np.abs(table), axis=1)
  scale = np.where(amax > 0, amax / FP8_MAX, 1.0).astype(np.float32)
  q = (table / scale[:, None]).astype(f8)
  lanes = scale.view(np.uint8).reshape(-1, INT8_SCALE_LANES).view(f8)
  return np.concatenate([q, lanes], axis=1)


def dequantize_rows_fp8(qrows: np.ndarray) -> np.ndarray:
  """Inverse of :func:`quantize_rows_fp8` (host-side form)."""
  q = qrows[:, :-INT8_SCALE_LANES].astype(np.float32)
  scale = np.ascontiguousarray(
      qrows[:, -INT8_SCALE_LANES:]).view(np.uint8).view(
          np.float32).reshape(-1)
  return q * scale[:, None]


def quantize_rows(table: np.ndarray, quantize: str) -> np.ndarray:
  """Dispatch one mode's row codec (f32 passes through)."""
  if quantize == "int8":
    return quantize_rows_int8(table)
  if quantize == "fp8":
    return quantize_rows_fp8(table)
  return np.ascontiguousarray(table, np.float32)


# ---------------------------------------------------------------------------
# freeze: train state -> host-side inference blocks
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FrozenTables:
  """Host-side inference image of one train state (see :func:`freeze`)."""

  quantize: str
  step: int
  meta: Dict[str, ServeClassMeta]
  device_blocks: Dict[str, List[np.ndarray]]  # per rank, serve layout
  host_images: Dict[str, List[np.ndarray]]    # per rank, serve layout
  ranking: Dict[str, List[np.ndarray]]        # per rank, serve phys rows
  dense: Any                                  # np-leaved pytrees
  emb_dense: Any
  # per host-tier class/rank: observed counts re-binned per SERVE
  # physical row (the ranking's raw signal — rides the artifact so a
  # FleetPlan can weigh rank popularity without the training run)
  counts: Dict[str, List[np.ndarray]] = dataclasses.field(
      default_factory=dict)


def _strip_block(train_lay: PackedLayout, meta: ServeClassMeta,
                 block: np.ndarray) -> np.ndarray:
  """One rank's packed TRAIN block -> its serve block: unpack (a pure
  reshape — the aux lanes fall away), optionally quantize, re-pack into
  the denser serve layout."""
  tbl, _aux = train_lay.unpack(np.asarray(block))
  rows = quantize_rows(np.ascontiguousarray(tbl, np.float32),
                       meta.quantize)
  return np.asarray(meta.packed.pack(rows), meta.np_dtype)


def _serve_grp_counts(meta: ServeClassMeta, train_lay: PackedLayout,
                      counts: np.ndarray) -> np.ndarray:
  """Training observed counts (per TRAIN physical row) re-binned per
  SERVE physical row. Counts spread uniformly over the train row's
  logical rows and re-sum per serve physical row (the two layouts pack
  different logical spans per row)."""
  rpp_t = train_lay.rows_per_phys
  sl = meta.packed
  logical = np.repeat(np.asarray(counts, np.int64), rpp_t)[:meta.rows]
  pad = sl.phys_rows * sl.rows_per_phys - meta.rows
  if pad:
    logical = np.concatenate([logical, np.zeros((pad,), np.int64)])
  return logical.reshape(sl.phys_rows, sl.rows_per_phys).sum(axis=1)


def _serve_ranking(per_grp: np.ndarray) -> np.ndarray:
  """Serve-physical-row counts -> rows in descending-priority order;
  ties break lowest row first, matching the store's default warm
  start."""
  return np.argsort(-per_grp, kind="stable").astype(np.int32)


def _to_host_tree(tree):
  from ..checkpoint import _to_host
  return jax.tree_util.tree_map(_to_host, tree)


def serve_class_meta(plan: DistEmbeddingStrategy, rule: SparseRule,
                     quantize: str, tiered_names=frozenset()):
  """Per sparse class: its :class:`ServeClassMeta` and the
  full-vocabulary TRAIN layout its rows strip from.

  The ONE place serve geometry is derived from a plan — :func:`freeze`
  (full export) and the streaming ``DeltaPublisher`` both consume this,
  which is what guarantees a delta row and a full re-export of the same
  logical row are byte-identical."""
  meta: Dict[str, ServeClassMeta] = {}
  full_lays: Dict[str, PackedLayout] = {}
  for key in plan.class_keys:
    cp = plan.classes[key]
    if cp.kind != "sparse":
      continue
    name = class_param_name(*key)
    rows = padded_rows(plan, key)
    # the full-vocabulary train layout: for tiered classes the device
    # buffer is compact, but the stripped image covers the whole class
    # (the host image is the authoritative copy)
    full_lay = PackedLayout(rows=rows, width=cp.width, n_aux=rule.n_aux)
    full_lays[name] = full_lay
    meta[name] = ServeClassMeta(
        name=name, rows=rows, width=cp.width,
        tier="host" if name in tiered_names else "device",
        quantize=quantize,
        combine_rpp=(full_lay.rows_per_phys
                     if rule.n_aux and full_lay.rows_per_phys > 1 else 1))
  return meta, full_lays


def freeze(plan: DistEmbeddingStrategy, rule: SparseRule,
           state: Dict[str, Any], quantize: str = "f32",
           store=None) -> FrozenTables:
  """Strip a fused train state into host-side inference blocks.

  Args:
    rule: the TRAINING rule (its ``n_aux`` defines the aux lanes being
      stripped; no optimizer math runs here).
    quantize: ``'f32'`` (stripped, full precision — bit-exact serving)
      or ``'int8'`` (per-row symmetric quantization with packed scales).
      Applies to sparse-kind classes; MXU-dense tables and the model's
      dense params stay f32 (small by definition — the quantization win
      lives in the row-gather bytes).
    store: the run's ``HostTierStore`` for tiered plans (flushed first;
      cold images strip rank-by-rank and the observed counts become the
      serve cache's priority ranking).
  """
  if quantize not in QUANTIZE_MODES:
    raise ValueError(f"unknown quantize mode {quantize!r}; "
                     f"have {list(QUANTIZE_MODES)}")
  if store is None and plan.host_tier_class_keys():
    raise ValueError(
        "plan has host-tier classes but no HostTierStore was passed: "
        "the cold images hold the authoritative majority of the rows. "
        "Pass the run's store via freeze(..., store=store).")
  engine = DistributedLookup(plan)
  layouts = engine.fused_layouts(
      rule, rows_overrides=store.tplan.rows_overrides if store else None)
  tiered_names = frozenset(store.tplan.tier_specs) if store is not None \
      else frozenset()
  if store is not None:
    if not store.owns_all:
      raise NotImplementedError(
          "freeze/export is a single-controller operation (the serving "
          "pods load the artifact read-only); a rank-owner-sharded "
          "store cannot supply every rank's image here.")
    store.flush(state["fused"])

  meta, full_lays = serve_class_meta(plan, rule, quantize, tiered_names)
  device_blocks: Dict[str, List[np.ndarray]] = {}
  host_images: Dict[str, List[np.ndarray]] = {}
  ranking: Dict[str, List[np.ndarray]] = {}
  grp_counts: Dict[str, List[np.ndarray]] = {}
  for name, m in meta.items():
    full_lay = full_lays[name]
    if m.tier == "host":
      host_images[name] = [
          _strip_block(full_lay, m, store.images[name][r])
          for r in range(plan.world_size)]
      grp_counts[name] = [
          _serve_grp_counts(m, full_lay, store.counts[name][r])
          for r in range(plan.world_size)]
      ranking[name] = [_serve_ranking(c) for c in grp_counts[name]]
    else:
      arr = state["fused"][name]
      if isinstance(arr, jax.Array) and not arr.is_fully_addressable:
        raise NotImplementedError(
            "freeze/export indexes the global fused buffers and requires "
            "fully-addressable arrays (single-controller); run the "
            "export on a single-controller restore of the checkpoint.")
      lay = layouts[name]
      # one rank block at a time: peak host memory is one train block
      # plus its serve block, never the class
      device_blocks[name] = [
          _strip_block(lay, m, np.asarray(jax.device_get(
              arr[r * lay.phys_rows:(r + 1) * lay.phys_rows])))
          for r in range(plan.world_size)]

  from ..checkpoint import _to_host
  return FrozenTables(
      quantize=quantize, step=int(_to_host(state["step"])), meta=meta,
      device_blocks=device_blocks, host_images=host_images,
      ranking=ranking, dense=_to_host_tree(state["dense"]),
      emb_dense=_to_host_tree(state["emb_dense"]), counts=grp_counts)


def place_state(state: Dict[str, Any], mesh=None,
                axis_name: str = "mp") -> Dict[str, Any]:
  """Device placement for a serve state dict: ``mp_table_*`` 2-D leaves
  shard ``P(axis, None)`` (serve buffers, MXU-dense tables), everything
  else replicates."""
  if mesh is None:
    return jax.tree_util.tree_map(jnp.asarray, state)
  specs = hybrid_partition_specs(state, axis_name)
  return jax.tree_util.tree_map(
      lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), state, specs)


def frozen_device_state(frozen: FrozenTables, plan: DistEmbeddingStrategy,
                        mesh=None, axis_name: str = "mp") -> Dict[str, Any]:
  """Build the serve state dict from in-memory frozen blocks (the
  export-free path — tests, the jaxpr audit, single-process serving).
  Tiered classes' compact device buffers are NOT built here; that is
  :class:`~.engine.ServeEngine`'s job (it owns the serve cache)."""
  serve = {name: np.concatenate(blocks)
           for name, blocks in frozen.device_blocks.items()}
  return place_state(
      {"dense": frozen.dense, "emb_dense": frozen.emb_dense,
       "serve": serve}, mesh, axis_name)


# ---------------------------------------------------------------------------
# durable artifact write / read
# ---------------------------------------------------------------------------


def vocab_snapshot(vocab):
  """Normalize a ``vocab`` argument to the serializable read-only form:
  a live ``dynvocab.DynVocabTranslator`` is snapshotted (mapping only),
  a ``ReadonlyIdTranslator`` passes through."""
  from ..dynvocab import ReadonlyIdTranslator
  if vocab is None or isinstance(vocab, ReadonlyIdTranslator):
    return vocab
  return ReadonlyIdTranslator.from_translator(vocab)


def export(path: str, plan: DistEmbeddingStrategy, rule: SparseRule,
           state: Dict[str, Any], quantize: str = "f32", store=None,
           extra: Optional[Dict[str, Any]] = None,
           vocab=None) -> FrozenTables:
  """Freeze the train state and write the serve artifact at ``path``.

  Rides the checkpoint durability protocol: every file fsynced, per-file
  crc32+size table in a manifest written LAST (``serve`` section carries
  the quantize mode and per-class geometry), atomic rename. A crash at
  any point leaves either a manifest-less ``.tmp`` (detectably
  incomplete) or a complete artifact; ``checkpoint.verify`` validates a
  published one. Returns the frozen blocks (callers that serve from the
  exporting process can skip the read-back).

  ``vocab``: for dynamic-vocabulary (``oov='allocate'``) trainers, the
  run's ``DynVocabTranslator`` (or an already-taken
  ``ReadonlyIdTranslator`` snapshot). The read-only raw-id -> row
  mapping rides the artifact as ``vocab_snapshot.npz`` + a
  ``vocab_snapshot`` manifest section, making the serve artifact
  SELF-CONTAINED: the serving process translates request ids against
  the exact id space the exported rows were trained under."""
  if jax.process_count() > 1:
    raise NotImplementedError(
        "export is a single-controller operation: the serving pods load "
        "the artifact read-only. Save a checkpoint from the "
        "multi-controller run and export from a single-controller "
        "restore.")
  snap = vocab_snapshot(vocab)
  frozen = freeze(plan, rule, state, quantize=quantize, store=store)

  tmp = path + ".tmp"
  if os.path.exists(tmp):
    import shutil
    shutil.rmtree(tmp)
  os.makedirs(tmp)
  checksums: Dict[str, Dict[str, int]] = {}

  def _seal(fpath: str) -> None:
    _fsync_path(fpath)
    faultinject.fire("ckpt_write", path=fpath)
    checksums[os.path.basename(fpath)] = _crc32_file(fpath)

  for name, blocks in sorted(frozen.device_blocks.items()):
    for r, block in enumerate(blocks):
      fpath = os.path.join(tmp, f"serve_{name}_r{r}.npy")
      np.save(fpath, frozen.meta[name].to_disk(block))
      _seal(fpath)
  for name, images in sorted(frozen.host_images.items()):
    for r, image in enumerate(images):
      fpath = os.path.join(tmp, f"serve_cold_{name}_r{r}.npy")
      np.save(fpath, frozen.meta[name].to_disk(image))
      _seal(fpath)
  if frozen.ranking:
    fpath = os.path.join(tmp, "serve_ranking.npz")
    arrays = {f"{name}/r{r}": order
              for name, orders in sorted(frozen.ranking.items())
              for r, order in enumerate(orders)}
    # the raw per-serve-physical-row counts ride alongside the derived
    # order (extra keys — old readers ignore them): the fleet planner's
    # hot-rank replication weights come from exactly these
    arrays.update({f"counts/{name}/r{r}": cnt
                   for name, cnts in sorted(frozen.counts.items())
                   for r, cnt in enumerate(cnts)})
    np.savez(fpath, **arrays)
    _seal(fpath)
  for part, tree in (("dense", frozen.dense),
                     ("emb_dense", frozen.emb_dense)):
    fpath = os.path.join(tmp, f"{part}.npz")
    np.savez(fpath, **_flatten_with_paths(tree))
    _seal(fpath)
  if snap is not None:
    fpath = os.path.join(tmp, "vocab_snapshot.npz")
    np.savez(fpath, **snap.state_arrays())
    _seal(fpath)

  manifest: Dict[str, Any] = {
      "format_version": SERVE_FORMAT_VERSION,
      "kind": "serve",
      "step": frozen.step,
      "rule": {"name": rule.name, "n_aux": rule.n_aux},
      "plan": _plan_fingerprint(plan),
      "serve": {
          "quantize": quantize,
          "classes": {n: m.to_json() for n, m in sorted(frozen.meta.items())},
      },
      "checksums": checksums,
  }
  if snap is not None:
    manifest["vocab_snapshot"] = snap.manifest_section()
  if extra is not None:
    manifest["extra"] = extra
  publish_manifest_last(tmp, path, manifest)
  return frozen


@dataclasses.dataclass
class ServeArtifact:
  """A loaded serve artifact, device-placed where that is unambiguous.

  ``state`` holds ``{'dense', 'emb_dense', 'serve'}`` with the
  device-tier classes' inference buffers in ``'serve'``; host-tier
  classes appear in ``host_images``/``ranking`` instead and become the
  serve cache + cold store when a :class:`~.engine.ServeEngine` is built
  on this artifact. ``vocab`` is the exported
  ``dynvocab.ReadonlyIdTranslator`` snapshot (None for static-vocab
  artifacts) — translate request raw ids through it before dispatch.

  **Owner-sharded form** (``load(owned_ranks=...)``): only the named
  ranks' blocks are materialized, host-side — ``rank_blocks`` holds the
  device-tier classes' serve-layout blocks per owned rank,
  ``host_images``/``ranking``/``counts`` carry ``None`` at un-owned
  ranks, and ``state['serve']`` is empty (a partial artifact cannot
  assemble the global device buffers; the fleet owner serves per-rank
  gathers from the host blocks instead). :meth:`rank_block` is the one
  access path and refuses un-owned ranks naming the rank."""

  quantize: str
  step: int
  meta: Dict[str, ServeClassMeta]
  state: Dict[str, Any]
  host_images: Dict[str, List[np.ndarray]]
  ranking: Dict[str, List[np.ndarray]]
  vocab: Any = None
  # observed counts per serve physical row (host-tier classes; empty
  # lists/zeros for artifacts exported before the counts rode along)
  counts: Dict[str, List[np.ndarray]] = dataclasses.field(
      default_factory=dict)
  # owner-sharded load only: class name -> {rank: serve-layout block}
  rank_blocks: Dict[str, Dict[int, np.ndarray]] = dataclasses.field(
      default_factory=dict)
  owned_ranks: Optional[tuple] = None  # None = full artifact

  def rank_block(self, name: str, rank: int) -> np.ndarray:
    """One rank's serve-layout block of one class, host-side
    ``[phys_rows, phys_width]`` (element dtype per the quantize mode).
    On an owner-sharded artifact, asking for an un-owned rank raises
    naming the rank — the fleet routing tier must send that gather to
    the rank's owner, never read a block this process does not hold."""
    m = self.meta.get(name)
    if m is None:
      raise KeyError(f"unknown serve class {name!r}; artifact has "
                     f"{sorted(self.meta)}")
    if self.owned_ranks is not None and rank not in self.owned_ranks:
      raise ValueError(
          f"class {name!r} rank {rank} is not owned by this artifact "
          f"(owned_ranks={self.owned_ranks}): an owner-sharded serve "
          "store materializes only its ranks' blocks — route the gather "
          "to the owning process (fleet.FleetRouter does).")
    if m.tier == "host":
      img = self.host_images[name][rank]
      if img is None:
        raise ValueError(
            f"class {name!r} rank {rank} image was not loaded "
            f"(owned_ranks={self.owned_ranks})")
      return img
    if self.owned_ranks is not None:
      return self.rank_blocks[name][rank]
    # full artifact: slice the (host-fetched) global device buffer
    lay = self.meta[name].packed
    return np.asarray(
        self.state["serve"][name][rank * lay.phys_rows:
                                  (rank + 1) * lay.phys_rows])


def _unflatten_paths(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
  """Path-keyed npz dict -> nested plain dict (serve states carry no
  optimizer pytrees, so plain dicts reproduce the structure)."""
  out: Dict[str, Any] = {}
  for key in sorted(flat):
    parts = key.split("/")
    d = out
    for p in parts[:-1]:
      d = d.setdefault(p, {})
    d[parts[-1]] = flat[key]
  return out


def load(path: str, plan: DistEmbeddingStrategy, mesh=None,
         axis_name: str = "mp",
         verify_integrity: bool = True,
         owned_ranks=None) -> ServeArtifact:
  """Load a serve artifact written by :func:`export`.

  The plan must match the exporting run's exactly (fingerprint
  equality): serve artifacts do not re-shard elastically under this
  loader — re-export from the checkpoint under the new plan, or re-cut
  the published artifact serve-side with ``fleet.reshard`` (the elastic
  window-wise path, no trainer round-trip).

  ``owned_ranks``: the owner-sharded form — materialize ONLY the named
  mesh ranks' blocks (host-side numpy, no device placement of the serve
  buffers; ``state['serve']`` stays empty). The empty tuple loads
  manifest + dense parts + vocab only (what a routing tier needs). This
  is PR 6's elastic cold-store owner contract re-aimed at inference:
  each serving process holds its ranks, ``ServeArtifact.rank_block``
  refuses the rest naming the rank."""
  import json
  if verify_integrity and owned_ranks is None:
    problems = verify_dir(path)
    if problems:
      raise ValueError(
          f"serve artifact {path!r} failed integrity verification: "
          + "; ".join(problems))
  with open(os.path.join(path, "manifest.json")) as f:
    manifest = json.load(f)
  if manifest.get("kind") != "serve":
    raise ValueError(
        f"{path!r} is not a serve artifact (manifest kind "
        f"{manifest.get('kind')!r}); training checkpoints restore via "
        "checkpoint.restore")
  if manifest["format_version"] != SERVE_FORMAT_VERSION:
    raise ValueError(f"serve artifact format {manifest['format_version']} "
                     f"unsupported (expected {SERVE_FORMAT_VERSION})")
  want = _plan_fingerprint(plan)
  if manifest["plan"] != want:
    diff = sorted(k for k in set(manifest["plan"]) | set(want)
                  if manifest["plan"].get(k) != want.get(k))
    raise ValueError(
        "serve artifact plan does not match the current plan (differs "
        f"in {diff}): serve artifacts do not re-shard — re-export from "
        "the checkpoint under this plan.")

  meta = {n: ServeClassMeta.from_json(n, d)
          for n, d in manifest["serve"]["classes"].items()}
  world = plan.world_size
  if owned_ranks is not None:
    owned_ranks = tuple(sorted(set(int(r) for r in owned_ranks)))
    if owned_ranks and (owned_ranks[0] < 0 or owned_ranks[-1] >= world):
      raise ValueError(
          f"owned_ranks {owned_ranks} outside [0, {world}) — serve "
          "stores shard by MESH rank, not process index")
  owned = set(range(world)) if owned_ranks is None else set(owned_ranks)

  if verify_integrity and owned_ranks is not None:
    # the partial-load contract extends to verification: crc32-read only
    # the files THIS process will open — an owner of two ranks of a
    # terabyte artifact must not scan every other owner's blocks
    needed = ["dense.npz", "emb_dense.npz"]
    if manifest.get("vocab_snapshot") is not None:
      needed.append("vocab_snapshot.npz")
    if any(m.tier == "host" for m in meta.values()):
      needed.append("serve_ranking.npz")
    for name, m in sorted(meta.items()):
      prefix = "serve_cold" if m.tier == "host" else "serve"
      needed.extend(f"{prefix}_{name}_r{r}.npy" for r in sorted(owned))
    problems = verify_dir(path, only=needed)
    if problems:
      raise ValueError(
          f"serve artifact {path!r} failed integrity verification: "
          + "; ".join(problems))

  serve: Dict[str, Any] = {}
  host_images: Dict[str, List[np.ndarray]] = {}
  ranking: Dict[str, List[np.ndarray]] = {}
  counts: Dict[str, List[np.ndarray]] = {}
  rank_blocks: Dict[str, Dict[int, np.ndarray]] = {}
  rank_npz = None
  if any(m.tier == "host" for m in meta.values()):
    with np.load(os.path.join(path, "serve_ranking.npz")) as z:
      # owned ranks' arrays only: a partial load must not materialize
      # every rank's ranking/counts
      rank_npz = {k: np.asarray(z[k]) for k in z.files
                  if int(k.rsplit("/r", 1)[1]) in owned}
  for name, m in sorted(meta.items()):
    lay = m.packed
    if m.tier == "host":
      host_images[name] = [
          m.from_disk(np.load(os.path.join(path,
                                           f"serve_cold_{name}_r{r}.npy")))
          if r in owned else None for r in range(world)]
      ranking[name] = [rank_npz[f"{name}/r{r}"] if r in owned else None
                      for r in range(world)]
      counts[name] = [
          (np.asarray(rank_npz[f"counts/{name}/r{r}"], np.int64)
           if f"counts/{name}/r{r}" in rank_npz
           else np.zeros((lay.phys_rows,), np.int64))
          if r in owned else None for r in range(world)]
      continue
    files = [os.path.join(path, f"serve_{name}_r{r}.npy")
             for r in range(world)]
    if owned_ranks is not None:
      # owner-sharded: host-side per-rank blocks only — no device
      # placement (the fleet owner answers host gathers off these)
      rank_blocks[name] = {r: m.from_disk(np.load(files[r]))
                           for r in range(world) if r in owned}
      continue
    shape = (world * lay.phys_rows, lay.phys_width)
    if mesh is None:
      serve[name] = jnp.asarray(np.concatenate(
          [m.from_disk(np.load(f)) for f in files]))
    else:
      sharding = NamedSharding(mesh, P(axis_name, None))

      def cb(index, files=files, lay=lay, m=m):
        rank = (index[0].start or 0) // lay.phys_rows
        # mmap: each device materializes exactly its rank block
        return m.from_disk(np.asarray(np.load(files[rank], mmap_mode="r")))

      serve[name] = jax.make_array_from_callback(shape, sharding, cb)

  for part in ("dense", "emb_dense"):
    with np.load(os.path.join(path, f"{part}.npz")) as z:
      flat = dict(z)
    tree = _unflatten_paths(flat)
    placed = place_state({part: tree}, mesh, axis_name)[part]
    if part == "dense":
      dense = placed
    else:
      emb_dense = placed
  vocab = None
  if manifest.get("vocab_snapshot") is not None:
    from ..dynvocab import ReadonlyIdTranslator
    with np.load(os.path.join(path, "vocab_snapshot.npz")) as z:
      vocab = ReadonlyIdTranslator.from_arrays(
          {k: np.asarray(v) for k, v in z.items()})
  state = {"dense": dense, "emb_dense": emb_dense, "serve": serve}
  return ServeArtifact(quantize=manifest["serve"]["quantize"],
                       step=int(manifest["step"]), meta=meta, state=state,
                       host_images=host_images, ranking=ranking,
                       vocab=vocab, counts=counts,
                       rank_blocks=rank_blocks, owned_ranks=owned_ranks)
