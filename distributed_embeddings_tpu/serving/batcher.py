"""Request micro-batcher: concurrent queries -> one padded device dispatch.

A serving device wants one big batch; users send many small concurrent
requests. The :class:`MicroBatcher` sits between them:

- **coalesce**: concurrent variable-size requests append to a FIFO; a
  flush packs whole requests (requests are never split) into one
  ``[max_batch, ...]`` dispatch, padding the tail with ``PAD_ID``
  categorical rows (the engine's hotness-padding sentinel — padded rows
  gather zero rows and their predictions are sliced off, never
  delivered).
- **deadline-or-full flush**: a flush fires when the packed rows reach
  ``max_batch`` (full) or the OLDEST pending request has waited
  ``max_delay_s`` (deadline) — the knob trading per-request latency
  against device efficiency. The padded dispatch shape is constant, so
  the serve step traces exactly once per batcher.
- **bounded queue, counted load-shed**: at most ``queue_rows`` rows may
  be pending; a request that would exceed the bound is REJECTED
  immediately (:class:`Rejected`, ``stats['rejected']`` counts it)
  instead of queueing into unbounded latency. Overload shows up as an
  explicit error rate at the edge — the only place it can be handled —
  not as a p99 that grew past every deadline.
- **pipelined completion**: the flusher thread hands the (asynchronous)
  device dispatch to a completer thread and immediately packs the next
  batch, so host-side packing and de-interleave overlap device compute;
  ``pipeline_depth`` bounds the in-flight dispatches.

De-interleave is positional: request k's predictions are exactly rows
``[off_k, off_k + n_k)`` of the dispatch result — the property test
pins that every request gets its own rows back under random arrival
interleavings.

Telemetry: the counters live in a ``telemetry.MetricsRegistry``
(``stats`` is the classic dict view), per-request latency feeds the
``serve/latency_s`` histogram, and the pack/dispatch/complete stages
run under spans — on the flusher/completer threads, so an enabled trace
shows host packing overlapping device compute on separate tracks.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..parallel.lookup_engine import PAD_ID
from ..telemetry import MetricsRegistry, span as _span
from ..telemetry import flight as _flight
from ..telemetry import trace as _trace


REJECT_REASONS = ("queue_full", "deadline_expired", "priority_shed",
                  "flusher_died")


class Rejected(RuntimeError):
  """The request was shed — counted, never silently dropped.

  ``reason`` names the shed class (callers route their backoff on it):

  - ``'queue_full'``: the bounded queue had no room (and nothing of
    lower priority to evict);
  - ``'deadline_expired'``: the request's own deadline passed before a
    flush could dispatch it;
  - ``'priority_shed'``: a higher-priority request evicted this one
    from the full queue;
  - ``'flusher_died'``: the batcher's flusher or completer thread died
    of an unexpected exception — every queued request failed with this
    reason instead of hanging forever, the flight recorder tripped,
    and ``/healthz`` names the dead thread (the batcher is closed;
    rebuild it).

  Each reason has its own counter (``serve/rejected/<reason>``);
  ``serve/rejected`` stays the exact total."""

  def __init__(self, msg: str, reason: str = "queue_full"):
    super().__init__(msg)
    self.reason = reason


class ServeFuture:
  """Per-request handle: blocks on :meth:`result` until the dispatch
  carrying this request completes (or fails, re-raising here)."""

  def __init__(self, n: int):
    self.n = n
    # latency stamps, not stage timing: the delta feeds the telemetry
    # histogram; the flush deadline below needs the same clock
    self.t_submit = time.monotonic()  # graftlint: disable=GL113
    self.t_done: Optional[float] = None
    self._event = threading.Event()
    self._value: Optional[np.ndarray] = None
    self._error: Optional[BaseException] = None

  def _fulfill(self, value: np.ndarray) -> None:
    self.t_done = time.monotonic()  # graftlint: disable=GL113 (latency stamp)
    self._value = value
    self._event.set()

  def _fail(self, exc: BaseException) -> None:
    self.t_done = time.monotonic()  # graftlint: disable=GL113 (latency stamp)
    self._error = exc
    self._event.set()

  def done(self) -> bool:
    return self._event.is_set()

  def result(self, timeout: Optional[float] = None) -> np.ndarray:
    if not self._event.wait(timeout):
      raise TimeoutError("serve request still pending")
    if self._error is not None:
      raise self._error
    return self._value

  @property
  def latency_s(self) -> Optional[float]:
    """submit -> fulfill wall time (None while pending)."""
    return None if self.t_done is None else self.t_done - self.t_submit


class _Pending:
  __slots__ = ("numerical", "cats", "future", "priority", "deadline_s",
               "seq", "trace_id")

  def __init__(self, numerical, cats, future, priority=0,
               deadline_s=None, seq=0, trace_id=None):
    self.numerical = numerical
    self.cats = cats
    self.future = future
    self.priority = priority
    self.deadline_s = deadline_s  # absolute monotonic stamp, or None
    self.seq = seq
    self.trace_id = trace_id  # minted at admission when tracing is on

  def expired(self, now: float) -> bool:
    return self.deadline_s is not None and now >= self.deadline_s


class MicroBatcher:
  """Coalesce concurrent requests into padded fixed-shape dispatches.

  Args:
    dispatch_fn: ``dispatch_fn(numerical [max_batch, F], cats) ->
      preds`` — typically ``ServeEngine.dispatch``. May return a device
      array (completion materializes it on the completer thread, off
      the flush path); the result's leading axis must be ``max_batch``.
    max_batch: the dispatch batch (constant — one trace). Requests
      larger than this are rejected outright.
    max_delay_s: deadline the oldest pending request may wait before a
      partial flush fires.
    queue_rows: pending-row bound (default ``8 * max_batch``); the
      load-shed knob.
    pipeline_depth: max dispatches in flight (completer queue bound).
    start: start the flusher/completer threads (tests drive
      :meth:`flush_now` deterministically with ``start=False``).
    registry: the ``telemetry.MetricsRegistry`` the batcher's counters
      (``serve/submitted|rejected|batches|completed|padded_rows``) and
      request-latency histogram (``serve/latency_s``) live in. Default
      is a PRIVATE registry: the load-shed accounting contract is
      exactly-counted per batcher, and two batchers sharing names would
      merge counts. Pass ``telemetry.get_registry()`` to publish into
      the process-wide registry. ``stats`` stays the classic dict view.
    name: thread-name prefix (``<name>-flush`` / ``<name>-complete``),
      and therefore the key of the per-thread ``/healthz`` dead-thread
      gauges. Give each batcher SHARING a registry its own name, or a
      rebuild of one batcher cannot be told apart from its siblings on
      the readiness plane.

  Locking (threadlint-checked — the ``guarded-by`` annotations in
  ``__init__`` are the machine-readable form): ONE plain ``Lock``
  (``_lock``, with ``_nonempty = Condition(_lock)`` over it — holding
  either is holding both) protects all cross-thread state: the queue
  (``_pending``/``_pending_rows``/``_seq``), lifecycle
  (``_closed``/``_dead``/``_orphans``), the admission knobs
  (``queue_rows``/``max_delay_s``) and the ``dispatch_fn`` binding.
  ``_dead`` and ``dispatch_fn`` are locked-write/racy-read by design
  (set-once death flag; one binding captured per flush) — annotated
  ``[writes]``. The ``*_locked`` helpers carry ``requires-lock``
  contracts: callers hold ``_lock``. The in-flight handoff between
  flusher and completer is the (internally synchronized)
  ``_inflight`` queue, not the lock.
  """

  def __init__(self, dispatch_fn: Callable, max_batch: int,
               max_delay_s: float = 0.002,
               queue_rows: Optional[int] = None,
               pipeline_depth: int = 2,
               start: bool = True,
               registry: Optional[MetricsRegistry] = None,
               name: str = "serve-batcher"):
    if max_batch < 1:
      raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    self.dispatch_fn = dispatch_fn          # guarded-by: _lock [writes]
    self.max_batch = int(max_batch)
    self.max_delay_s = float(max_delay_s)   # guarded-by: _lock [writes]
    self.queue_rows = int(queue_rows) if queue_rows is not None \
        else 8 * self.max_batch             # guarded-by: _lock [writes]
    self._lock = threading.Lock()
    self._nonempty = threading.Condition(self._lock)
    self._pending: List[_Pending] = []      # guarded-by: _lock
    self._pending_rows = 0                  # guarded-by: _lock
    self._closed = False                    # guarded-by: _lock
    self.telemetry = registry if registry is not None else MetricsRegistry()
    self._counters = {k: self.telemetry.counter(f"serve/{k}")
                      for k in ("submitted", "rejected", "batches",
                                "completed", "padded_rows")}
    self._counters.update(
        {f"rejected/{r}": self.telemetry.counter(f"serve/rejected/{r}")
         for r in REJECT_REASONS})
    # arrival order (FIFO tie-break within a priority)
    self._seq = 0                           # guarded-by: _lock
    self._latency = self.telemetry.histogram("serve/latency_s")
    self._inflight: _queue.Queue = _queue.Queue(maxsize=max(1,
                                                           pipeline_depth))
    self._flusher: Optional[threading.Thread] = None
    self._completer: Optional[threading.Thread] = None
    # (thread name, exception) once a worker thread died unexpectedly;
    # written once under the lock, read racily (benign: set-once, and
    # every reader path is only reachable after the locked write)
    self._dead: Optional[tuple] = None      # guarded-by: _lock [writes]
    # requests a dying thread had already popped from a queue (neither
    # pending nor in-flight — they would be invisible to the drain)
    self._orphans: List[_Pending] = []      # guarded-by: _lock
    # a REBUILT batcher on the same registry supersedes the dead one
    # with the SAME name (the Rejected message says "rebuild the
    # batcher"): clear ITS OWN dead-thread gauges only — a still-dead
    # sibling batcher (distinct name=) must keep /healthz failing — and
    # re-derive the unkeyed aggregate from whatever keyed gauges remain
    from ..telemetry.http import DEAD_THREAD_GAUGE_STEM
    self._flush_name = f"{name}-flush"
    self._complete_name = f"{name}-complete"
    metrics = self.telemetry.metrics()
    for t in (self._flush_name, self._complete_name):
      key = f"{DEAD_THREAD_GAUGE_STEM}/{t}"
      if key in metrics:
        self.telemetry.gauge(key).set(0)
    if DEAD_THREAD_GAUGE_STEM in metrics:
      others = any(
          n.startswith(DEAD_THREAD_GAUGE_STEM + "/") and m.value
          for n, m in self.telemetry.metrics().items())
      self.telemetry.gauge(DEAD_THREAD_GAUGE_STEM).set(1 if others else 0)
    if start:
      self._flusher = threading.Thread(
          target=self._guarded_loop,
          args=(self._flush_name, self._flush_loop),
          name=self._flush_name, daemon=True)
      self._completer = threading.Thread(
          target=self._guarded_loop,
          args=(self._complete_name, self._complete_loop),
          name=self._complete_name, daemon=True)
      self._flusher.start()
      self._completer.start()

  # ---- worker-thread death (no request may hang forever) ------------------
  def _guarded_loop(self, name: str, loop: Callable) -> None:
    try:
      loop()
    except BaseException as e:  # noqa: BLE001 — the thread IS the engine
      # room: an escaped exception here used to kill the thread silently
      # and leave every queued waiter blocked forever
      self._on_worker_death(name, e)

  def _on_worker_death(self, name: str, exc: BaseException) -> None:
    """A flusher/completer thread died of an UNEXPECTED exception (a
    dispatch failure is expected and delivered per request; this is a
    bug in the batcher's own machinery or a monkey-wrenched callback).
    Queued requests would otherwise hang forever: fail every pending
    and in-flight request with a counted ``flusher_died`` shed, close
    the batcher, trip the flight recorder (via the shed path), and
    surface the dead thread through the gauge ``/healthz`` scans
    (``telemetry.http.DEAD_THREAD_GAUGE_STEM`` — visible when the
    batcher shares the probe's registry)."""
    from ..telemetry.http import DEAD_THREAD_GAUGE_STEM
    with self._nonempty:
      if self._dead is None:
        self._dead = (name, exc)
      self._closed = True
      pending = self._pending[:]
      self._pending.clear()
      self._pending_rows = 0
      # the swap must happen under the lock: the OTHER worker thread's
      # exception path appends orphans too, and a racy swap here could
      # strand its orphan forever (threadlint GL120 caught this)
      orphans, self._orphans = self._orphans, []
      self._nonempty.notify_all()
    self.telemetry.gauge(DEAD_THREAD_GAUGE_STEM).set(1)
    self.telemetry.gauge(f"{DEAD_THREAD_GAUGE_STEM}/{name}").set(1)
    # one shed count PER failed request (the exact-accounting contract)
    for p in pending + orphans:
      if not p.future.done():
        p.future._fail(self._dead_rejected())
    self._drain_inflight_dead()

  def _drain_inflight_dead(self) -> None:
    """Fail every dispatched-but-uncompleted in-flight item: their
    waiters block on the completer, which may be the thread that just
    died (and a flusher blocked on a full in-flight queue is unblocked
    by this). Called by the death handler AND by ``_dispatch`` after an
    enqueue that raced the handler's one-shot drain — idempotent
    (already-failed futures are skipped), so both draining is safe and
    no item can land in the queue after the last drain unseen."""
    _name, exc = self._dead
    items = []
    while True:
      try:
        item = self._inflight.get_nowait()
      except _queue.Empty:
        break
      if item is not None:
        items.append(item)
    try:
      self._inflight.put_nowait(None)  # stop the surviving loop thread
    except _queue.Full:
      pass
    for taken, _out, rec, _ctx, fr in items:
      for p in taken:
        if not p.future.done():
          p.future._fail(self._dead_rejected())
      if fr is not None and rec is not None:
        try:
          fr.end(rec, error=exc)
        except BaseException:  # noqa: BLE001 — a broken recorder may be
          pass  # WHY the thread died; it must not abort the drain and
          # strand the remaining items' waiters

  def _dead_rejected(self) -> Rejected:
    name, exc = self._dead
    return self._reject(
        "flusher_died",
        f"MicroBatcher thread {name!r} died: {exc!r} — the batcher is "
        "closed; queued requests were failed (counted "
        "serve/rejected/flusher_died) and /healthz reports the dead "
        "thread. Rebuild the batcher; re-submit with backoff.")

  @property
  def stats(self) -> Dict[str, int]:
    """The classic counter view (now registry-backed)."""
    return {k: c.value for k, c in self._counters.items()}

  def set_admission(self, queue_rows: Optional[int] = None,
                    max_delay_s: Optional[float] = None) -> None:
    """Adjust the admission knobs between flushes — the control plane's
    actuation hook (:class:`~..control.ControlPolicy` tightens
    ``queue_rows`` as recent latency approaches a deadline-class
    budget, so overload sheds at the edge BEFORE the queue melts into
    p99 blowout). Same locked-swap discipline as
    :meth:`set_dispatch_fn`: pending requests already admitted stay
    admitted — a tightened bound applies to arrivals, never
    retroactively sheds queued work."""
    with self._lock:
      if queue_rows is not None:
        if int(queue_rows) < self.max_batch:
          raise ValueError(
              f"queue_rows {queue_rows} < max_batch {self.max_batch}: "
              "the queue could never admit one full dispatch")
        self.queue_rows = int(queue_rows)
      if max_delay_s is not None:
        if max_delay_s <= 0:
          raise ValueError(f"max_delay_s must be > 0, got {max_delay_s}")
        self.max_delay_s = float(max_delay_s)
      self._nonempty.notify_all()

  def set_dispatch_fn(self, dispatch_fn: Callable) -> None:
    """Swap the dispatch binding between flushes (the streaming
    subscriber's rebase hook: re-point the batcher at a freshly loaded
    engine without stopping either thread). ``_dispatch`` captures the
    binding once per flush, so every flush runs entirely through one
    binding — the swap can never split a batch across two engines."""
    with self._lock:
      self.dispatch_fn = dispatch_fn

  # ---- submission ---------------------------------------------------------
  def _reject(self, reason: str, msg: str) -> Rejected:
    """Count one shed (total + per-reason) and build the exception —
    the load-shed accounting contract: every shed is exactly one total
    count and exactly one reason count.  A shed also trips the flight
    recorder (no-op when none is installed): overload is exactly the
    moment the last-N-requests bundle is worth having.  ``defer=True``
    because this runs under the batcher's one lock — the bundle's
    write+fsync must not stall every submitter at peak overload."""
    self._counters["rejected"].inc()
    self._counters[f"rejected/{reason}"].inc()
    _flight.flight_trip(f"shed/{reason}", defer=True)
    return Rejected(msg, reason=reason)

  def _evict_for_locked(self, n: int, priority: int) -> None:  # requires-lock: _lock
    """Make room for an incoming higher-priority request by shedding
    pending LOWER-priority requests — lowest priority first, youngest
    first within a priority (the request that waited longest keeps its
    place). Sheds only what the incoming rows need; sheds nothing if
    even shedding everything below ``priority`` cannot make room."""
    room = self.queue_rows - self._pending_rows
    victims = sorted((p for p in self._pending if p.priority < priority),
                     key=lambda p: (p.priority, -p.seq))
    chosen, freed = [], 0
    for p in victims:
      if room + freed >= n:
        break
      chosen.append(p)
      freed += p.future.n
    if room + freed < n:
      return
    for p in chosen:
      self._pending.remove(p)
      self._pending_rows -= p.future.n
      p.future._fail(self._reject(
          "priority_shed",
          f"request shed for priority-{priority} traffic (this request "
          f"is priority {p.priority}; the queue is full). Re-submit "
          "with backoff, or raise this caller's priority class."))

  def submit(self, numerical, cats: Sequence, priority: int = 0,
             deadline_s: Optional[float] = None) -> ServeFuture:
    """Enqueue one request of ``n = numerical.shape[0]`` rows
    (``1 <= n <= max_batch``). Returns its :class:`ServeFuture`; raises
    :class:`Rejected` — counted, with ``reason`` — when it cannot be
    queued.

    ``priority``: admission class (higher wins). Flushes pack pending
    requests highest-priority-first, and a full queue sheds
    lower-priority pending work to admit higher-priority arrivals —
    so p99.9 for priority traffic survives overload instead of queueing
    behind it. ``deadline_s``: seconds from now this request is worth
    dispatching; one that expires in the queue is shed
    (``deadline_expired``) instead of wasting a dispatch slot on an
    answer nobody is waiting for."""
    numerical = np.asarray(numerical)
    cats = [np.asarray(c) for c in cats]
    n = numerical.shape[0]
    if n < 1 or n > self.max_batch:
      raise ValueError(
          f"request rows {n} outside [1, max_batch={self.max_batch}] — "
          "split oversized queries client-side")
    fut = ServeFuture(n)
    with self._nonempty:
      if self._dead is not None:
        # a counted shed rides a counted submit attempt, like every
        # other reject path (accepted = submitted - rejected must not
        # go negative); plain closed below stays an un-counted error
        self._counters["submitted"].inc()
        raise self._dead_rejected()
      if self._closed:
        raise RuntimeError("MicroBatcher is closed")
      self._counters["submitted"].inc()
      if self._pending_rows + n > self.queue_rows:
        # expired occupants have no claim on the rows a live request
        # needs: purge them before rejecting or evicting live work
        self._purge_expired_locked()
      if self._pending_rows + n > self.queue_rows:
        # an arrival OUTRANKING pending work may evict it (the victim
        # filter is strict-lower-priority, so all-equal traffic no-ops)
        self._evict_for_locked(n, priority)
      if self._pending_rows + n > self.queue_rows:
        raise self._reject(
            "queue_full",
            f"serve queue full ({self._pending_rows} rows pending, bound "
            f"{self.queue_rows}): request shed. The device is saturated "
            "— back off client-side or raise queue_rows (which only "
            "trades the error for latency).")
      self._seq += 1
      deadline = None
      if deadline_s is not None:
        # absolute stamp on the flush clock (deadline arithmetic)
        deadline = fut.t_submit + float(deadline_s)
      # ADMISSION is where a request's trace identity is minted: the id
      # rides the dispatch context over the fleet wire, so every
      # process track a dispatch touches carries this request's id.
      # Minted only when tracing or the flight recorder is active — the
      # disabled path allocates nothing extra.
      trace_id = _trace.mint_id(8) \
          if (_trace.current_tracer() is not None
              or _flight.current_flight_recorder() is not None) else None
      self._pending.append(_Pending(numerical, cats, fut,
                                    priority=int(priority),
                                    deadline_s=deadline, seq=self._seq,
                                    trace_id=trace_id))
      self._pending_rows += n
      self._nonempty.notify()
    return fut

  # ---- flush policy -------------------------------------------------------
  def _purge_expired_locked(self) -> None:  # requires-lock: _lock
    """Shed pending requests whose own deadline passed — counted
    ``deadline_expired``; their waiters fail immediately instead of
    riding a dispatch whose answer is already too late."""
    now = time.monotonic()  # graftlint: disable=GL113 (deadline arithmetic)
    expired = [p for p in self._pending if p.expired(now)]
    for p in expired:
      self._pending.remove(p)
      self._pending_rows -= p.future.n
      p.future._fail(self._reject(
          "deadline_expired",
          f"request deadline passed after {now - p.future.t_submit:.4f}s "
          "in the serve queue — shed instead of dispatched late."))

  def _take_batch_locked(self) -> List[_Pending]:  # requires-lock: _lock
    """Pop whole requests while they fit in max_batch rows: highest
    priority first, FIFO within a priority (all-default-priority
    traffic keeps the classic FIFO order exactly). Expired requests
    are purged first — they never occupy dispatch rows (the inline
    ``flush_now`` path's purge; the flusher thread purges in its
    readiness check)."""
    self._purge_expired_locked()
    order = sorted(self._pending, key=lambda p: (-p.priority, p.seq))
    taken, rows = [], 0
    for p in order:
      if rows + p.future.n > self.max_batch:
        break
      self._pending.remove(p)
      rows += p.future.n
      taken.append(p)
    self._pending_rows -= rows
    return taken

  def _flush_ready_locked(self) -> bool:  # requires-lock: _lock
    # purge expired waiters HERE (they fail at their own deadline — the
    # wait timeout wakes the loop then) rather than treating expiry as
    # flush-readiness: an expired co-tenant must not force the live
    # requests into a premature, heavily padded dispatch
    self._purge_expired_locked()
    if not self._pending:
      return False
    now = time.monotonic()  # graftlint: disable=GL113 (deadline arithmetic)
    if self._pending_rows >= self.max_batch \
        or self._pending[0].future.n == self.max_batch:
      return True
    oldest = self._pending[0].future.t_submit
    # flush-deadline arithmetic against the submit stamps, not timing
    return (now - oldest) >= self.max_delay_s

  def _flush_loop(self) -> None:
    while True:
      with self._nonempty:
        while not self._flush_ready_locked() and not self._closed:
          if self._pending:
            now = time.monotonic()  # graftlint: disable=GL113 (deadline)
            wait = self.max_delay_s - (now
                                       - self._pending[0].future.t_submit)
            # a per-request deadline expiring BEFORE the flush deadline
            # must wake the loop then: its waiter fails at its own
            # deadline, not up to max_delay_s late
            for p in self._pending:
              if p.deadline_s is not None:
                wait = min(wait, p.deadline_s - now)
            self._nonempty.wait(timeout=max(wait, 0.0) + 1e-4)
          else:
            self._nonempty.wait(timeout=0.05)
        if self._closed and not self._pending:
          taken = None  # shutdown: deliver the completer sentinel below
        else:
          taken = self._take_batch_locked()
      if taken is None:
        # completer shutdown sentinel, outside the lock and death-aware:
        # after a completer death the handler owns sentinel delivery and
        # its own sentinel may hold the last queue slot — a plain
        # blocking put here wedged this thread forever (and close()'s
        # join for its full timeout)
        while True:
          with self._lock:
            if self._dead is not None:
              return
          try:
            self._inflight.put(None, timeout=0.05)
            return
          except _queue.Full:
            continue
      if taken:
        try:
          self._dispatch(taken)
        except BaseException:
          # already popped from pending: record the batch so the death
          # handler can fail its waiters (a dispatch-fn failure is
          # handled INSIDE _dispatch; reaching here is machinery death).
          # Under the lock: the completer's death handler swaps the
          # orphan list concurrently (threadlint GL120 caught this)
          with self._lock:
            self._orphans.extend(taken)
          raise

  def flush_now(self) -> int:
    """Synchronous flush (tests / drain): packs and dispatches pending
    requests batch by batch, completing inline. Returns the number of
    dispatches issued."""
    n = 0
    while True:
      with self._nonempty:
        taken = self._take_batch_locked()
      if not taken:
        return n
      item = self._dispatch(taken, inline=True)
      self._complete(*item)
      n += 1

  # ---- dispatch + completion ---------------------------------------------
  def _pad_batch(self, taken: List[_Pending]):
    with _span("serve/pack", args={"requests": len(taken)}):
      numerical = np.concatenate([p.numerical for p in taken])
      cats = [np.concatenate([p.cats[i] for p in taken])
              for i in range(len(taken[0].cats))]
      pad = self.max_batch - numerical.shape[0]
      if pad:
        numerical = np.concatenate(
            [numerical, np.zeros((pad,) + numerical.shape[1:],
                                 numerical.dtype)])
        cats = [np.concatenate(
            [c, np.full((pad,) + c.shape[1:], PAD_ID, c.dtype)])
            for c in cats]
      self._counters["padded_rows"].inc(pad)
      return numerical, cats

  def _dispatch(self, taken: List[_Pending], inline: bool = False):
    dispatch_fn = self.dispatch_fn  # one binding per flush (see setter)
    # the dispatch context: primary id = the first packed request's,
    # trace_ids = every coalesced request's — each request's id appears
    # on every process track the fan-out touches
    tids = [p.trace_id for p in taken if p.trace_id is not None]
    ctx = _trace.mint_context(tids) if tids else None
    fr = _flight.current_flight_recorder()
    rec = None
    if fr is not None and ctx is not None:
      rec = fr.begin(ctx.trace_id, ctx.trace_ids)
      fr.bind(rec)
    # queue stage: how long the oldest coalesced request waited for
    # this flush (latency stamps on the submit clock, not timing)
    now = time.monotonic()  # graftlint: disable=GL113 (latency stamp)
    _flight.observe_stage(
        "queue", max(0.0, now - min(p.future.t_submit for p in taken)),
        registry=self.telemetry)
    try:
      with _trace.use_context(ctx):
        with _flight.stage("pack", registry=self.telemetry):
          numerical, cats = self._pad_batch(taken)
        with _span("serve/dispatch",
                   args={"requests": len(taken)}):
          out = dispatch_fn(numerical, cats)
      self._counters["batches"].inc()
    except BaseException as e:  # noqa: BLE001 — delivered per request
      for p in taken:
        p.future._fail(e)
      if rec is not None:
        fr.bind(None)
        fr.end(rec, error=e)
      if inline:
        raise
      return
    if fr is not None:
      fr.bind(None)
    # fr rides the item: completion must end the record against the
    # recorder that BEGAN it — re-resolving the global there would leak
    # the record (and wedge pending trips) across a recorder swap
    if inline:
      return (taken, out, rec, ctx, fr)
    # enqueue with a death-aware timed put: a plain blocking put could
    # wedge forever against a dead completer (the death handler's
    # sentinel may occupy the last slot), and a check-then-put could
    # land the item AFTER the handler's one-shot drain — so re-check
    # death on every Full timeout AND after a successful put, and
    # self-drain in the latter case (idempotent, see
    # _drain_inflight_dead) so the waiters can never be stranded
    while True:
      with self._lock:
        dead = self._dead is not None
      if dead:
        for p in taken:
          if not p.future.done():
            p.future._fail(self._dead_rejected())
        if rec is not None:
          fr.end(rec, error=self._dead[1])
        return None
      try:
        self._inflight.put((taken, out, rec, ctx, fr), timeout=0.05)
      except _queue.Full:
        continue
      with self._lock:
        dead = self._dead is not None
      if dead:
        self._drain_inflight_dead()
      return None

  def _complete(self, taken: List[_Pending], out: Any, rec=None,
                ctx=None, fr=None) -> None:
    if fr is not None and rec is not None:
      fr.bind(rec)  # the drain happens HERE, on the completer thread
    try:
      with _trace.use_context(ctx), \
          _span("serve/complete", args={"requests": len(taken)}):
        try:
          with _flight.stage("dequant", registry=self.telemetry):
            preds = np.asarray(out)  # materializes the device result
        except BaseException as e:  # noqa: BLE001
          for p in taken:
            p.future._fail(e)
          if fr is not None and rec is not None:
            fr.end(rec, error=e)
            rec = None
          return
        off = 0
        for p in taken:
          p.future._fulfill(preds[off:off + p.future.n])
          off += p.future.n
          self._counters["completed"].inc()
          self._latency.observe(p.future.latency_s)
      if fr is not None and rec is not None:
        fr.end(rec)
    finally:
      if fr is not None:
        fr.bind(None)

  def _complete_loop(self) -> None:
    while True:
      item = self._inflight.get()
      if item is None:
        return
      try:
        self._complete(*item)
      except BaseException:
        # popped from in-flight already: hand the batch to the death
        # handler (expected completion failures are delivered per
        # request inside _complete; this is machinery death). Locked:
        # the flusher's death handler may swap the list concurrently
        with self._lock:
          self._orphans.extend(item[0])
        raise

  # ---- lifecycle ----------------------------------------------------------
  def close(self, drain: bool = True) -> None:
    """Stop the batcher. ``drain`` flushes pending requests first;
    otherwise they fail with a shutdown error."""
    with self._nonempty:
      self._closed = True
      pending = [] if drain else self._pending[:]
      if not drain:
        self._pending.clear()
        self._pending_rows = 0
      self._nonempty.notify_all()
    for p in pending:
      p.future._fail(RuntimeError("MicroBatcher closed before dispatch"))
    if self._flusher is not None:
      self._flusher.join(timeout=10.0)
      self._completer.join(timeout=10.0)
    elif drain:
      try:
        self.flush_now()
      finally:
        # a dispatch failure aborts flush_now mid-drain; requests still
        # queued behind it must fail loudly, not strand their waiters
        with self._nonempty:
          leftover = self._pending[:]
          self._pending.clear()
          self._pending_rows = 0
        for p in leftover:
          p.future._fail(
              RuntimeError("MicroBatcher closed before dispatch"))
