"""Inference-first serving subsystem.

Training state is the wrong shape for serving: the packed buffers
interleave optimizer-state lanes with every table row (2-3x the bytes a
lookup needs), the step builders drag scatter-add backward plumbing and
guard/metrics machinery through the device, and nothing batches
concurrent user queries. This package is the serve-time counterpart:

- :mod:`.export` — freeze the train state into a contiguous inference
  artifact: optimizer lanes stripped, optional int8 per-row symmetric
  quantization (per-row f32 scale bit-packed alongside the row), written
  through the checkpoint layer's crc32-manifest-last durable protocol.
- :mod:`.engine` — a jitted serve step (dequantize-on-gather fused into
  the lookup; no scatters, no metrics, no guard; parameter buffers never
  donated) plus :class:`ServeEngine`, which drives it — tiered plans
  serve hot ids from the device cache and cold ids from the stripped
  host image through the tiering prefetcher's classify path.
- :mod:`.batcher` — a request micro-batcher: concurrent variable-size
  queries coalesce into one padded device dispatch with per-request
  de-interleave, a deadline-or-full flush policy, and a bounded queue
  that sheds load with a counted rejection instead of unbounded latency.

graftlint GL111 keeps this package honest: train-only surfaces (optax,
the guard/commit-gate helpers, the scatter-add emitters, the train step
builders) are unreachable from serving modules.
"""

from .batcher import MicroBatcher, Rejected
from .engine import ServeEngine, ServeTierConfig, make_serve_step
from .export import (
    ServeClassMeta,
    dequantize_rows_fp8,
    dequantize_rows_int8,
    export,
    freeze,
    load,
    quantize_rows,
    quantize_rows_fp8,
    quantize_rows_int8,
    serve_layout,
)

__all__ = [
    "MicroBatcher",
    "Rejected",
    "ServeClassMeta",
    "ServeEngine",
    "ServeTierConfig",
    "dequantize_rows_fp8",
    "dequantize_rows_int8",
    "export",
    "freeze",
    "load",
    "make_serve_step",
    "quantize_rows",
    "quantize_rows_fp8",
    "quantize_rows_int8",
    "serve_layout",
]
