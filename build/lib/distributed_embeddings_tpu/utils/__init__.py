"""Data pipelines and training utilities."""

from .data import (
    DummyDataset,
    RawBinaryCriteoDataset,
    categorical_dtype,
    dlrm_lr_schedule,
    write_dummy_criteo_split,
)

__all__ = [
    "DummyDataset",
    "RawBinaryCriteoDataset",
    "categorical_dtype",
    "dlrm_lr_schedule",
    "write_dummy_criteo_split",
]
