"""Datasets: Criteo split-binary reader + synthetic dummy data.

Counterpart of `/root/reference/examples/dlrm/utils.py:126-307`. The on-disk
format is the reference's: ``train/`` and ``test/`` directories containing
``label.bin`` (1 byte/sample), ``numerical.bin`` (float16, 13 per sample) and
``cat_N.bin`` (per-feature integer width chosen by vocabulary size:
int8/int16/int32 — `utils.py:117-123`).

Re-designed rather than ported: instead of raw ``os.pread`` offsets + a
hand-rolled prefetch thread per batch, each file is a ``np.memmap`` view and
a background thread keeps a bounded queue of ready batches (same prefetch
semantics, less code). Per-rank slicing supports both dp input (each rank
reads its batch shard) and mp input (each rank reads only its own tables'
files over the global batch) like the reference trainer
(`examples/dlrm/main.py:161-190`).
"""

from __future__ import annotations

import math
import os
import queue
import threading
from typing import List, Optional, Sequence

import numpy as np


def categorical_dtype(size: int) -> np.dtype:
  """Smallest integer dtype holding ids < size (reference `utils.py:117-123`)."""
  for t in (np.int8, np.int16, np.int32):
    if size < np.iinfo(t).max:
      return np.dtype(t)
  return np.dtype(np.int64)


class RawBinaryCriteoDataset:
  """Split-binary Criteo reader.

  Args:
    data_path: directory containing ``train/`` and ``test/`` splits.
    batch_size: samples per yielded batch (per rank for dp input, global
      for mp input).
    numerical_features: how many numerical features to load (0 = skip).
    categorical_features: feature ids to read (mp input: this rank's tables;
      None = all features present).
    categorical_feature_sizes: global vocabulary sizes (for dtypes).
    valid: read the ``test`` split.
    rank / world_size: dp slicing — rank r reads batch slice r.
    prefetch_depth: batches to keep ready in the background.
    drop_last_batch: drop the trailing partial batch.
  """

  def __init__(self,
               data_path: str,
               batch_size: int,
               numerical_features: int = 0,
               categorical_features: Optional[Sequence[int]] = None,
               categorical_feature_sizes: Optional[Sequence[int]] = None,
               valid: bool = False,
               rank: int = 0,
               world_size: int = 1,
               prefetch_depth: int = 10,
               drop_last_batch: bool = True,
               backend: str = "auto"):
    if backend not in ("auto", "native", "numpy"):
      raise ValueError(f"backend must be auto|native|numpy, got {backend!r}")
    split = "test" if valid else "train"
    base = os.path.join(data_path, split)
    self._base = base
    self._backend = backend
    self._drop_last = drop_last_batch
    self.batch_size = batch_size
    self.numerical_features = numerical_features
    self.rank, self.world_size = rank, world_size

    labels = np.memmap(os.path.join(base, "label.bin"), dtype=np.uint8,
                       mode="r")
    self.num_samples = labels.shape[0]
    rounder = math.floor if drop_last_batch else math.ceil
    self.num_batches = rounder(self.num_samples / (batch_size * world_size)) \
        if world_size > 1 else rounder(self.num_samples / batch_size)
    self.labels = labels

    self.numerical = None
    if numerical_features > 0:
      raw = np.memmap(os.path.join(base, "numerical.bin"), dtype=np.float16,
                      mode="r")
      if raw.shape[0] != self.num_samples * numerical_features:
        raise ValueError(
            f"numerical.bin holds {raw.shape[0]} values, expected "
            f"{self.num_samples * numerical_features}")
      self.numerical = raw.reshape(self.num_samples, numerical_features)

    self.categorical: List[np.memmap] = []
    self.categorical_ids = list(categorical_features or [])
    if self.categorical_ids:
      if categorical_feature_sizes is None:
        raise ValueError("categorical_feature_sizes required with "
                         "categorical_features")
      for fid in self.categorical_ids:
        dtype = categorical_dtype(categorical_feature_sizes[fid])
        arr = np.memmap(os.path.join(base, f"cat_{fid}.bin"), dtype=dtype,
                        mode="r")
        if arr.shape[0] != self.num_samples:
          raise ValueError(
              f"cat_{fid}.bin holds {arr.shape[0]} ids, expected "
              f"{self.num_samples}")
        self.categorical.append(arr)

    self._queue: Optional[queue.Queue] = None
    self._prefetch_depth = prefetch_depth

  def __len__(self):
    return self.num_batches

  def _slice(self, idx: int):
    if self.world_size > 1:
      # dp: rank r takes the r-th contiguous slice of the global batch
      global_start = idx * self.batch_size * self.world_size
      start = global_start + self.rank * self.batch_size
    else:
      start = idx * self.batch_size
    end = min(start + self.batch_size, self.num_samples)
    return start, end

  def __getitem__(self, idx: int):
    if idx >= self.num_batches:
      raise IndexError(idx)
    start, end = self._slice(idx)
    labels = np.asarray(self.labels[start:end], np.float32)
    numerical = (np.asarray(self.numerical[start:end], np.float32)
                 if self.numerical is not None else None)
    cats = [np.asarray(arr[start:end], np.int32) for arr in self.categorical]
    return numerical, cats, labels

  def __iter__(self):
    """Background-prefetched iteration.

    Uses the native C++ loader (``cc/data_loader.cc``: pread thread pool,
    in-worker fp16->fp32 and intN->int32 widening) when available; else the
    numpy memmap path with a prefetch thread (reference prefetch thread,
    `utils.py:262-292`)."""
    if self._backend != "numpy":
      it = self._iter_native()
      if it is not None:
        yield from it
        return
      if self._backend == "native":
        raise RuntimeError("native data loader unavailable (build failed?)")
    yield from self._iter_numpy()

  def _iter_native(self):
    from ..cc import load_data_loader
    lib = load_data_loader()
    if lib is None:
      return None
    return self._native_batches(lib)

  def _native_batches(self, lib):
    import ctypes

    n_cat = len(self.categorical_ids)
    cat_ids = (ctypes.c_int32 * n_cat)(*self.categorical_ids)
    itemsizes = (ctypes.c_int64 * n_cat)(
        *[arr.dtype.itemsize for arr in self.categorical])
    handle = lib.de_loader_open(
        self._base.encode(), self.numerical_features, n_cat, cat_ids,
        itemsizes, self.batch_size, self.rank, self.world_size,
        1 if self._drop_last else 0, self._prefetch_depth,
        min(8, max(2, self._prefetch_depth)))
    try:
      err = lib.de_loader_error(handle)
      if err:
        raise RuntimeError(f"native loader: {err.decode()}")
      lib.de_loader_start(handle)
      fptr = ctypes.POINTER(ctypes.c_float)
      iptr = ctypes.POINTER(ctypes.c_int32)
      while True:
        numerical = (np.empty((self.batch_size, self.numerical_features),
                              np.float32)
                     if self.numerical_features else None)
        cats = np.empty((n_cat, self.batch_size), np.int32)
        labels = np.empty(self.batch_size, np.float32)
        n = lib.de_loader_next(
            handle,
            numerical.ctypes.data_as(fptr) if numerical is not None else None,
            cats.ctypes.data_as(iptr) if n_cat else None,
            labels.ctypes.data_as(fptr))
        if n == -2:  # end of epoch (n == 0 is a real, empty per-rank slice)
          return
        if n < 0:
          err = lib.de_loader_error(handle)
          raise RuntimeError(
              f"native loader: {err.decode() if err else 'unknown error'}")
        yield (numerical[:n] if numerical is not None else None,
               [cats[f, :n] for f in range(n_cat)], labels[:n])
    finally:
      lib.de_loader_close(handle)

  def _iter_numpy(self):
    q: queue.Queue = queue.Queue(maxsize=self._prefetch_depth)
    stop = threading.Event()

    def producer():
      for i in range(self.num_batches):
        if stop.is_set():
          return
        q.put(self[i])
      q.put(None)

    thread = threading.Thread(target=producer, daemon=True)
    thread.start()
    try:
      while True:
        item = q.get()
        if item is None:
          return
        yield item
    finally:
      stop.set()


class DummyDataset:
  """Synthetic Criteo-shaped data (reference ``DummyDataset``,
  `utils.py:126-154`)."""

  def __init__(self, batch_size: int, num_numerical: int = 13,
               vocab_sizes: Sequence[int] = (), num_batches: int = 100,
               seed: int = 0):
    self.batch_size = batch_size
    self.num_numerical = num_numerical
    self.vocab_sizes = list(vocab_sizes)
    self.num_batches = num_batches
    self.seed = seed

  def __len__(self):
    return self.num_batches

  def __getitem__(self, idx: int):
    if idx >= self.num_batches:
      raise IndexError(idx)
    rng = np.random.default_rng(self.seed + idx)
    numerical = rng.uniform(0, 1, (self.batch_size, self.num_numerical)
                            ).astype(np.float32)
    cats = [rng.integers(0, v, self.batch_size).astype(np.int32)
            for v in self.vocab_sizes]
    labels = rng.integers(0, 2, self.batch_size).astype(np.float32)
    return numerical, cats, labels

  def __iter__(self):
    for i in range(self.num_batches):
      yield self[i]


def write_dummy_criteo_split(path: str, num_samples: int,
                             vocab_sizes: Sequence[int],
                             num_numerical: int = 13, seed: int = 0) -> None:
  """Write a tiny split-binary Criteo dataset (both splits) for tests."""
  rng = np.random.default_rng(seed)
  for split in ("train", "test"):
    base = os.path.join(path, split)
    os.makedirs(base, exist_ok=True)
    rng.integers(0, 2, num_samples, dtype=np.uint8).tofile(
        os.path.join(base, "label.bin"))
    rng.uniform(0, 1, num_samples * num_numerical).astype(np.float16).tofile(
        os.path.join(base, "numerical.bin"))
    for fid, size in enumerate(vocab_sizes):
      rng.integers(0, size, num_samples).astype(
          categorical_dtype(size)).tofile(os.path.join(base, f"cat_{fid}.bin"))


def dlrm_lr_schedule(base_lr: float, warmup_steps: int, decay_start_step: int,
                     decay_steps: int):
  """Warmup + polynomial(2) decay schedule (reference
  ``LearningRateScheduler``, `examples/dlrm/utils.py:45-88`) as an optax
  schedule function."""

  def schedule(step):
    import jax.numpy as jnp

    step = jnp.asarray(step, jnp.float32)
    warmup = base_lr * (step + 1) / max(warmup_steps, 1)
    decay_end = decay_start_step + decay_steps
    frac = jnp.clip((decay_end - step) / max(decay_steps, 1), 0.0, 1.0)
    decayed = base_lr * frac ** 2
    lr = jnp.where(step < warmup_steps, warmup,
                   jnp.where(step >= decay_start_step, decayed, base_lr))
    return lr

  return schedule
