"""Fused embedding lookup ops (TPU-native, XLA/JAX).

Functional equivalent of the reference's custom-op layer
(`/root/reference/distributed_embeddings/python/ops/embedding_lookup_ops.py:37-122`
backed by the CUDA kernels in
`/root/reference/distributed_embeddings/cc/kernels/embedding_lookup_kernels.cu`),
re-designed for XLA:

- Forward: gather + segment-reduce. XLA fuses this into a single HBM-bound
  loop on TPU (measured ~10 ns/row, faster than any Pallas per-row DMA
  gather we built — see docs/BENCHMARKS.md; the Pallas win is on the
  APPLY side, ``ops/pallas_apply.py``).
- Backward: the reference's CUDA backward radix-sorts ids, uniques them, and
  segment-sums duplicate gradients to emit deduplicated ``IndexedSlices``
  (`embedding_lookup_kernels.cu:464-633`), syncing the unique count to host.
  Under XLA we keep all shapes static: sort ids, segment-sum duplicate rows
  into per-unique-id slots (padded to nnz), then one scatter-add with no
  duplicate indices. This avoids both the host sync and XLA's serialized
  handling of duplicate scatter indices under power-law skew.

Everything here is shape-static and jit/vmap/shard_map compatible.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .ragged import RaggedIds, SparseIds, row_to_split

_COMBINERS = (None, "sum", "mean")


def _check_combiner(combiner):
  if combiner not in _COMBINERS:
    raise ValueError(f"combiner must be one of {_COMBINERS}, got {combiner!r}")


def _row_ids_from_splits(row_splits: jax.Array, nnz: int) -> jax.Array:
  """Expand CSR row_splits into a per-element row id array (static [nnz])."""
  # positions 0..nnz-1; row of element j = #splits <= j  - 1
  pos = jnp.arange(nnz, dtype=row_splits.dtype)
  return (jnp.searchsorted(row_splits, pos, side="right") - 1).astype(jnp.int32)


def _csr_forward(params, values, row_splits, combiner):
  nnz = values.shape[0]
  nrows = row_splits.shape[0] - 1
  row_ids = _row_ids_from_splits(row_splits, nnz)
  # clip (TPU-native clamp semantics) instead of JAX's default NaN fill
  rows = jnp.take(params, values, axis=0, mode="clip")
  out = jax.ops.segment_sum(rows, row_ids, num_segments=nrows)
  if combiner == "mean":
    counts = (row_splits[1:] - row_splits[:-1]).astype(out.dtype)
    out = out / jnp.maximum(counts, 1)[:, None]
  return out


def sparse_dedup_grad(values, row_splits, grad, combiner, vocab_size):
  """Deduplicated sparse gradient for a CSR lookup.

  TPU-native mirror of the reference grad kernel
  (`embedding_lookup_kernels.cu:464-633`): per-element weights (1 or 1/count
  for mean), sort by id, segment-sum runs of equal ids. Output is padded to
  ``nnz`` so every shape is static (the reference instead syncs the unique
  count to host, `.cu:523-527` — impossible and unnecessary under jit).

  Returns:
    (unique_ids, unique_grads): [nnz] int32 ids and [nnz, D] rows. Padding
    slots have ``unique_ids == vocab_size`` (out-of-range sentinel) and zero
    gradient rows, so a mode='drop' scatter ignores them.
  """
  nnz = values.shape[0]
  row_ids = _row_ids_from_splits(row_splits, nnz)
  g_rows = jnp.take(grad, row_ids, axis=0)
  if combiner == "mean":
    counts = (row_splits[1:] - row_splits[:-1]).astype(grad.dtype)
    inv = jnp.where(counts > 0, 1.0 / jnp.maximum(counts, 1), 0.0)
    g_rows = g_rows * jnp.take(inv, row_ids)[:, None]

  # clamp exactly like the forward gather (mode='clip') so the VJP is the
  # true derivative of the clamped forward computation
  ids32 = jnp.clip(values, 0, vocab_size - 1).astype(jnp.int32)
  sorted_ids, perm = jax.lax.sort_key_val(ids32, jnp.arange(nnz, dtype=jnp.int32))
  g_sorted = jnp.take(g_rows, perm, axis=0)
  is_start = jnp.concatenate(
      [jnp.ones((1,), bool), sorted_ids[1:] != sorted_ids[:-1]])
  seg = jnp.cumsum(is_start.astype(jnp.int32)) - 1  # run index per element
  unique_grads = jax.ops.segment_sum(g_sorted, seg, num_segments=nnz)
  # id of run k = first sorted id in run k; padding runs get the sentinel.
  unique_ids = jnp.full((nnz,), vocab_size, dtype=jnp.int32)
  unique_ids = unique_ids.at[seg].min(sorted_ids, mode="drop")
  return unique_ids, unique_grads


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def csr_lookup(params, values, row_splits, combiner="sum"):
  """Variable-hotness CSR lookup with combiner: out[i] = reduce(params[values[row_splits[i]:row_splits[i+1]]]).

  Equivalent of the reference ``EmbeddingLookupVariableHotness`` op
  (`embedding_lookup_ops.cc:45-69`). Shape: [nrows, D].
  """
  return _csr_forward(params, values, row_splits, combiner)


def _csr_lookup_fwd(params, values, row_splits, combiner):
  out = _csr_forward(params, values, row_splits, combiner)
  return out, (params.shape[0], values, row_splits)


def _csr_lookup_bwd(combiner, res, grad):
  vocab, values, row_splits = res
  unique_ids, unique_grads = sparse_dedup_grad(
      values, row_splits, grad, combiner, vocab)
  d_params = jnp.zeros((vocab, grad.shape[-1]), grad.dtype)
  # No duplicate indices after dedup -> XLA emits a fast parallel scatter.
  d_params = d_params.at[unique_ids].add(unique_grads, mode="drop")
  return d_params, None, None


csr_lookup.defvjp(_csr_lookup_fwd, _csr_lookup_bwd)


def embedding_lookup(params, ids, combiner=None):
  """Looks up embeddings for ``ids`` in ``params``.

  API parity with the reference ``embedding_lookup``
  (`embedding_lookup_ops.py:37-102`); same dispatch rules:

  - ``combiner is None``: plain gather; output shape ``ids.shape + (D,)``.
    (2-D dense ids only, like the reference.)
  - dense 2-D ids + combiner: fixed-hotness gather + reduce; ``[B, D]``.
    Hotness-1 short-circuits to a plain gather.
  - ``RaggedIds`` + combiner: CSR variable-hotness fused path; ``[B, D]``.
  - ``SparseIds`` + combiner: COO converted via :func:`row_to_split`, then the
    CSR path; ``[B, D]``.

  Args:
    params: [V, D] embedding table.
    ids: 2-D integer array, ``RaggedIds``, or ``SparseIds``.
    combiner: None, 'sum' or 'mean'.

  Returns:
    Embedding activations.
  """
  _check_combiner(combiner)
  if not isinstance(params, jax.Array) and not hasattr(params, "shape"):
    raise TypeError("params must be an array")

  if isinstance(ids, RaggedIds):
    if combiner is None:
      # Reference falls back to a per-value gather (ragged output). We return
      # the gathered values; callers re-wrap with the same row_splits.
      return jnp.take(params, ids.values, axis=0, mode="clip")
    return csr_lookup(params, ids.values, ids.row_splits, combiner)

  if isinstance(ids, SparseIds):
    if combiner is None:
      return jnp.take(params, ids.values, axis=0, mode="clip")
    splits = row_to_split(ids.indices, ids.nrows, dtype=ids.values.dtype)
    return csr_lookup(params, ids.values, splits, combiner)

  ids = jnp.asarray(ids)
  if ids.dtype not in (jnp.int32, jnp.int64):
    ids = ids.astype(jnp.int32)
  if combiner is None:
    return jnp.take(params, ids, axis=0, mode="clip")
  if ids.ndim != 2:
    raise ValueError(f"Only 2D input is supported with a combiner, got {ids.ndim}D")
  if ids.shape[1] == 1:
    return jnp.take(params, jnp.squeeze(ids, 1), axis=0, mode="clip")
  out = jnp.take(params, ids, axis=0, mode="clip")  # [B, H, D]
  if combiner == "sum":
    return jnp.sum(out, axis=1)
  return jnp.mean(out, axis=1)
