"""Embedding lookup ops."""

from .embedding_lookup import csr_lookup, embedding_lookup, sparse_dedup_grad
from .packed_table import (
    PackedLayout,
    SparseRule,
    adagrad_rule,
    gather_fused,
    scatter_add_fused,
    sgd_rule,
    sparse_rule,
)
from .ragged import RaggedIds, SparseIds, row_to_split
from .sparse_grad import (
    SparseOptimizer,
    SparseRows,
    dedup_rows,
    sparse_adagrad,
    sparse_optimizer,
    sparse_sgd,
)

__all__ = [
    "csr_lookup",
    "embedding_lookup",
    "sparse_dedup_grad",
    "PackedLayout",
    "SparseRule",
    "adagrad_rule",
    "gather_fused",
    "scatter_add_fused",
    "sgd_rule",
    "sparse_rule",
    "RaggedIds",
    "SparseIds",
    "row_to_split",
    "SparseOptimizer",
    "SparseRows",
    "dedup_rows",
    "sparse_adagrad",
    "sparse_optimizer",
    "sparse_sgd",
]
