"""Ragged (CSR) and sparse (COO) index containers for embedding lookups.

JAX has no RaggedTensor / SparseTensor. These light pytree containers carry the
same information the reference library consumes
(`/root/reference/distributed_embeddings/python/ops/embedding_lookup_ops.py:37-102`):

- ``RaggedIds``: CSR-style variable-hotness ids — ``values`` is the flat column
  index array, ``row_splits`` the per-sample offsets. Matches the layout
  ``tf.RaggedTensor(values, row_splits)`` the reference feeds its fused CUDA op.
- ``SparseIds``: COO ids as produced by a ``tf.SparseTensor`` — 2-D ``indices``
  with sorted rows, flat ``values``, and a static ``dense_shape``.

All shapes are static (JAX/XLA requirement): ``values`` has a fixed length per
trace; callers pad or bucket upstream. ``row_splits`` has length ``nrows + 1``.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class RaggedIds:
  """CSR-format variable-hotness ids: ``values[row_splits[i]:row_splits[i+1]]``
  are the ids of sample ``i``."""

  values: jax.Array  # [nnz] int32/int64
  row_splits: jax.Array  # [nrows + 1] int

  def tree_flatten(self):
    return (self.values, self.row_splits), None

  @classmethod
  def tree_unflatten(cls, aux, children):
    del aux
    return cls(*children)

  @property
  def nrows(self) -> int:
    return self.row_splits.shape[0] - 1

  @property
  def dtype(self):
    return self.values.dtype

  @property
  def shape(self):
    # 2-D logical shape with an unknown (ragged) second dim.
    return (self.nrows, None)

  def row_lengths(self) -> jax.Array:
    return self.row_splits[1:] - self.row_splits[:-1]

  @classmethod
  def from_row_lengths(cls, values, row_lengths):
    row_lengths = jnp.asarray(row_lengths)
    row_splits = jnp.concatenate(
        [jnp.zeros((1,), row_lengths.dtype), jnp.cumsum(row_lengths)])
    return cls(jnp.asarray(values), row_splits)

  @classmethod
  def from_dense(cls, dense):
    """Every element kept: dense [B, H] -> ragged with uniform hotness H."""
    dense = jnp.asarray(dense)
    b, h = dense.shape
    row_splits = jnp.arange(b + 1, dtype=jnp.int32) * h
    return cls(dense.reshape(-1), row_splits)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class SparseIds:
  """COO-format ids: ``indices`` is [nnz, 2] (row, col) with rows sorted
  ascending, ``values`` is [nnz]; ``dense_shape`` is a static (nrows, ncols)."""

  indices: jax.Array  # [nnz, 2] int
  values: jax.Array  # [nnz] int
  dense_shape: tuple  # static (nrows, ncols)

  def tree_flatten(self):
    return (self.indices, self.values), tuple(self.dense_shape)

  @classmethod
  def tree_unflatten(cls, aux, children):
    return cls(children[0], children[1], tuple(aux))

  @property
  def nrows(self) -> int:
    return int(self.dense_shape[0])

  @property
  def dtype(self):
    return self.values.dtype

  @property
  def shape(self):
    return tuple(self.dense_shape)


def row_to_split(indices: jax.Array, nrows: int, dtype=jnp.int32) -> jax.Array:
  """COO sorted row ids -> CSR row_splits.

  TPU-native equivalent of the reference ``RowToSplit`` CUDA kernel
  (`/root/reference/distributed_embeddings/cc/kernels/embedding_lookup_kernels.cu:337-356`),
  which runs one binary search per output element. ``jnp.searchsorted`` is the
  same vectorized binary search and compiles to a single fused XLA op, so no
  custom kernel is needed. Handles empty trailing rows (searchsorted saturates).

  Args:
    indices: [nnz, 2] COO indices with sorted ``indices[:, 0]``, or [nnz] rows.
    nrows: static number of rows.
    dtype: output dtype.

  Returns:
    [nrows + 1] row_splits with row_splits[0] == 0, row_splits[-1] == nnz.
  """
  rows = indices[:, 0] if indices.ndim == 2 else indices
  targets = jnp.arange(nrows + 1, dtype=rows.dtype)
  return jnp.searchsorted(rows, targets, side="left").astype(dtype)
