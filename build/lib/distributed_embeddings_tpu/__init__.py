"""distributed_embeddings_tpu: TPU-native distributed embedding training.

A from-scratch JAX/XLA/Pallas framework with the capabilities of NVIDIA's
``distributed-embeddings`` (reference at ``/root/reference``): fused
variable-hotness embedding lookups (``ops``), ``Embedding`` layers and the
``DistEmbeddingStrategy`` placement planner (``layers``), and the
``DistributedEmbedding`` hybrid model-parallel + data-parallel wrapper
(``layers.dist_model_parallel``) that shards embedding tables over a TPU mesh
and routes activations with XLA collectives over ICI.
"""

from .ops.embedding_lookup import embedding_lookup

__version__ = "0.1.0"

__all__ = ["embedding_lookup", "__version__"]
