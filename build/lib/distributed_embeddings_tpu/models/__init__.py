"""Model zoo: DLRM + synthetic benchmark models."""

from .dlrm import DLRM, MLP, bce_loss, dot_interact
from .synthetic import (
    SYNTHETIC_MODELS,
    EmbeddingGroup,
    SyntheticModel,
    SyntheticModelConfig,
    expand_tables,
    generate_batch,
    model_size_gib,
    power_law_ids,
)

__all__ = [
    "DLRM",
    "MLP",
    "bce_loss",
    "dot_interact",
    "SYNTHETIC_MODELS",
    "EmbeddingGroup",
    "SyntheticModel",
    "SyntheticModelConfig",
    "expand_tables",
    "generate_batch",
    "model_size_gib",
    "power_law_ids",
]
