"""Embedding layers."""

from .dist_model_parallel import (
    BroadcastGlobalVariablesCallback,
    DistributedEmbedding,
    DistributedOptimizer,
    broadcast_variables,
    finalize_hybrid_grads,
    get_weights,
    hybrid_partition_specs,
    set_weights,
)
from .embedding import (
    ConcatOneHotEmbedding,
    Embedding,
    TableConfig,
    collect_regularization_losses,
    resolve_constraint,
    resolve_regularizer,
)
from .planner import DistEmbeddingStrategy

__all__ = [
    "BroadcastGlobalVariablesCallback",
    "ConcatOneHotEmbedding",
    "DistEmbeddingStrategy",
    "DistributedEmbedding",
    "DistributedOptimizer",
    "Embedding",
    "TableConfig",
    "broadcast_variables",
    "collect_regularization_losses",
    "finalize_hybrid_grads",
    "get_weights",
    "hybrid_partition_specs",
    "resolve_constraint",
    "resolve_regularizer",
    "set_weights",
]
