// Native Criteo split-binary batch loader.
//
// TPU-native counterpart of the reference's Python data pipeline
// (`/root/reference/examples/dlrm/utils.py:157-307`: os.pread offsets,
// per-rank slicing, one background prefetch thread). Re-designed as native
// host code: a C++17 thread pool preads and type-widens batches directly
// into pinned ring-buffer slots, so the Python process only ever sees
// ready-to-ship numpy views. On TPU the feed path competes with the host's
// share of the step budget (the device is fed over PCIe/ICI by the same
// host that runs the input pipeline), so batch assembly — fp16->fp32
// widening of 13 numerical features and int8/16/32 -> int32 widening of
// each categorical stream — is done here, multi-threaded, not in numpy.
//
// On-disk format (reference `utils.py:117-123, 157-206`):
//   <base>/label.bin      uint8   [num_samples]
//   <base>/numerical.bin  float16 [num_samples, num_numerical]
//   <base>/cat_<id>.bin   intN    [num_samples]  (N = 8/16/32 by vocab size)
//
// Exposed as a plain C API for ctypes (no pybind11 in this image).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// ---------------------------------------------------------------------------
// fp16 -> fp32 (scalar; compilers vectorize the loop well with -O3)
// ---------------------------------------------------------------------------
inline float half_to_float(uint16_t h) {
  uint32_t sign = (uint32_t)(h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1Fu;
  uint32_t mant = h & 0x3FFu;
  uint32_t out;
  if (exp == 0) {
    if (mant == 0) {
      out = sign;  // +-0
    } else {
      // subnormal: normalize. mant's top set bit at position p becomes the
      // implicit bit; value = mant * 2^-24 so the fp32 exponent is 103 + p
      // = 113 - shift.
      int shift = 0;
      while (!(mant & 0x400u)) {
        mant <<= 1;
        ++shift;
      }
      mant &= 0x3FFu;
      out = sign | ((uint32_t)(113 - shift) << 23) | (mant << 13);
    }
  } else if (exp == 0x1F) {
    out = sign | 0x7F800000u | (mant << 13);  // inf / nan
  } else {
    out = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float f;
  std::memcpy(&f, &out, sizeof(f));
  return f;
}

ssize_t pread_full(int fd, void* buf, size_t count, off_t offset) {
  char* p = static_cast<char*>(buf);
  size_t done = 0;
  while (done < count) {
    ssize_t n = ::pread(fd, p + done, count - done, offset + (off_t)done);
    if (n <= 0) return n < 0 ? n : (ssize_t)done;
    done += (size_t)n;
  }
  return (ssize_t)done;
}

struct CatFile {
  int fd = -1;
  int itemsize = 4;  // 1, 2 or 4
};

struct Batch {
  int64_t index = -1;
  int64_t num_samples = 0;
  std::vector<float> numerical;  // [n, num_numerical]
  std::vector<int32_t> cats;     // [num_cat, n] feature-major
  std::vector<float> labels;     // [n]
  bool ready = false;
};

class Loader {
 public:
  Loader(const char* base_dir, int num_numerical, int num_cat,
         const int32_t* cat_ids, const int64_t* cat_itemsizes,
         int64_t batch_size, int64_t rank, int64_t world_size, int drop_last,
         int prefetch_depth, int num_threads)
      : num_numerical_(num_numerical),
        batch_size_(batch_size),
        rank_(rank),
        world_size_(world_size < 1 ? 1 : world_size),
        prefetch_depth_(prefetch_depth < 1 ? 1 : prefetch_depth) {
    std::string base(base_dir);
    label_fd_ = ::open((base + "/label.bin").c_str(), O_RDONLY);
    if (label_fd_ < 0) {
      err_ = "cannot open " + base + "/label.bin";
      return;
    }
    struct stat st;
    ::fstat(label_fd_, &st);
    num_samples_ = (int64_t)st.st_size;

    if (num_numerical_ > 0) {
      num_fd_ = ::open((base + "/numerical.bin").c_str(), O_RDONLY);
      if (num_fd_ < 0) {
        err_ = "cannot open " + base + "/numerical.bin";
        return;
      }
      ::fstat(num_fd_, &st);
      if ((int64_t)st.st_size != num_samples_ * num_numerical_ * 2) {
        err_ = "numerical.bin size mismatch";
        return;
      }
    }
    for (int i = 0; i < num_cat; ++i) {
      CatFile cf;
      cf.itemsize = (int)cat_itemsizes[i];
      std::string path = base + "/cat_" + std::to_string(cat_ids[i]) + ".bin";
      cf.fd = ::open(path.c_str(), O_RDONLY);
      if (cf.fd < 0) {
        err_ = "cannot open " + path;
        return;
      }
      ::fstat(cf.fd, &st);
      if ((int64_t)st.st_size != num_samples_ * cf.itemsize) {
        err_ = path + " size mismatch";
        return;
      }
      cats_.push_back(cf);
    }

    int64_t global_batch = batch_size_ * world_size_;
    num_batches_ = drop_last ? num_samples_ / global_batch
                             : (num_samples_ + global_batch - 1) / global_batch;

    int n = num_threads < 1 ? 1 : num_threads;
    for (int i = 0; i < n; ++i) {
      workers_.emplace_back([this] { this->WorkerLoop(); });
    }
  }

  ~Loader() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      shutdown_ = true;
    }
    cv_work_.notify_all();
    cv_done_.notify_all();
    for (auto& t : workers_) t.join();
    if (label_fd_ >= 0) ::close(label_fd_);
    if (num_fd_ >= 0) ::close(num_fd_);
    for (auto& c : cats_) ::close(c.fd);
  }

  const char* error() const { return err_.empty() ? nullptr : err_.c_str(); }
  int64_t num_samples() const { return num_samples_; }
  int64_t num_batches() const { return num_batches_; }

  // Reset iteration to batch 0 and (re)fill the prefetch window.
  void Start() {
    std::lock_guard<std::mutex> lk(mu_);
    next_to_schedule_ = 0;
    next_to_emit_ = 0;
    window_.clear();
    ScheduleLocked();
    cv_work_.notify_all();
  }

  // Blocking: copy batch `next_to_emit_` into caller buffers.
  // Returns the sample count (0 is a legitimate empty per-rank slice of a
  // real batch, e.g. a high rank past the data end with drop_last=0),
  // -2 at end of epoch, -1 on error.
  int64_t Next(float* numerical, int32_t* cats, float* labels) {
    std::unique_lock<std::mutex> lk(mu_);
    if (!err_.empty()) return -1;
    if (next_to_emit_ >= num_batches_) return -2;
    int64_t want = next_to_emit_;
    cv_done_.wait(lk, [&] {
      if (shutdown_ || !err_.empty()) return true;
      for (auto& b : window_)
        if (b.index == want && b.ready) return true;
      return false;
    });
    if (shutdown_ || !err_.empty()) return -1;
    Batch batch;
    for (auto it = window_.begin(); it != window_.end(); ++it) {
      if (it->index == want) {
        batch = std::move(*it);
        window_.erase(it);
        break;
      }
    }
    ++next_to_emit_;
    ScheduleLocked();
    cv_work_.notify_all();
    lk.unlock();

    int64_t n = batch.num_samples;
    if (numerical && num_numerical_ > 0)
      std::memcpy(numerical, batch.numerical.data(),
                  sizeof(float) * n * num_numerical_);
    // caller buffer is [num_cat, batch_size]; a short trailing batch (n <
    // batch_size) must keep the caller's row stride, not pack contiguously
    if (cats && !cats_.empty())
      for (size_t f = 0; f < cats_.size(); ++f)
        std::memcpy(cats + f * batch_size_, batch.cats.data() + f * n,
                    sizeof(int32_t) * n);
    if (labels) std::memcpy(labels, batch.labels.data(), sizeof(float) * n);
    return n;
  }

 private:
  // Assumes mu_ held: queue load tasks up to the prefetch depth.
  void ScheduleLocked() {
    while ((int64_t)window_.size() < prefetch_depth_ &&
           next_to_schedule_ < num_batches_) {
      Batch b;
      b.index = next_to_schedule_++;
      window_.push_back(std::move(b));
      pending_.push_back(window_.back().index);
    }
  }

  void WorkerLoop() {
    for (;;) {
      int64_t idx;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_work_.wait(lk, [&] { return shutdown_ || !pending_.empty(); });
        if (shutdown_) return;
        idx = pending_.front();
        pending_.pop_front();
      }
      LoadBatch(idx);
      cv_done_.notify_all();
    }
  }

  void LoadBatch(int64_t idx) {
    // dp slicing: rank r reads the r-th slice of global batch idx
    int64_t start = idx * batch_size_ * world_size_ + rank_ * batch_size_;
    int64_t end = start + batch_size_;
    if (end > num_samples_) end = num_samples_;
    int64_t n = end > start ? end - start : 0;

    Batch local;
    local.index = idx;
    local.num_samples = n;
    local.labels.resize(n);
    {
      std::vector<uint8_t> raw(n);
      if (pread_full(label_fd_, raw.data(), n, start) != (ssize_t)n) {
        Fail("short read on label.bin");
        return;
      }
      for (int64_t i = 0; i < n; ++i) local.labels[i] = (float)raw[i];
    }
    if (num_numerical_ > 0) {
      int64_t count = n * num_numerical_;
      std::vector<uint16_t> raw(count);
      if (pread_full(num_fd_, raw.data(), count * 2,
                     start * num_numerical_ * 2) != (ssize_t)(count * 2)) {
        Fail("short read on numerical.bin");
        return;
      }
      local.numerical.resize(count);
      for (int64_t i = 0; i < count; ++i)
        local.numerical[i] = half_to_float(raw[i]);
    }
    if (!cats_.empty()) {
      local.cats.resize(cats_.size() * n);
      std::vector<char> raw;
      for (size_t f = 0; f < cats_.size(); ++f) {
        const CatFile& cf = cats_[f];
        raw.resize(n * cf.itemsize);
        if (pread_full(cf.fd, raw.data(), n * cf.itemsize,
                       start * cf.itemsize) != (ssize_t)(n * cf.itemsize)) {
          Fail("short read on categorical file");
          return;
        }
        int32_t* out = local.cats.data() + f * n;
        switch (cf.itemsize) {
          case 1: {
            auto* p = reinterpret_cast<const int8_t*>(raw.data());
            for (int64_t i = 0; i < n; ++i) out[i] = p[i];
            break;
          }
          case 2: {
            auto* p = reinterpret_cast<const int16_t*>(raw.data());
            for (int64_t i = 0; i < n; ++i) out[i] = p[i];
            break;
          }
          default: {
            std::memcpy(out, raw.data(), n * 4);
            break;
          }
        }
      }
    }

    std::lock_guard<std::mutex> lk(mu_);
    for (auto& b : window_) {
      if (b.index == idx) {
        int64_t i = b.index;
        b = std::move(local);
        b.index = i;
        b.ready = true;
        break;
      }
    }
  }

  void Fail(const std::string& msg) {
    std::lock_guard<std::mutex> lk(mu_);
    if (err_.empty()) err_ = msg;
  }

  int num_numerical_;
  int64_t batch_size_, rank_, world_size_, prefetch_depth_;
  int64_t num_samples_ = 0, num_batches_ = 0;
  int label_fd_ = -1, num_fd_ = -1;
  std::vector<CatFile> cats_;

  std::mutex mu_;
  std::condition_variable cv_work_, cv_done_;
  std::deque<Batch> window_;       // in-flight + ready batches
  std::deque<int64_t> pending_;    // indices awaiting a worker
  int64_t next_to_schedule_ = 0, next_to_emit_ = 0;
  bool shutdown_ = false;
  std::string err_;
  std::vector<std::thread> workers_;
};

}  // namespace

extern "C" {

void* de_loader_open(const char* base_dir, int num_numerical, int num_cat,
                     const int32_t* cat_ids, const int64_t* cat_itemsizes,
                     int64_t batch_size, int64_t rank, int64_t world_size,
                     int drop_last, int prefetch_depth, int num_threads) {
  auto* l = new Loader(base_dir, num_numerical, num_cat, cat_ids,
                       cat_itemsizes, batch_size, rank, world_size, drop_last,
                       prefetch_depth, num_threads);
  return l;
}

const char* de_loader_error(void* h) {
  return static_cast<Loader*>(h)->error();
}

int64_t de_loader_num_samples(void* h) {
  return static_cast<Loader*>(h)->num_samples();
}

int64_t de_loader_num_batches(void* h) {
  return static_cast<Loader*>(h)->num_batches();
}

void de_loader_start(void* h) { static_cast<Loader*>(h)->Start(); }

int64_t de_loader_next(void* h, float* numerical, int32_t* cats,
                       float* labels) {
  return static_cast<Loader*>(h)->Next(numerical, cats, labels);
}

void de_loader_close(void* h) { delete static_cast<Loader*>(h); }

}  // extern "C"
