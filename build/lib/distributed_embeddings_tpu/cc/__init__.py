"""Native (C++) components, loaded via ctypes.

The reference builds its native code into ``_embedding_lookup_ops.so`` with
nvcc (`/root/reference/Makefile:38-52`); here TPU device code is Pallas
(``ops/pallas_apply.py``) and the native host code — the data loader — is
built by the Makefile in this directory into ``_data_loader.so``.

``load_data_loader()`` returns the ctypes library, building it on first use
if a toolchain is available; callers fall back to the numpy path when it
returns None.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_CC_DIR = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_CC_DIR, "_data_loader.so")

_lock = threading.Lock()
_lib = None
_load_attempted = False


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
  lib.de_loader_open.restype = ctypes.c_void_p
  lib.de_loader_open.argtypes = [
      ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
      ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
      ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
      ctypes.c_int, ctypes.c_int, ctypes.c_int,
  ]
  lib.de_loader_error.restype = ctypes.c_char_p
  lib.de_loader_error.argtypes = [ctypes.c_void_p]
  lib.de_loader_num_samples.restype = ctypes.c_int64
  lib.de_loader_num_samples.argtypes = [ctypes.c_void_p]
  lib.de_loader_num_batches.restype = ctypes.c_int64
  lib.de_loader_num_batches.argtypes = [ctypes.c_void_p]
  lib.de_loader_start.restype = None
  lib.de_loader_start.argtypes = [ctypes.c_void_p]
  lib.de_loader_next.restype = ctypes.c_int64
  lib.de_loader_next.argtypes = [
      ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
      ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
  ]
  lib.de_loader_close.restype = None
  lib.de_loader_close.argtypes = [ctypes.c_void_p]
  return lib


def build(force: bool = False) -> bool:
  """Compile ``_data_loader.so``; returns success."""
  if os.path.exists(_SO_PATH) and not force:
    return True
  try:
    subprocess.run(["make", "-C", _CC_DIR, "-s"] + (["-B"] if force else []),
                   check=True, capture_output=True, timeout=120)
    return os.path.exists(_SO_PATH)
  except (subprocess.SubprocessError, OSError):
    return False


def load_data_loader():
  """ctypes handle to the native loader, or None if unavailable."""
  global _lib, _load_attempted
  with _lock:
    if _lib is not None or _load_attempted:
      return _lib
    _load_attempted = True
    if not build():
      return None
    try:
      _lib = _configure(ctypes.CDLL(_SO_PATH))
    except OSError:
      _lib = None
    return _lib
