"""Quick Mosaic capability probes for the interaction-kernel design.

Each probe compiles a tiny kernel and reports OK / the failure class.
Usage: python tools/proto_mosaic_probes.py
"""

import sys
import traceback

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

S, F, D, N = 256, 27, 128, 351


def probe(name, fn):
  try:
    out = jax.jit(fn)()
    jax.block_until_ready(out)
    print(f"{name:58s}: OK", flush=True)
    return True
  except Exception as e:  # noqa: BLE001
    msg = str(e).split("\n")
    key = next((ln for ln in msg if "unsupported" in ln.lower()
                or "not implemented" in ln.lower() or "error" in ln.lower()),
               msg[0])
    print(f"{name:58s}: FAIL  {key[:90]}", flush=True)
    return False


def main():
  f16 = jnp.ones((S, F, D), jnp.bfloat16)
  da = jnp.ones((S, N), jnp.float32)
  m3t = jnp.ones((F, N, F), jnp.bfloat16)

  # 1. leading-dim read of a 3D ref -> 2D
  def k1(m_ref, o_ref):
    o_ref[...] = jnp.dot(m_ref[0], m_ref[1].T,
                         preferred_element_type=jnp.float32)
  probe("read m_ref[p] (3D ref -> 2D)", lambda: pl.pallas_call(
      k1, out_shape=jax.ShapeDtypeStruct((N, N), jnp.float32))(m3t))

  # 2. leading-dim write of 2D into 3D ref
  def k2(da_ref, m_ref, o_ref):
    for p in range(2):
      o_ref[p] = jnp.dot(da_ref[...].astype(jnp.bfloat16), m_ref[p],
                         preferred_element_type=jnp.float32)
  probe("write o_ref[p] = 2D (3D out ref)", lambda: pl.pallas_call(
      k2, out_shape=jax.ShapeDtypeStruct((2, S, F), jnp.float32))(da, m3t))

  # 3. batched dot, batch dim NOT leading on lhs: [F?,S,F] x [S,F,D]
  def k3(ds_ref, f_ref, o_ref):
    o_ref[...] = jax.lax.dot_general(
        ds_ref[...], f_ref[...], (((2,), (1,)), ((1,), (0,))),
        preferred_element_type=jnp.float32)
  probe("dot_general batch mid-dim lhs [F,S,F]x[S,F,D]", lambda: pl.pallas_call(
      k3, out_shape=jax.ShapeDtypeStruct((S, F, D), jnp.float32))(
          jnp.ones((F, S, F), jnp.bfloat16), f16))

  # 4. in-kernel transpose [F,S,F] -> [S,F,F]
  def k4(ds_ref, o_ref):
    o_ref[...] = jnp.transpose(ds_ref[...], (1, 0, 2))
  probe("transpose (1,0,2) [F,S,F]->[S,F,F]", lambda: pl.pallas_call(
      k4, out_shape=jax.ShapeDtypeStruct((S, F, F), jnp.bfloat16))(
          jnp.ones((F, S, F), jnp.bfloat16)))

  # 5. middle-dim 1-slice write via pl.dslice
  def k5(da_ref, m_ref, o_ref):
    v = jnp.dot(da_ref[...].astype(jnp.bfloat16), m_ref[0],
                preferred_element_type=jnp.float32)
    o_ref[:, pl.dslice(0, 1), :] = v[:, None, :]
  probe("write o_ref[:, 0:1, :] = [S,1,F]", lambda: pl.pallas_call(
      k5, out_shape=jax.ShapeDtypeStruct((S, F, F), jnp.float32))(da, m3t))

  # 6. concatenate 3D pieces along axis 0
  def k6(da_ref, m_ref, o_ref):
    pieces = [jnp.dot(da_ref[...].astype(jnp.bfloat16), m_ref[p],
                      preferred_element_type=jnp.float32)[None]
              for p in range(2)]
    o_ref[...] = jnp.concatenate(pieces, axis=0)
  probe("concat([S,F][None] x2, axis=0)", lambda: pl.pallas_call(
      k6, out_shape=jax.ShapeDtypeStruct((2, S, F), jnp.float32))(da, m3t))

  # 7. batched dot LEADING batch (known-good in variant B, recheck)
  def k7(ds_ref, f_ref, o_ref):
    o_ref[...] = jax.lax.dot_general(
        ds_ref[...], f_ref[...], (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
  probe("dot_general batch leading [S,F,F]x[S,F,D]", lambda: pl.pallas_call(
      k7, out_shape=jax.ShapeDtypeStruct((S, F, D), jnp.float32))(
          jnp.ones((S, F, F), jnp.bfloat16), f16))

  # 8. dot_general 2D x 3D (no batch): [S,N] x [F,N,F] -> [S,F,F]
  def k8(da_ref, m_ref, o_ref):
    o_ref[...] = jax.lax.dot_general(
        da_ref[...].astype(jnp.bfloat16), m_ref[...],
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
  probe("dot_general [S,N]x[F,N,F] -> [S,F,F]", lambda: pl.pallas_call(
      k8, out_shape=jax.ShapeDtypeStruct((S, F, F), jnp.float32))(da, m3t))


if __name__ == "__main__":
  main()
