"""Telemetry overhead budget: spans/counters must cost (almost) nothing.

The observability layer's acceptance (ISSUE 10, docs/BENCHMARKS.md
round 10) on the power-law workloads the stack actually runs:

1. **Overhead**: step time with tracing ENABLED (every host stage
   spanned, counters live) vs telemetry DISABLED, on (a) the TIERED
   trainer (classify/stage/write-back/re-rank + device window per step)
   and (b) the DYNVOCAB trainer (translate + guarded device step).
   Acceptance: **<= 3%** overhead on each (min-of-rounds timing — the
   span cost is ~µs against ~ms CPU-mesh steps, so anything above the
   bound is a regression, not noise).
2. **Trace content**: the emitted ``trace.json`` must SHOW the
   prefetch-ahead overlap the tiering layer claims — a
   ``tiered/classify`` span on the main-thread track strictly inside a
   ``device/step`` window on the virtual device track — plus the
   stage spans and per-thread tracks.
3. **Counter round-trip**: the process registry's ``state_dict`` must
   reload into a fresh registry value-for-value (the manifest
   ``telemetry``-section path), and the Prometheus textfile must
   publish atomically.

``--smoke`` is the ``make verify`` tier (tiny world, same structural
assertions, overhead only required FINITE); the full run enforces the
3% budget.  The verdict goes through ``telemetry.emit_verdict`` like
the chaos tools.

Usage: PYTHONPATH=/root/repo python tools/profile_telemetry.py [--smoke]
"""

import argparse
import json
import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
  os.environ["XLA_FLAGS"] = (
      flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from distributed_embeddings_tpu import telemetry  # noqa: E402
from distributed_embeddings_tpu.dynvocab import (  # noqa: E402
    DynVocabTrainer,
    DynVocabTranslator,
)
from distributed_embeddings_tpu.layers.embedding import TableConfig  # noqa: E402
from distributed_embeddings_tpu.layers.planner import (  # noqa: E402
    DistEmbeddingStrategy,
)
from distributed_embeddings_tpu.models import DLRM, bce_loss  # noqa: E402
from distributed_embeddings_tpu.models.dlrm import (  # noqa: E402
    _dlrm_initializer,
)
from distributed_embeddings_tpu.models.synthetic import (  # noqa: E402
    power_law_ids,
)
from distributed_embeddings_tpu.ops.packed_table import sparse_rule  # noqa: E402
from distributed_embeddings_tpu.parallel import create_mesh  # noqa: E402
from distributed_embeddings_tpu.tiering import (  # noqa: E402
    HostTierStore,
    TieredTrainer,
    TieringConfig,
    TieringPlan,
    init_tiered_state,
)
from distributed_embeddings_tpu.training import (  # noqa: E402
    init_sparse_state_direct,
    shard_params,
)

WORLD = 4
WIDTH = 16
ALPHA = 1.05


def _tables(vocab):
  return [TableConfig(input_dim=v, output_dim=WIDTH,
                      initializer=_dlrm_initializer(v)) for v in vocab]


def _model(vocab):
  return DLRM(vocab_sizes=list(vocab), embedding_dim=WIDTH,
              bottom_mlp=(32, WIDTH), top_mlp=(32, 1), world_size=WORLD,
              strategy="memory_balanced", dense_row_threshold=0)


def _batches(vocab, batch, n, seed=0):
  r = np.random.default_rng(seed)
  out = []
  for _ in range(n):
    numerical = r.standard_normal((batch, 13)).astype(np.float32)
    cats = [power_law_ids(r, batch, 1, v, ALPHA).astype(np.int32)[:, 0]
            for v in vocab]
    labels = r.integers(0, 2, batch).astype(np.float32)
    out.append((numerical, cats, labels))
  return out


def build_tiered(vocab, batch, host_thr, staging):
  plan = DistEmbeddingStrategy(_tables(vocab), WORLD, "memory_balanced",
                               dense_row_threshold=0,
                               host_row_threshold=host_thr)
  model = _model(vocab)
  mesh = create_mesh(WORLD)
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.adam(1e-3)
  batch0 = _batches(vocab, batch, 1, seed=100)[0]
  params = model.init(jax.random.PRNGKey(0), batch0[0],
                      batch0[1])["params"]
  dense = {k: v for k, v in params.items() if k != "embeddings"}
  tplan = TieringPlan(plan, rule,
                      TieringConfig(cache_fraction=0.25,
                                    staging_grps=staging))
  store = HostTierStore(tplan)
  state = shard_params(
      init_tiered_state(tplan, store, rule, dense, opt,
                        jax.random.PRNGKey(1), mesh=mesh), mesh)
  return TieredTrainer(model, tplan, store, bce_loss, opt, rule, mesh,
                       state, batch0, donate=False)


def build_dynvocab(vocab, batch):
  plan = DistEmbeddingStrategy(_tables(vocab), WORLD, "memory_balanced",
                               dense_row_threshold=0, oov="allocate",
                               admit_threshold=1, evict_ttl=None)
  model = _model(vocab)
  mesh = create_mesh(WORLD)
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.adam(1e-3)
  batch0 = _batches(vocab, batch, 1, seed=200)[0]
  batch0 = (batch0[0], [c.astype(np.int64) for c in batch0[1]], batch0[2])
  params = model.init(jax.random.PRNGKey(0), batch0[0],
                      [np.asarray(c) for c in batch0[1]])["params"]
  state = shard_params(
      init_sparse_state_direct(plan, rule, params, opt,
                               jax.random.PRNGKey(1)), mesh)
  translator = DynVocabTranslator(plan, rule)
  return DynVocabTrainer(model, plan, translator, bce_loss, opt, rule,
                         mesh, state, batch0, guard=True, donate=False)


def measure_overhead(run_steps, steps, rounds=3):
  """min-of-rounds step time with telemetry disabled vs tracing
  enabled, interleaved so drift hits both arms.  ``run_steps(k)`` runs
  k steps of the already-warm trainer."""
  run_steps(2)  # compile + residency warmup outside the clock
  t_off, t_on = [], []
  for _ in range(rounds):
    reg = telemetry.MetricsRegistry()
    with telemetry.timed("obs/window_off", reg) as t:
      run_steps(steps)
    t_off.append(t.elapsed / steps)
    tracer = telemetry.Tracer()
    telemetry.install_tracer(tracer)
    try:
      with telemetry.timed("obs/window_on", reg) as t:
        run_steps(steps)
    finally:
      telemetry.uninstall_tracer()
    t_on.append(t.elapsed / steps)
  off, on = min(t_off), min(t_on)
  return {"step_off_ms": off * 1e3, "step_on_ms": on * 1e3,
          "overhead": (on - off) / off}


def _spans(chrome, name):
  return [e for e in chrome["traceEvents"]
          if e.get("ph") == "X" and e["name"] == name]


def check_trace(path):
  """Structural assertions on the emitted trace: stage spans present,
  device window on its own track, and at least one prefetch-ahead
  classify strictly inside a device window — the PR-1 overlap claim,
  visible instead of asserted."""
  with open(path) as f:
    chrome = json.load(f)
  tracks = {e["tid"]: e["args"]["name"] for e in chrome["traceEvents"]
            if e.get("name") == "thread_name"}
  device_tids = {t for t, n in tracks.items() if n == "device"}
  need = ("tiered/classify", "tiered/stage", "tiered/write_back",
          "tiered/dispatch", "device/step")
  missing = [n for n in need if not _spans(chrome, n)]
  dev = [e for e in _spans(chrome, "device/step")
         if e["tid"] in device_tids]
  overlapped = 0
  for c in _spans(chrome, "tiered/classify"):
    if c["tid"] in device_tids:
      continue
    for d in dev:
      if d["ts"] < c["ts"] and c["ts"] + c["dur"] < d["ts"] + d["dur"]:
        overlapped += 1
        break
  return {
      "trace_events": len(chrome["traceEvents"]),
      "missing_spans": missing,
      "device_track": bool(dev),
      "classify_inside_device_window": overlapped,
      "ok": not missing and bool(dev) and overlapped > 0,
  }


def check_counters_roundtrip(tmpdir):
  """The registry must survive the JSON state_dict round trip
  value-for-value (the manifest ``telemetry``-section path) and publish
  an atomic Prometheus textfile."""
  reg = telemetry.get_registry()
  section = json.loads(json.dumps(reg.state_dict()))
  fresh = telemetry.MetricsRegistry()
  fresh.load_state_dict(section)
  bad = []
  for name, m in reg.metrics().items():
    if m.kind == "counter" and fresh.counter(name).value != m.value:
      bad.append(name)
    elif m.kind == "histogram" and \
        fresh.histogram(name, m.rel_err).count != m.count:
      bad.append(name)
  prom = os.path.join(tmpdir, "metrics.prom")
  telemetry.write_prometheus(reg, prom)
  n_counters = len(section["counters"])
  return {"counters_persisted": n_counters,
          "mismatches": bad,
          "prometheus_bytes": os.path.getsize(prom),
          "ok": not bad and n_counters > 0
                and not os.path.exists(prom + ".tmp")}


def run(smoke: bool) -> dict:
  import tempfile
  if smoke:
    vocab, batch, steps, staging = [2000, 300, 40], 64, 8, 64
  else:
    vocab, batch, steps, staging = [20000, 4000, 40], 512, 30, 256
  workdir = tempfile.mkdtemp(prefix="obs_bench_")
  result = {"world": WORLD, "vocab": vocab, "batch": batch,
            "steps_per_window": steps, "trace_path": None}

  # ---- tiered workload: overhead + the trace artifact ---------------------
  tiered = build_tiered(vocab, batch, host_thr=1000, staging=staging)
  stream = _batches(vocab, batch, max(steps, 6))
  result["tiered"] = measure_overhead(
      lambda k: tiered.run(stream[:k]), steps)
  trace_path = os.path.join(workdir, "trace.json")
  with telemetry.tracing(trace_path):
    tiered.run(stream[:6])
  result["trace_path"] = trace_path
  result["trace"] = check_trace(trace_path)

  # ---- dynvocab workload: overhead ----------------------------------------
  dyn = build_dynvocab(vocab[:2], batch)
  dyn_stream = _batches(vocab[:2], batch, max(steps, 6), seed=300)
  dyn_stream = [(n, [c.astype(np.int64) for c in cats], l)
                for n, cats, l in dyn_stream]

  def dyn_steps(k):
    for b in dyn_stream[:k]:
      dyn.step(*b)

  result["dynvocab"] = measure_overhead(dyn_steps, steps)

  # ---- counters round-trip -------------------------------------------------
  result["counters"] = check_counters_roundtrip(workdir)

  budget = 0.03
  finite = all(np.isfinite([result[w]["overhead"]
                            for w in ("tiered", "dynvocab")]))
  result["overhead_budget"] = budget
  if smoke:
    result["ok"] = bool(finite and result["trace"]["ok"]
                        and result["counters"]["ok"])
  else:
    result["ok"] = bool(
        finite and result["trace"]["ok"] and result["counters"]["ok"]
        and result["tiered"]["overhead"] <= budget
        and result["dynvocab"]["overhead"] <= budget)
  return result


if __name__ == "__main__":
  ap = argparse.ArgumentParser()
  ap.add_argument("--smoke", action="store_true",
                  help="tiny tier for make verify (overhead only "
                       "required finite)")
  args = ap.parse_args()
  res = run(smoke=args.smoke)
  sys.exit(telemetry.emit_verdict(
      "obs-smoke" if args.smoke else "obs-bench", res))
