#!/usr/bin/env python
"""graftlint: the repo-invariant linter (`make lint`).

Three passes (all on by default):

1. AST lint (``distributed_embeddings_tpu.analysis.astlint``): the GL1xx
   rule registry over every Python source in the tree — host syncs in
   step-builder code, bare excepts, un-fsynced renames in durable paths,
   wall clock/RNG in manifests, int32 index-arithmetic narrowing,
   unregistered pytest marks, unknown fault-injection sites, stale
   suppressions (GL124). Line-level ``# graftlint: disable=GLnnn``
   suppresses.
2. Concurrency lint (``...analysis.threadlint``): lock discipline over
   the LIBRARY package only — ``# guarded-by`` annotation enforcement
   (GL120), lock-acquisition-graph cycles (GL121), unannotated
   multi-thread-root mutation (GL122), condition-variable misuse
   (GL123), and the ``pyproject.toml [tool.graftlint] thread-roots``
   registry cross-check (GL125). Tests/tools spawn throwaway threads by
   design and are out of scope.
3. Jaxpr audit (``...analysis.jaxpr_audit``): traces the real step
   builders on a virtual CPU mesh and asserts structural invariants
   (exactly one scatter-add per fused class, collective axis hygiene,
   guard pmin iff guarded, no f64, no host callbacks), then diffs each
   artifact's op-class fingerprint against ``tests/data/
   jaxpr_fingerprints.json``.

Exit status 1 on any error-severity finding, audit violation, or
fingerprint drift; 0 otherwise. ``--json`` additionally emits the
normalized tool verdict through ``telemetry.emit_verdict`` (appended to
``$DE_TPU_VERDICT_LOG`` when set), like the chaos/soak tools.

Usage:
  python tools/graftlint.py                  # all passes, whole tree
  python tools/graftlint.py --ast-only [PATH ...]
  python tools/graftlint.py --jaxpr-only
  python tools/graftlint.py --update-fingerprints
  python tools/graftlint.py --list-rules
  python tools/graftlint.py --json
"""

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_PATHS = [
    "distributed_embeddings_tpu", "tests", "tools", "examples",
    "bench.py", "__graft_entry__.py",
]

# the concurrency pass lints the library package only (see module doc)
THREADLINT_PATHS = ["distributed_embeddings_tpu"]


def _setup_cpu_mesh_env():
  """Virtual CPU devices for the jaxpr audit — must precede jax import
  (same dance as tests/conftest.py; this environment pins a real-TPU
  backend that the audit must never touch)."""
  flags = os.environ.get("XLA_FLAGS", "")
  if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
  os.environ["JAX_PLATFORMS"] = "cpu"
  import jax
  jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
  ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
  ap.add_argument("paths", nargs="*", help="files/dirs for the AST pass "
                  f"(default: {' '.join(DEFAULT_PATHS)})")
  ap.add_argument("--ast-only", action="store_true",
                  help="skip the jaxpr audit (no jax import); the AST "
                  "and concurrency passes both run")
  ap.add_argument("--jaxpr-only", action="store_true",
                  help="skip the AST and concurrency passes")
  ap.add_argument("--update-fingerprints", action="store_true",
                  help="rewrite tests/data/jaxpr_fingerprints.json from "
                  "the current trace instead of diffing against it")
  ap.add_argument("--list-rules", action="store_true")
  ap.add_argument("--json", action="store_true",
                  help="emit the normalized tool verdict via "
                  "telemetry.emit_verdict ($DE_TPU_VERDICT_LOG hook)")
  ap.add_argument("-q", "--quiet", action="store_true")
  args = ap.parse_args(argv)
  if args.update_fingerprints and args.ast_only:
    ap.error("--update-fingerprints needs the jaxpr pass; drop --ast-only")

  from distributed_embeddings_tpu.analysis import astlint, threadlint

  if args.list_rules:
    for rid, rule in sorted(astlint.RULES.items()):
      print(f"{rid}  {rule.severity:<7}  {rule.title}")
    for rid, (severity, title) in sorted(threadlint.THREAD_RULES.items()):
      print(f"{rid}  {severity:<7}  {title}  [threadlint]")
    return 0

  say = (lambda *_: None) if args.quiet else print
  errors = 0
  result = {"ok": True}

  if not args.jaxpr_only:
    paths = args.paths or [os.path.join(REPO, p) for p in DEFAULT_PATHS]
    findings = astlint.lint_paths(paths, root=REPO)
    for f in findings:
      print(f.render())
      errors += f.severity == "error"
    say(f"graftlint ast: {len(findings)} finding(s) over "
        f"{len(list(astlint._iter_py_files(paths)))} file(s)")
    result["ast_findings"] = len(findings)

    # concurrency pass: fixed library scope regardless of positional
    # paths UNLESS explicit paths were given (then lint their
    # intersection story the simple way: the explicit paths)
    tpaths = args.paths or [os.path.join(REPO, p)
                            for p in THREADLINT_PATHS]
    tfindings = threadlint.lint_paths(tpaths, root=REPO)
    for f in tfindings:
      print(f.render())
      errors += f.severity == "error"
    say(f"graftlint thread: {len(tfindings)} finding(s) over "
        f"{len(list(astlint._iter_py_files(tpaths)))} file(s)")
    result["thread_findings"] = len(tfindings)

  if not args.ast_only:
    _setup_cpu_mesh_env()
    from distributed_embeddings_tpu.analysis import jaxpr_audit
    violations, prints = jaxpr_audit.run_audit(
        update_fingerprints=args.update_fingerprints,
        fingerprint_path=os.path.join(REPO, jaxpr_audit.FINGERPRINT_PATH),
        log=say)
    for v in violations:
      print(f"jaxpr-audit: {v}")
    errors += len(violations)
    say(f"graftlint jaxpr: {len(prints)} artifact(s), "
        f"{len(violations)} violation(s)")
    result["jaxpr_violations"] = len(violations)

  result["ok"] = errors == 0
  result["errors"] = errors
  if args.json:
    from distributed_embeddings_tpu.telemetry import emit_verdict
    return emit_verdict("graftlint", result, verbose=not args.quiet)
  if errors:
    print(f"graftlint: FAILED ({errors} error(s))")
    return 1
  say("graftlint: OK")
  return 0


if __name__ == "__main__":
  sys.exit(main())
