"""Cross-run SIGKILL chaos: kill a REAL trainer process, relaunch, verify.

`tools/chaos_train.py` injects faults *inside one process lifetime* — a
crash there is a Python exception the same process observes. Production
preemption is nothing like that: the kernel SIGKILLs the trainer, no
``finally`` runs, no buffers flush, and a NEW process (possibly on a
different worker set) must pick the run back up. This driver closes that
gap:

1. **reference**: one uninterrupted worker subprocess trains a fixed
   stream to completion, logging ``(consumed index, loss)`` per step;
2. **kill cycles**: a fresh worker is launched with a
   ``FaultInjector.kill_at`` rule — a real ``SIGKILL`` of itself at a
   deterministic fault-site event: mid-checkpoint-save (``ckpt_write`` /
   ``ckpt_rename``, leaving a torn ``.tmp``) or between steps (the
   ``sigkill`` marker the worker fires per batch). The driver asserts
   the process died by SIGKILL, then relaunches — **optionally at a
   different world size**: the relaunch auto-resumes through
   ``checkpoint.restore``'s elastic re-shard;
3. **verdict**: the stitched trajectory (run 1's committed prefix +
   the relaunch) must match the unkilled reference step-for-step —
   bit-for-bit at the same world, within an fp-associativity bound
   across a resize (the restored STATE is bit-exact; a different mesh
   reduces in a different order from the first post-resume step) — and
   the resumed accounting must satisfy ``consumed == steps + skipped``
   (the PR-2 stream-position invariant) with every injected NaN batch
   skipped exactly once across both process lifetimes;
4. **async snapshots**: one cycle runs with
   ``ResilientTrainer(async_snapshots=True)`` under an injected
   slow-storage delay and must log steps completing WHILE the writer
   thread is flushing, with an unchanged trajectory.

Run ``make chaos-kill`` — the verdict goes through
``telemetry.emit_verdict`` (the same normalized record, JSONL log hook,
and 0/1 exit-code convention as ``chaos_train.py``); the longer
multi-cycle variant is ``@pytest.mark.slow`` in ``tests/test_elastic.py``.
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if __name__ == "__main__":  # standalone: build the virtual CPU mesh
  flags = os.environ.get("XLA_FLAGS", "")
  if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
  os.environ.setdefault("JAX_PLATFORMS", "cpu")
  sys.path.insert(0, _REPO)

VOCAB = [500, 300, 150, 20]
GLOBAL_BATCH = 32  # divisible by every world size the cycles use


def _batches(n, seed=7, n_unique=6):
  """World-independent cycled batch stream (same recipe as chaos_train:
  repetition makes the short run's loss drop reliably)."""
  import numpy as np
  rng = np.random.default_rng(seed)
  out = []
  for _ in range(n_unique):
    numerical = rng.standard_normal((GLOBAL_BATCH, 13)).astype(np.float32)
    cats = [rng.integers(0, v, GLOBAL_BATCH).astype(np.int32)
            for v in VOCAB]
    labels = (numerical[:, 0] > 0).astype(np.float32)
    out.append((numerical, cats, labels))
  return [out[i % n_unique] for i in range(n)]


# ---------------------------------------------------------------------------
# worker: one trainer process lifetime
# ---------------------------------------------------------------------------


def run_worker(root: str, log_path: str, world: int, steps: int,
               nan_every: int = 6, snapshot_every: int = 4,
               kill_site: str = "", kill_event: int = -1,
               async_snapshots: bool = False,
               slow_writes: float = 0.0) -> dict:
  """Train the fixed stream from wherever the checkpoint root says the
  last lifetime stopped; append ``{"i", "loss"}`` JSONL per step."""
  import jax
  import numpy as np
  import optax

  from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
  from distributed_embeddings_tpu.models import DLRM, bce_loss
  from distributed_embeddings_tpu.ops.packed_table import sparse_rule
  from distributed_embeddings_tpu.parallel import create_mesh
  from distributed_embeddings_tpu.resilience import FaultInjector, faultinject
  from distributed_embeddings_tpu.resilience.trainer import ResilientTrainer
  from distributed_embeddings_tpu.training import (
      init_sparse_state,
      make_sparse_train_step,
      shard_batch,
      shard_params,
  )

  mesh = create_mesh(world)
  model = DLRM(vocab_sizes=VOCAB, embedding_dim=16, bottom_mlp=(32, 16),
               top_mlp=(32, 1), world_size=world, dense_row_threshold=32)
  plan = DistEmbeddingStrategy(
      [dict(input_dim=v, output_dim=16,
            initializer={"name": "uniform", "scale": 0.05}) for v in VOCAB],
      world, "basic", dense_row_threshold=32)
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.adagrad(0.05)
  batches = _batches(steps)
  nan_steps = set(range(nan_every - 1, steps, nan_every)) if nan_every \
      else set()
  stream = list(faultinject.nan_batches(batches, at_steps=nan_steps))

  numerical, cats, _ = batches[0]
  params = model.init(jax.random.PRNGKey(0), numerical,
                      [np.asarray(c) for c in cats])["params"]
  state = shard_params(init_sparse_state(plan, params, rule, opt), mesh)
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                state, batches[0], donate=False, guard=True)
  # auto-resume: a world != the saving lifetime's goes through the
  # elastic re-shard inside checkpoint.restore
  t = ResilientTrainer(step, state, plan, rule, root, mesh=mesh,
                       snapshot_every=snapshot_every,
                       async_snapshots=async_snapshots)

  inj = FaultInjector()
  if kill_site:
    inj.kill_at(kill_site, kill_event)
  if slow_writes:
    inj.delay_each("ckpt_write", slow_writes)
  overlap = 0
  with faultinject.injected(inj), open(log_path, "a") as log:
    for i in range(t.consumed, steps):
      # the between-steps kill marker: a kill_at('sigkill', k) rule dies
      # here, k steps after this lifetime's resume point
      faultinject.fire(faultinject.SIGKILL_SITE, batch=i)
      loss = t.step(*shard_batch(stream[i], mesh))
      if t.writer_active:
        overlap += 1
      log.write(json.dumps({"i": i, "loss": loss}) + "\n")
      log.flush()
    t.close()  # join an in-flight async snapshot before claiming success
  summary = {
      "world": world,
      "steps": t.step_count,
      "consumed": t.consumed,
      "skipped": t.skipped_steps,
      "expected_skips": len(nan_steps),
      "invariant_ok": t.consumed == t.step_count + t.skipped_steps,
      "overlap_steps": overlap,
      "resumed_from": t.resumed_from,
  }
  with open(log_path + ".summary", "w") as f:
    json.dump(summary, f)
  return summary


# ---------------------------------------------------------------------------
# driver: launch / kill / relaunch across real process lifetimes
# ---------------------------------------------------------------------------


def _spawn(root, log, world, steps, kill_site="", kill_event=-1,
           async_snapshots=False, slow_writes=0.0) -> int:
  env = dict(os.environ)
  env.setdefault("JAX_PLATFORMS", "cpu")
  flags = env.get("XLA_FLAGS", "")
  if "xla_force_host_platform_device_count" not in flags:
    env["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
  cmd = [sys.executable, os.path.abspath(__file__), "--worker",
         "--root", root, "--log", log, "--world", str(world),
         "--steps", str(steps)]
  if kill_site:
    cmd += ["--kill-site", kill_site, "--kill-event", str(kill_event)]
  if async_snapshots:
    cmd += ["--async-snapshots"]
  if slow_writes:
    cmd += ["--slow-writes", str(slow_writes)]
  return subprocess.run(cmd, cwd=_REPO, env=env).returncode


def _read_log(log) -> list:
  """Ordered ``(i, loss)`` records; the file appends across lifetimes,
  so a relaunch's records are exactly the tail past the kill point."""
  out = []
  if os.path.exists(log):
    with open(log) as f:
      for line in f:
        rec = json.loads(line)
        out.append((rec["i"], rec["loss"]))
  return out


def _read_summary(log):
  p = log + ".summary"
  if not os.path.exists(p):
    return None
  with open(p) as f:
    return json.load(f)


def _stitch(records) -> list:
  """Latest loss per consumed index across lifetimes, in stream order.

  Overlapping indices (a committed-but-then-replayed tail between the
  last snapshot and the kill) are resolved in favor of the LATER
  lifetime — the values training actually resumed from."""
  merged = {}
  for i, loss in records:
    merged[i] = loss
  return [merged[i] for i in sorted(merged)]


def _traj_equal(a, b) -> bool:
  import numpy as np
  return len(a) == len(b) and all(
      (np.isnan(x) and np.isnan(y)) or x == y for x, y in zip(a, b))


def _traj_close(a, b, resumed_at, rtol=5e-4, atol=1e-5) -> bool:
  """Exact before the resume point, fp-associativity bound after (the
  resized mesh reduces grads/losses in a different order — the restored
  state itself is bit-exact, pinned separately by tests/test_elastic)."""
  import numpy as np
  if len(a) != len(b):
    return False
  for i, (x, y) in enumerate(zip(a, b)):
    if np.isnan(x) or np.isnan(y):
      if not (np.isnan(x) and np.isnan(y)):
        return False
    elif i < resumed_at:
      if x != y:
        return False
    elif not np.isclose(x, y, rtol=rtol, atol=atol):
      return False
  return True


def run_chaos_kill(steps: int = 16, resize_world: int = 2,
                   verbose: bool = True, extra_cycles: bool = False) -> dict:
  """The full driver scenario; returns a verdict dict with ``ok``.

  Cycles: (A) SIGKILL mid-save, relaunch at the same world — stitched
  trajectory bit-exact vs the reference; (B) SIGKILL between steps,
  relaunch RESIZED to ``resize_world`` — elastic resume, trajectory
  exact before / allclose after the resume point, skip accounting exact
  across lifetimes; (C) async snapshots under slow storage — steps
  overlap the writer, trajectory unchanged. ``extra_cycles`` adds a
  kill at ``ckpt_rename`` (torn publication) and a resize BACK to the
  original world (N -> M -> N across lifetimes).
  """
  work = tempfile.mkdtemp(prefix="chaos_kill_")
  result = {"steps": steps, "cycles": {}}

  def cycle(name):
    root = os.path.join(work, name, "ckpts")
    log = os.path.join(work, name, "losses.jsonl")
    os.makedirs(os.path.dirname(log), exist_ok=True)
    return root, log

  # ---- reference: one uninterrupted lifetime at world 4 ------------------
  root, log = cycle("ref")
  rc = _spawn(root, log, 4, steps)
  ref_summary = _read_summary(log)
  ref = _stitch(_read_log(log))
  result["cycles"]["ref"] = {
      "rc": rc, "summary": ref_summary,
      "ok": rc == 0 and len(ref) == steps and bool(
          ref_summary and ref_summary["invariant_ok"])}

  # ---- cycle A: SIGKILL mid-save, same-world relaunch ---------------------
  # the first snapshot consumes ckpt_write events 0..7 (4 fused rank
  # files + 4 npz at world 4); event 9 dies two data files into the
  # SECOND save, leaving a manifest-less .tmp the relaunch must ignore
  root, log = cycle("mid_save")
  rc1 = _spawn(root, log, 4, steps, kill_site="ckpt_write", kill_event=9)
  torn = any(d.endswith(".tmp") for d in os.listdir(root))
  rc2 = _spawn(root, log, 4, steps)
  summary = _read_summary(log)
  traj = _stitch(_read_log(log))
  result["cycles"]["mid_save"] = {
      "killed_rc": rc1, "relaunch_rc": rc2, "torn_tmp_present": torn,
      "summary": summary,
      "trajectory_bit_exact": _traj_equal(traj, ref),
      "ok": rc1 == -signal.SIGKILL and rc2 == 0 and torn
            and _traj_equal(traj, ref)
            and bool(summary and summary["invariant_ok"]
                     and summary["skipped"] == summary["expected_skips"])}

  # ---- cycle B: SIGKILL between steps, RESIZED relaunch -------------------
  # killed at marker event 8 (after a NaN skip at stream index 5 has
  # been consumed), relaunched at a different world: the resume is an
  # elastic re-shard and the skip accounting must span both lifetimes
  root, log = cycle("resize")
  rc1 = _spawn(root, log, 4, steps, kill_site="sigkill", kill_event=8)
  n1 = len(_read_log(log))
  rc2 = _spawn(root, log, resize_world, steps)
  summary = _read_summary(log)
  records = _read_log(log)
  # the relaunch's records are the appended tail; its first index is the
  # REPLAY start (last snapshot's consumed position), and everything it
  # produced — replayed overlap included — is world-resized fp
  resumed_at = records[n1][0] if len(records) > n1 else steps
  traj = _stitch(records)
  result["cycles"]["resize"] = {
      "killed_rc": rc1, "relaunch_rc": rc2, "resumed_at": resumed_at,
      "summary": summary,
      "trajectory_matches": _traj_close(traj, ref, resumed_at),
      "ok": rc1 == -signal.SIGKILL and rc2 == 0
            and _traj_close(traj, ref, resumed_at)
            and bool(summary and summary["world"] == resize_world
                     and summary["invariant_ok"]
                     and summary["skipped"] == summary["expected_skips"])}

  # ---- cycle C: async snapshots overlap training --------------------------
  root, log = cycle("async")
  rc = _spawn(root, log, 4, steps, async_snapshots=True, slow_writes=0.05)
  summary = _read_summary(log)
  traj = _stitch(_read_log(log))
  result["cycles"]["async"] = {
      "rc": rc, "summary": summary,
      "trajectory_bit_exact": _traj_equal(traj, ref),
      "ok": rc == 0 and _traj_equal(traj, ref)
            and bool(summary and summary["overlap_steps"] > 0
                     and summary["invariant_ok"])}

  if extra_cycles:
    # torn publication: die between the manifest fsync and the rename
    root, log = cycle("mid_rename")
    rc1 = _spawn(root, log, 4, steps, kill_site="ckpt_rename",
                 kill_event=1)
    rc2 = _spawn(root, log, 4, steps)
    summary = _read_summary(log)
    traj = _stitch(_read_log(log))
    result["cycles"]["mid_rename"] = {
        "killed_rc": rc1, "relaunch_rc": rc2, "summary": summary,
        "ok": rc1 == -signal.SIGKILL and rc2 == 0
              and _traj_equal(traj, ref)
              and bool(summary and summary["invariant_ok"])}
    # N -> M -> N: kill the resized run too, come back at the original
    root, log = cycle("resize_back")
    rc1 = _spawn(root, log, 4, steps, kill_site="sigkill", kill_event=5)
    n1 = len(_read_log(log))
    rc2 = _spawn(root, log, resize_world, steps,
                 kill_site="sigkill", kill_event=4)
    rc3 = _spawn(root, log, 4, steps)
    summary = _read_summary(log)
    records = _read_log(log)
    resumed_at = records[n1][0] if len(records) > n1 else steps
    traj = _stitch(records)
    result["cycles"]["resize_back"] = {
        "rcs": [rc1, rc2, rc3], "summary": summary,
        "ok": rc1 == rc2 == -signal.SIGKILL and rc3 == 0
              and _traj_close(traj, ref, resumed_at)
              and bool(summary and summary["invariant_ok"]
                       and summary["skipped"] == summary["expected_skips"])}

  result["ok"] = all(c["ok"] for c in result["cycles"].values())
  if verbose:
    print(json.dumps(result, indent=1))
  return result


def main(argv=None) -> int:
  p = argparse.ArgumentParser(description=__doc__)
  p.add_argument("--worker", action="store_true")
  p.add_argument("--root", default="")
  p.add_argument("--log", default="")
  p.add_argument("--world", type=int, default=4)
  p.add_argument("--steps", type=int, default=16)
  p.add_argument("--kill-site", default="")
  p.add_argument("--kill-event", type=int, default=-1)
  p.add_argument("--async-snapshots", action="store_true")
  p.add_argument("--slow-writes", type=float, default=0.0)
  p.add_argument("--resize-world", type=int, default=2)
  p.add_argument("--extra-cycles", action="store_true")
  args = p.parse_args(argv)
  if args.worker:
    run_worker(args.root, args.log, args.world, args.steps,
               kill_site=args.kill_site, kill_event=args.kill_event,
               async_snapshots=args.async_snapshots,
               slow_writes=args.slow_writes)
    return 0
  from distributed_embeddings_tpu.telemetry import emit_verdict

  res = run_chaos_kill(steps=args.steps, resize_world=args.resize_world,
                       extra_cycles=args.extra_cycles, verbose=False)
  # same emitter as chaos_train.py: one verdict schema, one exit-code
  # convention, shared JSONL log hook ($DE_TPU_VERDICT_LOG)
  return emit_verdict("chaos-kill", res)


if __name__ == "__main__":
  sys.exit(main())
