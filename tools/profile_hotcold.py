"""Measure the primitives for a hot/cold-split power-law sparse path.

The Tiny/Small synthetic models are per-occurrence row-op bound
(docs/BENCHMARKS.md): 3.87M occurrences/step each pay ~19 ns gather +
~23 ns scatter + staging. Their power-law streams concentrate: with
alpha=1.05, ids < K cover ~47% (K=512) to ~63% (K=8192) of occurrences.
This tool measures every primitive a frequency-aware split would be built
from, on the REAL generator streams:

  1. full-stream fused scatter (today's apply)           [baseline]
  2. scatter with hot ids dropped (OOB sentinel)         [cold apply, no compaction]
  3. scatter on a compacted cold-only stream             [cold apply, compacted]
  4. masked one-hot head matmul fwd / fwd+bwd vs K       [hot fwd + hot apply]
  5. on-device cold compaction (searchsorted + gather)   [stream building]
  6. phys-row gather + bag-sum vs fused sub-row gather   [fwd extraction removal]
  7. cold-compacted fused gather + segment-sum combine   [cold fwd]

Usage: PYTHONPATH=/root/repo:/root/.axon_site python tools/profile_hotcold.py
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from distributed_embeddings_tpu.models.synthetic import power_law_ids
from distributed_embeddings_tpu.ops.packed_table import (
    PackedLayout,
    adagrad_rule,
    gather_fused,
)

B = 65536
ALPHA = 1.05
K_REPS = 6

# Tiny's 16-wide sparse class: (vocab, n_inputs_1hot, n_inputs_10hot)
TINY_W16 = [
    (1_000_000, 20, 1),   # 19 plain + 1 shared(1,10)
    (25_000_000, 2, 1),   # shared(1,10) + plain 1-hot
    (100_000, 2, 0),
]
RULE = adagrad_rule(0.01)
LAYOUT = PackedLayout(rows=52_200_000, width=16, n_aux=1)  # ~Tiny class rows


def build_class_stream(rng):
  """Concatenated routed id stream for the w16 class (logical ids)."""
  parts = []
  off = 0
  offsets = []
  for vocab, n1, n10 in TINY_W16:
    offsets.append((off, vocab))
    for _ in range(n1):
      parts.append(power_law_ids(rng, B, 1, vocab, ALPHA).ravel() + off)
    for _ in range(n10):
      parts.append(power_law_ids(rng, B, 10, vocab, ALPHA).ravel() + off)
    off += vocab
  return np.concatenate(parts).astype(np.int32), offsets


def _sync(x):
  # axon tunnel: block_until_ready can return before the work drains; a
  # scalar FETCH is the only reliable sync (see memory/axon-tpu-environment)
  leaf = jax.tree_util.tree_leaves(x)[0]
  float(jnp.asarray(leaf).ravel()[0])


def timeit(name, fn, buf, *args, donate=True, n_norm=None):
  """Chained donated steps, two chain lengths differenced. Returns carry so
  callers can keep the live end of a donated chain (the input is consumed)."""
  step = jax.jit(fn, donate_argnums=(0,) if donate else ())
  carry = step(buf, *args)
  _sync(carry)

  def run(n, carry):
    t0 = time.perf_counter()
    for _ in range(n):
      carry = step(carry, *args)
    _sync(carry)
    return time.perf_counter() - t0, carry

  _, carry = run(1, carry)
  t1, carry = run(K_REPS, carry)
  t2, carry = run(2 * K_REPS, carry)
  dt = (t2 - t1) / K_REPS
  per = f"  {dt / n_norm * 1e9:6.1f} ns/elem" if n_norm else ""
  print(f"{name:54s}: {dt * 1e3:8.2f} ms{per}", flush=True)
  return carry


def hot_mask_np(ids, offsets, k):
  m = np.zeros(ids.shape, bool)
  for off, vocab in offsets:
    kk = min(k, vocab)
    m |= (ids >= off) & (ids < off + kk)
  return m


def main():
  rng = np.random.default_rng(0)
  ids_np, offsets = build_class_stream(rng)
  n = ids_np.shape[0]
  rpp = LAYOUT.rows_per_phys
  print(f"class stream: {n} occurrences, rpp={rpp}, "
        f"phys_rows={LAYOUT.phys_rows}")
  for k in (512, 4096, 65536):
    cov = hot_mask_np(ids_np, offsets, k).mean()
    print(f"  coverage ids<K per table, K={k}: {cov:.3f}")

  grp_np = (ids_np // rpp).astype(np.int32)
  upd = jnp.asarray(rng.standard_normal((n, 128)).astype(np.float32) * 1e-6)

  def scatter(b, g, u):
    return b.at[g].add(u, mode="drop")

  def fresh_buf():
    return jnp.zeros((LAYOUT.phys_rows + 1, 128), jnp.float32)

  # 1. baseline full stream
  carry = timeit("scatter full stream (today)", scatter, fresh_buf(),
                 jnp.asarray(grp_np), upd, n_norm=n)
  print(f"  checksum {float(jnp.sum(carry[:8, :4])):.3e}")
  del carry

  # 2. hot ids dropped via OOB sentinel: cold apply without compaction
  for k in (512, 4096, 65536):
    hot = hot_mask_np(ids_np, offsets, k)
    grp_drop = jnp.asarray(np.where(hot, np.int32(2**31 - 1), grp_np))
    c = timeit(f"scatter hot->dropped (K={k}, cold={1-hot.mean():.2f})",
               scatter, fresh_buf(), grp_drop, upd, n_norm=n)
    del c, grp_drop

  # 2b. hot ids redirected to one dummy row (keeps stream, mega-dup)
  hot = hot_mask_np(ids_np, offsets, 4096)
  grp_dummy = jnp.asarray(np.where(hot, np.int32(LAYOUT.phys_rows), grp_np))
  c = timeit("scatter hot->dummy row (K=4096)", scatter, fresh_buf(),
             grp_dummy, upd, n_norm=n)
  del c, grp_dummy

  # 3. compacted cold-only stream
  for k in (512, 4096, 65536):
    hot = hot_mask_np(ids_np, offsets, k)
    cold_ids = grp_np[~hot]
    cn = cold_ids.shape[0]
    c = timeit(f"scatter cold-compacted (K={k}, n={cn})", scatter,
               fresh_buf(), jnp.asarray(cold_ids), upd[:cn], n_norm=cn)
    del c

  del upd

  # 4. masked one-hot head matmul: fwd and fwd+bwd, per K.
  #    All occurrences flow through (cold ids one-hot to zero), like a
  #    dense-class window. Chunked like _z_dense to bound staging.
  ids_dev = jnp.asarray(ids_np)
  # local ids for a single concatenated head of size K*len(tables): use
  # per-table local id minus offset; cold -> -1 (no one-hot)
  for k in (256, 512, 1024):
    local = np.full(n, -1, np.int32)
    base = 0
    for off, vocab in offsets:
      kk = min(k, vocab)
      sel = (ids_np >= off) & (ids_np < off + kk)
      local[sel] = ids_np[sel] - off + base
      base += kk
    head_rows = base
    local_dev = jnp.asarray(local)
    head = jnp.asarray(
        rng.standard_normal((head_rows, 16)).astype(np.float32))

    def z_head(h, ids_l):
      chunk = max(1, (1 << 25) // h.shape[0])
      nchunks = -(-n // chunk)
      pad = nchunks * chunk - n
      idsp = jnp.concatenate([ids_l, jnp.full((pad,), -1, jnp.int32)])

      def body(c, i):
        oh = jax.nn.one_hot(i, h.shape[0], dtype=jnp.bfloat16)
        z = jnp.einsum("gv,vw->gw", oh, h,
                       precision=jax.lax.Precision.HIGHEST,
                       preferred_element_type=jnp.float32)
        return c, z

      _, zs = jax.lax.scan(jax.checkpoint(body), None,
                           idsp.reshape(nchunks, chunk))
      return zs.reshape(-1, 16)[:n]

    def fwd_only(h, ids_l):
      z = z_head(h, ids_l)
      return h + 1e-12 * jnp.tanh(jnp.sum(z))  # non-linear consumer

    head = timeit(f"one-hot head fwd (K={k}, rows={head_rows})", fwd_only,
                  head, local_dev, n_norm=n)

    def fwd_bwd(h, ids_l):
      def loss(hh):
        z = z_head(hh, ids_l)
        return jnp.sum(jnp.tanh(z * 1e-3))
      g = jax.grad(loss)(h)
      return h - 1e-9 * g

    timeit(f"one-hot head fwd+bwd (K={k}, rows={head_rows})", fwd_bwd, head,
           local_dev, n_norm=n)
    del head

  # 5. on-device cold compaction: counts -> cumsum -> searchsorted -> gather
  cold_cap = int(n * 0.7)
  hot = hot_mask_np(ids_np, offsets, 4096)

  def compact(carry, ids_f):
    is_cold = ids_f < 0  # placeholder predicate; realistic: table-local < K
    # use a real predicate over concatenated offsets: approximate with two
    # range tests per table region (3 regions)
    m = jnp.zeros(ids_f.shape, bool)
    base = 0
    for off, vocab in offsets:
      kk = min(4096, vocab)
      m = m | ((ids_f >= off) & (ids_f < off + kk))
      base += kk
    is_cold = ~m
    csum = jnp.cumsum(is_cold.astype(jnp.int32))
    total = csum[-1]
    # positions of cold elements: searchsorted over csum for 1..cap
    tgt = jnp.arange(1, cold_cap + 1, dtype=jnp.int32)
    src = jnp.searchsorted(csum, tgt)
    vals = jnp.take(ids_f, jnp.clip(src, 0, n - 1), mode="clip")
    vals = jnp.where(tgt <= total, vals, -1)
    return carry + jnp.sum(vals == -12345), None

  def compact_step(carry, ids_f):
    c, _ = compact(carry, ids_f + (carry * 0).astype(jnp.int32))
    return c

  timeit("device compaction (mask+cumsum+searchsorted+take)",
         compact_step, jnp.zeros((), jnp.int32), ids_dev, donate=False,
         n_norm=n)

  # 6. phys-row gather + window-sum (10-hot bags) vs fused sub-row gather
  buf_g = jnp.zeros((LAYOUT.phys_rows + 1, 128), jnp.float32)
  ids10 = jnp.asarray(
      power_law_ids(rng, B, 10, 25_000_000, ALPHA).astype(np.int32)
      + 21_000_000)
  n10 = B * 10

  def fused_gather(c, idsb):
    idsb = idsb + (c * 0).astype(jnp.int32)
    rows = gather_fused(LAYOUT, buf_g, idsb)  # [B, 10, 32]
    z = jnp.sum(rows[..., :16], axis=1)
    return c + jnp.tanh(jnp.sum(z) * 1e-6) * 0 + jnp.float32(0)

  def phys_gather(c, idsb):
    idsb = idsb + (c * 0).astype(jnp.int32)
    grp_b = idsb // rpp
    rows = jnp.take(buf_g, grp_b, axis=0, mode="fill",
                    fill_value=0)  # [B, 10, 128]
    bag = jnp.sum(rows, axis=1)  # [B, 128]
    z = jnp.sum(bag.reshape(B, rpp, 32)[..., :16], axis=1)
    return c + jnp.tanh(jnp.sum(z) * 1e-6) * 0 + jnp.float32(0)

  timeit("fused sub-row gather 10-hot (today)", fused_gather,
         jnp.zeros((), jnp.float32), ids10, donate=False, n_norm=n10)
  timeit("phys-row gather + bag-sum 10-hot (BUT: wrong for "
         "sub-row-aliased bags? no - sum commutes)", phys_gather,
         jnp.zeros((), jnp.float32), ids10, donate=False, n_norm=n10)

  # 7. cold fused gather + segment-sum combine on a compacted ragged stream
  cold_ids10 = ids_np[~hot][:B * 4]  # ~4 cold per bag stand-in
  seg = np.sort(rng.integers(0, B, cold_ids10.shape[0])).astype(np.int32)
  cold_d = jnp.asarray(cold_ids10)
  seg_d = jnp.asarray(seg)
  nc = cold_ids10.shape[0]

  def cold_fwd(c, idsb, segb):
    idsb = idsb + (c * 0).astype(jnp.int32)
    rows = gather_fused(LAYOUT, buf_g, idsb)[:, :16]
    z = jax.ops.segment_sum(rows, segb, num_segments=B)
    return c + jnp.tanh(jnp.sum(z) * 1e-6) * 0 + jnp.float32(0)

  timeit(f"cold compacted gather+segsum (n={nc})", cold_fwd,
         jnp.zeros((), jnp.float32), cold_d, seg_d, donate=False, n_norm=nc)
  del buf_g

  # 8. WINDOW gather/scatter with 2-D (row, lane) starts: reads/writes the
  #    32-lane fused sub-row directly from/to the packed buffer — would kill
  #    both the gather-side extraction einsum and the apply-side expansion.
  stride = LAYOUT.stride  # 32
  grp_all = jnp.asarray(grp_np)
  # (id % rpp) * stride < 128 lanes of one physical row
  lane = jnp.asarray(((ids_np % rpp) * stride)  # graftlint: disable=GL106
                     .astype(np.int32))
  starts = jnp.stack([grp_all, lane], axis=1)  # [n, 2]
  bufw = jnp.zeros((LAYOUT.phys_rows + 1, 128), jnp.float32)

  gdn = jax.lax.GatherDimensionNumbers(
      offset_dims=(1,), collapsed_slice_dims=(0,), start_index_map=(0, 1))

  def win_gather(c, st):
    st = st + (c * 0).astype(jnp.int32)
    rows = jax.lax.gather(
        bufw, st, gdn, slice_sizes=(1, stride),
        mode=jax.lax.GatherScatterMode.FILL_OR_DROP)
    return c + jnp.tanh(jnp.sum(rows) * 1e-6) * 0 + jnp.float32(0)

  timeit("window-gather 2-D starts [n,32]", win_gather,
         jnp.zeros((), jnp.float32), starts, donate=False, n_norm=n)

  sdn = jax.lax.ScatterDimensionNumbers(
      update_window_dims=(1,), inserted_window_dims=(0,),
      scatter_dims_to_operand_dims=(0, 1))
  upd32 = jnp.asarray(
      rng.standard_normal((n, stride)).astype(np.float32) * 1e-6)

  def win_scatter(b, st, u):
    return jax.lax.scatter_add(
        b, st, u, sdn, mode=jax.lax.GatherScatterMode.FILL_OR_DROP)

  c = timeit("window-scatter-add 2-D starts [n,32]", win_scatter, bufw,
             starts, upd32, n_norm=n)
  print(f"  checksum {float(jnp.sum(c[:64, :4])):.3e}")
  del c

  # 9. re-run the full-stream baseline at the end (first-test artifact)
  upd = jnp.asarray(rng.standard_normal((n, 128)).astype(np.float32) * 1e-6)
  c = timeit("scatter full stream (today, re-run)", scatter,
             jnp.zeros((LAYOUT.phys_rows + 1, 128), jnp.float32),
             jnp.asarray(grp_np), upd, n_norm=n)
  del c


if __name__ == "__main__":
  main()
