"""Benchmark of the host-device overlap scheduler (`pipeline.py`).

Runs the SAME tiered power-law workload twice from identical initial
state — `overlap_host=False` (the serial loop: classify + stage +
dispatch + write-back in line) vs `overlap_host=True` (batch k+1's
classify/gather on the HostWorker while step k runs on device) — and
reports:

  - per-step wall time of both arms, and the reduction;
  - the serial step's host-pipeline vs device split (trace spans — what
    the scheduler CAN hide);
  - the hidden fraction: `tiered/overlap_hidden_s` (job seconds the
    device window absorbed) over `tiered/host_prepare` (total worker
    job seconds);
  - bit-exactness: the two arms' loss streams must be IDENTICAL — the
    overlap is a scheduling change, never a numerics change;
  - worker-track spans: the trace must show `tiered/host_prepare` on
    the `tiered-overlap` worker thread strictly inside a `device/step`
    window (the overlap, visible instead of asserted).

The bench workload is device-heavy on purpose (deep dense MLPs): the
overlap hides host time inside the device window, so the demonstrable
reduction is bounded by min(host, device) / (host + device). The gates
(`--smoke` checks machinery + parity only):

  - wall reduction >= 25%;
  - hidden fraction >= 70%;
  - overlapped wall <= 1.15 x max(host, device)  (the "toward
    max(host, device)" claim with 15% scheduling slack).

Usage: PYTHONPATH=/root/repo python tools/profile_overlap.py [--smoke]
"""

import argparse
import os
import sys
import time

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
  os.environ["XLA_FLAGS"] = (
      flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from distributed_embeddings_tpu import telemetry  # noqa: E402
from distributed_embeddings_tpu.layers.dist_model_parallel import (  # noqa: E402
    get_weights,
    set_weights,
)
from distributed_embeddings_tpu.layers.embedding import TableConfig  # noqa: E402
from distributed_embeddings_tpu.layers.planner import (  # noqa: E402
    DistEmbeddingStrategy,
)
from distributed_embeddings_tpu.models import DLRM, bce_loss  # noqa: E402
from distributed_embeddings_tpu.models.dlrm import _dlrm_initializer  # noqa: E402
from distributed_embeddings_tpu.models.synthetic import power_law_ids  # noqa: E402
from distributed_embeddings_tpu.ops.packed_table import sparse_rule  # noqa: E402
from distributed_embeddings_tpu.parallel import create_mesh  # noqa: E402
from distributed_embeddings_tpu.tiering import (  # noqa: E402
    HostTierStore,
    TieredTrainer,
    TieringConfig,
    TieringPlan,
    init_tiered_state_from_params,
)

WORLD = 4
WIDTH = 16
ALPHA = 1.05

# the serial host-pipeline stages (profile_tiering's split) vs the
# device window, summed from trace span durations
HOST_SPANS = ("tiered/classify", "tiered/stage", "tiered/write_back",
              "tiered/rerank")


def make_batches(vocab, batch, n, seed=7):
  r = np.random.default_rng(seed)
  out = []
  for _ in range(n):
    numerical = r.standard_normal((batch, 13)).astype(np.float32)
    cats = [power_law_ids(r, batch, 1, v, ALPHA).astype(np.int32)[:, 0]
            for v in vocab]
    labels = r.integers(0, 2, batch).astype(np.float32)
    out.append((numerical, cats, labels))
  return out


def build_trainer(vocab, batch, mlp, staging, frac, overlap, batch0):
  """One arm: a tiered trainer from DETERMINISTIC params (both arms
  init from the same seeds, so their states — and losses — match)."""
  tables = [TableConfig(input_dim=v, output_dim=WIDTH,
                        initializer=_dlrm_initializer(v)) for v in vocab]
  plan = DistEmbeddingStrategy(tables, WORLD, "memory_balanced",
                               dense_row_threshold=0,
                               host_row_threshold=1000)
  model = DLRM(vocab_sizes=vocab, embedding_dim=WIDTH, bottom_mlp=mlp[0],
               top_mlp=mlp[1], world_size=WORLD,
               strategy="memory_balanced", dense_row_threshold=0)
  mesh = create_mesh(WORLD)
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.adam(1e-3)
  params_b = model.init(jax.random.PRNGKey(0), batch0[0],
                        batch0[1])["params"]
  plan_b = DistEmbeddingStrategy(tables, WORLD, "memory_balanced",
                                 dense_row_threshold=0)
  tables_t = set_weights(plan, get_weights(plan_b, params_b["embeddings"]))
  params = {k: v for k, v in params_b.items() if k != "embeddings"}
  params["embeddings"] = {k: jnp.asarray(v) for k, v in tables_t.items()}
  tplan = TieringPlan(plan, rule, TieringConfig(cache_fraction=frac,
                                                staging_grps=staging,
                                                rerank_interval=0))
  store = HostTierStore(tplan)
  from distributed_embeddings_tpu.training import shard_params
  state = shard_params(init_tiered_state_from_params(
      tplan, store, rule, params, opt, mesh=mesh), mesh)
  return TieredTrainer(model, tplan, store, bce_loss, opt, rule, mesh,
                       state, batch0, donate=False, overlap_host=overlap)


def timed_window(trainer, batches):
  """Run one traced, wall-clocked window; returns (losses, wall_s,
  chrome_trace)."""
  tracer = telemetry.Tracer()
  telemetry.install_tracer(tracer)
  try:
    t0 = time.perf_counter()
    losses = trainer.run(batches)
    wall = time.perf_counter() - t0
  finally:
    telemetry.uninstall_tracer()
  return losses, wall, tracer.to_chrome()


def span_ms_per_step(chrome, names, n_steps):
  tot = sum(e["dur"] for e in chrome["traceEvents"]
            if e.get("ph") == "X" and e["name"] in names)
  return tot / n_steps / 1e3  # trace ts/dur are in microseconds


def worker_overlap_spans(chrome):
  """Count `tiered/host_prepare` spans on the worker thread strictly
  inside a `device/step` window."""
  tracks = {e["tid"]: e["args"]["name"] for e in chrome["traceEvents"]
            if e.get("name") == "thread_name"}
  worker_tids = {t for t, n in tracks.items() if n == "tiered-overlap"}
  device_tids = {t for t, n in tracks.items() if n == "device"}
  dev = [e for e in chrome["traceEvents"] if e.get("ph") == "X"
         and e["name"] == "device/step" and e["tid"] in device_tids]
  inside = 0
  for c in (e for e in chrome["traceEvents"] if e.get("ph") == "X"
            and e["name"] == "tiered/host_prepare"
            and e["tid"] in worker_tids):
    if any(d["ts"] < c["ts"] and c["ts"] + c["dur"] < d["ts"] + d["dur"]
           for d in dev):
      inside += 1
  return inside


def run(smoke: bool) -> dict:
  if smoke:
    vocab, batch, steps, warm = [2000, 300, 40], 64, 8, 3
    mlp, staging, frac = ((32, WIDTH), (32, 1)), 64, 0.3
  else:
    vocab, batch, steps, warm = [200_000, 20_000, 300], 256, 20, 4
    # device-heavy dense stack: the overlap hides the host pipeline
    # inside a device window big enough to hold it
    mlp, staging, frac = ((1024, 512, WIDTH), (1024, 512, 1)), 2048, 0.15
  batches = make_batches(vocab, batch, warm + steps)
  result = {"world": WORLD, "vocab": vocab, "batch": batch,
            "steps": steps, "alpha": ALPHA}

  reg = telemetry.get_registry()
  arms = {}
  for name, overlap in (("serial", False), ("overlap", True)):
    t = build_trainer(vocab, batch, mlp, staging, frac, overlap,
                      batches[0])
    t.run(batches[:warm])  # compile + residency warmup outside the clock
    h0 = (reg.histogram("tiered/overlap_hidden_s").sum,
          reg.histogram("tiered/host_prepare").sum)
    losses, wall, chrome = timed_window(t, batches[warm:])
    arms[name] = {
        "losses": losses, "wall_ms": wall / steps * 1e3, "chrome": chrome,
        "hidden_s": reg.histogram("tiered/overlap_hidden_s").sum - h0[0],
        "job_s": reg.histogram("tiered/host_prepare").sum - h0[1],
    }

  ser, ovl = arms["serial"], arms["overlap"]
  host_ms = span_ms_per_step(ser["chrome"], HOST_SPANS, steps)
  dev_ms = span_ms_per_step(ser["chrome"], ("device/step",), steps)
  parity = bool(np.array_equal(np.asarray(ser["losses"]),
                               np.asarray(ovl["losses"])))
  reduction = 1.0 - ovl["wall_ms"] / ser["wall_ms"]
  hidden_frac = (ovl["hidden_s"] / ovl["job_s"]) if ovl["job_s"] else 0.0
  bound_ms = 1.15 * max(host_ms, dev_ms)
  spans_inside = worker_overlap_spans(ovl["chrome"])
  result.update({
      "serial_ms": ser["wall_ms"], "overlap_ms": ovl["wall_ms"],
      "host_ms": host_ms, "device_ms": dev_ms,
      "reduction": reduction, "hidden_frac": hidden_frac,
      "bound_ms": bound_ms,
      "worker_spans_inside_device_window": spans_inside,
      "losses_bit_exact": parity,
  })
  if smoke:
    # machinery gates only: CPU-mesh step times at toy scale are noise
    result["ok"] = bool(parity and spans_inside > 0
                        and np.isfinite(reduction) and ovl["job_s"] > 0)
  else:
    result["ok"] = bool(parity and spans_inside > 0
                        and reduction >= 0.25
                        and hidden_frac >= 0.70
                        and ovl["wall_ms"] <= bound_ms)
  return result


if __name__ == "__main__":
  ap = argparse.ArgumentParser()
  ap.add_argument("--smoke", action="store_true",
                  help="tiny tier for make verify (parity + worker "
                       "spans only; no perf gates)")
  args = ap.parse_args()
  res = run(smoke=args.smoke)
  res.pop("chrome", None)
  sys.exit(telemetry.emit_verdict(
      "overlap-smoke" if args.smoke else "overlap-bench", res))
