"""Tiny synthetic model step ablation: route / gather / combine / apply.

Usage: python tools/profile_tiny_parts.py [batch]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.models import (
    SYNTHETIC_MODELS,
    SyntheticModel,
    bce_loss,
    expand_tables,
    generate_batch,
)
from distributed_embeddings_tpu.ops.packed_table import adagrad_rule
from distributed_embeddings_tpu.parallel.lookup_engine import DistributedLookup
from distributed_embeddings_tpu.training import init_sparse_state_direct

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
K = 4


def main():
  cfg = SYNTHETIC_MODELS["tiny"]
  tables, tmap, hotness = expand_tables(cfg)
  model = SyntheticModel(config=cfg, world_size=1)
  plan = DistEmbeddingStrategy(tables, 1, "basic", input_table_map=tmap,
                               dense_row_threshold=model.dense_row_threshold,
                               input_hotness=hotness, batch_hint=BATCH)
  engine = DistributedLookup(plan)
  rule = adagrad_rule(0.01)
  layouts = engine.fused_layouts(rule)
  numerical, cats, labels = generate_batch(cfg, BATCH, alpha=1.05, seed=0)
  cats = [np.minimum(c, tables[t].input_dim - 1).astype(np.int32)
          for c, t in zip(cats, tmap)]
  cats = [jnp.asarray(c if h > 1 else c[:, 0])
          for c, h in zip(cats, hotness)]
  hotness_of = lambda i: hotness[i]  # noqa: E731

  dummy_acts = [jnp.zeros((2, tables[t].output_dim), jnp.float32)
                for t in tmap]
  dense_params = model.init(jax.random.PRNGKey(0),
                            jnp.asarray(numerical[:2]), [c[:2] for c in cats],
                            emb_acts=dummy_acts)["params"]
  state = init_sparse_state_direct(plan, rule, dense_params,
                                   optax.adagrad(0.01), jax.random.PRNGKey(1))
  fused = state["fused"]
  jax.block_until_ready(fused)

  def timeit(name, body):
    step = jax.jit(body)
    c = step(fused, jnp.zeros((), jnp.float32))
    float(c)

    def run(n, c):
      t0 = time.perf_counter()
      for _ in range(n):
        c = step(fused, c)
      float(c)
      return time.perf_counter() - t0, c

    _, c = run(1, c)
    t1, c = run(K, c)
    t2, c = run(2 * K, c)
    print(f"{name:26s}: {(t2 - t1) / K * 1e3:8.2f} ms", flush=True)

  def dep_cats(carry):
    bump = (carry * 0).astype(jnp.int32)
    return [c + bump for c in cats]

  def route_only(fused, carry):
    ids_all = engine.route_ids(dep_cats(carry), hotness_of)
    s = sum((v[0] if isinstance(v, tuple) else v).sum()
            for v in ids_all.values())
    return carry + s.astype(jnp.float32) * 0

  timeit("route_ids", route_only)

  def gather_only(fused, carry):
    ids_all = engine.route_ids(dep_cats(carry), hotness_of)
    z, _ = engine.lookup_sparse_fused(fused, layouts, ids_all)
    return carry + sum(zb.sum() for zb in z.values()).astype(jnp.float32) * 0

  timeit("route+gather+combine", gather_only)

  def fwd_all(fused, carry):
    ids_all = engine.route_ids(dep_cats(carry), hotness_of)
    z, _ = engine.lookup_sparse_fused(fused, layouts, ids_all)
    acts = engine.finish_forward(z, state["emb_dense"], ids_all, BATCH,
                                 hotness_of)
    logits = model.apply({"params": state["dense"]},
                         jnp.asarray(numerical), cats, emb_acts=acts)
    return carry + bce_loss(logits, jnp.asarray(labels)) * 0

  timeit("forward(loss)", fwd_all)

  def apply_only(fused, carry):
    ids_all = engine.route_ids(dep_cats(carry), hotness_of)
    z, res = engine.lookup_sparse_fused(fused, layouts, ids_all)
    d_z = {bk: zb * 1e-9 for bk, zb in z.items()}
    new = engine.apply_sparse(fused, layouts, d_z, res, rule,
                              jnp.zeros((), jnp.int32))
    return carry + sum(v[0, 0] for v in new.values()).astype(jnp.float32) * 0

  timeit("route+gather+apply", apply_only)


if __name__ == "__main__":
  main()
