"""Break down DLRM model fwd+bwd cost: MLPs, interaction, precision.

Usage: python tools/profile_model_parts.py [batch]
"""

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
K = 8
W = 128
N_TABLES = 26


def timeit(name, fn, *args):
  step = jax.jit(fn)
  carry = step(*args)
  jax.block_until_ready(carry)
  float(carry)  # fetch warmup

  def run(n):
    c = carry
    t0 = time.perf_counter()
    for _ in range(n):
      c = step(*args)
    float(c)
    return time.perf_counter() - t0

  t1 = run(K)
  t2 = run(2 * K)
  print(f"{name:40s}: {(t2 - t1) / K * 1e3:8.2f} ms", flush=True)


def main():
  key = jax.random.PRNGKey(0)
  rng = np.random.default_rng(0)
  x13 = jnp.asarray(rng.standard_normal((BATCH, 13)), jnp.float32)
  labels = jnp.asarray(rng.integers(0, 2, BATCH), jnp.float32)
  acts = [jax.random.normal(jax.random.fold_in(key, i), (BATCH, W),
                            jnp.float32) for i in range(N_TABLES)]

  import flax.linen as nn
  from distributed_embeddings_tpu.models.dlrm import MLP, dot_interact, bce_loss

  bottom = MLP((512, 256, 128), activate_final=True)
  top = MLP((1024, 1024, 512, 256, 1))
  pb = bottom.init(key, x13)["params"]

  f = N_TABLES + 1
  inter_dim = f * (f - 1) // 2 + W
  xi = jax.random.normal(key, (BATCH, inter_dim), jnp.float32)
  pt = top.init(key, xi)["params"]

  def bottom_loss(p):
    return jnp.sum(bottom.apply({"params": p}, x13))

  def top_loss(p):
    logits = jnp.squeeze(top.apply({"params": p}, xi), -1)
    return bce_loss(logits, labels)

  def inter_loss(b_out, acts):
    return jnp.sum(dot_interact(b_out, acts))

  b_out = jax.random.normal(key, (BATCH, W), jnp.float32)

  timeit("bottom fwd", bottom_loss, pb)

  def bottom_vg(p):
    l, g = jax.value_and_grad(bottom_loss)(p)
    return l + sum(jnp.sum(v) for v in jax.tree_util.tree_leaves(g)) * 1e-30

  def top_vg(p):
    l, g = jax.value_and_grad(top_loss)(p)
    return l + sum(jnp.sum(v) for v in jax.tree_util.tree_leaves(g)) * 1e-30

  def inter_vg(b_out, acts):
    l, (gb, ga) = jax.value_and_grad(inter_loss, argnums=(0, 1))(b_out, acts)
    return l + jnp.sum(gb) * 1e-30 + sum(a.sum() for a in ga) * 1e-30

  timeit("bottom fwd+bwd", bottom_vg, pb)
  timeit("top fwd", top_loss, pt)
  timeit("top fwd+bwd", top_vg, pt)
  timeit("interact fwd", inter_loss, b_out, acts)
  timeit("interact fwd+bwd", inter_vg, b_out, acts)

  # precision sweep on the top MLP (the FLOPs king)
  for prec in ("bfloat16", "tensorfloat32", "float32", "highest"):
    with jax.default_matmul_precision(prec):
      def top_vg_p(p):
        l, g = jax.value_and_grad(top_loss)(p)
        return l + sum(jnp.sum(v) for v in jax.tree_util.tree_leaves(g)) \
            * 1e-30
      timeit(f"top fwd+bwd prec={prec}", top_vg_p, pt)

  # bf16 compute dtype (params f32, compute bf16 = AMP)
  top16 = MLP((1024, 1024, 512, 256, 1), dtype=jnp.bfloat16)

  def top16_vg(p):
    def loss(p):
      logits = jnp.squeeze(top16.apply({"params": p}, xi), -1)
      return bce_loss(logits, labels)
    l, g = jax.value_and_grad(loss)(p)
    return l + sum(jnp.sum(v) for v in jax.tree_util.tree_leaves(g)) * 1e-30

  timeit("top fwd+bwd bf16 compute", top16_vg, pt)


if __name__ == "__main__":
  main()
