"""Online-learning freshness bench: train -> delta-publish -> serve, live.

The measurement the streaming subsystem exists for: a trainer keeps
stepping on a power-law churn stream while a SEPARATE serving stack — a
``ServeEngine`` fed by a ``DeltaSubscriber`` poll thread, fronted by the
``MicroBatcher`` with client threads submitting concurrent requests —
adopts row-granular deltas published every few steps. Reported:

- **freshness**: the ``stream/freshness_s`` histogram (train-step ->
  servable wall lag, measured per promotion from the publisher's wall
  anchors), under the concurrent serve load — p50/p99/max;
- **delta economy**: mean delta bytes vs the full base-export bytes on
  the churn workload (row-granular publication only pays for rows the
  interval's batches actually touched);
- **convergence + exactness**: every published delta applied, zero
  refusals, zero dropped requests, and the delta-folded serve state
  byte-identical to a full re-export at the final watermark;
- **live scrape**: the registry's ``/metrics`` HTTP endpoint serves the
  stream counters while the loop runs;
- **back-pressure**: mid-run the subscriber's poll thread pauses while
  the publisher (``max_subscriber_lag``) keeps training — publication
  defers once the live heartbeat lags, the deferred intervals coalesce
  into one superset delta when polling resumes, and the report prices
  the publisher's THROTTLE OCCUPANCY (deferred / attempted);
- **cold-start economics**: a fresh subscriber replays the FULL chain
  (timed, delta bytes summed), then the chain is compacted through
  ``head - 1`` and a second cold start loads compacted base + the
  one-delta tail — the report compares replay bytes and wall time.

Acceptance (docs/BENCHMARKS.md round 11/12): mean delta bytes <= 50% of
the full-export bytes (expected far below), all deltas applied with the
delta-folded state bit-exact vs re-export, finite freshness
percentiles, and (bench tier) cold-start base+tail replay <= 25% of the
full-chain replay delta bytes. ``--smoke`` is the ``make verify`` tier:
tiny world, same structural assertions plus one compaction cycle.

Usage: PYTHONPATH=/root/repo python tools/profile_freshness.py [--smoke]
"""

import argparse
import os
import sys
import threading
import urllib.request

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
  os.environ["XLA_FLAGS"] = (
      flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from distributed_embeddings_tpu import telemetry  # noqa: E402
from distributed_embeddings_tpu.layers.dist_model_parallel import (  # noqa: E402
    set_weights,
)
from distributed_embeddings_tpu.layers.embedding import TableConfig  # noqa: E402
from distributed_embeddings_tpu.layers.planner import (  # noqa: E402
    DistEmbeddingStrategy,
)
from distributed_embeddings_tpu.models.synthetic import power_law_ids  # noqa: E402
from distributed_embeddings_tpu.ops.packed_table import sparse_rule  # noqa: E402
from distributed_embeddings_tpu.parallel import create_mesh  # noqa: E402
from distributed_embeddings_tpu.serving import (  # noqa: E402
    MicroBatcher,
    Rejected,
    ServeEngine,
)
from distributed_embeddings_tpu.serving.export import (  # noqa: E402
    export as serve_export,
)
from distributed_embeddings_tpu.serving.export import (  # noqa: E402
    load as serve_load,
)
from distributed_embeddings_tpu.streaming import (  # noqa: E402
    DeltaCompactor,
    DeltaPublisher,
    DeltaSubscriber,
    RowGenerationTracker,
    artifact_bytes,
    delta_dirname,
)
from distributed_embeddings_tpu.training import (  # noqa: E402
    init_sparse_state,
    make_sparse_train_step,
    shard_batch,
    shard_params,
)


class ActsModel:
  """Embedding activations straight through — the serve path's row
  bytes are the whole workload, which is what freshness prices."""

  def apply(self, variables, numerical, cats, emb_acts=None):
    del variables, numerical, cats
    return jnp.concatenate(list(emb_acts), axis=-1)


def loss_fn(preds, labels):
  return jnp.mean((jnp.sum(preds, axis=-1) - labels) ** 2)


def churn_batch(rng, sizes, hotness, b, step, drift=0.01):
  """Power-law head + a tail window drifting with ``step`` — each
  interval touches the hot head plus a moving sliver of the tail."""
  cats = []
  for s, h in zip(sizes, hotness):
    ids = power_law_ids(rng, b, h, s, 1.1).astype(np.int32)
    shift = int(step * drift * s)
    tail = rng.random(ids.shape) < 0.15
    ids[tail] = (ids[tail] + shift) % s
    cats.append(ids)
  numerical = rng.standard_normal((b, 4)).astype(np.float32)
  labels = rng.integers(0, 2, b).astype(np.float32)
  return numerical, cats, labels


def cold_start(plan, mesh, pubdir, head_seq, registry):
  """Time a fresh subscriber from the pubdir base to ``head_seq``;
  returns ``(elapsed_s, replay_delta_bytes, deltas_folded, sub)``. The
  probe is heartbeat-free so it never joins the back-pressure quorum or
  pins the GC retention floor."""
  with telemetry.timed("fresh/cold_start", registry) as tm:
    sub = DeltaSubscriber.from_artifact(ActsModel(), plan, pubdir,
                                        mesh=mesh, telemetry=registry,
                                        heartbeat=False)
    start = sub.applied_seq
    while sub.applied_seq < head_seq:
      if sub.poll_once() == 0:
        break
  replay_bytes = sum(
      artifact_bytes(os.path.join(pubdir, delta_dirname(s)))
      for s in range(start + 1, sub.applied_seq + 1))
  return tm.elapsed, replay_bytes, sub.applied_seq - start, sub


def run(world, sizes, hotness, intervals, steps_per_interval, b,
        quantize, pubdir, n_clients=2, max_subscriber_lag=3,
        pause_at=None, pause_intervals=0):
  rng = np.random.default_rng(0)
  widths = [16] * len(sizes)
  tables = [TableConfig(s, w, combiner="sum")
            for s, w in zip(sizes, widths)]
  plan = DistEmbeddingStrategy(tables, world, "memory_balanced",
                               dense_row_threshold=0,
                               input_hotness=hotness)
  weights = [rng.standard_normal((s, w)).astype(np.float32) * 0.1
             for s, w in zip(sizes, widths)]
  params = {"embeddings": {k: jnp.asarray(v)
                           for k, v in set_weights(plan, weights).items()}}
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.sgd(0.01)
  mesh = create_mesh(world) if world > 1 else None
  state = shard_params(init_sparse_state(plan, params, rule, opt), mesh)
  batch0 = churn_batch(rng, sizes, hotness, b, 0)
  step_fn = make_sparse_train_step(ActsModel(), plan, loss_fn, opt, rule,
                                   mesh, state, batch0, donate=False)

  registry = telemetry.MetricsRegistry()
  tracker = RowGenerationTracker(plan)
  # heartbeat_ttl far above any plausible pause: the paused subscriber
  # stops heartbeating (poll_once is the only writer), and an expired
  # heartbeat would drop it from the quorum and silently end the
  # throttling the bench is asserting — a timing flake on slow CI
  publisher = DeltaPublisher(pubdir, plan, rule, tracker,
                             quantize=quantize, telemetry=registry,
                             max_subscriber_lag=max_subscriber_lag,
                             heartbeat_ttl_s=600.0)

  # warm + root the chain
  step_no = 0
  for _ in range(steps_per_interval):
    batch = churn_batch(rng, sizes, hotness, b, step_no)
    publisher.observe_batch(batch[1])
    state, _ = step_fn(state, *shard_batch(batch, mesh))
    step_no += 1
  publisher.publish_base(state)
  base_bytes = artifact_bytes(os.path.join(pubdir, "base"))

  sub = DeltaSubscriber.from_artifact(ActsModel(), plan, pubdir,
                                      mesh=mesh, poll_interval_s=0.01,
                                      telemetry=registry).start()
  batcher = MicroBatcher(sub.dispatch, max_batch=b, max_delay_s=0.002,
                         registry=registry)
  scrape = telemetry.MetricsServer(registry)

  stop = threading.Event()
  client_failures = []
  served = [0]

  def client(seed):
    r = np.random.default_rng(seed)
    while not stop.is_set():
      n = int(r.integers(1, b + 1))
      numerical, cats, _ = churn_batch(r, sizes, hotness, n,
                                       int(r.integers(0, 100)))
      try:
        batcher.submit(numerical, cats).result(timeout=60.0)
        served[0] += 1  # benign race: a throughput indicator, not a pin
      except Rejected:
        pass  # load shed is counted by the batcher itself
      except Exception as e:  # noqa: BLE001 — collected for the verdict
        client_failures.append(repr(e))
        return

  clients = [threading.Thread(target=client, args=(1000 + i,),
                              daemon=True) for i in range(n_clients)]
  for c in clients:
    c.start()

  delta_bytes = []
  try:
    with telemetry.timed("fresh/loop", registry):
      interval_no = 0
      for _ in range(intervals):
        if pause_at is not None and interval_no == pause_at:
          # back-pressure scenario: the subscriber's poll thread stalls
          # (its heartbeat stays LIVE — the process is up, just slow),
          # the publisher keeps training, and once the lag reaches
          # max_subscriber_lag publication defers until polling resumes
          sub.stop()
        if pause_at is not None \
            and interval_no == pause_at + pause_intervals:
          sub.start()
        for _ in range(steps_per_interval):
          batch = churn_batch(rng, sizes, hotness, b, step_no)
          publisher.observe_batch(batch[1])
          state, _ = step_fn(state, *shard_batch(batch, mesh))
          step_no += 1
        if publisher.publish_delta(state) is not None:
          delta_bytes.append(publisher.last_publish_bytes)
        interval_no += 1
      sub.start()  # idempotent; revives the poller if a pause ran long
      # let polling catch back up, then ship any deferred (coalesced)
      # rows in one superset delta
      deadline_polls = 500
      while sub.applied_seq < publisher.seq and deadline_polls > 0:
        stop.wait(0.02)
        deadline_polls -= 1
      if publisher.publish_delta(state) is not None:
        delta_bytes.append(publisher.last_publish_bytes)
      # one post-recovery interval so the chain TAIL is a typical delta
      # (the coalesced superset above would otherwise dominate the tail
      # the cold-start economics below measure)
      for _ in range(steps_per_interval):
        batch = churn_batch(rng, sizes, hotness, b, step_no)
        publisher.observe_batch(batch[1])
        state, _ = step_fn(state, *shard_batch(batch, mesh))
        step_no += 1
      deadline_polls = 500
      while sub.applied_seq < publisher.seq and deadline_polls > 0:
        stop.wait(0.02)
        deadline_polls -= 1
      if publisher.publish_delta(state) is not None:
        delta_bytes.append(publisher.last_publish_bytes)
    # let the poll thread drain the tail of the chain
    deadline_polls = 500
    while sub.applied_seq < publisher.seq and deadline_polls > 0:
      stop.wait(0.02)
      deadline_polls -= 1
    scrape_text = urllib.request.urlopen(scrape.url, timeout=5
                                         ).read().decode()
  finally:
    stop.set()
    for c in clients:
      c.join(timeout=30.0)
    batcher.close()
    sub.stop()
    scrape.close()

  # exactness: the delta-folded serve state == a full re-export now
  full = os.path.join(pubdir, "full_reexport")
  serve_export(full, plan, rule, state, quantize=quantize)
  art = serve_load(full, plan, mesh=mesh)
  bit_exact = all(
      np.array_equal(np.asarray(sub.engine.state["serve"][n]).view(np.uint8),
                     np.asarray(a).view(np.uint8))
      for n, a in art.state["serve"].items())

  # cold-start economics: full-chain replay, then compact and re-probe
  head = publisher.seq
  full_s, full_replay_bytes, full_deltas, _probe = cold_start(
      plan, mesh, pubdir, head, telemetry.MetricsRegistry())
  compacted = DeltaCompactor(pubdir, telemetry=registry).compact_once(
      through_seq=max(head - 1, 0) or None)
  tail_s, tail_replay_bytes, tail_deltas, cold_sub = cold_start(
      plan, mesh, pubdir, head, telemetry.MetricsRegistry())
  cold_exact = cold_sub.applied_seq == head and all(
      np.array_equal(np.asarray(cold_sub.engine.state["serve"][n])
                     .view(np.uint8),
                     np.asarray(a).view(np.uint8))
      for n, a in art.state["serve"].items())

  throttled = registry.counter("stream/publishes_throttled").value
  attempted = throttled + registry.counter(
      "stream/deltas_published").value

  fresh = sub.freshness
  stats = batcher.stats
  return {
      "world": world,
      "quantize": quantize,
      "train_steps": step_no,
      "deltas_published": publisher.seq,
      "deltas_applied": sub.applied_seq,
      "refusals": registry.counter("stream/deltas_refused").value,
      "requests_completed": stats["completed"],
      "requests_rejected": stats["rejected"],
      "client_failures": client_failures,
      "served_during_stream": served[0],
      "freshness_s": {
          "count": fresh.count,
          "p50": fresh.p50,
          "p99": fresh.p99,
          "max": fresh.max,
      },
      "base_bytes": base_bytes,
      "delta_bytes_mean": (float(np.mean(delta_bytes))
                           if delta_bytes else 0.0),
      "delta_bytes_max": (int(np.max(delta_bytes)) if delta_bytes else 0),
      "delta_to_full_ratio": (float(np.mean(delta_bytes)) / base_bytes
                              if delta_bytes else 0.0),
      "bit_exact_vs_reexport": bool(bit_exact),
      "metrics_scrape_ok": "stream_freshness_s" in scrape_text,
      "loop_s": registry.histogram("fresh/loop").sum,
      "throttle": {
          "throttled": throttled,
          "coalesced": registry.counter("stream/deltas_coalesced").value,
          "occupancy": throttled / attempted if attempted else 0.0,
      },
      "cold_start": {
          "full_chain": {"s": full_s, "replay_bytes": full_replay_bytes,
                         "deltas": full_deltas},
          "base_tail": {"s": tail_s, "replay_bytes": tail_replay_bytes,
                        "deltas": tail_deltas},
          "replay_bytes_ratio": (tail_replay_bytes / full_replay_bytes
                                 if full_replay_bytes else 0.0),
          "time_ratio": tail_s / full_s if full_s else 0.0,
          "compacted_through": (compacted or {}).get("through_seq"),
          "gc_removed": (compacted or {}).get("gc_removed"),
          "cold_exact": bool(cold_exact),
      },
  }


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument("--smoke", action="store_true",
                  help="tiny-world make-verify tier (same assertions)")
  ap.add_argument("--quantize", default="int8",
                  choices=["f32", "int8", "fp8"])
  args = ap.parse_args()

  import tempfile
  pubdir = tempfile.mkdtemp(prefix="fresh_bench_")
  if args.smoke:
    result = run(world=2, sizes=[4000, 600], hotness=[2, 1],
                 intervals=4, steps_per_interval=2, b=16,
                 quantize=args.quantize, pubdir=pubdir, n_clients=2)
  else:
    result = run(world=4, sizes=[50000, 8000, 1200], hotness=[3, 2, 1],
                 intervals=12, steps_per_interval=4, b=64,
                 quantize=args.quantize, pubdir=pubdir, n_clients=3,
                 pause_at=6, pause_intervals=5)

  checks = {
      "all_deltas_applied": bool(result["deltas_published"] > 0
                                 and result["deltas_applied"]
                                 == result["deltas_published"]
                                 and result["refusals"] == 0),
      "no_client_failures": not result["client_failures"],
      "requests_served": bool(result["requests_completed"] > 0),
      "bit_exact_vs_reexport": result["bit_exact_vs_reexport"],
      "freshness_measured": bool(
          result["freshness_s"]["count"] >= result["deltas_published"]
          and np.isfinite(result["freshness_s"]["p99"])),
      "delta_bytes_below_half_full": bool(
          result["delta_to_full_ratio"] < 0.5),
      "metrics_scrape_ok": bool(result["metrics_scrape_ok"]),
      # one compaction cycle: a cold start on the compacted base + tail
      # replays fewer delta bytes than the full chain and lands on the
      # same serve bytes
      "compaction_cold_start_exact": bool(
          result["cold_start"]["cold_exact"]),
      "compaction_shrinks_replay": bool(
          result["cold_start"]["replay_bytes_ratio"] < 1.0
          or result["cold_start"]["full_chain"]["deltas"] <= 1),
  }
  if not args.smoke:
    # acceptance: cold start from compacted base+tail replays <= 25% of
    # the full-chain delta bytes on the bench workload
    checks["cold_start_replay_below_quarter"] = bool(
        result["cold_start"]["replay_bytes_ratio"] <= 0.25)
    # the paused-subscriber phase must actually defer publication (and
    # the resume coalesce it)
    checks["backpressure_throttled"] = bool(
        result["throttle"]["throttled"] > 0
        and result["throttle"]["coalesced"] > 0)
  result["checks"] = checks
  result["ok"] = all(checks.values())
  sys.exit(telemetry.emit_verdict("fresh_bench", result))


if __name__ == "__main__":
  main()
