"""Plan-scale dryrun for the big synthetic zoo configs (medium -> jumbo).

Builds each config's plan at shrunken vocab, jits one fused train step
over an 8-virtual-device CPU mesh, and records plan/trace wall time —
proof that the engine's bucket/slot caches keep thousand-table models
tractable (`lookup_engine._bucket_cache`; reference scale claim:
`config_v3.py`). Shared recipe: `utils/zoo_bench.run_zoo_plan_step`.

Usage: PYTHONPATH=/root/repo python tools/plan_scale_dryrun.py [medium|large|jumbo ...]
"""

import os
import sys

if "xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""):
  os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                             + " --xla_force_host_platform_device_count=8"
                             ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from distributed_embeddings_tpu.parallel import create_mesh  # noqa: E402
from distributed_embeddings_tpu.utils.zoo_bench import (  # noqa: E402
    run_zoo_plan_step,
)

WORLD = 8


if __name__ == "__main__":
  mesh = create_mesh(WORLD)
  for name in (sys.argv[1:] or ["medium", "large", "jumbo"]):
    r = run_zoo_plan_step(name, mesh, WORLD)
    assert np.isfinite(r["loss"]), r
    print(f"{r['name']:7s}: {r['tables']:5d} tables {r['inputs']:5d} inputs "
          f"{r['classes']:3d} classes | plan {r['plan_s']:6.2f}s  "
          f"model-init {r['init_s']:5.1f}s  "
          f"trace+compile+step {r['step_s']:6.1f}s  "
          f"loss {r['loss']:.5f}", flush=True)
