"""Real-TPU smoke test for the Pallas RMW apply kernel.

Runs the directed duplicate/eviction/OOB cases plus a randomized power-law
check against XLA's scatter-add ON THE REAL CHIP (the kernel's DMA
aliasing semantics cannot be validated in interpret mode: interpret does
not alias input and output buffers, so reads see stale data).

Run: make tpu-smoke   (or: python tools/smoke_pallas_apply.py)
Exit code 0 = all cases pass.
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np

from distributed_embeddings_tpu.ops.pallas_apply import apply_rows_cached

W = 128
FAILED = []


def check(name, ids, rows=16, slots=4, chunk=128):
  ids = jnp.asarray(np.asarray(ids, np.int32))
  n = ids.shape[0]
  delta = jnp.arange(1, n + 1, dtype=jnp.float32)[:, None] \
      * jnp.ones((n, W), jnp.float32)
  clip = jnp.where((ids >= 0) & (ids < rows), ids, rows)
  want = jnp.zeros((rows + 1, W), jnp.float32).at[clip].add(delta)[:rows]
  got = apply_rows_cached(jnp.zeros((rows, W), jnp.float32), ids, delta,
                          slots=slots, chunk=chunk)
  ok = bool(jnp.allclose(got, want, atol=1e-5))
  print(f"{name:34s}: {'OK' if ok else 'FAIL'}")
  if not ok:
    FAILED.append(name)


def main():
  if jax.default_backend() == "cpu":
    print("SKIP: no TPU backend (kernel requires real DMA aliasing)")
    return
  # The shared golden vectors (tests/pallas_goldens.py): the SAME
  # streams tier-1 runs through the numpy simulator, replayed here at
  # the kernel's 128-lane width against XLA's scatter AND against the
  # simulator — a hardware/sim divergence fails with a case name CI
  # already knows.
  import os
  sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                  "tests"))
  from pallas_goldens import CASE_NAMES, apply_vectors
  from distributed_embeddings_tpu.ops.pallas_apply_sim import (
      apply_rows_cached_sim,
  )
  for name in CASE_NAMES:
    buf, ids, delta, slots, _ = apply_vectors(name, width=W)
    got = apply_rows_cached(jnp.asarray(buf), jnp.asarray(ids),
                            jnp.asarray(delta), slots=slots)
    want = np.array(buf, np.float32)
    okm = (ids >= 0) & (ids < buf.shape[0])
    np.add.at(want, ids[okm], delta[okm])
    sim = apply_rows_cached_sim(buf, ids.astype(np.int64), delta,
                                slots=slots)
    err_xla = float(np.max(np.abs(np.asarray(got) - want)))
    err_sim = float(np.max(np.abs(np.asarray(got) - sim)))
    ok = err_xla < 1e-4 and err_sim < 1e-4
    print(f"golden:{name:27s}: {'OK' if ok else 'FAIL'} "
          f"(xla {err_xla:.2e}, sim {err_sim:.2e})")
    if not ok:
      FAILED.append(f"golden:{name}")
  # genuinely multi-grid-step: n > 8192 forces several chunks at
  # chunk=8192, with duplicates recurring across grid-step boundaries
  # (exercises c==0-only init and tag/wbuf persistence across steps)
  cross = (list(range(100)) * 100)[:10000]
  check("cross-chunk duplicates", cross, rows=128, slots=16, chunk=8192)

  rng = np.random.default_rng(0)
  rows, n = 1 << 18, 1 << 17
  base = jnp.asarray(rng.standard_normal((rows, W)), jnp.float32)
  ids = np.concatenate([rng.integers(0, rows, n // 2),
                        rng.zipf(1.3, n // 2) % rows]).astype(np.int32)
  rng.shuffle(ids)
  ids = jnp.asarray(ids)
  delta = jnp.asarray(rng.standard_normal((n, W)), jnp.float32)
  want = base.at[ids].add(delta)
  got = apply_rows_cached(base + 0, ids, delta)
  # f32 summation order differs on ~20k-fold duplicated rows; bound the
  # relative error instead of demanding bit equality
  err = float(jnp.max(jnp.abs(got - want) / (1 + jnp.abs(want))))
  ok = err < 1e-4
  print(f"{'randomized power-law vs XLA':34s}: "
        f"{'OK' if ok else 'FAIL'} (rel err {err:.2e})")
  if not ok:
    FAILED.append("randomized")

  # in-kernel delta scale (the SGD fast path: raw cotangents + scale)
  got_s = apply_rows_cached(base + 0, ids, delta,
                            scale=jnp.float32(-0.125))
  want_s = base.at[ids].add(-0.125 * delta)
  err = float(jnp.max(jnp.abs(got_s - want_s) / (1 + jnp.abs(want_s))))
  ok = err < 1e-4
  print(f"{'in-kernel scale vs XLA':34s}: "
        f"{'OK' if ok else 'FAIL'} (rel err {err:.2e})")
  if not ok:
    FAILED.append("scale")

  # narrow-class dispatch: lane-expanded sub-row deltas through the same
  # kernel at physical-row granularity (scatter_add_fused with rpp > 1).
  # The (128, 1) case is the 256-lane physical layout Mosaic cannot
  # serve (1-row dynamic slices of multi-tile rows); scatter_add_fused
  # must route it to XLA — the case asserts the fallback's correctness
  # under forced-kernel env (the gate must win over the force).
  from distributed_embeddings_tpu.ops.packed_table import (
      PackedLayout, scatter_add_fused)
  for width, n_aux in ((16, 1), (8, 1), (32, 1), (16, 0), (128, 1)):
    layout = PackedLayout(rows=4096, width=width, n_aux=n_aux)
    nids = 2048
    ids_n = jnp.asarray(rng.integers(-2, layout.rows + 2, nids), jnp.int32)
    delta_n = jnp.asarray(rng.standard_normal((nids, layout.stride)),
                          jnp.float32)
    base_n = jnp.asarray(rng.standard_normal(layout.shape), jnp.float32)
    # independent numpy reference built straight from the layout (for
    # the 256-lane (128,1) case the kernel gate sends BOTH env settings
    # to the XLA fallback, so an XLA-vs-XLA comparison would be vacuous)
    rpp = layout.rows_per_phys
    want_np = np.asarray(base_n).copy()
    ids_host = np.asarray(ids_n)
    delta_host = np.asarray(delta_n)  # ONE device fetch (per-row fetches
    # would pay the tunnel's ~100 ms RTT 2048 times)
    for i, lid in enumerate(ids_host):
      if 0 <= lid < layout.rows:
        grp, sub = divmod(int(lid), rpp)
        lo = sub * layout.stride
        want_np[grp, lo:lo + layout.stride] += delta_host[i]
    want = jnp.asarray(want_np)
    import os
    saved = os.environ.get("DE_TPU_PALLAS_APPLY")
    os.environ["DE_TPU_PALLAS_APPLY"] = "0"   # the XLA path
    got_xla = scatter_add_fused(layout, base_n + 0, ids_n, delta_n)
    os.environ["DE_TPU_PALLAS_APPLY"] = "1"   # the kernel (gated wide)
    got = scatter_add_fused(layout, base_n + 0, ids_n, delta_n)
    err_xla = float(jnp.max(jnp.abs(got_xla - want)))
    if err_xla > 1e-4:
      print(f"{'XLA fallback w%d aux%d' % (width, n_aux):34s}: FAIL "
            f"(max err {err_xla:.2e})")
      FAILED.append(f"xla w{width}")
    if saved is None:
      del os.environ["DE_TPU_PALLAS_APPLY"]
    else:
      os.environ["DE_TPU_PALLAS_APPLY"] = saved
    err = float(jnp.max(jnp.abs(got - want)))
    ok = err < 1e-4
    print(f"{'narrow w%d aux%d kernel vs XLA' % (width, n_aux):34s}: "
          f"{'OK' if ok else 'FAIL'} (max err {err:.2e})")
    if not ok:
      FAILED.append(f"narrow w{width}")

  if FAILED:
    print("FAILED:", FAILED)
    sys.exit(1)
  print("ALL PASS")


if __name__ == "__main__":
  main()
