"""Find the fastest TPU formulation of the DLRM pairwise interaction.

Usage: python tools/profile_interact_forms.py [batch]
"""

import sys
import time

import jax
import jax.numpy as jnp

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
K = 8
F = 27
D = 128


def timeit(name, fn, *args):
  step = jax.jit(fn)
  carry = step(*args)
  jax.block_until_ready(carry)
  float(carry)

  def run(n):
    t0 = time.perf_counter()
    for _ in range(n):
      c = step(*args)
    float(c)
    return time.perf_counter() - t0

  t1 = run(K)
  t2 = run(2 * K)
  print(f"{name:44s}: {(t2 - t1) / K * 1e3:8.2f} ms", flush=True)


def main():
  key = jax.random.PRNGKey(0)
  feats = jax.random.normal(key, (BATCH, F, D), jnp.float32)
  feats16 = feats.astype(jnp.bfloat16)

  def naive(x):
    return jnp.sum(jnp.einsum("bfd,bgd->bfg", x, x,
                              preferred_element_type=jnp.float32))

  timeit("einsum bfg f32", naive, feats)
  timeit("einsum bfg bf16 in", naive, feats16)

  for pack in (2, 4, 8, 16):
    def packed(x, pack=pack):
      p = x.reshape(BATCH // pack, pack * F, D)
      return jnp.sum(jnp.einsum("bpd,bqd->bpq", p, p,
                                preferred_element_type=jnp.float32))
    timeit(f"packed x{pack} f32", packed, feats)
    timeit(f"packed x{pack} bf16 in", packed, feats16)

  def packed_bf16out(x, pack=8):
    p = x.reshape(BATCH // pack, pack * F, D)
    return jnp.sum(jnp.einsum("bpd,bqd->bpq", p, p,
                              preferred_element_type=jnp.bfloat16)
                   .astype(jnp.float32))

  timeit("packed x8 bf16 in+out", packed_bf16out, feats16)

  # pad F to 32 first (aligned sublanes), then batched matmul
  def padded32(x):
    xp = jnp.pad(x, ((0, 0), (0, 5), (0, 0)))
    return jnp.sum(jnp.einsum("bfd,bgd->bfg", xp, xp,
                              preferred_element_type=jnp.float32))

  timeit("einsum F->32 padded f32", padded32, feats)

  # dot_general with explicit transpose staged
  def matmul_t(x):
    xt = jnp.swapaxes(x, 1, 2)  # [B, D, F]
    return jnp.sum(jnp.matmul(x, xt))

  timeit("matmul + swapaxes f32", matmul_t, feats)

  # one-sided: big single matmul [B*F, D] x [D, D] as calibration of peak
  def calib(x):
    w = jnp.ones((D, D), x.dtype)
    return jnp.sum(jnp.matmul(x.reshape(-1, D), w))

  timeit("calib [B*27,128]x[128,128] f32", calib, feats)
  timeit("calib bf16", calib, feats16)


if __name__ == "__main__":
  main()
