"""Distributed-tracing budget: overhead, one merged fleet timeline, flight.

The observability acceptance for the fleet serve path (ISSUE 14,
docs/BENCHMARKS.md round 18). Three measurements:

1. **Overhead**: tracing ENABLED (tracer + flight recorder + per-request
   trace-context minting) vs disabled on an in-proc world-2 fleet's
   ``predict`` loop. Acceptance: **<= 3%** on min-of-rounds (the PR 10
   budget, re-measured on the fleet path); the smoke tier requires it
   finite.

2. **One merged timeline** from a REAL multi-process world-2 fleet: the
   router in this process, TWO owner processes spawned over TCP
   (``--owner`` mode), jax.profiler around the serve loop. After the
   load: a clock-offset handshake per owner (``clock`` RPC,
   ``telemetry.estimate_clock_offset``), span-buffer collection
   (``trace`` RPC), ``telemetry.merge_traces`` + the device track
   anchored on the first dispatch span. Assertions: the merged JSON
   contains all THREE process tracks plus the device track; every
   dispatched request's trace id appears on the router track AND an
   owner track; every owner gather span's parent is a router rpc span;
   and after clock correction the rpc span STRICTLY contains its owner
   gather span.

3. **Failover flight recorder**: a fully replicated in-proc fleet
   serves an open loop while one owner is killed mid-load. The counted
   failover trips the flight recorder; acceptance: a bundle is dumped,
   its slowest request's critical path names the ``rpc`` stage (the
   failed-then-retried gather), and a ``failover`` note rides the
   record.

``--smoke`` runs all three at tiny world sizes (wired into ``make
verify``; overhead only required finite), timeout-guarded like the
other smoke tiers. Verdict via ``telemetry.emit_verdict``.

Usage: PYTHONPATH=/root/repo python tools/profile_trace.py [--smoke]
"""

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
  os.environ["XLA_FLAGS"] = (
      flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from distributed_embeddings_tpu import telemetry  # noqa: E402
from distributed_embeddings_tpu.fleet import (  # noqa: E402
    FleetConfig,
    FleetOwner,
    FleetPlan,
    FleetRouter,
    InProcTransport,
    SocketOwnerServer,
    SocketTransport,
)
from distributed_embeddings_tpu.layers.dist_model_parallel import (  # noqa: E402
    set_weights,
)
from distributed_embeddings_tpu.layers.embedding import TableConfig  # noqa: E402
from distributed_embeddings_tpu.layers.planner import (  # noqa: E402
    DistEmbeddingStrategy,
)
from distributed_embeddings_tpu.ops.packed_table import sparse_rule  # noqa: E402
from distributed_embeddings_tpu.parallel import create_mesh  # noqa: E402
from distributed_embeddings_tpu.parallel.lookup_engine import PAD_ID  # noqa: E402
from distributed_embeddings_tpu.resilience.retry import RetryPolicy  # noqa: E402
from distributed_embeddings_tpu.serving import MicroBatcher  # noqa: E402
from distributed_embeddings_tpu.serving.export import (  # noqa: E402
    export as serve_export,
)
from distributed_embeddings_tpu.telemetry.flight import (  # noqa: E402
    FlightRecorder,
)
from distributed_embeddings_tpu.training import (  # noqa: E402
    init_sparse_state,
    shard_params,
)


class ActsModel:
  def apply(self, variables, numerical, cats, emb_acts=None):
    del variables, numerical, cats
    return jnp.concatenate(list(emb_acts), axis=-1)


BENCH = dict(world=2, sizes=[32768, 8192], widths=[16, 16],
             hotness=[2, 1], req_rows=4, max_batch=64,
             n_requests=120, overhead_rounds=60)
SMOKE = dict(world=2, sizes=[1536, 768], widths=[16, 16],
             hotness=[2, 1], req_rows=4, max_batch=32,
             n_requests=60, overhead_rounds=25)

FLEET_CFG = FleetConfig(cache_fraction=0.05, staging_grps=256,
                        shard_min_phys_rows=16)


def make_plan(cfg):
  tables = [TableConfig(s, w, combiner="sum")
            for s, w in zip(cfg["sizes"], cfg["widths"])]
  return DistEmbeddingStrategy(tables, cfg["world"], "memory_balanced",
                               dense_row_threshold=0,
                               input_hotness=cfg["hotness"])


def build(cfg):
  rng = np.random.default_rng(7)
  plan = make_plan(cfg)
  weights = [(rng.standard_normal((s, w)) / np.sqrt(w)).astype(np.float32)
             for s, w in zip(cfg["sizes"], cfg["widths"])]
  params = {"embeddings": {k: jnp.asarray(v)
                           for k, v in set_weights(plan, weights).items()}}
  rule = sparse_rule("adagrad", 0.05)
  mesh = create_mesh(cfg["world"])
  state = shard_params(init_sparse_state(plan, params, rule,
                                         optax.sgd(0.01)), mesh)
  return plan, rule, mesh, state, rng


def mkreq(rng, cfg, n):
  ids = []
  for s, h in zip(cfg["sizes"], cfg["hotness"]):
    x = rng.integers(0, s, (n, h)).astype(np.int32)
    x[rng.random(x.shape) < 0.2] = PAD_ID
    ids.append(x)
  return rng.standard_normal((n, 4)).astype(np.float32), ids


# ---------------------------------------------------------------------------
# owner process mode (--owner): one FleetOwner behind a TCP server
# ---------------------------------------------------------------------------


def owner_main(args) -> int:
  cfg = SMOKE if args.smoke else BENCH
  telemetry.install_tracer(telemetry.Tracer(label=f"owner-{args.owner_id}"))
  plan = make_plan(cfg)
  ranks = tuple(int(r) for r in args.ranks.split(","))
  owner = FleetOwner(args.path, plan, ranks, owner_id=args.owner_id)
  server = SocketOwnerServer(owner)
  telemetry.atomic_write_text(args.portfile,
                              f"{server.host} {server.port}")
  stop = threading.Event()
  signal.signal(signal.SIGTERM, lambda *_: stop.set())
  while not stop.is_set():
    stop.wait(0.2)
  server.close()
  return 0


def spawn_owners(tmp, path, fplan, smoke):
  """Two real owner processes; returns (procs, addresses)."""
  procs, portfiles = [], []
  for k in range(fplan.n_owners):
    pf = os.path.join(tmp, f"owner{k}.port")
    ranks = ",".join(str(r) for r in fplan.owned_ranks(k))
    cmd = [sys.executable, os.path.abspath(__file__), "--owner",
           "--owner-id", str(k), "--ranks", ranks, "--path", path,
           "--portfile", pf] + (["--smoke"] if smoke else [])
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
         env.get("PYTHONPATH", "")])
    procs.append(subprocess.Popen(cmd, env=env))
    portfiles.append(pf)
  addresses = {}
  deadline = time.perf_counter() + 180.0
  for k, pf in enumerate(portfiles):
    while not os.path.isfile(pf):
      if time.perf_counter() > deadline:
        raise TimeoutError(f"owner {k} never published its port")
      if procs[k].poll() is not None:
        raise RuntimeError(f"owner {k} exited rc={procs[k].returncode} "
                           "before serving")
      time.sleep(0.1)
    with open(pf) as f:
      host, port = f.read().split()
    addresses[k] = (host, int(port))
  return procs, addresses


def stop_owners(procs):
  for p in procs:
    if p.poll() is None:
      p.terminate()
  for p in procs:
    try:
      p.wait(timeout=30)
    except subprocess.TimeoutExpired:
      p.kill()
      p.wait(timeout=10)


# ---------------------------------------------------------------------------
# 1. overhead: tracing-enabled fleet serve vs disabled
# ---------------------------------------------------------------------------


def check_overhead(cfg, tmp, result, smoke):
  plan, rule, mesh, state, rng = build(cfg)
  path = os.path.join(tmp, "art_overhead")
  serve_export(path, plan, rule, state, quantize="f32")
  fplan = FleetPlan.balanced(cfg["world"], 2)
  owners = {o: FleetOwner(path, plan, fplan.owned_ranks(o), owner_id=o)
            for o in range(2)}
  transport = InProcTransport(owners)
  router = FleetRouter(ActsModel(), plan, path, fplan, transport,
                       mesh=mesh, config=FLEET_CFG)
  reqs = [mkreq(rng, cfg, cfg["req_rows"]) for _ in range(8)]
  for r in reqs:
    router.predict(*r)  # compile every staging shape off the clock

  def min_predict_s(n):
    best = None
    for i in range(n):
      t0 = time.perf_counter()
      router.predict(*reqs[i % len(reqs)])
      dt = time.perf_counter() - t0
      best = dt if best is None else min(best, dt)
    return best

  n = cfg["overhead_rounds"]
  disabled = min_predict_s(n)
  rec = telemetry.install_flight_recorder(
      FlightRecorder(dir=os.path.join(tmp, "flight_ovh")))
  with telemetry.tracing(label="router"):
    enabled = min_predict_s(n)
  telemetry.uninstall_flight_recorder()
  router.close()
  overhead = (enabled - disabled) / disabled
  budget = float("inf") if smoke else 0.03
  ok = np.isfinite([disabled, enabled]).all() and overhead <= budget
  result["overhead"] = {
      "disabled_min_ms": disabled * 1e3, "enabled_min_ms": enabled * 1e3,
      "overhead_frac": overhead, "budget_frac": None if smoke else 0.03}
  print(f"tracing overhead on the fleet serve path: disabled "
        f"{disabled * 1e3:.2f} ms, enabled {enabled * 1e3:.2f} ms "
        f"({overhead:+.1%}) {'OK' if ok else 'FAIL'}")
  return bool(ok)


# ---------------------------------------------------------------------------
# 2. the merged timeline: router proc + 2 owner procs + device track
# ---------------------------------------------------------------------------


def _spans(trace, name=None):
  out = []
  for ev in trace.get("traceEvents", []):
    if ev.get("ph") == "X" and (name is None or ev.get("name") == name):
      out.append(ev)
  return out


def _process_names(trace):
  return {ev["pid"]: ev["args"]["name"]
          for ev in trace.get("traceEvents", [])
          if ev.get("ph") == "M" and ev.get("name") == "process_name"}


def check_merged_timeline(cfg, tmp, result, smoke):
  plan, rule, mesh, state, rng = build(cfg)
  path = os.path.join(tmp, "art_merged")
  serve_export(path, plan, rule, state, quantize="f32")
  fplan = FleetPlan.balanced(cfg["world"], 2)
  procs, addresses = spawn_owners(tmp, path, fplan, smoke)
  merged_path = os.path.join(tmp, "merged_trace.json")
  ok = True
  try:
    transport = SocketTransport(addresses)
    rec = telemetry.install_flight_recorder(
        FlightRecorder(dir=os.path.join(tmp, "flight_merged")))
    tdir = os.path.join(tmp, "jprof")
    with telemetry.tracing(label="router") as tracer:
      router = FleetRouter(ActsModel(), plan, path, fplan, transport,
                           mesh=mesh, config=FLEET_CFG)
      mb = MicroBatcher(router.dispatch, max_batch=cfg["max_batch"],
                        max_delay_s=0.002)
      warm = mkreq(rng, cfg, cfg["req_rows"])
      mb.submit(*warm).result(timeout=300)  # compile off the clock
      with jax.profiler.trace(tdir):
        futs = [mb.submit(*mkreq(rng, cfg, cfg["req_rows"]))
                for _ in range(cfg["n_requests"] // 4)]
        for f in futs:
          f.result(timeout=300)
      # the handshake + collection pass, while the owners are still up
      offsets = router.store.clock_offsets()
      owner_traces = router.store.collect_traces()
      mb.close()
      router.close()
    telemetry.uninstall_flight_recorder()
    router_trace = tracer.to_chrome()
    merged = telemetry.merge_traces(
        [{"trace": router_trace, "offset_ns": 0}]
        + [{"trace": owner_traces[o],
            "offset_ns": offsets[o].offset_ns,
            "label": f"owner-{o}"} for o in sorted(owner_traces)])
    # device track: anchored on the first dispatch span's start (the
    # dispatch->enqueue latency bounds the alignment error)
    dispatches = sorted(_spans(router_trace, "serve/dispatch"),
                        key=lambda e: e["ts"])
    anchor_ns = int(dispatches[0]["ts"] * 1e3) + router_trace["t0_ns"]
    import glob
    import gzip
    dpaths = sorted(glob.glob(
        f"{tdir}/plugins/profile/*/*.trace.json.gz"))
    device_ok = False
    if dpaths:
      with gzip.open(dpaths[-1]) as f:
        device_trace = json.load(f)
      merged = telemetry.attach_device_track(merged, device_trace,
                                             anchor_ns)
      device_ok = True
    telemetry.trace.save_trace(merged, merged_path)

    # --- assertions on the ONE merged artifact -------------------------
    names = _process_names(merged)
    labels = set(names.values())
    tracks_ok = {"router", "owner-0", "owner-1"} <= labels
    device_ok = device_ok and "device" in labels
    pid_of = {v: k for k, v in names.items()}

    def args_of(ev):
      return ev.get("args") or {}

    # every dispatched request id appears on the router track AND on
    # at least one owner track (the batch's trace_ids ride the wire)
    router_ids = set()
    for ev in _spans(merged, "serve/dispatch"):
      router_ids.update(args_of(ev).get("trace_ids",
                                        [args_of(ev).get("trace_id")]))
    router_ids.discard(None)
    owner_ids = set()
    # startup fills (warm cache, rankings) gather with no request
    # context; the request-path assertions cover the ctx-carrying spans
    gathers = [ev for ev in _spans(merged, "fleet/owner/gather")
               if names.get(ev["pid"], "").startswith("owner-")
               and "trace_id" in args_of(ev)]
    for ev in gathers:
      owner_ids.update(args_of(ev).get("trace_ids",
                                       [args_of(ev).get("trace_id")]))
    ids_ok = bool(router_ids) and router_ids <= owner_ids

    # parent/child across processes: every owner gather span's parent
    # is a router fleet/rpc span, and after clock correction the rpc
    # span STRICTLY contains the gather span
    rpc_by_span = {args_of(ev)["span_id"]: ev
                   for ev in _spans(merged, "fleet/rpc")
                   if names.get(ev["pid"]) == "router"
                   and "span_id" in args_of(ev)}
    nested = contained = 0
    for g in gathers:
      parent = args_of(g).get("parent_span_id")
      rpc = rpc_by_span.get(parent)
      if rpc is None:
        continue
      nested += 1
      if rpc["ts"] < g["ts"] and \
          g["ts"] + g["dur"] < rpc["ts"] + rpc["dur"]:
        contained += 1
    nesting_ok = nested == len(gathers) > 0 and contained == nested

    uncert_ms = max(o.uncertainty_ns for o in offsets.values()) / 1e6
    ok = tracks_ok and device_ok and ids_ok and nesting_ok
    result["merged"] = {
        "path": merged_path, "tracks": sorted(labels),
        "requests_traced": len(router_ids),
        "gather_spans": len(gathers), "rpc_contains_gather": contained,
        "clock_uncertainty_ms": uncert_ms,
        "offsets_ns": {o: off.to_json() for o, off in offsets.items()},
        "tracks_ok": tracks_ok, "device_ok": device_ok,
        "ids_ok": ids_ok, "nesting_ok": nesting_ok}
    print(f"merged timeline: tracks={sorted(labels)}  "
          f"{len(router_ids)} request ids across processes, "
          f"{contained}/{len(gathers)} gather spans strictly inside "
          f"their rpc span (clock uncertainty {uncert_ms:.3f} ms) "
          f"{'OK' if ok else 'FAIL'}")
    transport.close()
  finally:
    stop_owners(procs)
  return bool(ok)


# ---------------------------------------------------------------------------
# 3. failover -> flight-recorder bundle
# ---------------------------------------------------------------------------


def check_failover_flight(cfg, tmp, result, smoke):
  plan, rule, mesh, state, rng = build(cfg)
  path = os.path.join(tmp, "art_flight")
  serve_export(path, plan, rule, state, quantize="f32")
  fplan = FleetPlan.replicated(cfg["world"], 2, replicas=2,
                               hot_fraction=1.0)
  owners = {o: FleetOwner(path, plan, fplan.owned_ranks(o), owner_id=o)
            for o in range(2)}
  transport = InProcTransport(owners)
  cfg_f = FleetConfig(cache_fraction=0.05, staging_grps=256,
                      shard_min_phys_rows=16, revive_after_s=3600.0)
  router = FleetRouter(ActsModel(), plan, path, fplan, transport,
                       mesh=mesh, config=cfg_f,
                       retry_policy=RetryPolicy(retries=2, backoff=0.05))
  mb = MicroBatcher(router.dispatch, max_batch=cfg["max_batch"],
                    max_delay_s=0.002)
  # ONE request shape repeated, warmed BEFORE the recorder installs:
  # the ring must hold only load-time records — a warm-up dispatch
  # carrying the initial jit compile would out-slow the failover's rpc
  # stall and steal the critical-path assertion
  req = mkreq(rng, cfg, cfg["req_rows"])
  for _ in range(2):
    warm = [mb.submit(*req) for _ in range(6)]
    for f in warm:
      f.result(timeout=300)
  recorder = telemetry.install_flight_recorder(
      FlightRecorder(dir=os.path.join(tmp, "flight_failover"),
                     capacity=128))
  n = max(40, cfg["n_requests"] // 2)
  killer = threading.Timer(0.2, transport.kill, args=(0,))
  killer.start()
  failed = 0
  futs = []
  for i in range(n):
    futs.append(mb.submit(*req))
    time.sleep(0.005)
  for f in futs:
    try:
      f.result(timeout=300)
    except Exception:  # noqa: BLE001 — counted, must stay 0
      failed += 1
  killer.join()
  mb.close()
  router.close()
  telemetry.uninstall_flight_recorder()
  failovers = router.telemetry.counter("fleet/failovers").value
  bundles = list(recorder.bundles)
  bundle_ok = critical = note_ok = False
  if bundles:
    with open(bundles[0]) as f:
      bundle = json.load(f)
    bundle_ok = bundle["reason"] == "failover" \
        and len(bundle["requests"]) >= 1
    slowest = bundle.get("slowest") or {}
    critical = slowest.get("critical_stage") == "rpc"
    note_ok = any(nt.get("kind") == "failover"
                  for r in bundle["requests"] for nt in r.get("notes", []))
  ok = (failed == 0 and failovers >= 1 and bundle_ok and critical
        and note_ok)
  result["flight"] = {
      "requests": n, "failed": failed, "failovers": failovers,
      "bundles": len(bundles),
      "bundle": bundles[0] if bundles else None,
      "slowest_critical_stage": (slowest.get("critical_stage")
                                 if bundles else None),
      "failover_note_present": note_ok}
  print(f"failover flight recorder: {n} requests, failed={failed}, "
        f"failovers={failovers}, bundles={len(bundles)}, slowest "
        f"critical stage="
        f"{result['flight']['slowest_critical_stage']!r} "
        f"{'OK' if ok else 'FAIL'}")
  return bool(ok)


def main(cfg, tag, smoke):
  tmp = tempfile.mkdtemp(prefix="trace_bench_")
  result = {"config": dict(cfg)}
  keep = os.environ.get("DE_TPU_KEEP_TRACE")
  try:
    ok = check_overhead(cfg, tmp, result, smoke)
    ok = check_merged_timeline(cfg, tmp, result, smoke) and ok
    ok = check_failover_flight(cfg, tmp, result, smoke) and ok
    if keep:
      os.makedirs(keep, exist_ok=True)
      for name in ("merged_trace.json",):
        src = os.path.join(tmp, name)
        if os.path.isfile(src):
          shutil.copy(src, os.path.join(keep, name))
          result["merged"]["path"] = os.path.join(keep, name)
  finally:
    if not keep:
      shutil.rmtree(tmp, ignore_errors=True)
  result["ok"] = bool(ok)
  return telemetry.emit_verdict(tag, result)


if __name__ == "__main__":
  ap = argparse.ArgumentParser()
  ap.add_argument("--smoke", action="store_true",
                  help="tiny-world smoke tier (wired into make verify)")
  ap.add_argument("--owner", action="store_true",
                  help="internal: run one owner process (spawned by the "
                       "merged-timeline phase)")
  ap.add_argument("--owner-id", type=int, default=0)
  ap.add_argument("--ranks", type=str, default="0")
  ap.add_argument("--path", type=str, default="")
  ap.add_argument("--portfile", type=str, default="")
  args = ap.parse_args()
  if args.owner:
    raise SystemExit(owner_main(args))
  if args.smoke:
    raise SystemExit(main(SMOKE, "trace-smoke", smoke=True))
  raise SystemExit(main(BENCH, "trace-bench", smoke=False))
