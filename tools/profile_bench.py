"""Piecewise timing of the bench.py (synthetic Tiny) train step on the chip.

Times: full step, forward-only (loss), route+fused-gather only, and
apply_sparse only, using chained-scan deltas to defeat the tunnel's async
dispatch. Prints one line per part.

Usage: python tools/profile_bench.py [model] [batch]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.models import (
    SYNTHETIC_MODELS,
    SyntheticModel,
    bce_loss,
    expand_tables,
    generate_batch,
)
from distributed_embeddings_tpu.ops.packed_table import adagrad_rule
from distributed_embeddings_tpu.parallel.lookup_engine import DistributedLookup
from distributed_embeddings_tpu.training import (
    init_sparse_state_direct,
    make_sparse_train_step,
)

MODEL = sys.argv[1] if len(sys.argv) > 1 else "tiny"
BATCH = int(sys.argv[2]) if len(sys.argv) > 2 else 65536
K = 4


def timed_chain(fn, *args, k=K):
  """fn(*args) -> scalar; returns s/iter via (2K - K) delta timing."""

  def chain(length):
    @jax.jit
    def run(*a):
      def body(acc, _):
        return acc + fn(*a), None

      acc, _ = jax.lax.scan(body, jnp.zeros(()), None, length=length)
      return acc

    return run

  r1, r2 = chain(k), chain(2 * k)
  float(r1(*args))
  float(r2(*args))
  t0 = time.perf_counter()
  float(r1(*args))
  t1 = time.perf_counter()
  t2 = time.perf_counter()
  float(r2(*args))
  t3 = time.perf_counter()
  return ((t3 - t2) - (t1 - t0)) / k


def main():
  cfg = SYNTHETIC_MODELS[MODEL]
  tables, tmap, hotness = expand_tables(cfg)
  model = SyntheticModel(config=cfg, world_size=1)
  plan = DistEmbeddingStrategy(tables, 1, "basic", input_table_map=tmap,
                               dense_row_threshold=model.dense_row_threshold)
  n_sparse = sum(1 for k in plan.class_keys if plan.classes[k].kind == "sparse")
  occ = BATCH * sum(h for h in hotness)
  print(f"model={MODEL} batch={BATCH} sparse_classes={n_sparse} "
        f"occurrences~{occ / 1e6:.1f}M")

  numerical, cats, labels = generate_batch(cfg, BATCH, alpha=1.05, seed=0)
  cats = [np.minimum(c, tables[t].input_dim - 1).astype(np.int32)
          for c, t in zip(cats, tmap)]
  cats = [jnp.asarray(c if h > 1 else c[:, 0])
          for c, h in zip(cats, hotness)]
  batch = (jnp.asarray(numerical), cats, jnp.asarray(labels))

  dense_opt = optax.adagrad(0.01)
  rule = adagrad_rule(0.01)
  dummy_acts = [jnp.zeros((2, tables[t].output_dim), jnp.float32)
                for t in tmap]
  small_cats = [c[:2] for c in cats]
  dense_params = model.init(jax.random.PRNGKey(0), batch[0][:2], small_cats,
                            emb_acts=dummy_acts)["params"]

  state = init_sparse_state_direct(plan, rule, dense_params, dense_opt,
                                   jax.random.PRNGKey(1))
  jax.block_until_ready(state)
  engine = DistributedLookup(plan)
  layouts = engine.fused_layouts(rule)

  hotness_of = lambda i: (cats[i].shape[1] if cats[i].ndim == 2 else 1)  # noqa

  # ---- route + gather only ----------------------------------------------
  def fwd_gather(fused, cats_):
    ids_all = engine.route_ids(cats_, hotness_of)
    z, res = engine.lookup_sparse_fused(fused, layouts, ids_all)
    return sum(zb.sum() for zb in z.values())

  dt = timed_chain(lambda f: fwd_gather(f, cats), state["fused"])
  print(f"route+gather_fused : {dt * 1e3:8.2f} ms")

  # ---- full forward (loss) ----------------------------------------------
  def fwd(fused, emb_dense, dp, nump, cats_, labels_):
    ids_all = engine.route_ids(cats_, hotness_of)
    z, res = engine.lookup_sparse_fused(fused, layouts, ids_all)
    acts = engine.finish_forward(z, emb_dense, ids_all, BATCH, hotness_of)
    logits = model.apply({"params": {**dp, "embeddings": emb_dense}},
                         nump, cats_, emb_acts=acts)
    return bce_loss(logits, labels_)

  dt = timed_chain(
      lambda f, ed, dp: fwd(f, ed, dp, batch[0], cats, batch[2]),
      state["fused"], state["emb_dense"], state["dense"])
  print(f"forward total      : {dt * 1e3:8.2f} ms")

  # ---- scatter only ------------------------------------------------------
  def scat(fused, cats_):
    ids_all = engine.route_ids(cats_, hotness_of)
    z, res = engine.lookup_sparse_fused(fused, layouts, ids_all)
    d_z = {bk: jnp.ones_like(zb) for bk, zb in z.items()}
    new = engine.apply_sparse(fused, layouts, d_z, res, rule,
                              jnp.zeros((), jnp.int32))
    return sum(v.sum() for v in new.values()) * 0 + sum(
        v[0, 0] for v in new.values())

  # NOTE: includes route+gather (needed for residuals); subtract part 1.
  dt = timed_chain(lambda f: scat(f, cats), state["fused"])
  print(f"gather+apply_sparse: {dt * 1e3:8.2f} ms   (minus line 1 = scatter)")

  # ---- full step ---------------------------------------------------------
  state_avals = jax.eval_shape(lambda s: s, state)
  step = make_sparse_train_step(model, plan, bce_loss, dense_opt, rule,
                                None, state_avals, batch)
  compiled = step.lower(state_avals, *batch).compile()
  s2, loss = compiled(state, *batch)
  jax.block_until_ready(loss)
  t0 = time.perf_counter()
  for _ in range(K):
    s2, loss = compiled(s2, *batch)
  float(loss)
  t1 = time.perf_counter()
  t2 = time.perf_counter()
  for _ in range(2 * K):
    s2, loss = compiled(s2, *batch)
  float(loss)
  t3 = time.perf_counter()
  print(f"full step          : {((t3 - t2) - (t1 - t0)) / K * 1e3:8.2f} ms")


if __name__ == "__main__":
  main()
