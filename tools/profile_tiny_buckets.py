"""Per-bucket decomposition of the Tiny gather+combine block.

For each sparse bucket of the real plan: raw phys-row take vs full
gather_fused vs gather+combine, on the real routed ids and real fused
buffers. Finds where route+gather+combine's time above the 11 ns/row
gather floor actually goes.

Usage: PYTHONPATH=/root/repo:/root/.axon_site python -u tools/profile_tiny_buckets.py
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.models import (
    SYNTHETIC_MODELS,
    SyntheticModel,
    expand_tables,
    generate_batch,
)
from distributed_embeddings_tpu.ops.packed_table import (
    adagrad_rule,
    gather_fused,
)
from distributed_embeddings_tpu.parallel.lookup_engine import (
    DistributedLookup,
    class_param_name,
)
from distributed_embeddings_tpu.training import init_sparse_state_direct

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
K = 5


def _sync(x):
  leaf = jax.tree_util.tree_leaves(x)[0]
  float(jnp.asarray(leaf).ravel()[0])


def timeit(name, fn, *args, n_norm=None):
  step = jax.jit(fn)
  carry = step(jnp.zeros((), jnp.float32), *args)
  _sync(carry)

  def run(n, carry):
    t0 = time.perf_counter()
    for _ in range(n):
      carry = step(carry, *args)
    _sync(carry)
    return time.perf_counter() - t0, carry

  _, carry = run(1, carry)
  t1, carry = run(K, carry)
  t2, carry = run(2 * K, carry)
  dt = (t2 - t1) / K
  per = f"  {dt / n_norm * 1e9:6.1f} ns/row" if n_norm else ""
  print(f"{name:58s}: {dt * 1e3:8.2f} ms{per}", flush=True)


def main():
  cfg = SYNTHETIC_MODELS["tiny"]
  tables, tmap, hotness = expand_tables(cfg)
  model = SyntheticModel(config=cfg, world_size=1)
  plan = DistEmbeddingStrategy(tables, 1, "basic", input_table_map=tmap,
                               dense_row_threshold=model.dense_row_threshold,
                               input_hotness=hotness, batch_hint=BATCH)
  engine = DistributedLookup(plan)
  rule = adagrad_rule(0.01)
  layouts = engine.fused_layouts(rule)
  numerical, cats, labels = generate_batch(cfg, BATCH, alpha=1.05, seed=0)
  cats = [np.minimum(c, tables[t].input_dim - 1).astype(np.int32)
          for c, t in zip(cats, tmap)]
  cats = [jnp.asarray(c if h > 1 else c[:, 0])
          for c, h in zip(cats, hotness)]
  hotness_of = lambda i: hotness[i]  # noqa: E731

  dummy_acts = [jnp.zeros((2, tables[t].output_dim), jnp.float32)
                for t in tmap]
  dense_params = model.init(jax.random.PRNGKey(0),
                            jnp.asarray(numerical[:2]), [c[:2] for c in cats],
                            emb_acts=dummy_acts)["params"]
  state = init_sparse_state_direct(plan, rule, dense_params,
                                   optax.adagrad(0.01), jax.random.PRNGKey(1))
  fused = state["fused"]
  _sync(fused[sorted(fused)[0]])

  ids_all = jax.jit(lambda c: engine.route_ids(c, hotness_of))(cats)
  ids_all = {k: jax.device_put(v) for k, v in ids_all.items()}

  for bk in sorted(ids_all):
    if engine.plan.classes[bk.class_key].kind != "sparse":
      print(f"bucket {bk.width}w h={bk.h} vcap={bk.vcap}: dense, skipped")
      continue
    ids = ids_all[bk]
    name = class_param_name(*bk.class_key)
    layout = layouts[name]
    buf = fused[name]
    n = int(np.prod(ids.shape))
    rpp = layout.rows_per_phys

    def raw_take(c, idb, buf=buf, rpp=rpp, layout=layout):
      idb = idb + jnp.minimum(c.astype(jnp.int32), 0)
      grp = jnp.where((idb >= 0) & (idb < layout.rows), idb // rpp,
                      layout.phys_rows)
      rows = jnp.take(buf, grp, axis=0, mode="fill", fill_value=0)
      return c + jnp.tanh(jnp.sum(rows) * 1e-9) * 0 + jnp.float32(0)

    def gfused(c, idb, buf=buf, layout=layout):
      idb = idb + jnp.minimum(c.astype(jnp.int32), 0)
      rows = gather_fused(layout, buf, idb)
      return c + jnp.tanh(jnp.sum(rows) * 1e-9) * 0 + jnp.float32(0)

    def gcombine(c, idb, buf=buf, layout=layout, bk=bk):
      idb = idb + jnp.minimum(c.astype(jnp.int32), 0)
      z, aux = engine._z_sparse_fused(bk.class_key, layout, buf, idb, bk.rs)
      return (c + jnp.tanh(jnp.sum(z) * 1e-9) * 0
              + jnp.tanh(jnp.sum(aux) * 1e-9) * 0 + jnp.float32(0))

    label = f"{bk.width}w h={bk.h} n={n} rpp={rpp}"
    timeit(f"[{label}] raw phys take", raw_take, ids, n_norm=n)
    timeit(f"[{label}] gather_fused", gfused, ids, n_norm=n)
    timeit(f"[{label}] gather+combine", gcombine, ids, n_norm=n)


if __name__ == "__main__":
  main()
