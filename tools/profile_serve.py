"""Serve-path budget: step throughput + latency vs offered QPS.

Two measurements on the power-law synthetic workload (8-way CPU mesh):

1. **Step throughput** (``--steps`` section, always on): rows/s of the
   f32 ``make_sparse_eval_step`` (the pre-serving baseline — training
   layout, optimizer lanes riding every gather) vs the frozen-table
   serve step in f32 and int8, at equal batch. Acceptance: the int8
   serve step sustains **>= 1.5x** the f32 eval step's throughput (the
   stripped+quantized image moves 4x fewer gather bytes; the CPU mesh
   prices bytes, which is also what the TPU row-gather prices).

2. **Latency vs offered QPS** (micro-batcher): a closed-loop run finds
   the saturation throughput per configuration, then an open-loop
   POISSON arrival process offers fractions of it and reports
   p50/p99/p99.9 per-request latency — the serving metric that
   steps/sec cannot see. Sweeps {f32, int8} x {all-device, tiered} x
   batcher deadline settings. Acceptance: with the default batcher the
   int8 all-device configuration holds **p99 <= 3x p50 at 80% of its
   saturation QPS** (an unbatched or unbounded queue fails this the
   moment arrivals cluster).

``--smoke`` runs a tiny-world version wired into ``make verify``: a few
hundred requests, asserting the latency percentiles are finite and the
bounded-queue rejection counter is exact.

The recorded budgets live in docs/BENCHMARKS.md ("Round 8: the serving
engine").

Usage: PYTHONPATH=/root/repo python tools/profile_serve.py [--smoke]
"""

import argparse
import os
import threading
import time

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
  os.environ["XLA_FLAGS"] = (
      flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from distributed_embeddings_tpu import telemetry  # noqa: E402
from distributed_embeddings_tpu.layers.planner import (  # noqa: E402
    DistEmbeddingStrategy,
)
from distributed_embeddings_tpu.models.synthetic import (  # noqa: E402
    EmbeddingGroup,
    SyntheticModel,
    SyntheticModelConfig,
    expand_tables,
    generate_batch,
)
from distributed_embeddings_tpu.ops.packed_table import sparse_rule  # noqa: E402
from distributed_embeddings_tpu.parallel import create_mesh  # noqa: E402
from distributed_embeddings_tpu.serving import (  # noqa: E402
    MicroBatcher,
    Rejected,
    ServeEngine,
    ServeTierConfig,
)
from distributed_embeddings_tpu.serving.export import freeze  # noqa: E402
from distributed_embeddings_tpu.training import (  # noqa: E402
    init_sparse_state_direct,
    make_sparse_eval_step,
    shard_batch,
    shard_params,
)

WORLD = 8
GLOBAL_BATCH = 8192
ALPHA = 1.05
STEPS = 5

CFG = SyntheticModelConfig(
    name="serve-powerlaw",
    embedding_groups=(EmbeddingGroup(8, (8,), 4096, 16, False),),
    mlp_sizes=(64, 32), num_numerical_features=8, interact_stride=None)

SMOKE_CFG = SyntheticModelConfig(
    name="serve-smoke",
    embedding_groups=(EmbeddingGroup(4, (4,), 512, 16, False),),
    mlp_sizes=(32, 16), num_numerical_features=4, interact_stride=None)


def build(cfg, world, batch, host_thr=None):
  tables, tmap, hotness = expand_tables(cfg)
  model = SyntheticModel(cfg)
  plan = DistEmbeddingStrategy(
      tables, world, "memory_balanced", input_table_map=tmap,
      input_hotness=hotness, batch_hint=batch,
      dense_row_threshold=0, host_row_threshold=host_thr)
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.sgd(0.01)
  mesh = create_mesh(world)
  numerical, cats, labels = generate_batch(cfg, batch, alpha=ALPHA, seed=3)
  cats = [np.minimum(np.asarray(c), tables[t].input_dim - 1)
          for c, t in zip(cats, tmap)]
  bt_np = (numerical, [jnp.asarray(c) for c in cats], labels)
  dummy = [jnp.zeros((2, tables[t].output_dim), jnp.float32) for t in tmap]
  dense_params = model.init(jax.random.PRNGKey(0),
                            jnp.asarray(numerical[:2]),
                            [c[:2] for c in bt_np[1]],
                            emb_acts=dummy)["params"]
  if host_thr is None:
    state = shard_params(
        init_sparse_state_direct(plan, rule, dense_params, opt,
                                 jax.random.PRNGKey(1)), mesh)
    store = None
  else:
    from distributed_embeddings_tpu.tiering import (
        HostTierStore,
        TieringConfig,
        TieringPlan,
    )
    from distributed_embeddings_tpu.tiering.train import init_tiered_state
    tplan = TieringPlan(plan, rule,
                        TieringConfig(cache_fraction=0.25,
                                      staging_grps=256))
    store = HostTierStore(tplan)
    state = shard_params(
        init_tiered_state(tplan, store, rule, dense_params, opt,
                          jax.random.PRNGKey(1), mesh=mesh), mesh)
  return model, plan, rule, mesh, state, store, bt_np


def time_step(fn, args, steps=STEPS):
  out = fn(*args)  # compile + warm
  jax.block_until_ready(out)
  with telemetry.timed("serve/step_window") as t:
    for _ in range(steps):
      out = fn(*args)
    jax.block_until_ready(out)
  return t.elapsed / steps


def step_throughput(cfg, world, batch):
  """rows/s of eval-f32 vs serve-f32 vs serve-int8 at equal batch."""
  model, plan, rule, mesh, state, _store, bt_np = build(cfg, world, batch)
  batch0 = (jnp.asarray(bt_np[0]), bt_np[1], jnp.asarray(bt_np[2]))
  bt = shard_batch(batch0, mesh)
  ev = make_sparse_eval_step(model, plan, rule, mesh, state, batch0)
  dt_eval = time_step(lambda s, n, c: ev(s, n, c), (state, *bt[:2]))
  out = {"eval_f32": batch / dt_eval}
  for q in ("f32", "int8"):
    frozen = freeze(plan, rule, state, quantize=q)
    from distributed_embeddings_tpu.serving.export import (
        frozen_device_state,
    )
    from distributed_embeddings_tpu.serving.engine import make_serve_step
    sstate = frozen_device_state(frozen, plan, mesh)
    step = make_serve_step(model, plan, frozen.meta, mesh, sstate,
                           (batch0[0], batch0[1]))
    dt = time_step(lambda s, n, c: step(s, n, c), (sstate, *bt[:2]))
    out[f"serve_{q}"] = batch / dt
  return out


# ---------------------------------------------------------------------------
# latency vs offered QPS through the micro-batcher
# ---------------------------------------------------------------------------


def _requests(bt_np, req_rows, n, seed=0):
  rng = np.random.default_rng(seed)
  numerical, cats, _ = bt_np
  b = numerical.shape[0]
  out = []
  for _ in range(n):
    lo = int(rng.integers(0, b - req_rows))
    out.append((numerical[lo:lo + req_rows],
                [np.asarray(c[lo:lo + req_rows]) for c in cats]))
  return out

def closed_loop(mb, reqs, workers=8, duration_s=6.0):
  """Saturation: `workers` synchronous clients for `duration_s`;
  returns (requests/s, latencies)."""
  done, lats = [], []
  lock = threading.Lock()
  stop = time.monotonic() + duration_s

  def worker(w):
    i = w
    while time.monotonic() < stop:
      try:
        fut = mb.submit(*reqs[i % len(reqs)])
      except Rejected:
        time.sleep(0.001)
        continue
      out = fut.result(timeout=120)
      with lock:
        done.append(out.shape[0])
        lats.append(fut.latency_s)
      i += workers

  threads = [threading.Thread(target=worker, args=(w,))
             for w in range(workers)]
  t0 = time.monotonic()
  for t in threads:
    t.start()
  for t in threads:
    t.join()
  dt = time.monotonic() - t0
  return len(done) / dt, lats


def open_loop(mb, reqs, qps, n_requests, seed=0):
  """Poisson arrivals at `qps`; returns (latencies, rejected)."""
  rng = np.random.default_rng(seed)
  futs = []
  rejected = 0
  t_next = time.monotonic()
  for i in range(n_requests):
    t_next += float(rng.exponential(1.0 / qps))
    delay = t_next - time.monotonic()
    if delay > 0:
      time.sleep(delay)
    try:
      futs.append(mb.submit(*reqs[i % len(reqs)]))
    except Rejected:
      rejected += 1
  for f in futs:  # block until every accepted request completed
    f.result(timeout=120)
  return [f.latency_s for f in futs], rejected


def pcts(lats):
  """p50/p99/p99.9 through the telemetry histogram type (0.5% bounded
  relative error — far inside the acceptance margins), replacing the
  hand-rolled np.percentile copy every tool used to carry."""
  h = telemetry.Histogram("serve/latency_s", rel_err=0.005)
  h.observe_many(lats)
  return h.percentile(50), h.percentile(99), h.percentile(99.9)


def latency_sweep(cfg, world, batch, quantize, tiered, max_delay_s,
                  req_rows=4, n_requests=400, fractions=(0.4, 0.8)):
  """One configuration's closed-loop saturation + open-loop percentiles
  at offered fractions of it. Returns a result dict."""
  model, plan, rule, mesh, state, store, bt_np = build(
      cfg, world, batch, host_thr=1024 if tiered else None)
  frozen = freeze(plan, rule, state, quantize=quantize, store=store)
  eng = ServeEngine(
      model, plan, frozen, mesh=mesh,
      tier_config=ServeTierConfig(cache_fraction=0.25, staging_grps=256)
      if tiered else None)
  reqs = _requests(bt_np, req_rows, 64)
  mb = MicroBatcher(eng.dispatch, max_batch=batch,
                    max_delay_s=max_delay_s)
  # warm the trace before measuring (compile time is not serve latency)
  mb.submit(*reqs[0]).result(timeout=300)
  sat_qps, _ = closed_loop(mb, reqs)
  rows = {"sat_qps": sat_qps, "points": []}
  for frac in fractions:
    qps = max(sat_qps * frac, 1.0)
    lats, rejected = open_loop(mb, reqs, qps, n_requests)
    p50, p99, p999 = pcts(lats)
    rows["points"].append({"frac": frac, "qps": qps, "p50": p50,
                           "p99": p99, "p999": p999,
                           "rejected": rejected})
  mb.close()
  return rows


def main(full_sweep=True):
  print(f"serve budget: world={WORLD} batch={GLOBAL_BATCH} "
        f"tables=8x(4096 rows, w16, h8, adagrad lanes) zipf({ALPHA})")
  thr = step_throughput(CFG, WORLD, GLOBAL_BATCH)
  for k, v in thr.items():
    print(f"  {k:<10} {v / 1e3:8.1f} krows/s "
          f"({GLOBAL_BATCH / v * 1e3:6.1f} ms/step)")
  ratio = thr["serve_int8"] / thr["eval_f32"]
  ok_thr = ratio >= 1.5
  print(f"acceptance (int8 serve >= 1.5x f32 eval step): "
        f"{'OK' if ok_thr else 'FAIL'} ({ratio:.2f}x)")

  ok_lat = True
  if full_sweep:
    combos = [(q, t, d) for q in ("f32", "int8") for t in (False, True)
              for d in (0.002, 0.01)]
    print("latency vs offered QPS (micro-batched, Poisson arrivals; "
          "req=4 rows):")
    for q, tiered, delay in combos:
      r = latency_sweep(CFG, WORLD, 512, q, tiered, delay)
      print(f"  {q:<4} {'tiered' if tiered else 'device':<6} "
            f"delay={delay * 1e3:4.1f}ms  sat {r['sat_qps']:7.1f} req/s")
      for p in r["points"]:
        tag = ""
        if q == "int8" and not tiered and delay == 0.002 \
            and p["frac"] == 0.8:
          mode_ok = p["p99"] <= 3.0 * p["p50"]
          ok_lat = ok_lat and mode_ok
          tag = f"  <- acceptance {'OK' if mode_ok else 'FAIL'}"
        print(f"    offered {p['frac']:.0%} ({p['qps']:7.1f} req/s)  "
              f"p50 {p['p50'] * 1e3:7.1f}  p99 {p['p99'] * 1e3:7.1f}  "
              f"p99.9 {p['p999'] * 1e3:7.1f} ms  "
              f"rejected {p['rejected']}{tag}")
    print(f"acceptance (p99 <= 3x p50 at 80% of saturation): "
          f"{'OK' if ok_lat else 'FAIL'}")
  return 0 if (ok_thr and ok_lat) else 1


def main_smoke():
  """The make-verify tier: tiny world, a few hundred requests; asserts
  finite percentiles and EXACT rejection accounting."""
  world, batch = 2, 64
  model, plan, rule, mesh, state, _store, bt_np = build(
      SMOKE_CFG, world, batch)
  frozen = freeze(plan, rule, state, quantize="int8")
  eng = ServeEngine(model, plan, frozen, mesh=mesh)
  reqs = _requests(bt_np, 4, 32)
  mb = MicroBatcher(eng.dispatch, max_batch=batch, max_delay_s=0.002)
  mb.submit(*reqs[0]).result(timeout=300)  # compile outside the clock
  lats, rejected = open_loop(mb, reqs, qps=300.0, n_requests=200)
  p50, p99, p999 = pcts(lats)
  mb.close()
  print(f"serve-smoke: world={world} 201 requests  p50 {p50 * 1e3:.1f}  "
        f"p99 {p99 * 1e3:.1f}  p99.9 {p999 * 1e3:.1f} ms  "
        f"rejected {rejected}")
  ok = np.isfinite([p50, p99, p999]).all() and p99 >= p50 > 0
  # deterministic load-shed accounting: flusher paused, queue bound 16
  # rows, 10 x 3-row submissions -> exactly 5 accepted, 5 rejected
  mb2 = MicroBatcher(lambda n, c: np.zeros((batch, 1)), max_batch=8,
                     queue_rows=16, start=False)
  shed = 0
  for _ in range(10):
    try:
      mb2.submit(np.zeros((3, 2), np.float32), [np.zeros(3, np.int32)])
    except Rejected:
      shed += 1
  exact = shed == 5 and mb2.stats["rejected"] == 5 \
      and mb2.stats["submitted"] == 10
  mb2.close(drain=False)
  print(f"serve-smoke: rejection accounting "
        f"{'exact' if exact else 'WRONG'} ({shed}/5)")
  ok = ok and exact
  print(f"serve-smoke: {'OK' if ok else 'FAIL'}")
  return 0 if ok else 1


if __name__ == "__main__":
  ap = argparse.ArgumentParser()
  ap.add_argument("--smoke", action="store_true",
                  help="tiny-world smoke tier (wired into make verify)")
  ap.add_argument("--steps-only", action="store_true",
                  help="skip the latency sweep (throughput acceptance "
                       "only)")
  args = ap.parse_args()
  if args.smoke:
    raise SystemExit(main_smoke())
  raise SystemExit(main(full_sweep=not args.steps_only))
