"""Device-trace one DLRM bench step and print per-fusion timings.

Captures a jax.profiler device trace of the compiled bench step (exact
bench config: batch 65536, vocab 1/16, SGD, dense_row_threshold 4096,
batch_hint) and prints every device op over a duration floor, sorted by
total time — the ground-truth attribution for where the step's
milliseconds sit (fusion names carry the originating HLO/op metadata).

Usage: python tools/trace_dlrm.py [batch] [vocab_scale]
"""

import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.models import DLRM, bce_loss
from distributed_embeddings_tpu.ops.packed_table import sgd_rule
from distributed_embeddings_tpu.training import (
    init_sparse_state_direct,
    make_sparse_train_step,
)

CRITEO_1TB_VOCAB = [
    39884406, 39043, 17289, 7420, 20263, 3, 7120, 1543, 63, 38532951,
    2953546, 403346, 10, 2208, 11938, 155, 4, 976, 14, 39979771,
    25641295, 39664984, 585935, 12972, 108, 36
]

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
SCALE = float(sys.argv[2]) if len(sys.argv) > 2 else 0.0625
AMP = (os.environ.get("BENCH_AMP", "0") == "1"
       or os.environ.get("AMP", "0") == "1")
EXACT = os.environ.get("BENCH_EXACT", "0") == "1"


def main():
  vocab = [max(4, int(v * SCALE)) for v in CRITEO_1TB_VOCAB]
  model = DLRM(vocab_sizes=vocab, embedding_dim=128, world_size=1,
               dense_row_threshold=4096,
               compute_dtype=jnp.bfloat16 if AMP else jnp.float32)
  plan = DistEmbeddingStrategy(
      [dict(input_dim=v, output_dim=128, combiner=None) for v in vocab],
      1, "basic", dense_row_threshold=4096, batch_hint=BATCH)

  rng = np.random.default_rng(0)
  numerical = jnp.asarray(rng.standard_normal((BATCH, 13)), jnp.float32)
  cats = [jnp.asarray(rng.integers(0, v, BATCH), jnp.int32) for v in vocab]
  labels = jnp.asarray(rng.integers(0, 2, BATCH), jnp.float32)
  batch = (numerical, cats, labels)

  rule = sgd_rule(24.0)
  dense_opt = optax.sgd(24.0)
  dummy_acts = [jnp.zeros((2, 128), jnp.float32) for _ in vocab]
  dense_params = model.init(jax.random.PRNGKey(0), numerical[:2],
                            [c[:2] for c in cats], emb_acts=dummy_acts)["params"]
  state_avals = jax.eval_shape(
      lambda: init_sparse_state_direct(plan, rule, dense_params, dense_opt,
                                       jax.random.PRNGKey(1)))
  step = make_sparse_train_step(model, plan, bce_loss, dense_opt, rule,
                                None, state_avals, batch, exact=EXACT)
  compiled = step.lower(state_avals, *batch).compile()
  state = init_sparse_state_direct(plan, rule, dense_params, dense_opt,
                                   jax.random.PRNGKey(1))
  for _ in range(3):
    state, loss = compiled(state, *batch)
  float(loss)

  tdir = f"/tmp/dlrm_trace_{int(time.time())}"
  with jax.profiler.trace(tdir):
    for _ in range(2):
      state, loss = compiled(state, *batch)
    float(loss)

  from _bench_util import parse_device_trace
  tot, cnt, args_of, _, _ = parse_device_trace(tdir)
  grand = sum(tot.values())
  print(f"total device us (2 steps x outer events double-count ok): {grand:.0f}")
  for nm, us in sorted(tot.items(), key=lambda kv: -kv[1])[:60]:
    extra = ""
    a = args_of.get(nm)
    if a:
      extra = " | " + " ".join(f"{k}={str(v)[:70]}" for k, v in a.items()
                               if k in ("long_name", "tf_op", "source",
                                        "hlo_op", "hlo_module"))
    print(f"{us/2/1000.0:9.3f} ms x? n={cnt[nm]:3d}  {nm[:70]}{extra[:160]}")


if __name__ == "__main__":
  main()
