"""Ablation timing of the DLRM step: fwd only / fwd+bwd / full.

Usage: python tools/profile_dlrm_parts.py [batch] [vocab_scale]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.models import DLRM, bce_loss
from distributed_embeddings_tpu.ops.packed_table import sgd_rule
from distributed_embeddings_tpu.parallel.lookup_engine import DistributedLookup
from distributed_embeddings_tpu.training import init_sparse_state_direct

CRITEO_1TB_VOCAB = [
    39884406, 39043, 17289, 7420, 20263, 3, 7120, 1543, 63, 38532951,
    2953546, 403346, 10, 2208, 11938, 155, 4, 976, 14, 39979771,
    25641295, 39664984, 585935, 12972, 108, 36
]

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
SCALE = float(sys.argv[2]) if len(sys.argv) > 2 else 0.0625
K = 8


def main():
  vocab = [max(4, int(v * SCALE)) for v in CRITEO_1TB_VOCAB]
  model = DLRM(vocab_sizes=vocab, embedding_dim=128, world_size=1)
  plan = DistEmbeddingStrategy(
      [dict(input_dim=v, output_dim=128, combiner=None) for v in vocab],
      1, "basic", dense_row_threshold=model.dense_row_threshold)
  engine = DistributedLookup(plan)
  rule = sgd_rule(24.0)
  layouts = engine.fused_layouts(rule)
  dense_opt = optax.sgd(24.0)

  rng = np.random.default_rng(0)
  numerical = jnp.asarray(rng.standard_normal((BATCH, 13)), jnp.float32)
  cats = [jnp.asarray(rng.integers(0, v, BATCH), jnp.int32) for v in vocab]
  labels = jnp.asarray(rng.integers(0, 2, BATCH), jnp.float32)

  dummy_acts = [jnp.zeros((2, 128), jnp.float32) for _ in vocab]
  dense_params = model.init(jax.random.PRNGKey(0), numerical[:2],
                            [c[:2] for c in cats],
                            emb_acts=dummy_acts)["params"]
  state = init_sparse_state_direct(plan, rule, dense_params, dense_opt,
                                   jax.random.PRNGKey(1))
  jax.block_until_ready(state["fused"])
  hotness_of = lambda i: 1  # noqa: E731

  def timeit(name, step, state):
    state2 = step(state, numerical, cats, labels)
    float(jnp.ravel(jax.tree_util.tree_leaves(state2)[0])[0])

    def run(n, st):
      t0 = time.perf_counter()
      for _ in range(n):
        st = step(st, numerical, cats, labels)
      float(jnp.ravel(jax.tree_util.tree_leaves(st)[0])[0])
      return time.perf_counter() - t0, st

    t1, state2 = run(K, state2)
    t2, state2 = run(2 * K, state2)
    print(f"{name:28s}: {(t2 - t1) / K * 1e3:8.2f} ms", flush=True)

  # 1. route only
  def route_only(state, numerical, cats, labels):
    ids_all = engine.route_ids(cats, hotness_of)
    bump = sum(v.sum() for v in ids_all.values()) % 2
    return {**state, "step": state["step"] + bump}

  timeit("route_ids", jax.jit(route_only), state)

  # 2. route + gather
  def gather_only(state, numerical, cats, labels):
    ids_all = engine.route_ids(cats, hotness_of)
    z, res = engine.lookup_sparse_fused(state["fused"], layouts, ids_all)
    bump = (sum(zb.sum() for zb in z.values()) * 0).astype(jnp.int32)
    return {**state, "step": state["step"] + 1 + bump}

  timeit("route+gather", jax.jit(gather_only), state)

  # 3. forward to loss
  def fwd_only(state, numerical, cats, labels):
    ids_all = engine.route_ids(cats, hotness_of)
    z, res = engine.lookup_sparse_fused(state["fused"], layouts, ids_all)
    acts = engine.finish_forward(z, state["emb_dense"], ids_all, BATCH,
                                 hotness_of)
    logits = model.apply({"params": state["dense"]}, numerical, cats,
                         emb_acts=acts)
    loss = bce_loss(logits, labels)
    return {**state, "step": state["step"] + 1 + (loss * 0).astype(jnp.int32)}

  timeit("forward(loss)", jax.jit(fwd_only), state)

  # 4. fwd + bwd, no sparse apply
  def bwd_no_apply(state, numerical, cats, labels):
    ids_all = engine.route_ids(cats, hotness_of)
    z, res = engine.lookup_sparse_fused(state["fused"], layouts, ids_all)

    def loss_with(dp, z_sp):
      acts = engine.finish_forward(z_sp, state["emb_dense"], ids_all, BATCH,
                                   hotness_of)
      logits = model.apply({"params": dp}, numerical, cats, emb_acts=acts)
      return bce_loss(logits, labels)

    loss, (d_dense, d_z) = jax.value_and_grad(
        loss_with, argnums=(0, 1))(state["dense"], z)
    upd, dop = dense_opt.update(d_dense, state["dense_opt"], state["dense"])
    dense = optax.apply_updates(state["dense"], upd)
    bump = (sum(v.sum() for v in d_z.values()) * 0).astype(jnp.int32)
    return {**state, "dense": dense, "dense_opt": dop,
            "step": state["step"] + 1 + bump}

  timeit("fwd+bwd (no apply)", jax.jit(bwd_no_apply), state)

  # 5. full
  def full(state, numerical, cats, labels):
    ids_all = engine.route_ids(cats, hotness_of)
    z, res = engine.lookup_sparse_fused(state["fused"], layouts, ids_all)

    def loss_with(dp, z_sp):
      acts = engine.finish_forward(z_sp, state["emb_dense"], ids_all, BATCH,
                                   hotness_of)
      logits = model.apply({"params": dp}, numerical, cats, emb_acts=acts)
      return bce_loss(logits, labels)

    loss, (d_dense, d_z) = jax.value_and_grad(
        loss_with, argnums=(0, 1))(state["dense"], z)
    upd, dop = dense_opt.update(d_dense, state["dense_opt"], state["dense"])
    dense = optax.apply_updates(state["dense"], upd)
    fused = engine.apply_sparse(state["fused"], layouts, d_z, res, rule,
                                state["step"])
    return {**state, "dense": dense, "dense_opt": dop, "fused": fused,
            "step": state["step"] + 1}

  timeit("full step", jax.jit(full, donate_argnums=(0,)), state)


if __name__ == "__main__":
  main()
