"""Ablation timing of the DLRM step: route / gather / fwd / bwd / full.

State is passed as explicit args but only a scalar is returned, so
non-donated cases neither copy the multi-GiB buffers on output nor bake
them into the executable as constants (closing over them exploded
compile time).

Usage: [AMP=1] python tools/profile_dlrm_parts.py [batch] [vocab_scale]
"""

import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.models import DLRM, bce_loss
from distributed_embeddings_tpu.ops.packed_table import sgd_rule
from distributed_embeddings_tpu.parallel.lookup_engine import DistributedLookup
from distributed_embeddings_tpu.training import init_sparse_state_direct

CRITEO_1TB_VOCAB = [
    39884406, 39043, 17289, 7420, 20263, 3, 7120, 1543, 63, 38532951,
    2953546, 403346, 10, 2208, 11938, 155, 4, 976, 14, 39979771,
    25641295, 39664984, 585935, 12972, 108, 36
]

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
SCALE = float(sys.argv[2]) if len(sys.argv) > 2 else 0.0625
AMP = os.environ.get("AMP", "0") == "1"
K = 8


def main():
  vocab = [max(4, int(v * SCALE)) for v in CRITEO_1TB_VOCAB]
  model = DLRM(vocab_sizes=vocab, embedding_dim=128, world_size=1,
               compute_dtype=jnp.bfloat16 if AMP else jnp.float32)
  plan = DistEmbeddingStrategy(
      [dict(input_dim=v, output_dim=128, combiner=None) for v in vocab],
      1, "basic", dense_row_threshold=model.dense_row_threshold,
      batch_hint=BATCH)
  engine = DistributedLookup(plan)
  rule = sgd_rule(24.0)
  layouts = engine.fused_layouts(rule)
  dense_opt = optax.sgd(24.0)

  rng = np.random.default_rng(0)
  numerical = jnp.asarray(rng.standard_normal((BATCH, 13)), jnp.float32)
  cats = [jnp.asarray(rng.integers(0, v, BATCH), jnp.int32) for v in vocab]
  labels = jnp.asarray(rng.integers(0, 2, BATCH), jnp.float32)

  dummy_acts = [jnp.zeros((2, 128), jnp.float32) for _ in vocab]
  dense_params = model.init(jax.random.PRNGKey(0), numerical[:2],
                            [c[:2] for c in cats],
                            emb_acts=dummy_acts)["params"]
  state = init_sparse_state_direct(plan, rule, dense_params, dense_opt,
                                   jax.random.PRNGKey(1))
  jax.block_until_ready(state["fused"])
  hotness_of = lambda i: 1  # noqa: E731

  def timeit(name, body):
    """body(state, carry_scalar) -> scalar."""
    step = jax.jit(body)
    c = step(state, jnp.zeros((), jnp.float32))
    float(c)

    def run(n, c):
      t0 = time.perf_counter()
      for _ in range(n):
        c = step(state, c)
      float(c)
      return time.perf_counter() - t0, c

    t1, c = run(K, c)
    t2, c = run(2 * K, c)
    print(f"{name:22s}: {(t2 - t1) / K * 1e3:8.2f} ms", flush=True)

  def cats_dep(carry):
    bump = (carry * 0).astype(jnp.int32)
    return [c + bump for c in cats]

  def route_only(state, carry):
    ids_all = engine.route_ids(cats_dep(carry), hotness_of)
    return carry + sum(v.sum() for v in ids_all.values()).astype(
        jnp.float32) * 0

  timeit("route_ids", route_only)

  def gather_only(state, carry):
    ids_all = engine.route_ids(cats_dep(carry), hotness_of)
    z, _ = engine.lookup_sparse_fused(state["fused"], layouts, ids_all)
    return carry + sum(zb.sum() for zb in z.values()).astype(jnp.float32) * 0

  timeit("route+gather", gather_only)

  def fwd_only(state, carry):
    ids_all = engine.route_ids(cats_dep(carry), hotness_of)
    z, _ = engine.lookup_sparse_fused(state["fused"], layouts, ids_all)
    acts = engine.finish_forward(z, state["emb_dense"], ids_all, BATCH,
                                 hotness_of)
    logits = model.apply({"params": state["dense"]}, numerical, cats,
                         emb_acts=acts)
    return carry + bce_loss(logits, labels) * 0

  timeit("forward(loss)", fwd_only)

  def bwd_no_apply(state, carry):
    ids_all = engine.route_ids(cats_dep(carry), hotness_of)
    z, _ = engine.lookup_sparse_fused(state["fused"], layouts, ids_all)

    def loss_with(dp, z_sp):
      acts = engine.finish_forward(z_sp, state["emb_dense"], ids_all, BATCH,
                                   hotness_of)
      logits = model.apply({"params": dp}, numerical, cats, emb_acts=acts)
      return bce_loss(logits, labels)

    loss, (d_dense, d_z) = jax.value_and_grad(
        loss_with, argnums=(0, 1))(state["dense"], z)
    s = sum(jnp.sum(v) for v in jax.tree_util.tree_leaves(d_dense))
    s = s + sum(v.sum() for v in d_z.values())
    return carry + (loss + s).astype(jnp.float32) * 0

  timeit("fwd+bwd (no apply)", bwd_no_apply)

  from distributed_embeddings_tpu.training import make_sparse_train_step
  batch = (numerical, cats, labels)
  step = make_sparse_train_step(model, plan, bce_loss, dense_opt, rule,
                                None, state, batch)
  st, loss = step(state, *batch)
  float(loss)

  def run(n, st):
    t0 = time.perf_counter()
    for _ in range(n):
      st, loss = step(st, *batch)
    float(loss)
    return time.perf_counter() - t0, st

  t1, st = run(K, st)
  t2, st = run(2 * K, st)
  print(f"{'full step':22s}: {(t2 - t1) / K * 1e3:8.2f} ms", flush=True)


if __name__ == "__main__":
  main()
