"""Isolate stack/einsum/take/concat costs inside dot_interact.

Usage: python tools/profile_interact_pieces.py [batch]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
K = 8
F = 27
D = 128


def timeit(name, fn, *args):
  step = jax.jit(fn)
  c = step(*args)
  jax.block_until_ready(c)
  float(c)

  def run(n):
    t0 = time.perf_counter()
    for _ in range(n):
      c = step(*args)
    float(c)
    return time.perf_counter() - t0

  t1 = run(K)
  t2 = run(2 * K)
  print(f"{name:40s}: {(t2 - t1) / K * 1e3:8.2f} ms", flush=True)


def main():
  key = jax.random.PRNGKey(0)
  parts = [jax.random.normal(jax.random.fold_in(key, i), (BATCH, D),
                             jnp.float32) for i in range(F)]
  feats = jnp.stack(parts, axis=1)
  rows, cols = np.tril_indices(F, k=-1)
  # rows * F + cols < F^2 (feature count squared, tens not billions)
  take = jnp.asarray(rows * F + cols, jnp.int32)  # graftlint: disable=GL106
  p = len(rows)

  timeit("stack 27x[B,128]", lambda *ps: jnp.sum(jnp.stack(ps, 1)), *parts)

  def einsum_only(x):
    return jnp.sum(jnp.einsum("bfd,bgd->bfg", x, x,
                              preferred_element_type=jnp.float32))

  timeit("einsum only", einsum_only, feats)

  inter = jnp.einsum("bfd,bgd->bfg", feats, feats)
  flat = inter.reshape(BATCH, F * F)

  timeit("take axis1 379-of-729", lambda f: jnp.sum(jnp.take(f, take, axis=1)),
         flat)

  sel = np.zeros((F * F, p), np.float32)
  sel[np.asarray(take), np.arange(p)] = 1.0
  sel16 = jnp.asarray(sel, jnp.bfloat16)

  def take_mm(f):
    return jnp.sum(jnp.einsum("bi,ip->bp", f.astype(jnp.bfloat16), sel16,
                              preferred_element_type=jnp.float32))

  timeit("take via bf16 matmul", take_mm, flat)

  def einsum_take(x):
    i = jnp.einsum("bfd,bgd->bfg", x, x, preferred_element_type=jnp.float32)
    return jnp.sum(jnp.take(i.reshape(BATCH, F * F), take, axis=1))

  timeit("einsum + take", einsum_take, feats)

  def einsum_take_mm(x):
    i = jnp.einsum("bfd,bgd->bfg", x, x, preferred_element_type=jnp.float32)
    return jnp.sum(jnp.einsum("bi,ip->bp", i.reshape(BATCH, F * F)
                              .astype(jnp.bfloat16), sel16,
                              preferred_element_type=jnp.float32))

  timeit("einsum + take-matmul", einsum_take_mm, feats)

  # full fwd as in dot_interact (stack from parts)
  def full(x0, *rest):
    fe = jnp.stack([x0] + list(rest), 1)
    i = jnp.einsum("bfd,bgd->bfg", fe, fe, preferred_element_type=jnp.float32)
    acts = jnp.take(i.reshape(BATCH, F * F), take, axis=1)
    return jnp.sum(jnp.concatenate([acts, x0], axis=1))

  timeit("full fwd (stack+einsum+take+cat)", full, *parts)


if __name__ == "__main__":
  main()
