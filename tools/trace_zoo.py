"""Device-trace one synthetic-zoo fused step and print per-fusion timings.

Same harness as tools/trace_dlrm.py but for the zoo models — the
ground-truth attribution for where each model's milliseconds sit.

Usage: python tools/trace_zoo.py [model] [batch] [vocab_scale] [micro]
"""

import dataclasses
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.models import (
    SYNTHETIC_MODELS,
    SyntheticModel,
    bce_loss,
    expand_tables,
    generate_batch,
)
from distributed_embeddings_tpu.ops.packed_table import adagrad_rule
from distributed_embeddings_tpu.training import (
    init_sparse_state_direct,
    make_sparse_train_step,
)

MODEL = sys.argv[1] if len(sys.argv) > 1 else "tiny"
BATCH = int(sys.argv[2]) if len(sys.argv) > 2 else 65536
SCALE = float(sys.argv[3]) if len(sys.argv) > 3 else 1.0
MICRO = int(sys.argv[4]) if len(sys.argv) > 4 else 1


def main():
  cfg = SYNTHETIC_MODELS[MODEL]
  tables, tmap, hotness = expand_tables(cfg)
  model = SyntheticModel(config=cfg, world_size=1)
  thr = model.dense_row_threshold
  if SCALE != 1.0:
    tables = [dataclasses.replace(t, input_dim=max(8, int(t.input_dim * SCALE)))
              for t in tables]
    thr = max(8, int(thr * SCALE))
  plan = DistEmbeddingStrategy(tables, 1, "basic", input_table_map=tmap,
                               dense_row_threshold=thr,
                               input_hotness=hotness, batch_hint=BATCH)
  numerical, cats, labels = generate_batch(cfg, BATCH, alpha=1.05, seed=0)
  cats = [(c % tables[t].input_dim if SCALE != 1.0
           else np.minimum(c, tables[t].input_dim - 1)).astype(np.int32)
          for c, t in zip(cats, tmap)]
  cats = [jnp.asarray(c if h > 1 else c[:, 0])
          for c, h in zip(cats, hotness)]
  batch = (jnp.asarray(numerical), cats, jnp.asarray(labels))

  dense_opt = optax.adagrad(0.01)
  rule = adagrad_rule(0.01)
  dummy_acts = [jnp.zeros((2, tables[t].output_dim), jnp.float32)
                for t in tmap]
  dense_params = model.init(jax.random.PRNGKey(0), batch[0][:2],
                            [c[:2] for c in cats],
                            emb_acts=dummy_acts)["params"]
  state_avals = jax.eval_shape(
      lambda: init_sparse_state_direct(plan, rule, dense_params, dense_opt,
                                       jax.random.PRNGKey(1)))
  step = make_sparse_train_step(model, plan, bce_loss, dense_opt, rule,
                                None, state_avals, batch,
                                micro_batches=MICRO)
  compiled = step.lower(state_avals, *batch).compile()
  state = init_sparse_state_direct(plan, rule, dense_params, dense_opt,
                                   jax.random.PRNGKey(1))
  for _ in range(2):
    state, loss = compiled(state, *batch)
  float(loss)

  tdir = f"/tmp/zoo_trace_{MODEL}_{int(time.time())}"
  with jax.profiler.trace(tdir):
    for _ in range(2):
      state, loss = compiled(state, *batch)
    float(loss)

  from _bench_util import parse_device_trace
  tot, cnt, args_of, by_src, _ = parse_device_trace(tdir)
  print("== top ops ==")
  for nm, us in sorted(tot.items(), key=lambda kv: -kv[1])[:45]:
    a = args_of.get(nm)
    extra = ""
    if a:
      extra = " | " + " ".join(f"{k}={str(v)[:70]}" for k, v in a.items()
                               if k in ("long_name", "source"))
    print(f"{us/2/1000.0:9.3f} ms n={cnt[nm]:4d}  {nm[:46]}{extra[:150]}")
  print("== by source line ==")
  for src, us in sorted(by_src.items(), key=lambda kv: -kv[1])[:25]:
    print(f"{us/2/1000.0:9.3f} ms  {src}")


if __name__ == "__main__":
  main()
