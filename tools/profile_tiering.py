"""Microbenchmark of the tiered-storage path (`tiering/`) on the CPU mesh.

Trains a small DLRM whose big table is host-offloaded on a zipfian id
stream (the `utils/data.py` SyntheticDataset batch shape with
`models/synthetic.power_law_ids` categoricals — the uniform generator
would defeat the cache) and reports, per (alpha, cache_fraction) point:

  - hot-tier cache hit rate (cumulative over the run)
  - host-gather bytes/step (the staging upload the cold tier costs)
  - spill steps (batches whose deduped cold rows overflowed staging)
  - wall-clock step time, tiered vs. the all-device baseline, SPLIT
    into its host-pipeline part (classify + stage + write-back +
    re-rank, summed from the trace spans) and its device part
    (``device/step`` windows) — the split is what the overlap scheduler
    (``pipeline.py``, ``tools/profile_overlap.py``) can hide: serial
    wall ~ host + device, overlapped wall ~ max(host, device)

CPU-mesh numbers size the PROTOCOL (hit rate, bytes, spills are platform
independent); real-TPU host-gather bandwidth is a ROADMAP open item.

Usage: PYTHONPATH=/root/repo python tools/profile_tiering.py
"""

import os
import time

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
  os.environ["XLA_FLAGS"] = (
      flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from distributed_embeddings_tpu.layers.dist_model_parallel import (  # noqa: E402
    get_weights,
    set_weights,
)
from distributed_embeddings_tpu.layers.embedding import TableConfig  # noqa: E402
from distributed_embeddings_tpu.layers.planner import (  # noqa: E402
    DistEmbeddingStrategy,
)
from distributed_embeddings_tpu.models import DLRM, bce_loss  # noqa: E402
from distributed_embeddings_tpu.models.dlrm import _dlrm_initializer  # noqa: E402
from distributed_embeddings_tpu.models.synthetic import power_law_ids  # noqa: E402
from distributed_embeddings_tpu.ops.packed_table import sparse_rule  # noqa: E402
from distributed_embeddings_tpu.parallel import create_mesh  # noqa: E402
from distributed_embeddings_tpu.tiering import (  # noqa: E402
    HostTierStore,
    TieredTrainer,
    TieringConfig,
    TieringPlan,
    init_tiered_state_from_params,
)
from distributed_embeddings_tpu.training import (  # noqa: E402
    init_sparse_state,
    make_sparse_train_step,
    shard_batch,
    shard_params,
)
from distributed_embeddings_tpu import telemetry  # noqa: E402

WORLD = 4
VOCAB = [200_000, 20_000, 300]
WIDTH = 16
BATCH = 512
STEPS = 24
WARM = 4
STAGING = 2048


# the serial step's host-pipeline stages (everything the overlap worker
# could hide) vs the device window, summed from the trace span durations
HOST_SPANS = ("tiered/classify", "tiered/stage", "tiered/write_back",
              "tiered/rerank")


def _host_device_ms(events, n_steps):
  host = sum(dur for ph, _track, name, _t0, dur, _args in events
             if ph == "X" and name in HOST_SPANS)
  dev = sum(dur for ph, _track, name, _t0, dur, _args in events
            if ph == "X" and name == "device/step")
  return host / n_steps / 1e6, dev / n_steps / 1e6


def make_batches(alpha, n):
  r = np.random.default_rng(7)
  out = []
  for _ in range(n):
    numerical = r.standard_normal((BATCH, 13)).astype(np.float32)
    cats = [power_law_ids(r, BATCH, 1, v, alpha).astype(np.int32)[:, 0]
            for v in VOCAB]
    labels = r.integers(0, 2, BATCH).astype(np.float32)
    out.append((numerical, cats, labels))
  return out


def build(host_thr):
  tables = [TableConfig(input_dim=v, output_dim=WIDTH,
                        initializer=_dlrm_initializer(v)) for v in VOCAB]
  return DistEmbeddingStrategy(tables, WORLD, "memory_balanced",
                               dense_row_threshold=0,
                               host_row_threshold=host_thr)


def main():
  model = DLRM(vocab_sizes=VOCAB, embedding_dim=WIDTH,
               bottom_mlp=(64, WIDTH), top_mlp=(64, 1), world_size=WORLD,
               strategy="memory_balanced", dense_row_threshold=0)
  mesh = create_mesh(WORLD)
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.adam(1e-3)
  plan_b = build(None)
  plan_t = build(50_000)
  report = plan_t.tier_capacity_report(rule.n_aux)
  print(f"tables {VOCAB} width {WIDTH} world {WORLD} batch {BATCH}: "
        f"device-tier {report['device_bytes_per_rank']:,} B/rank, "
        f"cold store {report['host_bytes_per_rank']:,} B/rank")

  batches0 = make_batches(1.05, 1)
  params_b = model.init(jax.random.PRNGKey(0), batches0[0][0],
                        batches0[0][1])["params"]
  tables_t = set_weights(plan_t, get_weights(plan_b, params_b["embeddings"]))
  params_t = {k: v for k, v in params_b.items() if k != "embeddings"}
  params_t["embeddings"] = {k: jnp.asarray(v) for k, v in tables_t.items()}

  # ---- all-device baseline step time ------------------------------------
  state_b = shard_params(init_sparse_state(plan_b, params_b, rule, opt),
                         mesh)
  step_b = make_sparse_train_step(model, plan_b, bce_loss, opt, rule, mesh,
                                  state_b, batches0[0], donate=False)
  batches = make_batches(1.05, STEPS)
  for b in batches[:WARM]:
    state_b, loss = step_b(state_b, *shard_batch(b, mesh))
  jax.block_until_ready(loss)
  t0 = time.perf_counter()
  for b in batches[WARM:]:
    state_b, loss = step_b(state_b, *shard_batch(b, mesh))
  jax.block_until_ready(loss)
  base_ms = (time.perf_counter() - t0) / (STEPS - WARM) * 1e3
  print(f"all-device baseline: {base_ms:7.2f} ms/step")

  hdr = (f"{'alpha':>5} {'cache%':>6} | {'hit%':>6} {'gatherB/step':>12} "
         f"{'spills':>6} {'ms/step':>8} {'host-ms':>8} {'dev-ms':>8}")
  print(hdr)
  print("-" * len(hdr))
  for alpha in (1.05, 1.2):
    batches = make_batches(alpha, STEPS)
    for frac in (0.05, 0.15, 0.30):
      cfg = TieringConfig(cache_fraction=frac, staging_grps=STAGING,
                          rerank_interval=6)
      tplan = TieringPlan(plan_t, rule, cfg)
      store = HostTierStore(tplan)
      state = shard_params(
          init_tiered_state_from_params(tplan, store, rule, params_t, opt,
                                        mesh=mesh), mesh)
      trainer = TieredTrainer(model, tplan, store, bce_loss, opt, rule,
                              mesh, state, batches[0], donate=False)
      trainer.run(batches[:WARM])
      # reset counters so warmup compiles/fills don't skew the report
      for m in trainer.hits.values():
        m[:] = 0
      trainer.steps = 0
      trainer.prefetcher.total_host_gather_bytes = 0
      trainer.prefetcher.spill_steps = 0
      tracer = telemetry.Tracer()
      telemetry.install_tracer(tracer)
      try:
        t0 = time.perf_counter()
        trainer.run(batches[WARM:])
        dt = (time.perf_counter() - t0) / (STEPS - WARM)
      finally:
        telemetry.uninstall_tracer()
      host_ms, dev_ms = _host_device_ms(tracer.events(), STEPS - WARM)
      m = trainer.metrics_summary()
      print(f"{alpha:5.2f} {frac * 100:5.0f}% | {m['hit_rate'] * 100:5.1f}% "
            f"{m['host_gather_bytes'] // m['steps']:12,} "
            f"{m['spill_steps']:6d} {dt * 1e3:8.2f} {host_ms:8.2f} "
            f"{dev_ms:8.2f}")


if __name__ == "__main__":
  main()
