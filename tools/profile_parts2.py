"""Fine-grained DLRM step ablation: isolate apply scatter, MLP bwd,
interaction bwd, dense one-hot bwd at the exact bench shapes.

Usage: python tools/profile_parts2.py [batch] [vocab_scale]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

CRITEO_1TB_VOCAB = [
    39884406, 39043, 17289, 7420, 20263, 3, 7120, 1543, 63, 38532951,
    2953546, 403346, 10, 2208, 11938, 155, 4, 976, 14, 39979771,
    25641295, 39664984, 585935, 12972, 108, 36
]

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
SCALE = float(sys.argv[2]) if len(sys.argv) > 2 else 0.0625
K = 8
W = 128


def timeit(name, fn, *args, donate=()):
  """Times fn; with donate=(0,), fn must return the donated arg's successor.

  Returns the final carry (the live successor buffer) so callers can keep
  using it after the original was consumed by donation."""
  step = jax.jit(fn, donate_argnums=donate)
  args = list(args)
  carry = step(*args)
  jax.block_until_ready(carry)

  def run(n, carry):
    t0 = time.perf_counter()
    for _ in range(n):
      if donate:
        args[donate[0]] = carry if not isinstance(carry, tuple) else carry[0]
      carry = step(*args)
    jax.tree_util.tree_map(
        lambda x: float(x[(0,) * x.ndim]),
        carry if isinstance(carry, tuple) else (carry,))
    return time.perf_counter() - t0, carry

  t1, carry = run(K, carry)
  t2, carry = run(2 * K, carry)
  print(f"{name:34s}: {(t2 - t1) / K * 1e3:8.2f} ms", flush=True)
  return carry if not isinstance(carry, tuple) else carry[0]


def main():
  vocab = [max(4, int(v * SCALE)) for v in CRITEO_1TB_VOCAB]
  sparse_vocab = [v for v in vocab if v > 2048]
  n_sparse = len(sparse_vocab)
  rows_total = sum(sparse_vocab)
  print(f"sparse tables: {n_sparse}, total rows {rows_total}")

  rng = np.random.default_rng(0)
  key = jax.random.PRNGKey(0)

  # ---- 1. the apply scatter in isolation (exact shapes) ----
  buf = jax.random.normal(key, (rows_total, W), jnp.float32)
  ids = jnp.asarray(rng.integers(0, rows_total, n_sparse * BATCH), jnp.int32)
  d_z = jax.random.normal(key, (n_sparse, BATCH, W), jnp.float32)

  def apply_like(buf, ids, d_z):
    g = d_z.reshape(-1, W)
    delta = -24.0 * g
    ids2, delta = jax.lax.optimization_barrier((ids, delta))
    return buf.at[ids2].add(delta, mode="drop")

  buf = timeit("apply scatter (barrier)", apply_like, buf, ids, d_z, donate=(0,))

  def apply_nobarrier(buf, ids, d_z):
    g = d_z.reshape(-1, W)
    return buf.at[ids].add(-24.0 * g, mode="drop")

  buf = timeit("apply scatter (fused)", apply_nobarrier, buf, ids, d_z, donate=(0,))

  # scatter with ids pre-sorted (locality)
  ids_sorted = jnp.sort(ids)
  buf = timeit("apply scatter (sorted ids)", apply_like, buf, ids_sorted, d_z,
               donate=(0,))

  # per-table scatter windows (9 scatters of 64k rows each, into one donated
  # buffer) -- mimics per-bucket chunking
  offs = np.cumsum([0] + sparse_vocab[:-1])
  ids_tbl = jnp.stack([
      # id + table offset <= sum(sparse_vocab), < 2^31 at bench scale
      jnp.asarray(rng.integers(0, v, BATCH) + o,  # graftlint: disable=GL106
                  jnp.int32)
      for v, o in zip(sparse_vocab, offs)])

  # ---- 2. MLPs + interaction fwd / fwd+bwd ----
  from distributed_embeddings_tpu.models import DLRM, bce_loss
  model = DLRM(vocab_sizes=vocab, embedding_dim=W, world_size=1)
  numerical = jnp.asarray(rng.standard_normal((BATCH, 13)), jnp.float32)
  cats = [jnp.asarray(rng.integers(0, v, BATCH), jnp.int32) for v in vocab]
  labels = jnp.asarray(rng.integers(0, 2, BATCH), jnp.float32)
  acts = [jax.random.normal(jax.random.fold_in(key, i), (BATCH, W),
                            jnp.float32) for i in range(len(vocab))]
  dense_params = model.init(jax.random.PRNGKey(0), numerical[:2],
                            [c[:2] for c in cats],
                            emb_acts=[a[:2] for a in acts])["params"]

  def mlp_fwd(p, acts):
    logits = model.apply({"params": p}, numerical, cats, emb_acts=acts)
    return bce_loss(logits, labels)

  timeit("model fwd (acts given)", mlp_fwd, dense_params, acts)

  def mlp_bwd(p, acts):
    loss, (d_p, d_a) = jax.value_and_grad(mlp_fwd, argnums=(0, 1))(p, acts)
    return loss + sum(jnp.sum(v) for v in jax.tree_util.tree_leaves(d_p)) \
        + sum(a.sum() for a in d_a)

  timeit("model fwd+bwd (acts given)", mlp_bwd, dense_params, acts)

  # ---- 3. one big scatter vs same volume as one scatter per table ----
  def apply_per_table(buf, ids_tbl, d_z):
    for t in range(n_sparse):
      g = d_z[t]
      buf = buf.at[ids_tbl[t]].add(-24.0 * g, mode="drop")
    return buf

  buf = timeit("apply 9x per-table scatter", apply_per_table, buf, ids_tbl,
               d_z, donate=(0,))

  # ---- 4. scatter into small buffer (microbench replica) ----
  buf_small = jax.random.normal(key, (1 << 22, W), jnp.float32)
  ids_small = jnp.asarray(rng.integers(0, 1 << 22, n_sparse * BATCH),
                          jnp.int32)
  buf_small = timeit("scatter 590k -> 4M rows", apply_like, buf_small,
                     ids_small, d_z, donate=(0,))

  # ---- 5. pure scatter, no delta compute (deltas precomputed) ----
  delta_pre = jax.random.normal(key, (n_sparse * BATCH, W), jnp.float32)

  def pure_scatter(buf, ids, delta):
    return buf.at[ids].add(delta, mode="drop")

  buf = timeit("pure scatter (pre delta)", pure_scatter, buf, ids, delta_pre,
               donate=(0,))

  # ---- 6. gather same volume (reference point) ----
  def pure_gather(buf, ids):
    return jnp.take(buf, ids, axis=0, mode="fill", fill_value=0).sum()

  timeit("pure gather 590k", pure_gather, buf, ids)


if __name__ == "__main__":
  main()
