"""Pallas fused DLRM interaction vs the XLA matmul-form (round 5).

The round-4 trace shows the interaction block costs ~13 ms of the ~52 ms
DLRM step, and over half of it is pure layout copies: XLA lowers the
per-sample product einsum ("bpd,bqd->bpq") to a convolution that wants
batch-minor operand layouts, so the step pays [B,27,128]/[B,3456] copies
on both sides of the matmul pair (copy.226/227/232/234/235 + fusion.6 in
tools/trace_dlrm.py output, ~7.5 ms/step at B=64k).

A Pallas kernel computes the per-sample products from feats in their
NATURAL row-major layout (batched MXU dot over a VMEM-resident block),
so no relayout copies exist at all; the tiny inter tensor ([B,27,27])
round-trips HBM in bf16, and the selection matmuls (dense [B,729]@
[729,351], already layout-friendly) stay in XLA.

Measures fwd+bwd (value_and_grad of a non-linear consumer) for:
  A. the production `_tril_products` custom-VJP path (models/dlrm.py)
  B. pallas inter/d_feats kernels + XLA selection matmuls

Usage: python tools/proto_pallas_interact.py [batch] [block]
"""

import functools
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_embeddings_tpu.models.dlrm import (  # noqa: E402
    _tril_products,
    _tril_select_np,
)

B = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
S = int(sys.argv[2]) if len(sys.argv) > 2 else 256
F = 27
D = 128


def _inter_kernel(feats_ref, out_ref):
  f = feats_ref[...]  # [S, F, D] bf16, natural layout
  inter = jax.lax.dot_general(
      f, f, (((2,), (2,)), ((0,), (0,))),
      preferred_element_type=jnp.float32)  # [S, F, F]
  out_ref[...] = inter.astype(out_ref.dtype)


def _dfeats_kernel(dsym_ref, feats_ref, out_ref):
  ds = dsym_ref[...]  # [S, F, F] bf16 (symmetric)
  f = feats_ref[...]  # [S, F, D] bf16
  # d_feats = 2 * d_sym @ f  per sample ("spq,sqd->spd")
  d = jax.lax.dot_general(
      ds, f, (((2,), (1,)), ((0,), (0,))),
      preferred_element_type=jnp.float32)
  out_ref[...] = (2.0 * d).astype(out_ref.dtype)


def pallas_inter(feats):
  b = feats.shape[0]
  return pl.pallas_call(
      _inter_kernel,
      grid=(b // S,),
      in_specs=[pl.BlockSpec((S, F, D), lambda i: (i, 0, 0))],
      out_specs=pl.BlockSpec((S, F, F), lambda i: (i, 0, 0)),
      out_shape=jax.ShapeDtypeStruct((b, F, F), jnp.bfloat16),
  )(feats)


def pallas_dfeats(dsym, feats):
  b = feats.shape[0]
  return pl.pallas_call(
      _dfeats_kernel,
      grid=(b // S,),
      in_specs=[
          pl.BlockSpec((S, F, F), lambda i: (i, 0, 0)),
          pl.BlockSpec((S, F, D), lambda i: (i, 0, 0)),
      ],
      out_specs=pl.BlockSpec((S, F, D), lambda i: (i, 0, 0)),
      out_shape=jax.ShapeDtypeStruct((b, F, D), jnp.bfloat16),
  )(dsym, feats)


def _fused_fwd_kernel(npair, m_ref, feats_ref, acts_ref):
  f = feats_ref[...]  # [S, F, D] bf16
  inter = jax.lax.dot_general(
      f, f, (((2,), (2,)), ((0,), (0,))),
      preferred_element_type=jnp.float32)  # [S, F, F]
  i16 = inter.astype(jnp.bfloat16)
  # Mosaic cannot shape-cast [S,F,F]->[S,F*F]; unroll the selection matmul
  # over the p axis instead: acts = sum_p inter[:,p,:] @ M[p]
  acc = jnp.zeros((f.shape[0], npair), jnp.float32)
  for p in range(F):
    acc = acc + jnp.dot(i16[:, p, :], m_ref[p],
                        preferred_element_type=jnp.float32)
  acts_ref[...] = acc


def _fused_bwd_kernel(mt_ref, dacts_ref, feats_ref, dflat_ref, dsym_ref):
  da = dacts_ref[...].astype(jnp.bfloat16)  # [S, npair]
  for p in range(F):
    row = jnp.dot(da, mt_ref[p], preferred_element_type=jnp.float32)
    dsym_ref[:, pl.dslice(p, 1), :] = row[:, None, :]
  f = feats_ref[...]  # [S, F, D]
  d = jax.lax.dot_general(
      dsym_ref[...].astype(jnp.bfloat16), f, (((2,), (1,)), ((0,), (0,))),
      preferred_element_type=jnp.float32)  # [S, F, D]
  dflat_ref[...] = (2.0 * d).astype(dflat_ref.dtype)


def make_fused_acts():
  m_np, _ = _tril_select_np(F, -1)
  npair = m_np.shape[-1]
  m3 = jnp.asarray(m_np, jnp.bfloat16)  # [F, F, npair]
  m3t = jnp.asarray(np.swapaxes(m_np, 1, 2), jnp.bfloat16)  # [F, npair, F]

  @jax.custom_vjp
  def acts_fn(flat):
    a, _ = fwd(flat)
    return a

  def fwd(flat):
    b = flat.shape[0]
    f16 = flat.astype(jnp.bfloat16).reshape(b, F, D)
    acts = pl.pallas_call(
        functools.partial(_fused_fwd_kernel, npair),
        grid=(b // S,),
        in_specs=[
            pl.BlockSpec((F, F, npair), lambda i: (0, 0, 0)),
            pl.BlockSpec((S, F, D), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((S, npair), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, npair), jnp.float32),
    )(m3, f16)
    return acts, f16

  def bwd(f16, d_acts):
    b = f16.shape[0]
    sb = min(128, S)  # f32 scratch + padded constants: keep VMEM bounded
    d_feats = pl.pallas_call(
        _fused_bwd_kernel,
        grid=(b // sb,),
        in_specs=[
            pl.BlockSpec((F, npair, F), lambda i: (0, 0, 0)),
            pl.BlockSpec((sb, npair), lambda i: (i, 0)),
            pl.BlockSpec((sb, F, D), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((sb, F, D), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, F, D), jnp.bfloat16),
        scratch_shapes=[pltpu.VMEM((sb, F, F), jnp.float32)],
    )(m3t, d_acts, f16)
    return (d_feats.astype(jnp.float32).reshape(b, F * D),)

  acts_fn.defvjp(fwd, bwd)
  return acts_fn


def make_pallas_acts():
  m_np, _ = _tril_select_np(F, -1)
  mflat = jnp.asarray(m_np.reshape(F * F, -1), jnp.bfloat16)

  @jax.custom_vjp
  def acts_fn(flat):
    a, _ = fwd(flat)
    return a

  def fwd(flat):
    b = flat.shape[0]
    feats = flat.astype(jnp.bfloat16).reshape(b, F, D)
    inter = pallas_inter(feats)
    acts = jnp.dot(inter.reshape(b, F * F), mflat,
                   preferred_element_type=jnp.float32)
    return acts, feats

  def bwd(feats, d_acts):
    b = feats.shape[0]
    dsym = jnp.dot(d_acts.astype(jnp.bfloat16), mflat.T,
                   preferred_element_type=jnp.float32)
    d_feats = pallas_dfeats(dsym.astype(jnp.bfloat16).reshape(b, F, F),
                            feats)
    return (d_feats.astype(jnp.float32).reshape(b, F * D),)

  acts_fn.defvjp(fwd, bwd)
  return acts_fn


def _trace_device_ms(tag, step, *args, n=2):
  """Sum device-event time for n traced executions (ground truth through
  the relay; wall-clock chains degrade at length >4, docs/BENCHMARKS.md)."""
  import glob
  import gzip
  import json
  tdir = f"/tmp/interact_trace_{tag}_{int(time.time())}"
  out = step(*args)
  jax.block_until_ready(out)
  with jax.profiler.trace(tdir):
    for _ in range(n):
      out = step(*args)
    jax.block_until_ready(out)
  path = sorted(glob.glob(f"{tdir}/plugins/profile/*/*.trace.json.gz"))[-1]
  with gzip.open(path) as f:
    t = json.load(f)
  names = {}
  for e in t.get("traceEvents", []):
    if e.get("ph") == "M" and e.get("name") == "process_name":
      names[e["pid"]] = e["args"]["name"]
  dev_pids = {p for p, nm in names.items() if "TPU" in nm}
  # the top-level module execution events carry the whole-step time
  tot = 0.0
  cnt = 0
  for e in t.get("traceEvents", []):
    if (e.get("ph") == "X" and e.get("pid") in dev_pids
        and e.get("name", "").startswith("jit_")):
      tot += e.get("dur", 0.0)
      cnt += 1
  if os.environ.get("DUMP", "0") == "1":
    from collections import defaultdict
    per = defaultdict(float)
    info = {}
    for e in t.get("traceEvents", []):
      if e.get("ph") == "X" and e.get("pid") in dev_pids:
        per[e.get("name", "?")] += e.get("dur", 0.0)
        a = e.get("args") or {}
        if a.get("long_name"):
          info[e.get("name", "?")] = a["long_name"][:90]
    for nm, us in sorted(per.items(), key=lambda kv: -kv[1])[:14]:
      print(f"    {us/n/1000.0:8.3f} ms  {nm[:40]} {info.get(nm, '')}")
  return tot / max(cnt, 1) / 1000.0


def timeit(name, fn, flat):
  step = jax.jit(jax.value_and_grad(lambda x: jnp.sum(fn(x) ** 2)))
  ms = _trace_device_ms(name.split(":")[0].strip(), step, flat)
  print(f"{name:40s}: {ms:8.2f} ms fwd+bwd (device)", flush=True)
  return step(flat)


def main():
  rng = np.random.default_rng(0)
  flat = jnp.asarray(rng.standard_normal((B, F * D)) * 0.1, jnp.float32)

  base = lambda x: _tril_products(x, F, -1)
  acts_p = make_pallas_acts()

  acts_c = make_fused_acts()

  (l_a, g_a) = timeit("A: XLA matmul-form (production)", base, flat)
  (l_b, g_b) = timeit(f"B: pallas inter+dfeats (S={S})", acts_p, flat)
  (l_c, g_c) = timeit(f"C: pallas fully fused (S={S})", acts_c, flat)

  scale = float(jnp.max(jnp.abs(g_a)))
  for nm, l, g in (("B", l_b, g_b), ("C", l_c, g_c)):
    rel_l = abs(float(l_a) - float(l)) / abs(float(l_a))
    err_g = float(jnp.max(jnp.abs(g_a - g)))
    print(f"parity {nm}: loss rel {rel_l:.2e}; grad max abs err {err_g:.2e} "
          f"(grad scale {scale:.2e})")


if __name__ == "__main__":
  main()
