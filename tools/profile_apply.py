"""Isolate apply_sparse cost on the chip: chunked scan vs one-shot scatter.

Usage: python tools/profile_apply.py [apply_chunk_log2] [model] [batch]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.models import (
    SYNTHETIC_MODELS,
    SyntheticModel,
    bce_loss,
    expand_tables,
    generate_batch,
)
from distributed_embeddings_tpu.ops.packed_table import adagrad_rule
from distributed_embeddings_tpu.parallel.lookup_engine import DistributedLookup
from distributed_embeddings_tpu.training import init_sparse_state_direct

CHUNK_LOG = int(sys.argv[1]) if len(sys.argv) > 1 else 23
MODEL = sys.argv[2] if len(sys.argv) > 2 else "tiny"
BATCH = int(sys.argv[3]) if len(sys.argv) > 3 else 65536
K = 4


def main():
  cfg = SYNTHETIC_MODELS[MODEL]
  tables, tmap, hotness = expand_tables(cfg)
  model = SyntheticModel(config=cfg, world_size=1)
  plan = DistEmbeddingStrategy(tables, 1, "basic", input_table_map=tmap,
                               dense_row_threshold=model.dense_row_threshold)
  numerical, cats, labels = generate_batch(cfg, BATCH, alpha=1.05, seed=0)
  cats = [np.minimum(c, tables[t].input_dim - 1).astype(np.int32)
          for c, t in zip(cats, tmap)]
  cats = [jnp.asarray(c if h > 1 else c[:, 0])
          for c, h in zip(cats, hotness)]

  rule = adagrad_rule(0.01)
  dense_opt = optax.adagrad(0.01)
  dummy_acts = [jnp.zeros((2, tables[t].output_dim), jnp.float32)
                for t in tmap]
  small_cats = [c[:2] for c in cats]
  dense_params = model.init(jax.random.PRNGKey(0),
                            jnp.asarray(numerical[:2]), small_cats,
                            emb_acts=dummy_acts)["params"]
  state = init_sparse_state_direct(plan, rule, dense_params, dense_opt,
                                   jax.random.PRNGKey(1))
  fused = state["fused"]
  jax.block_until_ready(fused)

  engine = DistributedLookup(plan, apply_chunk=1 << CHUNK_LOG)
  layouts = engine.fused_layouts(rule)
  hotness_of = lambda i: hotness[i]  # noqa: E731

  @jax.jit
  def roundtrip(fused, cats_):
    """gather + apply, returning the updated fused params (donatable)."""
    ids_all = engine.route_ids(cats_, hotness_of)
    z, res = engine.lookup_sparse_fused(fused, layouts, ids_all)
    d_z = {bk: zb * 1e-6 for bk, zb in z.items()}
    return engine.apply_sparse(fused, layouts, d_z, res, rule,
                               jnp.zeros((), jnp.int32))

  rt = jax.jit(roundtrip, donate_argnums=(0,))
  fused = rt(fused, cats)
  probe = float(next(iter(fused.values()))[0, 0])  # force

  def run(n):
    nonlocal fused
    t0 = time.perf_counter()
    for _ in range(n):
      fused = rt(fused, cats)
    _ = float(next(iter(fused.values()))[0, 0])
    return time.perf_counter() - t0

  t1 = run(K)
  t2 = run(2 * K)
  print(f"apply_chunk=2^{CHUNK_LOG}: gather+apply roundtrip "
        f"{(t2 - t1) / K * 1e3:8.2f} ms/iter (probe {probe:.3g})")


if __name__ == "__main__":
  main()
