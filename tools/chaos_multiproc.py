"""Multi-controller chaos: REAL jax.distributed processes, dual kill.

``tools/chaos_preempt.py`` proved in-place elasticity on one
controller with a virtual mesh. This driver removes the last
simulation: the pod is TWO real ``jax.distributed`` processes (4
virtual CPU devices each, an 8-device global mesh over gloo
collectives), the fleet owners are real processes behind TCP sockets,
and the chaos kills BOTH kinds in one run:

1. **reference**: an unkilled 2-process pod trains the fixed stream at
   world 8 to completion (``--static``: membership ignored).
2. **pod cycle** (trainer-process kill): both controllers register
   pid leases; 6 lightweight member subprocesses fill the pod to 8.
   The driver SIGKILLs a member — every controller agrees on the
   shrink target through ``elastic.agreed_target_world`` (a broadcast,
   so both compare against the SAME number), posts its
   ``(step, world)`` to the **membership-change barrier**, and
   ``ResilientTrainer.resize`` regroups 8 -> 4 through the shared
   spill directory (each process publishes only the rank blocks it
   alone can address). A replacement member regrows the pod to 8.
   After two post-regrow barrier-protocol checkpoints land, the driver
   SIGKILLs trainer process 1 MID-STEP and process 0 moments later
   (stuck in the orphaned collective), then tears the newest
   checkpoint's rank-0 fused file in half. The relaunch must agree —
   via the restore-choice broadcast — on the newest VALID checkpoint
   on both controllers, resume, and finish the stream. The verdict
   checks: killed rcs are SIGKILL, relaunch rcs are 0, the torn dir
   was NOT the one resumed from, the stitched trajectory matches the
   reference (f32 bit-exact before the first resize, within the
   fp-associativity bound after), ``consumed == steps + skipped``
   holds across process lifetimes with every injected NaN batch
   skipped exactly once, and both membership barriers were counted.
3. **fleet cycle** (owner-process kill): a fully 2-way-replicated
   fleet of TWO owner subprocesses behind ``SocketTransport`` serves
   an open loop; the driver SIGKILLs owner 0 mid-gather. Acceptance:
   zero wrong answers (every completed request bitwise-matches the
   single-process engine), zero lost requests, a counted failover.
   Then the fleet scales DOWN under load: ``router.apply_fleet``
   drains the departing owner before the swap and the post-transition
   answers still bitwise-match.

``--smoke`` is the make-verify tier (fewer steps/requests, same
assertions). Verdicts via ``telemetry.emit_verdict`` (exit 0/1,
$DE_TPU_VERDICT_LOG).

Usage: python tools/chaos_multiproc.py [--smoke]
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if __name__ == "__main__":
  # the env must be set BEFORE jax imports, and differs per mode: a pod
  # controller owns 4 of the 8 global devices; the driver and the fleet
  # owners run their own single-process 8-device world; a member is
  # jax-free (a pid lease needs no devices).
  if "--pod" in sys.argv:
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ.pop("JAX_PLATFORMS", None)
  elif "--member" not in sys.argv:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
      os.environ["XLA_FLAGS"] = (
          flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
  sys.path.insert(0, _REPO)

VOCAB = [500, 300, 150, 20]
GLOBAL_BATCH = 32  # divisible by every world size the cycles use
POD_WORLDS = (4, 8)  # both split evenly across the 2 controllers
N_LIGHT_MEMBERS = 6  # 2 controllers + 6 = a full world-8 pod

FLEET = dict(sizes=[1536, 768], widths=[16, 16], hotness=[2, 1],
             req_rows=4, max_batch=32)


def _batches(n, seed=7, n_unique=6):
  """World-independent cycled batch stream (chaos_preempt's recipe)."""
  import numpy as np
  rng = np.random.default_rng(seed)
  out = []
  for _ in range(n_unique):
    numerical = rng.standard_normal((GLOBAL_BATCH, 13)).astype(np.float32)
    cats = [rng.integers(0, v, GLOBAL_BATCH).astype(np.int32)
            for v in VOCAB]
    labels = (numerical[:, 0] > 0).astype(np.float32)
    out.append((numerical, cats, labels))
  return [out[i % n_unique] for i in range(n)]


# ---------------------------------------------------------------------------
# member: a pod worker's liveness lease (NO jax import — a member is a
# process whose pid exists, nothing more; the controllers own the mesh)
# ---------------------------------------------------------------------------


def run_member(pod_dir: str, member_id: str) -> None:
  d = os.path.join(pod_dir, "members")
  os.makedirs(d, exist_ok=True)
  # lease format = elastic.register_member's, incl. the pid-incarnation
  # start time (elastic.proc_start_ticks, inlined to stay jax-free)
  try:
    with open(f"/proc/{os.getpid()}/stat", "rb") as f:
      stat = f.read()
    start = int(stat[stat.rindex(b")") + 1:].split()[19])
  except (OSError, ValueError, IndexError):
    start = None
  path = os.path.join(d, f"{member_id}.json")
  tmp = path + ".tmp"
  with open(tmp, "w") as f:
    json.dump({"id": member_id, "pid": os.getpid(), "start": start}, f)
    f.flush()
    os.fsync(f.fileno())
  os.replace(tmp, path)
  while True:  # live until killed (SIGKILL: the lease pid goes dead)
    time.sleep(1.0)


# ---------------------------------------------------------------------------
# pod: ONE controller of the 2-process trainer (--pod --proc-id {0,1})
# ---------------------------------------------------------------------------


def _put_global(x, mesh, spec):
  """Place a host array as a (possibly non-addressable) global array.

  ``jax.device_put`` onto a multi-process sharding runs an
  ``assert_equal`` broadcast per array; interleaved with sub-mesh step
  collectives those broadcasts wedge gloo. The callback constructor
  places purely locally — no cross-process traffic at all."""
  import jax
  import numpy as np
  from jax.sharding import NamedSharding
  x = np.asarray(jax.device_get(x))
  return jax.make_array_from_callback(
      x.shape, NamedSharding(mesh, spec), lambda idx, x=x: x[idx])


def _put_tree(tree, mesh, axis_name="mp"):
  import jax
  from distributed_embeddings_tpu.layers import hybrid_partition_specs
  specs = hybrid_partition_specs(tree, axis_name)
  return jax.tree_util.tree_map(
      lambda x, s: _put_global(x, mesh, s), tree, specs)


def _put_batch(batch, mesh, axis_name="mp"):
  import jax
  import numpy as np
  from jax.sharding import PartitionSpec as P

  def put(x):
    x = np.asarray(x)
    spec = P(axis_name) if x.ndim else P()
    return _put_global(x, mesh, spec)

  return jax.tree_util.tree_map(put, batch)


def _build_world(world):
  """Model/plan/step/state for one world size on the GLOBAL mesh.

  All-sparse (``dense_row_threshold=0``): the multi-controller resize
  requires dense/optimizer leaves replicated, and a dense-class
  embedding table would be mp-sharded across processes."""
  import jax
  import numpy as np
  import optax

  from jax.sharding import Mesh
  from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
  from distributed_embeddings_tpu.models import DLRM, bce_loss
  from distributed_embeddings_tpu.ops.packed_table import sparse_rule
  from distributed_embeddings_tpu.parallel.mesh import balanced_devices
  from distributed_embeddings_tpu.training import (
      init_sparse_state,
      make_sparse_train_step,
  )

  mesh = Mesh(np.array(balanced_devices(world)), ("mp",))
  model = DLRM(vocab_sizes=VOCAB, embedding_dim=16, bottom_mlp=(32, 16),
               top_mlp=(32, 1), world_size=world, dense_row_threshold=0)
  plan = DistEmbeddingStrategy(
      [dict(input_dim=v, output_dim=16,
            initializer={"name": "uniform", "scale": 0.05}) for v in VOCAB],
      world, "basic", dense_row_threshold=0)
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.adagrad(0.05)
  batches = _batches(4)
  numerical, cats, _ = batches[0]
  params = model.init(jax.random.PRNGKey(0), numerical,
                      [np.asarray(c) for c in cats])["params"]
  state = _put_tree(init_sparse_state(plan, params, rule, opt), mesh)
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                state, batches[0], donate=False, guard=True)
  return mesh, plan, rule, step, state


def run_pod(args) -> int:
  """One controller lifetime: join the 2-process world, train the fixed
  stream, resizing through the membership barrier whenever the agreed
  target world changes. Process 0 appends ``{"i", "loss"}`` JSONL per
  step to ``--log`` plus resize events and the final summary."""
  import jax
  jax.config.update("jax_platforms", "cpu")
  # real cross-process collectives on the CPU backend run over gloo
  jax.config.update("jax_cpu_collectives_implementation", "gloo")
  jax.distributed.initialize(
      coordinator_address=f"127.0.0.1:{args.port}",
      num_processes=2, process_id=args.proc_id)
  assert jax.process_count() == 2 and len(jax.devices()) == 8

  from distributed_embeddings_tpu import telemetry
  from distributed_embeddings_tpu.resilience import elastic, faultinject
  from distributed_embeddings_tpu.resilience.trainer import ResilientTrainer

  p0 = args.proc_id == 0
  me = f"p{args.proc_id}"
  steps = args.steps
  if not args.static:
    # lease FIRST: the build/restore below takes tens of seconds, and
    # the other controller's first membership scan must not see this
    # process's stale (relaunch) or missing (first launch) lease
    elastic.register_member(args.pod_dir, me)
  mesh, plan, rule, step, state = _build_world(8)
  nan_steps = set(range(args.nan_every - 1, steps, args.nan_every)) \
      if args.nan_every else set()
  stream = list(faultinject.nan_batches(_batches(steps),
                                        at_steps=nan_steps))

  root = os.path.join(args.pod_dir, "ckpts")
  t = ResilientTrainer(step, state, plan, rule, root, mesh=mesh,
                       snapshot_every=0, resume=True)
  if not args.static:
    sup = elastic.PreemptionSupervisor(args.pod_dir,
                                       allowed_worlds=POD_WORLDS)
  reg = telemetry.get_registry()

  cur = t.plan.world_size
  epoch = args.epoch_base
  events = []
  last_snap = -1
  log = open(args.log, "a") if p0 else None
  for i in range(t.consumed, steps):
    if not args.static:
      # ONE collectively-agreed target: p0's lease scan is broadcast,
      # so both controllers resize (or don't) at the same step boundary
      target = elastic.agreed_target_world(sup)
      if target != cur:
        new_mesh, new_plan, _rule, new_step, _s0 = _build_world(target)
        epoch += 1
        t.resize(new_plan, step_fn=new_step, new_mesh=new_mesh,
                 pod_dir=args.pod_dir, barrier_epoch=epoch,
                 member_id=me, n_participants=2)
        events.append({"event": "resize", "i": i, "from": cur,
                       "to": target})
        if p0:
          with open(args.log + ".events", "a") as ev:
            ev.write(json.dumps(events[-1]) + "\n")
        cur = target
    loss = t.step(*_put_batch(stream[i], t.mesh))
    if p0:
      log.write(json.dumps({"i": i, "loss": loss}) + "\n")
      log.flush()
    # barrier-protocol checkpoints, only at world 8 so the relaunch
    # (which restores before it can resize) rebuilds the same world
    if args.snapshot_every and cur == 8 and t.step_count \
        and t.step_count % args.snapshot_every == 0 \
        and t.step_count != last_snap:
      t.snapshot()
      last_snap = t.step_count
    if args.step_delay:
      time.sleep(args.step_delay)  # pace the run so chaos lands mid-run
  if p0:
    log.close()
    summary = {
        "world": cur,
        "steps": t.step_count,
        "consumed": t.consumed,
        "skipped": t.skipped_steps,
        "expected_skips": len(nan_steps),
        "invariant_ok": t.consumed == t.step_count + t.skipped_steps,
        "resumed_from": t.resumed_from,
        "resizes": reg.counter("elastic/resizes").value,
        "membership_barriers":
            reg.counter("elastic/membership_barriers").value,
        "events": events,
    }
    with open(args.log + ".summary", "w") as f:
      json.dump(summary, f)
  print("POD", args.proc_id, "OK")
  return 0


# ---------------------------------------------------------------------------
# owner: one FleetOwner process behind a TCP server (--owner)
# ---------------------------------------------------------------------------


def _fleet_plan():
  from distributed_embeddings_tpu.layers.embedding import TableConfig
  from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
  tables = [TableConfig(s, w, combiner="sum")
            for s, w in zip(FLEET["sizes"], FLEET["widths"])]
  return DistEmbeddingStrategy(tables, 2, "memory_balanced",
                               dense_row_threshold=0,
                               input_hotness=FLEET["hotness"])


def run_owner(args) -> int:
  from distributed_embeddings_tpu import telemetry
  from distributed_embeddings_tpu.fleet import FleetOwner, SocketOwnerServer

  plan = _fleet_plan()
  ranks = tuple(int(r) for r in args.ranks.split(","))
  owner = FleetOwner(args.path, plan, ranks, owner_id=args.owner_id)
  server = SocketOwnerServer(owner)
  telemetry.atomic_write_text(args.portfile,
                              f"{server.host} {server.port}")
  stop = threading.Event()
  signal.signal(signal.SIGTERM, lambda *_: stop.set())
  while not stop.is_set():
    stop.wait(0.2)
  server.close()
  return 0


# ---------------------------------------------------------------------------
# driver helpers
# ---------------------------------------------------------------------------


def _spawn(mode, *args, wait=True, env=None, outfile=None):
  cmd = [sys.executable, os.path.abspath(__file__), mode, *args]
  if env is None:
    env = dict(os.environ)
  out = open(outfile, "a") if outfile else None
  try:
    if wait:
      return subprocess.run(cmd, cwd=_REPO, env=env, stdout=out,
                            stderr=subprocess.STDOUT if out else None
                            ).returncode
    return subprocess.Popen(cmd, cwd=_REPO, env=env, stdout=out,
                            stderr=subprocess.STDOUT if out else None)
  finally:
    if out:
      out.close()


def _pod_env():
  """The controllers set their own XLA flags in --pod mode; scrub the
  driver's 8-device single-process env so it cannot leak through."""
  env = {k: v for k, v in os.environ.items()
         if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "PYTHONPATH")}
  env["PYTHONPATH"] = _REPO
  return env


def _free_port() -> int:
  import socket
  with socket.socket() as s:
    s.bind(("127.0.0.1", 0))
    return s.getsockname()[1]


def _spawn_pod(pod, log, port, steps, *, static=False, epoch_base=0,
               snapshot_every=0, step_delay=0.2, tag="a"):
  """Both controllers of one pod lifetime; stdout kept for debugging."""
  return [_spawn("--pod", "--pod-dir", pod, "--log", log,
                 "--port", str(port), "--proc-id", str(i),
                 "--steps", str(steps),
                 "--epoch-base", str(epoch_base),
                 "--snapshot-every", str(snapshot_every),
                 "--step-delay", str(step_delay),
                 *(["--static"] if static else []),
                 wait=False, env=_pod_env(),
                 outfile=os.path.join(pod, f"proc{i}.{tag}.out"))
          for i in range(2)]


def _read_log(log) -> list:
  out = []
  if os.path.exists(log):
    with open(log) as f:
      for line in f:
        rec = json.loads(line)
        out.append((rec["i"], rec["loss"]))
  return out


def _read_summary(log):
  p = log + ".summary"
  if not os.path.exists(p):
    return None
  with open(p) as f:
    return json.load(f)


def _stitch(records) -> list:
  merged = {}
  for i, loss in records:
    merged[i] = loss  # later lifetime wins (the relaunch overlap)
  return [merged[i] for i in sorted(merged)]


def _traj_close(a, b, resized_at, rtol=5e-4, atol=1e-5) -> bool:
  """Exact before the first resize, fp-associativity bound after (the
  resized mesh reduces grads/losses in a different order; the resharded
  state itself is bit-exact — tests/test_multiprocess_pod.py)."""
  import numpy as np
  if len(a) != len(b):
    return False
  for i, (x, y) in enumerate(zip(a, b)):
    if np.isnan(x) or np.isnan(y):
      if not (np.isnan(x) and np.isnan(y)):
        return False
    elif i < resized_at:
      if x != y:
        return False
    elif not np.isclose(x, y, rtol=rtol, atol=atol):
      return False
  return True


def _events_of(log) -> list:
  path = log + ".events"
  if not os.path.exists(path):
    return []
  with open(path) as f:
    return [json.loads(line) for line in f]


def _wait_for(cond, procs=(), timeout=300.0) -> bool:
  """Poll ``cond()`` until true; gives up at ``timeout`` or (after one
  final check) when any watched process already exited."""
  deadline = time.monotonic() + timeout
  while time.monotonic() < deadline:
    if cond():
      return True
    if any(p.poll() is not None for p in procs):
      return bool(cond())
    time.sleep(0.05)
  return bool(cond())


def _wait_lines(log, n, procs=(), timeout=300.0) -> int:
  _wait_for(lambda: len(_read_log(log)) >= n, procs=procs,
            timeout=timeout)
  return len(_read_log(log))


def _ckpt_names(root):
  if not os.path.isdir(root):
    return set()
  return {d for d in os.listdir(root)
          if d.startswith("ckpt_") and not d.endswith(".tmp")}


def _kill_all(procs):
  for p in procs:
    if p.poll() is None:
      p.kill()
  for p in procs:
    if p.poll() is None:
      p.wait()


# ---------------------------------------------------------------------------
# cycles
# ---------------------------------------------------------------------------


def run_reference(work, steps, result):
  pod = os.path.join(work, "ref")
  os.makedirs(pod, exist_ok=True)
  log = os.path.join(pod, "losses.jsonl")
  procs = _spawn_pod(pod, log, _free_port(), steps, static=True,
                     step_delay=0.0)
  rcs = []
  try:
    for p in procs:
      rcs.append(p.wait(timeout=600))
  finally:
    _kill_all(procs)
  summary = _read_summary(log)
  ref = _stitch(_read_log(log))
  result["cycles"]["ref"] = {
      "rcs": rcs, "summary": summary,
      "ok": rcs == [0, 0] and len(ref) == steps
            and bool(summary and summary["invariant_ok"])}
  return ref


def run_pod_cycle(work, steps, ref, result):
  """Shrink/regrow through the membership barrier, then the dual kill:
  both trainer processes SIGKILLed, the newest checkpoint torn, the
  relaunch agreeing on the newest VALID one."""
  pod = os.path.join(work, "pod")
  os.makedirs(pod, exist_ok=True)
  log = os.path.join(pod, "losses.jsonl")
  root = os.path.join(pod, "ckpts")
  members_dir = os.path.join(pod, "members")

  members = [_spawn("--member", "--pod-dir", pod, "--id", f"m{k}",
                    wait=False) for k in range(N_LIGHT_MEMBERS)]
  killed_rcs = []
  procs = []
  try:
    # all 6 light leases must exist before the controllers first scan
    # membership, or the pod would open by shrinking to 4
    _wait_for(lambda: os.path.isdir(members_dir) and sum(
        n.startswith("m") and n.endswith(".json")
        for n in os.listdir(members_dir)) >= N_LIGHT_MEMBERS,
        procs=members, timeout=60)
    port = _free_port()
    procs = _spawn_pod(pod, log, port, steps, snapshot_every=3)
    _wait_lines(log, 3, procs=procs)

    # ---- preemption: one member dies -> barrier-coordinated 8 -> 4 ----
    victim = members[0]
    victim.send_signal(signal.SIGKILL)
    killed_rcs.append(victim.wait())  # reap: the lease pid goes dead
    _wait_for(lambda: any(e["to"] == 4 for e in _events_of(log)),
              procs=procs)
    _wait_lines(log, len(_read_log(log)) + 2, procs=procs)

    # ---- replacement joins -> regrow 4 -> 8 ---------------------------
    members.append(_spawn("--member", "--pod-dir", pod, "--id", "m_r0",
                          wait=False))
    _wait_for(lambda: _events_of(log)
              and _events_of(log)[-1]["to"] == 8, procs=procs)
    at_regrow = _ckpt_names(root)
    # two fresh post-regrow checkpoints: the newest will be torn, the
    # one beneath it must already carry the post-resize counters
    _wait_for(lambda: len(_ckpt_names(root) - at_regrow) >= 2,
              procs=procs)
    fresh = sorted(_ckpt_names(root) - at_regrow,
                   key=lambda d: int(d.split("_")[1]))
    dual_kill_armed = len(fresh) >= 2 and all(
        p.poll() is None for p in procs)

    # ---- the dual kill: trainer 1 mid-step, trainer 0 mid-collective --
    procs[1].send_signal(signal.SIGKILL)
    rc1 = procs[1].wait()
    time.sleep(0.7)
    procs[0].send_signal(signal.SIGKILL)
    rc0 = procs[0].wait()

    # tear the newest checkpoint: truncate its rank-0 fused file so the
    # relaunch must broadcast-agree on the one beneath it
    names = sorted(_ckpt_names(root), key=lambda d: int(d.split("_")[1]))
    torn_dir = names[-1] if names else None
    if torn_dir:
      d = os.path.join(root, torn_dir)
      fused = sorted(n for n in os.listdir(d)
                     if n.startswith("fused_") and n.endswith("_r0.npy"))
      tf = os.path.join(d, fused[0])
      with open(tf, "r+b") as f:
        f.truncate(os.path.getsize(tf) // 2)

    # ---- relaunch: both controllers restore the newest VALID ----------
    procs = _spawn_pod(pod, log, _free_port(), steps, epoch_base=100,
                       snapshot_every=3, step_delay=0.0, tag="b")
    relaunch_rcs = [p.wait(timeout=600) for p in procs]
  finally:
    _kill_all(members)
    _kill_all(procs)

  summary = _read_summary(log)
  events = _events_of(log)
  traj = _stitch(_read_log(log))
  resized_at = events[0]["i"] if events else steps
  resumed = (summary or {}).get("resumed_from") or ""
  result["cycles"]["pod"] = {
      "member_killed_rcs": killed_rcs,
      "trainer_killed_rcs": [rc0, rc1],
      "relaunch_rcs": relaunch_rcs,
      "dual_kill_armed": dual_kill_armed,
      "events": events,
      "torn_dir": torn_dir,
      "resumed_from": resumed,
      "summary": summary,
      "trajectory_matches": _traj_close(traj, ref, resized_at),
      "ok": dual_kill_armed
            and all(k == -signal.SIGKILL for k in killed_rcs)
            and rc1 == -signal.SIGKILL and rc0 != 0
            and relaunch_rcs == [0, 0]
            and [e["to"] for e in events] == [4, 8]
            and bool(torn_dir) and bool(resumed)
            and os.path.basename(resumed) != torn_dir
            and len(traj) == steps
            and _traj_close(traj, ref, resized_at)
            and bool(summary and summary["invariant_ok"]
                     and summary["skipped"] == summary["expected_skips"]
                     and summary["resizes"] >= 2
                     and summary["membership_barriers"] >= 2)}


def run_fleet_cycle(work, n_requests, result):
  """Owner-process SIGKILL mid-gather over sockets, then a drained
  scale-down under load."""
  import numpy as np

  from distributed_embeddings_tpu import telemetry
  from distributed_embeddings_tpu.fleet import (
      FleetConfig, FleetPlan, FleetRouter, SocketTransport)
  from distributed_embeddings_tpu.parallel import create_mesh
  from distributed_embeddings_tpu.parallel.lookup_engine import PAD_ID
  from distributed_embeddings_tpu.serving import (
      MicroBatcher, Rejected, ServeEngine)
  from distributed_embeddings_tpu.serving.export import (
      export as serve_export, load as serve_load)
  from distributed_embeddings_tpu.layers.dist_model_parallel import (
      set_weights)
  from distributed_embeddings_tpu.ops.packed_table import sparse_rule
  from distributed_embeddings_tpu.training import init_sparse_state
  import jax.numpy as jnp
  import optax

  class ActsModel:
    def apply(self, variables, numerical, cats, emb_acts=None):
      del variables, numerical, cats
      return jnp.concatenate(list(emb_acts), axis=-1)

  rng = np.random.default_rng(7)
  plan = _fleet_plan()
  weights = [(rng.standard_normal((s, w)) / np.sqrt(w)).astype(np.float32)
             for s, w in zip(FLEET["sizes"], FLEET["widths"])]
  params = {"embeddings": {k: jnp.asarray(v)
                           for k, v in set_weights(plan, weights).items()}}
  rule = sparse_rule("adagrad", 0.05)
  mesh = create_mesh(2)
  from distributed_embeddings_tpu.training import shard_params
  state = shard_params(init_sparse_state(plan, params, rule,
                                         optax.sgd(0.01)), mesh)
  path = os.path.join(work, "fleet_art")
  serve_export(path, plan, rule, state, quantize="f32")
  single = ServeEngine(ActsModel(), plan,
                       serve_load(path, plan, mesh=mesh), mesh=mesh)

  def mkreq(n):
    ids = []
    for s, h in zip(FLEET["sizes"], FLEET["hotness"]):
      x = rng.integers(0, s, (n, h)).astype(np.int32)
      x[rng.random(x.shape) < 0.2] = PAD_ID
      ids.append(x)
    return rng.standard_normal((n, 4)).astype(np.float32), ids

  reqs = [mkreq(FLEET["req_rows"]) for _ in range(8)]
  wants = [np.asarray(single.predict(*r)) for r in reqs]

  def spawn_owner(owner_id, ranks, portfile):
    pf = os.path.join(work, portfile)
    p = _spawn("--owner", "--owner-id", str(owner_id), "--ranks",
               ",".join(str(r) for r in ranks), "--path", path,
               "--portfile", pf, wait=False,
               outfile=os.path.join(work, portfile + ".out"))
    deadline = time.monotonic() + 180.0
    while not os.path.isfile(pf):
      if p.poll() is not None:
        raise RuntimeError(f"owner {owner_id} exited rc={p.returncode} "
                           "before serving")
      if time.monotonic() > deadline:
        raise TimeoutError(f"owner {owner_id} never published its port")
      time.sleep(0.1)
    with open(pf) as f:
      host, port = f.read().split()
    return p, (host, int(port))

  fplan = FleetPlan.replicated(2, 2, replicas=2, hot_fraction=1.0)
  owner_procs = []
  p0, a0 = spawn_owner(0, fplan.owned_ranks(0), "owner0.port")
  owner_procs.append(p0)
  p1, a1 = spawn_owner(1, fplan.owned_ranks(1), "owner1.port")
  owner_procs.append(p1)
  cfg_f = FleetConfig(cache_fraction=0.05, staging_grps=256,
                      shard_min_phys_rows=16, revive_after_s=3600.0)
  rreg = telemetry.MetricsRegistry()
  router = FleetRouter(ActsModel(), plan, path, fplan,
                       SocketTransport({0: a0, 1: a1}), mesh=mesh,
                       config=cfg_f, telemetry=rreg)
  mb = MicroBatcher(router.dispatch, max_batch=FLEET["max_batch"],
                    max_delay_s=0.002)
  try:
    mb.submit(*reqs[0]).result(timeout=300)  # compile off the clock

    # ---- owner-process SIGKILL mid-gather over the socket transport --
    killer = threading.Timer(0.25, owner_procs[0].send_signal,
                             args=(signal.SIGKILL,))
    killer.start()
    futs, rejected = [], 0
    t = time.perf_counter()
    for i in range(n_requests):
      t += float(rng.exponential(1.0 / 150.0))
      now = time.perf_counter()
      if t > now:
        time.sleep(t - now)
      try:
        futs.append((i % len(reqs), mb.submit(*reqs[i % len(reqs)])))
      except Rejected:
        rejected += 1
    out = [(ri, f.result(timeout=300)) for ri, f in futs]
    killer.join()
    killed_rc = owner_procs[0].wait(timeout=30)
    wrong = sum(0 if np.array_equal(res, wants[ri]) else 1
                for ri, res in out)
    failovers = rreg.counter("fleet/failovers").value

    # ---- scale-down under load: drain, swap, still bit-exact ---------
    p2, a2 = spawn_owner(0, (0, 1), "owner2.port")
    owner_procs.append(p2)
    stop_pump = threading.Event()

    def pump():
      j = 0
      while not stop_pump.is_set():
        try:
          mb.submit(*reqs[j % len(reqs)]).result(timeout=60)
        except Exception:
          pass
        j += 1

    pumper = threading.Thread(target=pump, daemon=True)
    pumper.start()
    time.sleep(0.2)
    router.apply_fleet(FleetPlan.balanced(2, 1),
                       transport=SocketTransport({0: a2}))
    drained = rreg.counter("fleet/drained_gathers").value
    stop_pump.set()
    pumper.join(timeout=60)
    post_wrong = sum(
        0 if np.array_equal(np.asarray(router.predict(*reqs[k])),
                            wants[k]) else 1
        for k in range(len(reqs)))
  finally:
    mb.close()
    router.close()
    for p in owner_procs:
      if p.poll() is None:
        p.terminate()
    for p in owner_procs:
      if p.poll() is None:
        try:
          p.wait(timeout=30)
        except subprocess.TimeoutExpired:
          p.kill()
          p.wait()

  result["cycles"]["fleet"] = {
      "requests": n_requests, "wrong": wrong,
      "failed": n_requests - len(out) - rejected, "rejected": rejected,
      "failovers": failovers, "owner_killed_rc": killed_rc,
      "drained_gathers": drained, "post_scale_down_wrong": post_wrong,
      "ok": wrong == 0 and len(out) + rejected == n_requests
            and failovers >= 1 and killed_rc == -signal.SIGKILL
            and post_wrong == 0}


def run_chaos_multiproc(steps=26, n_requests=80, verbose=True) -> dict:
  work = tempfile.mkdtemp(prefix="chaos_multiproc_")
  result = {"steps": steps, "work": work, "cycles": {}}
  ref = run_reference(work, steps, result)
  if result["cycles"]["ref"]["ok"]:
    run_pod_cycle(work, steps, ref, result)
  else:
    result["cycles"]["pod"] = {"ok": False, "skipped": "reference failed"}
  run_fleet_cycle(work, n_requests, result)
  result["ok"] = all(c["ok"] for c in result["cycles"].values())
  if verbose:
    print(json.dumps(result, indent=1))
  return result


def main(argv=None) -> int:
  p = argparse.ArgumentParser(description=__doc__)
  p.add_argument("--pod", action="store_true")
  p.add_argument("--member", action="store_true")
  p.add_argument("--owner", action="store_true")
  p.add_argument("--pod-dir", default="")
  p.add_argument("--id", default="")
  p.add_argument("--log", default="")
  p.add_argument("--port", default="")
  p.add_argument("--proc-id", type=int, default=0)
  p.add_argument("--steps", type=int, default=26)
  p.add_argument("--static", action="store_true")
  p.add_argument("--step-delay", type=float, default=0.2)
  p.add_argument("--nan-every", type=int, default=6)
  p.add_argument("--epoch-base", type=int, default=0)
  p.add_argument("--snapshot-every", type=int, default=0)
  p.add_argument("--owner-id", type=int, default=0)
  p.add_argument("--ranks", default="")
  p.add_argument("--path", default="")
  p.add_argument("--portfile", default="")
  p.add_argument("--smoke", action="store_true")
  args = p.parse_args(argv)
  if args.member:
    run_member(args.pod_dir, args.id)
    return 0
  if args.pod:
    return run_pod(args)
  if args.owner:
    return run_owner(args)
  from distributed_embeddings_tpu.telemetry import emit_verdict

  steps = 22 if args.smoke else args.steps
  n_requests = 60 if args.smoke else 120
  res = run_chaos_multiproc(steps=steps, n_requests=n_requests,
                            verbose=False)
  return emit_verdict("chaos-multiproc", res)


if __name__ == "__main__":
  sys.exit(main(sys.argv[1:]))
