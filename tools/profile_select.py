"""Sub-row extraction/expansion: one-hot einsum vs VPU where-select.

The Tiny anatomy charges ~28 ms to the apply's lane expansion and ~25 ms to
the gather's sub-row extraction — both one-hot einsums over [n, rpp, stride]
that SHOULD be bandwidth-bound (~4 ms at these shapes). This measures the
einsum forms against pure where/select forms.

Usage: PYTHONPATH=/root/repo:/root/.axon_site python -u tools/profile_select.py
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from distributed_embeddings_tpu.models.synthetic import power_law_ids
from distributed_embeddings_tpu.ops.packed_table import PackedLayout

B = 65536
K_REPS = 5
LAYOUT = PackedLayout(rows=52_200_000, width=16, n_aux=1)


def _sync(x):
  leaf = jax.tree_util.tree_leaves(x)[0]
  float(jnp.asarray(leaf).ravel()[0])


def timeit(name, fn, buf, *args, donate=True, n_norm=None):
  step = jax.jit(fn, donate_argnums=(0,) if donate else ())
  carry = step(buf, *args)
  _sync(carry)

  def run(n, carry):
    t0 = time.perf_counter()
    for _ in range(n):
      carry = step(carry, *args)
    _sync(carry)
    return time.perf_counter() - t0, carry

  _, carry = run(1, carry)
  t1, carry = run(K_REPS, carry)
  t2, carry = run(2 * K_REPS, carry)
  dt = (t2 - t1) / K_REPS
  per = f"  {dt / n_norm * 1e9:6.1f} ns/elem" if n_norm else ""
  print(f"{name:52s}: {dt * 1e3:8.2f} ms{per}", flush=True)
  return carry


def main():
  rng = np.random.default_rng(0)
  ids_np = (power_law_ids(rng, B, 44, 25_000_000, 1.05).ravel()
            .astype(np.int32))
  n = ids_np.shape[0]
  rpp, stride = LAYOUT.rows_per_phys, LAYOUT.stride  # 4, 32
  grp = jnp.asarray((ids_np // rpp).astype(np.int32))
  sub = jnp.asarray((ids_np % rpp).astype(np.int32))
  delta32 = jnp.asarray(
      rng.standard_normal((n, stride)).astype(np.float32) * 1e-6)
  print(f"n={n}")

  # --- expansion [n,32] -> [n,128] ---------------------------------------
  def exp_einsum(d, s):
    oh = jax.nn.one_hot(s, rpp, dtype=d.dtype)
    return jnp.einsum("ns,nr->nrs", d, oh).reshape(-1, rpp * stride)

  def exp_where(d, s):
    # tile the 32-lane delta to 128 lanes, zero all but the sub window
    tiled = jnp.tile(d, (1, rpp))  # [n, 128]
    win = jax.lax.broadcasted_iota(jnp.int32, (1, rpp * stride), 1) // stride
    return jnp.where(win == s[:, None], tiled, 0.0)

  def run_exp(name, f):
    def step(c, d, s):
      s = s + jnp.minimum(c.astype(jnp.int32), 0)
      e = f(d, s)
      return c + jnp.tanh(jnp.sum(e)) * 0 + jnp.float32(0)
    timeit(name, step, jnp.zeros((), jnp.float32), delta32, sub,
           donate=False, n_norm=n)

  # numerics check
  a = exp_einsum(delta32[:1024], sub[:1024])
  b = exp_where(delta32[:1024], sub[:1024])
  print(f"  expand parity: {float(jnp.max(jnp.abs(a - b))):.2e}")

  # (expansion+scatter variants were measured on TPU and recorded in
  # docs/BENCHMARKS.md: einsum+scatter 22.2 ns/elem vs where+scatter
  # 25.3 — the einsum form fuses better into the scatter and was kept.)

  # --- extraction: gather + sub-row select + 10-hot combine --------------
  buf_g = jnp.zeros((LAYOUT.phys_rows + 1, 128), jnp.float32)
  ids10 = jnp.asarray(power_law_ids(rng, B, 10, 25_000_000, 1.05)
                      .astype(np.int32))
  n10 = B * 10

  def gather_extract_einsum(c, bg, idsb):
    idsb = idsb + jnp.minimum(c.astype(jnp.int32), 0)
    g = idsb // rpp
    s = idsb % rpp
    rows = jnp.take(bg, g, axis=0, mode="fill", fill_value=0)
    rows = rows[..., :rpp * stride].reshape(idsb.shape + (rpp, stride))
    oh = jax.nn.one_hot(s, rpp, dtype=rows.dtype)
    fused = jnp.einsum("...rs,...r->...s", rows, oh)
    z = jnp.sum(fused[..., :16], axis=1)
    return c + jnp.tanh(jnp.sum(z) * 1e-6) * 0 + jnp.float32(0)

  def gather_extract_where(c, bg, idsb):
    idsb = idsb + jnp.minimum(c.astype(jnp.int32), 0)
    g = idsb // rpp
    s = idsb % rpp
    rows = jnp.take(bg, g, axis=0, mode="fill", fill_value=0)  # [B,10,128]
    win = jax.lax.broadcasted_iota(
        jnp.int32, (1, 1, rpp * stride), 2) // stride
    masked = jnp.where(win == s[..., None], rows[..., :rpp * stride], 0.0)
    fused = jnp.sum(masked.reshape(idsb.shape + (rpp, stride)), axis=-2)
    z = jnp.sum(fused[..., :16], axis=1)
    return c + jnp.tanh(jnp.sum(z) * 1e-6) * 0 + jnp.float32(0)

  def gather_bagsum_where(c, bg, idsb):
    # sum phys rows over the bag FIRST (sum commutes), then window-select
    # per occurrence is unnecessary for the COMBINED result only when all
    # bag members were distinct lanes; instead select-before-sum at phys
    # width then one reshape-sum per bag:
    idsb = idsb + jnp.minimum(c.astype(jnp.int32), 0)
    g = idsb // rpp
    s = idsb % rpp
    rows = jnp.take(bg, g, axis=0, mode="fill", fill_value=0)
    win = jax.lax.broadcasted_iota(
        jnp.int32, (1, 1, rpp * stride), 2) // stride
    masked = jnp.where(win == s[..., None], rows[..., :rpp * stride], 0.0)
    bag = jnp.sum(masked, axis=1)  # [B, 128]
    z = jnp.sum(bag.reshape(B, rpp, stride)[..., :16], axis=1)
    return c + jnp.tanh(jnp.sum(z) * 1e-6) * 0 + jnp.float32(0)

  timeit("gather + extract einsum + combine (today)", gather_extract_einsum,
         jnp.zeros((), jnp.float32), buf_g, ids10, donate=False, n_norm=n10)
  timeit("gather + extract where + combine", gather_extract_where,
         jnp.zeros((), jnp.float32), buf_g, ids10, donate=False, n_norm=n10)
  timeit("gather + where-mask + bag-sum + window-sum", gather_bagsum_where,
         jnp.zeros((), jnp.float32), buf_g, ids10, donate=False, n_norm=n10)
  # 1-hot stream: extraction variants matter there too (no bag to amortize)
  ids1 = jnp.asarray(power_law_ids(rng, B * 10, 1, 25_000_000, 1.05)
                     .astype(np.int32))
  timeit("1-hot gather + extract einsum", gather_extract_einsum,
         jnp.zeros((), jnp.float32), buf_g, ids1, donate=False, n_norm=n10)
  timeit("1-hot gather + extract where", gather_extract_where,
         jnp.zeros((), jnp.float32), buf_g, ids1, donate=False, n_norm=n10)


if __name__ == "__main__":
  main()
