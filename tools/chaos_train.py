"""Chaos training run: injected faults on a CPU mesh, must skip/resume/converge.

The CI-facing proof that the resilience subsystem composes: one short
DLRM training run on the virtual CPU mesh is hit with — in one process,
deterministically —

1. **NaN batches** (an upstream feature-pipeline failure): the guarded
   step must skip each one bit-exactly and count it;
2. **a transient checkpoint-write error**: the durable save must retry
   and still publish a valid checkpoint;
3. **a kill mid-checkpoint-save** (preemption): the run dies with a
   manifest-less ``.tmp``; a fresh trainer must auto-resume from the
   last durable checkpoint;
4. after resume, the completed run's loss trajectory must be
   BIT-FOR-BIT identical to an uninterrupted reference run over the same
   stream, the skipped-step count must match the injected NaN count, and
   the post-warmup loss must have improved (the run converges despite
   the chaos).

Run directly (``make chaos``) — the verdict goes through the telemetry
layer's normalized emitter (``telemetry.emit_verdict``: one field
schema, one exit-code convention, optional JSONL log via
``$DE_TPU_VERDICT_LOG``, exit code 0/1) — or through the
``@pytest.mark.slow`` wrapper in ``tests/test_resilience.py`` with a
longer schedule.
"""

import json
import os
import sys
import tempfile

if __name__ == "__main__":  # standalone: build the virtual CPU mesh
  flags = os.environ.get("XLA_FLAGS", "")
  if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
  os.environ.setdefault("JAX_PLATFORMS", "cpu")
  sys.path.insert(0, os.path.dirname(os.path.dirname(
      os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from distributed_embeddings_tpu.layers.planner import (  # noqa: E402
    DistEmbeddingStrategy,
)
from distributed_embeddings_tpu.models import DLRM, bce_loss  # noqa: E402
from distributed_embeddings_tpu.ops.packed_table import sparse_rule  # noqa: E402
from distributed_embeddings_tpu.parallel import create_mesh  # noqa: E402
from distributed_embeddings_tpu.resilience import (  # noqa: E402
    FaultInjector,
    InjectedCrash,
    durable,
    faultinject,
)
from distributed_embeddings_tpu.resilience.trainer import (  # noqa: E402
    ResilientTrainer,
)
from distributed_embeddings_tpu.training import (  # noqa: E402
    init_sparse_state,
    make_sparse_train_step,
    shard_params,
)

WORLD = 4
VOCAB = [500, 300, 150, 20]


def _batches(n, world, seed=7, n_unique=6):
  """A cycled set of ``n_unique`` labeled batches: repetition makes the
  loss drop reliably within a short chaos run (the check is "training
  still learns through the chaos", not generalization)."""
  rng = np.random.default_rng(seed)
  b = 8 * world
  out = []
  for _ in range(n_unique):
    numerical = rng.standard_normal((b, 13)).astype(np.float32)
    cats = [rng.integers(0, v, b).astype(np.int32) for v in VOCAB]
    labels = (numerical[:, 0] > 0).astype(np.float32)
    out.append((numerical, cats, labels))
  return [out[i % n_unique] for i in range(n)]


def _traj_equal(a, b):
  """Bit-for-bit loss-trajectory equality; skipped steps' NaN losses
  compare equal to each other (NaN != NaN under ==)."""
  return len(a) == len(b) and all(
      (np.isnan(x) and np.isnan(y)) or x == y for x, y in zip(a, b))


def run_chaos(steps: int = 24, nan_every: int = 7, snapshot_every: int = 4,
              crash_at_write_event: int = 30, verbose: bool = True) -> dict:
  """Run the chaos scenario; returns a result dict with ``ok``."""
  mesh = create_mesh(WORLD)
  model = DLRM(vocab_sizes=VOCAB, embedding_dim=16, bottom_mlp=(32, 16),
               top_mlp=(32, 1), world_size=WORLD, dense_row_threshold=32)
  plan = DistEmbeddingStrategy(
      [dict(input_dim=v, output_dim=16,
            initializer={"name": "uniform", "scale": 0.05}) for v in VOCAB],
      WORLD, "basic", dense_row_threshold=32)
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.adagrad(0.05)
  batches = _batches(steps, WORLD)
  nan_steps = set(range(nan_every - 1, steps, nan_every))
  stream = list(faultinject.nan_batches(batches, at_steps=nan_steps))

  def fresh_state():
    numerical, cats, _ = batches[0]
    params = model.init(jax.random.PRNGKey(0), numerical,
                        [np.asarray(c) for c in cats])["params"]
    return shard_params(init_sparse_state(plan, params, rule, opt), mesh)

  state0 = fresh_state()
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                state0, batches[0], donate=False, guard=True)

  root_ref = tempfile.mkdtemp(prefix="chaos_ref_")
  root = tempfile.mkdtemp(prefix="chaos_")

  # ---- uninterrupted reference ------------------------------------------
  ref = ResilientTrainer(step, fresh_state(), plan, rule, root_ref,
                         mesh=mesh, snapshot_every=snapshot_every)
  losses_ref = ref.run(stream)

  # ---- chaos run: transient write fault + crash mid-save ----------------
  inj = (FaultInjector()
         .fail_first("ckpt_write", 1)            # retried by save_rotating
         .crash_after("ckpt_write", crash_at_write_event))
  t = ResilientTrainer(step, fresh_state(), plan, rule, root, mesh=mesh,
                       snapshot_every=snapshot_every)
  losses = []
  crashed = False
  from distributed_embeddings_tpu.training import shard_batch
  try:
    with faultinject.injected(inj):
      for batch in stream:
        losses.append(t.step(*shard_batch(batch, mesh)))
  except InjectedCrash:
    crashed = True
  committed_at_crash = t.step_count

  # ---- restart: fresh process stand-in, auto-resume ---------------------
  t2 = ResilientTrainer(step, fresh_state(), plan, rule, root, mesh=mesh,
                        snapshot_every=snapshot_every)
  resumed_at = t2.consumed  # checkpointed STREAM position (commits + skips)
  losses_resumed = t2.run(stream[resumed_at:]) if crashed else []
  trajectory = losses[:resumed_at] + losses_resumed

  finite_ref = [l for l in losses_ref if np.isfinite(l)]
  k = max(1, len(finite_ref) // 4)
  loss_head = float(np.mean(finite_ref[:k]))
  loss_tail = float(np.mean(finite_ref[-k:]))
  result = {
      "steps": steps,
      "crashed": crashed,
      "committed_at_crash": committed_at_crash,
      "resumed_at_batch": resumed_at,
      "resumed_from": t2.resumed_from,
      # the resumed trainer adopts the checkpoint's persisted skip count
      # and re-skips the replayed poison, so its total covers the WHOLE
      # logical run — every injected NaN batch, counted exactly once
      "skipped_total": t2.skipped_steps,
      "expected_skips": len(nan_steps),
      "final_step": t2.step_count if crashed else t.step_count,
      "trajectory_bit_exact": _traj_equal(trajectory, losses_ref),
      "loss_head_mean": loss_head,
      "loss_tail_mean": loss_tail,
      "checkpoints": [s for s, _ in durable.list_checkpoints(root)],
      # injection CONFIG, not telemetry: the first ckpt write raises a
      # TransientIOError that save_rotating must retry through — the run
      # only reaches a resumable checkpoint (checked above) if it did
      "ckpt_write_faults_injected": 1,
  }
  expected_committed = steps - len(nan_steps)
  result["ok"] = bool(
      crashed
      and result["trajectory_bit_exact"]
      and t2.skipped_steps == result["expected_skips"]
      and result["final_step"] == expected_committed
      and loss_tail < loss_head)
  if verbose:
    print(json.dumps(result, indent=1))
  return result


if __name__ == "__main__":
  from distributed_embeddings_tpu.telemetry import emit_verdict

  # the verdict record, the PASS/FAIL line, the optional JSONL log, and
  # the exit-code semantics all come from the one telemetry emitter —
  # chaos_kill.py emits through the same call, so the two cannot drift
  res = run_chaos(verbose=False)
  sys.exit(emit_verdict("chaos", res))
