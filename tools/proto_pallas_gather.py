"""Measure the Pallas batched-DMA gather bound vs XLA's gather.

VERDICT r3 asked whether a Pallas gather issuing row DMAs from the
scalar core (the A100 kernel's smem-staged batched fetch, translated)
can beat XLA's gather on the zoo's streams. This prototype measures the
per-row cost of the most favorable Pallas shape: a straight
HBM->HBM row copy pipeline, one DMA per occurrence, no extraction work,
depth-N in flight, semaphore waits amortized N at a time — an upper
bound for any DMA-per-row gather design (a real one still pays masking /
sub-row handling).

Compares against jnp.take on the same id stream (uniform and the Tiny
power-law mix).

Measured (round 4, v5e, 1M ids / 1M rows, zipf-1.2 stream; chained
dependency harness): XLA take 11.9 ns/row, this kernel 13.8 ns/row,
bit-exact parity (an earlier same-args harness read 11.7 vs 11.3; the
uniform stream's chained timings are unstable through the relay and are
not cited) — the scalar
core sustains ~one row DMA per 11 ns, the same rate XLA's gather
already streams at, so a DMA-per-row Pallas gather (however batched)
cannot deliver the 2-3x the zoo's gather share would need. The A100
kernel's ~6 ns/occ comes from 100+ parallel CTAs issuing smem-staged
fetches — there is no analogous parallel issue resource on v5e (one
scalar core; SparseCore on v4/v5p is that resource). Conclusion
recorded in docs/BENCHMARKS.md; the zoo's single-chip floor stands on
per-occurrence row-op costs, and the scaling story is sharding the
occurrence stream over the mesh.

Usage: python tools/proto_pallas_gather.py [n_ids] [rows]
"""

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 20
ROWS = int(sys.argv[2]) if len(sys.argv) > 2 else 1 << 20
W = 128
DEPTH = 128  # in-flight row DMAs


def _gather_kernel(chunk, total, ids_ref, buf, out, sem):
  c = pl.program_id(0)

  def issue(j, _):
    idx = ids_ref[j]
    g = c * chunk + j  # global position: slot reuse crosses grid steps
    slot = jnp.bitwise_and(g, DEPTH - 1)
    # wait the slot's previous copy before reusing its semaphore
    @pl.when(g >= DEPTH)
    def _():
      pltpu.make_async_copy(
          buf.at[pl.ds(0, 1), :], out.at[pl.ds(0, 1), :],
          sem.at[slot]).wait()
    pltpu.make_async_copy(
        buf.at[pl.ds(idx, 1), :], out.at[pl.ds(g, 1), :],
        sem.at[slot]).start()
    return 0

  jax.lax.fori_loop(0, chunk, issue, 0)

  nc = pl.num_programs(0)

  @pl.when(pl.program_id(0) == nc - 1)
  def _drain():
    def wait_one(s, _):
      pltpu.make_async_copy(
          buf.at[pl.ds(0, 1), :], out.at[pl.ds(0, 1), :], sem.at[s]).wait()
      return 0
    # the outstanding window spans the last min(DEPTH, total) GLOBAL
    # positions (slot reuse crosses grid steps), not just this chunk's
    jax.lax.fori_loop(0, min(DEPTH, total), wait_one, 0)


def pallas_gather(buf, ids, chunk=8192):
  n = ids.shape[0]
  chunk = min(chunk, n)
  pad = (-n) % chunk
  if pad:  # tail chunk: pad with row 0 (dropped below), never truncate
    ids = jnp.concatenate([ids, jnp.zeros((pad,), ids.dtype)])
  kernel = functools.partial(_gather_kernel, chunk, n + pad)
  out = pl.pallas_call(
      kernel,
      grid=((n + pad) // chunk,),
      in_specs=[
          pl.BlockSpec((chunk,), lambda i: (i,), memory_space=pltpu.SMEM),
          pl.BlockSpec(memory_space=pltpu.ANY),
      ],
      out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
      out_shape=jax.ShapeDtypeStruct((n + pad, W), buf.dtype),
      scratch_shapes=[pltpu.SemaphoreType.DMA((DEPTH,))],
      compiler_params=pltpu.CompilerParams(has_side_effects=True),
  )(ids, buf)
  return out[:n]


def timeit(name, fn, buf, ids):
  # chain: each call's ids depend on the previous output so no caching /
  # reordering layer can collapse repeated executions
  step = jax.jit(lambda b, i, bump: fn(b, (i + bump) % b.shape[0]))
  # warm with the SAME operand type the timed loop passes (a weak-typed
  # Python int would compile a different cache entry and the recompile
  # would land inside the first timed run)
  out = step(buf, ids, jnp.zeros((), ids.dtype))
  jax.block_until_ready(out)

  def run(k, o):
    t0 = time.perf_counter()
    for _ in range(k):
      bump = (o[0, 0] * 0).astype(ids.dtype)
      o = step(buf, ids, bump)
    jax.block_until_ready(o)
    return time.perf_counter() - t0, o

  t1, out = run(8, out)
  t2, out = run(16, out)
  ns = (t2 - t1) / 8 / N * 1e9
  print(f"{name:36s}: {ns:6.1f} ns/row", flush=True)
  return out


def main():
  rng = np.random.default_rng(0)
  buf = jnp.asarray(rng.standard_normal((ROWS, W)), jnp.float32)
  streams = {
      "uniform": rng.integers(0, ROWS, N).astype(np.int32),
      "zipf(1.2)": (rng.zipf(1.2, N) % ROWS).astype(np.int32),
  }
  for sname, ids_np in streams.items():
    ids = jnp.asarray(ids_np)
    want = timeit(f"XLA take / {sname}",
                  lambda b, i: jnp.take(b, i, axis=0), buf, ids)
    got = timeit(f"pallas DMA-per-row / {sname}", pallas_gather, buf, ids)
    err = float(jnp.max(jnp.abs(got - want)))
    print(f"  parity: max err {err:.1e}")


if __name__ == "__main__":
  main()
