"""Fleet-serving budget: exactness, failover, and latency vs fleet size.

The open-loop fleet load generator (``make fleet-bench``). Three
measurements on the 8-way CPU mesh:

1. **Exactness** (always on): fleet answers vs the single-process
   ``ServeEngine`` on identical requests — BIT-exact for f32 (including
   a tiered artifact), byte-exact for int8/fp8. The owners move the
   memory, never the arithmetic; this is the wire that proves it.

2. **Latency vs offered QPS across fleet sizes {1, 2, 4 owners}**: a
   closed-loop run finds each fleet's saturation throughput, then an
   open-loop POISSON arrival process offers fractions of it through the
   micro-batcher and reports p50/p99/p99.9 per-request latency.
   Per-process telemetry (each owner's and the router's private
   registry) rolls up through ``MetricsRegistry.merge`` — the fleet
   view the acceptance names. Acceptance: finite percentiles at every
   fleet size, and the rolled-up ``fleet/owner/gathers`` equals the sum
   of the members' own counts (the merge is exact, not approximate).

3. **Failover under load**: a fully 2-way-replicated fleet serves an
   open loop while one owner is KILLED mid-load. Acceptance: ZERO wrong
   answers (every completed request bitwise-matches the single-process
   engine), zero failed requests (the replica absorbed the rank), and
   ``fleet/failovers`` counted the event.

``--smoke`` runs a tiny-world tier wired into ``make verify`` (same
assertions, 1-2 owners, ~150 requests), timeout-guarded like the other
smoke tiers. Verdict via ``telemetry.emit_verdict`` either way; the
recorded budgets live in docs/BENCHMARKS.md ("Round 17: fleet
serving").

Usage: PYTHONPATH=/root/repo python tools/profile_fleet.py [--smoke]
"""

import argparse
import os
import shutil
import tempfile
import threading
import time

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
  os.environ["XLA_FLAGS"] = (
      flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402,F401  (device platform must initialize first)
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from distributed_embeddings_tpu import telemetry  # noqa: E402
from distributed_embeddings_tpu.fleet import (  # noqa: E402
    FleetConfig,
    FleetOwner,
    FleetPlan,
    FleetRouter,
    InProcTransport,
)
from distributed_embeddings_tpu.layers.dist_model_parallel import (  # noqa: E402
    set_weights,
)
from distributed_embeddings_tpu.layers.embedding import TableConfig  # noqa: E402
from distributed_embeddings_tpu.layers.planner import (  # noqa: E402
    DistEmbeddingStrategy,
)
from distributed_embeddings_tpu.ops.packed_table import sparse_rule  # noqa: E402
from distributed_embeddings_tpu.parallel import create_mesh  # noqa: E402
from distributed_embeddings_tpu.parallel.lookup_engine import PAD_ID  # noqa: E402
from distributed_embeddings_tpu.serving import (  # noqa: E402
    MicroBatcher,
    Rejected,
    ServeEngine,
    ServeTierConfig,
)
from distributed_embeddings_tpu.serving.export import (  # noqa: E402
    export as serve_export,
)
from distributed_embeddings_tpu.serving.export import load as serve_load  # noqa: E402
from distributed_embeddings_tpu.tiering import (  # noqa: E402
    HostTierStore,
    TieringConfig,
    TieringPlan,
    init_tiered_state_from_params,
)
from distributed_embeddings_tpu.training import (  # noqa: E402
    init_sparse_state,
    shard_params,
)


class ActsModel:
  def apply(self, variables, numerical, cats, emb_acts=None):
    del variables, numerical, cats
    return jnp.concatenate(list(emb_acts), axis=-1)


BENCH = dict(world=4, sizes=[65536, 16384, 4096], widths=[16, 16, 16],
             hotness=[4, 2, 1], req_rows=4, max_batch=64,
             n_requests=400, fleets=(1, 2, 4))
SMOKE = dict(world=2, sizes=[1536, 768], widths=[16, 16],
             hotness=[2, 1], req_rows=4, max_batch=32,
             n_requests=150, fleets=(1, 2))

FLEET_CFG = FleetConfig(cache_fraction=0.05, staging_grps=256,
                        shard_min_phys_rows=16)


def build(cfg, tiered=False, host_row_threshold=None):
  rng = np.random.default_rng(7)
  tables = [TableConfig(s, w, combiner="sum")
            for s, w in zip(cfg["sizes"], cfg["widths"])]
  kw = {}
  if tiered:
    kw["host_row_threshold"] = host_row_threshold or cfg["sizes"][-1]
  plan = DistEmbeddingStrategy(tables, cfg["world"], "memory_balanced",
                               dense_row_threshold=0,
                               input_hotness=cfg["hotness"], **kw)
  weights = [(rng.standard_normal((s, w)) / np.sqrt(w)).astype(np.float32)
             for s, w in zip(cfg["sizes"], cfg["widths"])]
  params = {"embeddings": {k: jnp.asarray(v)
                           for k, v in set_weights(plan, weights).items()}}
  rule = sparse_rule("adagrad", 0.05)
  mesh = create_mesh(cfg["world"])
  if tiered:
    tplan = TieringPlan(plan, rule, TieringConfig(cache_fraction=0.25,
                                                  staging_grps=256))
    store = HostTierStore(tplan)
    state = shard_params(
        init_tiered_state_from_params(tplan, store, rule, params,
                                      optax.sgd(0.01), mesh=mesh), mesh)
  else:
    store = None
    state = shard_params(init_sparse_state(plan, params, rule,
                                           optax.sgd(0.01)), mesh)
  return plan, rule, mesh, state, store, rng


def mkreq(rng, cfg, n):
  ids = []
  for s, h in zip(cfg["sizes"], cfg["hotness"]):
    x = rng.integers(0, s, (n, h)).astype(np.int32)
    x[rng.random(x.shape) < 0.2] = PAD_ID
    ids.append(x)
  return rng.standard_normal((n, 4)).astype(np.float32), ids


def build_fleet(path, plan, mesh, n_owners, replicas=1,
                config=FLEET_CFG):
  world = plan.world_size
  if replicas > 1:
    fplan = FleetPlan.replicated(world, n_owners, replicas=replicas,
                                 hot_fraction=1.0)
  else:
    fplan = FleetPlan.balanced(world, n_owners)
  owner_regs = {o: telemetry.MetricsRegistry()
                for o in range(n_owners)}
  owners = {o: FleetOwner(path, plan, fplan.owned_ranks(o), owner_id=o,
                          telemetry=owner_regs[o])
            for o in range(n_owners)}
  transport = InProcTransport(owners)
  router_reg = telemetry.MetricsRegistry()
  router = FleetRouter(ActsModel(), plan, path, fplan, transport,
                       mesh=mesh, config=config, telemetry=router_reg)
  return fplan, owners, owner_regs, transport, router, router_reg


def rollup(router_reg, owner_regs):
  """The fleet view: every member's private registry merged."""
  fleet = telemetry.MetricsRegistry()
  fleet.merge(router_reg)
  for reg in owner_regs.values():
    fleet.merge(reg)
  return fleet


def pcts(lats):
  if not lats:
    return float("nan"), float("nan"), float("nan")
  a = np.sort(np.asarray(lats))
  pick = lambda q: float(a[min(len(a) - 1, int(q * len(a)))])  # noqa: E731
  return pick(0.50), pick(0.99), pick(0.999)


def open_loop(mb, reqs, qps, n_requests, rng):
  """Poisson arrivals at the offered rate; returns (latencies,
  rejected, results)."""
  futs, rejected = [], 0
  t = time.perf_counter()
  for i in range(n_requests):
    t += float(rng.exponential(1.0 / qps))
    now = time.perf_counter()
    if t > now:
      time.sleep(t - now)
    numerical, ids = reqs[i % len(reqs)]
    try:
      futs.append((i % len(reqs), mb.submit(numerical, ids)))
    except Rejected:
      rejected += 1
  out, lats = [], []
  for ri, f in futs:
    out.append((ri, f.result(timeout=300)))
    lats.append(f.latency_s)
  return lats, rejected, out


# ---------------------------------------------------------------------------
# measurements
# ---------------------------------------------------------------------------


def check_exactness(cfg, tmp, result):
  """Fleet == single process, every layout."""
  oks = {}
  plan, rule, mesh, state, _store, rng = build(cfg)
  for quantize in ("f32", "int8", "fp8"):
    path = os.path.join(tmp, f"art_{quantize}")
    serve_export(path, plan, rule, state, quantize=quantize)
    single = ServeEngine(ActsModel(), plan,
                         serve_load(path, plan, mesh=mesh), mesh=mesh)
    _, owners, oregs, transport, router, rreg = build_fleet(
        path, plan, mesh, 2)
    ok = True
    for _ in range(3):
      numerical, ids = mkreq(rng, cfg, cfg["req_rows"])
      ok &= np.array_equal(single.predict(numerical, ids),
                           router.predict(numerical, ids))
    oks[quantize] = bool(ok)
    router.close()
  # tiered artifact (f32): the serve cache + cold images behind a fleet
  plan_t, rule_t, mesh_t, state_t, store_t, rng_t = build(cfg,
                                                          tiered=True)
  path = os.path.join(tmp, "art_tiered")
  serve_export(path, plan_t, rule_t, state_t, quantize="f32",
               store=store_t)
  single = ServeEngine(ActsModel(), plan_t,
                       serve_load(path, plan_t, mesh=mesh_t), mesh=mesh_t,
                       tier_config=ServeTierConfig(cache_fraction=0.25,
                                                   staging_grps=128))
  _, _, _, _, router, _ = build_fleet(path, plan_t, mesh_t, 2)
  ok = True
  for _ in range(2):
    numerical, ids = mkreq(rng_t, cfg, cfg["req_rows"])
    ok &= np.array_equal(single.predict(numerical, ids),
                         router.predict(numerical, ids))
  oks["tiered_f32"] = bool(ok)
  router.close()
  result["exact"] = oks
  print("exactness vs single-process: "
        + "  ".join(f"{k}={'OK' if v else 'FAIL'}"
                    for k, v in oks.items()))
  return all(oks.values())


def sweep_fleet_sizes(cfg, tmp, result):
  """p50/p99/p99.9 vs offered QPS across fleet sizes, telemetry rolled
  up through the registry merge."""
  plan, rule, mesh, state, _store, rng = build(cfg)
  path = os.path.join(tmp, "art_sweep")
  serve_export(path, plan, rule, state, quantize="int8")
  reqs = [mkreq(rng, cfg, cfg["req_rows"]) for _ in range(32)]
  ok = True
  rows = []
  print(f"latency vs offered QPS (req={cfg['req_rows']} rows, "
        "Poisson arrivals):")
  for n_owners in cfg["fleets"]:
    _, owners, oregs, transport, router, rreg = build_fleet(
        path, plan, mesh, n_owners)
    mb = MicroBatcher(router.dispatch, max_batch=cfg["max_batch"],
                      max_delay_s=0.002)
    mb.submit(*reqs[0]).result(timeout=300)  # compile off the clock
    # closed loop: saturation estimate
    t0 = time.perf_counter()
    n_sat = 40
    futs = [mb.submit(*reqs[i % len(reqs)]) for i in range(n_sat)]
    for f in futs:
      f.result(timeout=300)
    sat_qps = n_sat / (time.perf_counter() - t0)
    per_fleet = {"owners": n_owners, "sat_qps": sat_qps, "points": []}
    for frac in (0.5, 0.8):
      qps = max(1.0, sat_qps * frac)
      lats, rejected, _ = open_loop(mb, reqs, qps, cfg["n_requests"],
                                    rng)
      p50, p99, p999 = pcts(lats)
      ok &= bool(np.isfinite([p50, p99, p999]).all() and p99 >= p50 > 0)
      per_fleet["points"].append(
          {"frac": frac, "qps": qps, "p50": p50, "p99": p99,
           "p999": p999, "rejected": rejected})
      print(f"  owners={n_owners}  offered {frac:.0%} ({qps:7.1f} req/s)"
            f"  p50 {p50 * 1e3:7.1f}  p99 {p99 * 1e3:7.1f}  "
            f"p99.9 {p999 * 1e3:7.1f} ms  rejected {rejected}")
    mb.close()
    # the fleet roll-up: merged counters equal the members' sums
    fleet = rollup(rreg, oregs)
    want = sum(r.counter("fleet/owner/gathers").value
               for r in oregs.values())
    merged = fleet.counter("fleet/owner/gathers").value
    ok &= merged == want
    per_fleet["rollup_gathers"] = merged
    per_fleet["router_rpcs"] = rreg.counter("fleet/rpcs").value
    print(f"  owners={n_owners}  roll-up: fleet/owner/gathers {merged} "
          f"(= sum of members: {'OK' if merged == want else 'FAIL'}), "
          f"router rpcs {per_fleet['router_rpcs']}")
    router.close()
    rows.append(per_fleet)
  result["sweep"] = rows
  return ok


def check_failover_under_load(cfg, tmp, result):
  """Kill one owner of a fully replicated fleet mid-load: zero wrong
  answers, zero failed requests, counted failover — and a flight
  -recorder bundle: the failover trips the recorder, whose debug bundle
  must carry the recent request traces and the failover note."""
  import json as _json

  plan, rule, mesh, state, _store, rng = build(cfg)
  path = os.path.join(tmp, "art_failover")
  serve_export(path, plan, rule, state, quantize="f32")
  single = ServeEngine(ActsModel(), plan,
                       serve_load(path, plan, mesh=mesh), mesh=mesh)
  reqs = [mkreq(rng, cfg, cfg["req_rows"]) for _ in range(8)]
  wants = [np.asarray(single.predict(*r)) for r in reqs]
  cfg_f = FleetConfig(cache_fraction=0.05, staging_grps=256,
                      shard_min_phys_rows=16, revive_after_s=3600.0)
  _, owners, oregs, transport, router, rreg = build_fleet(
      path, plan, mesh, 2, replicas=2, config=cfg_f)
  recorder = telemetry.install_flight_recorder(
      telemetry.FlightRecorder(dir=os.path.join(tmp, "flight"),
                               capacity=128))
  mb = MicroBatcher(router.dispatch, max_batch=cfg["max_batch"],
                    max_delay_s=0.002)
  mb.submit(*reqs[0]).result(timeout=300)  # compile off the clock
  n = max(60, cfg["n_requests"] // 3)
  killer = threading.Timer(0.2, transport.kill, args=(0,))
  killer.start()
  lats, rejected, out = open_loop(mb, reqs, qps=200.0, n_requests=n,
                                  rng=rng)
  killer.join()
  mb.close()
  telemetry.uninstall_flight_recorder()
  wrong = sum(0 if np.array_equal(res, wants[ri]) else 1
              for ri, res in out)
  failovers = rreg.counter("fleet/failovers").value
  bundles = list(recorder.bundles)
  bundle_ok = note_ok = False
  if bundles:
    with open(bundles[0]) as f:
      bundle = _json.load(f)
    bundle_ok = bundle["reason"] == "failover" \
        and len(bundle["requests"]) >= 1
    note_ok = any(nt.get("kind") == "failover"
                  for r in bundle["requests"]
                  for nt in r.get("notes", []))
  result["failover"] = {"requests": n, "wrong": wrong,
                        "failed": n - len(out) - rejected,
                        "rejected": rejected, "failovers": failovers,
                        "flight_bundles": len(bundles),
                        "flight_bundle_ok": bundle_ok,
                        "flight_failover_note": note_ok}
  ok = wrong == 0 and len(out) + rejected == n and failovers >= 1 \
      and bundle_ok and note_ok
  print(f"failover under load: {n} requests, wrong={wrong}, "
        f"rejected={rejected}, failovers={failovers}, "
        f"flight bundles={len(bundles)} "
        f"{'OK' if ok else 'FAIL'}")
  router.close()
  return ok


def main(cfg, tag):
  tmp = tempfile.mkdtemp(prefix="fleet_bench_")
  result = {"config": {k: v for k, v in cfg.items()}}
  try:
    ok = check_exactness(cfg, tmp, result)
    ok = sweep_fleet_sizes(cfg, tmp, result) and ok
    ok = check_failover_under_load(cfg, tmp, result) and ok
  finally:
    shutil.rmtree(tmp, ignore_errors=True)
  result["ok"] = bool(ok)
  result["config"]["fleets"] = list(cfg["fleets"])
  return telemetry.emit_verdict(tag, result)


if __name__ == "__main__":
  ap = argparse.ArgumentParser()
  ap.add_argument("--smoke", action="store_true",
                  help="tiny-world smoke tier (wired into make verify)")
  args = ap.parse_args()
  if args.smoke:
    raise SystemExit(main(SMOKE, "fleet-smoke"))
  raise SystemExit(main(BENCH, "fleet-bench"))
