"""Time a single-chip DLRM (Criteo-shape) train step on the real TPU.

The north-star metric (BASELINE.json): Criteo DLRM step time / samples per
second per chip; reference = 9,157,869 samples/s on 8xA100 => 1,144,734
samples/s/chip (TF32), 1,302,029 (AMP).

Vocabulary is scaled to fit one 16 GB chip (f32 tables, SGD has no
per-row optimizer state); per-step indexed-row cost is vocab-size
insensitive (measured: gather/scatter cost per row is flat from 2^16 to
2^26 rows), so samples/s at scaled vocab is representative.

Usage: python tools/profile_dlrm.py [batch] [vocab_scale] [amp]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.models import DLRM, bce_loss
from distributed_embeddings_tpu.ops.packed_table import sgd_rule
from distributed_embeddings_tpu.training import (
    init_sparse_state_direct,
    make_sparse_train_step,
)

CRITEO_1TB_VOCAB = [
    39884406, 39043, 17289, 7420, 20263, 3, 7120, 1543, 63, 38532951,
    2953546, 403346, 10, 2208, 11938, 155, 4, 976, 14, 39979771,
    25641295, 39664984, 585935, 12972, 108, 36
]

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
SCALE = float(sys.argv[2]) if len(sys.argv) > 2 else 0.125
AMP = len(sys.argv) > 3 and sys.argv[3] == "amp"
K = 8


def main():
  vocab = [max(4, int(v * SCALE)) for v in CRITEO_1TB_VOCAB]
  rows = sum(vocab)
  print(f"batch={BATCH} scale={SCALE} amp={AMP} rows={rows / 1e6:.1f}M "
        f"tables_gib={rows * 128 * 4 / 2**30:.2f}")
  model = DLRM(vocab_sizes=vocab, embedding_dim=128, world_size=1,
               compute_dtype=jnp.bfloat16 if AMP else jnp.float32)
  plan = DistEmbeddingStrategy(
      [dict(input_dim=v, output_dim=128, combiner=None) for v in vocab],
      1, "basic", dense_row_threshold=model.dense_row_threshold)

  rng = np.random.default_rng(0)
  numerical = jnp.asarray(rng.standard_normal((BATCH, 13)), jnp.float32)
  cats = [jnp.asarray(rng.integers(0, v, BATCH), jnp.int32) for v in vocab]
  labels = jnp.asarray(rng.integers(0, 2, BATCH), jnp.float32)
  batch = (numerical, cats, labels)

  rule = sgd_rule(24.0)
  dense_opt = optax.sgd(24.0)
  dummy_acts = [jnp.zeros((2, 128), jnp.float32) for _ in vocab]
  small_cats = [c[:2] for c in cats]
  dense_params = model.init(jax.random.PRNGKey(0), numerical[:2], small_cats,
                            emb_acts=dummy_acts)["params"]

  state_avals = jax.eval_shape(
      lambda: init_sparse_state_direct(plan, rule, dense_params, dense_opt,
                                       jax.random.PRNGKey(1)))
  step = make_sparse_train_step(model, plan, bce_loss, dense_opt, rule,
                                None, state_avals, batch)
  compiled = step.lower(state_avals, *batch).compile()
  state = init_sparse_state_direct(plan, rule, dense_params, dense_opt,
                                   jax.random.PRNGKey(1))

  for _ in range(3):
    state, loss = compiled(state, *batch)
  float(loss)

  def run(n, state):
    t0 = time.perf_counter()
    for _ in range(n):
      state, loss = compiled(state, *batch)
    float(loss)
    return time.perf_counter() - t0, state

  t1, state = run(K, state)
  t2, state = run(2 * K, state)
  ms = (t2 - t1) / K * 1e3
  sps = BATCH / (ms / 1e3)
  base = 1302029.0 if AMP else 1144734.0
  print(f"DLRM step: {ms:.2f} ms  {sps:,.0f} samples/s/chip  "
        f"vs A100-chip {sps / base:.3f}x")


if __name__ == "__main__":
  main()
