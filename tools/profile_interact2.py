"""A/B the interaction's operand forms (fwd+bwd) on the real chip.

Variants build feats from 27 separate [B, 128] parts (the shape the model
actually has), run the product + triangle-selection + a nonlinear consumer,
and take grads w.r.t. every part — so the concat/stack build AND its
backward split are inside the measured region, like the real step.

Usage: python tools/profile_interact2.py [batch]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from distributed_embeddings_tpu.models.dlrm import _tril_select_np

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
F, D = 27, 128
K = 8


def timeit(name, fn, parts):
  step = jax.jit(fn)
  c = step(parts)
  jax.block_until_ready(c)

  def run(n):
    t0 = time.perf_counter()
    c = None
    for _ in range(n):
      c = step(parts)
    jax.block_until_ready(c)
    return time.perf_counter() - t0

  t1 = run(K)
  t2 = run(2 * K)
  print(f"{name:40s}: {(t2 - t1) / K * 1e3:8.2f} ms", flush=True)


def consume(acts):
  return jnp.sum(jnp.tanh(acts.astype(jnp.float32)))


def main():
  rng = np.random.default_rng(0)
  parts = [jnp.asarray(rng.standard_normal((BATCH, D)), jnp.float32)
           for _ in range(F)]
  m_np, p = _tril_select_np(F, -1)
  m = jnp.asarray(m_np)

  def v_concat(ps):  # current: lane concat + reshape, custom-vjp math inline
    def f(ps):
      feats = jnp.concatenate(ps, axis=1).reshape(BATCH, F, D)
      inter = jnp.einsum("bpd,bqd->bpq", feats, feats,
                         preferred_element_type=jnp.float32)
      return consume(jnp.einsum("bpq,pqn->bn", inter, m,
                                preferred_element_type=jnp.float32))
    g = jax.grad(f)(ps)
    return sum(x[0, 0] for x in g)

  def v_stack0(ps):  # [F, B, D] major-axis build
    def f(ps):
      feats = jnp.stack(ps, axis=0)
      inter = jnp.einsum("pbd,qbd->bpq", feats, feats,
                         preferred_element_type=jnp.float32)
      return consume(jnp.einsum("bpq,pqn->bn", inter, m,
                                preferred_element_type=jnp.float32))
    g = jax.grad(f)(ps)
    return sum(x[0, 0] for x in g)

  def v_stack1(ps):  # [B, F, D] via stack axis=1 (round-3 form)
    def f(ps):
      feats = jnp.stack(ps, axis=1)
      inter = jnp.einsum("bpd,bqd->bpq", feats, feats,
                         preferred_element_type=jnp.float32)
      return consume(jnp.einsum("bpq,pqn->bn", inter, m,
                                preferred_element_type=jnp.float32))
    g = jax.grad(f)(ps)
    return sum(x[0, 0] for x in g)

  def v_bf16(ps):  # concat form, bf16 operands into both einsums
    def f(ps):
      feats = jnp.concatenate(ps, axis=1).reshape(BATCH, F, D)
      fb = feats.astype(jnp.bfloat16)
      inter = jnp.einsum("bpd,bqd->bpq", fb, fb,
                         preferred_element_type=jnp.float32)
      return consume(jnp.einsum("bpq,pqn->bn", inter.astype(jnp.bfloat16),
                                m.astype(jnp.bfloat16),
                                preferred_element_type=jnp.float32))
    g = jax.grad(f)(ps)
    return sum(x[0, 0] for x in g)

  timeit("concat axis1 + reshape (current)", v_concat, parts)
  timeit("stack axis0 [F,B,D]", v_stack0, parts)
  timeit("stack axis1 [B,F,D] (round-3 build)", v_stack1, parts)
  timeit("concat + bf16 operands", v_bf16, parts)


if __name__ == "__main__":
  main()
