"""Shared microbenchmark harness for the tools/ profilers.

Sync discipline (load-bearing): under the axon TPU tunnel,
``jax.block_until_ready`` can return before queued work drains (observed:
0.08 ms "sync", then an 85 s fetch). The only reliable sync is FETCHING a
scalar, so every timing here ends with a host fetch of one element.

Timing: chained steps at two chain lengths, differenced, so dispatch/RTT
overheads cancel. With ``donate=True`` the first positional argument is
donated and the chain carries its successor.  Every differenced
measurement is also recorded into the process-wide telemetry registry
(histogram ``bench/<name>``), so the profilers share one metrics
surface with the rest of the stack instead of each keeping private
floats.
"""

import time

import jax
import jax.numpy as jnp

from distributed_embeddings_tpu.telemetry import get_registry


def sync(x):
  """Reliable device sync: fetch one scalar (see module docstring)."""
  leaf = jax.tree_util.tree_leaves(x)[0]
  float(jnp.asarray(leaf).ravel()[0])


def timeit(name, fn, first, *args, donate=True, n_norm=None, reps=5):
  """Time ``fn(first, *args)`` chained; print ms (and ns/elem). Returns the
  final carry (with donation the input is consumed — keep the carry)."""
  step = jax.jit(fn, donate_argnums=(0,) if donate else ())
  carry = step(first, *args)
  sync(carry)

  def run(n, carry):
    t0 = time.perf_counter()
    for _ in range(n):
      carry = step(carry, *args)
    sync(carry)
    return time.perf_counter() - t0, carry

  _, carry = run(1, carry)
  t1, carry = run(reps, carry)
  t2, carry = run(2 * reps, carry)
  dt = (t2 - t1) / reps
  get_registry().histogram(f"bench/{name}").observe(dt)
  per = f"  {dt / n_norm * 1e9:6.1f} ns/elem" if n_norm else ""
  print(f"{name:56s}: {dt * 1e3:8.2f} ms{per}", flush=True)
  return carry


def parse_device_trace(tdir):
  """Parse a jax.profiler trace dir into per-op aggregates.

  Returns ``(tot_us_by_name, cnt_by_name, args_of, by_src_us,
  total_jit_us)`` over the TPU device pids. Shared by bench.py's budget
  pin and the tools/ trace scripts — the profile path layout and the
  process_name/'source' conventions are XLA-version-dependent and must
  be fixed in ONE place when they shift.
  """
  import glob
  import gzip
  import json
  from collections import defaultdict

  path = sorted(glob.glob(f"{tdir}/plugins/profile/*/*.trace.json.gz"))[-1]
  with gzip.open(path) as f:
    t = json.load(f)
  names = {}
  for e in t.get("traceEvents", []):
    if e.get("ph") == "M" and e.get("name") == "process_name":
      names[e["pid"]] = e["args"]["name"]
  dev_pids = {p for p, n in names.items() if "TPU" in n}
  tot = defaultdict(float)
  cnt = defaultdict(int)
  args_of = {}
  by_src = defaultdict(float)
  total_jit = 0.0
  for e in t.get("traceEvents", []):
    if e.get("ph") != "X" or e.get("pid") not in dev_pids:
      continue
    nm = e.get("name", "?")
    dur = e.get("dur", 0.0)
    tot[nm] += dur
    cnt[nm] += 1
    a = e.get("args")
    if a:
      args_of[nm] = a
      src = a.get("source", "")
      if src:
        by_src[src] += dur
    if nm.startswith("jit_"):
      total_jit += dur
  return tot, cnt, args_of, by_src, total_jit
