"""Shared microbenchmark harness for the tools/ profilers.

Sync discipline (load-bearing): under the axon TPU tunnel,
``jax.block_until_ready`` can return before queued work drains (observed:
0.08 ms "sync", then an 85 s fetch). The only reliable sync is FETCHING a
scalar, so every timing here ends with a host fetch of one element.

Timing: chained steps at two chain lengths, differenced, so dispatch/RTT
overheads cancel. With ``donate=True`` the first positional argument is
donated and the chain carries its successor.
"""

import time

import jax
import jax.numpy as jnp


def sync(x):
  """Reliable device sync: fetch one scalar (see module docstring)."""
  leaf = jax.tree_util.tree_leaves(x)[0]
  float(jnp.asarray(leaf).ravel()[0])


def timeit(name, fn, first, *args, donate=True, n_norm=None, reps=5):
  """Time ``fn(first, *args)`` chained; print ms (and ns/elem). Returns the
  final carry (with donation the input is consumed — keep the carry)."""
  step = jax.jit(fn, donate_argnums=(0,) if donate else ())
  carry = step(first, *args)
  sync(carry)

  def run(n, carry):
    t0 = time.perf_counter()
    for _ in range(n):
      carry = step(carry, *args)
    sync(carry)
    return time.perf_counter() - t0, carry

  _, carry = run(1, carry)
  t1, carry = run(reps, carry)
  t2, carry = run(2 * reps, carry)
  dt = (t2 - t1) / reps
  per = f"  {dt / n_norm * 1e9:6.1f} ns/elem" if n_norm else ""
  print(f"{name:56s}: {dt * 1e3:8.2f} ms{per}", flush=True)
  return carry
