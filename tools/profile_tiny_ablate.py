"""Ground-truth Tiny step attribution by ablating the REAL train step.

Synthetic decompositions (profile_tiny_parts/buckets) have not matched the
end-to-end step: isolated micro-costs fuse differently in context. This
tool times the real fused train step with pieces surgically removed:

  full          : the real step (baseline, ~matches bench_synthetic)
  no_apply      : apply_sparse skipped (fused returned unchanged)
  no_model      : loss = mean(z_sparse) directly (no dense path/MLP/interact)
  no_gather     : z_sparse/residual aux replaced by zeros (routing + apply
                  with dummy deltas; gather cost removed)

Usage: PYTHONPATH=/root/repo:/root/.axon_site python -u tools/profile_tiny_ablate.py [model] [batch]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.models import (
    SYNTHETIC_MODELS,
    SyntheticModel,
    bce_loss,
    expand_tables,
    generate_batch,
)
from distributed_embeddings_tpu.ops.packed_table import adagrad_rule
from distributed_embeddings_tpu.parallel.lookup_engine import DistributedLookup
from distributed_embeddings_tpu.training import init_sparse_state_direct

MODEL = sys.argv[1] if len(sys.argv) > 1 else "tiny"
BATCH = int(sys.argv[2]) if len(sys.argv) > 2 else 65536
K = 5


def main():
  cfg = SYNTHETIC_MODELS[MODEL]
  tables, tmap, hotness = expand_tables(cfg)
  model = SyntheticModel(config=cfg, world_size=1)
  plan = DistEmbeddingStrategy(tables, 1, "basic", input_table_map=tmap,
                               dense_row_threshold=model.dense_row_threshold,
                               input_hotness=hotness, batch_hint=BATCH)
  engine = DistributedLookup(plan)
  rule = adagrad_rule(0.01)
  layouts = engine.fused_layouts(rule)
  numerical, cats_np, labels = generate_batch(cfg, BATCH, alpha=1.05, seed=0)
  cats_np = [np.minimum(c, tables[t].input_dim - 1).astype(np.int32)
             for c, t in zip(cats_np, tmap)]
  cats = [jnp.asarray(c if h > 1 else c[:, 0])
          for c, h in zip(cats_np, hotness)]
  hotness_of = lambda i: hotness[i]  # noqa: E731
  numerical = jnp.asarray(numerical)
  labels = jnp.asarray(labels)

  dummy_acts = [jnp.zeros((2, tables[t].output_dim), jnp.float32)
                for t in tmap]
  dense_params = model.init(jax.random.PRNGKey(0), numerical[:2],
                            [c[:2] for c in cats], emb_acts=dummy_acts
                            )["params"]
  state = init_sparse_state_direct(plan, rule, dense_params,
                                   optax.adagrad(0.01), jax.random.PRNGKey(1))
  state = {"dense": state["dense"], "emb_dense": state["emb_dense"],
           "fused": state["fused"], "step": jnp.zeros((), jnp.int32)}
  first_fused = sorted(state["fused"])[0]
  float(state["fused"][first_fused][0, 0])

  def make_step(kind):
    def local(st, num, cats_, labels_):
      b = num.shape[0]
      ids_all = engine.route_ids(cats_, hotness_of)
      z_sparse, residuals = engine.lookup_sparse_fused(
          st["fused"], layouts, ids_all)
      if kind == "no_gather":
        z_sparse = {k: jnp.zeros_like(v) for k, v in z_sparse.items()}
        residuals.aux_rows = {k: jnp.zeros_like(v)
                              for k, v in residuals.aux_rows.items()}

      if kind == "no_model":
        def loss_with(z_sp):
          return sum(jnp.sum(jnp.tanh(zb * 1e-3)) for zb in z_sp.values()) \
              / (b * 1000.0)
        loss, d_z = jax.value_and_grad(loss_with)(z_sparse)
        dense, emb_dense = st["dense"], st["emb_dense"]
      else:
        def loss_with(dense_p, emb_dense, z_sp):
          acts = engine.finish_forward(z_sp, emb_dense, ids_all, b,
                                       hotness_of)
          logits = model.apply({"params": dense_p}, num, cats_,
                               emb_acts=acts)
          return bce_loss(logits, labels_)

        loss, (d_dense, d_emb_dense, d_z) = jax.value_and_grad(
            loss_with, argnums=(0, 1, 2))(st["dense"], st["emb_dense"],
                                          z_sparse)
        dense = jax.tree_util.tree_map(lambda p, g: p - 0.01 * g,
                                       st["dense"], d_dense)
        emb_dense = jax.tree_util.tree_map(lambda p, g: p - 0.01 * g,
                                           st["emb_dense"], d_emb_dense)

      if kind == "no_apply":
        fused = {k: v + 0.0 for k, v in st["fused"].items()}
      else:
        fused = engine.apply_sparse(st["fused"], layouts, d_z, residuals,
                                    rule, st["step"])
      return ({"dense": dense, "emb_dense": emb_dense, "fused": fused,
               "step": st["step"] + 1}, loss)

    return jax.jit(local, donate_argnums=(0,))

  results = {}
  for kind in ("full", "no_apply", "no_model", "no_gather", "full2"):
    step = make_step(kind if kind != "full2" else "full")
    st, loss = step(state, numerical, cats, labels)
    float(st["fused"][first_fused][0, 0])
    state = st

    def run(n, st):
      t0 = time.perf_counter()
      for _ in range(n):
        st, _ = step(st, numerical, cats, labels)
      float(st["fused"][first_fused][0, 0])
      return time.perf_counter() - t0, st

    _, state = run(1, state)
    t1, state = run(K, state)
    t2, state = run(2 * K, state)
    dt = (t2 - t1) / K
    results[kind] = dt
    print(f"{kind:12s}: {dt * 1e3:8.2f} ms/step", flush=True)

  full = (results["full"] + results["full2"]) / 2
  for kind in ("no_apply", "no_model", "no_gather"):
    print(f"  {kind[3:]:8s} contributes ~{(full - results[kind]) * 1e3:7.2f} ms")


if __name__ == "__main__":
  main()
