"""Microbenchmark: indexed row ops on the real TPU chip.

Measures rows/s for the primitives that bound the sparse embedding path
(SURVEY §6 / bench.py): XLA gather (`jnp.take`), XLA scatter-add
(`.at[].add`), and a Pallas row-DMA gather with a D-deep in-flight window.

Timing through the axon tunnel: dispatch is async and block_until_ready
does not force remote completion, so each measurement chains K iterations
inside one jit (data-dependent carry) and fetches a scalar; the separately
measured fetch RTT is subtracted.

Usage: python tools/microbench_rowops.py [n_ids] [rows] [width]
"""

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N_IDS = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 22
ROWS = int(sys.argv[2]) if len(sys.argv) > 2 else 1 << 22
WIDTH = int(sys.argv[3]) if len(sys.argv) > 3 else 128
K = 8  # chained iterations per measurement


def fetch_rtt():
  probe = jax.jit(lambda x: x + 1)(jnp.zeros(()))
  float(probe)  # force compile + first fetch
  t0 = time.perf_counter()
  for _ in range(4):
    float(jax.jit(lambda x: x + 2)(probe))
  return (time.perf_counter() - t0) / 4


def timed(make_chain, *args, rtt=0.0):
  """make_chain(*args) -> jit fn running K data-dependent iterations and
  returning a scalar. Returns seconds per iteration."""
  fn = make_chain(*args)
  float(fn(*args))  # compile + warm
  t0 = time.perf_counter()
  float(fn(*args))
  return (time.perf_counter() - t0 - rtt) / K


def chain_gather(gather):
  """Chain K gathers with a data-dependent id perturbation (defeats CSE)."""

  def make(table, ids):
    @jax.jit
    def run(table, ids):
      def body(carry, k):
        acc, ids = carry
        out = gather(table, ids)
        # fold a cheap data dependency into the next iteration's ids
        bump = (out[0, 0] > jnp.inf).astype(jnp.int32)  # always 0, data-dep
        return (acc + out[0, 0], ids + bump), None

      (acc, _), _ = jax.lax.scan(body, (jnp.zeros((), table.dtype), ids),
                                 jnp.arange(K))
      return acc

    return run

  return make


def chain_scatter():
  def make(table, ids, deltas):
    @jax.jit
    def run(table, ids, deltas):
      def body(t, k):
        return t.at[ids].add(deltas, mode="drop"), None

      t, _ = jax.lax.scan(body, table, jnp.arange(K))
      return t[0, 0]

    return run

  return make


def pallas_gather(table, ids, tile=512, depth=8):
  n = ids.shape[0]
  w = table.shape[1]

  def kernel(ids_ref, table_ref, out_ref, sem):
    i = pl.program_id(0)

    def dma(j):
      idx = ids_ref[i * tile + j]
      return pltpu.make_async_copy(
          table_ref.at[pl.ds(idx, 1), :],
          out_ref.at[pl.ds(j, 1), :],
          sem.at[j % depth])

    for j in range(depth):
      dma(j).start()

    def body(j, _):
      dma(j).wait()

      @pl.when(j + depth < tile)
      def _():
        dma(j + depth).start()

      return 0

    jax.lax.fori_loop(0, tile, body, 0)

  return pl.pallas_call(
      kernel,
      grid_spec=pltpu.PrefetchScalarGridSpec(
          num_scalar_prefetch=1,
          grid=(n // tile,),
          in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
          out_specs=pl.BlockSpec((tile, w), lambda i, ids: (i, 0)),
          scratch_shapes=[pltpu.SemaphoreType.DMA((depth,))],
      ),
      out_shape=jax.ShapeDtypeStruct((n, w), table.dtype),
  )(ids, table)


def report(name, dt):
  print(f"{name:20s}: {dt * 1e3:8.2f} ms  {dt / N_IDS * 1e9:6.2f} ns/row  "
        f"{N_IDS * WIDTH * 4 / dt / 1e9:6.0f} GB/s")


def main():
  dev = jax.devices()[0]
  print(f"device: {dev.device_kind} ({dev.platform}), n_ids={N_IDS} "
        f"rows={ROWS} width={WIDTH}")
  rtt = fetch_rtt()
  print(f"fetch RTT: {rtt * 1e3:.1f} ms")
  table = jax.random.normal(jax.random.PRNGKey(0), (ROWS, WIDTH), jnp.float32)
  ids = jax.random.randint(jax.random.PRNGKey(1), (N_IDS,), 0, ROWS,
                           jnp.int32)
  deltas = jax.random.normal(jax.random.PRNGKey(2), (N_IDS, WIDTH),
                             jnp.float32)

  # HBM bandwidth reference: chained whole-table scale
  @jax.jit
  def copy_chain(t):
    def body(t, _):
      return t * 1.0000001, None
    t, _ = jax.lax.scan(body, t, jnp.arange(K))
    return t[0, 0]

  float(copy_chain(table))
  t0 = time.perf_counter()
  float(copy_chain(table))
  dt = (time.perf_counter() - t0 - rtt) / K
  print(f"copy {ROWS}x{WIDTH}: {dt * 1e3:.2f} ms/iter -> "
        f"{2 * ROWS * WIDTH * 4 / dt / 1e9:.0f} GB/s (r+w)")

  take = lambda t, i: jnp.take(t, i, axis=0, mode="fill", fill_value=0)
  report("jnp.take", timed(chain_gather(take), table, ids, rtt=rtt))
  report(".at[].add", timed(chain_scatter(), table, ids, deltas, rtt=rtt))

  for tile, depth in [(512, 8), (512, 16), (1024, 16), (1024, 32),
                      (2048, 32)]:
    g = functools.partial(pallas_gather, tile=tile, depth=depth)
    try:
      dt = timed(chain_gather(g), table, ids, rtt=rtt)
    except Exception as e:  # noqa: BLE001
      print(f"pallas t{tile} d{depth}: FAILED {type(e).__name__}: "
            f"{str(e)[:160]}")
      continue
    report(f"pallas t{tile} d{depth}", dt)

  got = np.asarray(pallas_gather(table, ids[:1 << 16]))
  want = np.asarray(jnp.take(table, ids[:1 << 16], axis=0))
  print("pallas gather correct:", np.array_equal(got, want))


if __name__ == "__main__":
  main()
