"""XLA scatter-add regime matrix: ns/row vs (buffer size x id-stream mix).

Decides the planner's generation-assignment policy: which combinations of
buffer size and power-law id mix keep the backward scatter in its fast
regime.

Usage: PYTHONPATH=/root/repo:/root/.axon_site python -u tools/profile_scatter_regimes.py
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from distributed_embeddings_tpu.models.synthetic import power_law_ids

B = 65536
K_REPS = 5


def _sync(x):
  float(jnp.asarray(x).ravel()[0])


def timeit(name, buf, ids, upd, n):
  step = jax.jit(lambda b, g, u: b.at[g].add(u, mode="drop"),
                 donate_argnums=(0,))
  carry = step(buf, ids, upd)
  _sync(carry)

  def run(k, carry):
    t0 = time.perf_counter()
    for _ in range(k):
      carry = step(carry, ids, upd)
    _sync(carry)
    return time.perf_counter() - t0, carry

  _, carry = run(1, carry)
  t1, carry = run(K_REPS, carry)
  t2, carry = run(2 * K_REPS, carry)
  dt = (t2 - t1) / K_REPS
  print(f"{name:58s}: {dt * 1e3:8.2f} ms  {dt / n * 1e9:6.1f} ns/row",
        flush=True)
  del carry


def main():
  rng = np.random.default_rng(0)

  def stream_1hot(n_tables, vocab, rows_total):
    """n_tables 1-hot inputs, tables laid side by side (phys ids)."""
    parts = []
    step_off = rows_total // max(n_tables, 1)
    for t in range(n_tables):
      ids = power_law_ids(rng, B, 1, vocab, 1.05).ravel() // 4
      parts.append(ids + t * step_off)
    return np.concatenate(parts).astype(np.int32)

  def stream_10hot(vocab, off):
    # id + offset <= sum of profiled vocabs, < 2^31 at bench scale
    return (power_law_ids(rng, B, 10, vocab, 1.05)  # graftlint: disable=GL106
            .ravel() // 4 + off).astype(np.int32)

  cases = []
  for phys_rows, label in ((1_000_000, "0.5GB"), (4_150_000, "2.1GB"),
                           (8_300_000, "4.2GB")):
    rt = phys_rows  # phys rows
    # 9 x 1-hot over 1M-vocab tables (the slow fusion.8 stream shape)
    s = stream_1hot(9, 1_000_000, rt * 4)
    cases.append((f"9x1hot 1M-vocab -> {label}", phys_rows, s))
    # 1-hot over a vocab as big as the buffer
    s = stream_1hot(1, rt * 4, rt * 4)
    cases.append((f"1x1hot full-vocab -> {label}", phys_rows, s))
    # 10-hot heavy dup
    s = stream_10hot(min(25_000_000, rt * 4), 0)
    cases.append((f"1x10hot 25M-vocab -> {label}", phys_rows, s))
    # mixed: 9x1hot + 10hot
    s = np.concatenate([stream_1hot(9, 1_000_000, rt * 4),
                        stream_10hot(min(25_000_000, rt * 4), 0)])
    cases.append((f"9x1hot + 10hot mixed -> {label}", phys_rows, s))

  for name, phys_rows, ids_np in cases:
    n = ids_np.shape[0]
    ids = jnp.asarray(np.clip(ids_np, 0, phys_rows - 1))
    upd = jnp.asarray(rng.standard_normal((n, 128)).astype(np.float32) * 1e-6)
    buf = jnp.zeros((phys_rows, 128), jnp.float32)
    timeit(f"{name} (n={n})", buf, ids, upd, n)
    del ids, upd, buf


if __name__ == "__main__":
  main()
