"""Exchange-path budget: wire bytes + step time across the wire knobs.

Measures the dp<->mp exchange traffic of the fused sparse train step on
the power-law synthetic workload, across the 2x2 of the round-6 plan
knobs ``wire_dtype`` x ``dedup_exchange``:

- **exchanged bytes / device-step**: summed from the traced jaxpr — every
  ``all_to_all`` / ``ppermute`` equation's payload size (the per-device
  block inside ``shard_map``), forward AND the autodiff-inserted reverse
  exchange. Static-shape accounting, so these are the bytes actually on
  the wire (the dedup'd path's win is its static unique capacity
  ``K = min(occurrences, rows + 1)`` per destination block — power-law
  duplication is what makes the vocab bound bite).
- **step time**: wall clock over compiled steps on the CPU mesh. CPU-mesh
  collectives are memcpys, so the BYTES column is the transferable
  result; the time column mostly prices the dedup sort and the smaller
  gather (real-TPU ICI time is a ROADMAP follow-on).

The workload: 8 tables of 1024 rows x width 32, hotness 8, zipf(1.05)
ids, global batch 16384 over an 8-way mesh — per destination block
131072 routed occurrences against a 1025-entry unique capacity, the
"same hot ids exchanged thousands of times" regime of Criteo-style
inputs (PAPERS.md, Dissecting Embedding Bag Performance).

``--overlap`` sweeps the round-7 knobs instead: ``overlap`` x
``wire_dtype`` (f32/bf16/fp8) x ``exchange_chunks`` (``--chunks``), all
with the dedup'd routing on (the production configuration since the
round-6 budget), reporting wire bytes, collective ROUND counts
(monolithic: all_to_alls; pipelined: ``(world-1) * chunks`` ppermutes
per exchange) and step time per mode. Acceptance: each pipelined
bf16/fp8 mode (best over the chunk sweep) steps at most as slow as THE
monolithic mode (f32, overlap off — the pre-round-7 exchange) on this
CPU-mesh proxy; the per-dtype monolithic comparison is printed
alongside (there is no compute/comm overlap to win on a memcpy mesh —
the real overlap win needs the ROADMAP's multichip run).

``--overlap-occupancy`` prices the round-20 fused schedule instead:
``overlap='fused'`` moves each round's row gather INSIDE the round body
(just-in-time before that round's send) so the TPU kernel's double
buffer can hide it under the previous chunk's DMA flight. Three
configurations (pipelined f32 — the round-7 schedule with its
monolithic pre-gather — fused f32, fused fp8, all dedup'd) are measured
for step wall, per-round wall (step / traced ppermute rounds) and wire
bytes, plus the schedule's **gather-hidden fraction**: of the
``world x chunks`` chunk-gathers each float exchange issues, the ones
with a prior send eligible to be in flight — everything except the
self-round's chunks and the first sending chunk. On this CPU-mesh
proxy the rounds are memcpys, so the fraction is SCHEDULE ACCOUNTING
(the upper bound the kernel's double buffer realizes on real ICI), not
measured concurrency — same honest-labeling stance as the round-7
sweep. Acceptance: fused f32 steps at most as slow as pipelined f32
(the gathers moved, none were added) with losses bit-exact, and the
hidden fraction >= 50%. ``--smoke`` shrinks the workload and gates on
machinery + parity + the accounting only (CPU step times at toy scale
are noise); it rides ``make verify`` as the exchange-smoke tier, with
verdicts through ``telemetry.emit_verdict``.

The recorded budgets live in docs/BENCHMARKS.md ("Round 6: the
compressed exchange", "Round 7: the overlapped exchange", "Round 23:
the fused exchange").

Usage: PYTHONPATH=/root/repo python tools/profile_exchange.py \
    [--overlap | --overlap-occupancy [--smoke]]
"""

import argparse
import os
import time

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
  os.environ["XLA_FLAGS"] = (
      flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from distributed_embeddings_tpu.analysis.jaxpr_audit import (  # noqa: E402
    walk_eqns,
)
from distributed_embeddings_tpu.layers.embedding import TableConfig  # noqa: E402
from distributed_embeddings_tpu.layers.planner import (  # noqa: E402
    DistEmbeddingStrategy,
)
from distributed_embeddings_tpu.models import bce_loss  # noqa: E402
from distributed_embeddings_tpu.models.synthetic import (  # noqa: E402
    EmbeddingGroup,
    SyntheticModel,
    SyntheticModelConfig,
    expand_tables,
    generate_batch,
)
from distributed_embeddings_tpu.ops.packed_table import sparse_rule  # noqa: E402
from distributed_embeddings_tpu.parallel import create_mesh  # noqa: E402
from distributed_embeddings_tpu.training import (  # noqa: E402
    init_sparse_state_direct,
    make_sparse_train_step,
    shard_batch,
    shard_params,
)

WORLD = 8
GLOBAL_BATCH = 16384
ALPHA = 1.05
STEPS = 3

CFG = SyntheticModelConfig(
    name="exchange-powerlaw",
    embedding_groups=(EmbeddingGroup(8, (8,), 1024, 32, False),),
    mlp_sizes=(64, 32), num_numerical_features=8, interact_stride=None)


def wire_stats(jaxpr):
  """Per-device wire accounting of one step: ``(bytes, a2a_rounds,
  ppermute_rounds)`` summed over all_to_all AND ppermute payloads."""
  total, n_a2a, n_pp = 0, 0, 0
  for eqn in walk_eqns(jaxpr):
    if eqn.primitive.name in ("all_to_all", "ppermute"):
      aval = eqn.invars[0].aval
      total += int(np.prod(aval.shape)) * aval.dtype.itemsize
      if eqn.primitive.name == "all_to_all":
        n_a2a += 1
      else:
        n_pp += 1
  return total, n_a2a, n_pp


def a2a_bytes(jaxpr) -> int:
  """Per-device wire bytes of one step (all collective payloads)."""
  return wire_stats(jaxpr)[0]


def build(mesh, wire_dtype, dedup, overlap="none", chunks=1, cfg=None,
          batch_size=None):
  cfg = cfg or CFG
  batch_size = batch_size or GLOBAL_BATCH
  tables, tmap, hotness = expand_tables(cfg)
  model = SyntheticModel(cfg)
  plan = DistEmbeddingStrategy(
      tables, WORLD, "memory_balanced", input_table_map=tmap,
      input_hotness=hotness, batch_hint=batch_size,
      wire_dtype=wire_dtype, dedup_exchange=dedup,
      overlap=overlap, exchange_chunks=chunks)
  rule = sparse_rule("sgd", 0.01)
  opt = optax.sgd(0.01)
  numerical, cats, labels = generate_batch(cfg, batch_size, alpha=ALPHA,
                                           seed=3)
  cats = [jnp.asarray(np.minimum(c, tables[t].input_dim - 1))
          for c, t in zip(cats, tmap)]
  batch = (jnp.asarray(numerical), cats, jnp.asarray(labels))
  dummy = [jnp.zeros((2, tables[t].output_dim), jnp.float32) for t in tmap]
  dense_params = model.init(jax.random.PRNGKey(0), batch[0][:2],
                            [c[:2] for c in cats], emb_acts=dummy)["params"]
  state = shard_params(
      init_sparse_state_direct(plan, rule, dense_params, opt,
                               jax.random.PRNGKey(1)), mesh)
  bt = shard_batch(batch, mesh)
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                state, batch, donate=False)
  return step, state, bt


def measure(mesh, wire_dtype, dedup, overlap="none", chunks=1, cfg=None,
            batch_size=None):
  step, state, bt = build(mesh, wire_dtype, dedup, overlap, chunks, cfg,
                          batch_size)
  nbytes, n_a2a, n_pp = wire_stats(jax.make_jaxpr(step)(state, *bt).jaxpr)
  state2, loss = step(state, *bt)  # compile + warm
  jax.block_until_ready(loss)
  t0 = time.perf_counter()
  for _ in range(STEPS):
    state2, loss = step(state2, *bt)
  jax.block_until_ready(loss)
  dt = (time.perf_counter() - t0) / STEPS
  return nbytes, n_a2a, n_pp, dt, float(loss)


def main():
  mesh = create_mesh(WORLD)
  print(f"exchange budget: world={WORLD} batch={GLOBAL_BATCH} "
        f"tables=8x(1024 rows, w32, h8) zipf({ALPHA})")
  results = {}
  for wire in ("f32", "bf16"):
    for dedup in (False, True):
      nbytes, _, _, dt, loss = measure(mesh, wire, dedup)
      results[(wire, dedup)] = (nbytes, dt)
      print(f"  wire={wire:<4} dedup={int(dedup)}  "
            f"exchanged {nbytes / 1024:9.1f} KiB/device-step  "
            f"step {dt * 1e3:7.1f} ms  loss {loss:.5f}")
  base = results[("f32", False)][0]
  for mode in (("f32", True), ("bf16", False), ("bf16", True)):
    red = 1.0 - results[mode][0] / base
    print(f"  reduction vs seed exchange: wire={mode[0]} "
          f"dedup={int(mode[1])}: {red * 100:.1f}%")
  red = 1.0 - results[("bf16", True)][0] / base
  ok = red >= 0.40
  print(f"acceptance (>= 40% with dedup+bf16): "
        f"{'OK' if ok else 'FAIL'} ({red * 100:.1f}%)")
  return 0 if ok else 1


def main_overlap(chunk_list):
  """The round-7 sweep: overlap x wire_dtype x chunks, dedup'd routing
  everywhere (the production configuration the round-6 budget landed
  on). Prints wire bytes + collective rounds + step time per mode."""
  mesh = create_mesh(WORLD)
  print(f"overlapped-exchange budget: world={WORLD} batch={GLOBAL_BATCH} "
        f"tables=8x(1024 rows, w32, h8) zipf({ALPHA}) dedup=1")
  results = {}
  for wire in ("f32", "bf16", "fp8"):
    for overlap, chunks in ([("none", 1)]
                            + [("pipelined", c) for c in chunk_list]):
      nbytes, n_a2a, n_pp, dt, loss = measure(mesh, wire, True, overlap,
                                              chunks)
      results[(wire, overlap, chunks)] = (nbytes, dt)
      rounds = f"{n_a2a} a2a" if overlap == "none" else f"{n_pp} ppermute"
      print(f"  wire={wire:<4} overlap={overlap:<9} chunks={chunks}  "
            f"exchanged {nbytes / 1024:9.1f} KiB/device-step  "
            f"rounds {rounds:>13}  step {dt * 1e3:7.1f} ms  "
            f"loss {loss:.5f}")
  # Acceptance bar: every pipelined bf16/fp8 configuration must step at
  # most as slow as THE monolithic mode (f32, overlap off — the
  # pre-round-7 exchange). On this CPU-mesh proxy the rounds are
  # memcpys, so there is no flight time to hide — only schedule overhead
  # to absorb — and the per-dtype comparison printed above is the honest
  # picture: pipelined f32 WINS outright (the self block never crosses
  # the wire: (world-1)/world of the monolithic bytes), while the narrow
  # wires pay visible per-round overhead against their own monolithic
  # forms. The overlap win proper (gather of chunk k under chunk k+1's
  # flight) is a real-TPU multichip measurement — ROADMAP.
  mono_f32 = results[("f32", "none", 1)][1]
  ok = True
  for wire in ("bf16", "fp8"):
    best_c, best = min(
        ((c, results[(wire, "pipelined", c)][1]) for c in chunk_list),
        key=lambda kv: kv[1])
    own = results[(wire, "none", 1)][1]
    mode_ok = best <= mono_f32
    ok = ok and mode_ok
    print(f"  pipelined {wire} best (chunks={best_c}): {best * 1e3:.1f} ms "
          f"(monolithic {wire}: {own * 1e3:.1f} ms, monolithic f32: "
          f"{mono_f32 * 1e3:.1f} ms) -> {'OK' if mode_ok else 'FAIL'}")
  print(f"acceptance (pipelined bf16/fp8 <= the monolithic mode's step "
        f"time): {'OK' if ok else 'FAIL'}")
  return 0 if ok else 1


SMOKE_CFG = SyntheticModelConfig(
    name="exchange-smoke",
    embedding_groups=(EmbeddingGroup(2, (4,), 512, 16, False),),
    mlp_sizes=(32, 16), num_numerical_features=8, interact_stride=None)
SMOKE_BATCH = 1024


def gather_hidden_fraction(world, chunks):
  """Schedule accounting of the fused exchange: of the ``world x
  chunks`` chunk-gathers one float exchange issues, how many run with a
  prior send eligible to be in flight (the TPU kernel's double buffer
  overlaps each round-body gather with the previous chunk's DMA). The
  self-round's ``chunks`` gathers ship nothing and the first SENDING
  chunk's gather has no flight yet — everything else hides."""
  total = world * chunks
  hidden = (world - 1) * chunks - 1
  return hidden / total


def main_occupancy(chunks, smoke=False):
  """The round-20 fused-schedule pricing: pipelined f32 (monolithic
  pre-gather) vs fused f32 / fused fp8 (just-in-time round-body
  gathers), dedup'd routing everywhere. Emits the exchange-smoke /
  exchange-occupancy verdict."""
  from distributed_embeddings_tpu import telemetry
  cfg = SMOKE_CFG if smoke else None
  batch = SMOKE_BATCH if smoke else None
  mesh = create_mesh(WORLD)
  g = cfg or CFG
  print(f"fused-exchange occupancy: world={WORLD} "
        f"batch={batch or GLOBAL_BATCH} chunks={chunks} "
        f"tables={g.embedding_groups[0].num_tables}x"
        f"({g.embedding_groups[0].num_rows} rows, "
        f"w{g.embedding_groups[0].width}, "
        f"h{g.embedding_groups[0].nnz[0]}) zipf({ALPHA}) dedup=1")
  modes = {}
  for name, wire, overlap in (("pipelined-f32", "f32", "pipelined"),
                              ("fused-f32", "f32", "fused"),
                              ("fused-fp8", "fp8", "fused")):
    nbytes, _, n_pp, dt, loss = measure(mesh, wire, True, overlap, chunks,
                                        cfg, batch)
    per_round = dt / n_pp if n_pp else float("nan")
    modes[name] = {"step_ms": dt * 1e3, "rounds": n_pp,
                   "per_round_us": per_round * 1e6,
                   "wire_kib": nbytes / 1024, "loss": loss}
    print(f"  {name:<14} step {dt * 1e3:7.1f} ms  rounds {n_pp:4d}  "
          f"per-round {per_round * 1e6:7.1f} us  "
          f"wire {nbytes / 1024:9.1f} KiB  loss {loss:.6f}")
  frac = gather_hidden_fraction(WORLD, chunks)
  print(f"  gather-hidden fraction (schedule accounting, CPU proxy — "
        f"the double buffer's upper bound on real ICI): "
        f"{frac * 100:.1f}% of {WORLD * chunks} chunk-gathers/exchange")
  # parity: fused f32 re-times the SAME f32 math on the same batches,
  # so its loss must equal pipelined f32 bit-for-bit (the tier-1 parity
  # matrix proves the full state; the smoke keeps the cheap end-to-end
  # echo of it)
  parity = modes["fused-f32"]["loss"] == modes["pipelined-f32"]["loss"]
  slack = modes["fused-f32"]["step_ms"] <= modes["pipelined-f32"]["step_ms"]
  result = {"world": WORLD, "chunks": chunks, "smoke": smoke,
            "modes": modes, "gather_hidden_frac": frac,
            "losses_bit_exact": bool(parity)}
  if smoke:
    # machinery gates only: CPU-mesh step times at toy scale are noise
    result["ok"] = bool(parity and frac >= 0.5
                        and modes["fused-f32"]["rounds"]
                        == modes["pipelined-f32"]["rounds"])
  else:
    print(f"  fused f32 {modes['fused-f32']['step_ms']:.1f} ms vs "
          f"pipelined f32 {modes['pipelined-f32']['step_ms']:.1f} ms "
          f"-> {'OK' if slack else 'FAIL'}")
    result["ok"] = bool(parity and frac >= 0.5 and slack)
  return telemetry.emit_verdict(
      "exchange-smoke" if smoke else "exchange-occupancy", result)


if __name__ == "__main__":
  ap = argparse.ArgumentParser()
  ap.add_argument("--overlap", action="store_true",
                  help="sweep overlap x wire_dtype x chunks (round 7) "
                       "instead of the round-6 wire_dtype x dedup 2x2")
  ap.add_argument("--overlap-occupancy", action="store_true",
                  help="price the fused just-in-time schedule (round "
                       "20): per-round wall, gather-hidden fraction, "
                       "wire bytes, fused vs pipelined step time")
  ap.add_argument("--smoke", action="store_true",
                  help="tiny tier for make verify (machinery + parity "
                       "+ schedule accounting; no CPU perf gates). "
                       "Only with --overlap-occupancy.")
  ap.add_argument("--chunks", default="1,2,4",
                  help="comma-separated exchange_chunks values for the "
                       "--overlap sweep (--overlap-occupancy uses the "
                       "FIRST value > 1, default 2)")
  args = ap.parse_args()
  if args.overlap_occupancy:
    chunk_list = [int(c) for c in args.chunks.split(",")]
    occ_chunks = next((c for c in chunk_list if c > 1), 2)
    raise SystemExit(main_occupancy(occ_chunks, smoke=args.smoke))
  if args.overlap:
    raise SystemExit(main_overlap(
        [int(c) for c in args.chunks.split(",")]))
  raise SystemExit(main())
