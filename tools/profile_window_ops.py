"""Window (2-D start) gather/scatter vs sub-row extract/expand, + compaction.

If XLA's TPU lowering keeps its ~10/20 ns per-row costs with a (row, lane)
start and a 32-lane window, the packed-table gather extraction einsum and
apply expansion einsum can be deleted entirely.

Usage: PYTHONPATH=/root/repo:/root/.axon_site python -u tools/profile_window_ops.py
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from distributed_embeddings_tpu.models.synthetic import power_law_ids
from distributed_embeddings_tpu.ops.packed_table import PackedLayout

B = 65536
ALPHA = 1.05
K_REPS = 5
LAYOUT = PackedLayout(rows=52_200_000, width=16, n_aux=1)


def _sync(x):
  # axon tunnel: block_until_ready can return before the work drains; a
  # scalar FETCH is the only reliable sync (see memory/axon-tpu-environment)
  leaf = jax.tree_util.tree_leaves(x)[0]
  float(jnp.asarray(leaf).ravel()[0])


def timeit(name, fn, buf, *args, donate=True, n_norm=None):
  step = jax.jit(fn, donate_argnums=(0,) if donate else ())
  carry = step(buf, *args)
  _sync(carry)

  def run(n, carry):
    t0 = time.perf_counter()
    for _ in range(n):
      carry = step(carry, *args)
    _sync(carry)
    return time.perf_counter() - t0, carry

  _, carry = run(1, carry)
  t1, carry = run(K_REPS, carry)
  t2, carry = run(2 * K_REPS, carry)
  dt = (t2 - t1) / K_REPS
  per = f"  {dt / n_norm * 1e9:6.1f} ns/elem" if n_norm else ""
  print(f"{name:48s}: {dt * 1e3:8.2f} ms{per}", flush=True)
  return carry


def main():
  rng = np.random.default_rng(0)
  ids_np = (power_law_ids(rng, B, 44, 25_000_000, ALPHA).ravel()
            .astype(np.int32))
  n = ids_np.shape[0]
  rpp = LAYOUT.rows_per_phys
  stride = LAYOUT.stride
  grp_np = (ids_np // rpp).astype(np.int32)
  # (id % rpp) * stride < 128 lanes of one physical row
  lane_np = ((ids_np % rpp) * stride).astype(np.int32)  # graftlint: disable=GL106
  starts = jnp.stack(
      [jnp.asarray(grp_np), jnp.asarray(lane_np)], axis=1)  # [n, 2]
  print(f"n={n} rpp={rpp} stride={stride} phys_rows={LAYOUT.phys_rows}")

  bufw = jnp.zeros((LAYOUT.phys_rows + 1, 128), jnp.float32)

  # --- window gather: [n, 32] sub-rows straight out of the packed buffer
  gdn = jax.lax.GatherDimensionNumbers(
      offset_dims=(1,), collapsed_slice_dims=(0,), start_index_map=(0, 1))

  def win_gather(c, b, st):
    # carry-dependent starts (not provably zero) defeat constant folding
    # without touching the 6.7 GB operand
    st = st + jnp.minimum(c.astype(jnp.int32), 0)
    rows = jax.lax.gather(b, st, gdn, slice_sizes=(1, stride),
                          mode=jax.lax.GatherScatterMode.FILL_OR_DROP)
    return c + jnp.tanh(jnp.sum(rows) * 1e-6) * 0 + jnp.float32(0)

  timeit("window-gather 2-D starts [n,32]", win_gather,
         jnp.zeros((), jnp.float32), bufw, starts, donate=False, n_norm=n)

  # --- plain row gather (floor reference)
  def row_gather(c, b, g):
    g = g + jnp.minimum(c.astype(jnp.int32), 0)
    rows = jnp.take(b, g, axis=0, mode="fill", fill_value=0)
    return c + jnp.tanh(jnp.sum(rows) * 1e-6) * 0 + jnp.float32(0)

  timeit("row-gather [n,128] (floor)", row_gather,
         jnp.zeros((), jnp.float32), bufw, jnp.asarray(grp_np),
         donate=False, n_norm=n)

  # --- window scatter-add
  sdn = jax.lax.ScatterDimensionNumbers(
      update_window_dims=(1,), inserted_window_dims=(0,),
      scatter_dims_to_operand_dims=(0, 1))
  upd32 = jnp.asarray(
      rng.standard_normal((n, stride)).astype(np.float32) * 1e-6)

  def win_scatter(b, st, u):
    return jax.lax.scatter_add(
        b, st, u, sdn, mode=jax.lax.GatherScatterMode.FILL_OR_DROP)

  c = timeit("window-scatter-add 2-D starts [n,32]", win_scatter, bufw,
             starts, upd32, n_norm=n)
  print(f"  checksum {float(jnp.sum(c[:64, :4])):.3e}")
  bufw = c

  # --- baseline: expansion einsum + full-row scatter (today's apply path)
  upd128 = jnp.asarray(
      rng.standard_normal((n, 128)).astype(np.float32) * 1e-6)

  def row_scatter(b, g, u):
    return b.at[g].add(u, mode="drop")

  bufw = timeit("row-scatter [n,128] (floor)", row_scatter, bufw,
                jnp.asarray(grp_np), upd128, n_norm=n)

  sub = jnp.asarray((ids_np % rpp).astype(np.int32))

  def expand_scatter(b, g, s, u):
    oh = jax.nn.one_hot(s, rpp, dtype=u.dtype)
    up = jnp.einsum("ns,nr->nrs", u, oh).reshape(-1, rpp * stride)
    return b.at[g].add(up, mode="drop")

  bufw = timeit("expand einsum + row-scatter (today)", expand_scatter, bufw,
                jnp.asarray(grp_np), sub, upd32, n_norm=n)
  del bufw

  # --- device compaction, non-foldable this time
  cold_cap = int(n * 0.55)

  def compact_step(c, ids_f):
    ids_f = ids_f + jnp.minimum(c, 0)
    is_cold = ids_f >= 4096
    csum = jnp.cumsum(is_cold.astype(jnp.int32))
    total = csum[-1]
    tgt = jnp.arange(1, cold_cap + 1, dtype=jnp.int32)
    src = jnp.searchsorted(csum, tgt)
    vals = jnp.take(ids_f, jnp.clip(src, 0, n - 1), mode="clip")
    vals = jnp.where(tgt <= total, vals, -1)
    return c + jnp.minimum(jnp.sum(vals == -12345), 0).astype(jnp.int32)

  timeit(f"device compaction cumsum+searchsorted+take (n={n})",
         compact_step, jnp.zeros((), jnp.int32), jnp.asarray(ids_np),
         donate=False, n_norm=n)


if __name__ == "__main__":
  main()
