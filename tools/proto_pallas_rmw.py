"""Prototype: Pallas read-modify-write scatter vs XLA scatter-add.

Measures the per-row cost ceiling of DMA-pipelined random-row RMW on the
real chip. Correctness for duplicate ids is NOT handled here (timing uses
ids drawn without replacement per chunk); the production kernel gates on
this number being clearly under XLA's ~75 ns/row.

Usage: python tools/proto_pallas_rmw.py [n_ids] [rows] [depth] [chunk]
"""

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N_IDS = int(sys.argv[1]) if len(sys.argv) > 1 else 9 * 65536
ROWS = int(sys.argv[2]) if len(sys.argv) > 2 else 1 << 23
DEPTH = int(sys.argv[3]) if len(sys.argv) > 3 else 8
CHUNK = int(sys.argv[4]) if len(sys.argv) > 4 else 4096
W = 128
K = 8


def rmw_scatter(buf, ids, delta, depth=DEPTH, chunk=CHUNK):
  """buf[ids[i]] += delta[i] via per-row DMA RMW. Assumes no duplicate id
  is in flight within `depth` positions (prototype)."""
  n = ids.shape[0]
  assert n % chunk == 0

  def kernel(ids_ref, buf_in, delta_ref, buf_out, rbuf, wbuf, rsem, wsem):
    def start_read(j):
      idx = ids_ref[j]
      pltpu.make_async_copy(
          buf_in.at[pl.ds(idx, 1), :], rbuf.at[j % depth], rsem.at[j % depth]
      ).start()

    for j in range(depth):
      start_read(j)

    def body(j, _):
      slot = j % depth
      pltpu.make_async_copy(
          buf_in.at[pl.ds(0, 1), :], rbuf.at[slot], rsem.at[slot]).wait()

      @pl.when(j >= depth)
      def _():
        pltpu.make_async_copy(
            wbuf.at[slot], buf_out.at[pl.ds(0, 1), :], wsem.at[slot]).wait()

      wbuf[slot] = rbuf[slot] + delta_ref[pl.ds(j, 1), :]
      idx = ids_ref[j]
      pltpu.make_async_copy(
          wbuf.at[slot], buf_out.at[pl.ds(idx, 1), :], wsem.at[slot]).start()

      @pl.when(j + depth < chunk)
      def _():
        start_read(j + depth)

      return 0

    jax.lax.fori_loop(0, chunk, body, 0)

    def drain(j, _):
      pltpu.make_async_copy(
          wbuf.at[j % depth], buf_out.at[pl.ds(0, 1), :],
          wsem.at[j % depth]).wait()
      return 0

    jax.lax.fori_loop(max(0, chunk - depth), chunk, drain, 0)

  return pl.pallas_call(
      kernel,
      grid=(n // chunk,),
      in_specs=[
          pl.BlockSpec((chunk,), lambda i: (i,),
                       memory_space=pltpu.SMEM),  # ids chunk
          pl.BlockSpec(memory_space=pltpu.ANY),  # buf (aliased)
          pl.BlockSpec((chunk, W), lambda i: (i, 0)),  # delta
      ],
      out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
      scratch_shapes=[
          pltpu.VMEM((DEPTH, 1, W), jnp.float32),
          pltpu.VMEM((DEPTH, 1, W), jnp.float32),
          pltpu.SemaphoreType.DMA((DEPTH,)),
          pltpu.SemaphoreType.DMA((DEPTH,)),
      ],
      out_shape=jax.ShapeDtypeStruct(buf.shape, buf.dtype),
      input_output_aliases={1: 0},
      compiler_params=pltpu.CompilerParams(has_side_effects=True),
  )(ids, buf, delta)


def write_only(buf, ids, delta, depth=DEPTH, chunk=CHUNK):
  """Ceiling probe: random-row writes, no read/add."""
  n = ids.shape[0]

  def kernel(ids_ref, buf_in, delta_ref, buf_out, wsem):
    def body(j, _):
      slot = j % depth

      @pl.when(j >= depth)
      def _():
        pltpu.make_async_copy(
            delta_ref.at[pl.ds(0, 1), :], buf_out.at[pl.ds(0, 1), :],
            wsem.at[slot]).wait()

      idx = ids_ref[j]
      pltpu.make_async_copy(
          delta_ref.at[pl.ds(j, 1), :], buf_out.at[pl.ds(idx, 1), :],
          wsem.at[slot]).start()
      return 0

    jax.lax.fori_loop(0, chunk, body, 0)

    def drain(j, _):
      pltpu.make_async_copy(
          delta_ref.at[pl.ds(0, 1), :], buf_out.at[pl.ds(0, 1), :],
          wsem.at[j % depth]).wait()
      return 0

    jax.lax.fori_loop(max(0, chunk - depth), chunk, drain, 0)

  return pl.pallas_call(
      kernel,
      grid=(n // chunk,),
      in_specs=[
          pl.BlockSpec((chunk,), lambda i: (i,), memory_space=pltpu.SMEM),
          pl.BlockSpec(memory_space=pltpu.ANY),
          pl.BlockSpec((chunk, W), lambda i: (i, 0)),
      ],
      out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
      scratch_shapes=[pltpu.SemaphoreType.DMA((DEPTH,))],
      out_shape=jax.ShapeDtypeStruct(buf.shape, buf.dtype),
      input_output_aliases={1: 0},
      compiler_params=pltpu.CompilerParams(has_side_effects=True),
  )(ids, buf, delta)


def timeit(name, fn, buf, ids, delta):
  step = jax.jit(fn, donate_argnums=(0,))
  carry = step(buf, ids, delta)
  jax.block_until_ready(carry)
  float(carry[0, 0])

  def run(n, carry):
    t0 = time.perf_counter()
    for _ in range(n):
      carry = step(carry, ids, delta)
    float(carry[0, 0])
    return time.perf_counter() - t0, carry

  _, carry = run(1, carry)  # absorb fetch-program compile
  t1, carry = run(K, carry)
  t2, carry = run(2 * K, carry)
  dt = (t2 - t1) / K
  print(f"{name:34s}: {dt * 1e3:8.2f} ms  {dt / N_IDS * 1e9:6.1f} ns/row",
        flush=True)
  return carry


def main():
  print(f"n_ids={N_IDS} rows={ROWS} depth={DEPTH} chunk={CHUNK}")
  key = jax.random.PRNGKey(0)
  rng = np.random.default_rng(0)
  buf = jnp.zeros((ROWS, W), jnp.float32)
  # per-chunk duplicate-free ids (prototype correctness assumption)
  ids_np = np.concatenate([
      rng.choice(ROWS, CHUNK, replace=False)
      for _ in range(N_IDS // CHUNK)]).astype(np.int32)
  ids = jnp.asarray(ids_np)
  delta = jax.random.normal(key, (N_IDS, W), jnp.float32)

  # correctness probe at small size (vs XLA scatter)
  small_buf = jnp.zeros((1 << 16, W), jnp.float32)
  sid = jnp.asarray(rng.choice(1 << 16, CHUNK, replace=False).astype(np.int32))
  sdelta = jax.random.normal(key, (CHUNK, W), jnp.float32)
  got = rmw_scatter(small_buf, sid, sdelta)
  want = jnp.zeros((1 << 16, W), jnp.float32).at[sid].add(sdelta)
  print("rmw correct:", bool(jnp.allclose(got, want, atol=1e-6)))

  buf = timeit("pallas rmw", rmw_scatter, buf, ids, delta)
  buf = timeit("pallas write-only", write_only, buf, ids, delta)

  def xla_scatter(buf, ids, delta):
    return buf.at[ids].add(delta, mode="drop")

  buf = timeit("xla scatter", xla_scatter, buf, ids, delta)


if __name__ == "__main__":
  main()
