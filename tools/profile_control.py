"""Control-plane budget: hedged tail latency and self-scaling under load.

The closed-loop control-plane exerciser (``make control-bench``). Two
measurements on the 8-way CPU mesh:

1. **Hedging tightens the tail**: a fully replicated fleet serves an
   open-loop Poisson load while ONE replica is made slow (a
   ``fleet_rpc`` delay rule matched to that owner). The same load runs
   with hedging disabled and enabled. Acceptance: ZERO wrong answers in
   both modes (every completed request bitwise-matches the
   single-process engine — a hedge returns the same f32 bytes or
   nothing), at least one hedge fired, and the hedged p99.9 is
   measurably below the unhedged p99.9 (the recorded budget lives in
   docs/BENCHMARKS.md).

2. **Self-scaling under a 3x QPS step**: the fleet starts at one owner
   per rank with a :class:`FleetAutoscaler` ticking on a background
   thread (QPS sampled from the batcher's ``serve/submitted`` counter
   through :class:`CounterRate`). The offered load steps to ~3x the
   initial rate mid-run. Acceptance: the autoscaler issues a
   ``scale_up`` actuated through ``apply_fleet`` (owner spawn + replica
   promotion) WHILE requests are in flight, with zero wrong answers and
   zero dropped requests (every submitted request either completes
   bit-exactly or was shed as a counted rejection), finite p99.9, and
   every decision recorded in the replayable ``control/decisions``
   stream. The phase latencies also drive one :class:`ControlPolicy`
   tick against a deadline-class budget, so the SLO-admission wiring is
   exercised end to end.

``--smoke`` runs a tiny-world tier wired into ``make verify`` (same
assertions, ~half the requests). Verdict via ``telemetry.emit_verdict``
either way.

Usage: PYTHONPATH=/root/repo python tools/profile_control.py [--smoke]
"""

import argparse
import os
import shutil
import tempfile
import threading
import time

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
  os.environ["XLA_FLAGS"] = (
      flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402,F401  (device platform must initialize first)
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from distributed_embeddings_tpu import telemetry  # noqa: E402
from distributed_embeddings_tpu.control import (  # noqa: E402
    AutoscalerConfig,
    ControlPolicy,
    ControlSnapshot,
    CounterRate,
    DecisionLog,
    FleetAutoscaler,
)
from distributed_embeddings_tpu.fleet import (  # noqa: E402
    FleetConfig,
    FleetOwner,
    FleetPlan,
    FleetRouter,
    InProcTransport,
)
from distributed_embeddings_tpu.layers.dist_model_parallel import (  # noqa: E402
    set_weights,
)
from distributed_embeddings_tpu.layers.embedding import TableConfig  # noqa: E402
from distributed_embeddings_tpu.layers.planner import (  # noqa: E402
    DistEmbeddingStrategy,
)
from distributed_embeddings_tpu.ops.packed_table import sparse_rule  # noqa: E402
from distributed_embeddings_tpu.parallel import create_mesh  # noqa: E402
from distributed_embeddings_tpu.parallel.lookup_engine import PAD_ID  # noqa: E402
from distributed_embeddings_tpu.resilience import faultinject  # noqa: E402
from distributed_embeddings_tpu.serving import (  # noqa: E402
    MicroBatcher,
    Rejected,
    ServeEngine,
)
from distributed_embeddings_tpu.serving.export import (  # noqa: E402
    export as serve_export,
)
from distributed_embeddings_tpu.serving.export import load as serve_load  # noqa: E402
from distributed_embeddings_tpu.training import (  # noqa: E402
    init_sparse_state,
    shard_params,
)


class ActsModel:
  def apply(self, variables, numerical, cats, emb_acts=None):
    del variables, numerical, cats
    return jnp.concatenate(list(emb_acts), axis=-1)


BENCH = dict(world=4, sizes=[65536, 16384, 4096], widths=[16, 16, 16],
             hotness=[4, 2, 1], req_rows=4, max_batch=64,
             n_hedge=240, n_ramp=240, slow_s=0.05, hedge_qps=10.0)
SMOKE = dict(world=2, sizes=[1536, 768], widths=[16, 16],
             hotness=[2, 1], req_rows=4, max_batch=32,
             n_hedge=100, n_ramp=120, slow_s=0.04, hedge_qps=12.0)

HEDGE_KW = dict(hedge_quantile=0.5, hedge_min_s=0.005,
                hedge_min_samples=10)


def build(cfg):
  rng = np.random.default_rng(7)
  tables = [TableConfig(s, w, combiner="sum")
            for s, w in zip(cfg["sizes"], cfg["widths"])]
  plan = DistEmbeddingStrategy(tables, cfg["world"], "memory_balanced",
                               dense_row_threshold=0,
                               input_hotness=cfg["hotness"])
  weights = [(rng.standard_normal((s, w)) / np.sqrt(w)).astype(np.float32)
             for s, w in zip(cfg["sizes"], cfg["widths"])]
  params = {"embeddings": {k: jnp.asarray(v)
                           for k, v in set_weights(plan, weights).items()}}
  rule = sparse_rule("adagrad", 0.05)
  mesh = create_mesh(cfg["world"])
  state = shard_params(init_sparse_state(plan, params, rule,
                                         optax.sgd(0.01)), mesh)
  return plan, rule, mesh, state, rng


def mkreq(rng, cfg, n):
  ids = []
  for s, h in zip(cfg["sizes"], cfg["hotness"]):
    x = rng.integers(0, s, (n, h)).astype(np.int32)
    x[rng.random(x.shape) < 0.2] = PAD_ID
    ids.append(x)
  return rng.standard_normal((n, 4)).astype(np.float32), ids


def build_fleet(path, plan, mesh, fplan, **fleet_kw):
  owners = {o: FleetOwner(path, plan, fplan.owned_ranks(o), owner_id=o)
            for o in range(fplan.n_owners)}
  transport = InProcTransport(owners)
  reg = telemetry.MetricsRegistry()
  router = FleetRouter(ActsModel(), plan, path, fplan, transport,
                       mesh=mesh, telemetry=reg, **fleet_kw)
  return owners, transport, router, reg


def pcts(lats):
  if not lats:
    return float("nan"), float("nan"), float("nan")
  a = np.sort(np.asarray(lats))
  pick = lambda q: float(a[min(len(a) - 1, int(q * len(a)))])  # noqa: E731
  return pick(0.50), pick(0.99), pick(0.999)


def open_loop(mb, reqs, qps, n_requests, rng):
  futs, rejected = [], 0
  t = time.perf_counter()
  for i in range(n_requests):
    t += float(rng.exponential(1.0 / qps))
    now = time.perf_counter()
    if t > now:
      time.sleep(t - now)
    numerical, ids = reqs[i % len(reqs)]
    try:
      futs.append((i % len(reqs), mb.submit(numerical, ids)))
    except Rejected:
      rejected += 1
  out, lats = [], []
  for ri, f in futs:
    out.append((ri, f.result(timeout=300)))
    lats.append(f.latency_s)
  return lats, rejected, out


# ---------------------------------------------------------------------------
# measurements
# ---------------------------------------------------------------------------


def check_hedging_tightens_tail(cfg, tmp, result):
  """One slow replica, same Poisson load, hedging off vs on: zero
  wrong answers both ways, and the hedged p99.9 beats the unhedged."""
  plan, rule, mesh, state, rng = build(cfg)
  path = os.path.join(tmp, "art_hedge")
  serve_export(path, plan, rule, state, quantize="f32")
  single = ServeEngine(ActsModel(), plan,
                       serve_load(path, plan, mesh=mesh), mesh=mesh)
  reqs = [mkreq(rng, cfg, cfg["req_rows"]) for _ in range(8)]
  wants = [np.asarray(single.predict(*r)) for r in reqs]
  fplan = FleetPlan.replicated(plan.world_size, 2, replicas=2,
                               hot_fraction=1.0)
  rows = {}
  ok = True
  for mode, hedge_kw in (("off", {}), ("on", HEDGE_KW)):
    fcfg = FleetConfig(cache_fraction=0.05, staging_grps=256,
                       shard_min_phys_rows=16, revive_after_s=3600.0,
                       **hedge_kw)
    owners, transport, router, reg = build_fleet(path, plan, mesh,
                                                 fplan, config=fcfg)
    mb = MicroBatcher(router.dispatch, max_batch=cfg["max_batch"],
                      max_delay_s=0.002)
    mb.submit(*reqs[0]).result(timeout=300)  # compile off the clock
    for _ in range(12):  # warm the per-owner recent-latency windows
      mb.submit(*reqs[1]).result(timeout=300)
    inj = faultinject.FaultInjector()
    inj.delay_when("fleet_rpc", cfg["slow_s"], owner=0)
    # offered BELOW the slow replica's service rate: the measured tail
    # is per-request latency, not a saturated queue (a saturated queue
    # hides the hedge behind queueing delay in both modes)
    with faultinject.injected(inj):
      lats, rejected, out = open_loop(mb, reqs, qps=cfg["hedge_qps"],
                                      n_requests=cfg["n_hedge"], rng=rng)
    mb.close()
    wrong = sum(0 if np.array_equal(res, wants[ri]) else 1
                for ri, res in out)
    p50, p99, p999 = pcts(lats)
    c = router.store._counters
    rows[mode] = {"wrong": wrong, "rejected": rejected,
                  "p50": p50, "p99": p99, "p999": p999,
                  "hedges": c["hedges"].value,
                  "hedges_won": c["hedges_won"].value,
                  "hedges_wasted": c["hedges_wasted"].value}
    ok &= wrong == 0 and len(out) + rejected == cfg["n_hedge"]
    if mode == "off":
      # the disabled control plane is a true no-op: nothing counted,
      # nothing allocated
      ok &= c["hedges"].value == 0 and not router.store._gather_window
    print(f"hedging {mode:>3}: p50 {p50 * 1e3:6.1f}  p99 "
          f"{p99 * 1e3:6.1f}  p99.9 {p999 * 1e3:6.1f} ms  "
          f"wrong={wrong} hedges={c['hedges'].value} "
          f"won={c['hedges_won'].value}")
    router.close()
  ok &= rows["on"]["hedges"] >= 1 and rows["on"]["hedges_won"] >= 1
  ok &= rows["on"]["p999"] < rows["off"]["p999"]
  rows["p999_tightening"] = (rows["off"]["p999"] - rows["on"]["p999"]) \
      / max(rows["off"]["p999"], 1e-9)
  print(f"hedging p99.9: {rows['off']['p999'] * 1e3:.1f} -> "
        f"{rows['on']['p999'] * 1e3:.1f} ms "
        f"({rows['p999_tightening']:.0%} tighter) "
        f"{'OK' if ok else 'FAIL'}")
  result["hedging"] = rows
  return ok


def check_autoscale_ramp(cfg, tmp, result):
  """3x QPS step under a live autoscaler: the fleet re-sizes through
  ``apply_fleet`` mid-load with zero wrong answers and zero dropped
  requests, every decision logged."""
  plan, rule, mesh, state, rng = build(cfg)
  path = os.path.join(tmp, "art_ramp")
  serve_export(path, plan, rule, state, quantize="f32")
  single = ServeEngine(ActsModel(), plan,
                       serve_load(path, plan, mesh=mesh), mesh=mesh)
  reqs = [mkreq(rng, cfg, cfg["req_rows"]) for _ in range(8)]
  wants = [np.asarray(single.predict(*r)) for r in reqs]
  world = plan.world_size
  fplan1 = FleetPlan.balanced(world, 2)  # one owner per rank
  fcfg = FleetConfig(cache_fraction=0.05, staging_grps=256,
                     shard_min_phys_rows=16, revive_after_s=3600.0)
  owners, transport, router, reg = build_fleet(path, plan, mesh, fplan1,
                                               config=fcfg)
  # one registry for batcher + router: the ticker's QPS probe samples
  # serve/submitted and the decision counters land beside it
  mb = MicroBatcher(router.dispatch, max_batch=cfg["max_batch"],
                    max_delay_s=0.002, registry=reg)
  mb.submit(*reqs[0]).result(timeout=300)  # compile off the clock
  # closed-loop saturation estimate calibrates the band
  t0 = time.perf_counter()
  n_sat = 30
  for i in range(n_sat):
    mb.submit(*reqs[i % len(reqs)]).result(timeout=300)
  sat_qps = n_sat / (time.perf_counter() - t0)
  base_qps = max(5.0, 0.2 * sat_qps)

  spawned = {}  # actuation artifacts, closed at the end

  def actuate(target_replicas, rec):
    fplan2 = FleetPlan.replicated(world, 2, replicas=target_replicas,
                                  hot_fraction=1.0)
    owners2 = {o: FleetOwner(path, plan, fplan2.owned_ranks(o),
                             owner_id=o)
               for o in range(fplan2.n_owners)}
    router.apply_fleet(fplan2, InProcTransport(owners2))
    spawned["owners"] = owners2
    replicas_now[0] = target_replicas

  decisions = DecisionLog(os.path.join(tmp, "decisions.jsonl"),
                          telemetry=reg)
  scaler = FleetAutoscaler(
      AutoscalerConfig(qps_high_per_replica=2.0 * base_qps,
                       qps_low_per_replica=0.1 * base_qps,
                       min_replicas=1, max_replicas=2,
                       up_after=2, down_after=50, cooldown_ticks=4),
      actuate=actuate, decisions=decisions)
  replicas_now = [1]
  rate = CounterRate()
  stop = threading.Event()
  tick_n = [0]

  def ticker():
    while not stop.wait(0.05):
      tick_n[0] += 1
      qps = rate.sample(reg.counter("serve/submitted").value,
                        time.time())
      scaler.tick(ControlSnapshot(tick=tick_n[0], qps=qps,
                                  replicas=replicas_now[0]))

  th = threading.Thread(target=ticker, daemon=True)
  th.start()
  # phase A: in-band load; phase B: the 3x step the band cannot absorb
  # at one replica per rank
  latsA, rejA, outA = open_loop(mb, reqs, base_qps,
                                cfg["n_ramp"] // 3, rng)
  latsB, rejB, outB = open_loop(mb, reqs, 3.0 * base_qps,
                                cfg["n_ramp"], rng)
  stop.set()
  th.join(timeout=5.0)
  mb.close()
  wrong = sum(0 if np.array_equal(res, wants[ri]) else 1
              for ri, res in outA + outB)
  n_total = cfg["n_ramp"] // 3 + cfg["n_ramp"]
  completed = len(outA) + len(outB)
  rejected = rejA + rejB
  scale_ups = [r for r in decisions.records if r["action"] == "scale_up"]
  p50, p99, p999 = pcts(latsA + latsB)
  decisions.close()
  # the phase latencies drive one SLO-admission tick end to end
  policy = ControlPolicy(mb, {"interactive": max(0.05, 4 * p99)},
                         decisions=DecisionLog(telemetry=reg))
  for s in latsA + latsB:
    policy.observe_latency(s)
  adm = policy.tick()
  result["ramp"] = {
      "sat_qps": sat_qps, "base_qps": base_qps,
      "requests": n_total, "completed": completed,
      "rejected": rejected, "wrong": wrong,
      "scale_ups": len(scale_ups), "replicas_final": replicas_now[0],
      "p50": p50, "p99": p99, "p999": p999,
      "decisions": len(decisions.records),
      "admission_action": adm["action"],
  }
  ok = (wrong == 0 and completed + rejected == n_total
        and len(scale_ups) >= 1 and replicas_now[0] == 2
        and bool(np.isfinite([p50, p99, p999]).all()))
  print(f"autoscale ramp: {n_total} requests ({base_qps:.0f} -> "
        f"{3 * base_qps:.0f} req/s), wrong={wrong}, "
        f"dropped={n_total - completed - rejected}, "
        f"rejected={rejected}, scale_ups={len(scale_ups)}, "
        f"replicas={replicas_now[0]}, p99.9 {p999 * 1e3:.1f} ms, "
        f"decisions={len(decisions.records)} "
        f"{'OK' if ok else 'FAIL'}")
  router.close()
  return ok


def main(cfg, tag):
  tmp = tempfile.mkdtemp(prefix="control_bench_")
  result = {"config": {k: v for k, v in cfg.items()}}
  try:
    ok = check_hedging_tightens_tail(cfg, tmp, result)
    ok = check_autoscale_ramp(cfg, tmp, result) and ok
  finally:
    shutil.rmtree(tmp, ignore_errors=True)
  result["ok"] = bool(ok)
  return telemetry.emit_verdict(tag, result)


if __name__ == "__main__":
  ap = argparse.ArgumentParser()
  ap.add_argument("--smoke", action="store_true",
                  help="tiny-world smoke tier (wired into make verify)")
  args = ap.parse_args()
  if args.smoke:
    raise SystemExit(main(SMOKE, "control-smoke"))
  raise SystemExit(main(BENCH, "control-bench"))
