"""Dynamic-vocabulary churn bench: admission vs admit-everything.

The workload is the production shape the dynvocab subsystem exists for —
**power-law ids with a drifting tail**: a stable hot head (the same raw
ids every step, power-law-weighted) plus a tail whose raw ids shift
every step, so tail ids are overwhelmingly one-shot. Two identical
training runs consume the SAME stream through
``dynvocab.DynVocabTrainer``:

- **admit-everything** (``admit_threshold=1``): every first-seen id
  earns a row immediately — the static-vocab reflex, which burns a row
  (table + interleaved optimizer lanes) per one-shot tail id;
- **admission** (``admit_threshold=K``): an id must be observed K times
  (count-min-sketch estimate) before allocating — one-shot tail ids
  never earn a row and emit a zero embedding instead.

Both runs evict on the same TTL (recycling through the freelist, rows
re-zeroed in place), so the comparison is pure admission policy.

Acceptance (docs/BENCHMARKS.md round 9): admission cuts row allocations
to <= 50% of admit-everything's **at equal final eval loss** (evaluated
on the hot head through each run's own translator, read-only, within an
fp-associativity-scale tolerance — the tail ids the policies treat
differently are one-shot either way, so they carry no learning).

``--smoke`` runs the tiny-world tier wired into ``make verify`` (same
assertions, smaller stream); the full run records the round-9 budget.

Usage: PYTHONPATH=/root/repo python tools/profile_dynvocab.py [--smoke]
"""

import argparse
import json
import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
  os.environ["XLA_FLAGS"] = (
      flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from distributed_embeddings_tpu.dynvocab import (  # noqa: E402
    DynVocabTrainer,
    DynVocabTranslator,
)
from distributed_embeddings_tpu.telemetry import timed  # noqa: E402
from distributed_embeddings_tpu.layers.embedding import TableConfig  # noqa: E402
from distributed_embeddings_tpu.layers.planner import (  # noqa: E402
    DistEmbeddingStrategy,
)
from distributed_embeddings_tpu.models import DLRM, bce_loss  # noqa: E402
from distributed_embeddings_tpu.models.dlrm import (  # noqa: E402
    _dlrm_initializer,
)
from distributed_embeddings_tpu.ops.packed_table import (  # noqa: E402
    sparse_rule,
)
from distributed_embeddings_tpu.parallel import create_mesh  # noqa: E402
from distributed_embeddings_tpu.training import (  # noqa: E402
    init_sparse_state_direct,
    make_sparse_eval_step,
    shard_batch,
    shard_params,
)

WORLD = 4
WIDTH = 16
NUM_DENSE = 13


def churn_cats(rng, step, batch, vocab_sizes, hot, drift_base, alpha):
  """One step's raw-id inputs: power-law ranks; ranks below ``hot`` are
  the STABLE head (same raw id every step), ranks above it map to raw
  ids offset by the step index — the drifting tail, one-shot by
  construction."""
  del alpha  # the log-uniform rank draw below fixes the skew shape
  cats = []
  for ti, _v in enumerate(vocab_sizes):
    # log-uniform ranks over [1, drift_base] — a heavy head (rank 0 is
    # the single most likely id) with a long thin tail, the power-law
    # shape without scipy
    u = rng.random(batch)
    ranks = np.floor(np.exp(u * np.log(drift_base))).astype(np.int64) - 1
    ranks = np.clip(ranks, 0, drift_base - 1)
    head = ranks < hot
    raw = np.where(head, ranks,
                   np.int64(10 ** 9) + np.int64(ti) * np.int64(10 ** 8)
                   + np.int64(step) * np.int64(drift_base) + ranks)
    cats.append(raw.astype(np.int64))
  return cats


def build_run(vocab_sizes, admit_threshold, evict_ttl, batch, seed):
  tables = [TableConfig(input_dim=v, output_dim=WIDTH,
                        initializer=_dlrm_initializer(v))
            for v in vocab_sizes]
  plan = DistEmbeddingStrategy(tables, WORLD, "memory_balanced",
                               dense_row_threshold=0, oov="allocate",
                               admit_threshold=admit_threshold,
                               evict_ttl=evict_ttl)
  model = DLRM(vocab_sizes=list(vocab_sizes), embedding_dim=WIDTH,
               bottom_mlp=(32, WIDTH), top_mlp=(32, 1), world_size=WORLD,
               strategy="memory_balanced", dense_row_threshold=0)
  mesh = create_mesh(WORLD)
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.adam(1e-3)
  r = np.random.default_rng(seed)
  num = r.standard_normal((batch, NUM_DENSE)).astype(np.float32)
  cats0 = [r.integers(0, v, batch, dtype=np.int64) for v in vocab_sizes]
  labels0 = r.integers(0, 2, batch).astype(np.float32)
  batch0 = (num, cats0, labels0)
  dummy = [np.zeros((2, WIDTH), np.float32) for _ in vocab_sizes]
  dense = model.init(jax.random.PRNGKey(0), num[:2],
                     [c[:2] for c in cats0], emb_acts=dummy)["params"]
  state = shard_params(
      init_sparse_state_direct(plan, rule, dense, opt,
                               jax.random.PRNGKey(1)), mesh)
  translator = DynVocabTranslator(plan, rule)
  trainer = DynVocabTrainer(model, plan, translator, bce_loss, opt, rule,
                            mesh, state, batch0, guard=True, donate=False)
  return plan, model, mesh, rule, trainer


def eval_loss(plan_args, model, mesh, rule, trainer, eval_batch):
  """Final eval loss on the hot head, ids translated READ-ONLY through
  the run's own translator, scored by the static eval step (built on an
  oov='clip' plan of the same tables — the knob changes no layout, so
  the trained state evaluates directly)."""
  vocab_sizes, = plan_args
  tables = [TableConfig(input_dim=v, output_dim=WIDTH,
                        initializer=_dlrm_initializer(v))
            for v in vocab_sizes]
  plan_eval = DistEmbeddingStrategy(tables, WORLD, "memory_balanced",
                                    dense_row_threshold=0)
  num, cats, labels = eval_batch
  cats_t = trainer.translator.translate_readonly(cats)
  ev = make_sparse_eval_step(model, plan_eval, rule, mesh, trainer.state,
                             (num, cats_t))
  sb = shard_batch((num, [np.asarray(c, np.int32) for c in cats_t]),
                   mesh)
  preds = ev(trainer.state, *sb)
  return float(np.asarray(bce_loss(preds, np.asarray(labels))))


def totals_of(trainer):
  per = trainer.metrics_summary()["per_class"]
  return {
      "allocs": sum(v["allocs"] for v in per.values()),
      "evictions": sum(v["evictions"] for v in per.values()),
      "admit_denied": sum(v["admit_denied"] for v in per.values()),
      "occupancy": sum(v["occupancy"] for v in per.values()),
  }


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument("--smoke", action="store_true",
                  help="tiny tier for make verify")
  ap.add_argument("--threshold", type=int, default=3,
                  help="admission threshold of the admission run")
  args = ap.parse_args()

  if args.smoke:
    vocab_sizes, batch, steps, hot, drift_base = [2000, 500], 128, 20, 120, 1500
    ttl = 12
  else:
    vocab_sizes, batch, steps, hot, drift_base = [20000, 4000], 256, 120, 600, 8000
    ttl = 30
  alpha = 1.05

  def stream(step):
    r = np.random.default_rng(1000 + step)
    num = r.standard_normal((batch, NUM_DENSE)).astype(np.float32)
    cats = churn_cats(r, step, batch, vocab_sizes, hot, drift_base, alpha)
    labels = r.integers(0, 2, batch).astype(np.float32)
    return num, cats, labels

  runs = {}
  for label, thr in (("admit_everything", 1), ("admission", args.threshold)):
    with timed(f"vocab/run/{label}") as tw:
      _, model, mesh, rule, trainer = build_run(vocab_sizes, thr, ttl,
                                                batch, seed=7)
      for s in range(steps):
        trainer.step(*stream(s))
      # hot-head eval batch: raw ids every run admitted long ago
      r = np.random.default_rng(99)
      eval_cats = [r.integers(0, hot, batch).astype(np.int64)
                   for _ in vocab_sizes]
      eb = (r.standard_normal((batch, NUM_DENSE)).astype(np.float32),
            eval_cats, r.integers(0, 2, batch).astype(np.float32))
      loss = eval_loss((vocab_sizes,), model, mesh, rule, trainer, eb)
    runs[label] = {**totals_of(trainer), "eval_loss": loss,
                   "wall_s": round(tw.elapsed, 2)}

  a, b = runs["admit_everything"], runs["admission"]
  ratio = b["allocs"] / max(1, a["allocs"])
  dloss = abs(a["eval_loss"] - b["eval_loss"])
  verdict = {
      "workload": {"vocab": vocab_sizes, "batch": batch, "steps": steps,
                   "hot_head": hot, "drift_base": drift_base,
                   "evict_ttl": ttl,
                   "admit_threshold": args.threshold},
      "runs": runs,
      "alloc_ratio": round(ratio, 4),
      "eval_loss_delta": round(dloss, 5),
      "accept_alloc_halved": ratio <= 0.5,
      "accept_equal_loss": dloss <= 0.05,
  }
  ok = verdict["accept_alloc_halved"] and verdict["accept_equal_loss"]
  verdict["ok"] = ok
  print(json.dumps(verdict, indent=1))
  return 0 if ok else 1


if __name__ == "__main__":
  sys.exit(main())
