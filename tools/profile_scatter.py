"""Isolate XLA scatter-add cost factors on the chip.

Factors: table size, id distribution (uniform vs power-law), update operand
(precomputed vs computed-by-expansion), update width.

Usage: python tools/profile_scatter.py
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

K = 4
N = 1 << 22  # 4.2M update rows


def zipf_ids(n, rows, alpha=1.05, seed=0):
  rng = np.random.default_rng(seed)
  u = rng.random(n)
  # inverse-CDF approximate zipf over [0, rows)
  s = 1.0 - alpha
  ids = ((rows ** s - 1.0) * u + 1.0) ** (1.0 / s) - 1.0
  return np.clip(ids.astype(np.int64), 0, rows - 1).astype(np.int32)


def time_donated(step, state, args, k=K):
  st = step(state, *args)
  float(jnp.ravel(st)[0])

  def run(n, st):
    t0 = time.perf_counter()
    for _ in range(n):
      st = step(st, *args)
    float(jnp.ravel(st)[0])
    return time.perf_counter() - t0, st

  t1, st = run(k, st)
  t2, st = run(2 * k, st)
  return (t2 - t1) / k


def main():
  for rows_log in (22,):
    rows = 1 << rows_log
    fresh = lambda: jnp.zeros((rows, 128), jnp.float32)  # noqa: E731
    upd = jax.random.normal(jax.random.PRNGKey(2), (N, 128), jnp.float32)
    upd32 = jax.random.normal(jax.random.PRNGKey(3), (N, 32), jnp.float32)
    for dist in ("uniform", "zipf"):
      if dist == "uniform":
        ids = jnp.asarray(
            np.random.default_rng(0).integers(0, rows, N, dtype=np.int64)
            .astype(np.int32))
      else:
        ids = jnp.asarray(zipf_ids(N, rows))

      scat = jax.jit(lambda b, i, u: b.at[i].add(u, mode="drop"),
                     donate_argnums=(0,))
      dt = time_donated(scat, fresh(), (ids, upd))
      print(f"rows=2^{rows_log} {dist:7s} precomputed [N,128]: "
            f"{dt * 1e3:7.2f} ms  {dt / N * 1e9:6.2f} ns/row", flush=True)

      # expansion fused into scatter: [N,32] delta -> one-hot [N,128]
      def exp_scat(b, i, u32):
        sub = i % 4
        oh = jax.nn.one_hot(sub, 4, dtype=u32.dtype)
        full = jnp.einsum("ns,nr->nrs", u32, oh).reshape(-1, 128)
        return b.at[i // 4].add(full, mode="drop")

      scat2 = jax.jit(exp_scat, donate_argnums=(0,))
      dt = time_donated(scat2, fresh(), (ids, upd32))
      print(f"rows=2^{rows_log} {dist:7s} fused-expand [N,32]: "
            f"{dt * 1e3:7.2f} ms  {dt / N * 1e9:6.2f} ns/row", flush=True)

      # sorted uniform ids (locality effect)
      ids_sorted = jnp.sort(ids)
      dt = time_donated(scat, fresh(), (ids_sorted, upd))
      print(f"rows=2^{rows_log} {dist:7s} sorted  [N,128]: "
            f"{dt * 1e3:7.2f} ms  {dt / N * 1e9:6.2f} ns/row", flush=True)


if __name__ == "__main__":
  main()
