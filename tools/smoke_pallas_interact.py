"""Real-TPU smoke test for the fused Pallas interaction kernels.

Checks the per-part fwd/bwd kernels (the DLRM hot path,
`ops/pallas_interact.py`) against the XLA matmul-form `_tril_products`
ON THE REAL CHIP at the bench feature shape (F=27, D=128) — interpret
mode covers semantics (tests/test_pallas_interact.py); this validates
the Mosaic lowering itself (the VMEM concat/scatter + batched MXU dots).

Run: python tools/smoke_pallas_interact.py   (also run by bench.py smoke)
Exit code 0 = pass.
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np

from distributed_embeddings_tpu.models.dlrm import _tril_select_np
from distributed_embeddings_tpu.ops.pallas_interact import (
    interact_parts_bwd,
    interact_parts_fwd,
    xla_reference,
)

F, D, B = 27, 128, 1024


def _xla_reference(flat, f, k):
  m_np, _ = _tril_select_np(f, k)
  return xla_reference(flat, m_np, f)


def main():
  if jax.default_backend() == "cpu":
    print("pallas interact smoke skipped: no TPU backend")
    return
  rng = np.random.default_rng(5)
  parts = [jnp.asarray(rng.standard_normal((B, D)) * 0.3, jnp.bfloat16)
           for _ in range(F)]
  m_np, _ = _tril_select_np(F, -1)
  failed = []

  got = jax.jit(interact_parts_fwd)(parts, jnp.asarray(m_np, jnp.bfloat16))
  flat = jnp.concatenate(parts, axis=1)
  want, vjp = jax.vjp(lambda y: _xla_reference(y, F, -1), flat)
  err = float(jnp.max(jnp.abs(got - want)))
  scale = float(jnp.max(jnp.abs(want)))
  ok = err <= 2e-2 * max(scale, 1.0)
  print(f"interact fwd vs XLA form           : "
        f"{'OK' if ok else 'FAIL'} (max err {err:.2e}, scale {scale:.1f})")
  if not ok:
    failed.append("fwd")

  d_acts = jnp.asarray(rng.standard_normal(want.shape), jnp.float32)
  (want_flat,) = vjp(d_acts)
  m3t = jnp.asarray(np.swapaxes(m_np, 1, 2), jnp.bfloat16)
  got_parts = jax.jit(interact_parts_bwd)(d_acts, parts, m3t)
  werr = 0.0
  for p in range(F):
    w = np.asarray(want_flat[:, p * D:(p + 1) * D], np.float32)
    g = np.asarray(got_parts[p], np.float32)
    werr = max(werr, float(np.max(np.abs(g - w))))
  wscale = float(np.max(np.abs(np.asarray(want_flat))))
  ok = werr <= 4e-2 * max(wscale, 1.0)
  print(f"interact bwd vs XLA vjp            : "
        f"{'OK' if ok else 'FAIL'} (max err {werr:.2e}, scale {wscale:.1f})")
  if not ok:
    failed.append("bwd")

  if failed:
    print(f"FAILED: {failed}")
    sys.exit(1)
  print("interact smoke PASS")


if __name__ == "__main__":
  main()
