"""On-chip DLRM convergence rehearsal: f32 vs AMP with the Pallas kernels.

The CPU rehearsal (`tests/test_dlrm_convergence.py`) exercises the XLA
paths only; this runs the same learnable task ON THE REAL CHIP at bench
shapes, where the fused interaction kernels, the Pallas RMW apply, and
the bf16 operand storage are all live — the hardware training-outcome
evidence that the kernel paths learn identically.

Usage: python tools/rehearse_dlrm.py [steps] [batch]
Prints per-path tail loss + rank-AUC.
"""

import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.models import DLRM, bce_loss
from distributed_embeddings_tpu.ops.packed_table import sgd_rule
from distributed_embeddings_tpu.training import (
    init_sparse_state_direct,
    make_sparse_eval_step,
    make_sparse_train_step,
)

CRITEO_1TB_VOCAB = [
    39884406, 39043, 17289, 7420, 20263, 3, 7120, 1543, 63, 38532951,
    2953546, 403346, 10, 2208, 11938, 155, 4, 976, 14, 39979771,
    25641295, 39664984, 585935, 12972, 108, 36
]

STEPS = int(sys.argv[1]) if len(sys.argv) > 1 else 300
BATCH = int(sys.argv[2]) if len(sys.argv) > 2 else 8192
VOCAB = [max(4, min(v // 256, 32000)) for v in CRITEO_1TB_VOCAB]
LR = 2.0


def _stream(seed):
  rng = np.random.default_rng(seed)
  scores = [rng.standard_normal(v).astype(np.float32) * 1.2 for v in VOCAB]

  def batch(step, n=BATCH):
    r = np.random.default_rng(seed * 100003 + step)
    cats = [r.integers(0, v, n).astype(np.int32) for v in VOCAB]
    logit = sum(s[c] for s, c in zip(scores, cats)) / np.sqrt(len(VOCAB))
    labels = (r.random(n) < 1.0 / (1.0 + np.exp(-logit))).astype(np.float32)
    numerical = r.standard_normal((n, 13)).astype(np.float32) * 0.1
    return (jnp.asarray(numerical), [jnp.asarray(c) for c in cats],
            jnp.asarray(labels))

  return batch


def _rank_auc(scores, labels):
  order = np.argsort(scores)
  ranks = np.empty_like(order, dtype=np.float64)
  ranks[order] = np.arange(1, len(scores) + 1)
  pos = labels > 0.5
  n_pos, n_neg = pos.sum(), (~pos).sum()
  if n_pos == 0 or n_neg == 0:
    return 0.5
  return (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


def run(dtype, tag):
  stream = _stream(11)
  numerical, cats, labels = stream(0)
  rule = sgd_rule(LR)
  opt = optax.sgd(LR)
  model = DLRM(vocab_sizes=VOCAB, embedding_dim=128, world_size=1,
               dense_row_threshold=16, batch_hint=BATCH,
               compute_dtype=dtype)
  plan = DistEmbeddingStrategy(
      [dict(input_dim=v, output_dim=128, combiner=None) for v in VOCAB],
      1, "basic", dense_row_threshold=16, batch_hint=BATCH)
  dummy = [jnp.zeros((2, 128), jnp.float32) for _ in VOCAB]
  dense_params = model.init(jax.random.PRNGKey(0), numerical[:2],
                            [c[:2] for c in cats],
                            emb_acts=dummy)["params"]
  state = init_sparse_state_direct(plan, rule, dense_params, opt,
                                   jax.random.PRNGKey(1))
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, None,
                                state, (numerical, cats, labels),
                                donate=False)
  losses = []
  t0 = time.time()
  for i in range(STEPS):
    n_, c_, l_ = stream(i)
    state, loss = step(state, n_, c_, l_)
    if i % 50 == 0 or i >= STEPS - 25:
      losses.append(float(loss))
  n_eval = 4 * BATCH
  ev_num, ev_cats, ev_labels = stream(10_000, n=n_eval)
  ev = make_sparse_eval_step(model, plan, rule, None, state,
                             (ev_num, ev_cats, ev_labels))
  logits = np.asarray(jax.device_get(ev(state, ev_num, ev_cats)))
  auc = _rank_auc(logits, np.asarray(ev_labels))
  tail = float(np.mean(losses[-20:]))
  print(f"{tag:12s}: start {losses[0]:.4f} -> tail {tail:.4f}, "
        f"AUC {auc:.4f}  ({time.time() - t0:.0f}s)", flush=True)
  return tail, auc


def main():
  t_f32, a_f32 = run(jnp.float32, "f32")
  t_amp, a_amp = run(jnp.bfloat16, "amp(bf16)")
  ok = abs(t_f32 - t_amp) < 0.03 and abs(a_f32 - a_amp) < 0.03 \
      and min(a_f32, a_amp) > 0.65
  print(f"parity: tail d={abs(t_f32 - t_amp):.4f}, "
        f"AUC d={abs(a_f32 - a_amp):.4f} -> {'OK' if ok else 'FAIL'}")
  if not ok:
    sys.exit(1)


if __name__ == "__main__":
  main()
