"""Discriminate what bounds the XLA scatter-add at bench shapes.

Axes: buffer rows (row-bound vs buffer-bound), n_ids scaling,
unique_indices, id sortedness, width.

Usage: python tools/profile_scatter2.py
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

K = 8
W = 128


def timeit(name, fn, buf, *args):
  step = jax.jit(fn, donate_argnums=(0,))
  carry = step(buf, *args)
  jax.block_until_ready(carry)

  def run(n, carry):
    t0 = time.perf_counter()
    for _ in range(n):
      carry = step(carry, *args)
    float(carry[0, 0])
    return time.perf_counter() - t0, carry

  t1, carry = run(K, carry)
  t2, carry = run(2 * K, carry)
  dt = (t2 - t1) / K
  n = args[0].shape[0]
  print(f"{name:42s}: {dt * 1e3:8.2f} ms  {dt / n * 1e9:6.1f} ns/row",
        flush=True)
  return carry


def main():
  rng = np.random.default_rng(0)
  key = jax.random.PRNGKey(0)
  n_ids = 9 * 65536

  def scatter(buf, ids, delta):
    return buf.at[ids].add(delta, mode="drop")

  def scatter_uniq(buf, ids, delta):
    return buf.at[ids].add(delta, mode="drop", unique_indices=True)

  delta = jax.random.normal(key, (n_ids, W), jnp.float32)

  for rows_log in (24.5, 23.5, 22, 20, 18, 16):
    rows = int(2 ** rows_log)
    buf = jnp.zeros((rows, W), jnp.float32)
    ids = jnp.asarray(rng.integers(0, rows, n_ids), jnp.int32)
    buf = timeit(f"scatter 590k -> 2^{rows_log:g} rows", scatter, buf, ids,
                 delta)
    del buf

  rows = int(2 ** 23.5)
  buf = jnp.zeros((rows, W), jnp.float32)
  ids = jnp.asarray(rng.integers(0, rows, n_ids), jnp.int32)
  buf = timeit("scatter unique_indices=True", scatter_uniq, buf, ids, delta)
  ids_sorted = jnp.sort(ids)
  buf = timeit("scatter sorted + unique", scatter_uniq, buf, ids_sorted,
               delta)

  # n_ids scaling at fixed buffer
  for n_log in (16, 18, 20):
    n = 1 << n_log
    ids_n = jnp.asarray(rng.integers(0, rows, n), jnp.int32)
    delta_n = jax.random.normal(key, (n, W), jnp.float32)
    buf = timeit(f"scatter 2^{n_log} ids -> 2^23.5 rows", scatter, buf,
                 ids_n, delta_n)

  # width scaling: is it per-row or per-byte?
  for w in (8, 32, 512):
    bufw = jnp.zeros((rows, w), jnp.float32)
    deltaw = jax.random.normal(key, (n_ids, w), jnp.float32)
    bufw = timeit(f"scatter 590k width {w}", scatter, bufw, ids, deltaw)
    del bufw

  # f32 vs bf16 updates
  bufh = jnp.zeros((rows, W), jnp.bfloat16)
  deltah = delta.astype(jnp.bfloat16)
  bufh = timeit("scatter 590k bf16", scatter, bufh, ids, deltah)


if __name__ == "__main__":
  main()
