"""Streaming chaos: SIGKILL every delta-chain participant, relaunch, verify.

`tools/chaos_kill.py` proved the TRAINING loop survives real SIGKILLs;
this driver proves the ONLINE-LEARNING loop does — the trainer→serving
delta chain survives the death of any participant without ever serving
wrong rows:

1. **reference**: one unkilled trainer runs a fixed stream, publishing a
   base + row-granular deltas, and dumps its final state in serve
   layout — the byte-exact target every killed cycle must reproduce;
2. **trainer kill mid-publish** (``delta_seal`` site): a real SIGKILL
   during a delta's seal leaves a torn ``delta_<seq>.tmp``; the
   relaunch auto-resumes through ``ResilientTrainer(stream=publisher)``
   — the checkpoint manifest's ``stream`` section restores the chain
   state + generation stamps and ``publisher.attach()`` re-joins the
   existing chain from the pubdir tail (NO re-root: the base
   fingerprint is unchanged and every delta's ``base_fingerprint``
   stays sha256-continuous across the kill); rows touched between the
   restored snapshot and the kill are re-published as a superset delta;
3. **trainer kill between steps after a publish** (``sigkill`` marker):
   exercises tail ADOPTION — the restored snapshot predates deltas the
   killed lifetime already published, so attach validates and adopts
   them and force-re-stamps their rows;
4. **subscriber kill mid-promote** (``delta_promote`` site): a fresh
   cold-start relaunch replays the chain and converges to the same
   bytes;
5. **compactor kill mid-fold** (``compact_fold`` site): the torn
   ``base.compact.tmp`` never touches the live base (still verifies);
   the relaunch compacts through ``final_seq - 1`` and a cold-start
   subscriber then loads compacted base + the one-delta tail — same
   bytes again, with the folded/GC'd prefix gone.

Verdict via ``telemetry.emit_verdict`` (exit 0 iff every cycle passed).
``--smoke`` is the ``make verify`` tier: 2 worker subprocesses (the
mid-publish kill + relaunch), subscriber/compaction checks in-driver.
The full run is ``make chaos-stream``; the long variant is
``@pytest.mark.slow`` in ``tests/test_streaming.py``.
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if __name__ == "__main__":  # standalone: build the virtual CPU mesh
  flags = os.environ.get("XLA_FLAGS", "")
  if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
  os.environ.setdefault("JAX_PLATFORMS", "cpu")
  sys.path.insert(0, _REPO)

VOCAB = [500, 300, 150]
WIDTHS = [16, 8, 8]  # two widths -> >= 2 fused classes (compact_fold
                     # fires per class, so the mid-fold kill can land
                     # between them)
HOTNESS = [2, 1, 1]
GLOBAL_BATCH = 16


def _make_plan(world):
  from distributed_embeddings_tpu.layers.embedding import TableConfig
  from distributed_embeddings_tpu.layers.planner import (
      DistEmbeddingStrategy,
  )
  tables = [TableConfig(v, w, combiner="sum")
            for v, w in zip(VOCAB, WIDTHS)]
  return DistEmbeddingStrategy(tables, world, "memory_balanced",
                               dense_row_threshold=0,
                               input_hotness=HOTNESS)


def _batches(n, seed=11):
  """World-independent deterministic stream (multi-hot, PAD holes)."""
  import numpy as np
  from distributed_embeddings_tpu.parallel.lookup_engine import PAD_ID
  rng = np.random.default_rng(seed)
  out = []
  for _ in range(n):
    cats = []
    for v, h in zip(VOCAB, HOTNESS):
      x = rng.integers(0, v, (GLOBAL_BATCH, h)).astype(np.int32)
      x[rng.random(x.shape) < 0.2] = PAD_ID
      cats.append(x)
    numerical = rng.standard_normal((GLOBAL_BATCH, 4)).astype(np.float32)
    labels = rng.integers(0, 2, GLOBAL_BATCH).astype(np.float32)
    out.append((numerical, cats, labels))
  return out


class _ActsModel:
  """Embedding activations straight through — the serve-state bytes are
  the whole comparison surface."""

  def apply(self, variables, numerical, cats, emb_acts=None):
    import jax.numpy as jnp
    del variables, numerical, cats
    return jnp.concatenate(list(emb_acts), axis=-1)


def _loss(preds, labels):
  import jax.numpy as jnp
  return jnp.mean((jnp.sum(preds, axis=-1) - labels) ** 2)


def _dump_state_digest(out_path, plan, rule, state, quantize):
  """Final train state in serve layout (freeze codecs), byte-comparable
  across processes: per class the concatenated disk-form blocks, plus
  the flat dense parts."""
  import numpy as np
  from distributed_embeddings_tpu.checkpoint import _flatten_with_paths
  from distributed_embeddings_tpu.serving.export import freeze
  frozen = freeze(plan, rule, state, quantize=quantize)
  flat = {}
  for name, blocks in frozen.device_blocks.items():
    flat["serve/" + name] = frozen.meta[name].to_disk(
        np.concatenate(blocks))
  for part, tree in (("dense", frozen.dense),
                     ("emb_dense", frozen.emb_dense)):
    for k, v in _flatten_with_paths(tree).items():
      flat[f"{part}/{k}"] = v
  np.savez(out_path, **flat)


def _dump_engine_digest(out_path, sub):
  """A subscriber's folded serve state in the same digest layout."""
  import numpy as np
  from distributed_embeddings_tpu.checkpoint import _flatten_with_paths
  eng = sub.engine
  flat = {}
  for name, buf in eng.state["serve"].items():
    flat["serve/" + name] = eng.meta[name].to_disk(np.asarray(buf))
  for part in ("dense", "emb_dense"):
    for k, v in _flatten_with_paths(eng.state[part]).items():
      flat[f"{part}/{k}"] = v
  np.savez(out_path, **flat)


def _digests_equal(a_path, b_path):
  import numpy as np
  with np.load(a_path) as za, np.load(b_path) as zb:
    a = {k: np.asarray(v) for k, v in za.items()}
    b = {k: np.asarray(v) for k, v in zb.items()}
  if set(a) != set(b):
    return False
  return all(np.array_equal(a[k].view(np.uint8), b[k].view(np.uint8))
             for k in a)


# ---------------------------------------------------------------------------
# workers: one participant process lifetime each
# ---------------------------------------------------------------------------


def run_trainer(root, pubdir, world, steps, publish_every=2,
                snapshot_every=2, quantize="f32", kill_site="",
                kill_event=-1, digest_path=""):
  """One trainer lifetime: auto-resume + ATTACH, observe/step/publish."""
  import jax
  import numpy as np
  import optax

  from distributed_embeddings_tpu import telemetry
  from distributed_embeddings_tpu.layers.dist_model_parallel import (
      set_weights,
  )
  from distributed_embeddings_tpu.ops.packed_table import sparse_rule
  from distributed_embeddings_tpu.parallel import create_mesh
  from distributed_embeddings_tpu.resilience import (
      FaultInjector,
      faultinject,
  )
  from distributed_embeddings_tpu.resilience.trainer import ResilientTrainer
  from distributed_embeddings_tpu.streaming import (
      DeltaPublisher,
      RowGenerationTracker,
  )
  from distributed_embeddings_tpu.training import (
      init_sparse_state,
      make_sparse_train_step,
      shard_batch,
      shard_params,
  )

  plan = _make_plan(world)
  rng = np.random.default_rng(0)
  weights = [rng.standard_normal((v, w)).astype(np.float32) * 0.1
             for v, w in zip(VOCAB, WIDTHS)]
  params = {"embeddings": {k: np.asarray(v) for k, v in
                           set_weights(plan, weights).items()}}
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.sgd(0.01)
  mesh = create_mesh(world) if world > 1 else None
  state = shard_params(init_sparse_state(plan, params, rule, opt), mesh)
  stream = _batches(steps)
  step = make_sparse_train_step(_ActsModel(), plan, _loss, opt, rule,
                                mesh, state, stream[0], donate=False,
                                guard=True)

  tracker = RowGenerationTracker(plan)
  publisher = DeltaPublisher(pubdir, plan, rule, tracker,
                             quantize=quantize)
  t = ResilientTrainer(step, state, plan, rule, root, mesh=mesh,
                       snapshot_every=snapshot_every, stream=publisher)
  if publisher.fingerprint is None:
    # fresh start (or a pre-chain checkpoint): root the chain, then
    # snapshot immediately so any later kill can ATTACH instead of
    # re-rooting
    publisher.publish_base(t.state)
    t.snapshot()

  inj = FaultInjector()
  if kill_site:
    inj.kill_at(kill_site, kill_event)
  with faultinject.injected(inj):
    for i in range(t.consumed, steps):
      faultinject.fire(faultinject.SIGKILL_SITE, batch=i)
      publisher.observe_batch(stream[i][1])
      t.step(*shard_batch(stream[i], mesh))
      if (i + 1) % publish_every == 0:
        publisher.publish_delta(t.state)
    publisher.publish_delta(t.state)  # ship any tail rows
    t.snapshot()

  reg = telemetry.get_registry()
  summary = {
      "world": world,
      "steps": t.step_count,
      "consumed": t.consumed,
      "final_seq": publisher.seq,
      "final_fingerprint": publisher.fingerprint,
      "base_fingerprint": publisher.base_fingerprint,
      "resumed_from": t.resumed_from,
      "attaches": reg.counter("stream/attaches").value,
      "attach_deltas_adopted":
          reg.counter("stream/attach_deltas_adopted").value,
  }
  if digest_path:
    _dump_state_digest(digest_path, plan, rule, t.state, quantize)
  with open(os.path.join(pubdir, "chain_done.json"), "w") as f:
    json.dump(summary, f)
  return summary


def run_subscriber(pubdir, world, out_path, kill_site="", kill_event=-1,
                   subscriber_id="chaos-sub", max_polls=500):
  """One subscriber lifetime: cold-start, fold to the chain head, dump
  the folded state digest."""
  import time

  from distributed_embeddings_tpu import telemetry
  from distributed_embeddings_tpu.parallel import create_mesh
  from distributed_embeddings_tpu.resilience import (
      FaultInjector,
      faultinject,
  )
  from distributed_embeddings_tpu.streaming import (
      DeltaSubscriber,
      artifact_bytes,
      delta_dirname,
  )

  with open(os.path.join(pubdir, "chain_done.json")) as f:
    done = json.load(f)
  plan = _make_plan(world)
  mesh = create_mesh(world) if world > 1 else None
  reg = telemetry.MetricsRegistry()
  inj = FaultInjector()
  if kill_site:
    inj.kill_at(kill_site, kill_event)
  with faultinject.injected(inj):
    sub = DeltaSubscriber.from_artifact(
        _ActsModel(), plan, pubdir, mesh=mesh, telemetry=reg,
        subscriber_id=subscriber_id)
    start_seq = sub.applied_seq  # compacted bases anchor mid-chain
    polls = 0
    while (sub.applied_seq < done["final_seq"]
           or sub.fingerprint != done["final_fingerprint"]):
      sub.poll_once()
      polls += 1
      if polls >= max_polls:
        break
      time.sleep(0.01)
  folded_bytes = artifact_bytes(os.path.join(pubdir, "base")) + sum(
      artifact_bytes(os.path.join(pubdir, delta_dirname(s)))
      for s in range(start_seq + 1, sub.applied_seq + 1))
  _dump_engine_digest(out_path, sub)
  summary = {
      "applied_seq": sub.applied_seq,
      "start_seq": start_seq,
      "converged": sub.fingerprint == done["final_fingerprint"],
      "refusals": reg.counter("stream/deltas_refused").value,
      "rebases": reg.counter("stream/rebases").value,
      "cold_start_bytes": folded_bytes,
      "last_refusal": sub.last_refusal,
  }
  with open(out_path + ".summary", "w") as f:
    json.dump(summary, f)
  return summary


def run_compactor(pubdir, through=None, kill_site="", kill_event=-1):
  from distributed_embeddings_tpu.resilience import (
      FaultInjector,
      faultinject,
  )
  from distributed_embeddings_tpu.streaming import DeltaCompactor

  inj = FaultInjector()
  if kill_site:
    inj.kill_at(kill_site, kill_event)
  with faultinject.injected(inj):
    res = DeltaCompactor(pubdir).compact_once(through_seq=through)
  with open(os.path.join(pubdir, "compact_done.json"), "w") as f:
    json.dump(res, f)
  return res


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def _spawn(role, pubdir, world, extra_args=()):
  env = dict(os.environ)
  env.setdefault("JAX_PLATFORMS", "cpu")
  flags = env.get("XLA_FLAGS", "")
  if "xla_force_host_platform_device_count" not in flags:
    env["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
  cmd = [sys.executable, os.path.abspath(__file__), "--worker", role,
         "--pubdir", pubdir, "--world", str(world)] + list(extra_args)
  return subprocess.run(cmd, cwd=_REPO, env=env).returncode


def _chain_links_continuous(pubdir):
  """Every published delta's ``base_fingerprint`` equals the sha256
  manifest fingerprint of its predecessor — the no-re-root proof."""
  from distributed_embeddings_tpu.checkpoint import (
      manifest_fingerprint,
      read_manifest,
  )
  from distributed_embeddings_tpu.streaming import (
      chain_anchor,
      delta_dirname,
      published_delta_seqs,
  )
  base = os.path.join(pubdir, "base")
  fp = manifest_fingerprint(base)
  anchor_seq, prev, _root = chain_anchor(read_manifest(base), fp)
  for seq in published_delta_seqs(pubdir):
    if seq <= anchor_seq:
      return False  # a folded delta survived GC'ing AND the base moved
    dpath = os.path.join(pubdir, delta_dirname(seq))
    if read_manifest(dpath).get("base_fingerprint") != prev:
      return False
    prev = manifest_fingerprint(dpath)
  return True


def run_chaos_stream(steps=12, world=2, publish_every=2, quantize="f32",
                     smoke=False, verbose=False):
  from distributed_embeddings_tpu import checkpoint

  work = tempfile.mkdtemp(prefix="chaos_stream_")
  result = {"steps": steps, "world": world, "quantize": quantize,
            "cycles": {}}

  def dirs(name):
    d = os.path.join(work, name)
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, "ckpts"), os.path.join(d, "pub")

  t_args = ["--steps", str(steps), "--publish-every", str(publish_every),
            "--quantize", quantize]

  # ---- reference: one unkilled trainer lifetime --------------------------
  ref_root, ref_pub = dirs("ref")
  ref_digest = os.path.join(work, "ref", "digest.npz")
  rc = _spawn("trainer", ref_pub, world,
              t_args + ["--root", ref_root, "--digest", ref_digest])
  ref_ok = rc == 0 and os.path.exists(ref_digest)
  result["cycles"]["ref"] = {"rc": rc, "ok": ref_ok}
  if not ref_ok:
    result["ok"] = False
    return result

  # ---- cycle A: trainer SIGKILLed mid-publish (torn delta tmp) -----------
  root, pub = dirs("mid_publish")
  digest = os.path.join(work, "mid_publish", "digest.npz")
  rc1 = _spawn("trainer", pub, world,
               t_args + ["--root", root, "--kill-site", "delta_seal",
                         "--kill-event", "7"])
  torn = any(n.startswith("delta_") and n.endswith(".tmp")
             for n in os.listdir(pub))
  base_fp_kill = checkpoint.manifest_fingerprint(
      os.path.join(pub, "base"))
  rc2 = _spawn("trainer", pub, world,
               t_args + ["--root", root, "--digest", digest])
  with open(os.path.join(pub, "chain_done.json")) as f:
    done_a = json.load(f)
  base_fp_after = checkpoint.manifest_fingerprint(
      os.path.join(pub, "base"))
  result["cycles"]["mid_publish"] = {
      "killed_rc": rc1, "relaunch_rc": rc2, "torn_tmp_present": torn,
      "summary": done_a,
      "no_reroot": base_fp_kill == base_fp_after,
      "chain_continuous": _chain_links_continuous(pub),
      "state_matches_ref": _digests_equal(digest, ref_digest),
      "ok": rc1 == -signal.SIGKILL and rc2 == 0 and torn
            and base_fp_kill == base_fp_after
            and _chain_links_continuous(pub)
            and _digests_equal(digest, ref_digest)}

  # ---- cycle B: trainer SIGKILLed after a publish (tail ADOPTION) --------
  if not smoke:
    root, pub2 = dirs("adopt_tail")
    digest2 = os.path.join(work, "adopt_tail", "digest.npz")
    rc1 = _spawn("trainer", pub2, world,
                 t_args + ["--root", root, "--kill-site", "sigkill",
                           "--kill-event", "6"])
    rc2 = _spawn("trainer", pub2, world,
                 t_args + ["--root", root, "--digest", digest2])
    with open(os.path.join(pub2, "chain_done.json")) as f:
      done_b = json.load(f)
    result["cycles"]["adopt_tail"] = {
        "killed_rc": rc1, "relaunch_rc": rc2, "summary": done_b,
        "chain_continuous": _chain_links_continuous(pub2),
        "state_matches_ref": _digests_equal(digest2, ref_digest),
        "ok": rc1 == -signal.SIGKILL and rc2 == 0
              and done_b["attaches"] >= 1
              and done_b["attach_deltas_adopted"] >= 1
              and _chain_links_continuous(pub2)
              and _digests_equal(digest2, ref_digest)}

  # ---- cycle C: subscriber SIGKILLed mid-promote, cold relaunch ----------
  sub_out = os.path.join(work, "mid_publish", "sub_digest.npz")
  if smoke:
    # in-driver cold fold (no kill): still proves the post-kill chain
    # folds to the reference bytes
    summary = run_subscriber(pub, world, sub_out,
                             subscriber_id="smoke-sub")
    rc1 = rc2 = None  # the SIGKILL half is the full tier's job
    killed_ok = True
  else:
    rc1 = _spawn("subscriber", pub, world,
                 ["--out", sub_out, "--kill-site", "delta_promote",
                  "--kill-event", "1", "--sub-id", "chaos-sub-a"])
    killed_ok = rc1 == -signal.SIGKILL
    rc2 = _spawn("subscriber", pub, world,
                 ["--out", sub_out, "--sub-id", "chaos-sub-a"])
    killed_ok = killed_ok and rc2 == 0
    with open(sub_out + ".summary") as f:
      summary = json.load(f)
  result["cycles"]["sub_promote"] = {
      "killed_rc": rc1, "relaunch_rc": rc2, "summary": summary,
      "state_matches_ref": _digests_equal(sub_out, ref_digest),
      "ok": killed_ok and summary["converged"]
            and summary["refusals"] == 0
            and _digests_equal(sub_out, ref_digest)}
  full_chain_bytes = summary["cold_start_bytes"]

  # ---- cycle D: compactor SIGKILLed mid-fold, relaunch, cold base+tail ---
  through = done_a["final_seq"] - 1
  if smoke:
    from distributed_embeddings_tpu.resilience import faultinject
    from distributed_embeddings_tpu.streaming import DeltaCompactor
    inj = faultinject.FaultInjector().crash_after("compact_fold", 1)
    crashed = False
    try:
      with faultinject.injected(inj):
        DeltaCompactor(pub).compact_once(through_seq=through)
    except faultinject.InjectedCrash:
      crashed = True
    # smoke substitutes an injected crash for the real SIGKILL (one
    # process, no relaunch); the full tier exercises the real kill
    rc1 = -signal.SIGKILL if crashed else 0
  else:
    rc1 = _spawn("compactor", pub, world,
                 ["--through", str(through), "--kill-site",
                  "compact_fold", "--kill-event", "1"])
  torn_tmp = os.path.isdir(os.path.join(pub, "base.compact.tmp"))
  base_still_valid = not checkpoint.verify(os.path.join(pub, "base"))
  if smoke:
    res = run_compactor(pub, through=through)
    rc2 = 0
  else:
    rc2 = _spawn("compactor", pub, world, ["--through", str(through)])
    with open(os.path.join(pub, "compact_done.json")) as f:
      res = json.load(f)
  compacted = (checkpoint.read_manifest(os.path.join(pub, "base"))
               .get("stream", {}).get("compacted"))
  cold_out = os.path.join(work, "mid_publish", "cold_digest.npz")
  cold = run_subscriber(pub, world, cold_out,
                        subscriber_id="chaos-cold")
  result["cycles"]["compact"] = {
      "killed_rc": rc1, "relaunch_rc": rc2,
      "torn_tmp_present": torn_tmp,
      "base_valid_after_kill": base_still_valid,
      "result": res, "cold_summary": cold,
      "cold_state_matches_ref": _digests_equal(cold_out, ref_digest),
      "replay_bytes_full_chain": full_chain_bytes,
      "replay_bytes_base_tail": cold["cold_start_bytes"],
      "ok": rc1 == -signal.SIGKILL and rc2 == 0 and torn_tmp
            and base_still_valid
            and bool(compacted
                     and int(compacted["through_seq"]) == through)
            and cold["start_seq"] == through
            and cold["converged"] and cold["refusals"] == 0
            and _digests_equal(cold_out, ref_digest)}

  # ---- cycle E: a refused delta trips the flight recorder ----------------
  # Plant an out-of-order delta past the chain head (a copy of the head
  # delta under the next seq — its manifest seq and base_fingerprint
  # both break the link) and run one more in-driver subscriber: it
  # converges through the real chain, REFUSES the bogus link naming the
  # field, and the refusal trips the installed flight recorder, whose
  # debug bundle is the verdict's artifact.
  import shutil as _shutil

  from distributed_embeddings_tpu import telemetry
  from distributed_embeddings_tpu.streaming import delta_dirname

  flight_dir = os.path.join(work, "flight")
  recorder = telemetry.install_flight_recorder(
      telemetry.FlightRecorder(dir=flight_dir, min_interval_s=0.0))
  try:
    with open(os.path.join(pub, "chain_done.json")) as f:
      done_final = json.load(f)
    head = int(done_final["final_seq"])
    _shutil.copytree(os.path.join(pub, delta_dirname(head)),
                     os.path.join(pub, delta_dirname(head + 1)))
    fl_out = os.path.join(work, "mid_publish", "flight_digest.npz")
    fl = run_subscriber(pub, world, fl_out,
                        subscriber_id="chaos-flight")
  finally:
    telemetry.uninstall_flight_recorder()
  bundle_reason = None
  if recorder.bundles:
    with open(recorder.bundles[0]) as f:
      bundle_reason = json.load(f)["reason"]
  result["cycles"]["refusal_flight"] = {
      "refusals": fl["refusals"], "last_refusal": fl["last_refusal"],
      "converged": fl["converged"],
      "flight_bundles": len(recorder.bundles),
      "bundle_reason": bundle_reason,
      "ok": fl["converged"] and fl["refusals"] >= 1
            and len(recorder.bundles) >= 1
            and bundle_reason == "refusal"}

  result["ok"] = all(c["ok"] for c in result["cycles"].values())
  if verbose:
    print(json.dumps(result, indent=1))
  return result


def main(argv=None) -> int:
  p = argparse.ArgumentParser(description=__doc__)
  p.add_argument("--worker", default="",
                 choices=["", "trainer", "subscriber", "compactor"])
  p.add_argument("--root", default="")
  p.add_argument("--pubdir", default="")
  p.add_argument("--out", default="")
  p.add_argument("--digest", default="")
  p.add_argument("--world", type=int, default=2)
  p.add_argument("--steps", type=int, default=12)
  p.add_argument("--publish-every", type=int, default=2)
  p.add_argument("--quantize", default="f32",
                 choices=["f32", "int8", "fp8"])
  p.add_argument("--kill-site", default="")
  p.add_argument("--kill-event", type=int, default=-1)
  p.add_argument("--through", type=int, default=-1)
  p.add_argument("--sub-id", default="chaos-sub")
  p.add_argument("--smoke", action="store_true")
  args = p.parse_args(argv)
  if args.worker == "trainer":
    run_trainer(args.root, args.pubdir, args.world, args.steps,
                publish_every=args.publish_every,
                quantize=args.quantize, kill_site=args.kill_site,
                kill_event=args.kill_event, digest_path=args.digest)
    return 0
  if args.worker == "subscriber":
    run_subscriber(args.pubdir, args.world, args.out,
                   kill_site=args.kill_site, kill_event=args.kill_event,
                   subscriber_id=args.sub_id)
    return 0
  if args.worker == "compactor":
    run_compactor(args.pubdir,
                  through=None if args.through < 0 else args.through,
                  kill_site=args.kill_site, kill_event=args.kill_event)
    return 0

  from distributed_embeddings_tpu.telemetry import emit_verdict

  res = run_chaos_stream(
      steps=args.steps, world=args.world,
      publish_every=args.publish_every,
      quantize=args.quantize if not args.smoke else "f32",
      smoke=args.smoke)
  return emit_verdict("chaos-stream", res)


if __name__ == "__main__":
  sys.exit(main())
