"""Quantify the narrow-minor-dim tile-padding tax on v5e.

Every [n, 16]/[n, 32] f32 intermediate is tile-padded to 128 lanes. If the
tax is real, a full-phys-width (128-lane) pipeline for narrow classes is
the remaining Tiny win; if not, the step is at its row-op floor.

Usage: PYTHONPATH=/root/repo:/root/.axon_site python -u tools/profile_padding_tax.py
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

N = 2_883_584
K_REPS = 6


def _sync(x):
  leaf = jax.tree_util.tree_leaves(x)[0]
  float(jnp.asarray(leaf).ravel()[0])


def timeit(name, fn, *args, n_norm=None):
  step = jax.jit(fn)
  carry = step(jnp.zeros((), jnp.float32), *args)
  _sync(carry)

  def run(n, carry):
    t0 = time.perf_counter()
    for _ in range(n):
      carry = step(carry, *args)
    _sync(carry)
    return time.perf_counter() - t0, carry

  _, carry = run(1, carry)
  t1, carry = run(K_REPS, carry)
  t2, carry = run(2 * K_REPS, carry)
  dt = (t2 - t1) / K_REPS
  per = f"  {dt / n_norm * 1e9:6.1f} ns/row" if n_norm else ""
  print(f"{name:56s}: {dt * 1e3:8.2f} ms{per}", flush=True)


def main():
  rng = np.random.default_rng(0)

  # elementwise chain on [N, w]: 6 ops (mimics the adagrad rule math)
  for w in (16, 32, 128):
    x = jnp.asarray(rng.standard_normal((N, w)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((N, w)).astype(np.float32))

    def rule_math(c, a, b):
      a = a + jnp.minimum(c, 0.0)
      g2 = a * a
      acc = b + g2
      scaled = jnp.where(acc > 0, a * jax.lax.rsqrt(acc + 1e-7), 0.0)
      d = jnp.concatenate([-0.01 * scaled, g2], axis=-1)
      return c + jnp.tanh(jnp.sum(d) * 1e-6) * 0 + jnp.float32(0)

    timeit(f"adagrad rule math on [N,{w}] (+concat)", rule_math, x, y,
           n_norm=N)
    del x, y

  # combine: [G, 10, 32] -> sum axis 1 -> [G, 32]
  g10 = jnp.asarray(
      rng.standard_normal((65536, 10, 32)).astype(np.float32))

  def combine(c, r):
    r = r + jnp.minimum(c, 0.0)
    z = jnp.sum(r, axis=1)
    return c + jnp.tanh(jnp.sum(z) * 1e-6) * 0 + jnp.float32(0)

  timeit("combine sum [64k,10,32]->[64k,32]", combine, g10, n_norm=655360)
  del g10

  g10w = jnp.asarray(
      rng.standard_normal((65536, 10, 128)).astype(np.float32))
  timeit("combine sum [64k,10,128]->[64k,128]", combine, g10w,
         n_norm=655360)
  del g10w

  # broadcast of dz over hotness: [G, 32] -> [G*10, 32] (apply's g exp)
  dz = jnp.asarray(rng.standard_normal((65536, 32)).astype(np.float32))

  def bcast(c, d):
    d = d + jnp.minimum(c, 0.0)
    g = jnp.broadcast_to(d[:, None, :], (65536, 10, 32)).reshape(-1, 32)
    return c + jnp.tanh(jnp.sum(g * g) * 1e-6) * 0 + jnp.float32(0)

  timeit("dz broadcast [64k,32]->[655k,32] (+square)", bcast, dz,
         n_norm=655360)


if __name__ == "__main__":
  main()
