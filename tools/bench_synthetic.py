"""Synthetic model zoo step-time benchmark on the real chip.

Counterpart of the reference's synthetic benchmark
(`/root/reference/examples/benchmarks/synthetic_models/README.md:71-75`,
1xA100 column): one full fused train step (Adagrad) at global batch 65536.

Usage: python tools/bench_synthetic.py [model] [batch] [steps] [vocab_scale]
                                       [micro_batches]

``micro_batches`` > 1 runs the bounded-memory accumulation mode
(make_sparse_train_step(micro_batches=n)): per-occurrence temporaries are
capped at 1/n of the one-shot step, which is what lets Large (6,312
occurrences/sample) step on the 16 GiB chip at all.
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.models import (
    SYNTHETIC_MODELS,
    SyntheticModel,
    bce_loss,
    expand_tables,
    generate_batch,
)
from distributed_embeddings_tpu.ops.packed_table import adagrad_rule
from distributed_embeddings_tpu.training import (
    init_sparse_state_direct,
    make_sparse_train_step,
)

A100_1X_MS = {"tiny": 24.433, "small": 67.355}  # reference README:71-72
# medium/large never fit one GPU; the reference's smallest configs are
# 8xA100 at 63.393 ms (README:73) and 32xA100 at 67.57 ms (README:74) =>
# one A100's share is an equivalent per-chip step time of N * t_N
A100_PER_CHIP_EQ_MS = {"medium": 8 * 63.393, "large": 32 * 67.57}

MODEL = sys.argv[1] if len(sys.argv) > 1 else "tiny"
BATCH = int(sys.argv[2]) if len(sys.argv) > 2 else 65536
STEPS = int(sys.argv[3]) if len(sys.argv) > 3 else 12
# vocab scale for models that exceed one chip's HBM (same representativeness
# argument as bench.py: per-step indexed-row cost is vocab-size-insensitive)
SCALE = float(sys.argv[4]) if len(sys.argv) > 4 else 1.0
MICRO = int(sys.argv[5]) if len(sys.argv) > 5 else 1


def main():
  cfg = SYNTHETIC_MODELS[MODEL]
  tables, tmap, hotness = expand_tables(cfg)
  model = SyntheticModel(config=cfg, world_size=1)
  thr = model.dense_row_threshold
  if SCALE != 1.0:
    import dataclasses
    tables = [dataclasses.replace(t, input_dim=max(8, int(t.input_dim * SCALE)))
              for t in tables]
    # scale the dense/sparse split point too, or shrinking vocabularies
    # silently reclassifies sparse tables onto the MXU one-hot path and
    # the scaled run measures a different workload
    thr = max(8, int(thr * SCALE))
  plan = DistEmbeddingStrategy(tables, 1, "basic", input_table_map=tmap,
                               dense_row_threshold=thr,
                               input_hotness=hotness, batch_hint=BATCH)

  batches = []
  for i in range(2):
    numerical, cats, labels = generate_batch(cfg, BATCH, alpha=1.05, seed=i)
    # ids are drawn against the UNSCALED vocab; fold into the scaled one
    # with modulo (clamping would pile the tail mass onto the last row and
    # inflate the duplicate rate the apply cost depends on)
    cats = [(c % tables[t].input_dim if SCALE != 1.0
             else np.minimum(c, tables[t].input_dim - 1)).astype(np.int32)
            for c, t in zip(cats, tmap)]
    cats = [jnp.asarray(c if h > 1 else c[:, 0])
            for c, h in zip(cats, hotness)]
    batches.append((jnp.asarray(numerical), cats, jnp.asarray(labels)))

  dense_opt = optax.adagrad(0.01)
  rule = adagrad_rule(0.01)
  dummy_acts = [jnp.zeros((2, tables[t].output_dim), jnp.float32)
                for t in tmap]
  small_cats = [c[:2] for c in batches[0][1]]
  dense_params = model.init(jax.random.PRNGKey(0), batches[0][0][:2],
                            small_cats, emb_acts=dummy_acts)["params"]

  # AOT compile from abstract shapes BEFORE the big allocations
  state_avals = jax.eval_shape(
      lambda: init_sparse_state_direct(plan, rule, dense_params, dense_opt,
                                       jax.random.PRNGKey(1)))
  # BENCH_EXACT=1: reference dedup semantics (sort-based exact backward)
  import os
  exact = os.environ.get("BENCH_EXACT", "0") == "1"
  step = make_sparse_train_step(model, plan, bce_loss, dense_opt, rule,
                                None, state_avals, batches[0], exact=exact,
                                micro_batches=MICRO)
  compiled = step.lower(state_avals, *batches[0]).compile()
  state = init_sparse_state_direct(plan, rule, dense_params, dense_opt,
                                   jax.random.PRNGKey(1))
  for i in range(3):
    state, loss = compiled(state, *batches[i % 2])
  float(loss)

  def chain(n, state):
    t0 = time.perf_counter()
    for i in range(n):
      state, loss = compiled(state, *batches[i % 2])
    float(loss)
    return time.perf_counter() - t0, state

  t1, state = chain(STEPS, state)
  t2, state = chain(2 * STEPS, state)
  ms = (t2 - t1) / STEPS * 1e3
  base = A100_1X_MS.get(MODEL)
  base_label = "1xA100"
  if base is None:
    base = A100_PER_CHIP_EQ_MS.get(MODEL)
    base_label = "A100 per-chip-eq (8x/8, assumes perfect scaling)"
  # compare samples/s (the reference column is global batch 65536)
  vs = (f"  vs {base_label} {(BATCH / ms) / (65536 / base):.3f}x"
        if base else "")
  scale_tag = f" vocab_scale={SCALE:g}" if SCALE != 1.0 else ""
  scale_tag += f" micro_batches={MICRO}" if MICRO > 1 else ""
  print(f"{MODEL}{scale_tag} batch={BATCH}: {ms:.2f} ms/step "
        f"({BATCH / ms * 1e3:,.0f} samples/s){vs}")


if __name__ == "__main__":
  main()
