"""In-run preemption chaos: SIGKILL a pod member, the survivors resize
IN PLACE — no checkpoint restore round-trip — then regrow; a SIGTERM'd
worker drains gracefully.

``tools/chaos_kill.py`` proved elasticity ACROSS restarts: kill the
trainer, relaunch at a different world, restore through the elastic
checkpoint path. Production preemption is gentler and harsher at once —
spot reclaims take ONE worker (the job should keep running without a
restore round-trip), and a maintenance notice is a SIGTERM with a
deadline (the worker should finish its step, snapshot, and exit 0).
This driver closes both gaps:

1. **reference**: one uninterrupted pod trains a fixed stream at world
   4 to completion (``--static``: membership ignored).
2. **preempt cycle**: the pod process (the trainer, owning the virtual
   mesh) registers a ``members/`` lease and polls a
   ``resilience.elastic.PreemptionSupervisor`` between steps; the
   driver spawns 3 lightweight member subprocesses (pid leases, no jax)
   and SIGKILLs one of them while the pod is mid-run. The pod detects
   the loss (pid probe), QUIESCES, and ``ResilientTrainer.resize``s
   4 -> 2 in place (``elastic_resize``: same regroup path as the
   elastic restore, every logical row f32 bit-exact); when the driver
   spawns a replacement member it regrows 2 -> 4. The verdict checks:
   the killed member really died by SIGKILL; the pod NEVER touched a
   checkpoint (``resumed_from`` is None, zero ``ckpt/restores``, the
   ckpt root stays empty); ``elastic/resizes`` counts both moves and
   ``elastic/quiesce_s`` observed them; the stitched trajectory matches
   the reference — bit-exact before the first resize, within the
   fp-associativity bound after (a resized mesh reduces in a different
   order; the resharded STATE itself is bit-exact, pinned by
   tests/test_preempt.py) — and ``consumed == steps + skipped`` holds
   across the whole run with every injected NaN batch skipped exactly
   once.
3. **drain cycle**: a worker runs with
   ``ResilientTrainer.install_sigterm_drain``; the driver SIGTERMs it
   mid-run. The worker finishes the in-flight step, snapshots, and
   exits 0 (the armed watchdog would have hard-exited 3 had the drain
   overrun its deadline — exit 0 IS the within-deadline proof); a
   relaunch auto-resumes and the stitched trajectory is bit-exact vs
   the reference.

``--smoke`` is the make-verify tier (fewer steps, same assertions);
the full run adds a double-shrink (4 -> 2 -> 1 -> 4) cycle. Verdicts go
through ``telemetry.emit_verdict`` (exit 0/1, $DE_TPU_VERDICT_LOG).
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if __name__ == "__main__":  # standalone: build the virtual CPU mesh
  flags = os.environ.get("XLA_FLAGS", "")
  if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
  os.environ.setdefault("JAX_PLATFORMS", "cpu")
  sys.path.insert(0, _REPO)

VOCAB = [500, 300, 150, 20]
GLOBAL_BATCH = 32  # divisible by every world size the cycles use


def _batches(n, seed=7, n_unique=6):
  """World-independent cycled batch stream (chaos_kill's recipe)."""
  import numpy as np
  rng = np.random.default_rng(seed)
  out = []
  for _ in range(n_unique):
    numerical = rng.standard_normal((GLOBAL_BATCH, 13)).astype(np.float32)
    cats = [rng.integers(0, v, GLOBAL_BATCH).astype(np.int32)
            for v in VOCAB]
    labels = (numerical[:, 0] > 0).astype(np.float32)
    out.append((numerical, cats, labels))
  return [out[i % n_unique] for i in range(n)]


# ---------------------------------------------------------------------------
# member: a pod worker's liveness lease (NO jax import — a member is a
# process whose pid exists, nothing more; the pod leader owns the mesh)
# ---------------------------------------------------------------------------


def run_member(pod_dir: str, member_id: str) -> None:
  d = os.path.join(pod_dir, "members")
  os.makedirs(d, exist_ok=True)
  # lease format = elastic.register_member's, incl. the pid-incarnation
  # start time (elastic.proc_start_ticks, inlined to stay jax-free)
  try:
    with open(f"/proc/{os.getpid()}/stat", "rb") as f:
      stat = f.read()
    start = int(stat[stat.rindex(b")") + 1:].split()[19])
  except (OSError, ValueError, IndexError):
    start = None
  path = os.path.join(d, f"{member_id}.json")
  tmp = path + ".tmp"
  with open(tmp, "w") as f:
    json.dump({"id": member_id, "pid": os.getpid(), "start": start}, f)
    f.flush()
    os.fsync(f.fileno())
  os.replace(tmp, path)
  while True:  # live until killed (SIGKILL: the lease pid goes dead)
    time.sleep(1.0)


# ---------------------------------------------------------------------------
# pod: the trainer process — polls membership, resizes IN PLACE
# ---------------------------------------------------------------------------


def _build_world(world):
  """Model/plan/step/state for one world size (chaos_kill's recipe)."""
  import jax
  import numpy as np
  import optax

  from distributed_embeddings_tpu.layers.planner import DistEmbeddingStrategy
  from distributed_embeddings_tpu.models import DLRM, bce_loss
  from distributed_embeddings_tpu.ops.packed_table import sparse_rule
  from distributed_embeddings_tpu.parallel import create_mesh
  from distributed_embeddings_tpu.training import (
      init_sparse_state,
      make_sparse_train_step,
      shard_params,
  )

  mesh = create_mesh(world)
  model = DLRM(vocab_sizes=VOCAB, embedding_dim=16, bottom_mlp=(32, 16),
               top_mlp=(32, 1), world_size=world, dense_row_threshold=32)
  plan = DistEmbeddingStrategy(
      [dict(input_dim=v, output_dim=16,
            initializer={"name": "uniform", "scale": 0.05}) for v in VOCAB],
      world, "basic", dense_row_threshold=32)
  rule = sparse_rule("adagrad", 0.05)
  opt = optax.adagrad(0.05)
  batches = _batches(4)
  numerical, cats, _ = batches[0]
  params = model.init(jax.random.PRNGKey(0), numerical,
                      [np.asarray(c) for c in cats])["params"]
  state = shard_params(init_sparse_state(plan, params, rule, opt), mesh)
  step = make_sparse_train_step(model, plan, bce_loss, opt, rule, mesh,
                                state, batches[0], donate=False, guard=True)
  return mesh, plan, rule, step, state


def run_pod(pod_dir: str, log_path: str, world: int, steps: int,
            nan_every: int = 6, static: bool = False,
            step_delay: float = 0.12,
            drain_deadline: float = 0.0) -> dict:
  """One pod-leader lifetime: train the fixed stream, resizing in place
  whenever the supervisor's target world changes. Appends
  ``{"i", "loss"}`` JSONL per step to ``log_path`` and resize events to
  ``log_path + '.events'``."""
  from distributed_embeddings_tpu import telemetry
  from distributed_embeddings_tpu.resilience import elastic, faultinject
  from distributed_embeddings_tpu.resilience.trainer import ResilientTrainer
  from distributed_embeddings_tpu.training import shard_batch

  mesh, plan, rule, step, state = _build_world(world)
  batches = _batches(steps)
  nan_steps = set(range(nan_every - 1, steps, nan_every)) if nan_every \
      else set()
  stream = list(faultinject.nan_batches(batches, at_steps=nan_steps))

  root = os.path.join(pod_dir, "ckpts")
  t = ResilientTrainer(step, state, plan, rule, root, mesh=mesh,
                       snapshot_every=0, resume=drain_deadline > 0)
  if drain_deadline > 0:
    t.install_sigterm_drain(deadline_s=drain_deadline)
  elastic.register_member(pod_dir, "leader")
  sup = elastic.PreemptionSupervisor(pod_dir, allowed_worlds=(1, 2, 4))
  reg = telemetry.get_registry()

  cur = world
  worlds_seen = [world]
  events = []
  drained = False
  with open(log_path, "a") as log:
    for i in range(t.consumed, steps):
      if not static:
        target = sup.target_world()
        if target != cur:
          # a member died (or a replacement joined) while the previous
          # step was in flight: quiesce and re-shard IN PLACE — the
          # checkpoint root is never touched
          new_mesh, new_plan, _rule, new_step, _s0 = _build_world(target)
          t.resize(new_plan, step_fn=new_step, new_mesh=new_mesh)
          events.append({"event": "resize", "i": i, "from": cur,
                         "to": target})
          with open(log_path + ".events", "a") as ev:
            ev.write(json.dumps(events[-1]) + "\n")
          cur = target
          worlds_seen.append(target)
      loss = t.step(*shard_batch(stream[i], t.mesh))
      log.write(json.dumps({"i": i, "loss": loss}) + "\n")
      log.flush()
      if t.maybe_drain():
        drained = True
        break
      if step_delay:
        time.sleep(step_delay)  # pace the run so chaos lands mid-run
  summary = {
      "world": cur,
      "worlds_seen": worlds_seen,
      "steps": t.step_count,
      "consumed": t.consumed,
      "skipped": t.skipped_steps,
      "expected_skips": len(nan_steps),
      "invariant_ok": t.consumed == t.step_count + t.skipped_steps,
      "resumed_from": t.resumed_from,
      "resizes": reg.counter("elastic/resizes").value,
      "quiesce_observations": reg.histogram("elastic/quiesce_s").count,
      "restores": reg.counter("ckpt/restores").value,
      "ckpt_root_entries": (sorted(os.listdir(root))
                            if os.path.isdir(root) else []),
      "drained": drained,
      "events": events,
  }
  with open(log_path + ".summary", "w") as f:
    json.dump(summary, f)
  return summary


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def _spawn(mode: str, *args: str, wait: bool = True):
  env = dict(os.environ)
  env.setdefault("JAX_PLATFORMS", "cpu")
  flags = env.get("XLA_FLAGS", "")
  if "xla_force_host_platform_device_count" not in flags:
    env["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
  cmd = [sys.executable, os.path.abspath(__file__), mode, *args]
  if wait:
    return subprocess.run(cmd, cwd=_REPO, env=env).returncode
  return subprocess.Popen(cmd, cwd=_REPO, env=env)


def _read_log(log) -> list:
  out = []
  if os.path.exists(log):
    with open(log) as f:
      for line in f:
        rec = json.loads(line)
        out.append((rec["i"], rec["loss"]))
  return out


def _read_summary(log):
  p = log + ".summary"
  if not os.path.exists(p):
    return None
  with open(p) as f:
    return json.load(f)


def _stitch(records) -> list:
  merged = {}
  for i, loss in records:
    merged[i] = loss  # later lifetime wins (the drain-relaunch overlap)
  return [merged[i] for i in sorted(merged)]


def _traj_equal(a, b) -> bool:
  import numpy as np
  return len(a) == len(b) and all(
      (np.isnan(x) and np.isnan(y)) or x == y for x, y in zip(a, b))


def _traj_close(a, b, resized_at, rtol=5e-4, atol=1e-5) -> bool:
  """Exact before the first resize, fp-associativity bound after (the
  resized mesh reduces grads/losses in a different order; the resharded
  state itself is bit-exact — tests/test_preempt.py)."""
  import numpy as np
  if len(a) != len(b):
    return False
  for i, (x, y) in enumerate(zip(a, b)):
    if np.isnan(x) or np.isnan(y):
      if not (np.isnan(x) and np.isnan(y)):
        return False
    elif i < resized_at:
      if x != y:
        return False
    elif not np.isclose(x, y, rtol=rtol, atol=atol):
      return False
  return True


def _events_of(log) -> list:
  path = log + ".events"
  if not os.path.exists(path):
    return []
  with open(path) as f:
    return [json.loads(line) for line in f]


def _wait_for(cond, proc=None, timeout=240.0) -> bool:
  """Poll ``cond()`` until true; gives up at ``timeout`` or (after one
  final check) when ``proc`` has already exited — a finished pod will
  produce no further lines or events, so waiting on is pointless."""
  deadline = time.monotonic() + timeout
  while time.monotonic() < deadline:
    if cond():
      return True
    if proc is not None and proc.poll() is not None:
      return bool(cond())
    time.sleep(0.05)
  return bool(cond())


def _wait_lines(log, n, proc=None, timeout=240.0) -> int:
  _wait_for(lambda: len(_read_log(log)) >= n, proc=proc, timeout=timeout)
  return len(_read_log(log))


def run_chaos_preempt(steps: int = 26, verbose: bool = True,
                      extra_cycles: bool = False) -> dict:
  """The full driver scenario; returns a verdict dict with ``ok``."""
  work = tempfile.mkdtemp(prefix="chaos_preempt_")
  result = {"steps": steps, "cycles": {}}

  def cycle(name):
    pod = os.path.join(work, name)
    log = os.path.join(pod, "losses.jsonl")
    os.makedirs(pod, exist_ok=True)
    return pod, log

  # ---- reference: one uninterrupted static pod at world 4 ----------------
  pod, log = cycle("ref")
  rc = _spawn("--pod", "--pod-dir", pod, "--log", log, "--world", "4",
              "--steps", str(steps), "--static", "--step-delay", "0")
  ref_summary = _read_summary(log)
  ref = _stitch(_read_log(log))
  result["cycles"]["ref"] = {
      "rc": rc, "summary": ref_summary,
      "ok": rc == 0 and len(ref) == steps and bool(
          ref_summary and ref_summary["invariant_ok"])}

  # ---- preempt cycle: SIGKILL members, shrink in place, regrow -----------
  def preempt_cycle(name, kill_n, expected_min):
    """SIGKILL ``kill_n`` of the 3 member subprocesses mid-run (the pod
    should shrink in place to ``expected_min``), then register as many
    replacements (it should regrow to 4). Membership changes need not
    map 1:1 onto resize events — e.g. two quick kills can collapse into
    one 4 -> 2 move — so the assertions are on the WORLD trajectory:
    reached expected_min, ended back at 4, never restored."""
    pod, log = cycle(name)
    members = [_spawn("--member", "--pod-dir", pod, "--id", f"w{k}",
                      wait=False) for k in range(1, 4)]
    killed_rcs = []
    try:
      proc = _spawn("--pod", "--pod-dir", pod, "--log", log, "--world",
                    "4", "--steps", str(steps), wait=False)
      _wait_lines(log, 4, proc=proc)
      for k in range(kill_n):
        victim = members[k]
        victim.send_signal(signal.SIGKILL)
        killed_rcs.append(victim.wait())  # reap: the lease pid goes dead
      _wait_for(lambda: any(e["to"] == expected_min
                            for e in _events_of(log)), proc=proc)
      _wait_lines(log, len(_read_log(log)) + 2, proc=proc)
      members.extend(_spawn("--member", "--pod-dir", pod, "--id",
                            f"r{k}", wait=False) for k in range(kill_n))
      _wait_for(lambda: _events_of(log)
                and _events_of(log)[-1]["to"] == 4, proc=proc)
      rc = proc.wait(timeout=600)
    finally:
      for m in members:
        if m.poll() is None:
          m.kill()
          m.wait()
    summary = _read_summary(log)
    events = _events_of(log)
    traj = _stitch(_read_log(log))
    resized_at = events[0]["i"] if events else steps
    worlds = [4] + [e["to"] for e in (summary or {}).get("events", [])]
    no_restore = bool(
        summary and summary["resumed_from"] is None
        and summary["restores"] == 0 and not summary["ckpt_root_entries"])
    return {
        "rc": rc, "killed_rcs": killed_rcs, "events": events,
        "worlds": worlds, "summary": summary,
        "no_restore_roundtrip": no_restore,
        "trajectory_matches": _traj_close(traj, ref, resized_at),
        "ok": rc == 0
              and all(k == -signal.SIGKILL for k in killed_rcs)
              and len(events) >= 2 and worlds[-1] == 4
              and min(worlds) == expected_min
              and no_restore
              and _traj_close(traj, ref, resized_at)
              and bool(summary and summary["invariant_ok"]
                       and summary["skipped"] == summary["expected_skips"]
                       and summary["resizes"] == len(summary["events"])
                       and summary["quiesce_observations"]
                       >= summary["resizes"])}

  result["cycles"]["preempt"] = preempt_cycle("preempt", kill_n=1,
                                              expected_min=2)

  # ---- drain cycle: SIGTERM mid-run -> snapshot, exit 0, resume exact ----
  pod, log = cycle("drain")
  proc = _spawn("--pod", "--pod-dir", pod, "--log", log, "--world", "4",
                "--steps", str(steps), "--static",
                "--drain-deadline", "60", wait=False)
  _wait_lines(log, 4, proc=proc)
  proc.send_signal(signal.SIGTERM)
  rc1 = proc.wait(timeout=600)
  s1 = _read_summary(log)
  root = os.path.join(pod, "ckpts")
  snapshot_present = os.path.isdir(root) and any(
      d.startswith("ckpt_") and not d.endswith(".tmp")
      for d in os.listdir(root))
  # relaunch: auto-resume from the drain snapshot, finish the stream
  rc2 = _spawn("--pod", "--pod-dir", pod, "--log", log, "--world", "4",
               "--steps", str(steps), "--static", "--step-delay", "0",
               "--drain-deadline", "60")
  s2 = _read_summary(log)
  traj = _stitch(_read_log(log))
  result["cycles"]["drain"] = {
      "sigterm_rc": rc1, "relaunch_rc": rc2,
      "drained_summary": s1, "final_summary": s2,
      "snapshot_present": snapshot_present,
      "trajectory_bit_exact": _traj_equal(traj, ref),
      "ok": rc1 == 0 and rc2 == 0 and snapshot_present
            and bool(s1 and s1["drained"] and s1["invariant_ok"])
            and bool(s2 and s2["resumed_from"] and s2["invariant_ok"]
                     and s2["skipped"] == s2["expected_skips"])
            and _traj_equal(traj, ref)}

  if extra_cycles:
    # deep shrink: every member SIGKILLed — the pod must keep training
    # on its last survivor (world 1, the floor), then regrow to 4 when
    # three replacements register
    result["cycles"]["deep_shrink"] = preempt_cycle(
        "deep_shrink", kill_n=3, expected_min=1)

  result["ok"] = all(c["ok"] for c in result["cycles"].values())
  if verbose:
    print(json.dumps(result, indent=1))
  return result


def main(argv=None) -> int:
  p = argparse.ArgumentParser(description=__doc__)
  p.add_argument("--pod", action="store_true")
  p.add_argument("--member", action="store_true")
  p.add_argument("--pod-dir", default="")
  p.add_argument("--id", default="")
  p.add_argument("--log", default="")
  p.add_argument("--world", type=int, default=4)
  p.add_argument("--steps", type=int, default=26)
  p.add_argument("--static", action="store_true")
  p.add_argument("--step-delay", type=float, default=0.12)
  p.add_argument("--drain-deadline", type=float, default=0.0)
  p.add_argument("--smoke", action="store_true")
  args = p.parse_args(argv)
  if args.member:
    run_member(args.pod_dir, args.id)
    return 0
  if args.pod:
    run_pod(args.pod_dir, args.log, args.world, args.steps,
            static=args.static, step_delay=args.step_delay,
            drain_deadline=args.drain_deadline)
    return 0
  from distributed_embeddings_tpu.telemetry import emit_verdict

  steps = 18 if args.smoke else args.steps
  res = run_chaos_preempt(steps=steps, extra_cycles=not args.smoke,
                          verbose=False)
  return emit_verdict("chaos-preempt", res)


if __name__ == "__main__":
  sys.exit(main())
