"""Isolate the narrow-row (rpp>1) fused-gather cost and test alternatives.

The packed layout stores 4 logical 16-wide rows per 128-lane physical row;
extraction currently one-hots the sub-row index and einsums over a
[N, rpp, stride] view — whose small minor dims tile-pad badly. Candidate:
4-way shift-select that stays [N, 128] the whole way.

Usage: python tools/profile_narrow_gather.py
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

N = 1 << 21          # ~2M occurrences (Tiny's 16-wide class is 2.88M)
R = 1 << 23          # physical rows
W = 128              # phys width
RPP = 4
STRIDE = 32          # 16 table + 16 acc lanes
K = 4


def timeit(name, fn, *args):
  step = jax.jit(fn)
  c = step(*args)
  jax.block_until_ready(c)
  float(c)

  def run(n):
    t0 = time.perf_counter()
    for _ in range(n):
      c = step(*args)
    float(c)
    return time.perf_counter() - t0

  run(1)
  t1 = run(K)
  t2 = run(2 * K)
  print(f"{name:40s}: {(t2 - t1) / K * 1e3:8.2f} ms", flush=True)


def main():
  key = jax.random.PRNGKey(0)
  rng = np.random.default_rng(0)
  buf = jax.random.normal(key, (R, W), jnp.float32)
  ids = jnp.asarray(rng.integers(0, R * RPP, N), jnp.int32)

  def raw_gather(buf, ids):
    g = jnp.take(buf, ids // RPP, axis=0, mode="fill", fill_value=0)
    return jnp.sum(jnp.tanh(g[:, :1]))

  timeit("raw phys-row gather [N,128]", raw_gather, buf, ids)

  def onehot_extract(buf, ids):
    grp, sub = ids // RPP, ids % RPP
    g = jnp.take(buf, grp, axis=0, mode="fill", fill_value=0)
    g = g.reshape(N, RPP, STRIDE)
    oh = jax.nn.one_hot(sub, RPP, dtype=g.dtype)
    out = jnp.einsum("nrs,nr->ns", g, oh)
    return jnp.sum(jnp.tanh(out[:, :1]))

  timeit("gather + one-hot einsum extract", onehot_extract, buf, ids)

  def shift_select(buf, ids):
    grp, sub = ids // RPP, ids % RPP
    g = jnp.take(buf, grp, axis=0, mode="fill", fill_value=0)
    out = jnp.zeros_like(g)
    for j in range(RPP):
      shifted = jnp.concatenate(
          [g[:, j * STRIDE:], jnp.zeros((N, j * STRIDE), g.dtype)], axis=1)
      out = jnp.where((sub == j)[:, None], shifted, out)
    return jnp.sum(jnp.tanh(out[:, :1]))

  timeit("gather + 4-way shift-select [N,128]", shift_select, buf, ids)

  def take_along(buf, ids):
    grp, sub = ids // RPP, ids % RPP
    g = jnp.take(buf, grp, axis=0, mode="fill", fill_value=0)
    g = g.reshape(N, RPP, STRIDE)
    out = jnp.take_along_axis(g, sub[:, None, None], axis=1)[:, 0]
    return jnp.sum(jnp.tanh(out[:, :1]))

  timeit("gather + take_along_axis extract", take_along, buf, ids)

  def extract_even_ids(buf, ids):
    # lower bound: extraction with sub statically 0 (pure slice)
    grp = ids // RPP
    g = jnp.take(buf, grp, axis=0, mode="fill", fill_value=0)
    return jnp.sum(jnp.tanh(g[:, :STRIDE][:, :1]))

  timeit("gather + static slice (bound)", extract_even_ids, buf, ids)

  # combine: sum over hotness 10 of [n, 10, 16] vs lane-friendly forms
  nb = N // 10 * 10
  rows16 = jax.random.normal(key, (nb // 10, 10, 16), jnp.float32)

  def combine_naive(r):
    return jnp.sum(jnp.tanh(jnp.sum(r, axis=1)[:, :1]))

  timeit("combine sum [B,10,16] axis=1", combine_naive, rows16)

  rows160 = jax.random.normal(key, (nb // 10, 160), jnp.float32)
  sel = np.zeros((160, 16), np.float32)
  for h in range(10):
    sel[h * 16:(h + 1) * 16, :] = np.eye(16)
  sel = jnp.asarray(sel)

  def combine_matmul(r):
    return jnp.sum(jnp.tanh((r @ sel)[:, :1]))

  timeit("combine matmul [B,160]@[160,16]", combine_matmul, rows160)


if __name__ == "__main__":
  main()
